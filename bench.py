"""Round benchmark: batched sentiment throughput on the real chip.

Headline metric (BASELINE.md): songs/sec sentiment-classified.  The driver
target is all ~1M songs in < 60 s on a v5e-8 ⇒ ≥ ~16,667 songs/s pod-wide,
i.e. ≥ ~2,083 songs/s *per chip*.  The measurement runs the full-size
DistilBERT-sst2 architecture (66M params, seq len 128, bf16) end-to-end —
host tokenization included — on however many chips are visible (one, under
the round driver) and reports songs/sec with ``vs_baseline`` = measured /
per-chip share of the target.

Contract: prints exactly ONE JSON line on stdout, **including on failure**
(``parsed`` must never be null again — round 1 lost its perf data to an
UNAVAILABLE axon backend).  The measurement therefore runs in a child
process: each attempt gets a fresh backend init (a failed `jax.devices()`
poisons the parent's backend cache), transient UNAVAILABLE tunnel errors
get bounded retries with backoff, and a terminal failure still emits the
contractual line with an ``error`` field.

The whole parent — attempts, backoffs, and the terminal error line — runs
under ONE wall-clock deadline (``MUSICAAL_BENCH_DEADLINE_S``, default
480 s), chosen to sit well inside the round driver's own budget: round 3's
retry loop could out-wait its caller (worst case ~44 min), so the driver
killed it at rc 124 and the "always one JSON line" contract never executed.
Attempt timeouts and retry sleeps now shrink to whatever budget remains,
and the error line is emitted *before* the deadline, never after.  Every
attempt is additionally gated on a cheap ``--probe`` child (just
``jax.devices()``), so a dead tunnel costs seconds per cycle instead of a
full ~155 s attempt and a late-window recovery still gets measured.
``tests/test_bench_budget.py`` pins the worst case.

Additional suites backing PERFORMANCE.md live in ``benchmarks/`` (see
``python bench.py --list-suites``).
"""

from __future__ import annotations

import argparse
import json
import math
import os
import subprocess
import sys
import time

PER_CHIP_TARGET = 16_667 / 8  # songs/sec per chip for the <60s/1M goal
METRIC = "sentiment_songs_per_sec_distilbert"
# One wall-clock budget for the WHOLE parent: attempts + backoffs + the
# terminal error line all fit inside it.  Must stay well under the round
# driver's own timeout or the contractual line never reaches stdout.
_DEFAULT_DEADLINE_S = 480.0


def _env_deadline() -> float:
    # A malformed override must not crash before the contractual line can
    # be emitted, and a non-finite/non-positive one must not disable the
    # deadline — fall back to the default instead.
    try:
        value = float(os.environ["MUSICAAL_BENCH_DEADLINE_S"])
    except (KeyError, ValueError):
        return _DEFAULT_DEADLINE_S
    return value if math.isfinite(value) and value > 0 else _DEFAULT_DEADLINE_S


OVERALL_DEADLINE_S = _env_deadline()
# Per-attempt cap: first axon compile is slow (~20-40 s) but a healthy run
# finishes in well under 2 min; a child still silent at 5 min is wedged.
ATTEMPT_CAP_S = 300.0
# Don't launch an attempt that couldn't cover a cold compile + the 16k-song
# sweep: SIGKILLing a child mid-compile wedges the axon lease (CLAUDE.md),
# which is worse than giving up cleanly.
MIN_ATTEMPT_S = 150.0
# Reserved tail for collecting the child + printing the terminal line.
SAFETY_S = 15.0
# Backoff before retrying a failed attempt.  The axon loopback tunnel's
# UNAVAILABLE is frequently transient; a wedged lease can take longer than
# this whole budget to clear, in which case the error line IS the result.
RETRY_SLEEPS = (10.0, 30.0, 60.0)
# A dead tunnel used to burn a full attempt per try (round 4 spent its
# whole 465 s window failing ~155 s attempts).  A probe child that only
# calls ``jax.devices()`` settles in seconds either way, so the parent
# cycles cheap probes while the tunnel is down and still has budget for a
# full measurement if it recovers late in the window.
PROBE_TIMEOUT_S = 35.0
# After a probe had to be SIGKILLed (hang, not a clean error), the tunnel
# may be slow-but-alive mid backend init — killing it again at 35 s every
# cycle risks the very lease wedge the probe exists to avoid (CLAUDE.md).
# Give subsequent probes a longer leash.
PROBE_HUNG_TIMEOUT_S = 90.0
# Smallest window worth probing in (interpreter start + jax import can
# take >10 s on the sandbox's single pinned CPU).  Below this, skip the
# probe and spend the tail on a blind attempt instead — this also keeps
# the minimum deadline that admits a measurement at MIN_ATTEMPT_S +
# SAFETY_S, same as before probes existed.
MIN_PROBE_S = 15.0
# Gap between probes of a dead tunnel.
PROBE_GAP_S = 20.0


def measure() -> dict:
    """One full measurement — runs inside the child process."""
    import jax

    from music_analyst_tpu.utils.cache import (
        enable_persistent_compilation_cache,
    )

    enable_persistent_compilation_cache()
    # Memory-only telemetry (no sink — this child's stdout is the one-line
    # contract and its cwd is not a run directory): spans + jax compile
    # listeners feed the payload's ``telemetry`` sub-object.
    from music_analyst_tpu.telemetry import (
        get_telemetry,
        install_jax_listeners,
    )

    tel = get_telemetry()
    install_jax_listeners()
    devices = jax.devices()
    n_chips = len(devices)
    platform = devices[0].platform

    from music_analyst_tpu.data.synthetic import generate_dataset
    from music_analyst_tpu.data.csv_io import iter_songs
    from music_analyst_tpu.models.distilbert import DistilBertClassifier

    # MUSICAAL_BENCH_SMOKE=1: CI-sized run (tiny model, 512 songs) so
    # `make smoke` can exercise the full contract — child process, salvage,
    # --baseline comparison — in seconds.  The payload carries
    # ``"smoke": true`` and capture_all.sh refuses to publish it.
    smoke = os.environ.get("MUSICAAL_BENCH_SMOKE") == "1"
    if smoke:
        dataset = "/tmp/musicaal_bench_songs_smoke.csv"
        n_songs = 512
    else:
        dataset = "/tmp/musicaal_bench_songs.csv"
        n_songs = 16_384
    if not os.path.exists(dataset):
        generate_dataset(dataset, num_songs=n_songs, seed=11)
    texts = [text for _, _, text in iter_songs(dataset)]

    # Auto length bucketing: derives buckets from the first batch's token
    # lengths and only keeps ones worth a compiled shape.  On this corpus
    # (~84% of rows at the seq-128 cap) it resolves to the flat path —
    # measured either way by the `bucketing` suite.
    # MUSICAAL_BENCH_MODEL switches the headline configuration (e.g.
    # "distilbert-int8" for the dynamic-quant MXU path); the sentiment_int8
    # suite is the A/B that justifies any non-default choice.
    model = os.environ.get(
        "MUSICAAL_BENCH_MODEL", "distilbert-tiny" if smoke else "distilbert"
    )
    allowed = {
        f"distilbert{size}{quant}{pack}"
        for size in ("", "-tiny")
        for quant in ("", "-int8")
        for pack in ("", "-packed")
    }
    if model not in allowed:
        # Fail loudly: from_pretrained_or_random ignores unknown base
        # names, and a typo silently measuring the default config would
        # mislabel the headline capture.
        raise ValueError(
            f"MUSICAAL_BENCH_MODEL must be one of {sorted(allowed)}, "
            f"got {model!r}"
        )
    packed = model.endswith("-packed")
    clf = DistilBertClassifier.from_pretrained_or_random(
        model, max_len=128,
        # Packing and bucketing are exclusive right-sizing levers; the
        # bucketing suite A/Bs them against each other.
        length_buckets=None if packed else "auto",
    )
    precision = "int8" if clf.config.quant == "int8" else "bf16"
    # 8192 measured best on v5e: ~10% over 4096 (amortizes dispatch).
    batch = 256 if smoke else 8192

    # Warmup: compile + first dispatch.
    with tel.span("warmup", rows=batch):
        clf.classify_batch(texts[:batch])

    # Bounded prefetch pipeline (runtime/prefetch.py — replaces the old
    # hand-rolled one-deep loop): tokenize and transfer stages run up to
    # ``depth`` batches ahead of the device; collect() in the consumer is
    # an np.asarray readback — reliable on axon.
    from music_analyst_tpu.runtime import (
        PrefetchPipeline,
        Stage,
        resolve_prefetch_depth,
    )

    pipe = PrefetchPipeline(
        [
            Stage("tokenize", clf.prepare),
            Stage("h2d", lambda p: clf.launch(clf.transfer(p))),
        ],
        depth=resolve_prefetch_depth(),
        name="pipeline",
        sink_name="compute",
    )
    batches = (
        texts[i : i + batch] for i in range(0, len(texts), batch)
    )
    start = time.perf_counter()
    with tel.span("measure", rows=len(texts)):
        for handle in pipe.run(batches):
            clf.collect(handle)
    elapsed = time.perf_counter() - start

    songs_per_sec = len(texts) / elapsed
    tel.count("rows_classified", len(texts))
    payload = {
        "telemetry": tel.summary(top=3),
        "metric": METRIC,
        "value": round(songs_per_sec, 1),
        "unit": (
            f"songs/sec on {n_chips} {platform} chip(s), seq128 "
            f"{precision}, host tokenize included"
        ),
        "vs_baseline": round(songs_per_sec / (PER_CHIP_TARGET * n_chips), 3),
        "length_buckets": list(clf.length_buckets or ()),
        "packed": packed,
    }
    if smoke:
        payload["smoke"] = True
    return payload


# Watchdog defaults inside the bench children (override/disable with
# $MUSICAAL_WATCHDOG_S).  The measurement child allows a slow first axon
# compile; the probe child must classify a dead tunnel BEFORE the parent
# SIGKILLs it at PROBE_TIMEOUT_S — SIGKILL leaves no post-mortem, the
# watchdog's flight record is the only artifact that survives.
CHILD_WATCHDOG_S = 120.0
PROBE_WATCHDOG_S = 20.0


def _run_child() -> int:
    from music_analyst_tpu.observability import (
        install_flight_recorder,
        resolve_watchdog_timeout,
        start_watchdog,
    )

    install_flight_recorder()
    start_watchdog(resolve_watchdog_timeout(default=CHILD_WATCHDOG_S))
    print(json.dumps(measure()))
    return 0


def _probe_child() -> int:
    """Cheapest possible device touch: no compile, no data, no cache."""
    from music_analyst_tpu.observability import (
        install_flight_recorder,
        resolve_watchdog_timeout,
        start_watchdog,
        watch,
    )

    install_flight_recorder()
    start_watchdog(resolve_watchdog_timeout(default=PROBE_WATCHDOG_S))
    with watch("device_probe", kind="probe"):
        import jax

        n = len(jax.devices())
    print(n)
    return 0


def _probe_device(run, budget: float) -> tuple[str, str]:
    """Launch a probe child; a dead tunnel fails here in seconds, not the
    ~155 s a full measurement attempt used to burn (VERDICT r4 #5).

    Returns ``(status, error)`` with status ``"ok"`` | ``"error"`` (clean
    child failure) | ``"timeout"`` (child had to be killed — the caller
    treats that differently, see PROBE_HUNG_TIMEOUT_S).
    """
    try:
        proc = run(
            [sys.executable, os.path.abspath(__file__), "--probe"],
            capture_output=True,
            text=True,
            timeout=budget,
        )
    except subprocess.TimeoutExpired:
        return (
            "timeout",
            f"device probe timed out after {budget:.0f}s (tunnel dead?)",
        )
    if proc.returncode != 0:
        tail = (proc.stderr or proc.stdout or "").strip().splitlines()
        detail = " | ".join(tail[-3:]) if tail else f"rc={proc.returncode}"
        return "error", f"device probe failed: {detail}"
    return "ok", ""


def _find_baseline(results_dir: str | None = None) -> tuple[str, float] | None:
    """Newest committed ``BENCH_r*.json`` whose parsed value is usable.

    "Usable" = the driver capture parsed to a positive headline value
    (failed rounds carry 0.0/None and cannot anchor a ratio).  Round files
    sort lexically, so the last usable one is the newest.
    """
    import glob

    if results_dir is None:
        # Round captures live next to bench.py (BENCH_r01.json, ...).
        results_dir = os.path.dirname(os.path.abspath(__file__))
    best = None
    for path in sorted(glob.glob(os.path.join(results_dir, "BENCH_r*.json"))):
        try:
            with open(path, encoding="utf-8") as fh:
                capture = json.load(fh)
        except (OSError, json.JSONDecodeError):
            continue
        parsed = capture.get("parsed") or {}
        value = parsed.get("value")
        if isinstance(value, (int, float)) and value > 0:
            best = (os.path.basename(path), float(value))
    return best


def _baseline_augment(threshold: float = 0.1,
                      results_dir: str | None = None):
    """``--baseline``: embed a vs-committed-capture comparison in the line.

    Returns an augment hook for :func:`_run_parent`; the default (no
    ``--baseline``) stays the identity — ``tests/test_bench_budget.py``
    pins exact payload passthrough.
    """
    base = _find_baseline(results_dir)

    def augment(payload: dict) -> dict:
        if base is None:
            payload["vs_baseline_detail"] = {
                "baseline_file": None,
                "error": "no usable BENCH_r*.json capture",
            }
            return payload
        name, value = base
        current = payload.get("value") or 0.0
        payload["vs_baseline_detail"] = {
            "baseline_file": name,
            "baseline_value": value,
            "ratio": round(current / value, 3),
            "regression": bool((value - current) / value > threshold),
            "threshold": threshold,
        }
        return payload

    return augment


def _fresh_flight_record(since_wall: float) -> tuple[str | None, str | None]:
    """(path, taxonomy) of a child-dumped flight record newer than
    ``since_wall`` in ``$MUSICAAL_FLIGHT_RECORD_DIR``; (None, None) if no
    record, a stale one (probe and measure children share the file name),
    or no record dir is configured (the unit tests' fake-run parents).
    """
    directory = os.environ.get("MUSICAAL_FLIGHT_RECORD_DIR", "").strip()
    if not directory:
        return None, None
    path = os.path.join(directory, "flight_record.json")
    try:
        if os.path.getmtime(path) < since_wall:
            return None, None
        with open(path, encoding="utf-8") as fh:
            record = json.load(fh)
    except (OSError, ValueError):
        return None, None
    return path, record.get("taxonomy")


def _last_json_line(text: str) -> dict | None:
    for line in reversed(text.strip().splitlines()):
        line = line.strip()
        if line.startswith("{"):
            try:
                return json.loads(line)
            except json.JSONDecodeError:
                continue
    return None


def _salvage(stdout, *, require_metric: bool, augment=None) -> bool:
    """Print a child's result line if its stdout carries one.

    ``require_metric`` gates on the headline metric name for children that
    did not exit cleanly, so a stray JSON line can't masquerade as success.
    ``augment`` (the ``--baseline`` hook) may enrich the payload; ``None``
    is strict passthrough.
    """
    if isinstance(stdout, bytes):
        stdout = stdout.decode(errors="replace")
    result = _last_json_line(stdout or "")
    if result is None or (require_metric and result.get("metric") != METRIC):
        return False
    if augment is not None:
        result = augment(result)
    print(json.dumps(result))
    return True


def _run_parent(
    attempts: int,
    deadline_s: float | None = None,
    *,
    run=subprocess.run,
    sleep=time.sleep,
    clock=time.monotonic,
    augment=None,
) -> int:
    """Attempt the measurement under one hard wall-clock deadline.

    ``run``/``sleep``/``clock`` are injectable so the budget test can pin
    the worst case with a fake clock instead of real minutes.
    """
    if deadline_s is None:
        deadline_s = OVERALL_DEADLINE_S
    start = clock()

    def remaining() -> float:
        return deadline_s - (clock() - start)

    # Taxonomy for the terminal line: a child's flight record (written by
    # its watchdog before the parent killed it) is ground truth; the
    # pattern classifier over the error string is the fallback.
    from music_analyst_tpu.observability.report import classify_error

    last_error = "no attempt fit inside the deadline"
    last_error_kind: str | None = "deadline_expired"
    flight_record: str | None = None
    attempt = 0
    probe_cap = PROBE_TIMEOUT_S
    while attempt < attempts and remaining() - SAFETY_S >= MIN_ATTEMPT_S:
        # Gate the attempt on a cheap device probe when the window affords
        # one.  Probes don't count against ``attempts``: while the tunnel
        # is down the parent cycles probe+gap instead of burning
        # MIN_ATTEMPT_S per try, so a late-window recovery still gets a
        # full measurement.
        afford_probe = remaining() - SAFETY_S - MIN_ATTEMPT_S
        if afford_probe >= MIN_PROBE_S:
            t_probe = time.time()
            status, probe_error = _probe_device(
                run, min(probe_cap, afford_probe)
            )
            if status == "ok":
                # A proven-healthy tunnel drops any escalated leash: if it
                # dies again later, the short cadence maximizes the probe
                # cycles left in the window.
                probe_cap = PROBE_TIMEOUT_S
            else:
                last_error = probe_error
                path, taxonomy = _fresh_flight_record(t_probe)
                if path:
                    flight_record = path
                last_error_kind = (
                    taxonomy
                    or ("tunnel_dead" if status == "timeout"
                        else classify_error(probe_error))
                )
                probe_cap = (
                    PROBE_HUNG_TIMEOUT_S
                    if status == "timeout"
                    else PROBE_TIMEOUT_S
                )
                afford_gap = (
                    remaining() - SAFETY_S - MIN_ATTEMPT_S - MIN_PROBE_S
                )
                if afford_gap > 0:
                    sleep(min(PROBE_GAP_S, afford_gap))
                    continue
                # No room for another probe cycle: fall through to one
                # last-ditch blind attempt on the tail budget — against a
                # still-dead tunnel it hangs harmlessly inside the
                # deadline, but it rides out a recovery the next probe
                # would have missed.
        if remaining() - SAFETY_S < MIN_ATTEMPT_S:
            break
        budget = min(ATTEMPT_CAP_S, remaining() - SAFETY_S)
        t_attempt = time.time()
        try:
            proc = run(
                [sys.executable, os.path.abspath(__file__), "--child"],
                capture_output=True,
                text=True,
                timeout=budget,
            )
        except subprocess.TimeoutExpired as exc:
            proc = None
            # A child can print the result line and then hang in interpreter
            # teardown (axon tunnel threads) — salvage its stdout before
            # writing the attempt off.
            if _salvage(exc.stdout, require_metric=True, augment=augment):
                return 0
            last_error = f"attempt timed out after {budget:.0f}s (tunnel hang?)"
        if proc is not None:
            # A completed measurement counts even when the interpreter died
            # non-zero afterwards (axon teardown) — same salvage rule as the
            # timeout path.
            if _salvage(proc.stdout, require_metric=proc.returncode != 0,
                        augment=augment):
                return 0
            tail = (proc.stderr or proc.stdout or "").strip().splitlines()
            last_error = (
                " | ".join(tail[-3:]) if tail else f"rc={proc.returncode}"
            )
        # The child's watchdog classifies its own hang (compile_hang vs
        # stage_stall vs tunnel_dead) far better than the parent can from
        # the outside; its record also carries the thread stacks.
        path, taxonomy = _fresh_flight_record(t_attempt)
        if path:
            flight_record = path
        last_error_kind = taxonomy or classify_error(
            last_error, None if proc is None else proc.returncode
        )
        attempt += 1
        # Backoff (a killed mid-compile child wedges the lease and wants a
        # gap) — but only what the remaining budget can afford: sleeping
        # past the point where another attempt fits would waste the tail.
        gap = RETRY_SLEEPS[min(attempt - 1, len(RETRY_SLEEPS) - 1)]
        affordable = remaining() - SAFETY_S - MIN_ATTEMPT_S
        if attempt < attempts and affordable > 0:
            sleep(min(gap, affordable))
    # Terminal failure: still exactly one parseable JSON line, emitted
    # BEFORE the deadline (the loop guard guarantees ≥ SAFETY_S remains).
    if flight_record is None and os.environ.get("MUSICAAL_FLIGHT_RECORD_DIR"):
        # No child left a record (e.g. nothing but the deadline expired):
        # the parent dumps its own, so every failed bench has an artifact.
        from music_analyst_tpu.observability import get_flight_recorder

        flight_record = get_flight_recorder().dump(
            reason="bench_deadline",
            taxonomy=last_error_kind,
            detail=last_error[-500:],
        )
    payload = {
        "metric": METRIC,
        "value": 0.0,
        "unit": "songs/sec (benchmark failed; see error)",
        "vs_baseline": 0.0,
        "error": last_error[-800:],
        "error_kind": last_error_kind,
        "gave_up_after_s": round(clock() - start, 1),
    }
    if flight_record:
        payload["flight_record"] = flight_record
    if augment is not None:
        payload = augment(payload)
    print(json.dumps(payload))
    return 0


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument("--child", action="store_true", help=argparse.SUPPRESS)
    parser.add_argument("--probe", action="store_true", help=argparse.SUPPRESS)
    parser.add_argument(
        "--attempts", type=int, default=4,
        help="Max measurement attempts before emitting the error line",
    )
    parser.add_argument(
        "--deadline", type=float, default=None,
        help="Overall wall-clock budget in seconds (default "
             "$MUSICAAL_BENCH_DEADLINE_S or 480); the contractual JSON "
             "line is always emitted before it elapses",
    )
    parser.add_argument(
        "--suite", default=None,
        help="Run a PERFORMANCE.md suite from benchmarks/ instead of the "
             "headline metric (see --list-suites)",
    )
    parser.add_argument("--list-suites", action="store_true")
    parser.add_argument(
        "--baseline", action="store_true",
        help="Embed vs_baseline_detail (comparison against the newest "
             "usable benchmarks/results/BENCH_r*.json capture) in the "
             "output line",
    )
    parser.add_argument(
        "--baseline-threshold", type=float, default=0.1,
        help="Relative throughput drop vs the baseline capture that "
             "flags regression=true (default 0.10)",
    )
    args = parser.parse_args(argv)

    if args.list_suites or args.suite:
        from benchmarks import run_suite, suite_names

        if args.list_suites:
            print("\n".join(suite_names()))
            return 0
        # Suites run under the same parent wall clock as the headline
        # metric: arm the shared budget so child-spawning suites (e.g.
        # coldstart) clamp their timeouts to what actually remains.
        from benchmarks._util import arm_deadline

        arm_deadline(
            args.deadline if args.deadline is not None else OVERALL_DEADLINE_S
        )
        return run_suite(args.suite)
    if args.probe:
        return _probe_child()
    if args.child:
        return _run_child()
    # One shared flight-record dir for the whole bench: children inherit it
    # via the environment and dump there when their watchdog trips or they
    # crash; the parent reads it back to classify the terminal error line.
    if not os.environ.get("MUSICAAL_FLIGHT_RECORD_DIR"):
        import tempfile

        os.environ["MUSICAAL_FLIGHT_RECORD_DIR"] = tempfile.mkdtemp(
            prefix="musicaal_flight_"
        )
    augment = (
        _baseline_augment(args.baseline_threshold) if args.baseline else None
    )
    return _run_parent(args.attempts, args.deadline, augment=augment)


if __name__ == "__main__":
    sys.exit(main())
