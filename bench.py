"""Round benchmark: batched sentiment throughput on the real chip.

Headline metric (BASELINE.md): songs/sec sentiment-classified.  The driver
target is all ~1M songs in < 60 s on a v5e-8 ⇒ ≥ ~16,667 songs/s pod-wide,
i.e. ≥ ~2,083 songs/s *per chip*.  This bench runs the full-size
DistilBERT-sst2 architecture (66M params, seq len 128, bf16) end-to-end —
host tokenization included — on however many chips are visible (one, under
the round driver) and reports songs/sec with ``vs_baseline`` = measured /
per-chip share of the target.

Prints exactly ONE JSON line on stdout.
"""

from __future__ import annotations

import json
import os
import sys
import time

PER_CHIP_TARGET = 16_667 / 8  # songs/sec per chip for the <60s/1M goal


def main() -> int:
    import jax

    from music_analyst_tpu.utils.cache import (
        enable_persistent_compilation_cache,
    )

    enable_persistent_compilation_cache()
    n_chips = len(jax.devices())

    from music_analyst_tpu.data.synthetic import generate_dataset
    from music_analyst_tpu.data.csv_io import iter_songs
    from music_analyst_tpu.models.distilbert import DistilBertClassifier

    dataset = "/tmp/musicaal_bench_songs.csv"
    n_songs = 16_384
    if not os.path.exists(dataset):
        generate_dataset(dataset, num_songs=n_songs, seed=11)
    texts = [text for _, _, text in iter_songs(dataset)]

    clf = DistilBertClassifier(max_len=128)
    batch = 8192  # measured best on v5e: ~10% over 4096 (amortizes dispatch)

    # Warmup: compile + first dispatch.
    clf.classify_batch(texts[:batch])

    # One-deep host/device pipeline: tokenize batch i+1 while batch i runs.
    start = time.perf_counter()
    done = 0
    pending = None
    while done < len(texts):
        handle = clf.submit(texts[done : done + batch])
        if pending is not None:
            clf.collect(pending)
        pending = handle
        done += batch
    if pending is not None:
        clf.collect(pending)
    elapsed = time.perf_counter() - start

    songs_per_sec = len(texts) / elapsed
    result = {
        "metric": "sentiment_songs_per_sec_distilbert",
        "value": round(songs_per_sec, 1),
        "unit": f"songs/sec on {n_chips} chip(s), seq128 bf16, host tokenize included",
        "vs_baseline": round(songs_per_sec / (PER_CHIP_TARGET * n_chips), 3),
    }
    print(json.dumps(result))
    return 0


if __name__ == "__main__":
    sys.exit(main())
