"""Round benchmark: batched sentiment throughput on the real chip.

Headline metric (BASELINE.md): songs/sec sentiment-classified.  The driver
target is all ~1M songs in < 60 s on a v5e-8 ⇒ ≥ ~16,667 songs/s pod-wide,
i.e. ≥ ~2,083 songs/s *per chip*.  The measurement runs the full-size
DistilBERT-sst2 architecture (66M params, seq len 128, bf16) end-to-end —
host tokenization included — on however many chips are visible (one, under
the round driver) and reports songs/sec with ``vs_baseline`` = measured /
per-chip share of the target.

Contract: prints exactly ONE JSON line on stdout, **including on failure**
(``parsed`` must never be null again — round 1 lost its perf data to an
UNAVAILABLE axon backend).  The measurement therefore runs in a child
process: each attempt gets a fresh backend init (a failed `jax.devices()`
poisons the parent's backend cache), transient UNAVAILABLE tunnel errors
get bounded retries with backoff, and a terminal failure still emits the
contractual line with an ``error`` field.

Additional suites backing PERFORMANCE.md live in ``benchmarks/`` (see
``python bench.py --list-suites``).
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

PER_CHIP_TARGET = 16_667 / 8  # songs/sec per chip for the <60s/1M goal
METRIC = "sentiment_songs_per_sec_distilbert"
# Backoff before retrying a failed attempt.  The axon loopback tunnel's
# UNAVAILABLE is frequently transient but a wedged device lease can take
# minutes to clear (CLAUDE.md), so the gaps grow aggressively.
RETRY_SLEEPS = (20, 60, 180)


def measure() -> dict:
    """One full measurement — runs inside the child process."""
    import jax

    from music_analyst_tpu.utils.cache import (
        enable_persistent_compilation_cache,
    )

    enable_persistent_compilation_cache()
    devices = jax.devices()
    n_chips = len(devices)
    platform = devices[0].platform

    from music_analyst_tpu.data.synthetic import generate_dataset
    from music_analyst_tpu.data.csv_io import iter_songs
    from music_analyst_tpu.models.distilbert import DistilBertClassifier

    dataset = "/tmp/musicaal_bench_songs.csv"
    n_songs = 16_384
    if not os.path.exists(dataset):
        generate_dataset(dataset, num_songs=n_songs, seed=11)
    texts = [text for _, _, text in iter_songs(dataset)]

    clf = DistilBertClassifier(max_len=128)
    batch = 8192  # measured best on v5e: ~10% over 4096 (amortizes dispatch)

    # Warmup: compile + first dispatch.
    clf.classify_batch(texts[:batch])

    # One-deep host/device pipeline: tokenize batch i+1 while batch i runs.
    start = time.perf_counter()
    done = 0
    pending = None
    while done < len(texts):
        handle = clf.submit(texts[done : done + batch])
        if pending is not None:
            clf.collect(pending)
        pending = handle
        done += batch
    if pending is not None:
        clf.collect(pending)  # np.asarray readback — reliable on axon
    elapsed = time.perf_counter() - start

    songs_per_sec = len(texts) / elapsed
    return {
        "metric": METRIC,
        "value": round(songs_per_sec, 1),
        "unit": (
            f"songs/sec on {n_chips} {platform} chip(s), seq128 bf16, "
            "host tokenize included"
        ),
        "vs_baseline": round(songs_per_sec / (PER_CHIP_TARGET * n_chips), 3),
    }


def _run_child() -> int:
    print(json.dumps(measure()))
    return 0


def _last_json_line(text: str) -> dict | None:
    for line in reversed(text.strip().splitlines()):
        line = line.strip()
        if line.startswith("{"):
            try:
                return json.loads(line)
            except json.JSONDecodeError:
                continue
    return None


def _run_parent(attempts: int) -> int:
    last_error = "no attempts ran"
    for attempt in range(attempts):
        try:
            proc = subprocess.run(
                [sys.executable, os.path.abspath(__file__), "--child"],
                capture_output=True,
                text=True,
                # Generous: first axon compile is slow and killing it can
                # wedge the device lease — but a dead tunnel must not hang
                # the driver forever.
                timeout=600,
            )
        except subprocess.TimeoutExpired:
            proc = None
            last_error = "attempt timed out after 600s (tunnel hang?)"
        if proc is not None:
            result = (
                _last_json_line(proc.stdout) if proc.returncode == 0 else None
            )
            if result is not None:
                print(json.dumps(result))
                return 0
            tail = (proc.stderr or proc.stdout or "").strip().splitlines()
            last_error = (
                " | ".join(tail[-3:]) if tail else f"rc={proc.returncode}"
            )
        # Backoff applies to timeouts too — killing a child mid-compile is
        # exactly the case that wedges the lease and needs the longest gap.
        if attempt + 1 < attempts:
            time.sleep(RETRY_SLEEPS[min(attempt, len(RETRY_SLEEPS) - 1)])
    # Terminal failure: still exactly one parseable JSON line.
    print(
        json.dumps(
            {
                "metric": METRIC,
                "value": 0.0,
                "unit": "songs/sec (benchmark failed; see error)",
                "vs_baseline": 0.0,
                "error": last_error[-800:],
            }
        )
    )
    return 0


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument("--child", action="store_true", help=argparse.SUPPRESS)
    parser.add_argument(
        "--attempts", type=int, default=4,
        help="Max measurement attempts before emitting the error line",
    )
    parser.add_argument(
        "--suite", default=None,
        help="Run a PERFORMANCE.md suite from benchmarks/ instead of the "
             "headline metric (see --list-suites)",
    )
    parser.add_argument("--list-suites", action="store_true")
    args = parser.parse_args(argv)

    if args.list_suites or args.suite:
        from benchmarks import run_suite, suite_names

        if args.list_suites:
            print("\n".join(suite_names()))
            return 0
        return run_suite(args.suite)
    if args.child:
        return _run_child()
    return _run_parent(args.attempts)


if __name__ == "__main__":
    sys.exit(main())
