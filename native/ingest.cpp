// Native host ingest for music_analyst_tpu.
//
// The reference keeps its hot path native (C, src/parallel_spotify.c); this
// framework does too, but designed for the TPU pipeline instead of MPI
// ranks: one pass over the dataset produces dense token-id arrays ready to
// be sharded over a device mesh (SURVEY.md §7 "the host tokenizer becomes
// the throughput ceiling → it must be the C++ component").
//
// Architecture (not a translation of the reference's per-rank loops):
//   Phase 1 — parallel record-boundary scan.  CSV record boundaries are
//     newlines at even quote parity.  Each thread scans a byte chunk with
//     memchr jumps between '"' and '\n', collecting newline positions under
//     both parity hypotheses; a prefix-sum of per-chunk quote counts then
//     selects the correct hypothesis per chunk (same trick simdjson uses
//     for its structural scan).  This avoids the reference's "seek and
//     discard a partial record" heuristic and its exact-boundary record
//     loss (SURVEY.md §5 quirk #4).
//   Phase 2 — parallel record parsing + tokenization.  Contiguous record
//     ranges per thread; each thread owns a string interner (open
//     addressing, FNV-1a) and emits local ids.
//   Phase 3 — sequential vocab merge + id remap, preserving record order.
//
// Field/tokenizer semantics are byte-exact with the Python oracle
// (music_analyst_tpu/data/csv_io.py, tokenizer.py), which is itself
// byte-exact with the reference C binary; parity is enforced by
// tests/test_native.py.

#include <atomic>
#include <cctype>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <string>
#include <thread>
#include <unordered_map>
#include <utility>
#include <vector>

namespace {

// ---------------------------------------------------------------------------
// String interner: open addressing, FNV-1a, power-of-two capacity.
// ---------------------------------------------------------------------------

struct Interner {
  // Keys live in one arena; slots store (offset, len, id).
  std::string arena;
  std::vector<uint32_t> key_offset;
  std::vector<uint32_t> key_len;
  std::vector<int32_t> slot_id;     // -1 = empty, else index into key_*
  size_t mask = 0;
  size_t count = 0;

  explicit Interner(size_t initial_capacity = 1 << 12) {
    size_t cap = 16;
    while (cap < initial_capacity) cap <<= 1;
    slot_id.assign(cap, -1);
    mask = cap - 1;
  }

  static uint64_t hash(const char* s, size_t n) {
    uint64_t h = 1469598103934665603ull;
    for (size_t i = 0; i < n; ++i) {
      h ^= (unsigned char)s[i];
      h *= 1099511628211ull;
    }
    return h;
  }

  void grow() {
    size_t new_cap = (mask + 1) << 1;
    std::vector<int32_t> fresh(new_cap, -1);
    size_t new_mask = new_cap - 1;
    for (int32_t id : slot_id) {
      if (id < 0) continue;
      uint64_t h = hash(arena.data() + key_offset[id], key_len[id]);
      size_t pos = h & new_mask;
      while (fresh[pos] >= 0) pos = (pos + 1) & new_mask;
      fresh[pos] = id;
    }
    slot_id.swap(fresh);
    mask = new_mask;
  }

  int32_t intern(const char* s, size_t n) {
    if (count * 10 >= (mask + 1) * 7) grow();  // 0.7 load factor
    uint64_t h = hash(s, n);
    size_t pos = h & mask;
    while (true) {
      int32_t id = slot_id[pos];
      if (id < 0) {
        int32_t fresh_id = (int32_t)count++;
        key_offset.push_back((uint32_t)arena.size());
        key_len.push_back((uint32_t)n);
        arena.append(s, n);
        slot_id[pos] = fresh_id;
        return fresh_id;
      }
      if (key_len[id] == n &&
          memcmp(arena.data() + key_offset[id], s, n) == 0) {
        return id;
      }
      pos = (pos + 1) & mask;
    }
  }

  const char* key(int32_t id, size_t* n) const {
    *n = key_len[id];
    return arena.data() + key_offset[id];
  }
};

// ---------------------------------------------------------------------------
// Phase 1: parallel record-boundary scan.
// ---------------------------------------------------------------------------

struct ChunkScan {
  size_t quote_count = 0;
  std::vector<size_t> newlines_even;  // newline pos, local parity even
  std::vector<size_t> newlines_odd;
};

// Record terminators are unquoted '\n' OR lone '\r' (the oracle's
// iter_csv_records_exact).  Emitting a terminator at BOTH bytes of a
// "\r\n" pair is deliberate: the extra record is the lone "\n", which every
// consumer drops as blank, and the preceding record's content is identical
// after terminator trimming — so no pair-straddles-chunk logic is needed.
void scan_chunk(const char* data, size_t begin, size_t end, ChunkScan* out) {
  auto next_at = [&](char c, size_t from) -> size_t {
    if (from >= end) return SIZE_MAX;
    const char* p = (const char*)memchr(data + from, c, end - from);
    return p ? (size_t)(p - data) : SIZE_MAX;
  };
  size_t qp = next_at('"', begin);
  size_t np = next_at('\n', begin);
  size_t cp = next_at('\r', begin);
  bool odd = false;  // local parity within the chunk
  while (true) {
    size_t tp = np < cp ? np : cp;  // nearest terminator candidate
    if (qp == SIZE_MAX && tp == SIZE_MAX) break;
    size_t pos;
    if (tp < qp) {
      (odd ? out->newlines_odd : out->newlines_even).push_back(tp);
      pos = tp + 1;
    } else {
      odd = !odd;
      out->quote_count++;
      pos = qp + 1;
    }
    if (qp < pos) qp = next_at('"', pos);
    if (np < pos) np = next_at('\n', pos);
    if (cp < pos) cp = next_at('\r', pos);
  }
}

std::vector<size_t> find_record_ends(const char* data, size_t n,
                                     unsigned threads) {
  std::vector<ChunkScan> scans(threads);
  std::vector<std::thread> pool;
  size_t chunk = n / threads + 1;
  for (unsigned t = 0; t < threads; ++t) {
    size_t begin = std::min((size_t)t * chunk, n);
    size_t end = std::min(begin + chunk, n);
    pool.emplace_back(scan_chunk, data, begin, end, &scans[t]);
  }
  for (auto& th : pool) th.join();

  std::vector<size_t> ends;
  bool odd_before = false;  // global parity entering the chunk
  for (unsigned t = 0; t < threads; ++t) {
    const auto& picked =
        odd_before ? scans[t].newlines_odd : scans[t].newlines_even;
    ends.insert(ends.end(), picked.begin(), picked.end());
    if (scans[t].quote_count & 1) odd_before = !odd_before;
  }
  if (n > 0 && (ends.empty() || ends.back() != n - 1)) {
    ends.push_back(n - 1);  // trailing record without newline
  }
  return ends;
}

// ---------------------------------------------------------------------------
// Field cleaning + tokenization (byte-exact with the Python oracle).
// ---------------------------------------------------------------------------

inline bool c_isspace(unsigned char c) {
  return c == ' ' || c == '\t' || c == '\n' || c == '\r' || c == '\v' ||
         c == '\f';
}

// Trim, unquote (or keep outer quotes verbatim), unescape "" —
// csv_io.clean_field(raw, preserve_outer_quotes).  The preserve form is the
// splitter's semantics (reference duplicate_field with
// preserve_outer_quotes=1).
void clean_field(const char* s, size_t n, bool preserve_outer_quotes,
                 std::string* out) {
  size_t b = 0, e = n;
  while (b < e && c_isspace((unsigned char)s[b])) ++b;
  while (e > b && c_isspace((unsigned char)s[e - 1])) --e;
  bool quoted = (e - b) >= 2 && s[b] == '"' && s[e - 1] == '"';
  out->clear();
  if (quoted) {
    if (preserve_outer_quotes) {
      out->assign(s + b, e - b);
      return;
    }
    ++b;
    --e;
  }
  for (size_t i = b; i < e; ++i) {
    if (s[i] == '"' && i + 1 < e && s[i + 1] == '"') {
      out->push_back('"');
      ++i;
    } else {
      out->push_back(s[i]);
    }
  }
  // second trim
  size_t b2 = 0, e2 = out->size();
  while (b2 < e2 && c_isspace((unsigned char)(*out)[b2])) ++b2;
  while (e2 > b2 && c_isspace((unsigned char)(*out)[e2 - 1])) --e2;
  if (b2 > 0 || e2 < out->size()) *out = out->substr(b2, e2 - b2);
}

inline bool token_char(unsigned char c) {
  return (c >= '0' && c <= '9') || (c >= 'a' && c <= 'z') ||
         (c >= 'A' && c <= 'Z') || c == '\'';
}

inline char lower_ascii(unsigned char c) {
  return (c >= 'A' && c <= 'Z') ? (char)(c + 32) : (char)c;
}

// ---------------------------------------------------------------------------
// Phase 2: per-thread record parsing.
// ---------------------------------------------------------------------------

struct ThreadOut {
  Interner words{1 << 14};
  Interner artists{1 << 10};
  std::vector<int32_t> word_ids;        // local word ids, record order
  std::vector<int64_t> tokens_per_song;
  std::vector<int32_t> artist_local;    // local artist ids, -1 = empty
  // Optional record capture for the fused joint pipeline: cleaned
  // artist/song/text bytes concatenated, 3 lengths per parsed song.
  bool capture = false;
  std::string rec_blob;
  std::vector<uint32_t> field_lens;
};

void process_records(const char* data, const std::vector<size_t>& starts,
                     const std::vector<size_t>& ends, size_t rec_begin,
                     size_t rec_end, ThreadOut* out) {
  std::string artist, song, text, token;
  for (size_t r = rec_begin; r < rec_end; ++r) {
    const char* rec = data + starts[r];
    size_t len = ends[r] + 1 - starts[r];
    while (len > 0 && (rec[len - 1] == '\n' || rec[len - 1] == '\r')) --len;
    if (len == 0) continue;  // blank line

    // Split on unquoted commas; text = everything after the third comma
    // (csv_io.parse_record_exact semantics).
    size_t commas = 0;
    size_t field0_end = SIZE_MAX, field1_end = SIZE_MAX, text_begin = SIZE_MAX;
    bool in_q = false;
    for (size_t i = 0; i < len; ++i) {
      char c = rec[i];
      if (c == '"') {
        if (in_q && i + 1 < len && rec[i + 1] == '"') {
          ++i;
        } else {
          in_q = !in_q;
        }
      } else if (c == ',' && !in_q) {
        if (commas == 0) field0_end = i;
        else if (commas == 1) field1_end = i;
        ++commas;
        if (commas == 3) {
          text_begin = i + 1;
          break;
        }
      }
    }
    if (commas < 3) continue;  // reference rejects short records

    clean_field(rec, field0_end, false, &artist);
    clean_field(rec + text_begin, len - text_begin, false, &text);
    if (out->capture) {
      clean_field(rec + field0_end + 1, field1_end - field0_end - 1, false,
                  &song);
      out->rec_blob.append(artist);
      out->rec_blob.append(song);
      out->rec_blob.append(text);
      out->field_lens.push_back((uint32_t)artist.size());
      out->field_lens.push_back((uint32_t)song.size());
      out->field_lens.push_back((uint32_t)text.size());
    }

    // Tokenize (tokenizer.tokenize_ascii semantics: runs of
    // [0-9A-Za-z'], >= 3 bytes, ASCII-lowercased).
    int64_t song_tokens = 0;
    token.clear();
    for (size_t i = 0, tn = text.size(); i <= tn; ++i) {
      unsigned char c = i < tn ? (unsigned char)text[i] : 0;
      if (i < tn && token_char(c)) {
        token.push_back(lower_ascii(c));
      } else if (!token.empty()) {
        if (token.size() >= 3) {
          out->word_ids.push_back(out->words.intern(token.data(), token.size()));
          ++song_tokens;
        }
        token.clear();
      }
    }
    out->tokens_per_song.push_back(song_tokens);
    out->artist_local.push_back(
        artist.empty() ? -1
                       : out->artists.intern(artist.data(), artist.size()));
  }
}

// ---------------------------------------------------------------------------
// Result handle + phase 3 merge.
// ---------------------------------------------------------------------------

struct IngestHandle {
  std::string error;
  std::vector<int32_t> word_ids;
  std::vector<int64_t> word_offsets;
  std::vector<int32_t> artist_ids;
  Interner words{1 << 16};
  Interner artists{1 << 12};
  // Captured records (fused joint pipeline): cleaned artist/song/text
  // bytes, record order; rec_offsets has 3*songs+1 cumulative entries.
  std::string rec_blob;
  std::vector<int64_t> rec_offsets;
};

// hardware_concurrency() can report 1 inside cgroup-limited sandboxes
// where extra threads still overlap memory stalls; floor the default at 4
// (measured 2.3x on the 50k-song synthetic corpus even under nproc==1).
static unsigned resolve_threads(int num_threads) {
  return num_threads > 0 ? (unsigned)num_threads
                         : std::max(4u, std::thread::hardware_concurrency());
}

// Whole file into *data; *error (when non-null) gets "failed to open/read".
static bool read_whole_file(const char* path, std::string* data,
                            std::string* error) {
  FILE* fp = fopen(path, "rb");
  if (!fp) {
    if (error) *error = std::string("failed to open ") + path;
    return false;
  }
  fseek(fp, 0, SEEK_END);
  long file_size = ftell(fp);
  fseek(fp, 0, SEEK_SET);
  data->resize((size_t)file_size);
  bool ok = file_size <= 0 ||
            fread(&(*data)[0], 1, (size_t)file_size, fp) == (size_t)file_size;
  fclose(fp);
  if (!ok && error) *error = std::string("failed to read ") + path;
  return ok;
}

IngestHandle* ingest(const char* path, long long limit, int num_threads,
                     bool capture_records) {
  auto* h = new IngestHandle();
  std::string data;
  if (!read_whole_file(path, &data, &h->error)) return h;

  unsigned threads = resolve_threads(num_threads);

  std::vector<size_t> ends = find_record_ends(data.data(), data.size(), threads);
  // Record r spans [starts[r], ends[r]]; record 0 is the header.
  std::vector<size_t> starts(ends.size());
  for (size_t r = 0; r < ends.size(); ++r) {
    starts[r] = r == 0 ? 0 : ends[r - 1] + 1;
  }
  size_t first = ends.empty() ? 0 : 1;  // skip header record
  size_t total_records = ends.size() > first ? ends.size() - first : 0;

  // The record --limit counts *parsed songs*; short/blank records don't
  // count, so the cut must happen after parsing.  Parse everything (cheap
  // relative to the dataset) and trim afterwards when a limit is set.
  std::vector<ThreadOut> outs(threads);
  std::vector<std::thread> pool;
  size_t per = total_records / threads + 1;
  for (unsigned t = 0; t < threads; ++t) {
    outs[t].capture = capture_records;
    size_t rb = first + std::min((size_t)t * per, total_records);
    size_t re = first + std::min((size_t)(t + 1) * per, total_records);
    pool.emplace_back(process_records, data.data(), std::cref(starts),
                      std::cref(ends), rb, re, &outs[t]);
  }
  for (auto& th : pool) th.join();

  // Phase 3: merge vocabularies, remap ids, concatenate in record order.
  if (capture_records) {
    h->rec_offsets.push_back(0);
    size_t total_blob = 0;
    for (const auto& out : outs) total_blob += out.rec_blob.size();
    h->rec_blob.reserve(total_blob);
  }
  for (auto& out : outs) {
    std::vector<int32_t> word_remap(out.words.count);
    for (size_t i = 0; i < out.words.count; ++i) {
      size_t n;
      const char* k = out.words.key((int32_t)i, &n);
      word_remap[i] = h->words.intern(k, n);
    }
    std::vector<int32_t> artist_remap(out.artists.count);
    for (size_t i = 0; i < out.artists.count; ++i) {
      size_t n;
      const char* k = out.artists.key((int32_t)i, &n);
      artist_remap[i] = h->artists.intern(k, n);
    }
    size_t id_cursor = 0;
    size_t blob_cursor = 0;
    for (size_t s = 0; s < out.tokens_per_song.size(); ++s) {
      if (limit >= 0 && (long long)h->artist_ids.size() >= limit) break;
      int64_t n_tokens = out.tokens_per_song[s];
      for (int64_t k = 0; k < n_tokens; ++k) {
        h->word_ids.push_back(word_remap[out.word_ids[id_cursor + k]]);
      }
      id_cursor += (size_t)n_tokens;
      int32_t a = out.artist_local[s];
      h->artist_ids.push_back(a < 0 ? -1 : artist_remap[a]);
      if (capture_records) {
        for (size_t f = 0; f < 3; ++f) {
          uint32_t flen = out.field_lens[3 * s + f];
          h->rec_blob.append(out.rec_blob, blob_cursor, flen);
          blob_cursor += flen;
          h->rec_offsets.push_back((int64_t)h->rec_blob.size());
        }
      }
    }
    // Each thread's capture buffer is dead once merged; free it eagerly so
    // the peak is ~2x the captured text, not 3x (1M-song joint runs hold
    // hundreds of MB here).
    std::string().swap(out.rec_blob);
  }
  h->word_offsets.reserve(h->artist_ids.size() + 1);
  h->word_offsets.push_back(0);
  // Rebuild offsets from the merged ids: recompute per-song counts in the
  // same order we appended them.
  {
    int64_t acc = 0;
    size_t song_index = 0;
    for (auto& out : outs) {
      for (size_t s = 0; s < out.tokens_per_song.size(); ++s) {
        if (song_index >= h->artist_ids.size()) break;
        acc += out.tokens_per_song[s];
        h->word_offsets.push_back(acc);
        ++song_index;
      }
    }
  }
  return h;
}

}  // namespace

// ---------------------------------------------------------------------------
// C ABI (bound by music_analyst_tpu/data/native.py).
// ---------------------------------------------------------------------------

extern "C" {

void* man_ingest(const char* path, long long limit, int num_threads) {
  return ingest(path, limit, num_threads, /*capture_records=*/false);
}

// v2 adds record capture for the fused joint pipeline (one parse feeds
// both the histogram arrays and the sentiment batches).
void* man_ingest_v2(const char* path, long long limit, int num_threads,
                    int capture_records) {
  return ingest(path, limit, num_threads, capture_records != 0);
}

long long man_records_bytes(void* handle) {
  return (long long)((IngestHandle*)handle)->rec_blob.size();
}

// blob: rec_blob bytes; offsets: int64[3*songs+1] cumulative field ends.
void man_copy_records(void* handle, char* blob, long long* offsets) {
  auto* h = (IngestHandle*)handle;
  memcpy(blob, h->rec_blob.data(), h->rec_blob.size());
  memcpy(offsets, h->rec_offsets.data(),
         h->rec_offsets.size() * sizeof(int64_t));
}

const char* man_error(void* handle) {
  auto* h = (IngestHandle*)handle;
  return h->error.empty() ? nullptr : h->error.c_str();
}

long long man_song_count(void* handle) {
  return (long long)((IngestHandle*)handle)->artist_ids.size();
}

long long man_token_count(void* handle) {
  return (long long)((IngestHandle*)handle)->word_ids.size();
}

int man_word_vocab_size(void* handle) {
  return (int)((IngestHandle*)handle)->words.count;
}

int man_artist_vocab_size(void* handle) {
  return (int)((IngestHandle*)handle)->artists.count;
}

long long man_word_vocab_bytes(void* handle) {
  return (long long)((IngestHandle*)handle)->words.arena.size();
}

long long man_artist_vocab_bytes(void* handle) {
  return (long long)((IngestHandle*)handle)->artists.arena.size();
}

void man_copy_word_ids(void* handle, void* out) {
  auto* h = (IngestHandle*)handle;
  memcpy(out, h->word_ids.data(), h->word_ids.size() * sizeof(int32_t));
}

void man_copy_word_offsets(void* handle, void* out) {
  auto* h = (IngestHandle*)handle;
  memcpy(out, h->word_offsets.data(),
         h->word_offsets.size() * sizeof(int64_t));
}

void man_copy_artist_ids(void* handle, void* out) {
  auto* h = (IngestHandle*)handle;
  memcpy(out, h->artist_ids.data(), h->artist_ids.size() * sizeof(int32_t));
}

// Length-prefixed vocab export: concatenated UTF-8 bytes + int32 length per
// token (tokens may contain any byte, including newlines).
static void copy_vocab(const Interner& in, char* blob, int32_t* lens) {
  memcpy(blob, in.arena.data(), in.arena.size());
  for (size_t i = 0; i < in.count; ++i) {
    lens[i] = (int32_t)in.key_len[i];
  }
}

void man_copy_word_vocab(void* handle, char* blob, int32_t* lens) {
  copy_vocab(((IngestHandle*)handle)->words, blob, lens);
}

void man_copy_artist_vocab(void* handle, char* blob, int32_t* lens) {
  copy_vocab(((IngestHandle*)handle)->artists, blob, lens);
}

void man_free(void* handle) { delete (IngestHandle*)handle; }

// ---------------------------------------------------------------------------
// Batch hash tokenizer for the encoder classifier.
//
// Byte-exact with HashWordTokenizer (models/tokenization.py): ASCII
// lowercase; words = runs of [a-z0-9']; ASCII whitespace separates; any
// other character (one UTF-8 char, multi-byte included) is a single token;
// id = reserved + FNV-1a(bytes) % (vocab - reserved).  Rows are processed
// in parallel worker threads.
// ---------------------------------------------------------------------------

namespace {

inline uint32_t fnv1a32(const unsigned char* s, size_t n) {
  uint32_t h = 2166136261u;
  for (size_t i = 0; i < n; ++i) {
    h = (h ^ s[i]) * 16777619u;
  }
  return h;
}

struct HashSpec {
  int32_t vocab_size, cls_id, sep_id, pad_id, reserved;
  int32_t hash_id(const unsigned char* s, size_t n, unsigned char* scratch)
      const {
    // hash the ASCII-lowercased bytes
    for (size_t i = 0; i < n; ++i) {
      unsigned char c = s[i];
      scratch[i] = (c >= 'A' && c <= 'Z') ? (unsigned char)(c + 32) : c;
    }
    return reserved + (int32_t)(fnv1a32(scratch, n) %
                                (uint32_t)(vocab_size - reserved));
  }
};

void hash_tokenize_row(const unsigned char* data, size_t n,
                       const HashSpec& spec, int32_t max_len, int32_t* out,
                       int32_t* out_len, std::vector<unsigned char>* scratch) {
  const int32_t max_tokens = max_len - 2;
  out[0] = spec.cls_id;
  int32_t ids_emitted = 0;
  size_t i = 0;
  size_t word_start = SIZE_MAX;
  if (scratch->size() < n + 1) scratch->resize(n + 1);
  while (i < n && ids_emitted < max_tokens) {
    unsigned char b = data[i];
    unsigned char lb = (b >= 'A' && b <= 'Z') ? (unsigned char)(b + 32) : b;
    bool is_word = (lb >= 'a' && lb <= 'z') || (lb >= '0' && lb <= '9') ||
                   lb == '\'';
    if (is_word) {
      if (word_start == SIZE_MAX) word_start = i;
      ++i;
      continue;
    }
    if (word_start != SIZE_MAX) {
      out[1 + ids_emitted++] = spec.hash_id(data + word_start, i - word_start,
                                            scratch->data());
      word_start = SIZE_MAX;
      if (ids_emitted >= max_tokens) break;
    }
    if (b == ' ' || b == '\t' || b == '\n' || b == '\r' || b == '\v' ||
        b == '\f') {
      ++i;
      continue;
    }
    size_t char_len = 1;
    if (b >= 0xF0) char_len = 4;
    else if (b >= 0xE0) char_len = 3;
    else if (b >= 0xC0) char_len = 2;
    if (i + char_len > n) char_len = n - i;
    out[1 + ids_emitted++] = spec.hash_id(data + i, char_len, scratch->data());
    i += char_len;
  }
  if (word_start != SIZE_MAX && ids_emitted < max_tokens) {
    out[1 + ids_emitted++] = spec.hash_id(data + word_start, i - word_start,
                                          scratch->data());
  }
  out[1 + ids_emitted] = spec.sep_id;
  *out_len = ids_emitted + 2;
  for (int32_t j = ids_emitted + 2; j < max_len; ++j) out[j] = spec.pad_id;
}

}  // namespace

// Dataset column splitter: writes <artist>.csv and <text>.csv with the
// reference's preserve-quotes semantics (split_dataset_columns in
// data/splitter.py is the byte-exact oracle).  Single pass over an
// in-memory copy of the dataset with buffered sequential writes.
// Returns 1 on success, 0 on I/O failure.
int man_split_columns(const char* dataset_path, const char* artist_path,
                      const char* text_path, const char* artist_header,
                      const char* text_header, int num_threads) {
  std::string data;
  if (!read_whole_file(dataset_path, &data, nullptr)) return 0;
  unsigned threads = resolve_threads(num_threads);
  std::vector<size_t> ends =
      find_record_ends(data.data(), data.size(), threads);

  std::string artist_buf, text_buf;
  artist_buf.reserve(1 << 20);
  text_buf.reserve(data.size() + (data.size() >> 2));
  artist_buf.append(*artist_header ? artist_header : "Artists");
  artist_buf.push_back('\n');
  text_buf.append(*text_header ? text_header : "Texts");
  text_buf.push_back('\n');

  std::string artist, text;
  for (size_t r = 1; r < ends.size(); ++r) {  // record 0 is the header
    const char* rec = data.data() + (ends[r - 1] + 1);
    size_t len = ends[r] - ends[r - 1];
    while (len > 0 && (rec[len - 1] == '\n' || rec[len - 1] == '\r')) --len;
    if (len == 0) continue;
    size_t commas = 0, field0_end = SIZE_MAX, text_begin = SIZE_MAX;
    bool in_q = false;
    for (size_t i = 0; i < len; ++i) {
      char c = rec[i];
      if (c == '"') {
        if (in_q && i + 1 < len && rec[i + 1] == '"') ++i;
        else in_q = !in_q;
      } else if (c == ',' && !in_q) {
        if (commas == 0) field0_end = i;
        if (++commas == 3) { text_begin = i + 1; break; }
      }
    }
    if (commas < 3) continue;
    clean_field(rec, field0_end, true, &artist);
    clean_field(rec + text_begin, len - text_begin, true, &text);
    artist_buf.append(artist);
    artist_buf.push_back('\n');
    text_buf.append(text);
    text_buf.push_back('\n');
  }

  FILE* af = fopen(artist_path, "wb");
  FILE* tf = fopen(text_path, "wb");
  int ok = af && tf;
  if (af) {
    ok = ok && fwrite(artist_buf.data(), 1, artist_buf.size(), af) ==
                   artist_buf.size();
    fclose(af);
  }
  if (tf) {
    ok = ok && fwrite(text_buf.data(), 1, text_buf.size(), tf) ==
                   text_buf.size();
    fclose(tf);
  }
  return ok ? 1 : 0;
}

// Multi-controller partitioner: byte range of process p's ceil-share of
// contiguous data records (record-exact, header excluded from the split).
// Runs the same parallel quote-parity boundary scan the ingest uses —
// O(file/threads) native work per process, replacing the whole-file
// pure-Python record parse (parallel/distributed.py's former
// _my_record_range).  out[0] = header end (exclusive byte offset),
// out[1]/out[2] = slice begin/end (exclusive).  Returns the number of
// records in the slice, or -1 on I/O failure.
long long man_record_ranges(const char* path, int n_procs, int p,
                            int num_threads, long long* out) {
  out[0] = out[1] = out[2] = 0;
  std::string data;
  if (!read_whole_file(path, &data, nullptr)) return -1;
  unsigned threads = resolve_threads(num_threads);
  std::vector<size_t> ends =
      find_record_ends(data.data(), data.size(), threads);
  if (ends.empty()) return 0;
  // Record r spans (ends[r-1], ends[r]]; record 0 is the header.  Body
  // record j (0-based) is overall record j+1, so the byte range of body
  // slice [lo, hi) is (ends[lo], ends[hi]].
  long long n_body = (long long)ends.size() - 1;
  long long share =
      (n_procs > 1 && n_body > 0) ? (n_body + n_procs - 1) / n_procs : n_body;
  long long lo = std::min((long long)p * share, n_body);
  long long hi = std::min(lo + share, n_body);
  out[0] = (long long)ends[0] + 1;
  out[1] = (long long)ends[lo] + 1;
  out[2] = (long long)ends[hi] + 1;
  return hi - lo;
}

// texts: concatenated UTF-8 blob; offsets: int64[n_rows+1]; out int32
// [n_rows, max_len]; out_lens int32 [n_rows].
// ---------------------------------------------------------------------------
// WordPiece batch tokenizer (Latin fast path).
//
// Byte-exact with models/tokenization.py (bert_basic_tokenize +
// WordPieceTokenizer, themselves differentially pinned against HF's
// BertTokenizer): whitespace split, control-char removal, single-char
// punctuation tokens, never_split special tokens, per-char lowering /
// accent stripping, greedy longest-match-first ##-continuation subwords.
// The Unicode knowledge (categories, lowercase, NFD) lives in a table
// the PYTHON side builds from unicodedata for codepoints < 0x370
// (ASCII + the Latin blocks — every Western-language lyric) and hands to
// man_wp_create, so the native path cannot drift from the Python
// semantics.  Rows containing codepoints beyond the table (Greek has
// context-dependent lowercasing, CJK needs isolation) or invalid UTF-8
// are flagged unhandled and re-encoded by the Python fallback.  The
// Python WordPiece is ~10x slower than the DistilBERT device forward —
// this kernel is the real-weights throughput unlock.
// ---------------------------------------------------------------------------

namespace {

struct WordPieceVocab {
  std::unordered_map<std::string, int32_t> map;
  std::vector<std::pair<std::string, int32_t>> specials;  // never_split
  // Per-codepoint class (0=drop, 1=ws, 2=punct, 3=word) + normalized
  // replacement bytes, Python-built (models/tokenization.py).
  std::vector<unsigned char> cls_table;
  std::vector<std::string> repl;
  int32_t cls_id = -1, sep_id = -1, pad_id = 0, unk_id = 100;
  int32_t max_word_chars = 100;
};

void wp_emit_word(const WordPieceVocab& v, const std::string& word,
                  int32_t word_chars, std::vector<int32_t>* ids,
                  std::string* buf, std::vector<int32_t>* pieces) {
  // Length limit counts CHARACTERS (Python len), not UTF-8 bytes.  The
  // greedy byte-prefix search below still equals Python's char-prefix
  // search: a slice ending mid-char is invalid UTF-8 and can never match
  // a (valid UTF-8) vocab entry.
  if (word_chars > v.max_word_chars) {
    ids->push_back(v.unk_id);
    return;
  }
  pieces->clear();
  size_t start = 0;
  while (start < word.size()) {
    size_t end = word.size();
    int32_t cur = -1;
    while (start < end) {
      buf->assign(start > 0 ? "##" : "");
      buf->append(word, start, end - start);
      auto it = v.map.find(*buf);
      if (it != v.map.end()) {
        cur = it->second;
        break;
      }
      --end;
    }
    if (cur < 0) {  // whole word becomes [UNK], matched pieces discarded
      ids->push_back(v.unk_id);
      return;
    }
    pieces->push_back(cur);
    start = end;
  }
  ids->insert(ids->end(), pieces->begin(), pieces->end());
}

// Returns 1 when every codepoint sat inside the table and the row was
// encoded; 0 = Python fallback (nothing written).
int wp_encode_row(const WordPieceVocab& v, const unsigned char* s, size_t n,
                  int32_t max_len, int32_t* out, int32_t* out_len,
                  std::vector<int32_t>* ids, std::string* word,
                  std::string* buf, std::vector<int32_t>* pieces) {
  if (max_len < 2) return 0;  // no room for [CLS]+[SEP]; the Python
                              // fallback raises cleanly, never write OOB
  const size_t table_n = v.cls_table.size();
  ids->clear();
  ids->push_back(v.cls_id);
  word->clear();
  int32_t word_chars = 0;
  const size_t limit = (size_t)max_len - 1;
  bool stopped = false;
  size_t i = 0;
  while (i < n) {
    if (ids->size() >= limit) {
      stopped = true;
      break;
    }
    if (s[i] == '[') {
      const std::pair<std::string, int32_t>* hit = nullptr;
      for (const auto& sp : v.specials) {
        if (i + sp.first.size() <= n &&
            std::memcmp(s + i, sp.first.data(), sp.first.size()) == 0) {
          hit = &sp;
          break;
        }
      }
      if (hit != nullptr) {
        if (!word->empty()) {
          wp_emit_word(v, *word, word_chars, ids, buf, pieces);
          word->clear();
          word_chars = 0;
        }
        if (ids->size() >= limit) {
          stopped = true;
          break;
        }
        ids->push_back(hit->second);
        i += hit->first.size();
        continue;
      }
    }
    unsigned char b = s[i];
    uint32_t cp;
    size_t clen;
    if (b < 0x80) {
      cp = b;
      clen = 1;
    } else if (b >= 0xC0 && b < 0xE0) {
      // 2-byte sequence: codepoints 0x80..0x7FF — may sit in the table.
      if (i + 1 >= n || (s[i + 1] & 0xC0) != 0x80) return 0;  // invalid
      cp = ((uint32_t)(b & 0x1F) << 6) | (uint32_t)(s[i + 1] & 0x3F);
      clen = 2;
    } else {
      // 3/4-byte sequences start at 0x800, past any table this kernel
      // is given; stray continuation bytes are invalid UTF-8.
      return 0;
    }
    if (cp >= table_n) return 0;
    switch (v.cls_table[cp]) {
      case 1:  // whitespace
        if (!word->empty()) {
          wp_emit_word(v, *word, word_chars, ids, buf, pieces);
          word->clear();
          word_chars = 0;
        }
        break;
      case 0:  // control: REMOVED before wordization ("a\0b" -> "ab"),
        break;  // exactly like the Python/HF clean-text pass
      case 2:  // punctuation: its own single-char token
        if (!word->empty()) {
          wp_emit_word(v, *word, word_chars, ids, buf, pieces);
          word->clear();
          word_chars = 0;
        }
        if (ids->size() >= limit) {
          stopped = true;
        } else {
          wp_emit_word(v, v.repl[cp], 1, ids, buf, pieces);
        }
        break;
      default:  // word char: append the normalized replacement bytes
        // (empty for a bare combining mark, which adds no char either)
        if (!v.repl[cp].empty()) {
          word->append(v.repl[cp]);
          // The replacement's char count: ASCII bytes count 1 each;
          // UTF-8 continuation bytes (0b10xxxxxx) don't start a char.
          for (unsigned char rb : v.repl[cp]) {
            if ((rb & 0xC0) != 0x80) ++word_chars;
          }
        }
        break;
    }
    if (stopped) break;
    i += clen;
  }
  if (!stopped && !word->empty()) {
    wp_emit_word(v, *word, word_chars, ids, buf, pieces);
  }
  if (ids->size() > limit) ids->resize(limit);
  ids->push_back(v.sep_id);
  *out_len = (int32_t)ids->size();
  for (size_t j = 0; j < ids->size(); ++j) out[j] = (*ids)[j];
  for (int32_t j = *out_len; j < max_len; ++j) out[j] = v.pad_id;
  return 1;
}

}  // namespace

void* man_wp_create(const char* vocab_blob, long long n_bytes,
                    int max_word_chars, const unsigned char* cls_table,
                    int table_n, const char* repl_blob,
                    const int32_t* repl_offsets) {
  auto* v = new WordPieceVocab();
  v->max_word_chars = max_word_chars;
  v->cls_table.assign(cls_table, cls_table + table_n);
  v->repl.reserve(table_n);
  for (int c = 0; c < table_n; ++c) {
    v->repl.emplace_back(repl_blob + repl_offsets[c],
                         (size_t)(repl_offsets[c + 1] - repl_offsets[c]));
  }
  const char* p = vocab_blob;
  const char* endp = vocab_blob + n_bytes;
  int32_t idx = 0;
  while (p < endp) {
    // Universal-newline line split, matching the Python tokenizer's
    // text-mode read: '\n', '\r\n', AND bare '\r' all terminate a line
    // (classic-Mac vocab files used to shift every id by fusing lines).
    const char* q = p;
    while (q < endp && *q != '\n' && *q != '\r') ++q;
    // Assignment (not emplace): duplicate lines keep the LAST index, the
    // Python dict-comprehension behavior.
    v->map[std::string(p, (size_t)(q - p))] = idx++;
    if (q < endp) q += (*q == '\r' && q + 1 < endp && q[1] == '\n') ? 2 : 1;
    p = q;
  }
  auto find = [&](const char* t) -> int32_t {
    auto it = v->map.find(t);
    return it == v->map.end() ? (int32_t)-1 : it->second;
  };
  v->cls_id = find("[CLS]");
  v->sep_id = find("[SEP]");
  if (v->cls_id < 0 || v->sep_id < 0) {
    delete v;
    return nullptr;  // Python raises on these; never half-work natively
  }
  int32_t pad = find("[PAD]");
  v->pad_id = pad >= 0 ? pad : 0;
  int32_t unk = find("[UNK]");
  v->unk_id = unk >= 0 ? unk : 100;
  for (const char* t : {"[PAD]", "[UNK]", "[CLS]", "[SEP]", "[MASK]"}) {
    int32_t id = find(t);
    if (id >= 0) v->specials.emplace_back(t, id);
  }
  return v;
}

void man_wp_destroy(void* handle) { delete (WordPieceVocab*)handle; }

void man_wp_encode_batch(const void* handle, const char* blob,
                         const long long* offsets, long long n_rows,
                         int max_len, int num_threads, int32_t* out,
                         int32_t* out_lens, unsigned char* handled) {
  const WordPieceVocab& v = *(const WordPieceVocab*)handle;
  unsigned threads = resolve_threads(num_threads);
  if ((long long)threads > n_rows) threads = n_rows > 0 ? (unsigned)n_rows : 1;
  std::vector<std::thread> pool;
  long long per = n_rows / threads + 1;
  for (unsigned t = 0; t < threads; ++t) {
    long long rb = std::min((long long)t * per, n_rows);
    long long re = std::min(rb + per, n_rows);
    pool.emplace_back([=, &v]() {
      std::vector<int32_t> ids, pieces;
      std::string word, buf;
      for (long long r = rb; r < re; ++r) {
        handled[r] = (unsigned char)wp_encode_row(
            v, (const unsigned char*)blob + offsets[r],
            (size_t)(offsets[r + 1] - offsets[r]), max_len,
            out + (long long)r * max_len, out_lens + r, &ids, &word, &buf,
            &pieces);
      }
    });
  }
  for (auto& th : pool) th.join();
}

void man_hash_tokenize_batch(const char* blob, const long long* offsets,
                             long long n_rows, int max_len, int vocab_size,
                             int cls_id, int sep_id, int pad_id, int reserved,
                             int num_threads, int32_t* out,
                             int32_t* out_lens) {
  HashSpec spec{vocab_size, cls_id, sep_id, pad_id, reserved};
  unsigned threads = resolve_threads(num_threads);
  if ((long long)threads > n_rows) threads = n_rows > 0 ? (unsigned)n_rows : 1;
  std::vector<std::thread> pool;
  long long per = n_rows / threads + 1;
  for (unsigned t = 0; t < threads; ++t) {
    long long rb = std::min((long long)t * per, n_rows);
    long long re = std::min(rb + per, n_rows);
    pool.emplace_back([=]() {
      std::vector<unsigned char> scratch(256);
      for (long long r = rb; r < re; ++r) {
        hash_tokenize_row(
            (const unsigned char*)blob + offsets[r],
            (size_t)(offsets[r + 1] - offsets[r]), spec, max_len,
            out + r * max_len, out_lens + r, &scratch);
      }
    });
  }
  for (auto& th : pool) th.join();
}

}  // extern "C"
