// Race-detection selftest for the multithreaded ingest.
//
// The reference has no sanitizers at all (SURVEY.md §5 "Race detection:
// none"; its Makefile is warnings-only).  This binary drives the full
// threaded pipeline — parallel quote-parity boundary scan + per-thread
// record parse/tokenize/intern + merge — so it can run under
// -fsanitize=thread (`make -C native selftest_tsan`), where any data race
// in the chunk handoff or interner merge becomes a hard failure.
//
// Usage: selftest <csv_path> [threads]

#include "ingest.cpp"

#include <cstdio>
#include <cstdlib>

int main(int argc, char** argv) {
  if (argc < 2) {
    std::fprintf(stderr, "usage: %s <csv_path> [threads]\n", argv[0]);
    return 2;
  }
  int threads = argc > 2 ? std::atoi(argv[2]) : 8;
  void* h = man_ingest(argv[1], -1, threads);
  const char* err = man_error(h);
  if (err && *err) {
    std::fprintf(stderr, "ingest error: %s\n", err);
    man_free(h);
    return 1;
  }
  std::printf("songs=%lld tokens=%lld words=%d artists=%d threads=%d\n",
              man_song_count(h), man_token_count(h), man_word_vocab_size(h),
              man_artist_vocab_size(h), threads);
  man_free(h);
  return 0;
}
