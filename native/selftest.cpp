// Race-detection selftest for the multithreaded ingest.
//
// The reference has no sanitizers at all (SURVEY.md §5 "Race detection:
// none"; its Makefile is warnings-only).  This binary drives the full
// threaded pipeline — parallel quote-parity boundary scan + per-thread
// record parse/tokenize/intern + merge — so it can run under
// -fsanitize=thread (`make -C native selftest_tsan`), where any data race
// in the chunk handoff or interner merge becomes a hard failure.
//
// Usage: selftest <csv_path> [threads]

#include "ingest.cpp"

#include <cstdio>
#include <cstdlib>

int main(int argc, char** argv) {
  if (argc < 2) {
    std::fprintf(stderr, "usage: %s <csv_path> [threads]\n", argv[0]);
    return 2;
  }
  int threads = argc > 2 ? std::atoi(argv[2]) : 8;
  void* h = man_ingest(argv[1], -1, threads);
  const char* err = man_error(h);
  if (err && *err) {
    std::fprintf(stderr, "ingest error: %s\n", err);
    man_free(h);
    return 1;
  }
  std::printf("songs=%lld tokens=%lld words=%d artists=%d threads=%d\n",
              man_song_count(h), man_token_count(h), man_word_vocab_size(h),
              man_artist_vocab_size(h), threads);
  man_free(h);

  // Threaded WordPiece batch under the same sanitizer: the vocab handle
  // is shared read-only across workers; any write slipping in races.
  {
    const char vocab[] = "[PAD]\n[UNK]\n[CLS]\n[SEP]\n[MASK]\nlove\n##s\n";
    // ASCII-only classes: ws / punct / word (full Unicode table is
    // Python-built in production; class semantics are what's raced here).
    unsigned char cls[128];
    char repl[128];
    int32_t offs[129];
    for (int c = 0; c < 128; ++c) {
      bool ws = c == ' ' || c == '\t' || c == '\n' || c == '\r';
      bool punct = (c >= 33 && c <= 47) || (c >= 58 && c <= 64) ||
                   (c >= 91 && c <= 96) || (c >= 123 && c <= 126);
      cls[c] = ws ? 1 : (c < 32 || c == 127) ? 0 : punct ? 2 : 3;
      repl[c] = (char)((c >= 'A' && c <= 'Z') ? c + 32 : c);
      offs[c] = c;
    }
    offs[128] = 128;
    void* wp = man_wp_create(vocab, (long long)sizeof(vocab) - 1, 100, cls,
                             128, repl, offs);
    if (!wp) {
      std::fprintf(stderr, "wp_create failed\n");
      return 1;
    }
    const int rows = 512, max_len = 16;
    std::string blob;
    std::vector<long long> offsets(rows + 1, 0);
    for (int r = 0; r < rows; ++r) {
      blob += "love loves [MASK] zzz! ";
      offsets[r + 1] = (long long)blob.size();
    }
    std::vector<int32_t> out((size_t)rows * max_len);
    std::vector<int32_t> lens(rows);
    std::vector<unsigned char> handled(rows);
    man_wp_encode_batch(wp, blob.data(), offsets.data(), rows, max_len,
                        threads, out.data(), lens.data(), handled.data());
    long long total = 0;
    for (int r = 0; r < rows; ++r) total += lens[r];
    std::printf("wp rows=%d total_ids=%lld handled=%d\n", rows, total,
                (int)handled[0]);
    man_wp_destroy(wp);
  }
  return 0;
}
