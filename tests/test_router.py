"""Replica router: JSQ dispatch, health-aware failover, zero loss.

The scale-out serving contracts (ISSUE 12):

* every admitted request settles — answered by a replica (possibly after
  a requeue when its first replica died) or failed with a structured
  error; nothing is silently dropped;
* killing one of N replicas under load loses zero admitted requests and
  records the health transition for the manifest;
* outputs are byte-identical whether one replica or N serve the fleet
  (dispatch placement may never change an answer);
* the ``router.dispatch`` fault site is absorbed by the shared retry
  policy, and ``router_stall`` is a classified taxonomy kind.

The fleet spawns real worker processes (``python -m music_analyst_tpu
serve --socket … --mock``), so these tests cover the wire protocol and
process lifecycle end-to-end, not just the dispatch data structures.
"""

import io
import json
import os
import signal
import time

import pytest

from music_analyst_tpu.serving.batcher import resolve_replicas, resolve_tp
from music_analyst_tpu.serving.router import (
    ReplicaHandle,
    ReplicaRouter,
    _RouterDecode,
    router_stats,
    spawn_replicas,
)


def test_resolve_replicas_and_tp(monkeypatch):
    assert resolve_replicas(None) == 1
    assert resolve_replicas(3) == 3
    monkeypatch.setenv("MUSICAAL_SERVE_REPLICAS", "4")
    assert resolve_replicas(None) == 4
    monkeypatch.setenv("MUSICAAL_SERVE_REPLICAS", "junk")
    assert resolve_replicas(None) == 1  # malformed env falls back
    with pytest.raises(ValueError):
        resolve_replicas("junk")  # explicit value is a usage error
    with pytest.raises(ValueError):
        resolve_replicas(0)

    assert resolve_tp(None) == 1
    assert resolve_tp(2) == 2
    monkeypatch.setenv("MUSICAAL_SERVE_TP", "2")
    assert resolve_tp(None) == 2
    monkeypatch.setenv("MUSICAAL_SERVE_TP", "-3")
    assert resolve_tp(None) == 1


@pytest.fixture(scope="module")
def fleet(tmp_path_factory):
    """Two mock worker processes behind one router (shared across the
    read-only tests; the kill test spawns its own victims)."""
    base = tmp_path_factory.mktemp("fleet")
    handles = spawn_replicas(
        2, str(base), model="mock", mock=True, warmup=False
    )
    router = ReplicaRouter(handles, poll_interval_s=0.1).start()
    yield router, handles
    router.drain()


def _settle(reqs, timeout=30.0):
    for req in reqs:
        assert req.wait(timeout), f"request {req.id} never settled"
    return [req.response for req in reqs]


TEXTS = [
    "I love the sunshine and happy days",
    "tears and sorrow in the lonely night",
    "",
    "la la la the radio plays",
    "broken hearts mend slowly",
    "dancing together in the summer rain",
    "cry me a river",
    "golden mornings forever",
]


def test_dispatch_balance_and_zero_loss(fleet):
    router, handles = fleet
    reqs = [
        router.submit(i, "sentiment", TEXTS[i % len(TEXTS)])
        for i in range(16)
    ]
    responses = _settle(reqs)
    assert all(r.get("ok") for r in responses), responses
    stats = router.stats()
    per_replica = {
        name: snap["dispatched"] for name, snap in stats["replicas"].items()
    }
    # JSQ must use both replicas at offered load >> fleet width.
    assert all(n > 0 for n in per_replica.values()), per_replica
    assert stats["admitted"] >= 16
    assert router_stats()["replica_count"] == 2


def test_cross_replica_determinism(fleet):
    """The fleet's answers are identical to the in-process backend's —
    dispatch placement (1 replica or N, whichever replica answers) may
    never change a label."""
    from music_analyst_tpu.engines.sentiment import get_backend

    router, _ = fleet
    expected = get_backend("mock", mock=True).classify_batch(TEXTS)
    reqs = [
        router.submit(f"det-{i}", "sentiment", text)
        for i, text in enumerate(TEXTS)
    ]
    responses = _settle(reqs)
    assert [r["label"] for r in responses] == expected
    # And again, to cross replicas regardless of which took round one.
    reqs = [
        router.submit(f"det2-{i}", "sentiment", text)
        for i, text in enumerate(TEXTS)
    ]
    assert [r["label"] for r in _settle(reqs)] == expected


def test_wordcount_op_routes_and_matches_contract(fleet):
    router, _ = fleet
    req = router.submit("wc", "wordcount", "hello hello world")
    (resp,) = _settle([req])
    assert resp["ok"] and resp["counts"] == {"hello": 2, "world": 1}


def test_bad_op_fails_at_the_router_edge(fleet):
    router, _ = fleet
    req = router.submit("bad", "no-such-op", "text")
    assert req.done  # settled synchronously, never dispatched
    assert req.response["error"]["kind"] == "bad_request"


def test_injected_dispatch_fault_absorbed_in_place(fleet):
    """``router.dispatch:error@1`` trips once and the shared RetryPolicy
    absorbs it against the same replica — no health transition."""
    from music_analyst_tpu.resilience import (
        configure_faults,
        fault_stats,
    )

    router, _ = fleet
    before = len(router.stats()["health_transitions"])
    configure_faults("router.dispatch:error@1")
    try:
        reqs = [
            router.submit(f"fault-{i}", "sentiment", "happy text")
            for i in range(4)
        ]
        responses = _settle(reqs)
        trips = fault_stats()["router.dispatch"]["trips"]
    finally:
        configure_faults(None)
    assert all(r.get("ok") for r in responses), responses
    assert trips == 1
    assert len(router.stats()["health_transitions"]) == before


def test_kill_replica_under_load_loses_nothing(tmp_path):
    """SIGKILL one of two replicas with requests in flight: the victims'
    pending requests requeue to the survivor, every admitted request is
    answered, and the manifest-visible health transition is recorded."""
    handles = spawn_replicas(
        2, str(tmp_path), model="mock", mock=True, warmup=False
    )
    # respawn=False: this test pins the UNSUPERVISED kill semantics
    # (the corpse stays dead); auto-respawn has its own coverage.
    router = ReplicaRouter(
        handles, poll_interval_s=0.05, respawn=False
    ).start()
    try:
        first = [
            router.submit(i, "sentiment", TEXTS[i % len(TEXTS)])
            for i in range(4)
        ]
        os.kill(handles[0].proc.pid, signal.SIGKILL)
        second = [
            router.submit(100 + i, "sentiment", TEXTS[i % len(TEXTS)])
            for i in range(8)
        ]
        responses = _settle(first + second, timeout=60.0)
        assert all(r is not None for r in responses)
        assert all(r.get("ok") for r in responses), responses
        stats = router.stats()
        transitions = stats["health_transitions"]
        assert transitions, "replica death must record a transition"
        assert transitions[0]["replica"] == "replica-0"
        assert transitions[0]["to"] in ("unhealthy", "dead")
        assert transitions[0]["kind"] == "tunnel_dead"
        # The poll thread eventually notices the corpse is gone for good.
        deadline = time.monotonic() + 5.0
        while (handles[0].health != "dead"
               and time.monotonic() < deadline):
            time.sleep(0.05)
        assert handles[0].health == "dead"
        assert handles[1].health == "healthy"
    finally:
        router.drain()


def test_all_replicas_dead_fails_structurally(tmp_path):
    """No healthy replica → admitted requests fail with ``replica_lost``
    (classified router_stall), not a hang or a drop."""
    handle = ReplicaHandle("replica-0", str(tmp_path / "never.sock"))
    handle.health = "dead"
    router = ReplicaRouter([handle], max_queue=4).start()
    try:
        req = router.submit("r1", "sentiment", "text")
        assert req.wait(10.0)
        assert req.response["error"]["kind"] == "replica_lost"
    finally:
        router.drain()


def test_queue_full_shed_carries_retry_after(tmp_path):
    handle = ReplicaHandle("replica-0", str(tmp_path / "never.sock"))
    router = ReplicaRouter([handle], max_queue=1)  # dispatch NOT started
    router.submit("q1", "sentiment", "fills the queue")
    shed = router.submit("q2", "sentiment", "bounced")
    assert shed.done
    error = shed.response["error"]
    assert error["kind"] == "queue_full"
    assert error["retry_after_ms"] >= 1.0
    assert router.stats()["shed"] == 1
    assert router.stats()["retry_after_ms_last"] == error["retry_after_ms"]


def test_router_stall_taxonomy_and_classification():
    from music_analyst_tpu.observability.report import classify_error
    from music_analyst_tpu.observability.watchdog import TAXONOMY
    from music_analyst_tpu.resilience.faults import SITES

    assert TAXONOMY["router"] == "router_stall"
    assert "router.dispatch" in SITES
    assert classify_error("replica lost (tunnel_dead)") == "router_stall"
    assert classify_error("router.dispatch gave up") == "router_stall"


def test_server_fronts_router_with_manifest_section(fleet):
    """A stock SentimentServer with the router in the batcher seat:
    in-order NDJSON replies, and stats_snapshot carries the fleet view
    (the manifest's ``serving.router`` section)."""
    from music_analyst_tpu.serving.server import SentimentServer

    router, _ = fleet
    server = SentimentServer(
        router, mode="stdio", decode=_RouterDecode(router), router=router
    )
    lines = "\n".join([
        json.dumps({"id": "a", "op": "sentiment", "text": TEXTS[0]}),
        json.dumps({"id": "b", "op": "wordcount", "text": "la la la"}),
        json.dumps({"id": "c", "op": "ping"}),
    ]) + "\n"
    out = io.StringIO()
    written = server.handle_stream(io.StringIO(lines), out)
    assert written == 3
    replies = [json.loads(l) for l in out.getvalue().splitlines()]
    assert [r["id"] for r in replies] == ["a", "b", "c"]
    assert all(r["ok"] for r in replies)
    snapshot = server.stats_snapshot()
    assert snapshot["router"]["replica_count"] == 2
    assert "replica-0" in snapshot["router"]["replicas"]
    assert snapshot["router"]["dispatched"] >= 2


def test_report_aggregates_router_fleet(tmp_path):
    """telemetry-report surfaces per-replica dispatch counts and health
    transitions from the manifest's serving.router section."""
    from music_analyst_tpu.observability.report import (
        build_report,
        render_report,
    )

    manifest = {
        "run": "serve", "ok": True, "wall_seconds": 1.0,
        "serving": {
            "router": {
                "replica_count": 2, "healthy_count": 1,
                "dispatched": 10, "requeued": 3, "shed": 0,
                "health_transitions": [
                    {"replica": "replica-0", "from": "healthy",
                     "to": "dead", "kind": "tunnel_dead",
                     "reason": "worker process exited", "t_s": 0.5},
                ],
                "replicas": {
                    "replica-0": {"dispatched": 4, "requeues": 3,
                                  "health": "dead"},
                    "replica-1": {"dispatched": 6, "requeues": 0,
                                  "health": "healthy"},
                },
            },
        },
    }
    run_dir = tmp_path / "run"
    run_dir.mkdir()
    (run_dir / "run_manifest.json").write_text(json.dumps(manifest))
    from music_analyst_tpu.observability.report import load_run

    record = load_run(str(run_dir))
    report = build_report([record])
    (entry,) = report["router_fleet"]
    assert entry["replica_count"] == 2
    assert entry["health_transitions"] == 1
    assert entry["replicas"]["replica-1"]["dispatched"] == 6
    text = "\n".join(render_report(report))
    assert "router fleet" in text
    assert "replica-0: 4 / 3 / dead" in text
