"""Per-request distributed tracing (ISSUE 16).

Contract families:

* **resolve/sampling** — flag > env > default; malformed flag is a
  usage error, malformed env falls back; head sampling is a
  deterministic function of the trace id.
* **zero effect when disabled** — no ``trace_id`` on any reply, no
  trace file, no extra meta, byte-for-byte the untraced wire.
* **waterfall** — a traced stdio generate request yields >=6 phases
  whose span sum covers >=95% of its measured wire latency;
  ``trace-report`` reconstructs it (exit 0) and the manifest's
  ``trace_exemplars`` ids resolve to complete waterfalls.
* **tail sampling** — sheds, preemptions and failures always flush,
  with the keep reason on the record; a preempted+resumed request's
  span tree shows the ``gap.preempt`` phase.
* **degradation** — an injected ``reqtrace.flush`` fault degrades to a
  counted ``trace_drops``; replies are untouched.
* **rates** — RateMeter rolling windows; ``stats`` sections carry them.
"""

import json
import os
import signal
import subprocess
import sys
import tempfile
import time

import pytest

from music_analyst_tpu.serving.batcher import DynamicBatcher
from music_analyst_tpu.serving.slo import RateMeter
from music_analyst_tpu.telemetry.reqtrace import (
    PHASE_NAMES,
    configure_reqtrace,
    get_reqtrace,
    resolve_trace_sample,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _echo_ops(delay_s=0.0):
    def echo(texts):
        if delay_s:
            time.sleep(delay_s)
        return [{"text": t} for t in texts]

    return {"echo": echo}


@pytest.fixture
def traced(tmp_path):
    """A recorder flushing into ``tmp_path`` at sample 1.0; restores the
    disabled global (and the env the enable exported) afterwards."""
    recorder = configure_reqtrace(1.0, directory=str(tmp_path))
    yield tmp_path, recorder
    os.environ.pop("MUSICAAL_TRACE_DIR", None)
    os.environ.pop("MUSICAAL_TRACE_SAMPLE", None)
    configure_reqtrace(None, None)


def _records(tmp_path):
    path = tmp_path / "request_traces.jsonl"
    if not path.exists():
        return []
    return [json.loads(l) for l in path.read_text().splitlines() if l]


# ---------------------------------------------------------------- resolve


def test_resolve_trace_sample(monkeypatch):
    monkeypatch.delenv("MUSICAAL_TRACE_SAMPLE", raising=False)
    assert resolve_trace_sample(None) == 0.0
    assert resolve_trace_sample(0.25) == 0.25
    assert resolve_trace_sample("1.0") == 1.0
    monkeypatch.setenv("MUSICAAL_TRACE_SAMPLE", "0.5")
    assert resolve_trace_sample(None) == 0.5
    monkeypatch.setenv("MUSICAAL_TRACE_SAMPLE", "junk")
    assert resolve_trace_sample(None) == 0.0  # malformed env falls back
    monkeypatch.setenv("MUSICAAL_TRACE_SAMPLE", "7")
    assert resolve_trace_sample(None) == 0.0  # out-of-range env falls back
    with pytest.raises(ValueError):
        resolve_trace_sample("junk")  # explicit flag is a usage error
    with pytest.raises(ValueError):
        resolve_trace_sample(1.5)


def test_head_sampling_deterministic(traced):
    _, rt = traced
    ids = [os.urandom(8).hex() for _ in range(64)]
    rt.sample = 0.5
    first = [rt.sampled(i) for i in ids]
    assert first == [rt.sampled(i) for i in ids]  # same coin every call
    assert any(first) and not all(first)
    rt.sample = 0.0
    assert not any(rt.sampled(i) for i in ids)
    rt.sample = 1.0
    assert all(rt.sampled(i) for i in ids)


# ------------------------------------------------------- disabled = inert


def test_disabled_zero_wire_effect(tmp_path):
    assert not get_reqtrace().enabled  # the suite default
    b = DynamicBatcher(_echo_ops(), max_batch=4, max_wait_ms=1.0,
                       max_queue=8).start()
    try:
        reqs = [b.submit(i, "echo", f"t{i}") for i in range(4)]
        for r in reqs:
            assert r.wait(10.0)
        for r in reqs:
            assert "trace_id" not in r.response, r.response
            assert "trace" not in r.meta and "trace_t" not in r.meta
    finally:
        b.drain()
    assert not (tmp_path / "request_traces.jsonl").exists()


# -------------------------------------------------- tail keep: sheds fail


def test_sheds_carry_trace_ids_and_tail_flush(traced):
    tmp_path, rt = traced
    rt.sample = 0.0  # head sampling off: only the tail keep may flush
    b = DynamicBatcher(_echo_ops(delay_s=0.05), max_batch=2,
                       max_wait_ms=1.0, max_queue=2).start()
    try:
        reqs = [b.submit(i, "echo", f"t{i}") for i in range(12)]
        for r in reqs:
            assert r.wait(10.0)
    finally:
        b.drain()
    shed = [r for r in reqs if not r.response["ok"]]
    served = [r for r in reqs if r.response["ok"]]
    assert shed and served
    for r in reqs:  # every settle path stamps the id — sheds included
        assert isinstance(r.response.get("trace_id"), str), r.response
    for r in reqs:  # replay the reply-write seam the server owns
        rt.finish_request(r)
    records = _records(tmp_path)
    # Only the sheds flushed (tail keep); the healthy ones discarded.
    assert len(records) == len(shed)
    assert {r["kept"] for r in records} == {"queue_full"}
    assert {r["trace_id"] for r in records} == {
        r.response["trace_id"] for r in shed
    }
    stats = rt.stats()
    assert stats["tail_kept"] == len(shed)
    assert stats["discarded"] == len(served)


# ------------------------------------------------------ stdio end-to-end


def _subprocess_env(**overrides):
    env = dict(os.environ)
    env.update(JAX_PLATFORMS="cpu", PALLAS_AXON_POOL_IPS="")
    env.pop("MUSICAAL_TRACE_DIR", None)
    env.pop("MUSICAAL_TRACE_SAMPLE", None)
    env.update(overrides)
    return env


def test_stdio_waterfall_trace_report_and_exemplars(tmp_path):
    """The acceptance waterfall: one traced generate request through the
    real stdio server — >=6 phases covering >=95% of wire latency, a
    0-exit trace-report, and exemplar ids that resolve to complete
    waterfalls."""
    requests = [
        {"id": "t1", "op": "generate", "text": "sunny morning",
         "max_new_tokens": 4},
        {"id": "t2", "op": "generate", "text": "rainy night",
         "max_new_tokens": 4},
    ]
    proc = subprocess.run(
        [sys.executable, "-m", "music_analyst_tpu", "serve", "--stdio",
         "--model", "llama-tiny", "--quiet", "--slots", "2",
         "--prefill-chunk", "32", "--max-new-tokens", "4",
         "--max-batch", "2", "--max-wait-ms", "2",
         "--trace-sample", "1.0", "--profile-dir", str(tmp_path),
         "--telemetry-dir", str(tmp_path)],
        input="".join(json.dumps(r) + "\n" for r in requests),
        capture_output=True, text=True, timeout=240,
        cwd=REPO, env=_subprocess_env(),
    )
    assert proc.returncode == 0, proc.stderr
    replies = {r["id"]: r
               for r in (json.loads(l) for l in proc.stdout.splitlines()
                         if l)}
    assert set(replies) == {"t1", "t2"}  # settle order may differ
    assert all(r["ok"] for r in replies.values())
    assert all(isinstance(r.get("trace_id"), str)
               for r in replies.values())

    records = _records(tmp_path)
    by_id = {r["trace_id"]: r for r in records}
    gen = by_id[replies["t1"]["trace_id"]]
    phases = [s for s in gen["spans"] if s["cat"] == "phase"]
    names = [s["name"] for s in phases]
    assert set(names) <= PHASE_NAMES
    assert len(phases) >= 6, names
    for expected in ("admit", "queue", "prefill", "decode", "commit",
                     "reply"):
        assert expected in names, names
    covered = sum(s["dur"] for s in phases)
    assert covered >= 0.95 * gen["wire_s"], (covered, gen["wire_s"])
    # Detail spans exist but never enter the attribution set.
    details = [s["name"] for s in gen["spans"] if s["cat"] == "detail"]
    assert "prefill.chunk" in details

    from music_analyst_tpu.observability.report import (
        build_trace_report,
        load_trace_records,
        run_trace_report,
    )

    assert run_trace_report([str(tmp_path)]) == 0
    report = build_trace_report(load_trace_records([str(tmp_path)]))
    assert report["n_complete"] == len(records)

    # Exemplar linkage: every quantile exemplar id in the manifest
    # resolves to a complete waterfall in request_traces.jsonl.
    manifest = json.loads((tmp_path / "run_manifest.json").read_text())
    exemplars = manifest["trace_exemplars"]["serving.request_seconds"]
    complete_ids = {
        t["trace_id"] for t in report["traces"] if t["complete"]
    }
    for quantile in ("p50", "p95", "p99"):
        assert exemplars[quantile]["trace_id"] in complete_ids
    assert manifest["reqtrace"]["flushed"] == len(records)
    # The rolling-rate satellite: serving sections carry window rates.
    assert manifest["serving"]["requests"]["rates"]["window_s"] == 10.0
    assert manifest["serving"]["decode"]["rates"]["req_s"] > 0.0
    # telemetry-report surfaces the exemplars next to the quantiles.
    from music_analyst_tpu.observability.report import build_report, load_run

    rec = load_run(str(tmp_path))
    rep = build_report([rec])
    blocks = [q for q in rep["latency_quantiles"]
              if q["name"] == "serving.request_seconds"]
    assert blocks and blocks[0]["exemplars"]["p99"]["trace_id"] in (
        complete_ids
    )


# -------------------------------------------------- preemption span tree


def test_preempted_resumed_span_tree(traced):
    """A preempted+resumed request's span tree shows the preemption gap
    (``gap.preempt``), tail-keeps with reason ``preempted``, keeps its
    cursor partition covering >=95% of wire latency — and tracing adds
    zero retraces while outputs stay byte-identical to untraced."""
    from music_analyst_tpu.models.llama import (
        LlamaConfig,
        LlamaZeroShotClassifier,
    )
    from music_analyst_tpu.serving.decode_loop import ContinuousScheduler

    tmp_path, rt = traced
    clf = LlamaZeroShotClassifier(config=LlamaConfig.tiny(),
                                  max_prompt_len=64)
    sched = ContinuousScheduler(
        clf, n_slots=1, prefill_chunk=16, prompt_region=64,
        max_new_tokens=8, max_queue=8, page_size=8, kv_pages=32,
        ttft_slo_ms=1.0,  # arm preemption; deadlines below stay generous
    )
    sched.warmup()

    def _staged(tag):
        low = sched.submit(f"low-{tag}", "slow burning ballad",
                           max_new_tokens=8, priority=1,
                           deadline_ms=60_000.0)
        for _ in range(32):
            sched._tick()
            slot = sched._slots[0]
            if slot is not None and slot.active and slot.steps > 0:
                break
        high = sched.submit(f"high-{tag}", "gold chorus mid decode",
                            max_new_tokens=8, priority=5,
                            deadline_ms=60_000.0)
        sched.run_until_idle()
        for req in (low, high):
            assert (req.response or {}).get("ok"), req.response
        return low, high

    # Untraced baseline on the same runtime (recorder off), then traced.
    # The enable exported env — pop it first or the re-resolve stays on.
    os.environ.pop("MUSICAAL_TRACE_DIR", None)
    os.environ.pop("MUSICAAL_TRACE_SAMPLE", None)
    configure_reqtrace(None, None)
    base_low, base_high = _staged("base")
    assert "trace_id" not in base_low.response
    rt = configure_reqtrace(1.0, directory=str(tmp_path))
    variants_before = sched.runtime.compiled_variants()
    low, high = _staged("traced")
    assert sched.runtime.compiled_variants() == variants_before  # no retrace
    assert low.response["text"] == base_low.response["text"]
    assert high.response["text"] == base_high.response["text"]
    assert sched.stats()["preemptions"] >= 2  # one per staged run

    for req in (low, high):
        rt.finish_request(req)
    records = {r["req_id"]: r for r in _records(tmp_path)}
    victim = records["low-traced"]
    # Tail-kept either way: the 1 ms SLO that arms preemption also marks
    # the victim's own TTFT miss, and keep() is first-reason-wins.
    assert victim["kept"] in ("preempted", "ttft_slo_miss")
    names = [s["name"] for s in victim["spans"] if s["cat"] == "phase"]
    assert "gap.preempt" in names
    # The interrupted phase is marked, and work resumes after the gap.
    preempted_spans = [
        s for s in victim["spans"]
        if (s.get("attrs") or {}).get("preempted")
    ]
    assert preempted_spans
    gap_i = names.index("gap.preempt")
    assert gap_i > 0 and gap_i < len(names) - 1  # work before AND after
    covered = sum(
        s["dur"] for s in victim["spans"] if s["cat"] == "phase"
    )
    assert covered >= 0.95 * victim["wire_s"]
    # The slot-stealing gold request flushed too (untouched by the gap).
    assert records["high-traced"]["wire_s"] > 0


# ------------------------------------------- cross-process fleet waterfall


def test_router_cross_process_waterfall(traced):
    """Two replica workers behind the router, one SIGKILLed mid-load:
    worker records parent-link to the front end's span, the front's
    ``downstream`` phase covers the worker round-trip, and any requeued
    request tail-keeps with a ``hop.requeue`` span."""
    from music_analyst_tpu.serving.router import (
        ReplicaRouter,
        spawn_replicas,
    )

    tmp_path, _ = traced
    rt = configure_reqtrace(1.0, directory=str(tmp_path), role="router")
    with tempfile.TemporaryDirectory() as base:
        handles = spawn_replicas(2, base, model="mock", mock=True,
                                 warmup=False, trace_sample=1.0)
        router = ReplicaRouter(handles, poll_interval_s=0.1).start()
        try:
            reqs = [router.submit(i, "sentiment", f"happy {i}")
                    for i in range(6)]
            os.kill(handles[0].proc.pid, signal.SIGKILL)
            reqs += [router.submit(6 + i, "sentiment", f"gray {i}")
                     for i in range(4)]
            for r in reqs:
                assert r.wait(60.0), f"request {r.id} never settled"
            for r in reqs:
                rt.finish_request(r)
            stats = router.stats()
        finally:
            router.drain()
    assert stats["rates"]["window_s"] == 10.0 and (
        stats["rates"]["req_s"] > 0.0
    )
    records = _records(tmp_path)
    fronts = [r for r in records if r["role"] == "router"]
    workers = [r for r in records if r["role"] == "server"]
    assert fronts and workers
    front_spans = {r["span"]: r for r in fronts}
    linked = [w for w in workers if w["parent"] in front_spans]
    assert linked, "no worker record parent-links to a front record"
    # Same trace id on both halves of a linked pair.
    for w in linked:
        assert front_spans[w["parent"]]["trace_id"] == w["trace_id"]
    ok_fronts = [
        r for r in fronts
        if "downstream" in [s["name"] for s in r["spans"]]
    ]
    assert ok_fronts, "no front record recorded a downstream phase"
    if stats["requeued"]:
        requeued = [
            r for r in fronts
            if "hop.requeue" in [s["name"] for s in r["spans"]]
        ]
        assert requeued and any(
            r["kept"] == "requeued" for r in requeued
        )


# --------------------------------------------------- flush fault degrades


def test_flush_fault_degrades_to_drops(traced):
    from music_analyst_tpu.resilience import configure_faults, fault_stats

    tmp_path, rt = traced
    b = DynamicBatcher(_echo_ops(), max_batch=4, max_wait_ms=1.0,
                       max_queue=8).start()
    configure_faults("reqtrace.flush:error@1+")
    try:
        reqs = [b.submit(i, "echo", f"t{i}") for i in range(4)]
        for r in reqs:
            assert r.wait(10.0)
            assert r.response["ok"]
            assert isinstance(r.response.get("trace_id"), str)
            rt.finish_request(r)  # the flush — and its fault — fires here
        trips = fault_stats()["reqtrace.flush"]["trips"]
    finally:
        configure_faults(None)
        b.drain()
    assert trips == 4
    stats = rt.stats()
    assert stats["trace_drops"] == 4 and stats["flushed"] == 0
    assert _records(tmp_path) == []  # no torn file, nothing half-written


# ----------------------------------------------------- trace-report gates


def test_trace_report_exit_codes(tmp_path, capsys):
    from music_analyst_tpu.observability.report import run_trace_report

    empty = tmp_path / "empty"
    empty.mkdir()
    assert run_trace_report([str(empty)]) == 2  # no usable input

    incomplete = {
        "schema": 1, "trace_id": "aa" * 8, "span": "1-1", "parent": None,
        "pid": 1, "role": "server", "req_id": "x", "op": "echo",
        "tenant": "default", "priority": 1, "kept": "head",
        "spans": [{"name": "admit", "cat": "phase", "t": 1.0,
                   "dur": 0.001}],
    }
    path = tmp_path / "request_traces.jsonl"
    path.write_text(json.dumps(incomplete) + "\n")
    assert run_trace_report([str(tmp_path)]) == 1  # traces, none complete

    complete = dict(incomplete, trace_id="bb" * 8, wire_s=0.01, spans=[
        {"name": "admit", "cat": "phase", "t": 1.0, "dur": 0.002},
        {"name": "queue", "cat": "phase", "t": 1.002, "dur": 0.002},
        {"name": "batch", "cat": "phase", "t": 1.004, "dur": 0.002},
        {"name": "commit", "cat": "phase", "t": 1.006, "dur": 0.002},
        {"name": "reply", "cat": "phase", "t": 1.008, "dur": 0.002},
    ])
    path.write_text(json.dumps(incomplete) + "\n"
                    + json.dumps(complete) + "\n")
    assert run_trace_report([str(tmp_path)]) == 0
    out = capsys.readouterr().out
    assert "attribution:" in out and "INCOMPLETE" in out
    assert run_trace_report([str(path)], json_output=True) == 0
    report = json.loads(capsys.readouterr().out.splitlines()[-1])
    assert report["n_traces"] == 2 and report["n_complete"] == 1
    trace = [t for t in report["traces"] if t["complete"]][0]
    assert trace["coverage"] == 1.0
    assert set(trace["attribution"]) == {
        "admit", "queue", "batch", "commit", "reply"
    }


# ------------------------------------------------------------ rate meters


def test_rate_meter_rolls_and_decays():
    meter = RateMeter(tau_s=10.0)
    assert meter.rate() == 0.0
    for _ in range(5):
        meter.mark()
    assert 0.3 <= meter.rate() <= 0.51  # ~5 events / 10 s window
    fast = RateMeter(tau_s=0.05)
    fast.mark(10)
    r0 = fast.rate()
    time.sleep(0.2)
    assert fast.rate() < r0 / 10.0  # an idle meter forgets the burst


def test_batcher_stats_carry_rates():
    b = DynamicBatcher(_echo_ops(), max_batch=4, max_wait_ms=1.0,
                       max_queue=8).start()
    try:
        reqs = [b.submit(i, "echo", f"t{i}") for i in range(4)]
        for r in reqs:
            assert r.wait(10.0)
        rates = b.stats()["rates"]
        assert rates["window_s"] == 10.0
        assert rates["req_s"] > 0.0 and rates["shed_s"] == 0.0
    finally:
        b.drain()
