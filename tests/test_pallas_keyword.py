"""Pallas keyword kernel (interpret mode on CPU) vs the XLA kernel."""

import numpy as np

from music_analyst_tpu.ops.keyword_sentiment import encode_batch, keyword_scores
from music_analyst_tpu.ops.pallas_keyword import keyword_scores_pallas


def test_matches_xla_kernel():
    texts = [
        "I love sunshine and smiles",
        "cry me a river of tears",
        "LOVE and PAIN in equal measure",
        "nothing to see here",
        "",
        "lovely day with sad news",
    ]
    batch, overflow = encode_batch(texts, 256)
    assert not overflow
    want = np.asarray(keyword_scores(batch))
    got = keyword_scores_pallas(batch)
    np.testing.assert_array_equal(got, want)


def test_non_tile_batch_padding():
    rng = np.random.default_rng(0)
    words = ["love", "tears", "night", "dance", "sad"]
    texts = [
        " ".join(rng.choice(words, size=rng.integers(1, 12)))
        for _ in range(300)  # not a multiple of TILE_B
    ]
    batch, _ = encode_batch(texts, 128)
    want = np.asarray(keyword_scores(batch))
    got = keyword_scores_pallas(batch)
    np.testing.assert_array_equal(got, want)
