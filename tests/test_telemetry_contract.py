"""Telemetry must never violate the driver-facing contracts.

Two hard lines in the sand: ``bench.py`` keeps printing exactly ONE JSON
line on stdout with the telemetry sub-object riding inside it, and
``--no-telemetry`` CLI runs leave ZERO extra files behind.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys

import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import bench  # noqa: E402

from music_analyst_tpu.cli.main import main  # noqa: E402
from music_analyst_tpu.telemetry import configure, get_telemetry  # noqa: E402


@pytest.fixture(autouse=True)
def _restore_telemetry():
    """main() calls configure(); undo whatever a test left behind."""
    yield
    configure(enabled=True, directory=None)


class FakeClock:
    def __init__(self):
        self.now = 0.0

    def __call__(self):
        return self.now

    def advance(self, s):
        self.now += s


def test_bench_payload_with_telemetry_is_one_line(capsys):
    """A child payload carrying the ``telemetry`` sub-object passes the
    parent verbatim — still exactly one stdout line."""
    clock = FakeClock()
    payload = {
        "metric": bench.METRIC,
        "value": 1234.5,
        "unit": "songs/sec",
        "vs_baseline": 0.6,
        "telemetry": {
            "events": 7,
            "top_spans": [
                {"name": "measure", "count": 1, "total_s": 2.0, "max_s": 2.0}
            ],
            "compile": {"count": 3, "seconds": 11.0},
        },
    }

    def run(cmd, capture_output, text, timeout):
        clock.advance(3.0 if "--probe" in cmd else 30.0)
        out = "1\n" if "--probe" in cmd else json.dumps(payload) + "\n"
        return subprocess.CompletedProcess(cmd, 0, stdout=out, stderr="")

    rc = bench._run_parent(
        4, bench._DEFAULT_DEADLINE_S,
        run=run, sleep=clock.advance, clock=clock,
    )
    assert rc == 0
    lines = capsys.readouterr().out.strip().splitlines()
    assert len(lines) == 1, f"expected exactly one stdout line, got {lines!r}"
    got = json.loads(lines[0])
    assert got == payload
    assert got["telemetry"]["compile"]["count"] == 3


def test_bench_measure_summary_shape():
    """The summary measure() embeds has the fixed three-key shape the
    capture tooling reads (without running the heavy measurement)."""
    tel = configure(enabled=True, directory=None)
    with tel.span("measure"):
        pass
    summary = tel.summary(top=3)
    assert set(summary) == {"events", "top_spans", "compile"}
    assert summary["events"] >= 1
    assert summary["top_spans"][0]["name"] == "measure"
    assert {"count", "seconds"} <= set(summary["compile"])


def test_cli_no_telemetry_writes_zero_extra_files(fixture_csv, tmp_path):
    out = tmp_path / "out"
    rc = main([
        "wordcount-per-song", str(fixture_csv),
        "--output-dir", str(out), "--no-telemetry",
    ])
    assert rc == 0
    assert sorted(p.name for p in out.iterdir()) == [
        "word_counts_by_song.csv", "word_counts_global.csv",
    ]
    assert not get_telemetry().enabled


def test_cli_telemetry_dir_emits_parseable_artifacts(fixture_csv, tmp_path):
    out, tdir = tmp_path / "out", tmp_path / "telemetry"
    rc = main([
        "sentiment", str(fixture_csv), "--mock", "--limit", "3",
        "--output-dir", str(out), "--telemetry-dir", str(tdir),
    ])
    assert rc == 0
    events = [
        json.loads(line)
        for line in (tdir / "telemetry.jsonl").read_text().splitlines()
    ]
    assert events and all("t_mono" in ev for ev in events)
    manifest = json.loads((tdir / "run_manifest.json").read_text())
    assert manifest["engine"] == "sentiment"
    assert manifest["device"]["platform"] == "cpu"
    assert manifest["device"]["count"] == 8
    assert "compile" in manifest
    # The run's own output dir got no telemetry files — they went to the
    # explicit --telemetry-dir.
    assert not (out / "telemetry.jsonl").exists()
    assert not (out / "run_manifest.json").exists()


def test_cli_default_telemetry_lands_in_output_dir(fixture_csv, tmp_path):
    out = tmp_path / "out"
    rc = main([
        "wordcount-per-song", str(fixture_csv), "--output-dir", str(out),
    ])
    assert rc == 0
    assert (out / "telemetry.jsonl").exists()
    manifest = json.loads((out / "run_manifest.json").read_text())
    assert manifest["engine"] == "persong"
    assert manifest["counters"]["rows_processed"] > 0


def test_split_stays_memory_only_without_flag(fixture_csv, tmp_path):
    """The split listing is a compared artifact: no telemetry files may
    appear in its output dir unless --telemetry-dir points elsewhere."""
    cols = tmp_path / "cols"
    rc = main(["split", str(fixture_csv), "--output-dir", str(cols)])
    assert rc == 0
    assert not any(p.name.startswith(("telemetry", "run_manifest"))
                   for p in cols.iterdir())
