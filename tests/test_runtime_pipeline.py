"""The bounded-depth prefetch pipeline's contracts (runtime/prefetch.py +
runtime/wire.py): ordering at every depth, prompt failure propagation
(a raising stage can never hang the run), cancellation that drains and
joins, bounded backpressure, real overlap, wire narrowing round-trips,
and byte-identical engine artifacts with the pipeline on or off
(the SURVEY §5 golden contract, ISSUE 3 acceptance).
"""

import json
import threading
import time

import numpy as np
import pytest

from music_analyst_tpu.runtime import (
    DEFAULT_PREFETCH_DEPTH,
    PrefetchPipeline,
    Stage,
    count_h2d_bytes,
    narrow_lengths,
    pack_mask,
    resolve_prefetch_depth,
    unpack_mask,
)


# --------------------------------------------------------------- executor


@pytest.mark.parametrize("depth", [0, 1, 3])
def test_results_in_source_order(depth):
    pipe = PrefetchPipeline(
        [Stage("double", lambda x: x * 2), Stage("inc", lambda x: x + 1)],
        depth=depth,
    )
    assert list(pipe.run(iter(range(57)))) == [x * 2 + 1 for x in range(57)]


def test_multiworker_stage_keeps_order():
    # Uneven per-item latency would scramble results if the window didn't
    # flush in submission order.
    def jittery(x):
        time.sleep(0.001 * (x % 3))
        return x * x

    pipe = PrefetchPipeline([Stage("sq", jittery, workers=4)], depth=2)
    assert list(pipe.run(iter(range(40)))) == [x * x for x in range(40)]


def test_stage_exception_propagates_promptly():
    def boom(x):
        if x == 5:
            raise RuntimeError("stage blew up")
        return x

    pipe = PrefetchPipeline([Stage("t", boom)], depth=2)
    t0 = time.perf_counter()
    with pytest.raises(RuntimeError, match="stage blew up"):
        list(pipe.run(iter(range(10_000))))
    # "Promptly": nothing waited out a queue timeout chain or a join.
    assert time.perf_counter() - t0 < 2.0


def test_source_exception_propagates():
    def bad_source():
        yield 1
        raise ValueError("source died")

    pipe = PrefetchPipeline([Stage("id", lambda x: x)], depth=1)
    with pytest.raises(ValueError, match="source died"):
        list(pipe.run(bad_source()))


def test_consumer_close_cancels_and_joins():
    before = {t.ident for t in threading.enumerate()}
    pipe = PrefetchPipeline([Stage("id", lambda x: x)], depth=2)
    gen = pipe.run(iter(range(100_000)))
    assert next(gen) == 0
    gen.close()  # early exit: must cancel, drain, and join the threads
    deadline = time.time() + 6.0
    while time.time() < deadline:
        alive = [
            t for t in threading.enumerate()
            if t.ident not in before and t.is_alive()
        ]
        if not alive:
            break
        time.sleep(0.01)
    assert not alive, f"pipeline threads leaked: {alive}"


def test_backpressure_bounds_source_readahead():
    pulled = []

    def source():
        for i in range(1000):
            pulled.append(i)
            yield i

    pipe = PrefetchPipeline([Stage("id", lambda x: x)], depth=2)
    gen = pipe.run(source())
    next(gen)
    time.sleep(0.3)  # producer side runs free; consumer holds back
    # Bound: depth items in each of 2 queues + one in-hand per thread.
    assert len(pulled) <= 2 * 2 + 3, pulled
    gen.close()


def test_overlap_reduces_wall_time():
    def slow_source():
        for i in range(10):
            time.sleep(0.015)
            yield i

    def slow_stage(x):
        time.sleep(0.015)
        return x

    def wall(depth):
        pipe = PrefetchPipeline([Stage("s", slow_stage)], depth=depth)
        t0 = time.perf_counter()
        assert list(pipe.run(slow_source())) == list(range(10))
        return time.perf_counter() - t0

    serial, overlapped = wall(0), wall(2)
    # Perfect overlap halves it; generous margin for a loaded CI box.
    assert overlapped < serial * 0.8, (serial, overlapped)


def test_stats_and_summary_shape():
    pipe = PrefetchPipeline(
        [Stage("a", lambda x: x)], depth=2, name="p", sink_name="sink"
    )
    list(pipe.run(iter(range(8))))
    summary = pipe.summary()
    assert summary["depth"] == 2
    names = [s["stage"] for s in summary["stages"]]
    assert names == ["source", "a", "sink"]
    a = summary["stages"][1]
    assert a["items"] == 8
    for key in ("work_s", "stall_s", "backpressure_s", "queue_depth_max"):
        assert key in a
    assert summary["max_queue_depth"] >= 0


def test_resolve_prefetch_depth(monkeypatch):
    monkeypatch.delenv("MUSICAAL_PREFETCH_DEPTH", raising=False)
    assert resolve_prefetch_depth(None) == DEFAULT_PREFETCH_DEPTH
    assert resolve_prefetch_depth(0) == 0
    assert resolve_prefetch_depth("3") == 3
    monkeypatch.setenv("MUSICAAL_PREFETCH_DEPTH", "1")
    assert resolve_prefetch_depth(None) == 1
    assert resolve_prefetch_depth(4) == 4  # explicit arg beats env
    with pytest.raises(ValueError):
        resolve_prefetch_depth(-1)
    with pytest.raises(ValueError):
        resolve_prefetch_depth("two")


def test_pipeline_publishes_telemetry():
    from music_analyst_tpu.telemetry import configure

    tel = configure(enabled=True, directory=None)
    pipe = PrefetchPipeline(
        [Stage("tokenize", lambda x: x), Stage("h2d", lambda x: x)],
        depth=2, name="pipeline", sink_name="compute",
    )
    list(pipe.run(iter(range(5))))
    assert "pipeline.h2d_stall_s" in tel.gauges
    assert "pipeline.compute_stall_s" in tel.gauges
    recorded = tel.pipeline_summary()["pipeline"]
    assert [s["stage"] for s in recorded["stages"]] == [
        "source", "tokenize", "h2d", "compute",
    ]
    # The key only appears in the compact digest when a pipeline ran
    # (bench contract pins the pipeline-free three-key shape).
    assert "pipeline" in tel.summary()


# ------------------------------------------------------------------- wire


def test_narrow_lengths_dtype_policy():
    values = np.array([0, 5, 127], dtype=np.int64)
    assert narrow_lengths(values, 128).dtype == np.int16
    assert narrow_lengths(values, (1 << 15) - 1).dtype == np.int16
    assert narrow_lengths(values, 1 << 15).dtype == np.int32
    np.testing.assert_array_equal(narrow_lengths(values, 128), values)


@pytest.mark.parametrize("length", [1, 7, 8, 9, 64, 100])
def test_pack_unpack_mask_roundtrip(length):
    rng = np.random.default_rng(3)
    mask = rng.integers(0, 2, size=(4, length)).astype(bool)
    packed = pack_mask(mask)
    assert packed.dtype == np.uint8
    assert packed.shape == (4, -(-length // 8))
    unpacked = np.asarray(unpack_mask(packed, length))
    np.testing.assert_array_equal(unpacked, mask)


def test_count_h2d_bytes_counters():
    from music_analyst_tpu.telemetry import configure

    tel = configure(enabled=True, directory=None)
    ids = np.zeros((4, 8), np.int16)
    lens = np.zeros((4,), np.int16)
    shipped = count_h2d_bytes([ids, lens])
    assert shipped == ids.nbytes + lens.nbytes
    assert tel.counters["pipeline.h2d_bytes"] == shipped
    # Baseline is the 4-byte wire both arrays used before narrowing.
    assert tel.counters["pipeline.h2d_bytes_saved"] == shipped


def test_forward_donation_disabled_on_cpu():
    from music_analyst_tpu.runtime.wire import forward_donation_kwargs

    assert forward_donation_kwargs(1, 2) == {}  # tests force JAX_PLATFORMS=cpu


# ---------------------------------------------------------------- backends


def test_distilbert_staged_hooks_match_classify_batch():
    from music_analyst_tpu.models.distilbert import (
        DistilBertClassifier,
        DistilBertConfig,
    )

    clf = DistilBertClassifier(config=DistilBertConfig.tiny(), max_len=32)
    texts = ["love and joy forever", "", "hate hate hate", "ok song"] * 3
    staged = clf.collect(clf.launch(clf.transfer(clf.prepare(texts))))
    assert staged == clf.classify_batch(texts)


def test_train_step_donates_state():
    import jax
    import jax.numpy as jnp

    from music_analyst_tpu.engines.train import (
        init_train_state,
        make_optimizer,
        make_train_step,
    )
    from music_analyst_tpu.models.llama import LlamaConfig, LlamaModel

    cfg = LlamaConfig.tiny()
    model = LlamaModel(cfg)
    ids = jnp.ones((2, 17), jnp.int32)
    lengths = jnp.full((2,), 17, jnp.int32)
    opt = make_optimizer()
    state = init_train_state(model, opt, (ids, lengths))
    step = make_train_step(model, opt)
    leaf_before = next(
        iter(jax.tree_util.tree_leaves(state.params))
    )
    new_state, loss = step(state, ids, lengths)
    assert np.isfinite(float(loss))
    # donate_argnums=(0,): the old state's buffers were handed to XLA.
    assert leaf_before.is_deleted()
    # The returned state is live and steps again.
    _, loss2 = step(new_state, ids, lengths)
    assert np.isfinite(float(loss2))


def test_prefetch_batches_places_and_narrows():
    import jax
    import jax.numpy as jnp

    from music_analyst_tpu.engines.train import prefetch_batches

    batches = [
        (np.ones((2, 16), np.int32), np.full((2,), 16, np.int64)),
        (np.ones((2, 16), np.int32), np.full((2,), 9, np.int64)),
    ]
    out = list(prefetch_batches(iter(batches), depth=2))
    assert len(out) == 2
    for token_ids, lengths in out:
        assert isinstance(token_ids, jax.Array)
        assert lengths.dtype == jnp.int16  # narrowed, widened in the loss
        np.testing.assert_array_equal(np.asarray(token_ids), 1)

    # Three-element batches keep their segment_ids (also narrowed).
    seg = np.array([[1] * 8 + [2] * 8] * 2, np.int64)
    out3 = list(
        prefetch_batches(
            iter([(np.ones((2, 16), np.int32), np.full((2,), 16), seg)]),
            depth=1,
        )
    )
    token_ids, lengths, seg_out = out3[0]
    assert seg_out.dtype == jnp.int16
    np.testing.assert_array_equal(np.asarray(seg_out), seg)


# ---------------------------------------------------------------- engines


def _read_artifacts(out_dir):
    out = {}
    for name in ("sentiment_totals.json", "sentiment_details.csv"):
        out[name] = (out_dir / name).read_bytes()
    return out


@pytest.mark.parametrize("depth", [0, 2])
def test_sentiment_artifacts_byte_identical_across_depths(
    fixture_csv, tmp_path, depth
):
    from music_analyst_tpu.engines.sentiment import run_sentiment

    out = tmp_path / f"d{depth}"
    run_sentiment(
        str(fixture_csv), mock=True, output_dir=str(out), quiet=True,
        batch_size=2, prefetch_depth=depth,
    )
    ref = tmp_path / "ref"
    run_sentiment(
        str(fixture_csv), mock=True, output_dir=str(ref), quiet=True,
        batch_size=2, prefetch_depth=0,
    )
    assert _read_artifacts(out) == _read_artifacts(ref)


def test_joint_word_counts_byte_identical_with_prefetch(
    fixture_csv, tmp_path
):
    from music_analyst_tpu.engines.joint import run_joint

    blobs = {}
    for depth in (0, 2):
        out = tmp_path / f"joint_d{depth}"
        run_joint(
            str(fixture_csv), output_dir=str(out), mock=True, quiet=True,
            batch_size=2, prefetch_depth=depth,
        )
        blobs[depth] = (out / "word_counts.csv").read_bytes()
    # SURVEY §5 golden contract: the ranking artifact cannot move by a
    # byte when the data plane pipelines.
    assert blobs[0] == blobs[2]


def test_sentiment_manifest_has_pipeline_section(fixture_csv, tmp_path):
    from music_analyst_tpu.engines.sentiment import run_sentiment

    run_sentiment(
        str(fixture_csv), mock=True, output_dir=str(tmp_path), quiet=True,
        batch_size=2, prefetch_depth=2,
    )
    manifest = json.loads((tmp_path / "run_manifest.json").read_text())
    pipeline = manifest["pipeline"]["pipeline"]
    assert pipeline["depth"] == 2
    stages = {s["stage"]: s for s in pipeline["stages"]}
    assert {"source", "tokenize", "h2d", "compute"} <= set(stages)
    for entry in stages.values():
        assert entry["stall_s"] >= 0.0
    assert pipeline["max_queue_depth"] >= 0
    assert manifest["gauges"]["pipeline.compute_stall_s"] >= 0.0


def test_sentiment_raising_backend_does_not_hang(fixture_csv, tmp_path):
    from music_analyst_tpu.engines.sentiment import run_sentiment

    class RaisingBackend:
        name = "raising"
        reports_latency = False

        def submit(self, texts):
            raise RuntimeError("tokenizer exploded")

        def collect(self, handle):
            return handle

    t0 = time.perf_counter()
    with pytest.raises(RuntimeError, match="tokenizer exploded"):
        run_sentiment(
            str(fixture_csv), output_dir=str(tmp_path), quiet=True,
            batch_size=2, backend=RaisingBackend(), prefetch_depth=2,
        )
    assert time.perf_counter() - t0 < 10.0


def test_tracing_shim_removed_and_unreferenced():
    """The PR-2 ``metrics/tracing.py`` deprecation shim is gone (PR 3
    migrated the last internal import; PR 4 deleted it) — and nothing in
    the package source refers to it anymore."""
    import pathlib

    import music_analyst_tpu

    pkg_root = pathlib.Path(music_analyst_tpu.__file__).parent
    assert not (pkg_root / "metrics" / "tracing.py").exists()
    with pytest.raises(ImportError):
        import music_analyst_tpu.metrics.tracing  # noqa: F401
    offenders = [
        str(path)
        for path in pkg_root.rglob("*.py")
        if "metrics.tracing" in path.read_text(encoding="utf-8")
    ]
    assert not offenders, f"stale metrics.tracing imports: {offenders}"
