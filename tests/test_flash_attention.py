"""Flash attention kernel ≡ dense attention (the model-family invariant)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from music_analyst_tpu.models.layers import (
    causal_mask,
    dot_product_attention,
    padding_mask,
)
from music_analyst_tpu.ops.flash_attention import flash_attention


def _qkv(key, B, S, H, D, n_kv=None, kv_len=None):
    n_kv = n_kv or H
    kv_len = kv_len or S
    kq, kk, kv = jax.random.split(jax.random.key(key), 3)
    q = jax.random.normal(kq, (B, S, H, D), jnp.float32)
    k = jax.random.normal(kk, (B, kv_len, n_kv, D), jnp.float32)
    v = jax.random.normal(kv, (B, kv_len, n_kv, D), jnp.float32)
    return q, k, v


def test_matches_dense_full_attention():
    q, k, v = _qkv(0, B=2, S=256, H=4, D=64)
    out = flash_attention(q, k, v)
    ref = dot_product_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


def test_matches_dense_causal():
    q, k, v = _qkv(1, B=2, S=256, H=4, D=64)
    out = flash_attention(q, k, v, causal=True)
    ref = dot_product_attention(q, k, v, mask=causal_mask(256, 256, 0))
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


def test_matches_dense_padding_lengths():
    q, k, v = _qkv(2, B=3, S=128, H=2, D=64)
    lengths = jnp.asarray([128, 70, 1], jnp.int32)
    out = flash_attention(q, k, v, lengths=lengths)
    ref = dot_product_attention(q, k, v, mask=padding_mask(lengths, 128))
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


def test_gqa_head_grouping():
    q, k, v = _qkv(3, B=2, S=128, H=8, D=64, n_kv=2)
    out = flash_attention(q, k, v, causal=True)
    ref = dot_product_attention(q, k, v, mask=causal_mask(128, 128, 0))
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


def test_causal_plus_lengths_compose():
    q, k, v = _qkv(4, B=2, S=128, H=2, D=64)
    lengths = jnp.asarray([100, 128], jnp.int32)
    out = flash_attention(q, k, v, lengths=lengths, causal=True)
    mask = causal_mask(128, 128, 0) & padding_mask(lengths, 128)
    ref = dot_product_attention(q, k, v, mask=mask)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


def test_multiple_kv_blocks_online_softmax():
    """KV longer than one block exercises the running rescale."""
    q, k, v = _qkv(5, B=1, S=128, H=2, D=64, kv_len=512)
    out = flash_attention(q, k, v, block_kv=128)
    ref = dot_product_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


def test_bf16_io():
    q, k, v = _qkv(6, B=2, S=128, H=4, D=64)
    q, k, v = (x.astype(jnp.bfloat16) for x in (q, k, v))
    out = flash_attention(q, k, v, causal=True)
    assert out.dtype == jnp.bfloat16
    ref = dot_product_attention(q, k, v, mask=causal_mask(128, 128, 0))
    np.testing.assert_allclose(
        np.asarray(out, jnp.float32), np.asarray(ref, jnp.float32),
        atol=3e-2, rtol=3e-2,
    )


def test_ragged_blocks_fall_back_to_divisors():
    """Requested blocks that don't divide S degrade to a smaller
    tile-aligned divisor (ADVICE r1: S=768 with the default block_q=512
    used to raise) and stay correct."""
    q, k, v = _qkv(7, B=1, S=768, H=2, D=64)
    out = flash_attention(q, k, v, block_q=512, block_kv=512)
    ref = dot_product_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


def test_fit_block_tile_aligned_divisors_only():
    from music_analyst_tpu.ops.flash_attention import _fit_block

    assert _fit_block(512, 768) == 384   # largest 8-aligned divisor ≤ 512
    assert _fit_block(512, 256) == 256   # exact fit
    assert _fit_block(512, 7) == 7       # ≤ one tile: whole sequence
    assert _fit_block(8, 1024) == 8
    with pytest.raises(ValueError, match="pad the sequence"):
        _fit_block(64, 100)              # no 8-aligned divisor exists


def _random_segments(key, B, S, max_segs=4):
    """Contiguous segment ids 1..k per row plus a trailing pad segment 0."""
    rng = np.random.default_rng(key)
    seg = np.zeros((B, S), np.int32)
    for b in range(B):
        n_segs = rng.integers(1, max_segs + 1)
        # Random cut points -> contiguous spans, like pack_segments output.
        cuts = np.sort(rng.choice(np.arange(1, S - 1), size=n_segs - 1,
                                  replace=False)) if n_segs > 1 else []
        bounds = [0, *cuts, rng.integers(S // 2, S + 1)]
        for i in range(len(bounds) - 1):
            if bounds[i] < bounds[i + 1]:
                seg[b, bounds[i]:bounds[i + 1]] = i + 1
    return jnp.asarray(seg)


def test_segment_ids_match_dense_block_diagonal():
    """Segment-masked flash ≡ dense attention under the same block-diagonal
    mask (the packed-batch contract, models/distilbert.py)."""
    B, S, H, D = 3, 256, 4, 64
    q, k, v = _qkv(7, B=B, S=S, H=H, D=D)
    seg = _random_segments(7, B, S)
    out = flash_attention(q, k, v, q_segment_ids=seg, block_q=64,
                          block_kv=64)
    mask = (seg[:, None, :, None] == seg[:, None, None, :])
    ref = dot_product_attention(q, k, v, mask=mask)
    # Compare only rows with a real segment: dense gives fully-masked
    # (pad-segment-0-vs-itself differs only where both formulations are
    # garbage-by-contract; segment 0 matches itself in both, so compare
    # everything).
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


def test_segment_ids_compose_with_lengths_and_gqa():
    B, S, H, D = 2, 128, 8, 64
    q, k, v = _qkv(8, B=B, S=S, H=H, D=D, n_kv=2)
    seg = _random_segments(11, B, S)
    lengths = jnp.asarray([128, 100], jnp.int32)
    out = flash_attention(q, k, v, lengths=lengths, q_segment_ids=seg,
                          block_q=32, block_kv=32)
    mask = ((seg[:, None, :, None] == seg[:, None, None, :])
            & padding_mask(lengths, S))
    ref = dot_product_attention(q, k, v, mask=mask)
    # Fully-masked queries (pad tokens beyond `lengths` whose segment has
    # no valid key) are garbage in both formulations (flash: zeros; dense:
    # uniform-average) — compare only queries with >= 1 valid key.
    valid_q = np.asarray(mask.sum(axis=-1) > 0)[:, 0]  # [B, S]
    out, ref = np.asarray(out), np.asarray(ref)
    np.testing.assert_allclose(out[valid_q], ref[valid_q],
                               atol=2e-5, rtol=2e-5)


def test_segment_ids_isolation():
    """Tokens in one segment are bit-wise independent of other segments'
    content: perturbing segment 2 must not change segment 1's output."""
    B, S, H, D = 1, 128, 2, 64
    q, k, v = _qkv(9, B=B, S=S, H=H, D=D)
    seg = jnp.asarray(np.repeat([[1, 2]], 64, axis=1).reshape(1, S))
    out1 = flash_attention(q, k, v, q_segment_ids=seg, block_q=32,
                           block_kv=32)
    k2 = k.at[:, 64:].multiply(3.0)
    v2 = v.at[:, 64:].add(7.0)
    out2 = flash_attention(q, k2, v2, q_segment_ids=seg, block_q=32,
                           block_kv=32)
    np.testing.assert_array_equal(np.asarray(out1)[:, :64],
                                  np.asarray(out2)[:, :64])
    assert np.abs(np.asarray(out1)[:, 64:] -
                  np.asarray(out2)[:, 64:]).max() > 1e-3


def test_segment_ids_validation():
    q, k, v = _qkv(10, B=1, S=64, H=2, D=64, kv_len=128)
    seg = jnp.zeros((1, 64), jnp.int32)
    with pytest.raises(ValueError, match="kv_segment_ids is required"):
        flash_attention(q, k, v, q_segment_ids=seg)
    with pytest.raises(ValueError, match="without q_segment_ids"):
        flash_attention(q, k, v, kv_segment_ids=jnp.zeros((1, 128),
                                                          jnp.int32))
