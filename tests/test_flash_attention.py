"""Flash attention kernel ≡ dense attention (the model-family invariant)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from music_analyst_tpu.models.layers import (
    causal_mask,
    dot_product_attention,
    padding_mask,
)
from music_analyst_tpu.ops.flash_attention import flash_attention


def _qkv(key, B, S, H, D, n_kv=None, kv_len=None):
    n_kv = n_kv or H
    kv_len = kv_len or S
    kq, kk, kv = jax.random.split(jax.random.key(key), 3)
    q = jax.random.normal(kq, (B, S, H, D), jnp.float32)
    k = jax.random.normal(kk, (B, kv_len, n_kv, D), jnp.float32)
    v = jax.random.normal(kv, (B, kv_len, n_kv, D), jnp.float32)
    return q, k, v


def test_matches_dense_full_attention():
    q, k, v = _qkv(0, B=2, S=256, H=4, D=64)
    out = flash_attention(q, k, v)
    ref = dot_product_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


def test_matches_dense_causal():
    q, k, v = _qkv(1, B=2, S=256, H=4, D=64)
    out = flash_attention(q, k, v, causal=True)
    ref = dot_product_attention(q, k, v, mask=causal_mask(256, 256, 0))
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


def test_matches_dense_padding_lengths():
    q, k, v = _qkv(2, B=3, S=128, H=2, D=64)
    lengths = jnp.asarray([128, 70, 1], jnp.int32)
    out = flash_attention(q, k, v, lengths=lengths)
    ref = dot_product_attention(q, k, v, mask=padding_mask(lengths, 128))
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


def test_gqa_head_grouping():
    q, k, v = _qkv(3, B=2, S=128, H=8, D=64, n_kv=2)
    out = flash_attention(q, k, v, causal=True)
    ref = dot_product_attention(q, k, v, mask=causal_mask(128, 128, 0))
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


def test_causal_plus_lengths_compose():
    q, k, v = _qkv(4, B=2, S=128, H=2, D=64)
    lengths = jnp.asarray([100, 128], jnp.int32)
    out = flash_attention(q, k, v, lengths=lengths, causal=True)
    mask = causal_mask(128, 128, 0) & padding_mask(lengths, 128)
    ref = dot_product_attention(q, k, v, mask=mask)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


def test_multiple_kv_blocks_online_softmax():
    """KV longer than one block exercises the running rescale."""
    q, k, v = _qkv(5, B=1, S=128, H=2, D=64, kv_len=512)
    out = flash_attention(q, k, v, block_kv=128)
    ref = dot_product_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


def test_bf16_io():
    q, k, v = _qkv(6, B=2, S=128, H=4, D=64)
    q, k, v = (x.astype(jnp.bfloat16) for x in (q, k, v))
    out = flash_attention(q, k, v, causal=True)
    assert out.dtype == jnp.bfloat16
    ref = dot_product_attention(q, k, v, mask=causal_mask(128, 128, 0))
    np.testing.assert_allclose(
        np.asarray(out, jnp.float32), np.asarray(ref, jnp.float32),
        atol=3e-2, rtol=3e-2,
    )


def test_ragged_blocks_fall_back_to_divisors():
    """Requested blocks that don't divide S degrade to a smaller
    tile-aligned divisor (ADVICE r1: S=768 with the default block_q=512
    used to raise) and stay correct."""
    q, k, v = _qkv(7, B=1, S=768, H=2, D=64)
    out = flash_attention(q, k, v, block_q=512, block_kv=512)
    ref = dot_product_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


def test_fit_block_tile_aligned_divisors_only():
    from music_analyst_tpu.ops.flash_attention import _fit_block

    assert _fit_block(512, 768) == 384   # largest 8-aligned divisor ≤ 512
    assert _fit_block(512, 256) == 256   # exact fit
    assert _fit_block(512, 7) == 7       # ≤ one tile: whole sequence
    assert _fit_block(8, 1024) == 8
    with pytest.raises(ValueError, match="pad the sequence"):
        _fit_block(64, 100)              # no 8-aligned divisor exists
