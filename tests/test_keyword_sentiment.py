"""Device keyword kernel vs the reference heuristic, exactly re-stated."""

import numpy as np
import pytest

from music_analyst_tpu.ops.keyword_sentiment import (
    MAX_KEYWORD_LEN,
    NEGATIVE_KEYWORDS,
    POSITIVE_KEYWORDS,
    encode_batch,
    keyword_labels,
    keyword_scores,
    score_texts,
)
from music_analyst_tpu.utils.labels import score_to_label


def reference_mock_classify(lyrics: str) -> str:
    """Verbatim restatement of scripts/sentiment_classifier.py:57-83."""
    lyrics = lyrics.strip()
    if not lyrics:
        return "Neutral"
    lowered = lyrics.lower()
    score = 0
    for word in POSITIVE_KEYWORDS:
        if word in lowered:
            score += 1
    for word in NEGATIVE_KEYWORDS:
        if word in lowered:
            score -= 1
    if score > 0:
        return "Positive"
    if score < 0:
        return "Negative"
    return "Neutral"


CASES = [
    "I love sunshine and smiles",           # positive
    "cry me a river of tears",              # negative
    "LOVE and PAIN in equal measure",       # balanced -> neutral
    "nothing to see here",                  # no keywords
    "",                                     # empty
    "   \t  ",                              # whitespace only
    "lovely day",                           # substring containment: 'love'
    "crying sadness",                       # 'cry' + 'sad'
    "sunshine sunshine sunshine",           # repeats count once
    "hap py jo y",                          # split keywords don't match
    "Smile! though your heart is aching",   # punctuation adjacent
]


def test_kernel_matches_reference_on_cases():
    got = [score_to_label(int(s)) for s in score_texts(CASES)]
    want = [reference_mock_classify(t) for t in CASES]
    assert got == want


def test_kernel_matches_reference_randomized():
    rng = np.random.default_rng(42)
    words = list(POSITIVE_KEYWORDS + NEGATIVE_KEYWORDS) + [
        "the", "music", "night", "dance", "street", "heart", "fire",
    ]
    texts = [
        " ".join(rng.choice(words, size=rng.integers(0, 40)))
        for _ in range(300)
    ]
    got = [score_to_label(int(s)) for s in score_texts(texts)]
    want = [reference_mock_classify(t) for t in texts]
    assert got == want


def test_long_lyric_chunked_path_exact():
    # Keyword placed beyond the dense window and straddling a window edge.
    filler = "na " * 3000  # ~9000 bytes > 4096 window
    text = filler + "sunshine"
    assert score_to_label(int(score_texts([text], length=4096)[0])) == "Positive"
    # keyword exactly straddles the first window boundary
    pad = "x" * (4096 - 4)
    straddle = pad + "tears"
    assert (
        score_to_label(int(score_texts([straddle], length=4096)[0]))
        == reference_mock_classify(straddle)
    )


def test_label_ids_device_path():
    batch, overflow = encode_batch(["love", "tears", "meh"], 64)
    assert overflow == []
    labels = np.asarray(keyword_labels(batch))
    np.testing.assert_array_equal(labels, [0, 2, 1])


def test_uppercase_handled_on_device():
    batch, _ = encode_batch(["LOVE IS ALL", "TEARS FALL"], 64)
    scores = np.asarray(keyword_scores(batch))
    assert scores[0] == 1 and scores[1] == -1
