"""Full-size Llama-3-8B program construction under dp×tp (VERDICT r4 §2.4).

The zero-egress, one-chip environment can never *execute* the 8B config
with real weights, so TP at true scale was the one evidence gap in the
parallelism story.  This test closes what is closable without hardware:
abstractly initialize the FULL 8B parameter tree (``jax.eval_shape`` —
no bytes materialize), attach the production TP partition specs to every
leaf on a dp×tp mesh, and ``jit(...).lower()`` the forward — which runs
the whole tracing + SPMD-partitioning pipeline over the real 8B shapes
and fails loudly on any axis-divisibility or rule mismatch a real pod
run would hit.  Compilation/execution is deliberately skipped (hours of
XLA time for no additional sharding signal).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from music_analyst_tpu.models.layers import causal_mask
from music_analyst_tpu.models.llama import LlamaConfig, LlamaModel
from music_analyst_tpu.parallel.sharding import partition_specs, prune_spec


@pytest.mark.parametrize("tp", [2, 4])
def test_llama3_8b_forward_lowers_sharded(tp):
    cfg = LlamaConfig()  # the real 8B architecture (BASELINE config[3])
    assert cfg.dim == 4096 and cfg.n_layers == 32  # guard: full size
    model = LlamaModel(cfg)
    devices = np.array(jax.devices()[: 8]).reshape(8 // tp, tp)
    mesh = Mesh(devices, ("dp", "tp"))

    B, S = 8, 256
    ids = jax.ShapeDtypeStruct((B, S), jnp.int32)
    pos = jax.ShapeDtypeStruct((B, S), jnp.int32)

    # Abstract init: the full 8B param tree as shapes only.
    params_shape = jax.eval_shape(
        lambda k: model.init(
            k,
            jnp.zeros((1, 8), jnp.int32),
            jnp.zeros((1, 8), jnp.int32),
            causal_mask(8, 8, 0),
        )["params"],
        jax.random.key(0),
    )
    n_params = sum(
        int(np.prod(leaf.shape))
        for leaf in jax.tree_util.tree_leaves(params_shape)
    )
    assert n_params > 7.5e9, f"not the 8B config ({n_params/1e9:.2f}B)"

    # Production TP rules → NamedShardings on every leaf; every sharded
    # axis must divide by tp or lower() raises.
    specs = partition_specs(params_shape)
    axis_names = set(mesh.axis_names)
    pruned = jax.tree_util.tree_map(
        lambda spec: prune_spec(spec, axis_names),
        specs,
        is_leaf=lambda x: isinstance(x, P),
    )
    params_sharded = jax.tree_util.tree_map(
        lambda leaf, spec: jax.ShapeDtypeStruct(
            leaf.shape, leaf.dtype, sharding=NamedSharding(mesh, spec)
        ),
        params_shape,
        pruned,
    )
    data_sharding = NamedSharding(mesh, P("dp"))
    ids = jax.ShapeDtypeStruct(ids.shape, ids.dtype, sharding=data_sharding)
    pos = jax.ShapeDtypeStruct(pos.shape, pos.dtype, sharding=data_sharding)

    def forward(params, token_ids, positions):
        logits, _ = model.apply(
            {"params": params}, token_ids, positions, causal_mask(S, S, 0)
        )
        return logits

    # The production rules actually produced a TP placement (not a
    # prune-to-replicated regression): the attention projections carry the
    # tp axis after pruning for this mesh.
    q_spec = pruned["layer_0"]["attention"]["q_proj"]["kernel"]
    assert "tp" in tuple(q_spec), q_spec

    lowered = jax.jit(forward).lower(params_sharded, ids, pos)
    hlo = lowered.as_text()
    # The partitioner really saw the 8-way mesh…
    assert "mhlo.num_partitions = 8" in hlo
    # …and the tp-sharded params survived into the program: every layer
    # contributes several tp-annotated arguments (q/k/v/o + MLP), so the
    # count must exceed the layer count by a wide margin.  The textual
    # sharding format differs by jax version/partitioner: Shardy prints
    # axis names ('{"tp"}'), GSPMD prints device tilings
    # ('mhlo.sharding = "{devices=[…]…}"') — count whichever appears.
    n_shardy = hlo.count('{"tp"}')
    n_gspmd = hlo.count('mhlo.sharding = "{devices=')
    # Under GSPMD the two dp-sharded data args also carry tilings;
    # everything beyond those is a partitioned parameter (the only other
    # specs the rules emit are tp or replicated, and replication prints
    # as "{replicated}").
    n_tp = n_shardy if n_shardy else max(0, n_gspmd - 2)
    assert n_tp >= cfg.n_layers * 4, (n_shardy, n_gspmd)


def test_llama3_8b_weight_quantized_fits_one_chip_and_lowers():
    """Stored-int8 8B tree fits a single 16 GB HBM chip, and the
    weight-quantized forward lowers under the production dp×tp rules.

    The bf16 8B tree is ~16 GB — it does NOT fit one v5e chip next to
    activations; the whole point of the weight-only store is that the
    int8 tree (codes + scales + float embeddings/norms) does.  The byte
    budget is asserted from ``param_tree_bytes`` over the abstract
    quantized tree (no bytes materialize), then the quantized forward is
    lowered exactly like the float test above so SPMD partitioning sees
    the packed shapes.
    """
    import dataclasses

    from music_analyst_tpu.ops.quant import (
        QuantizedParam,
        param_tree_bytes,
        quantize_tree,
    )

    cfg = LlamaConfig()
    assert cfg.dim == 4096 and cfg.n_layers == 32
    model = LlamaModel(cfg)
    params_shape = jax.eval_shape(
        lambda k: model.init(
            k,
            jnp.zeros((1, 8), jnp.int32),
            jnp.zeros((1, 8), jnp.int32),
            causal_mask(8, 8, 0),
        )["params"],
        jax.random.key(0),
    )
    qtree = jax.eval_shape(lambda t: quantize_tree(t, "int8"), params_shape)

    accounted = param_tree_bytes(qtree)
    HBM = 16 * (1 << 30)
    assert accounted["stored_bytes"] < HBM, accounted
    # The quantizer actually hit the decoder stack: every layer's 7
    # projection kernels plus lm_head.
    assert accounted["n_quantized_leaves"] == cfg.n_layers * 7 + 1
    # The runtime bound: stored tree + the largest single dequant working
    # buffer (the accounting's conservative upper bound — the fused
    # epilogue never actually materializes float weights) still fits.
    assert (accounted["stored_bytes"]
            + accounted["dequant_transient_bytes"] < HBM), accounted

    # Lower the weight-quantized forward under dp×tp: partition specs
    # handle QuantizedParam leaves atomically (q gets the kernel rule,
    # scales replicate over contraction axes).
    qcfg = dataclasses.replace(cfg, weight_quant="int8")
    qmodel = LlamaModel(qcfg)
    mesh = Mesh(np.array(jax.devices()[:8]).reshape(4, 2), ("dp", "tp"))
    axis_names = set(mesh.axis_names)
    specs = partition_specs(qtree)

    def _sds(leaf, spec):
        return jax.ShapeDtypeStruct(
            leaf.shape, leaf.dtype,
            sharding=NamedSharding(mesh, prune_spec(spec, axis_names)),
        )

    def _shard_leaf(leaf, spec):
        if isinstance(leaf, QuantizedParam):
            import dataclasses as dc

            return dc.replace(
                leaf, q=_sds(leaf.q, spec.q), scale=_sds(leaf.scale, spec.scale)
            )
        return _sds(leaf, spec)

    is_qp = lambda x: isinstance(x, QuantizedParam)
    params_sharded = jax.tree_util.tree_map(
        _shard_leaf, qtree, specs, is_leaf=is_qp
    )
    B, S = 8, 256
    data_sharding = NamedSharding(mesh, P("dp"))
    ids = jax.ShapeDtypeStruct((B, S), jnp.int32, sharding=data_sharding)
    pos = jax.ShapeDtypeStruct((B, S), jnp.int32, sharding=data_sharding)

    def forward(params, token_ids, positions):
        logits, _ = qmodel.apply(
            {"params": params}, token_ids, positions, causal_mask(S, S, 0)
        )
        return logits

    hlo = jax.jit(forward).lower(params_sharded, ids, pos).as_text()
    assert "mhlo.num_partitions = 8" in hlo
