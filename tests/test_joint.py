"""Joint pipeline: all five artifacts from one run; multihost no-op path."""

import json

import pytest

from music_analyst_tpu.cli.main import main
from music_analyst_tpu.engines.joint import run_joint


def test_joint_writes_all_artifacts(fixture_csv, tmp_path):
    result = run_joint(
        str(fixture_csv), output_dir=str(tmp_path), mock=True, quiet=True
    )
    for name in (
        "word_counts.csv",
        "top_artists.csv",
        "sentiment_totals.json",
        "sentiment_details.csv",
        "performance_metrics.json",
    ):
        assert (tmp_path / name).exists(), name
    metrics = json.loads((tmp_path / "performance_metrics.json").read_text())
    assert "sentiment" in metrics["stages"]
    assert "ingest" in metrics["stages"]
    assert result.analysis.total_songs == 7
    # Fused pipeline: ONE parse, one parser, one consistent song count
    # (the pre-fusion 7-vs-8 split between the exact parser and the
    # DictReader re-read is gone inside a joint run).
    assert sum(result.sentiment.counts.values()) == 7
    assert len(result.sentiment.rows) == result.analysis.total_songs
    assert result.songs_per_second > 0
    # The per-chip column carries the wordcount engine's measured per-chip
    # values plus the lock-stepped sentiment stage (a constant offset).
    per_chip = [e["compute_seconds"] for e in metrics["per_chip"]]
    assert len(per_chip) == 8
    sentiment_seconds = metrics["stages"]["sentiment"]
    for got, base in zip(per_chip, result.analysis.per_chip_compute):
        assert got == pytest.approx(base + sentiment_seconds, abs=1e-6)


def test_joint_reads_dataset_once(fixture_csv, tmp_path, monkeypatch):
    """The sentiment stage must consume captured ingest records — never a
    second DictReader pass over the file (BASELINE config[4] fusion)."""
    from music_analyst_tpu.engines import sentiment as sentiment_mod

    def _boom(*a, **k):
        raise AssertionError("joint pipeline re-read the dataset")

    monkeypatch.setattr(sentiment_mod, "iter_songs", _boom)
    result = run_joint(
        str(fixture_csv), output_dir=str(tmp_path), mock=True, quiet=True
    )
    assert sum(result.sentiment.counts.values()) == 7


def test_joint_sentiment_rows_carry_song_titles(fixture_csv, tmp_path):
    result = run_joint(
        str(fixture_csv), output_dir=str(tmp_path), mock=True, quiet=True
    )
    by_song = {row.song: row.artist for row in result.sentiment.rows}
    assert by_song["Ahe's My Kind Of Girl"] == "ABBA"
    assert by_song["Unknown Song"] == ""  # empty-artist record still counted


def test_joint_via_cli(fixture_csv, tmp_path, capsys):
    rc = main(
        [
            "analyze",
            str(fixture_csv),
            "--with-sentiment",
            "--mock",
            "--output-dir",
            str(tmp_path),
        ]
    )
    assert rc == 0
    out = capsys.readouterr().out
    assert "Joint pipeline:" in out
    assert (tmp_path / "sentiment_totals.json").exists()


def test_multihost_single_process_degenerates():
    from music_analyst_tpu.parallel import multihost

    assert multihost.process_count() == 1
    assert multihost.is_coordinator()
    assert multihost.broadcast_from_coordinator({"a": 1}) == {"a": 1}
    multihost.barrier("test")  # no-op, must not raise
    assert multihost.all_agree(42)
