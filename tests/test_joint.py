"""Joint pipeline: all five artifacts from one run; multihost no-op path."""

import json

from music_analyst_tpu.cli.main import main
from music_analyst_tpu.engines.joint import run_joint


def test_joint_writes_all_artifacts(fixture_csv, tmp_path):
    result = run_joint(
        str(fixture_csv), output_dir=str(tmp_path), mock=True, quiet=True
    )
    for name in (
        "word_counts.csv",
        "top_artists.csv",
        "sentiment_totals.json",
        "sentiment_details.csv",
        "performance_metrics.json",
    ):
        assert (tmp_path / name).exists(), name
    metrics = json.loads((tmp_path / "performance_metrics.json").read_text())
    assert "sentiment" in metrics["stages"]
    assert "ingest" in metrics["stages"]
    assert result.analysis.total_songs == 7
    assert sum(result.sentiment.counts.values()) == 8  # DictReader rows
    assert result.songs_per_second > 0


def test_joint_via_cli(fixture_csv, tmp_path, capsys):
    rc = main(
        [
            "analyze",
            str(fixture_csv),
            "--with-sentiment",
            "--mock",
            "--output-dir",
            str(tmp_path),
        ]
    )
    assert rc == 0
    out = capsys.readouterr().out
    assert "Joint pipeline:" in out
    assert (tmp_path / "sentiment_totals.json").exists()


def test_multihost_single_process_degenerates():
    from music_analyst_tpu.parallel import multihost

    assert multihost.process_count() == 1
    assert multihost.is_coordinator()
    assert multihost.broadcast_from_coordinator({"a": 1}) == {"a": 1}
    multihost.barrier("test")  # no-op, must not raise
    assert multihost.all_agree(42)
