"""The benchmark registry must stay real: every advertised suite imports,
registers, runs (smoke shapes), and returns a JSON-serializable table.

Round-2 regression guard: the registry once advertised six modules of
which zero existed (VERDICT round 2, Weak #1) — this test makes an empty
or import-broken registry a test failure, not a silent stderr warning.
"""

import json

import pytest


def test_every_advertised_module_registers(monkeypatch):
    monkeypatch.setenv("MUSICAAL_BENCH_SMOKE", "1")
    import benchmarks

    names = benchmarks.suite_names()
    # Every module in the advertised tuple must have registered >= 1 suite.
    assert len(names) >= len(benchmarks._SUITE_MODULES)
    for expected in (
        "roofline", "flash_sweep", "generation", "coldstart", "ingest",
        "scaling", "joint", "llama_zeroshot", "sentiment_int8", "bucketing",
        "overlap", "streaming", "serving", "router", "slo", "crash",
    ):
        assert expected in names


@pytest.mark.parametrize(
    "name",
    ["roofline", "flash_sweep", "generation", "ingest", "joint",
     "llama_zeroshot", "sentiment_int8", "bucketing", "overlap",
     "streaming", "serving"],
)
def test_suite_runs_smoke(name, monkeypatch):
    monkeypatch.setenv("MUSICAAL_BENCH_SMOKE", "1")
    import benchmarks

    benchmarks._load_all()
    table = benchmarks._SUITES[name]()
    assert table["suite"] == name
    assert table["smoke"] is True
    json.dumps(table)  # must be a valid JSON document


@pytest.mark.parametrize("name", ["coldstart", "scaling", "router"])
def test_subprocess_suite_runs_smoke(name, monkeypatch):
    """The suites that spawn fresh Python processes (cold-start cost,
    device-count sweep, replica fleet) — slower, so split out for
    visibility."""
    monkeypatch.setenv("MUSICAAL_BENCH_SMOKE", "1")
    import benchmarks

    benchmarks._load_all()
    table = benchmarks._SUITES[name]()
    assert table["suite"] == name
    json.dumps(table)
    if name == "coldstart":
        assert table["warm_process_seconds"] > 0
    elif name == "router":
        assert table["failover_drill"]["zero_loss"] is True
        assert all(r["balanced"] for r in table["rows"])
    else:
        assert len(table["runs"]) >= 1


def test_slo_suite_meets_acceptance_bar(monkeypatch):
    """The overload suite's headline booleans ARE the ISSUE-13 bar:
    gold TTFT inside its SLO at 4× load, every rejection structured,
    nothing silently dropped, preempt-resume byte-identical with zero
    retraces."""
    monkeypatch.setenv("MUSICAAL_BENCH_SMOKE", "1")
    import benchmarks

    benchmarks._load_all()
    table = benchmarks._SUITES["slo"]()
    assert table["suite"] == "slo" and table["smoke"] is True
    json.dumps(table)
    assert table["gold_within_slo"] is True
    assert table["all_sheds_structured"] is True
    assert table["zero_silent_drops"] is True
    assert table["preempt_bytes_identical"] is True
    assert table["zero_retraces"] is True
