"""Column splitters: dataset preprocessor + generic tool."""

from music_analyst_tpu.data.splitter import (
    read_header_labels,
    sanitize_filename,
    sanitize_header_name,
    split_csv_columns,
    split_dataset_columns,
)


class TestSanitizers:
    def test_header_name_c_semantics(self):
        assert sanitize_header_name("artist") == "artist"
        assert sanitize_header_name("my col!") == "my_col_"
        assert sanitize_header_name("") == "col"
        # multi-byte char -> one underscore per byte (C byte loop)
        assert sanitize_header_name("é") == "__"
        assert sanitize_header_name("a\r\nb") == "ab"

    def test_filename_python_semantics(self):
        assert sanitize_filename("My Col!") == "My_Col_"
        assert sanitize_filename("") == "col"
        # \w is Unicode in the generic tool: accents survive
        assert sanitize_filename("é") == "é"


class TestDatasetSplitter:
    def test_split_preserves_quoting(self, fixture_csv, tmp_path):
        artist_label, text_label = read_header_labels(str(fixture_csv))
        assert (artist_label, text_label) == ("artist", "text")
        artist_path, text_path = split_dataset_columns(
            str(fixture_csv),
            str(tmp_path / "split_columns"),
            sanitize_header_name(artist_label),
            sanitize_header_name(text_label),
            artist_label,
            text_label,
        )
        artist_lines = open(artist_path, "rb").read().split(b"\n")
        assert artist_lines[0] == b"artist"
        assert artist_lines[1] == b"ABBA"
        # Quoted artist stays quoted verbatim
        assert b'"Earth, Wind & Fire"' in artist_lines
        text_data = open(text_path, "rb").read()
        # Outer quotes + escaped quotes preserved, embedded newline preserved
        assert b'""summer evening""' in text_data
        assert b"wonderful face  \nAnd it means" in text_data

    def test_bad_rows_skipped(self, fixture_csv, tmp_path):
        artist_path, _ = split_dataset_columns(
            str(fixture_csv), str(tmp_path), "artist", "text", "artist", "text"
        )
        content = open(artist_path, "rb").read()
        assert b"BadRow" not in content


class TestSplitterBackends:
    def test_native_matches_python_byte_for_byte(self, fixture_csv, tmp_path):
        from music_analyst_tpu.data import native

        if not native.available():
            import pytest

            pytest.skip("native lib unavailable")
        a = split_dataset_columns(
            str(fixture_csv), str(tmp_path / "py"), "artist", "text",
            "artist", "text", backend="python",
        )
        b = split_dataset_columns(
            str(fixture_csv), str(tmp_path / "nat"), "artist", "text",
            "artist", "text", backend="native",
        )
        for pa, pb in zip(a, b):
            assert open(pa, "rb").read() == open(pb, "rb").read()

    def test_native_matches_python_lone_cr(self, tmp_path):
        from music_analyst_tpu.data import native

        if not native.available():
            import pytest

            pytest.skip("native lib unavailable")
        src = tmp_path / "cr.csv"
        src.write_bytes(
            b"artist,song,link,text\r"
            b'A,S1,/l,"kept\rinside"\r'
            b"B,S2,/l,plain text\r\n"
            b"C,S3,/l,last row"
        )
        a = split_dataset_columns(
            str(src), str(tmp_path / "py"), "artist", "text",
            "artist", "text", backend="python",
        )
        b = split_dataset_columns(
            str(src), str(tmp_path / "nat"), "artist", "text",
            "artist", "text", backend="native",
        )
        for pa, pb in zip(a, b):
            assert open(pa, "rb").read() == open(pb, "rb").read()


class TestGenericSplitter:
    def test_one_file_per_column(self, fixture_csv, tmp_path):
        out_dir, names = split_csv_columns(
            str(fixture_csv), output_dir=str(tmp_path / "cols")
        )
        assert names == ["artist.csv", "song.csv", "link.csv", "text.csv"]
        artist_rows = (out_dir / "artist.csv").read_text(encoding="utf-8-sig")
        assert artist_rows.splitlines()[0] == "artist"
        assert "Beyoncé" in artist_rows

    def test_collision_suffixes(self, tmp_path):
        src = tmp_path / "dup.csv"
        src.write_text("a,a,b\n1,2,3\n", encoding="utf-8")
        out_dir, names = split_csv_columns(str(src), output_dir=str(tmp_path / "o"))
        assert names == ["a.csv", "a_2.csv", "b.csv"]

    def test_no_header_mode(self, tmp_path):
        src = tmp_path / "nh.csv"
        src.write_text("1,2\n3,4\n", encoding="utf-8")
        out_dir, names = split_csv_columns(
            str(src), output_dir=str(tmp_path / "o2"), no_header=True
        )
        assert names == ["col1.csv", "col2.csv"]
        assert (out_dir / "col1.csv").read_text(encoding="utf-8-sig") == "1\n3\n"
