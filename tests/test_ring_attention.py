"""Ring attention vs full attention on the 8-way sequence-parallel mesh."""

import numpy as np
import pytest

from music_analyst_tpu.models.layers import dot_product_attention
from music_analyst_tpu.ops.ring_attention import ring_attention
from music_analyst_tpu.parallel.mesh import build_mesh, MeshSpec


@pytest.fixture(scope="module")
def sp_mesh():
    return build_mesh(MeshSpec((("sp", 8),)))


def _rand_qkv(rng, B=2, S=64, H=4, D=16, kv_heads=None):
    q = rng.normal(size=(B, S, H, D)).astype(np.float32)
    k = rng.normal(size=(B, S, kv_heads or H, D)).astype(np.float32)
    v = rng.normal(size=(B, S, kv_heads or H, D)).astype(np.float32)
    return q, k, v


def test_matches_full_attention(sp_mesh):
    rng = np.random.default_rng(0)
    q, k, v = _rand_qkv(rng)
    want = np.asarray(dot_product_attention(q, k, v))
    got = np.asarray(ring_attention(q, k, v, sp_mesh))
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)


def test_matches_full_attention_causal(sp_mesh):
    rng = np.random.default_rng(1)
    q, k, v = _rand_qkv(rng)
    import jax.numpy as jnp

    S = q.shape[1]
    mask = (jnp.arange(S)[None, :] <= jnp.arange(S)[:, None])[None, None]
    want = np.asarray(dot_product_attention(q, k, v, mask))
    got = np.asarray(ring_attention(q, k, v, sp_mesh, causal=True))
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)


def test_long_sequence_small_blocks(sp_mesh):
    # sequence length 512 -> 64 per device
    rng = np.random.default_rng(2)
    q, k, v = _rand_qkv(rng, B=1, S=512, H=2, D=8)
    want = np.asarray(dot_product_attention(q, k, v))
    got = np.asarray(ring_attention(q, k, v, sp_mesh))
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)


def test_flash_ring_matches_full_attention(sp_mesh):
    """Ring schedule with the Pallas per-hop kernel ≡ dense full attention."""
    rng = np.random.default_rng(3)
    q, k, v = _rand_qkv(rng, B=2, S=1024, H=2, D=64)
    want = np.asarray(dot_product_attention(q, k, v))
    got = np.asarray(ring_attention(q, k, v, sp_mesh, use_flash=True))
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-4)


def test_flash_ring_matches_full_attention_causal(sp_mesh):
    rng = np.random.default_rng(4)
    q, k, v = _rand_qkv(rng, B=1, S=1024, H=2, D=64)
    import jax.numpy as jnp

    S = q.shape[1]
    mask = (jnp.arange(S)[None, :] <= jnp.arange(S)[:, None])[None, None]
    want = np.asarray(dot_product_attention(q, k, v, mask))
    got = np.asarray(
        ring_attention(q, k, v, sp_mesh, causal=True, use_flash=True)
    )
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-4)


def test_gqa_ring_matches_full_attention(sp_mesh):
    rng = np.random.default_rng(5)
    q, k, v = _rand_qkv(rng, B=2, S=64, H=8, D=16, kv_heads=2)
    want = np.asarray(dot_product_attention(q, k, v))
    got = np.asarray(ring_attention(q, k, v, sp_mesh))
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)


def test_gqa_flash_ring_matches_full_attention(sp_mesh):
    rng = np.random.default_rng(6)
    q, k, v = _rand_qkv(rng, B=1, S=1024, H=4, D=64, kv_heads=2)
    want = np.asarray(dot_product_attention(q, k, v))
    got = np.asarray(ring_attention(q, k, v, sp_mesh, use_flash=True))
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-4)


def _doc_segments(rng, B, S, n_docs=5):
    """Contiguous document ids 1..n spanning the whole sequence — cut
    points deliberately NOT aligned to the 8-way shard boundaries."""
    import jax.numpy as jnp

    seg = np.zeros((B, S), np.int32)
    for b in range(B):
        cuts = np.sort(rng.choice(np.arange(1, S), size=n_docs - 1,
                                  replace=False))
        bounds = [0, *cuts.tolist(), S]
        for i in range(n_docs):
            seg[b, bounds[i]:bounds[i + 1]] = i + 1
    return jnp.asarray(seg)


def test_segmented_ring_matches_dense_block_diagonal(sp_mesh):
    """Packed-documents masking: ring with segment ids ≡ dense attention
    under the same block-diagonal mask, with segments crossing device
    boundaries."""
    rng = np.random.default_rng(7)
    q, k, v = _rand_qkv(rng, B=2, S=64, H=4, D=16)
    seg = _doc_segments(rng, 2, 64)
    mask = (seg[:, None, :, None] == seg[:, None, None, :])
    want = np.asarray(dot_product_attention(q, k, v, mask))
    got = np.asarray(ring_attention(q, k, v, sp_mesh, segment_ids=seg))
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)


def test_segmented_causal_ring(sp_mesh):
    rng = np.random.default_rng(8)
    q, k, v = _rand_qkv(rng, B=1, S=64, H=2, D=16)
    seg = _doc_segments(rng, 1, 64, n_docs=3)
    import jax.numpy as jnp

    S = q.shape[1]
    mask = ((seg[:, None, :, None] == seg[:, None, None, :])
            & (jnp.arange(S)[None, :] <= jnp.arange(S)[:, None])[None, None])
    want = np.asarray(dot_product_attention(q, k, v, mask))
    got = np.asarray(
        ring_attention(q, k, v, sp_mesh, causal=True, segment_ids=seg)
    )
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)


def test_segmented_flash_ring_matches_dense(sp_mesh):
    """Per-hop Pallas kernel with rotating segment shards ≡ dense."""
    rng = np.random.default_rng(9)
    q, k, v = _rand_qkv(rng, B=1, S=1024, H=2, D=64)
    seg = _doc_segments(rng, 1, 1024, n_docs=7)
    mask = (seg[:, None, :, None] == seg[:, None, None, :])
    want = np.asarray(dot_product_attention(q, k, v, mask))
    got = np.asarray(
        ring_attention(q, k, v, sp_mesh, use_flash=True, segment_ids=seg)
    )
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-4)
