"""Serving layer: batcher semantics, protocol equivalence, fault injection.

Three contract families (ISSUE 8):

* **batcher** — flush on max-batch AND on max-wait deadline; bounded
  admission sheds with structured ``queue_full`` (never blocks, never
  drops silently); a poison request fails alone.
* **equivalence** — the stdio serve path returns labels identical to the
  batch ``sentiment`` engine over the same inputs at every ``max_batch``
  in {1, 3, 8}, replies ordered per request id even under mid-stream
  queue pressure.
* **lifecycle** — SIGTERM mid-batch drains gracefully (exit 0, every
  admitted request answered, flight record left behind); the run
  manifest grows a ``serving`` section; histograms carry p50/p95/p99.
"""

import io
import json
import os
import signal
import subprocess
import sys
import time

import pytest

from music_analyst_tpu.serving.batcher import (
    DynamicBatcher,
    resolve_max_batch,
    resolve_max_queue,
    resolve_max_wait_ms,
)
from music_analyst_tpu.serving.residency import ModelResidency, warmup_sizes
from music_analyst_tpu.serving.server import SentimentServer, build_ops

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _echo_ops(batch_sizes=None, delay_s=0.0):
    """An instrumented echo op: records dispatched batch sizes."""
    def echo(texts):
        if batch_sizes is not None:
            batch_sizes.append(len([t for t in texts if t]))
        if delay_s:
            time.sleep(delay_s)
        return [{"text": t} for t in texts]

    return {"echo": echo}


# ------------------------------------------------------------------ batcher


def test_resolve_flags_and_env(monkeypatch):
    assert resolve_max_batch(None) == 32
    assert resolve_max_batch(7) == 7
    monkeypatch.setenv("MUSICAAL_SERVE_MAX_BATCH", "16")
    assert resolve_max_batch(None) == 16
    monkeypatch.setenv("MUSICAAL_SERVE_MAX_BATCH", "junk")
    assert resolve_max_batch(None) == 32  # malformed env falls back
    monkeypatch.setenv("MUSICAAL_SERVE_MAX_WAIT_MS", "12.5")
    assert resolve_max_wait_ms(None) == 12.5
    monkeypatch.setenv("MUSICAAL_SERVE_MAX_QUEUE", "-3")
    assert resolve_max_queue(None) == 1024
    with pytest.raises(ValueError):
        resolve_max_batch("junk")  # explicit flag is a usage error
    with pytest.raises(ValueError):
        resolve_max_wait_ms(-1.0)


def test_flush_on_max_batch():
    sizes = []
    b = DynamicBatcher(_echo_ops(sizes), max_batch=4,
                       max_wait_ms=10_000.0, max_queue=64).start()
    try:
        reqs = [b.submit(i, "echo", f"t{i}") for i in range(4)]
        for r in reqs:
            assert r.wait(5.0)
        # Deadline was far away: the flush must have been the size trigger.
        assert sizes == [4]
        assert [r.response["text"] for r in reqs] == [
            "t0", "t1", "t2", "t3"
        ]
    finally:
        b.drain()


def test_flush_on_deadline():
    sizes = []
    b = DynamicBatcher(_echo_ops(sizes), max_batch=64,
                       max_wait_ms=20.0, max_queue=64).start()
    try:
        start = time.monotonic()
        reqs = [b.submit(i, "echo", f"t{i}") for i in range(3)]
        for r in reqs:
            assert r.wait(5.0)
        waited = time.monotonic() - start
        assert sizes == [3]  # partial batch, flushed by the deadline
        assert waited >= 0.015  # ...not before it
    finally:
        b.drain()


def test_queue_full_sheds_structured():
    b = DynamicBatcher(_echo_ops(delay_s=0.05), max_batch=2,
                       max_wait_ms=1.0, max_queue=2).start()
    try:
        reqs = [b.submit(i, "echo", f"t{i}") for i in range(12)]
        for r in reqs:
            assert r.wait(10.0)
        shed = [r for r in reqs if not r.response["ok"]]
        served = [r for r in reqs if r.response["ok"]]
        assert shed and served  # overload: some of each
        assert {r.response["error"]["kind"] for r in shed} == {"queue_full"}
        # Shedding is immediate — a shed request is settled at submit time.
        # The batcher survives: a later request still gets served.
        late = b.submit("late", "echo", "still alive")
        assert late.wait(10.0)
        assert late.response["ok"]
        stats = b.stats()
        assert stats["shed"] == len(shed)
        assert stats["completed"] == len(served) + 1
    finally:
        b.drain()


def test_unknown_op_and_drain_refusal():
    b = DynamicBatcher(_echo_ops(), max_batch=2, max_wait_ms=1.0).start()
    bad = b.submit("x", "nope", "text")
    assert bad.done and bad.response["error"]["kind"] == "bad_request"
    b.drain()
    refused = b.submit("y", "echo", "after drain")
    assert refused.done and refused.response["error"]["kind"] == "draining"


def test_poison_request_fails_alone():
    def poisoned(texts):
        if any("POISON" in t for t in texts):
            raise RuntimeError("bad row in batch")
        return [{"text": t} for t in texts]

    b = DynamicBatcher({"echo": poisoned}, max_batch=4,
                       max_wait_ms=10_000.0, max_queue=16).start()
    try:
        texts = ["ok-a", "POISON pill", "ok-b", "ok-c"]
        reqs = [b.submit(i, "echo", t) for i, t in enumerate(texts)]
        for r in reqs:
            assert r.wait(10.0)
        assert reqs[0].response["ok"] and reqs[2].response["ok"]
        assert reqs[3].response["ok"]
        poison = reqs[1].response
        assert poison["ok"] is False
        assert poison["error"]["kind"] == "request_failed"
        assert poison["id"] == 1  # the structured error names the request
        stats = b.stats()
        assert stats["isolation_retries"] >= 1
        assert stats["failed"] == 1 and stats["completed"] == 3
    finally:
        b.drain()


def test_padding_is_pow2_buckets():
    b = DynamicBatcher(_echo_ops(), max_batch=8, max_wait_ms=5.0,
                       max_queue=16).start()
    try:
        reqs = [b.submit(i, "echo", f"t{i}") for i in range(3)]
        for r in reqs:
            assert r.wait(5.0)
        stats = b.stats()
        assert stats["rows"] == 3
        assert stats["padded_rows"] == 4  # 3 → pow2 bucket 4
    finally:
        b.drain()


def test_in_batch_dedup_folds_identical_texts():
    """Identical texts in one flush occupy ONE device row; every
    requester still gets its own (identical) reply."""
    sizes = []
    b = DynamicBatcher(_echo_ops(sizes), max_batch=6,
                       max_wait_ms=10_000.0, max_queue=16).start()
    try:
        texts = ["same song"] * 4 + ["other", "third"]
        reqs = [b.submit(i, "echo", t) for i, t in enumerate(texts)]
        for r in reqs:
            assert r.wait(5.0)
        # All six answered, each with its own text, despite 3 rows folded.
        assert [r.response["text"] for r in reqs] == texts
        assert sizes == [3]  # the device saw only the unique rows
        stats = b.stats()
        assert stats["completed"] == 6
        assert stats["rows"] == 3
        assert stats["dedup_folded"] == 3
        assert stats["dedup_factor"] == 2.0  # (3 + 3) / 3
    finally:
        b.drain()


def test_queue_full_shed_carries_retry_after_hint():
    """A shed reply tells the client when to come back: the hint is the
    queue-drain estimate, floored at one flush deadline and capped."""
    from music_analyst_tpu.serving.batcher import _RETRY_AFTER_CAP_MS

    b = DynamicBatcher(_echo_ops(delay_s=0.05), max_batch=2,
                       max_wait_ms=5.0, max_queue=2).start()
    try:
        reqs = [b.submit(i, "echo", f"t{i}") for i in range(12)]
        for r in reqs:
            assert r.wait(10.0)
        shed = [r.response for r in reqs if not r.response["ok"]]
        assert shed
        for resp in shed:
            hint = resp["error"]["retry_after_ms"]
            assert 5.0 <= hint <= _RETRY_AFTER_CAP_MS
        assert b.stats()["retry_after_ms_last"] == \
            shed[-1]["error"]["retry_after_ms"]
    finally:
        b.drain()


def test_retry_after_estimate_floors_and_rates():
    b = DynamicBatcher(_echo_ops(), max_batch=4, max_wait_ms=10.0,
                       max_queue=64)
    # No flush yet: falls back to queued-batches × deadline, floored.
    assert b.retry_after_ms(depth=0) == 10.0
    assert b.retry_after_ms(depth=8) == 20.0  # 2 full batches × 10 ms
    b._flush_rate = 100.0  # rows/s observed
    assert b.retry_after_ms(depth=4) == 40.0  # 4 rows / 100 per s


# ---------------------------------------------------------------- residency


def test_warmup_sizes_ladder():
    assert warmup_sizes(1) == [1]
    assert warmup_sizes(8) == [1, 2, 4, 8]
    assert warmup_sizes(5) == [1, 2, 4, 8]  # covering bucket included


def test_residency_loads_once_and_warms():
    res = ModelResidency(model="mock", mock=True)
    clf = res.acquire()
    assert res.acquire() is clf  # load-once
    record = res.warmup(4)
    assert record["sizes"] == [1, 2, 4]
    snap = res.snapshot()
    assert snap["loaded"] and snap["warm"]
    assert snap["warmup"]["sizes"] == [1, 2, 4]


# -------------------------------------------------------------- equivalence


def _serve_stream(lines, backend, **batcher_kwargs):
    """Run one in-process stdio session; returns parsed reply dicts."""
    batcher = DynamicBatcher(build_ops(backend), **batcher_kwargs).start()
    server = SentimentServer(batcher, mode="stdio")
    out = io.StringIO()
    server.handle_stream(
        io.StringIO("".join(line + "\n" for line in lines)),
        out,
        drain_on_eof=True,
    )
    return [json.loads(line) for line in out.getvalue().splitlines()]


@pytest.fixture(scope="module")
def mock_backend():
    return ModelResidency(model="mock", mock=True).acquire()


@pytest.fixture(scope="module")
def oracle(fixture_csv, tmp_path_factory, mock_backend):
    """The batch sentiment engine's labels over the fixture corpus."""
    import csv

    from music_analyst_tpu.engines.sentiment import run_sentiment

    out_dir = tmp_path_factory.mktemp("sentiment-oracle")
    run_sentiment(str(fixture_csv), model="mock", mock=True,
                  output_dir=str(out_dir), backend=mock_backend,
                  quiet=True)
    with open(out_dir / "sentiment_details.csv", newline="",
              encoding="utf-8") as fh:
        rows = list(csv.DictReader(fh))
    from music_analyst_tpu.data.csv_io import iter_songs

    songs = list(iter_songs(str(fixture_csv)))
    assert len(songs) == len(rows)
    return songs, [row["label"] for row in rows]


@pytest.mark.parametrize("max_batch", [1, 3, 8])
def test_serve_labels_identical_to_batch_cli(oracle, mock_backend,
                                             max_batch):
    songs, labels = oracle
    lines = [
        json.dumps({"id": f"r{i}", "op": "sentiment", "text": text})
        for i, (_, _, text) in enumerate(songs)
    ]
    replies = _serve_stream(lines, mock_backend, max_batch=max_batch,
                            max_wait_ms=2.0, max_queue=len(lines) + 1)
    assert [r["id"] for r in replies] == [f"r{i}" for i in range(len(songs))]
    assert all(r["ok"] for r in replies)
    assert [r["label"] for r in replies] == labels


def test_ordering_under_queue_pressure(oracle, mock_backend):
    """A burst far deeper than max_batch (the whole corpus at once, with
    a deliberately slow deadline) still answers per-request-id in order
    with the exact batch labels."""
    songs, labels = oracle
    lines = [
        json.dumps({"id": f"q{i}", "op": "sentiment", "text": text})
        for i, (_, _, text) in enumerate(songs)
    ]
    replies = _serve_stream(lines, mock_backend, max_batch=3,
                            max_wait_ms=50.0, max_queue=len(lines) + 1)
    assert [r["id"] for r in replies] == [f"q{i}" for i in range(len(songs))]
    assert [r["label"] for r in replies] == labels


def test_shedding_keeps_order_and_server_alive(mock_backend):
    lines = [
        json.dumps({"id": f"s{i}", "op": "sentiment",
                    "text": "love " * (i % 3 + 1)})
        for i in range(40)
    ]
    replies = _serve_stream(lines, mock_backend, max_batch=2,
                            max_wait_ms=0.0, max_queue=4)
    assert [r["id"] for r in replies] == [f"s{i}" for i in range(40)]
    shed = [r for r in replies if not r["ok"]]
    served = [r for r in replies if r["ok"]]
    assert served  # the server kept answering through the overload
    for r in shed:
        assert r["error"]["kind"] == "queue_full"


def test_wordcount_op_matches_tokenizer_contract(mock_backend):
    import collections

    from music_analyst_tpu.data.tokenizer import tokenize_latin1

    text = "Hello hello world the THE the banana"
    replies = _serve_stream(
        [json.dumps({"id": "w", "op": "wordcount", "text": text})],
        mock_backend, max_batch=2, max_wait_ms=1.0,
    )
    counts = collections.Counter(tokenize_latin1(text))
    expected = dict(sorted(counts.items(), key=lambda kv: (-kv[1], kv[0])))
    assert replies[0]["counts"] == expected
    assert replies[0]["total_words"] == sum(counts.values())
    # count-desc, strcmp-asc ranking is a golden contract (SURVEY.md §5)
    assert list(replies[0]["counts"]) == list(expected)


def test_protocol_control_ops_and_bad_lines(mock_backend):
    replies = _serve_stream(
        [
            json.dumps({"id": "p", "op": "ping"}),
            "this is not json",
            json.dumps({"id": "m", "op": "sentiment"}),  # missing text
            json.dumps({"id": "ok", "op": "sentiment", "text": "love"}),
        ],
        mock_backend, max_batch=2, max_wait_ms=1.0,
    )
    assert replies[0] == {"id": "p", "ok": True, "op": "ping",
                          "protocol": "ndjson/v1"}
    assert replies[1]["ok"] is False
    assert replies[1]["error"]["kind"] == "bad_request"
    assert replies[2]["ok"] is False
    assert replies[2]["error"]["kind"] == "bad_request"
    assert replies[3]["ok"] is True and "label" in replies[3]


def test_shutdown_op_drains(mock_backend):
    replies = _serve_stream(
        [
            json.dumps({"id": "a", "op": "sentiment", "text": "love"}),
            json.dumps({"id": "z", "op": "shutdown"}),
            json.dumps({"id": "late", "op": "sentiment", "text": "x"}),
        ],
        mock_backend, max_batch=8, max_wait_ms=10_000.0,
    )
    by_id = {r["id"]: r for r in replies}
    # The pre-shutdown request was flushed by the drain (not the deadline,
    # which was 10 s out), and the shutdown itself acked.
    assert by_id["a"]["ok"] is True
    assert by_id["z"]["ok"] is True and by_id["z"]["draining"] is True
    if "late" in by_id:  # raced admission close: either answered or shed
        assert by_id["late"]["ok"] or (
            by_id["late"]["error"]["kind"] == "draining"
        )


# ---------------------------------------------------- quantiles (telemetry)


def test_histogram_quantiles_exact_below_cap():
    from music_analyst_tpu.telemetry.core import Histogram

    h = Histogram((0.5, 1.0))
    for i in range(1, 101):
        h.observe(i / 100.0)
    assert h.quantile(0.50) == pytest.approx(0.50)
    assert h.quantile(0.95) == pytest.approx(0.95)
    assert h.quantile(0.99) == pytest.approx(0.99)
    d = h.as_dict()
    assert d["p50_s"] == pytest.approx(0.50)
    assert d["p95_s"] == pytest.approx(0.95)
    assert d["p99_s"] == pytest.approx(0.99)
    assert d["min_s"] == pytest.approx(0.01)
    assert d["max_s"] == pytest.approx(1.0)


def test_histogram_quantiles_deterministic_above_cap():
    from music_analyst_tpu.telemetry.core import Histogram

    def build():
        h = Histogram((1.0,))
        for i in range(10_000):  # > the 4096 reservoir cap
            h.observe((i * 37 % 1000) / 1000.0)
        return h.quantiles()

    a, b = build(), build()
    assert a == b  # seeded reservoir: reproducible manifests
    assert 0.4 < a["p50"] < 0.6
    assert a["p99"] >= a["p95"] >= a["p50"]


def test_manifest_histograms_carry_quantiles(tmp_path):
    from music_analyst_tpu.telemetry import get_telemetry

    tel = get_telemetry()
    with tel.run_scope("serve", str(tmp_path)):
        for i in range(200):
            tel.observe("serving.request_seconds", (i + 1) / 1000.0)
    manifest = json.loads((tmp_path / "run_manifest.json").read_text())
    hist = manifest["histograms"]["serving.request_seconds"]
    assert hist["p50_s"] == pytest.approx(0.100)
    assert hist["p95_s"] == pytest.approx(0.190)
    assert hist["p99_s"] == pytest.approx(0.198)


def test_telemetry_report_surfaces_quantiles(tmp_path):
    from music_analyst_tpu.observability.report import (
        build_report,
        load_run,
        render_report,
    )

    run_dir = tmp_path / "run1"
    run_dir.mkdir()
    (run_dir / "run_manifest.json").write_text(json.dumps({
        "schema": 1, "engine": "serve", "counters": {},
        "histograms": {
            "serving.request_seconds": {
                "count": 10, "sum_s": 1.0,
                "p50_s": 0.08, "p95_s": 0.2, "p99_s": 0.35,
            },
        },
        "serving": {"protocol": "ndjson/v1",
                    "requests": {"admitted": 10}},
    }))
    rec = load_run(str(run_dir))
    assert rec["latency_quantiles"]["serving.request_seconds"] == {
        "p50_s": 0.08, "p95_s": 0.2, "p99_s": 0.35,
    }
    assert rec["serving"]["protocol"] == "ndjson/v1"
    report = build_report([rec])
    assert report["latency_quantiles"][0]["p99_s"] == 0.35
    text = "\n".join(render_report(report))
    assert "latency quantiles" in text
    assert "serving.request_seconds" in text


def test_serve_stall_taxonomy_registered():
    from music_analyst_tpu.observability.report import classify_error
    from music_analyst_tpu.observability.watchdog import TAXONOMY

    assert TAXONOMY["serve"] == "serve_stall"
    assert classify_error("serve.dispatch silent for 10s") == "serve_stall"


# ------------------------------------------------- subprocess / lifecycle


def _serve_cmd(*extra):
    return [
        sys.executable, "-m", "music_analyst_tpu", "serve",
        "--stdio", "--mock", "--quiet", *extra,
    ]


def _subprocess_env(**overrides):
    env = dict(os.environ)
    env.update(JAX_PLATFORMS="cpu", PALLAS_AXON_POOL_IPS="")
    env.update(overrides)
    return env


def test_cli_stdio_roundtrip_and_manifest(tmp_path):
    requests = [
        {"id": "a", "op": "sentiment", "text": "I love sunshine"},
        {"id": "b", "op": "wordcount", "text": "hello hello world"},
        {"id": "c", "op": "ping"},
    ]
    proc = subprocess.run(
        _serve_cmd("--max-batch", "2", "--max-wait-ms", "2",
                   "--telemetry-dir", str(tmp_path)),
        input="".join(json.dumps(r) + "\n" for r in requests),
        capture_output=True, text=True, timeout=240,
        cwd=REPO, env=_subprocess_env(),
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    replies = [json.loads(line) for line in proc.stdout.splitlines()]
    assert [r["id"] for r in replies] == ["a", "b", "c"]
    assert all(r["ok"] for r in replies)
    manifest = json.loads((tmp_path / "run_manifest.json").read_text())
    serving = manifest["serving"]
    assert serving["protocol"] == "ndjson/v1"
    assert serving["mode"] == "stdio"
    assert serving["requests"]["completed"] == 2
    assert serving["requests"]["latency"]["p50_s"] is not None
    assert serving["residency"]["warm"] is True


def test_sigterm_mid_batch_drains_gracefully(tmp_path):
    """SIGTERM with requests parked in a partial batch (deadline 60 s
    out): the server must answer them, leave a flight record, exit 0."""
    flight_dir = tmp_path / "flight"
    flight_dir.mkdir()
    proc = subprocess.Popen(
        _serve_cmd("--max-batch", "64", "--max-wait-ms", "60000",
                   "--no-warmup", "--telemetry-dir", str(tmp_path)),
        stdin=subprocess.PIPE, stdout=subprocess.PIPE,
        stderr=subprocess.PIPE, text=True, cwd=REPO,
        env=_subprocess_env(MUSICAAL_FLIGHT_RECORD_DIR=str(flight_dir)),
    )
    try:
        # Ping first: its reply proves the server is up AND the reader
        # thread has consumed everything we wrote before it.
        proc.stdin.write(json.dumps({"id": "up", "op": "ping"}) + "\n")
        proc.stdin.flush()
        ready = json.loads(proc.stdout.readline())
        assert ready["id"] == "up" and ready["ok"]
        for i in range(3):
            proc.stdin.write(json.dumps({
                "id": f"g{i}", "op": "sentiment", "text": "love " * (i + 1),
            }) + "\n")
        proc.stdin.flush()
        # The requests sit in a partial batch (max_batch 64, deadline
        # 60 s): give the reader a beat to admit them, then SIGTERM.
        time.sleep(1.0)
        proc.send_signal(signal.SIGTERM)
        out, err = proc.communicate(timeout=120)
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.communicate(timeout=30)
    assert proc.returncode == 0, err[-2000:]
    replies = [json.loads(line) for line in out.splitlines()]
    by_id = {r["id"]: r for r in replies}
    for i in range(3):
        assert by_id[f"g{i}"]["ok"] is True, by_id  # drained, not dropped
    record = json.loads((flight_dir / "flight_record.json").read_text())
    assert record["reason"].startswith("serve_drain:signal:SIGTERM")
    manifest = json.loads((tmp_path / "run_manifest.json").read_text())
    assert manifest["serving"]["drain_reason"] == "signal:SIGTERM"
    assert manifest["serving"]["requests"]["completed"] == 3


# ------------------------------------------------------------ bench suite


def test_serving_bench_suite_meets_acceptance(monkeypatch):
    """The ISSUE 8 + ISSUE 20 acceptance bars, pinned: coalesced
    throughput ≥ 2× sequential at offered load ≥ max_batch; overload
    sheds with structured queue_full errors and every request still
    gets a reply; the warm Zipf response-cache replay ≥ 5× the
    cache-off control with hit-path latency that never saw a dispatch."""
    monkeypatch.setenv("MUSICAAL_BENCH_SMOKE", "1")
    import benchmarks

    benchmarks._load_all()
    table = benchmarks._SUITES["serving"]()
    assert table["suite"] == "serving" and table["smoke"] is True
    assert table["coalescing_speedup"] >= 2.0
    assert table["overload"]["shed_kinds"] == ["queue_full"]
    assert table["overload"]["all_answered"] is True
    for row in table["rows"]:
        assert row["p50_s"] is not None
        assert row["p99_s"] >= row["p50_s"]
    rc = table["response_cache"]
    assert rc["warm_speedup"] >= 5.0
    assert rc["warm_hits"] == rc["draws"] * 3  # warm replay: all hits
    assert rc["cold_hit_rate"] > 0.0  # head repeats answer mid-cold-pass
    assert rc["hit_p99_ms"] < 1.0  # hash + dict lookup, no dispatch
    assert rc["stats"]["corrupt"] == 0 and rc["stats"]["write_errors"] == 0
