"""End-to-end real-weights path: crafted HF checkpoints → CLI → artifacts.

The loaders are oracle-tested at the tensor/logit level
(``test_distilbert_checkpoint.py`` / ``test_llama_checkpoint.py``); this
file verifies the remaining seam someone's real ``MUSICAAL_*_CKPT`` run
exercises: env var → ``from_pretrained_or_random`` → ``run_sentiment`` →
``sentiment_totals.json``/``sentiment_details.csv``, with the labels pinned
against an independent torch recomputation of the same checkpoint
(reference analogue: the live end-to-end path,
``scripts/sentiment_classifier.py:126-172``).
"""

import csv
import json

import numpy as np
import pytest

torch = pytest.importorskip("torch")

from test_distilbert_checkpoint import (  # noqa: E402
    _hf_state_dict as distil_state_dict,
    _oracle_forward as distil_oracle,
)
from test_llama_checkpoint import (  # noqa: E402
    _hf_state_dict as llama_state_dict,
)

from music_analyst_tpu.cli.main import main
from music_analyst_tpu.data.csv_io import iter_songs
from music_analyst_tpu.models.distilbert import DistilBertConfig


def _read_details(path):
    with open(path, newline="", encoding="utf-8") as fh:
        return list(csv.DictReader(fh))


def test_distilbert_ckpt_env_to_artifacts(fixture_csv, tmp_path, monkeypatch):
    """--model distilbert-tiny + $MUSICAAL_DISTILBERT_CKPT: every label in
    the artifacts matches a plain-torch forward of the checkpoint."""
    cfg = DistilBertConfig.tiny()  # what --model distilbert-tiny resolves to
    sd = distil_state_dict(cfg, seed=3)
    # Saturate decisions: 40x the head weights pushes every non-empty
    # text's confidence far from the 0.6 Neutral threshold, so the bf16
    # model and the f32 oracle can't disagree on the label (guarded below).
    sd["classifier.weight"] = sd["classifier.weight"] * 40
    sd["classifier.bias"] = torch.zeros_like(sd["classifier.bias"])
    ckpt = tmp_path / "pytorch_model.bin"
    torch.save(sd, ckpt)
    monkeypatch.setenv("MUSICAAL_DISTILBERT_CKPT", str(ckpt))

    out = tmp_path / "out"
    rc = main([
        "sentiment", str(fixture_csv), "--model", "distilbert-tiny",
        "--output-dir", str(out),
    ])
    assert rc == 0

    # Independent oracle: tokenize each song exactly as the backend does,
    # forward through plain torch ops, apply the documented 2->3 label rule.
    from music_analyst_tpu.models.tokenization import resolve_bert_tokenizer

    tok = resolve_bert_tokenizer(None, vocab_size=cfg.vocab_size)
    expected = []
    for artist, song, text in iter_songs(str(fixture_csv)):
        if not text.strip():
            expected.append((artist, song, "Neutral"))
            continue
        row, n = tok.encode(text, 128)
        logits = distil_oracle(
            sd, cfg, torch.tensor(np.asarray(row[:n])[None], dtype=torch.long)
        )
        probs = torch.softmax(logits[0], dim=-1)
        conf = float(probs.max())
        assert conf > 0.8, (
            f"crafted checkpoint not saturated for {song!r} (conf={conf}); "
            "the bf16-vs-f32 comparison would be fragile"
        )
        label = ("Negative", "Positive")[int(probs.argmax())]
        expected.append((artist, song, label))

    rows = _read_details(out / "sentiment_details.csv")
    assert [(r["artist"], r["song"], r["label"]) for r in rows] == expected

    totals = json.loads((out / "sentiment_totals.json").read_text())
    want_totals = {"Positive": 0, "Neutral": 0, "Negative": 0}
    for _, _, label in expected:
        want_totals[label] += 1
    assert totals == want_totals


def test_llama_ckpt_env_to_artifacts(fixture_csv, tmp_path, monkeypatch):
    """--model llama3-tiny + $MUSICAAL_LLAMA_CKPT: the CLI run's labels
    equal a directly-constructed backend given the same checkpoint, so the
    env glue demonstrably routed the weights."""
    from music_analyst_tpu.models.llama import (
        LlamaConfig,
        LlamaZeroShotClassifier,
    )

    cfg = LlamaConfig.tiny()  # what --model llama3-tiny resolves to
    sd = llama_state_dict(cfg, seed=5)
    ckpt = tmp_path / "pytorch_model.bin"
    torch.save(sd, ckpt)
    monkeypatch.setenv("MUSICAAL_LLAMA_CKPT", str(ckpt))

    out = tmp_path / "out"
    rc = main([
        "sentiment", str(fixture_csv), "--model", "llama3-tiny",
        "--output-dir", str(out),
    ])
    assert rc == 0

    direct = LlamaZeroShotClassifier(config=cfg, checkpoint_path=str(ckpt))
    assert direct.pretrained
    songs = list(iter_songs(str(fixture_csv)))
    want_labels = direct.classify_batch([text for _, _, text in songs])

    rows = _read_details(out / "sentiment_details.csv")
    assert [r["label"] for r in rows] == want_labels
    totals = json.loads((out / "sentiment_totals.json").read_text())
    assert sum(totals.values()) == len(songs)
    for label in set(totals):
        assert totals[label] == want_labels.count(label)
