"""Telemetry registry: spans, counters, sinks, manifest, engine wiring.

The byte-stability tests at the bottom are the load-bearing ones: turning
telemetry ON must not perturb the golden artifacts (``word_counts.csv``
byte-identical, ``performance_metrics.json`` structurally identical) —
the whole subsystem rides alongside the reference contracts, never in
them.
"""

import json
import threading

import pytest

from music_analyst_tpu.telemetry import (
    DEFAULT_BUCKETS,
    Histogram,
    Telemetry,
    configure,
    get_telemetry,
)


@pytest.fixture(autouse=True)
def _fresh_telemetry():
    """Each test gets a clean, enabled registry; the CLI's configure()
    mutates process-wide state, so restore the default afterwards."""
    yield configure(enabled=True, directory=None)
    configure(enabled=True, directory=None)


# ---------------------------------------------------------------- spans


def test_span_nesting_links_parents():
    tel = Telemetry()
    with tel.span("outer") as outer:
        with tel.span("middle") as middle:
            with tel.span("inner", rows=3) as inner:
                pass
    assert outer.parent_id is None
    assert middle.parent_id == outer.span_id
    assert inner.parent_id == middle.span_id
    assert inner.attrs == {"rows": 3}
    assert all(sp.duration_s >= 0.0 for sp in tel.spans)
    # Completion order: innermost closes first.
    assert [sp.name for sp in tel.spans] == ["inner", "middle", "outer"]


def test_span_attrs_via_set():
    tel = Telemetry()
    with tel.span("work") as sp:
        sp.set(rows=7, backend="mock")
    assert tel.spans[0].attrs == {"rows": 7, "backend": "mock"}


def test_record_span_preserves_duration():
    tel = Telemetry()
    tel.record_span("tokenize", 1.25, rows=10)
    sp = tel.spans[0]
    assert sp.name == "tokenize" and sp.duration_s == 1.25
    assert tel.span_aggregates["tokenize"] == [1, 1.25, 1.25]


def test_spans_are_thread_safe():
    tel = Telemetry()
    n_threads, per_thread = 8, 50
    errors = []

    def work(i):
        try:
            for j in range(per_thread):
                with tel.span(f"t{i}"):
                    tel.count("iterations")
                tel.record_span("measured", 0.001)
        except Exception as exc:  # pragma: no cover - failure path
            errors.append(exc)

    threads = [
        threading.Thread(target=work, args=(i,)) for i in range(n_threads)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errors
    assert tel.counters["iterations"] == n_threads * per_thread
    assert tel.span_aggregates["measured"][0] == n_threads * per_thread
    # Each thread's stack is its own: no span got a cross-thread parent.
    for sp in tel.spans:
        if sp.parent_id is not None:
            parent = next(p for p in tel.spans if p.span_id == sp.parent_id)
            assert parent.thread == sp.thread


def test_disabled_registry_is_inert(tmp_path):
    tel = Telemetry(enabled=False)
    with tel.span("x") as sp:
        sp.set(rows=1)  # _NullSpan absorbs attrs
    tel.count("c")
    tel.observe("h", 0.5)
    tel.record_span("y", 1.0)
    with tel.run_scope("engine", str(tmp_path)):
        pass
    assert tel.spans == [] and tel.counters == {} and tel.events == 0
    assert list(tmp_path.iterdir()) == []


# ------------------------------------------------- counters / histograms


def test_counter_aggregation():
    tel = Telemetry()
    tel.count("songs", 10)
    tel.count("songs", 5)
    tel.count("retries")
    assert tel.counters == {"songs": 15, "retries": 1}


def test_histogram_buckets():
    h = Histogram(buckets=(0.01, 0.1, 1.0))
    for v in (0.005, 0.05, 0.5, 5.0, 0.05):
        h.observe(v)
    d = h.as_dict()
    assert d["buckets_le"] == [0.01, 0.1, 1.0, "inf"]
    assert d["counts"] == [1, 2, 1, 1]
    assert d["count"] == 5
    assert d["sum_s"] == pytest.approx(5.605)


def test_observe_uses_default_buckets():
    tel = Telemetry()
    tel.observe("lat", 0.02)
    assert tel.histograms["lat"].buckets == tuple(sorted(DEFAULT_BUCKETS))


def test_compile_stats_counts_backend_compile_only():
    tel = Telemetry()
    tel.record_jax_event("/jax/core/compile/backend_compile_duration", 2.0)
    tel.record_jax_event("/jax/core/compile/backend_compile_duration", 1.0)
    tel.record_jax_event("/jax/core/compile/jaxpr_trace_duration", 9.0)
    tel.record_jax_event("/jax/compilation_cache/cache_hits")
    stats = tel.compile_stats()
    assert stats == {"count": 2, "seconds": 3.0}


def test_top_spans_ranked_by_total():
    tel = Telemetry()
    tel.record_span("slow", 3.0)
    tel.record_span("fast", 0.1)
    tel.record_span("fast", 0.2)
    top = tel.top_spans(2)
    assert [t["name"] for t in top] == ["slow", "fast"]
    assert top[1]["count"] == 2 and top[1]["max_s"] == 0.2


# ----------------------------------------------------- run scope + sinks


def test_run_scope_writes_jsonl_and_manifest(tmp_path):
    tel = Telemetry()
    with tel.run_scope("wordcount", str(tmp_path)):
        with tel.span("ingest", rows=4):
            pass
        tel.count("songs_ingested", 4)
        tel.annotate(mesh_shape={"dp": 8})

    log = tmp_path / "telemetry.jsonl"
    assert log.exists()
    events = [json.loads(line) for line in log.read_text().splitlines()]
    assert events, "JSONL log must not be empty"
    # Every line is a self-describing event with both clocks.
    for ev in events:
        assert ev["type"] in ("span", "event")
        assert "t_wall" in ev and "t_mono" in ev
    names = [ev["name"] for ev in events]
    assert names[0] == "run_start" and names[-1] == "run_end"
    assert "ingest" in names and "engine:wordcount" in names
    ingest = next(ev for ev in events if ev["name"] == "ingest")
    assert ingest["attrs"] == {"rows": 4} and ingest["dur_s"] >= 0.0
    run_end = next(ev for ev in events if ev["name"] == "run_end")
    assert run_end["attrs"]["counters"] == {"songs_ingested": 4}

    manifest = json.loads((tmp_path / "run_manifest.json").read_text())
    for key in (
        "schema", "engine", "argv", "wall_seconds", "jax_version",
        "jaxlib_version", "git_describe", "device", "peak_rss_bytes",
        "compile", "counters", "context", "spans", "event_count",
    ):
        assert key in manifest, key
    assert manifest["engine"] == "wordcount"
    assert manifest["device"]["platform"] == "cpu"
    assert manifest["device"]["count"] == 8  # the emulated test mesh
    assert manifest["counters"] == {"songs_ingested": 4}
    assert manifest["context"]["mesh_shape"] == {"dp": 8}
    assert {"count", "seconds"} <= set(manifest["compile"])


def test_nested_run_scopes_degrade_to_spans(tmp_path):
    """joint -> wordcount/sentiment: one owner, ONE manifest, nested
    engines show up as engine:<name> spans instead of resetting state."""
    tel = Telemetry()
    outer_dir = tmp_path / "outer"
    inner_dir = tmp_path / "inner"
    with tel.run_scope("joint", str(outer_dir)):
        tel.count("songs", 2)
        with tel.run_scope("wordcount", str(inner_dir)):
            tel.count("songs", 3)
    assert not inner_dir.exists()  # nested scope opened no sink
    manifest = json.loads((outer_dir / "run_manifest.json").read_text())
    assert manifest["engine"] == "joint"
    assert manifest["counters"] == {"songs": 5}  # not reset by the nest
    names = [
        json.loads(line)["name"]
        for line in (outer_dir / "telemetry.jsonl").read_text().splitlines()
    ]
    assert "engine:wordcount" in names
    assert names.count("run_start") == 1 and names.count("run_end") == 1


def test_back_to_back_runs_reset_state(tmp_path):
    tel = Telemetry()
    with tel.run_scope("a", str(tmp_path / "a")):
        tel.count("rows", 1)
    with tel.run_scope("b", str(tmp_path / "b")):
        pass
    manifest_b = json.loads(
        (tmp_path / "b" / "run_manifest.json").read_text()
    )
    assert manifest_b["counters"] == {}  # run a's counters did not bleed


def test_explicit_directory_wins_over_output_dir(tmp_path):
    tel = Telemetry()
    tel.directory = str(tmp_path / "telemetry")
    with tel.run_scope("x", str(tmp_path / "output")):
        pass
    assert (tmp_path / "telemetry" / "telemetry.jsonl").exists()
    assert (tmp_path / "telemetry" / "run_manifest.json").exists()
    assert not (tmp_path / "output").exists()


def test_memory_only_when_no_directory(tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)
    tel = Telemetry()
    with tel.run_scope("x", None):
        tel.count("rows", 1)
    assert list(tmp_path.iterdir()) == []
    assert tel.events > 0  # still counted in memory


def test_jsonl_appends_across_runs(tmp_path):
    tel = Telemetry()
    for _ in range(2):
        with tel.run_scope("x", str(tmp_path)):
            pass
    lines = (tmp_path / "telemetry.jsonl").read_text().splitlines()
    assert sum(json.loads(l)["name"] == "run_start" for l in lines) == 2


# ------------------------------------------------------- engine wiring


def test_stage_timer_spans_and_seconds_agree():
    from music_analyst_tpu.metrics.timer import StageTimer

    tel = get_telemetry()
    timer = StageTimer()
    with timer.stage("device_compute"):
        pass
    with timer.stage("device_compute"):
        pass
    # StageTimer semantics unchanged: accumulated float per stage name.
    assert set(timer.seconds) == {"device_compute"}
    assert timer.seconds["device_compute"] >= 0.0
    # ... and each stage() also recorded a telemetry span.
    assert tel.span_aggregates["device_compute"][0] == 2


def test_wordcount_engine_emits_required_stage_spans(fixture_csv, tmp_path):
    from music_analyst_tpu.engines.wordcount import run_analysis

    run_analysis(
        str(fixture_csv), output_dir=str(tmp_path),
        ingest_backend="python", quiet=True,
    )
    log = tmp_path / "telemetry.jsonl"
    assert log.exists()
    events = [json.loads(line) for line in log.read_text().splitlines()]
    names = {ev["name"] for ev in events}
    # ≥1 span per pipeline stage (the acceptance bar): ingest, compute,
    # write — plus the split stage this engine owns.
    assert {"split", "ingest", "device_compute", "aggregate_export"} <= names
    manifest = json.loads((tmp_path / "run_manifest.json").read_text())
    assert manifest["engine"] == "wordcount"
    assert manifest["counters"]["songs_ingested"] > 0
    assert manifest["counters"]["words_counted"] > 0
    assert manifest["context"]["mesh_shape"]["dp"] == 8


def test_sentiment_engine_emits_stage_spans(fixture_csv, tmp_path):
    from music_analyst_tpu.engines.sentiment import run_sentiment

    run_sentiment(
        str(fixture_csv), mock=True, output_dir=str(tmp_path), quiet=True,
    )
    events = [
        json.loads(line)
        for line in (tmp_path / "telemetry.jsonl").read_text().splitlines()
    ]
    names = {ev["name"] for ev in events}
    assert {"ingest", "compute", "write", "backend_init"} <= names
    manifest = json.loads((tmp_path / "run_manifest.json").read_text())
    assert manifest["engine"] == "sentiment"
    assert manifest["counters"]["rows_classified"] > 0
    assert "sentiment.batch_seconds" in manifest["histograms"]


def test_persong_engine_emits_stage_spans(fixture_csv, tmp_path):
    from music_analyst_tpu.engines.persong import run_per_song_wordcount

    run_per_song_wordcount(
        str(fixture_csv), output_dir=str(tmp_path), quiet=True,
    )
    events = [
        json.loads(line)
        for line in (tmp_path / "telemetry.jsonl").read_text().splitlines()
    ]
    names = {ev["name"] for ev in events}
    assert {"ingest", "tokenize", "write"} <= names
    manifest = json.loads((tmp_path / "run_manifest.json").read_text())
    assert manifest["counters"]["rows_processed"] > 0
    assert manifest["counters"]["words_counted"] > 0


def test_train_step_records_spans():
    import jax.numpy as jnp
    import numpy as np

    from music_analyst_tpu.engines.train import (
        init_train_state,
        make_optimizer,
        make_train_step,
    )
    from music_analyst_tpu.models.llama import LlamaConfig, LlamaModel

    cfg = LlamaConfig.tiny()
    model = LlamaModel(cfg)
    opt = make_optimizer(1e-3)
    token_ids = jnp.asarray(
        np.random.default_rng(0).integers(1, cfg.vocab_size, (2, 16))
    )
    lengths = jnp.asarray([16, 12])
    state = init_train_state(model, opt, (token_ids, lengths))
    step = make_train_step(model, opt)
    tel = get_telemetry()
    before = tel.span_aggregates.get("train_step", [0])[0]
    state, loss = step(state, token_ids, lengths)
    state, loss = step(state, token_ids, lengths)
    assert tel.span_aggregates["train_step"][0] == before + 2
    assert tel.counters["train_steps"] >= 2
    assert jnp.isfinite(loss)


# --------------------------------------------------- golden byte parity


def test_artifacts_identical_with_and_without_telemetry(
    fixture_csv, tmp_path
):
    """The acceptance bar: word_counts.csv byte-identical, and
    performance_metrics.json structurally identical (timings jitter
    run-to-run; keys/counts must not)."""
    from music_analyst_tpu.engines.wordcount import run_analysis

    on_dir, off_dir = tmp_path / "on", tmp_path / "off"
    configure(enabled=True, directory=None)
    run_analysis(
        str(fixture_csv), output_dir=str(on_dir),
        ingest_backend="python", quiet=True,
    )
    configure(enabled=False)
    run_analysis(
        str(fixture_csv), output_dir=str(off_dir),
        ingest_backend="python", quiet=True,
    )

    assert (on_dir / "word_counts.csv").read_bytes() == (
        off_dir / "word_counts.csv"
    ).read_bytes()
    assert (on_dir / "top_artists.csv").read_bytes() == (
        off_dir / "top_artists.csv"
    ).read_bytes()

    def structure(obj):
        if isinstance(obj, dict):
            return {k: structure(v) for k, v in sorted(obj.items())}
        if isinstance(obj, list):
            return [structure(v) for v in obj]
        return type(obj).__name__

    on_metrics = json.loads((on_dir / "performance_metrics.json").read_text())
    off_metrics = json.loads(
        (off_dir / "performance_metrics.json").read_text()
    )
    assert structure(on_metrics) == structure(off_metrics)
    # Count fields ARE deterministic — pin them exactly.
    for key in ("total_songs", "total_words", "processes"):
        assert on_metrics[key] == off_metrics[key]

    # Telemetry-off wrote no extra files.
    assert not (off_dir / "telemetry.jsonl").exists()
    assert not (off_dir / "run_manifest.json").exists()
    assert (on_dir / "telemetry.jsonl").exists()
