"""Packed-documents decoder forward ≡ separate per-document forwards.

The decoder side of the packing story (the encoder side lives in
``test_packing.py``): with ``segment_ids`` + per-segment-restarted
``positions``, a causal LlamaModel forward over two documents sharing one
row must produce exactly the logits each document gets in its own row —
on the dense impl (mask array = causal & same-segment) AND on the flash
impl (the kernel takes segment ids natively).
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np

from music_analyst_tpu.models.layers import causal_mask, segment_mask
from music_analyst_tpu.models.llama import LlamaConfig, LlamaModel

CFG = LlamaConfig(
    vocab_size=128, dim=32, n_layers=2, n_heads=4, n_kv_heads=2,
    hidden_dim=64, rope_theta=1e4, max_seq_len=64, dtype="float32",
)

L1, L2 = 24, 40  # two documents packed into one 64-token row
S = L1 + L2


def _packed_inputs(rng):
    ids = jnp.asarray(rng.integers(1, CFG.vocab_size, (1, S)), jnp.int32)
    seg = jnp.asarray([[1] * L1 + [2] * L2], jnp.int32)
    pos = jnp.asarray([list(range(L1)) + list(range(L2))], jnp.int32)
    return ids, seg, pos


def _separate_logits(model, params, ids):
    """Each document alone in its own row, full causal attention."""
    outs = []
    for sl in (slice(0, L1), slice(L1, S)):
        doc = ids[:, sl]
        n = doc.shape[1]
        pos = jnp.arange(n)[None, :]
        logits, _ = model.apply(
            {"params": params}, doc, pos, causal_mask(n, n, 0),
            lengths=jnp.asarray([n], jnp.int32),
        )
        outs.append(np.asarray(logits)[0])
    return np.concatenate(outs, axis=0)  # [S, V]


def _run(attn_impl):
    cfg = dataclasses.replace(CFG, attn_impl=attn_impl)
    model = LlamaModel(cfg)
    rng = np.random.default_rng(0)
    ids, seg, pos = _packed_inputs(rng)
    params = model.init(
        jax.random.key(0), ids, pos, causal_mask(S, S, 0)
    )["params"]

    if attn_impl == "dense":
        # Dense path expresses packing in the mask array.
        mask = causal_mask(S, S, 0) & segment_mask(seg)
        packed_logits, _ = model.apply({"params": params}, ids, pos, mask)
    else:
        packed_logits, _ = model.apply(
            {"params": params}, ids, pos, None,
            lengths=jnp.asarray([S], jnp.int32), segment_ids=seg,
        )
    packed_logits = np.asarray(packed_logits)[0]   # [S, V]
    want = _separate_logits(model, params, ids)
    np.testing.assert_allclose(packed_logits, want, rtol=2e-4, atol=2e-4)


def test_segment_ids_rejected_off_the_flash_prefill_path():
    import pytest

    model = LlamaModel(CFG)  # dense impl
    rng = np.random.default_rng(1)
    ids, seg, pos = _packed_inputs(rng)
    params = model.init(
        jax.random.key(0), ids, pos, causal_mask(S, S, 0)
    )["params"]
    with pytest.raises(ValueError, match="flash prefill"):
        model.apply({"params": params}, ids, pos, causal_mask(S, S, 0),
                    segment_ids=seg)


def test_packed_decoder_dense_matches_separate():
    _run("dense")


def test_packed_decoder_flash_matches_separate():
    # Flash needs block-divisible seq lens; 64 = L1+L2 satisfies _fit_block.
    _run("flash")
