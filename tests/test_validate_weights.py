"""CI coverage for the real-weight validation harness (engines/validate.py).

Exercises the exact command a user runs once real weights exist — crafted
tiny HF checkpoints stand in for them, the way every checkpoint test here
does.  The independent side is transformers' own torch modules, so these
tests also pin that our architecture configs translate into HF configs
that consume the checkpoints exactly.
"""

import dataclasses
import json

import numpy as np
import pytest

torch = pytest.importorskip("torch")
pytest.importorskip("transformers")

from test_distilbert_checkpoint import (  # noqa: E402
    _hf_state_dict as distil_state_dict,
)
from test_llama_checkpoint import (  # noqa: E402
    _hf_state_dict as llama_state_dict,
)

from music_analyst_tpu.cli.main import main  # noqa: E402
from music_analyst_tpu.engines.validate import run_validation  # noqa: E402
from music_analyst_tpu.models.distilbert import DistilBertConfig  # noqa: E402


def _distil_ckpt(tmp_path, saturate=True):
    cfg = DistilBertConfig.tiny()
    sd = distil_state_dict(cfg, seed=3)
    if saturate:
        # Push every non-empty text far from the 0.6 Neutral threshold so
        # bf16-vs-f32 noise cannot flip a label (same trick as
        # test_e2e_checkpoint.py).
        sd["classifier.weight"] = sd["classifier.weight"] * 40
        sd["classifier.bias"] = torch.zeros_like(sd["classifier.bias"])
    path = tmp_path / "pytorch_model.bin"
    torch.save(sd, path)
    return path


def test_validate_distilbert_full_agreement(fixture_csv, tmp_path,
                                            monkeypatch):
    monkeypatch.setenv(
        "MUSICAAL_DISTILBERT_CKPT", str(_distil_ckpt(tmp_path))
    )
    out = tmp_path / "out"
    report = run_validation(
        str(fixture_csv), model="distilbert-tiny", output_dir=str(out),
        quiet=True,
    )
    assert report["rows"] > 0
    assert report["agreement"] == 1.0
    assert report["disagreements"] == []
    # Confusion diagonal covers every row.
    diag = sum(
        report["confusion_oracle_to_ours"][lab][lab]
        for lab in ("Positive", "Neutral", "Negative")
    )
    assert diag == report["rows"]
    on_disk = json.loads((out / "weight_validation.json").read_text())
    assert on_disk["agreement"] == 1.0


def test_validate_covers_int8_and_packed_variants(fixture_csv, tmp_path,
                                                  monkeypatch):
    """The harness certifies the quantized and packed execution paths
    against the same float oracle.  The x40 head scaling guards the
    0.6-Neutral-threshold path (both sides commit); argmax stability
    under the int8 perturbation comes from the seed-3 fixture's decisive
    top-class margins (deterministic in CI — scaling is argmax-invariant
    and does NOT protect near-ties, so a flip here means the quantized
    path's perturbation grew past tests/test_quant.py's bound)."""
    monkeypatch.setenv(
        "MUSICAAL_DISTILBERT_CKPT", str(_distil_ckpt(tmp_path))
    )
    for model in ("distilbert-tiny-int8", "distilbert-tiny-packed"):
        report = run_validation(
            str(fixture_csv), model=model, quiet=True,
        )
        assert report["agreement"] == 1.0, (model, report["disagreements"])


def test_validate_cli_gate(fixture_csv, tmp_path, monkeypatch):
    """The documented one-command path, including the CI gate flag."""
    monkeypatch.setenv(
        "MUSICAAL_DISTILBERT_CKPT", str(_distil_ckpt(tmp_path))
    )
    rc = main([
        "validate", str(fixture_csv), "--model", "distilbert-tiny",
        "--min-agreement", "0.99",
    ])
    assert rc == 0


def test_validate_llama(fixture_csv, tmp_path):
    from music_analyst_tpu.models.llama import (
        LlamaConfig,
        LlamaZeroShotClassifier,
    )

    cfg = dataclasses.replace(LlamaConfig.tiny(), dtype="float32")
    sd = llama_state_dict(cfg, seed=5)
    # Ship as a sharded directory — the form real Llama weights arrive in;
    # backend and oracle must both merge the shards.
    ckpt = tmp_path / "ckpt_dir"
    ckpt.mkdir()
    keys = sorted(sd)
    torch.save({k: sd[k] for k in keys[::2]},
               ckpt / "pytorch_model-00001-of-00002.bin")
    torch.save({k: sd[k] for k in keys[1::2]},
               ckpt / "pytorch_model-00002-of-00002.bin")
    # Inject a float32 backend so ours-vs-oracle is a math comparison, not
    # a bf16 rounding lottery on random tiny weights.
    clf = LlamaZeroShotClassifier(config=cfg, checkpoint_path=str(ckpt))
    assert clf.pretrained
    report = run_validation(
        str(fixture_csv), model="llama3-tiny",
        checkpoint_path=str(ckpt), backend=clf, quiet=True,
    )
    assert report["rows"] > 0
    assert report["agreement"] == 1.0, report["disagreements"]


def test_validate_llama_tied_embeddings_oracle_logit_parity(tmp_path):
    """Checkpoints without a separate lm_head (tied embeddings) flow
    through the oracle's tie_word_embeddings branch, and the oracle model
    matches our loader's model at the logit level (label agreement on
    unscaled random fixtures is chaotic over long prompts, so the pin is
    on logits — the quantity both scoring paths consume)."""
    import jax
    import jax.numpy as jnp

    from music_analyst_tpu.engines.validate import build_llama_oracle
    from music_analyst_tpu.models.layers import causal_mask
    from music_analyst_tpu.models.llama import (
        LlamaConfig,
        LlamaModel,
        load_hf_torch_checkpoint,
    )

    cfg = dataclasses.replace(LlamaConfig.tiny(), dtype="float32")
    sd = llama_state_dict(cfg, seed=6, tied=True)
    assert "lm_head.weight" not in sd
    ckpt = tmp_path / "pytorch_model.bin"
    torch.save(sd, ckpt)

    hf = build_llama_oracle(str(ckpt), cfg)
    assert hf.config.tie_word_embeddings

    model = LlamaModel(cfg)
    rng = np.random.default_rng(3)
    ids = jnp.asarray(rng.integers(0, cfg.vocab_size, (2, 16)), jnp.int32)
    pos = jnp.broadcast_to(jnp.arange(16), (2, 16))
    params = model.init(jax.random.key(0), ids, pos,
                        causal_mask(16, 16, 0))["params"]
    params = load_hf_torch_checkpoint(params, str(ckpt))
    ours, _ = model.apply({"params": params}, ids, pos,
                          causal_mask(16, 16, 0))
    with torch.no_grad():
        theirs = hf(
            torch.tensor(np.asarray(ids), dtype=torch.long)
        ).logits.numpy()
    np.testing.assert_allclose(np.asarray(ours), theirs, rtol=1e-3,
                               atol=1e-3)


def test_validate_requires_checkpoint(fixture_csv, monkeypatch):
    monkeypatch.delenv("MUSICAAL_DISTILBERT_CKPT", raising=False)
    with pytest.raises(RuntimeError, match="MUSICAAL_DISTILBERT_CKPT"):
        run_validation(str(fixture_csv), model="distilbert-tiny")


def test_validate_rejects_weightless_models(fixture_csv):
    with pytest.raises(ValueError, match="mock"):
        run_validation(str(fixture_csv), model="mock")


def test_validate_oracle_catches_a_poisoned_path(fixture_csv, tmp_path,
                                                 monkeypatch):
    """The harness must be able to FAIL: poison the backend's params and
    the oracle disagreement has to show up in the report."""
    import jax

    from music_analyst_tpu.models.distilbert import DistilBertClassifier

    ckpt = _distil_ckpt(tmp_path)
    clf = DistilBertClassifier(
        config=DistilBertConfig.tiny(), checkpoint_path=str(ckpt)
    )
    # Flip the head: guarantees wrong labels wherever the oracle commits.
    clf.params = dict(clf.params)
    clf.params["classifier"] = dict(clf.params["classifier"])
    clf.params["classifier"]["kernel"] = -np.asarray(
        jax.device_get(clf.params["classifier"]["kernel"])
    )
    report = run_validation(
        str(fixture_csv), model="distilbert-tiny",
        checkpoint_path=str(ckpt), backend=clf, quiet=True,
    )
    assert report["agreement"] < 1.0
    assert report["disagreements"]
