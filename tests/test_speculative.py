"""Speculative decoding (draft-and-verify) inside the fixed decode programs.

Contract families (ISSUE 15):

* **equivalence** — greedy text under speculation is byte-identical to
  the non-speculative scan at every draft depth, on both KV backends,
  under shuffled arrival and mixed per-request budgets; EOS-latch and
  budget-freeze semantics survive accepted blocks.
* **shapes** — the verify program joins the warmup ladder only when
  speculation is on; zero retraces across a speculative workload
  (``compiled_variants`` flat); the proposed depth adapts inside the
  fixed ``k+1`` block.
* **resilience** — an injected ``spec.draft`` fault degrades the tick to
  plain decode with identical bytes and a counted fallback; preemption
  mid-speculation checkpoints and resumes O(1) with identical bytes.
* **knobs** — ``--speculate-k`` / ``MUSICAAL_SERVE_SPECULATE_K``
  resolution: explicit bad values raise, malformed env falls back.
* **dedup** — identical in-flight generate requests fold to one slot
  and fan the reply out (``dedup_folded``), each reply under its own id.
"""

import json
import random

import pytest

from music_analyst_tpu.serving.batcher import resolve_speculate_k


@pytest.fixture(scope="module")
def clf():
    from music_analyst_tpu.models.llama import (
        LlamaConfig,
        LlamaZeroShotClassifier,
    )

    return LlamaZeroShotClassifier(
        config=LlamaConfig.tiny(), max_prompt_len=64
    )


PROMPTS = [
    "golden sunshine on the river",
    "rain",
    "shadows fall across the empty street tonight",
    "my heart beats a broken drum",
    "la la la la",
    "winter wind and summer fire",
    "ok",
    "the long road home winds past the silver lake and over the hills",
]

# Streams that emit EOS well before a 16-token budget under the tiny
# config at seed 0 — the EOS-latch × accepted-block interaction.
EOS_PROMPTS = ["la la la", "hey hey", "sun", "dance dance"]


def _scheduler(clf, **kwargs):
    from music_analyst_tpu.serving.decode_loop import ContinuousScheduler

    kwargs.setdefault("prefill_chunk", 16)
    kwargs.setdefault("prompt_region", 64)
    kwargs.setdefault("max_new_tokens", 16)
    kwargs.setdefault("max_queue", 64)
    return ContinuousScheduler(clf, **kwargs)


def _run(sched, prompts, budgets=None, order=None):
    budgets = budgets or [sched.plan.max_new] * len(prompts)
    order = order if order is not None else range(len(prompts))
    reqs = {}
    for i in order:
        reqs[i] = sched.submit(i, prompts[i], max_new_tokens=budgets[i])
    sched.run_until_idle()
    out = []
    for i in range(len(prompts)):
        resp = reqs[i].response or {}
        assert resp.get("ok"), resp
        out.append(resp)
    return out


# ---------------------------------------------------------- equivalence


@pytest.mark.parametrize("page_size", [None, 0], ids=["paged", "slots"])
@pytest.mark.parametrize("k", [2, 4, 8])
def test_speculative_matches_static_greedy(clf, page_size, k):
    """Byte-identical greedy text at every draft depth, both backends,
    shuffled arrival — acceptance is exact argmax equality and the
    correction token is the argmax itself, so no interleaving of
    accepted blocks and plain ticks can change a byte."""
    want = clf.generate_batch(PROMPTS, max_new_tokens=16)
    kwargs = dict(n_slots=4, speculate_k=k)
    if page_size is not None:
        kwargs["page_size"] = page_size
    sched = _scheduler(clf, **kwargs)
    order = list(range(len(PROMPTS)))
    random.Random(k).shuffle(order)
    got = [r["text"] for r in _run(sched, PROMPTS, order=order)]
    assert got == want
    spec = sched.stats()["speculation"]
    assert spec["enabled"] and spec["k"] == k
    assert spec["fallbacks"] == 0


def test_mixed_budgets_freeze_identically(clf):
    """Per-request budgets truncate exactly under speculation: drafts
    past a slot's budget are never proposed, the commit clamp never
    exceeds it, and the bytes match the plain scheduler's."""
    budgets = [1, 2, 3, 16, 1, 2, 3, 16]
    plain = _scheduler(clf, n_slots=4, speculate_k=0)
    want = [r["text"] for r in _run(plain, PROMPTS, budgets=budgets)]
    sched = _scheduler(clf, n_slots=4, speculate_k=8)
    got = _run(sched, PROMPTS, budgets=budgets)
    assert [r["text"] for r in got] == want
    for resp, budget in zip(got, budgets):
        assert resp["tokens"] <= budget


def test_eos_latch_survives_accepted_blocks(clf):
    """Streams that emit EOS mid-block settle at the EOS position — the
    verify scan carries no latch; the host truncates at the first EOS in
    the committed prefix, so text matches the static scan exactly."""
    want = clf.generate_batch(EOS_PROMPTS, max_new_tokens=16)
    sched = _scheduler(clf, n_slots=4, speculate_k=4)
    got = [r["text"] for r in _run(sched, EOS_PROMPTS)]
    assert got == want


# --------------------------------------------------------------- shapes


def test_verify_joins_warmup_ladder_only_when_on(clf):
    """speculate_k>0 adds exactly one warmed program per backend (the
    verify block); the default ladder stays 4 paged / 5 monolithic as
    asserted in test_continuous."""
    paged = _scheduler(clf, n_slots=2, speculate_k=4)
    record = paged.warmup()
    assert record["kv_backend"] == "paged"
    assert record["programs"] == 5
    assert record["speculate_k"] == 4

    mono = _scheduler(clf, n_slots=2, page_size=0, speculate_k=4)
    record = mono.warmup()
    assert record["kv_backend"] == "slots"
    assert record["programs"] == 6


def test_zero_retraces_across_speculative_workload(clf):
    """The verify program is one fixed shape: adaptive draft depth,
    mixed budgets, EOS, and plain-tick fallbacks all run inside it."""
    sched = _scheduler(clf, n_slots=4, speculate_k=4)
    sched.warmup()
    variants = sched.runtime.compiled_variants()
    budgets = [16, 1, 16, 3, 16, 2, 16, 16]
    _run(sched, PROMPTS, budgets=budgets)
    _run(sched, PROMPTS[:4])
    assert sched.runtime.compiled_variants() == variants


def test_speculate_k_capped_to_budget_region(clf):
    """A draft block must fit the decode region: k is capped at
    construction to max_new - 1, keeping the verify shape legal."""
    sched = _scheduler(clf, n_slots=2, max_new_tokens=4, speculate_k=64)
    assert sched.speculate_k == 3


def test_speculation_stats_populated(clf):
    sched = _scheduler(clf, n_slots=4, speculate_k=4)
    _run(sched, ["la la la la la la", "do do do do do do"] * 2)
    spec = sched.stats()["speculation"]
    assert spec["enabled"] and spec["k"] == 4
    assert spec["plain_ticks"] + spec["dispatches"] > 0
    if spec["dispatches"]:
        assert spec["accepted_tokens_per_dispatch"] >= 1.0
        assert spec["acceptance_rate"] is not None
    assert "acceptance_rate_hist" in spec
    assert "accepted_tokens_hist" in spec

    plain = _scheduler(clf, n_slots=2, speculate_k=0)
    stats = plain.stats()["speculation"]
    assert not stats["enabled"] and stats["k"] == 0


# ----------------------------------------------------------- resilience


def test_draft_fault_degrades_to_plain_decode(clf):
    """An injected ``spec.draft`` fault costs the tick's speedup, never
    a token: bytes identical to the clean run, fallbacks counted."""
    from music_analyst_tpu.resilience import configure_faults

    want = clf.generate_batch(PROMPTS[:4], max_new_tokens=16)
    sched = _scheduler(clf, n_slots=4, speculate_k=4)
    configure_faults("spec.draft:error@1+")
    try:
        got = [r["text"] for r in _run(sched, PROMPTS[:4])]
    finally:
        configure_faults(None)
    assert got == want
    spec = sched.stats()["speculation"]
    assert spec["fallbacks"] > 0
    assert spec["dispatches"] == 0  # every eligible tick fell back


def test_preempt_resume_mid_speculation_byte_identical(clf):
    """SLO preemption lands while slots are speculating: the victim
    checkpoints, resumes O(1), and every request's bytes still match
    the static scan — speculation state (draft cache, EWMA) is host-only
    and rebuilt, never persisted wrong."""
    low_prompts = PROMPTS[:2]
    high_prompt = PROMPTS[7]
    static = clf.generate_batch(low_prompts + [high_prompt],
                                max_new_tokens=16)
    sched = _scheduler(clf, n_slots=2, speculate_k=4, ttft_slo_ms=1.0,
                       kv_pages=24)
    sched.warmup()
    variants = sched.runtime.compiled_variants()
    low = [
        sched.submit(i, p, priority=1, deadline_ms=60_000.0)
        for i, p in enumerate(low_prompts)
    ]
    for _ in range(64):
        sched._tick()
        if any(s is not None and s.active and s.steps > 0
               for s in sched._slots):
            break
    high = sched.submit("gold", high_prompt, priority=5,
                        deadline_ms=60_000.0)
    for _ in range(64):
        if sched.stats()["preemptions"] >= 1:
            break
        sched._tick()
    sched.run_until_idle()
    for req, want in zip(low, static[:2]):
        assert req.response["ok"], req.response
        assert req.response["text"] == want
    assert high.response["ok"] and high.response["text"] == static[-1]
    stats = sched.stats()
    assert stats["preemptions"] >= 1
    assert stats["resumed_o1"] >= 1
    assert stats["resume_chunks_skipped"] >= 1
    assert sched.runtime.compiled_variants() == variants


# ---------------------------------------------------------------- knobs


def test_resolve_speculate_k(monkeypatch):
    monkeypatch.delenv("MUSICAAL_SERVE_SPECULATE_K", raising=False)
    assert resolve_speculate_k(None) == 0  # off by default
    assert resolve_speculate_k(4) == 4
    monkeypatch.setenv("MUSICAAL_SERVE_SPECULATE_K", "6")
    assert resolve_speculate_k(None) == 6
    monkeypatch.setenv("MUSICAAL_SERVE_SPECULATE_K", "junk")
    assert resolve_speculate_k(None) == 0  # malformed env falls back
    with pytest.raises(ValueError):
        resolve_speculate_k("junk")  # explicit value is a usage error
    with pytest.raises(ValueError):
        resolve_speculate_k(-1)


# ---------------------------------------------------------------- dedup


def test_identical_inflight_generates_fold_to_one_slot(clf):
    """Greedy decode is deterministic, so identical in-flight
    (tenant, prompt, budget) generate requests compute once: followers
    fold onto the primary's slot and the reply fans out under each
    request's own id."""
    sched = _scheduler(clf, n_slots=2, speculate_k=4)
    same = [
        sched.submit(f"dup-{i}", "one hit song", max_new_tokens=8)
        for i in range(4)
    ]
    other = sched.submit("solo", "a different tune", max_new_tokens=8)
    # Same prompt at a different budget is a different stream: no fold.
    longer = sched.submit("long", "one hit song", max_new_tokens=12)
    sched.run_until_idle()
    texts = set()
    for req in same:
        assert req.response["ok"], req.response
        assert req.response["id"] == req.id
        texts.add(req.response["text"])
    assert len(texts) == 1
    assert other.response["ok"] and longer.response["ok"]
    assert longer.response["text"].startswith(next(iter(texts)))
    assert sched.stats()["dedup_folded"] == 3


@pytest.mark.slow
def test_continuous_suite_speculation_bar(monkeypatch):
    """The continuous suite's speculation A/B booleans ARE the ISSUE-15
    bar: ≥2× fewer decode dispatches on the chorus-like smoke workload,
    byte-identical greedy text, zero retraces.

    The gated ratio is the dispatch count — a deterministic function of
    the accepted-draft lengths, immune to the sandbox's wall-clock
    noise — so one attempt suffices (ISSUE 18 retired the retry-up-to-3
    workaround the old tokens/s bar needed)."""
    monkeypatch.setenv("MUSICAAL_BENCH_SMOKE", "1")
    from benchmarks.continuous import _speculation_ab

    row = _speculation_ab(
        n_requests=16, n_slots=8, budget=128, speculate_k=8
    )
    assert row["identical_outputs"] is True
    assert row["fewer_dispatches"] is True
    assert row["zero_retrace"] is True
    assert row["dispatch_ratio_ok"] is True, row


# ------------------------------------------------------------- reporting


def test_report_aggregates_speculation(tmp_path):
    """telemetry-report rolls the manifest's serving.decode.speculation
    sections into cross-run acceptance/accepted-tokens quantiles."""
    from music_analyst_tpu.observability.report import (
        build_report,
        load_run,
        render_report,
    )

    def _manifest(label, rate, atpd):
        return {
            "run": label, "ok": True, "wall_seconds": 1.0,
            "serving": {
                "decode": {
                    "speculation": {
                        "enabled": True, "k": 8, "dispatches": 73,
                        "plain_ticks": 4, "fallbacks": 0,
                        "acceptance_rate": rate,
                        "accepted_tokens_per_dispatch": atpd,
                    },
                },
            },
        }

    records = []
    for i, (rate, atpd) in enumerate([(0.91, 6.2), (0.97, 7.8)]):
        run_dir = tmp_path / f"run{i}"
        run_dir.mkdir()
        (run_dir / "run_manifest.json").write_text(
            json.dumps(_manifest(f"run{i}", rate, atpd))
        )
        records.append(load_run(str(run_dir)))
    report = build_report(records)
    spec = report["speculation"]
    assert [r["label"] for r in spec["runs"]] == ["run0", "run1"]
    assert spec["acceptance_rate"]["n"] == 2
    assert spec["acceptance_rate"]["max"] == 0.97
    assert spec["accepted_tokens_per_dispatch"]["p50"] == 6.2
    text = "\n".join(render_report(report))
    assert "speculative decoding" in text
    assert "acceptance rate across 2 run(s)" in text

    # A spec-off run contributes nothing: the block stays empty.
    plain = build_report([{
        "label": "plain", "kind": "run_dir", "ok": True,
        "error": None, "error_kind": None,
        "serving": {"decode": {"speculation": {"enabled": False}}},
    }])
    assert plain["speculation"]["runs"] == []
    assert plain["speculation"]["acceptance_rate"] is None
    assert "speculative decoding" not in "\n".join(render_report(plain))
