"""Byte-for-byte differential tests against the reference PYTHON scripts.

`test_reference_differential.py` pins parity against the compiled C
binary; these runs execute the reference's actual Python entry points
(`scripts/sentiment_classifier.py --mock`, `scripts/word_count_per_song.py`,
`scripts/split_csv_columns.py`) as subprocesses on the same inputs and
diff every artifact byte-for-byte.
"""

import os
import subprocess
import sys

import pytest

REF = "/root/reference"
FIXTURE = os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "fixtures", "mini_songs.csv"
)

pytestmark = pytest.mark.skipif(
    not os.path.isdir(os.path.join(REF, "scripts")),
    reason="reference scripts not mounted",
)


def _run_ref(script, args, cwd, expect_failure=False):
    proc = subprocess.run(
        [sys.executable, os.path.join(REF, "scripts", script), *args],
        capture_output=True, text=True, cwd=cwd,
    )
    if expect_failure:
        assert proc.returncode != 0
    else:
        assert proc.returncode == 0, proc.stderr
    return proc


def _clean_fixture(tmp_path):
    """The raw fixture contains a deliberately short row that CRASHES the
    reference scripts (DictReader yields None for missing columns and the
    reference calls .strip() on it — scripts/sentiment_classifier.py:59,
    the None-robustness gap SURVEY.md §2.2 P5 documents).  Differential
    runs need an input the reference survives."""
    import csv as _csv

    out = tmp_path / "fixture_clean.csv"
    with open(FIXTURE, newline="", encoding="utf-8") as fh:
        rows = [r for r in _csv.reader(fh)]
    with open(out, "w", newline="", encoding="utf-8") as fh:
        writer = _csv.writer(fh)
        for row in rows:
            if len(row) >= 4:
                writer.writerow(row)
    return str(out)


def _read(path):
    with open(path, "rb") as fh:
        return fh.read()


def test_mock_sentiment_byte_parity(tmp_path):
    fixture = _clean_fixture(tmp_path)
    ref_out = tmp_path / "ref"
    ours_out = tmp_path / "ours"
    _run_ref(
        "sentiment_classifier.py",
        [fixture, "--mock", "--output-dir", str(ref_out)],
        cwd=str(tmp_path),
    )
    from music_analyst_tpu.engines.sentiment import run_sentiment

    run_sentiment(fixture, mock=True, output_dir=str(ours_out), quiet=True)
    assert _read(ref_out / "sentiment_totals.json") == _read(
        ours_out / "sentiment_totals.json"
    )
    assert _read(ref_out / "sentiment_details.csv") == _read(
        ours_out / "sentiment_details.csv"
    )


def test_mock_sentiment_with_limit_byte_parity(tmp_path):
    fixture = _clean_fixture(tmp_path)
    ref_out = tmp_path / "ref"
    ours_out = tmp_path / "ours"
    _run_ref(
        "sentiment_classifier.py",
        [fixture, "--mock", "--limit", "4", "--output-dir", str(ref_out)],
        cwd=str(tmp_path),
    )
    from music_analyst_tpu.engines.sentiment import run_sentiment

    run_sentiment(fixture, mock=True, limit=4, output_dir=str(ours_out),
                  quiet=True)
    assert _read(ref_out / "sentiment_totals.json") == _read(
        ours_out / "sentiment_totals.json"
    )
    assert _read(ref_out / "sentiment_details.csv") == _read(
        ours_out / "sentiment_details.csv"
    )


def test_word_count_per_song_byte_parity(tmp_path):
    fixture = _clean_fixture(tmp_path)
    ref_out = tmp_path / "ref"
    ours_out = tmp_path / "ours"
    _run_ref(
        "word_count_per_song.py",
        [fixture, "--output-dir", str(ref_out)],
        cwd=str(tmp_path),
    )
    from music_analyst_tpu.engines.persong import run_per_song_wordcount

    run_per_song_wordcount(fixture, output_dir=str(ours_out), quiet=True)
    for name in ("word_counts_global.csv", "word_counts_by_song.csv"):
        assert _read(ref_out / name) == _read(ours_out / name), name


def test_split_csv_columns_byte_parity(tmp_path):
    ref_out = tmp_path / "ref_cols"
    ours_out = tmp_path / "our_cols"
    _run_ref(
        "split_csv_columns.py",
        [FIXTURE, "--output-dir", str(ref_out)],
        cwd=str(tmp_path),
    )
    from music_analyst_tpu.data.splitter import split_csv_columns

    split_csv_columns(FIXTURE, output_dir=str(ours_out))
    ref_files = sorted(os.listdir(ref_out))
    our_files = sorted(os.listdir(ours_out))
    assert ref_files == our_files
    for name in ref_files:
        assert _read(ref_out / name) == _read(ours_out / name), name


def test_synthetic_corpus_script_parity(tmp_path):
    """Same three scripts on a generated 300-song corpus with quoting
    edge cases."""
    from music_analyst_tpu.data.synthetic import generate_dataset

    data = tmp_path / "songs.csv"
    generate_dataset(str(data), num_songs=300, seed=13)

    ref_out = tmp_path / "ref"
    ours_out = tmp_path / "ours"
    _run_ref(
        "sentiment_classifier.py",
        [str(data), "--mock", "--output-dir", str(ref_out)],
        cwd=str(tmp_path),
    )
    from music_analyst_tpu.engines.sentiment import run_sentiment

    run_sentiment(str(data), mock=True, output_dir=str(ours_out), quiet=True)
    assert _read(ref_out / "sentiment_totals.json") == _read(
        ours_out / "sentiment_totals.json"
    )
    assert _read(ref_out / "sentiment_details.csv") == _read(
        ours_out / "sentiment_details.csv"
    )

    _run_ref(
        "word_count_per_song.py",
        [str(data), "--output-dir", str(ref_out / "persong")],
        cwd=str(tmp_path),
    )
    from music_analyst_tpu.engines.persong import run_per_song_wordcount

    run_per_song_wordcount(str(data), output_dir=str(ours_out / "persong"),
                           quiet=True)
    for name in ("word_counts_global.csv", "word_counts_by_song.csv"):
        assert _read(ref_out / "persong" / name) == _read(
            ours_out / "persong" / name
        ), name


def test_reference_crashes_on_short_rows_we_handle(tmp_path):
    """Documented robustness divergence (MIGRATION.md): the reference's
    sentiment script crashes on rows missing the text column; ours labels
    them Neutral and keeps going."""
    _run_ref(
        "sentiment_classifier.py",
        [FIXTURE, "--mock", "--output-dir", str(tmp_path / "ref")],
        cwd=str(tmp_path),
        expect_failure=True,
    )
    from music_analyst_tpu.engines.sentiment import run_sentiment

    result = run_sentiment(FIXTURE, mock=True,
                           output_dir=str(tmp_path / "ours"), quiet=True)
    assert sum(result.counts.values()) == 8
