"""Crash-consistent serving: the durable request journal (ISSUE 14).

Contract families:

* **WAL framing** — length+CRC framed records; a torn tail or bit-rot is
  counted (``corrupt_truncated``), the segment's tail abandoned, and
  replay carries on — corruption degrades to recompute, never to a wrong
  or duplicate answer.
* **replay + dedup** — admitted-but-unanswered records come back from
  :meth:`recover` oldest-first; replied ids hit the bounded dedup index
  (exactly-once at the wire); records are idempotent upserts, so replay
  of compacted + live history converges to one state.
* **durability protocol** — ``atomic_write(durable=True)`` fsyncs the
  staged file BEFORE the rename and the directory after (the regression
  pinned here: rename-only publication is not a write barrier); reply
  records group-commit (append the batch, one fsync, then the wire).
* **unclean detection** — the ``clean`` marker is the dirty bit: absent
  marker + segments on disk means the previous process never ran its
  shutdown path.
* **O(1) resume** — a preempted decode resumes from its checkpoint with
  zero prefill chunks (``resumed_o1``/``resume_chunks_skipped``), greedy
  tokens byte-identical, zero retraces, on BOTH KV backends; a drain
  that lands while the victim is still waiting answers every admitted
  request (the SIGTERM × preemption seam).
* **crash drill** — the subprocess SIGKILL/restart drill from the
  ``crash`` bench suite, one cheap seam, asserted as a test.
"""

import json
import os
import struct
import zlib

import pytest

from music_analyst_tpu.serving.journal import RequestJournal
from music_analyst_tpu.utils.atomic import atomic_write

_HEADER = struct.Struct(">II")


def _segments(directory):
    return sorted(
        name for name in os.listdir(directory)
        if name.startswith("journal-") and name.endswith(".log")
    )


def _active_segment(directory):
    return os.path.join(directory, _segments(directory)[-1])


# ------------------------------------------------------------ WAL basics


def test_recover_replays_unanswered_and_dedups_replied(tmp_path):
    d = str(tmp_path / "wal")
    j = RequestJournal(d)
    assert j.recover() == []  # first boot: nothing to replay, not unclean
    assert j.stats()["unclean_start"] is False
    j.record_admitted("a", "sentiment", "sunny day", tenant="gold",
                      priority=3)
    j.record_admitted("b", "wordcount", "la la la")
    j.record_replied("a", {"ok": True, "label": "Positive"})
    j.close()

    j2 = RequestJournal(d)
    unanswered = j2.recover()
    assert [r["id"] for r in unanswered] == ["b"]
    assert unanswered[0]["op"] == "wordcount"
    assert unanswered[0]["text"] == "la la la"
    # Clean shutdown: the marker was present, so not an unclean start.
    assert j2.stats()["unclean_start"] is False
    # The replied id dedups byte-identically; the open one does not.
    assert j2.lookup_reply("a") == {"ok": True, "label": "Positive"}
    assert j2.lookup_reply("b") is None
    stats = j2.stats()
    assert stats["replayed"] == 1
    assert stats["deduped"] == 1  # the lookup_reply hit above
    assert stats["open_requests"] == 1
    j2.close()


def test_non_string_ids_and_slo_fields_round_trip(tmp_path):
    """Wire ids are arbitrary JSON values; SLO fields journal as null so
    a replay re-submits with the server's own defaults."""
    d = str(tmp_path / "wal")
    j = RequestJournal(d)
    j.recover()
    j.record_admitted(7, "generate", "verse one", deadline_ms=None,
                      meta={"max_new_tokens": 4})
    j.record_admitted([1, "x"], "sentiment", "chorus")
    j.record_replied(7, {"ok": True, "text": "verse one two"})
    j.close()
    j2 = RequestJournal(d)
    unanswered = j2.recover()
    assert [r["id"] for r in unanswered] == [[1, "x"]]
    assert j2.lookup_reply(7) == {"ok": True, "text": "verse one two"}
    record = next(r for r in [unanswered[0]])
    assert record["deadline_ms"] is None
    j2.close()


def test_journal_used_before_recover_is_a_usage_error(tmp_path):
    j = RequestJournal(str(tmp_path / "wal"))
    with pytest.raises(RuntimeError, match="recover"):
        j.record_admitted("a", "sentiment", "x")


# ---------------------------------------------------- corruption tolerance


def test_torn_tail_is_counted_skipped_and_never_crashes(tmp_path):
    """A crash mid-``write`` leaves a partial frame; replay abandons the
    tail, keeps everything before it, and reports the damage."""
    d = str(tmp_path / "wal")
    j = RequestJournal(d, sync_every=1)
    j.recover()
    j.record_admitted("a", "sentiment", "first")
    j.record_replied("a", {"ok": True, "label": "Positive"})
    j.record_admitted("b", "sentiment", "second")
    # Simulate SIGKILL: abandon the handle (no close/compact/marker) and
    # tear the tail with a partial header.
    with open(_active_segment(d), "ab") as fh:
        fh.write(b"\xff\xff\xff")
    j2 = RequestJournal(d)
    unanswered = j2.recover()
    stats = j2.stats()
    assert stats["unclean_start"] is True
    assert stats["corrupt_truncated"] >= 1
    assert [r["id"] for r in unanswered] == ["b"]  # survived the tear
    assert j2.lookup_reply("a") == {"ok": True, "label": "Positive"}
    j2.close()


def test_crc_flip_abandons_tail_but_keeps_prefix(tmp_path):
    d = str(tmp_path / "wal")
    j = RequestJournal(d, sync_every=1)
    j.recover()
    j.record_admitted("keep", "sentiment", "intact record")
    j.record_admitted("rot", "sentiment", "this one rots")
    path = _active_segment(d)
    with open(path, "rb") as fh:
        data = bytearray(fh.read())
    data[-1] ^= 0x5A  # bit-rot inside the LAST record's payload
    with open(path, "wb") as fh:
        fh.write(data)
    j2 = RequestJournal(d)
    unanswered = j2.recover()
    assert [r["id"] for r in unanswered] == ["keep"]
    assert j2.stats()["corrupt_truncated"] == 1
    j2.close()


def test_length_past_eof_is_corruption_not_overread(tmp_path):
    d = str(tmp_path / "wal")
    j = RequestJournal(d, sync_every=1)
    j.recover()
    j.record_admitted("ok", "sentiment", "fine")
    with open(_active_segment(d), "ab") as fh:
        fh.write(_HEADER.pack(10_000, zlib.crc32(b"x")) + b"short")
    j2 = RequestJournal(d)
    assert [r["id"] for r in j2.recover()] == ["ok"]
    assert j2.stats()["corrupt_truncated"] == 1
    j2.close()


# --------------------------------------------------- rotation + compaction


def test_rotation_compacts_history_to_live_state(tmp_path):
    """Sealed segments collapse into one fresh segment holding only live
    state — the directory stays small and restart replay stays O(live),
    not O(all traffic)."""
    d = str(tmp_path / "wal")
    j = RequestJournal(d, sync_every=4, rotate_bytes=4096, dedup_limit=8)
    j.recover()
    filler = "x" * 200
    for i in range(64):
        j.record_admitted(i, "sentiment", f"{filler} {i}")
        j.record_replied(i, {"ok": True, "label": "Positive", "i": i})
    j.record_admitted("open", "sentiment", "still in flight")
    stats = j.stats()
    assert stats["rotations"] >= 1
    assert stats["compactions"] >= 1
    assert len(_segments(d)) <= 2  # compacted history + active segment
    j.close()

    j2 = RequestJournal(d)
    unanswered = j2.recover()
    assert [r["id"] for r in unanswered] == ["open"]
    # Dedup window survives compaction (bounded by dedup_limit).
    assert j2.lookup_reply(63) == {"ok": True, "label": "Positive",
                                   "i": 63}
    assert j2.stats()["dedup_index"] <= 8
    j2.close()


def test_dedup_index_is_lru_bounded(tmp_path):
    d = str(tmp_path / "wal")
    j = RequestJournal(d, dedup_limit=4)
    j.recover()
    for i in range(6):
        j.record_admitted(i, "sentiment", f"t{i}")
        j.record_replied(i, {"ok": True, "i": i})
    assert j.lookup_reply(0) is None  # evicted: recompute (pure op) is
    assert j.lookup_reply(1) is None  # correct, just not free
    assert j.lookup_reply(5) == {"ok": True, "i": 5}
    assert j.stats()["dedup_index"] <= 4
    j.close()


# ------------------------------------------------------------ group commit


def test_group_commit_defers_fsync_until_sync_barrier(tmp_path):
    d = str(tmp_path / "wal")
    j = RequestJournal(d, sync_every=100)
    j.recover()
    syncs0 = j.stats()["syncs"]
    for i in range(3):
        j.record_admitted(i, "sentiment", f"t{i}")
        j.record_replied(i, {"ok": True, "i": i}, sync=False)
    assert j.stats()["syncs"] == syncs0  # nothing forced a barrier yet
    j.sync()
    assert j.stats()["syncs"] == syncs0 + 1  # the whole batch, one fsync
    # The batch is durable: a crash-and-restart sees every reply.
    j2 = RequestJournal(d)
    j2.recover()
    assert all(j2.lookup_reply(i) is not None for i in range(3))
    j2.close()
    j.close()


def test_unclean_marker_lifecycle(tmp_path):
    d = str(tmp_path / "wal")
    j = RequestJournal(d)
    j.recover()
    j.record_admitted("a", "sentiment", "x")
    j.close()
    assert os.path.exists(os.path.join(d, "clean"))
    j2 = RequestJournal(d)
    j2.recover()  # consumes the marker: this process's crash is visible
    assert j2.stats()["unclean_start"] is False
    assert not os.path.exists(os.path.join(d, "clean"))
    # Abandon j2 (SIGKILL stand-in): next boot must see an unclean start.
    j3 = RequestJournal(d)
    j3.recover()
    assert j3.stats()["unclean_start"] is True
    j3.close()


# --------------------------------------- atomic_write durability regression


def test_atomic_write_durable_fsyncs_before_rename(tmp_path, monkeypatch):
    """The write-barrier regression: ``durable=True`` must fsync the
    staged file BEFORE the rename publishes it (data reaches the platter
    before the name does) and the directory after."""
    events = []
    real_fsync, real_replace = os.fsync, os.replace
    monkeypatch.setattr(
        os, "fsync",
        lambda fd: (events.append("fsync"), real_fsync(fd))[1],
    )
    monkeypatch.setattr(
        os, "replace",
        lambda a, b: (events.append("replace"), real_replace(a, b))[1],
    )
    target = str(tmp_path / "artifact.bin")
    with atomic_write(target, mode="wb", encoding=None, durable=True) as fh:
        fh.write(b"payload")
    assert events.index("fsync") < events.index("replace")
    assert "fsync" in events[events.index("replace"):]  # dir fsync after
    with open(target, "rb") as fh:
        assert fh.read() == b"payload"


def test_atomic_write_default_stays_cheap(tmp_path, monkeypatch):
    """Bulk artifact writers keep the historical fast path: no fsync
    unless ``durable=True`` or ``$MUSICAAL_ATOMIC_FSYNC=1``."""
    monkeypatch.delenv("MUSICAAL_ATOMIC_FSYNC", raising=False)
    calls = []
    real_fsync = os.fsync
    monkeypatch.setattr(
        os, "fsync", lambda fd: (calls.append(fd), real_fsync(fd))[1]
    )
    with atomic_write(str(tmp_path / "fast.txt")) as fh:
        fh.write("cheap")
    assert calls == []
    monkeypatch.setenv("MUSICAAL_ATOMIC_FSYNC", "1")
    with atomic_write(str(tmp_path / "paranoid.txt")) as fh:
        fh.write("durable")
    assert len(calls) >= 1


# ----------------------------------------------- O(1) resume (tentpole b)


@pytest.fixture(scope="module")
def clf():
    from music_analyst_tpu.models.llama import (
        LlamaConfig,
        LlamaZeroShotClassifier,
    )

    return LlamaZeroShotClassifier(
        config=LlamaConfig.tiny(), max_prompt_len=64
    )


LOW_PROMPTS = [
    "midnight train ballad of the patient tenant",
    "thunder rolls over the empty stage",
]
HIGH_PROMPT = "gold tenant single drops mid decode"


def _scheduler(clf, **kwargs):
    from music_analyst_tpu.serving.decode_loop import ContinuousScheduler

    kwargs.setdefault("prefill_chunk", 16)
    kwargs.setdefault("prompt_region", 64)
    kwargs.setdefault("max_new_tokens", 8)
    kwargs.setdefault("max_queue", 16)
    return ContinuousScheduler(clf, **kwargs)


def _force_preemption(sched):
    """Submit a low-priority decode, let it reach mid-decode, then land a
    gold admit whose 1 ms TTFT target arms the slot steal."""
    low = [
        sched.submit(i, p, priority=1, deadline_ms=60_000.0)
        for i, p in enumerate(LOW_PROMPTS)
    ]
    for _ in range(64):
        sched._tick()
        if any(s is not None and s.active and s.steps > 0
               for s in sched._slots):
            break
    high = sched.submit("gold", HIGH_PROMPT, priority=5,
                        deadline_ms=60_000.0)
    for _ in range(64):
        if sched.stats()["preemptions"] >= 1:
            break
        sched._tick()
    return low, high


@pytest.mark.parametrize("page_size", [None, 0], ids=["paged", "slots"])
def test_preempt_resume_is_o1_and_byte_identical(clf, page_size):
    """The resumed victim re-enters decode from its checkpoint — zero
    prefill chunks re-run (``resume_chunks_skipped`` counts the skips),
    greedy tokens byte-identical to the undisturbed scan, zero retraces,
    on both KV backends."""
    static = clf.generate_batch(LOW_PROMPTS + [HIGH_PROMPT],
                                max_new_tokens=8)
    # Oversubscribed page pool (paged backend): the checkpoint pins the
    # victim's pages, so without headroom the incoming gold admit's
    # pressure valve would release it and degrade resume to re-prefill.
    kwargs = dict(n_slots=2, ttft_slo_ms=1.0, kv_pages=24)
    if page_size is not None:
        kwargs["page_size"] = page_size
    sched = _scheduler(clf, **kwargs)
    sched.warmup()
    variants_before = sched.runtime.compiled_variants()
    low, high = _force_preemption(sched)
    sched.run_until_idle()
    for req, want in zip(low, static[:len(LOW_PROMPTS)]):
        assert req.response["ok"], req.response
        assert req.response["text"] == want
    assert high.response["ok"] and high.response["text"] == static[-1]
    stats = sched.stats()
    assert stats["preemptions"] >= 1
    assert stats["resumed_o1"] >= 1
    assert stats["resume_chunks_skipped"] >= 1  # O(1), not re-prefill
    assert sched.runtime.compiled_variants() == variants_before


def test_drain_answers_preempted_victim_awaiting_resume(clf):
    """The SIGTERM × preemption seam (satellite 4): a drain that lands
    while the preempted victim is requeued awaiting its checkpoint
    resume must still answer every admitted request — drain means
    'finish the backlog', and the backlog includes the victim."""
    static = clf.generate_batch(LOW_PROMPTS + [HIGH_PROMPT],
                                max_new_tokens=8)
    sched = _scheduler(clf, n_slots=1, ttft_slo_ms=1.0, kv_pages=24)
    sched.warmup()
    low, high = _force_preemption(sched)
    assert sched.stats()["preemptions"] >= 1
    assert not all(r.done for r in low)  # the victim is still waiting
    sched.drain()  # inline: no loop thread was started
    for req, want in zip(low, static[:len(LOW_PROMPTS)]):
        assert req.response["ok"], req.response
        assert req.response["text"] == want
    assert high.response["ok"] and high.response["text"] == static[-1]
    assert sched.stats()["resumed_o1"] >= 1


# ----------------------------------------------------- crash drill (wire)


def test_crash_drill_sigkill_accounts_and_dedups(tmp_path):
    """One cheap seam of the full subprocess drill (the ``crash`` bench
    suite runs all four): SIGKILL a journaled mock server post-admit,
    restart on the same journal dir, re-send everything — 100%
    accounting, zero duplicate computes, unclean stamped."""
    from benchmarks.crash import _MOCK_ARGS, _mock_trace, run_drill

    row = run_drill(
        "post_admit", "serve.admit:crash@3", str(tmp_path),
        model_args=_MOCK_ARGS, trace=_mock_trace(8, seed=23),
    )
    assert row["killed_by_sigkill"] is True
    assert row["recovered_exit_ok"] is True
    assert row["all_accounted"] is True
    assert row["loadgen_silent_drops"] == 0
    assert row["duplicates_deduped"] is True
    assert row["unclean_stamped"] is True
    assert row["journal"]["unclean_start"] is True
