"""Content-addressed response cache: keys, tiers, fault degradation,
and the admission-edge contract (ISSUE 20).

Contract families:

* **keys** — the cache key separates on everything that changes reply
  bytes (op, generation budget, backend fingerprint: quant schemes,
  checkpoint identity) and nothing that doesn't (whitespace variants
  fold through the shared ``normalize_text`` identity contract).
* **tiers** — cold → warm → cross-restart round trip through the
  memory LRU and the on-disk tier; cached replies are byte-identical
  to computed ones (the ``cached`` stamp lives in stats/trace, never
  the payload).
* **never wrong** — truncated or CRC-flipped entries are detected,
  evicted, and recomputed; injected read faults degrade to recompute
  WITHOUT evicting (transient ≠ corrupt); injected write faults leave
  the settle uncached.  Sites ``response_cache.read`` and
  ``response_cache.write`` (resilience/faults.py roster).
* **admission edge** — hits run before the shed ladder (a would-shed
  repeat is answered, not rejected), charge zero tenant tokens and
  zero engine-ledger chip-seconds, and trigger zero retraces of the
  compiled decode programs; journal dedup (re-sent id) and response
  cache (same text, NEW id) compose without double answers.
"""

import io
import json
import os

import pytest

from music_analyst_tpu.serving.response_cache import (
    CACHEABLE_OPS,
    ResponseCache,
    backend_fingerprint,
    checkpoint_stamp,
    normalize_text,
    resolve_response_cache_dir,
    response_key,
    try_answer,
)


@pytest.fixture(scope="module")
def mock_backend():
    from music_analyst_tpu.serving.residency import ModelResidency

    return ModelResidency(model="mock", mock=True).acquire()


@pytest.fixture(scope="module")
def ops(mock_backend):
    from music_analyst_tpu.serving.server import build_ops

    return build_ops(mock_backend)


@pytest.fixture(scope="module")
def clf():
    from music_analyst_tpu.models.llama import (
        LlamaConfig,
        LlamaZeroShotClassifier,
    )

    return LlamaZeroShotClassifier(
        config=LlamaConfig.tiny(), max_prompt_len=64
    )


def _batcher(ops, cache=None, **kwargs):
    from music_analyst_tpu.serving.batcher import DynamicBatcher

    kwargs.setdefault("max_batch", 4)
    kwargs.setdefault("max_wait_ms", 2.0)
    kwargs.setdefault("max_queue", 64)
    return DynamicBatcher(ops, response_cache=cache, **kwargs)


def _settled(reqs, timeout=60.0):
    out = []
    for req in reqs:
        assert req.wait(timeout=timeout), f"request {req.id} never settled"
        out.append(dict(req.response))
    return out


def _sans_id(payload):
    return {k: v for k, v in payload.items() if k != "id"}


TEXTS = [
    "sunshine and happy days by the golden river",
    "tears and sorrow in the lonely broken night",
    "la la la the radio plays our song again",
]


# ------------------------------------------------------------------- keys


def test_normalize_text_is_the_shared_identity_contract():
    assert normalize_text("  I  love\tthis \n song ") == "I love this song"
    assert normalize_text("I love this song") == "I love this song"
    assert normalize_text("") == ""


def test_key_separates_on_everything_that_changes_bytes():
    fp = backend_fingerprint(model="llama", weight_quant="int8")
    base = response_key("hello world", "generate", 16, fp)
    # Whitespace variants fold; anything output-relevant separates.
    assert response_key(" hello \t world ", "generate", 16, fp) == base
    assert response_key("hello worlds", "generate", 16, fp) != base
    assert response_key("hello world", "sentiment", 16, fp) != base
    assert response_key("hello world", "generate", 8, fp) != base
    assert response_key("hello world", "generate", None, fp) != base
    for other in (
        backend_fingerprint(model="llama", weight_quant="int4"),
        backend_fingerprint(model="llama", weight_quant="int8",
                            kv_quant="int8"),
        backend_fingerprint(model="llama", weight_quant="int8",
                            checkpoint="ckpt:1:2"),
        backend_fingerprint(model="distilbert", weight_quant="int8"),
    ):
        assert response_key("hello world", "generate", 16, other) != base


def test_backend_fingerprint_drops_none_and_sorts():
    assert backend_fingerprint(b="2", a="1") == "a=1;b=2"
    assert backend_fingerprint(a="1", gone=None) == "a=1"
    # absent ≠ empty: an unset knob and an empty one are different backends
    assert backend_fingerprint(a="") != backend_fingerprint()


def test_checkpoint_stamp_rekeys_on_swapped_weights(tmp_path, monkeypatch):
    monkeypatch.delenv("MUSICAAL_LLAMA_CKPT", raising=False)
    monkeypatch.delenv("MUSICAAL_LLAMA_TOKENIZER", raising=False)
    monkeypatch.delenv("MUSICAAL_DISTILBERT_CKPT", raising=False)
    monkeypatch.delenv("MUSICAAL_BERT_VOCAB", raising=False)
    assert checkpoint_stamp() is None  # mock/synthetic: no real weights
    ckpt = tmp_path / "model.ckpt"
    ckpt.write_bytes(b"v1")
    monkeypatch.setenv("MUSICAAL_LLAMA_CKPT", str(ckpt))
    first = checkpoint_stamp()
    assert first and str(ckpt) in first
    ckpt.write_bytes(b"version two")  # swapped in place: size changes
    assert checkpoint_stamp() != first


def test_resolve_dir_precedence(tmp_path, monkeypatch):
    monkeypatch.delenv("MUSICAAL_RESPONSE_CACHE", raising=False)
    default = resolve_response_cache_dir()
    assert default and default.endswith("musicaal_responses")
    monkeypatch.setenv("MUSICAAL_RESPONSE_CACHE", str(tmp_path))
    assert resolve_response_cache_dir() == str(tmp_path)
    assert resolve_response_cache_dir("/explicit") == "/explicit"
    monkeypatch.setenv("MUSICAAL_RESPONSE_CACHE", "off")
    assert resolve_response_cache_dir() is None
    monkeypatch.setenv("MUSICAAL_RESPONSE_CACHE", str(tmp_path))
    assert resolve_response_cache_dir(use_cache=False) is None


# ------------------------------------------------------------------ tiers


def test_cold_warm_cross_restart_roundtrip(tmp_path):
    d = str(tmp_path / "rc")
    cache = ResponseCache(d, fingerprint="fp")
    key = cache.key_for("sentiment", "sunny song")
    assert cache.lookup(key) is None  # cold
    payload = {"id": "r1", "ok": True, "op": "sentiment",
               "label": "Positive"}
    assert cache.put(key, payload)
    got = cache.lookup(key)  # warm: memory tier
    assert got == {"ok": True, "op": "sentiment", "label": "Positive"}
    assert "id" not in got  # identity belongs to the request
    stats = cache.stats()
    assert stats["mem_hits"] == 1 and stats["stores"] == 1

    restarted = ResponseCache(d, fingerprint="fp")  # cross-restart
    got2 = restarted.lookup(key)
    assert got2 == got
    assert restarted.stats()["disk_hits"] == 1
    assert restarted.lookup(key) is not got2  # copies, not aliases
    got2["label"] = "poisoned"
    assert restarted.lookup(key)["label"] == "Positive"


def test_put_rejects_errors_and_never_raises(tmp_path):
    cache = ResponseCache(str(tmp_path), fingerprint="fp")
    key = cache.key_for("sentiment", "x")
    assert not cache.put(key, {"id": "a", "ok": False,
                               "error": {"kind": "queue_full"}})
    assert not cache.put(key, "not a dict")
    assert cache.lookup(key) is None


def test_mem_lru_bound_and_disk_byte_budget_eviction(tmp_path):
    cache = ResponseCache(str(tmp_path), fingerprint="fp",
                          mem_entries=2, max_bytes=300)
    keys = []
    for i in range(6):
        key = cache.key_for("sentiment", f"song number {i}")
        cache.put(key, {"ok": True, "label": f"L{i}"})
        keys.append(key)
    assert cache.stats()["mem_entries"] == 2  # LRU front tier bounded
    assert cache.stats()["evictions"] > 0  # disk tier held to max_bytes
    on_disk = [n for n in os.listdir(tmp_path) if n.endswith(".json")]
    total = sum(
        os.path.getsize(os.path.join(tmp_path, n)) for n in on_disk
    )
    assert total <= 300


def test_uncacheable_ops_pass_through(ops):
    cache = ResponseCache(None, fingerprint="fp")
    assert "stats" not in CACHEABLE_OPS

    class _Req:
        op = "stats"
        text = ""
        id = "s"
        meta = {}

    assert try_answer(cache, _Req()) is False
    assert cache.stats()["lookups"] == 0


# ----------------------------------------------- byte identity (sentiment)


def test_sentiment_cached_replies_byte_identical_no_dispatch(
    ops, tmp_path
):
    d = str(tmp_path / "rc")
    control = _batcher(ops).start()
    want = _settled(
        [control.submit(f"r{i}", "sentiment", t)
         for i, t in enumerate(TEXTS)]
    )
    control.drain()

    cache = ResponseCache(d, fingerprint=backend_fingerprint(model="mock"))
    cold = _batcher(ops, cache).start()
    got_cold = _settled(
        [cold.submit(f"r{i}", "sentiment", t)
         for i, t in enumerate(TEXTS)]
    )
    cold.drain()
    assert got_cold == want  # same serialized fields, same order

    # Fresh batcher + restarted cache: every reply comes from disk, the
    # wire payload is byte-for-byte the computed one, and the device is
    # never dispatched (zero batches, zero rows).
    warm_cache = ResponseCache(
        d, fingerprint=backend_fingerprint(model="mock")
    )
    warm = _batcher(ops, warm_cache).start()
    got_warm = _settled(
        [warm.submit(f"r{i}", "sentiment", t)
         for i, t in enumerate(TEXTS)]
    )
    stats = warm.stats()
    warm.drain()
    assert [json.dumps(r, sort_keys=False) for r in got_warm] == [
        json.dumps(r, sort_keys=False) for r in want
    ]
    assert stats["cache_hits"] == len(TEXTS)
    assert stats["batches"] == 0 and stats["rows"] == 0
    assert stats["admitted"] == 0  # hits never enter the queue
    assert stats["response_cache"]["hit_rate"] == 1.0
    # the ``cached`` stamp is metadata, never payload
    assert all("cached" not in r for r in got_warm)


def test_whitespace_variant_hits_same_entry(ops, tmp_path):
    cache = ResponseCache(str(tmp_path), fingerprint="fp")
    b = _batcher(ops, cache).start()
    first = _settled([b.submit("a", "sentiment", "happy  song")])[0]
    second = _settled([b.submit("b", "sentiment", " happy\tsong ")])[0]
    stats = b.stats()
    b.drain()
    assert stats["cache_hits"] == 1
    assert _sans_id(second) == _sans_id(first)


# ----------------------------------- byte identity + zero cost (generate)


def test_generate_cached_replies_byte_identical_zero_chip_seconds(
    clf, tmp_path
):
    from music_analyst_tpu.serving.decode_loop import ContinuousScheduler

    kw = dict(n_slots=2, prefill_chunk=16, prompt_region=64,
              max_new_tokens=8, max_queue=32)
    prompts = ["golden sunshine on the river", "rain falls tonight"]

    control = ContinuousScheduler(clf, **kw)
    control.warmup()
    creqs = [
        control.submit(f"c{i}", p, max_new_tokens=8, tenant="gold")
        for i, p in enumerate(prompts)
    ]
    control.run_until_idle()
    want = [_sans_id(r) for r in _settled(creqs)]

    cache = ResponseCache(str(tmp_path / "rc"), fingerprint="llama-tiny")
    sched = ContinuousScheduler(clf, response_cache=cache, **kw)
    sched.warmup()
    variants0 = sched.runtime.compiled_variants()
    reqs = [
        sched.submit(f"a{i}", p, max_new_tokens=8, tenant="gold")
        for i, p in enumerate(prompts)
    ]
    sched.run_until_idle()
    assert [_sans_id(r) for r in _settled(reqs)] == want
    chip0 = sched.slo_snapshot()["tenants"]["gold"]["chip_seconds"]
    assert chip0 > 0.0

    # Warm repeats: answered in submit — byte-identical, zero new
    # chip-seconds billed, zero retraces, decode loop never ticks.
    repeats = [
        sched.submit(f"b{i}", p, max_new_tokens=8, tenant="gold")
        for i, p in enumerate(prompts)
    ]
    assert all(r.done for r in repeats)  # settled without run_until_idle
    assert [_sans_id(r) for r in _settled(repeats)] == want
    stats = sched.stats()
    assert stats["cache_hits"] == len(prompts)
    assert sched.slo_snapshot()["tenants"]["gold"]["chip_seconds"] == chip0
    assert sched.runtime.compiled_variants() == variants0

    # A different budget is a different answer: must miss, not hit.
    other = sched.submit("d0", prompts[0], max_new_tokens=4, tenant="gold")
    assert not other.done
    sched.run_until_idle()
    assert _settled([other])[0]["ok"]
    assert sched.stats()["cache_hits"] == len(prompts)  # unchanged


# ------------------------------------------------------------- never wrong


def test_truncated_entry_detected_evicted_recomputed(ops, tmp_path):
    d = str(tmp_path)
    cache = ResponseCache(d, fingerprint="fp")
    key = cache.key_for("sentiment", TEXTS[0])
    cache.put(key, {"ok": True, "op": "sentiment", "label": "Positive"})
    path = os.path.join(d, f"{key}.json")
    blob = open(path, "r", encoding="utf-8").read()
    with open(path, "w", encoding="utf-8") as fh:
        fh.write(blob[: len(blob) // 2])  # torn write

    fresh = ResponseCache(d, fingerprint="fp")
    assert fresh.lookup(key) is None  # degraded to miss, never wrong
    assert fresh.stats()["corrupt"] == 1
    assert not os.path.exists(path)  # corrupt entries are evicted

    # The miss path recomputes and republishes.
    b = _batcher(ops, fresh).start()
    reply = _settled([b.submit("r", "sentiment", TEXTS[0])])[0]
    b.drain()
    assert reply["ok"] and os.path.exists(path)


def test_crc_flip_detected_evicted_never_served(tmp_path):
    d = str(tmp_path)
    cache = ResponseCache(d, fingerprint="fp")
    key = cache.key_for("sentiment", "tampered song")
    cache.put(key, {"ok": True, "label": "Positive"})
    path = os.path.join(d, f"{key}.json")
    record = json.load(open(path, "r", encoding="utf-8"))
    record["payload"]["label"] = "Negative"  # flipped bytes, stale CRC
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(record, fh)

    fresh = ResponseCache(d, fingerprint="fp")
    assert fresh.lookup(key) is None
    assert fresh.stats()["corrupt"] == 1
    assert not os.path.exists(path)


def test_read_fault_falls_back_without_evicting(tmp_path):
    from music_analyst_tpu.resilience import configure_faults, fault_stats

    d = str(tmp_path)
    cache = ResponseCache(d, fingerprint="fp")
    key = cache.key_for("sentiment", "faulted read song")
    cache.put(key, {"ok": True, "label": "Positive"})
    path = os.path.join(d, f"{key}.json")

    fresh = ResponseCache(d, fingerprint="fp")
    configure_faults("response_cache.read:error@1")
    try:
        assert fresh.lookup(key) is None  # transient: degrade to compute
        trips = fault_stats()["response_cache.read"]["trips"]
    finally:
        configure_faults(None)
    assert trips == 1
    assert fresh.stats()["read_fallbacks"] == 1
    assert fresh.stats()["corrupt"] == 0
    assert os.path.exists(path)  # transient ≠ corrupt: NOT evicted
    assert fresh.lookup(key) == {"ok": True, "label": "Positive"}


def test_write_fault_leaves_settle_uncached(tmp_path):
    from music_analyst_tpu.resilience import configure_faults, fault_stats

    d = str(tmp_path)
    cache = ResponseCache(d, fingerprint="fp")
    key = cache.key_for("sentiment", "faulted write song")
    configure_faults("response_cache.write:error@1")
    try:
        cache.put(key, {"ok": True, "label": "Positive"})
        trips = fault_stats()["response_cache.write"]["trips"]
    finally:
        configure_faults(None)
    assert trips == 1
    assert cache.stats()["write_errors"] == 1
    assert not os.path.exists(os.path.join(d, f"{key}.json"))
    # The memory tier still answered this process; a restart recomputes.
    assert cache.lookup(key) is not None
    assert ResponseCache(d, fingerprint="fp").lookup(key) is None


# --------------------------------------------------------- admission edge


def test_hits_never_charged_to_tenant_bucket(ops, tmp_path):
    cache = ResponseCache(str(tmp_path), fingerprint="fp")
    b = _batcher(ops, cache, tenant_budget=1.0).start()
    prime = _settled([b.submit("p", "sentiment", TEXTS[0],
                               tenant="miser")])[0]
    assert prime["ok"]
    # Burst far past the 1 req/s bucket (burst 2): every repeat hits and
    # none touches the bucket, so nothing sheds.
    reqs = [
        b.submit(f"h{i}", "sentiment", TEXTS[0], tenant="miser")
        for i in range(10)
    ]
    replies = _settled(reqs)
    stats = b.stats()
    b.drain()
    assert all(r["ok"] for r in replies)
    assert stats["cache_hits"] == 10
    assert stats["shed_tenant_budget"] == 0
    # An uncached text from the same tenant still meters normally.
    b2 = _batcher(ops, cache, tenant_budget=1.0)
    for i in range(3):
        b2.submit(f"u{i}", "sentiment", f"fresh uncached text {i}",
                  tenant="miser")
    assert b2.stats()["shed_tenant_budget"] > 0


def test_would_shed_request_is_answered_from_cache(ops, tmp_path):
    cache = ResponseCache(str(tmp_path), fingerprint="fp")
    primer = _batcher(ops, cache).start()
    _settled([primer.submit("p", "sentiment", TEXTS[0])])
    primer.drain()

    # Unstarted batcher with a one-deep queue: the first uncached submit
    # fills it, the second sheds queue_full — but the cached repeat is
    # answered BEFORE the shed ladder ever runs.
    b = _batcher(ops, cache, max_queue=1)
    queued = b.submit("q", "sentiment", "uncached filler text")
    assert not queued.done
    shed = b.submit("s", "sentiment", "another uncached text")
    assert shed.response["error"]["kind"] == "queue_full"
    hit = b.submit("h", "sentiment", TEXTS[0])
    assert hit.done and hit.response["ok"]
    stats = b.stats()
    assert stats["cache_hits"] == 1
    assert stats["shed_queue_full"] == 1  # only the uncached one


def test_journal_dedup_and_response_cache_compose(ops, tmp_path):
    """Re-sent id → journal dedup (never reaches the cache); same text
    under a NEW id → response-cache hit.  Exactly-once is unchanged and
    every cached reply is journaled like a computed one."""
    from music_analyst_tpu.serving.journal import RequestJournal
    from music_analyst_tpu.serving.server import SentimentServer

    journal = RequestJournal(str(tmp_path / "wal"))
    journal.recover()
    cache = ResponseCache(str(tmp_path / "rc"), fingerprint="fp")
    # Stream 1 computes and journals id "a"; stream 2 (a re-dispatching
    # client against a restarted server — the journal's wire contract)
    # re-sends "a" and sends the same text under the NEW id "b".
    first = [json.dumps({"id": "a", "op": "sentiment", "text": TEXTS[0]})]
    second = [
        json.dumps({"id": "a", "op": "sentiment", "text": TEXTS[0]}),
        json.dumps({"id": "b", "op": "sentiment", "text": TEXTS[0]}),
    ]
    out = io.StringIO()
    batcher2 = None
    for lines in (first, second):
        batcher2 = _batcher(ops, cache).start()
        server = SentimentServer(batcher2, mode="stdio", journal=journal)
        server.handle_stream(
            io.StringIO("".join(line + "\n" for line in lines)),
            out, drain_on_eof=True,
        )
    replies = [json.loads(line) for line in out.getvalue().splitlines()]
    assert [r["id"] for r in replies] == ["a", "a", "b"]
    assert _sans_id(replies[1]) == _sans_id(replies[0])
    assert _sans_id(replies[2]) == _sans_id(replies[0])
    assert journal.stats()["deduped"] == 1  # the re-sent id
    assert batcher2.stats()["cache_hits"] == 1  # only the new-id repeat
    # The cached reply was journaled: a restart dedups id "b" too.
    journal.close()
    j2 = RequestJournal(str(tmp_path / "wal"))
    j2.recover()
    assert _sans_id(j2.lookup_reply("b")) == _sans_id(replies[2])
    j2.close()


def test_stats_snapshot_carries_response_cache_section(ops, tmp_path):
    from music_analyst_tpu.serving.server import SentimentServer

    cache = ResponseCache(str(tmp_path), fingerprint="fp")
    batcher = _batcher(ops, cache).start()
    server = SentimentServer(batcher, mode="stdio")
    _settled([batcher.submit("x", "sentiment", TEXTS[0])])
    _settled([batcher.submit("y", "sentiment", TEXTS[0])])
    snap = server.stats_snapshot()
    batcher.drain()
    rc = snap["response_cache"]
    assert rc["lookups"] == 2 and rc["hits"] == 1
    assert rc["hit_rate"] == 0.5
    assert rc["dedup_factor"] > 1.0
    assert "bytes" in rc and "evictions" in rc
