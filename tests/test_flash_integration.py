"""Model forward with attn_impl="flash" ≡ the dense default."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np

from music_analyst_tpu.models.layers import causal_mask, padding_mask


def test_llama_flash_matches_dense():
    from music_analyst_tpu.models.llama import LlamaConfig, LlamaModel

    dense_cfg = LlamaConfig(
        vocab_size=300, dim=64, n_layers=2, n_heads=4, n_kv_heads=2,
        hidden_dim=128, rope_theta=1e4, max_seq_len=256, dtype="float32",
    )
    flash_cfg = dataclasses.replace(dense_cfg, attn_impl="flash")
    B, S = 2, 128
    ids = jnp.asarray(
        np.random.default_rng(0).integers(0, 300, (B, S)), jnp.int32
    )
    positions = jnp.broadcast_to(jnp.arange(S), (B, S))
    lengths = jnp.asarray([S, S - 29], jnp.int32)
    mask = causal_mask(S, S, 0) & padding_mask(lengths, S)

    dense = LlamaModel(dense_cfg)
    params = dense.init(jax.random.key(0), ids, positions, mask)["params"]
    ref, _ = dense.apply({"params": params}, ids, positions, mask)

    flash = LlamaModel(flash_cfg)
    out, _ = flash.apply(
        {"params": params}, ids, positions, mask, lengths=lengths
    )
    # Padded query rows attend degenerately in both impls; compare valid rows.
    for b, n in enumerate([S, S - 29]):
        np.testing.assert_allclose(
            np.asarray(out)[b, :n], np.asarray(ref)[b, :n],
            atol=2e-4, rtol=2e-4,
        )


def test_distilbert_flash_matches_dense():
    from music_analyst_tpu.models.distilbert import (
        DistilBertConfig,
        DistilBertForSentiment,
    )

    dense_cfg = DistilBertConfig(
        vocab_size=500, dim=64, n_layers=2, n_heads=4, hidden_dim=128,
        max_positions=128, dtype="float32",
    )
    flash_cfg = dataclasses.replace(dense_cfg, attn_impl="flash")
    B, S = 3, 128
    ids = jnp.asarray(
        np.random.default_rng(1).integers(0, 500, (B, S)), jnp.int32
    )
    lengths = jnp.asarray([128, 64, 5], jnp.int32)

    dense = DistilBertForSentiment(dense_cfg)
    params = dense.init(jax.random.key(0), ids, lengths)["params"]
    ref = dense.apply({"params": params}, ids, lengths)
    out = DistilBertForSentiment(flash_cfg).apply(
        {"params": params}, ids, lengths
    )
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-4, rtol=2e-4)
