"""Native C++ ingest vs the pure-Python oracle: byte-exact parity."""

import csv

import numpy as np
import pytest

from music_analyst_tpu.data import native
from music_analyst_tpu.data.ingest import ingest_python

pytestmark = pytest.mark.skipif(
    not native.available(),
    reason=f"native lib unavailable: {native.unavailable_reason() if not native.available() else ''}",
)


def word_counts(res):
    hist = np.bincount(
        res.word_ids[res.word_ids >= 0], minlength=len(res.word_vocab)
    )
    return {
        res.word_vocab.tokens[i]: int(n) for i, n in enumerate(hist) if n
    }


def artist_counts(res):
    from collections import Counter

    return Counter(
        res.artist_vocab.tokens[i] for i in res.artist_ids if i >= 0
    )


def assert_parity(native_res, python_res):
    assert native_res.song_count == python_res.song_count
    assert native_res.token_count == python_res.token_count
    np.testing.assert_array_equal(
        native_res.word_offsets, python_res.word_offsets
    )
    assert word_counts(native_res) == word_counts(python_res)
    assert artist_counts(native_res) == artist_counts(python_res)
    # token *streams* must match too (same tokens in the same positions),
    # not just the histograms
    native_tokens = [
        native_res.word_vocab.tokens[i] for i in native_res.word_ids
    ]
    python_tokens = [
        python_res.word_vocab.tokens[i] for i in python_res.word_ids
    ]
    assert native_tokens == python_tokens
    native_artists = [
        native_res.artist_vocab.tokens[i] if i >= 0 else None
        for i in native_res.artist_ids
    ]
    python_artists = [
        python_res.artist_vocab.tokens[i] if i >= 0 else None
        for i in python_res.artist_ids
    ]
    assert native_artists == python_artists


def test_fixture_parity(fixture_csv):
    n = native.ingest_native(str(fixture_csv))
    p = ingest_python(fixture_csv.read_bytes())
    assert_parity(n, p)


def test_randomized_adversarial_parity(tmp_path):
    """Quoted commas, embedded newlines, `""` escapes, accents, empties."""
    rng = np.random.default_rng(7)
    path = tmp_path / "adversarial.csv"
    fragments = [
        "love", "tears", "café", "don't", "'''", "a,b", 'he said ""hi""',
        "line1\nline2", "  padded  ", "x" * 500, "", "naïveté",
        "end with comma,", ",start with comma", 'quote " inside',
    ]
    with open(path, "w", newline="", encoding="utf-8") as fh:
        writer = csv.writer(fh)
        writer.writerow(["artist", "song", "link", "text"])
        for i in range(500):
            parts = rng.choice(fragments, size=rng.integers(1, 6))
            text = " ".join(parts)
            artist = ["ABBA", "Earth, Wind & Fire", "", 'A "quoted" name',
                      "José González"][int(rng.integers(0, 5))]
            writer.writerow([artist, f"S{i}", f"/l/{i}", text])
    n = native.ingest_native(str(path))
    p = ingest_python(path.read_bytes())
    assert_parity(n, p)

    # The record-range partitioner must stay record-exact on the same
    # adversarial bytes: slices contiguous, disjoint, reassembling to the
    # file, with quoted newlines never splitting a record.
    data = path.read_bytes()
    for n_procs in (2, 5):
        slices = [
            native.record_range(str(path), n_procs, proc)
            for proc in range(n_procs)
        ]
        header_end = slices[0][0]
        cursor = header_end
        for he, begin, end, _ in slices:
            assert he == header_end
            assert begin == cursor
            cursor = end
        assert cursor == len(data)
        # Per-slice ingest totals sum to the whole-file totals (no record
        # lost or double-counted at any boundary).
        total = sum(
            ingest_python(data[:he] + data[b:e]).song_count
            for he, b, e, _ in slices
        )
        assert total == p.song_count


def test_synthetic_parity_and_threads(tmp_path):
    from music_analyst_tpu.data.synthetic import generate_dataset

    path = tmp_path / "synthetic.csv"
    generate_dataset(str(path), num_songs=2000, seed=3)
    p = ingest_python(path.read_bytes())
    for threads in (1, 4, 8):
        n = native.ingest_native(str(path), num_threads=threads)
        assert_parity(n, p)


def test_limit_parity(fixture_csv):
    n = native.ingest_native(str(fixture_csv), limit=3)
    p = ingest_python(fixture_csv.read_bytes(), limit=3)
    assert_parity(n, p)


def test_crlf_dataset(tmp_path):
    path = tmp_path / "crlf.csv"
    data = (
        b"artist,song,link,text\r\n"
        b'A,S1,/l,"hello world line"\r\n'
        b"B,S2,/l,short words here\r\n"
    )
    path.write_bytes(data)
    n = native.ingest_native(str(path))
    p = ingest_python(data)
    assert_parity(n, p)


def test_lone_cr_dataset(tmp_path):
    # Classic-Mac lone-\r terminators and an unquoted mid-file \r: the
    # oracle's record reader (csv_io.iter_csv_records_exact) treats an
    # unquoted \r exactly like \n; the native boundary scan must agree.
    path = tmp_path / "cr.csv"
    data = (
        b"artist,song,link,text\r"
        b'A,S1,/l,"hello\rworld line"\r'   # quoted \r is NOT a terminator
        b"B,S2,/l,short words here\r"
        b"C,S3,/l,mixed ending row\r\n"
        b"D,S4,/l,final row words"
    )
    path.write_bytes(data)
    n = native.ingest_native(str(path))
    p = ingest_python(data)
    assert_parity(n, p)


def test_lone_cr_wordpiece_vocab(tmp_path):
    # Thin native-layer twin of test_wordpiece_differential.py's
    # universal-newline case: a classic-Mac (bare-\r) vocab must produce
    # the same handle contents as the \n vocab — ingest.cpp's vocab
    # parser treats \r, \r\n, and \n as one terminator.
    from music_analyst_tpu.data.native import (
        wp_create, wp_destroy, wp_encode_batch,
    )
    from music_analyst_tpu.models.tokenization import _wp_char_table

    vocab = ["[PAD]", "[UNK]", "[CLS]", "[SEP]", "[MASK]",
             "the", "rain", "love", "##s", "##ing"]
    lf = tmp_path / "lf.txt"
    lf.write_bytes("\n".join(vocab).encode() + b"\n")
    cr = tmp_path / "cr.txt"
    cr.write_bytes("\r".join(vocab).encode() + b"\r")
    table = _wp_char_table()
    h_lf = wp_create(str(lf), table)
    h_cr = wp_create(str(cr), table)
    assert h_lf and h_cr
    try:
        texts = ["the rains", "loves the rain"]
        ids_lf, lens_lf, ok_lf = wp_encode_batch(h_lf, texts, 12)
        ids_cr, lens_cr, ok_cr = wp_encode_batch(h_cr, texts, 12)
        assert ok_lf.all() and ok_cr.all()
        np.testing.assert_array_equal(ids_cr, ids_lf)
        np.testing.assert_array_equal(lens_cr, lens_lf)
        # A fused-lines regression would leave the CR vocab one entry
        # short and shift ids; equality above catches it, this guards the
        # test itself from an all-[UNK] vacuous pass.
        assert ids_lf[:, 1].min() >= 5  # first content token is real
    finally:
        wp_destroy(h_lf)
        wp_destroy(h_cr)


def test_tsan_selftest(tmp_path):
    """Full threaded pipeline under ThreadSanitizer: any data race in the
    boundary-scan handoff or interner merge fails hard.  (The reference has
    no race detection at all — SURVEY.md §5.)"""
    import os
    import shutil
    import subprocess

    if shutil.which("g++") is None:
        pytest.skip("g++ unavailable")
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    build = subprocess.run(
        ["make", "-C", os.path.join(repo, "native"), "selftest_tsan"],
        capture_output=True, text=True,
    )
    if build.returncode != 0:
        pytest.skip(f"tsan build unavailable: {build.stderr[-200:]}")
    from music_analyst_tpu.data.synthetic import generate_dataset

    path = tmp_path / "songs.csv"
    generate_dataset(str(path), num_songs=2000, seed=7)
    run = subprocess.run(
        [os.path.join(repo, "native", "selftest_tsan"), str(path), "8"],
        capture_output=True, text=True,
    )
    assert run.returncode == 0, run.stderr
    assert "ThreadSanitizer" not in run.stderr, run.stderr
    assert "songs=2000" in run.stdout


def test_record_capture_parity(tmp_path):
    """capture_records: native blob/offsets byte-identical to the Python
    oracle, on a corpus with quoted commas, escaped quotes, multi-line
    fields, and short rows (the joint pipeline's input contract)."""
    from music_analyst_tpu.data.synthetic import generate_dataset

    path = tmp_path / "songs.csv"
    generate_dataset(str(path), num_songs=500, seed=13)
    n = native.ingest_native(str(path), capture_records=True)
    p = ingest_python(path.read_bytes(), capture_records=True)
    assert n.has_records and p.has_records
    assert n.records_blob == p.records_blob
    np.testing.assert_array_equal(n.record_offsets, p.record_offsets)
    assert len(n.record_offsets) == 3 * n.song_count + 1
    # limit composes with capture
    n3 = native.ingest_native(str(path), limit=17, capture_records=True)
    p3 = ingest_python(path.read_bytes(), limit=17, capture_records=True)
    assert n3.song_count == 17
    assert n3.records_blob == p3.records_blob
    # records decode to the same rows the exact-parser oracle yields
    from music_analyst_tpu.data.csv_io import iter_dataset_fields

    want = [
        tuple(f.decode("utf-8", errors="replace") for f in fields)
        for fields in iter_dataset_fields(path.read_bytes())
    ]
    assert list(n.iter_records()) == want


def test_record_capture_off_by_default(tmp_path):
    from music_analyst_tpu.data.synthetic import generate_dataset

    path = tmp_path / "songs.csv"
    generate_dataset(str(path), num_songs=20, seed=3)
    res = native.ingest_native(str(path))
    assert not res.has_records
    with pytest.raises(ValueError):
        next(res.iter_records())


class TestRecordRanges:
    """man_record_ranges: record-exact multi-controller partitioning."""

    def _slices(self, path, n_procs):
        return [native.record_range(str(path), n_procs, p)
                for p in range(n_procs)]

    def test_single_proc_covers_whole_file(self, fixture_csv):
        data = fixture_csv.read_bytes()
        header_end, begin, end, n = native.record_range(str(fixture_csv), 1, 0)
        assert data[:header_end] + data[begin:end] == data
        assert n > 0

    def test_partition_is_exact_cover(self, tmp_path):
        from music_analyst_tpu.data.synthetic import generate_dataset

        path = tmp_path / "songs.csv"
        generate_dataset(str(path), num_songs=157, seed=3)
        data = path.read_bytes()
        for n_procs in (2, 3, 8):
            slices = self._slices(path, n_procs)
            header_end = slices[0][0]
            # Slices are contiguous, disjoint, and cover the post-header
            # bytes exactly once.
            cursor = header_end
            total_records = 0
            for he, begin, end, n in slices:
                assert he == header_end
                assert begin == cursor
                cursor = end
                total_records += n
            assert cursor == len(data)
            # Every process reconstructs header + its slice; concatenating
            # the bodies reproduces the file byte-exactly.
            rebuilt = data[:header_end] + b"".join(
                data[b:e] for _, b, e, _ in slices
            )
            assert rebuilt == data
            assert total_records >= 157  # every song record owned once

    def test_empty_and_header_only(self, tmp_path):
        empty = tmp_path / "empty.csv"
        empty.write_bytes(b"")
        assert native.record_range(str(empty), 4, 1) == (0, 0, 0, 0)
        header_only = tmp_path / "h.csv"
        header_only.write_bytes(b"artist,song,link,text\n")
        he, begin, end, n = native.record_range(str(header_only), 4, 0)
        assert (he, n) == (len(b"artist,song,link,text\n"), 0)
        assert begin == end

    def test_matches_python_fallback_counts(self, tmp_path):
        """Native partition and the Python fallback agree on the dataset's
        ingest result: same global counts from either slicing."""
        from music_analyst_tpu.data.csv_io import iter_csv_records_exact

        path = tmp_path / "songs.csv"
        path.write_bytes(
            b"artist,song,link,text\n"
            b'A,"S,1",/l,"hello world lyric"\n'
            b'B,S2,/l,"multi\nline ""quoted"" lyric"\r\n'
            b"A,S3,/l,short words here\r"
            b"C,S4,/l,final row no newline"
        )
        data = path.read_bytes()
        records = list(iter_csv_records_exact(data))
        n_procs = 2
        for p in range(n_procs):
            he, begin, end, _ = native.record_range(str(path), n_procs, p)
            mini = data[:he] + data[begin:end]
            got = ingest_python(mini)
            # Python split of the same record list for comparison
            body = records[1:]
            share = -(-len(body) // n_procs)
            want = ingest_python(
                records[0] + b"".join(body[p * share:(p + 1) * share])
            )
            assert got.song_count == want.song_count
            assert word_counts(got) == word_counts(want)
