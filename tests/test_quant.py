"""Dynamic int8 matmul path: op-level error bounds + model parity.

The quant modules share the float param tree with the dense modules, so
the parity tests initialize ONE set of params with the dense model and
apply both models to the same inputs — any structural drift between the
trees fails loudly at apply time.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np

from music_analyst_tpu.ops.quant import (
    quant_dense_axis_last,
    quant_dense_axis_last2,
    quant_matmul,
)


def test_quant_matmul_error_bound():
    rng = np.random.default_rng(0)
    x = rng.normal(size=(64, 256)).astype(np.float32)
    w = rng.normal(size=(256, 128)).astype(np.float32)
    exact = x @ w
    got = np.asarray(quant_matmul(jnp.asarray(x), jnp.asarray(w)))
    rel = np.linalg.norm(got - exact) / np.linalg.norm(exact)
    assert rel < 0.02, rel  # symmetric int8: ~0.8% per operand


def test_quant_dense_layouts_match_dense_math():
    rng = np.random.default_rng(1)
    x = jnp.asarray(rng.normal(size=(4, 10, 32)), jnp.float32)
    # axis=-1 with multi-dim features [dim, heads, head_dim]
    k = jnp.asarray(rng.normal(size=(32, 4, 8)), jnp.float32)
    b = jnp.asarray(rng.normal(size=(4, 8)), jnp.float32)
    got = np.asarray(quant_dense_axis_last(x, k, b))
    exact = np.einsum("btk,khd->bthd", x, k) + np.asarray(b)
    assert got.shape == exact.shape
    rel = np.linalg.norm(got - exact) / np.linalg.norm(exact)
    assert rel < 0.03, rel
    # axis=(-2,-1): [B, T, H, D] @ [H, D, N]
    xo = jnp.asarray(rng.normal(size=(4, 10, 4, 8)), jnp.float32)
    ko = jnp.asarray(rng.normal(size=(4, 8, 32)), jnp.float32)
    bo = jnp.asarray(rng.normal(size=(32,)), jnp.float32)
    got2 = np.asarray(quant_dense_axis_last2(xo, ko, bo))
    exact2 = np.einsum("bthd,hdn->btn", xo, ko) + np.asarray(bo)
    assert got2.shape == exact2.shape
    rel2 = np.linalg.norm(got2 - exact2) / np.linalg.norm(exact2)
    assert rel2 < 0.03, rel2


def test_int8_model_logits_track_dense_model():
    """Same params through the fp32 and int8 DistilBERT forwards: logits
    must correlate tightly — quantization noise, not structural change."""
    from music_analyst_tpu.models.distilbert import (
        DistilBertConfig,
        DistilBertForSentiment,
    )

    cfg = dataclasses.replace(DistilBertConfig.tiny(), dtype="float32")
    qcfg = dataclasses.replace(cfg, quant="int8")
    model = DistilBertForSentiment(cfg)
    qmodel = DistilBertForSentiment(qcfg)
    rng = np.random.default_rng(3)
    ids = jnp.asarray(rng.integers(0, cfg.vocab_size, (8, 32)), jnp.int32)
    lengths = jnp.asarray(rng.integers(4, 33, (8,)), jnp.int32)
    params = model.init(jax.random.key(0), ids, lengths)["params"]
    dense_logits = np.asarray(model.apply({"params": params}, ids, lengths))
    quant_logits = np.asarray(qmodel.apply({"params": params}, ids, lengths))
    assert dense_logits.shape == quant_logits.shape
    corr = np.corrcoef(dense_logits.ravel(), quant_logits.ravel())[0, 1]
    assert corr > 0.99, corr
    spread = dense_logits.max() - dense_logits.min()
    assert np.abs(quant_logits - dense_logits).max() < 0.1 * spread


def test_int8_classifier_end_to_end():
    from music_analyst_tpu.models.distilbert import DistilBertClassifier

    clf = DistilBertClassifier.from_pretrained_or_random(
        "distilbert-tiny-int8", max_len=64
    )
    assert clf.config.quant == "int8"
    labels = clf.classify_batch(["love and rain", "", "tears " * 30])
    assert labels[1] == "Neutral"
    assert all(l in ("Positive", "Neutral", "Negative") for l in labels)
