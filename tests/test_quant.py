"""Dynamic int8 matmul path: op-level error bounds + model parity.

The quant modules share the float param tree with the dense modules, so
the parity tests initialize ONE set of params with the dense model and
apply both models to the same inputs — any structural drift between the
trees fails loudly at apply time.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np

from music_analyst_tpu.ops.quant import (
    quant_dense_axis_last,
    quant_dense_axis_last2,
    quant_matmul,
)


def test_quant_matmul_error_bound():
    rng = np.random.default_rng(0)
    x = rng.normal(size=(64, 256)).astype(np.float32)
    w = rng.normal(size=(256, 128)).astype(np.float32)
    exact = x @ w
    got = np.asarray(quant_matmul(jnp.asarray(x), jnp.asarray(w)))
    rel = np.linalg.norm(got - exact) / np.linalg.norm(exact)
    assert rel < 0.02, rel  # symmetric int8: ~0.8% per operand


def test_quant_dense_layouts_match_dense_math():
    rng = np.random.default_rng(1)
    x = jnp.asarray(rng.normal(size=(4, 10, 32)), jnp.float32)
    # axis=-1 with multi-dim features [dim, heads, head_dim]
    k = jnp.asarray(rng.normal(size=(32, 4, 8)), jnp.float32)
    b = jnp.asarray(rng.normal(size=(4, 8)), jnp.float32)
    got = np.asarray(quant_dense_axis_last(x, k, b))
    exact = np.einsum("btk,khd->bthd", x, k) + np.asarray(b)
    assert got.shape == exact.shape
    rel = np.linalg.norm(got - exact) / np.linalg.norm(exact)
    assert rel < 0.03, rel
    # axis=(-2,-1): [B, T, H, D] @ [H, D, N]
    xo = jnp.asarray(rng.normal(size=(4, 10, 4, 8)), jnp.float32)
    ko = jnp.asarray(rng.normal(size=(4, 8, 32)), jnp.float32)
    bo = jnp.asarray(rng.normal(size=(32,)), jnp.float32)
    got2 = np.asarray(quant_dense_axis_last2(xo, ko, bo))
    exact2 = np.einsum("bthd,hdn->btn", xo, ko) + np.asarray(bo)
    assert got2.shape == exact2.shape
    rel2 = np.linalg.norm(got2 - exact2) / np.linalg.norm(exact2)
    assert rel2 < 0.03, rel2


def test_int8_model_logits_track_dense_model():
    """Same params through the fp32 and int8 DistilBERT forwards: logits
    must correlate tightly — quantization noise, not structural change."""
    from music_analyst_tpu.models.distilbert import (
        DistilBertConfig,
        DistilBertForSentiment,
    )

    cfg = dataclasses.replace(DistilBertConfig.tiny(), dtype="float32")
    qcfg = dataclasses.replace(cfg, quant="int8")
    model = DistilBertForSentiment(cfg)
    qmodel = DistilBertForSentiment(qcfg)
    rng = np.random.default_rng(3)
    ids = jnp.asarray(rng.integers(0, cfg.vocab_size, (8, 32)), jnp.int32)
    lengths = jnp.asarray(rng.integers(4, 33, (8,)), jnp.int32)
    params = model.init(jax.random.key(0), ids, lengths)["params"]
    dense_logits = np.asarray(model.apply({"params": params}, ids, lengths))
    quant_logits = np.asarray(qmodel.apply({"params": params}, ids, lengths))
    assert dense_logits.shape == quant_logits.shape
    corr = np.corrcoef(dense_logits.ravel(), quant_logits.ravel())[0, 1]
    assert corr > 0.99, corr
    spread = dense_logits.max() - dense_logits.min()
    assert np.abs(quant_logits - dense_logits).max() < 0.1 * spread


def test_int8_llama_logits_track_dense_model():
    from music_analyst_tpu.models.layers import causal_mask
    from music_analyst_tpu.models.llama import LlamaConfig, LlamaModel

    cfg = LlamaConfig(
        vocab_size=128, dim=32, n_layers=2, n_heads=4, n_kv_heads=2,
        hidden_dim=64, rope_theta=1e4, max_seq_len=32, dtype="float32",
    )
    qcfg = dataclasses.replace(cfg, quant="int8")
    model, qmodel = LlamaModel(cfg), LlamaModel(qcfg)
    rng = np.random.default_rng(5)
    ids = jnp.asarray(rng.integers(0, cfg.vocab_size, (2, 16)), jnp.int32)
    pos = jnp.broadcast_to(jnp.arange(16), (2, 16))
    mask = causal_mask(16, 16, 0)
    params = model.init(jax.random.key(0), ids, pos, mask)["params"]
    dense_logits, _ = model.apply({"params": params}, ids, pos, mask)
    quant_logits, _ = qmodel.apply({"params": params}, ids, pos, mask)
    dense_logits = np.asarray(dense_logits)
    quant_logits = np.asarray(quant_logits)
    corr = np.corrcoef(dense_logits.ravel(), quant_logits.ravel())[0, 1]
    assert corr > 0.99, corr


def test_int8_llama_preset_suffix():
    from music_analyst_tpu.models.llama import LlamaZeroShotClassifier

    clf = LlamaZeroShotClassifier.from_pretrained_or_random(
        "llama3-tiny-int8", max_prompt_len=64
    )
    assert clf.config.quant == "int8"
    labels = clf.classify_batch(["la la love", ""])
    assert labels[1] == "Neutral"


def test_quant_dense_init_matches_dense_general_scale():
    """Self-initialized quant modules must use DenseGeneral's flattened
    fan-in, not raw lecun_normal on the 3-D shape (which under-scales
    q/k/v kernels by sqrt(n_heads))."""
    from flax import linen as nn

    from music_analyst_tpu.models.layers import QuantDenseGeneral

    x = jnp.zeros((2, 768))
    dense = nn.DenseGeneral(features=(12, 64), axis=-1, name="d")
    quant = QuantDenseGeneral(features=(12, 64), axis=-1, name="q")
    kd = dense.init(jax.random.key(0), x)["params"]["kernel"]
    kq = quant.init(jax.random.key(0), x)["params"]["kernel"]
    assert kd.shape == kq.shape
    ratio = np.std(np.asarray(kq)) / np.std(np.asarray(kd))
    assert 0.8 < ratio < 1.25, ratio


def test_int8_moe_logits_track_dense_model():
    """MoE × int8 composes (r4 VERDICT weak #6): the same param tree run
    with quant='int8' must track the float MoE model — the expert einsums
    are quantized, not just the attention projections."""
    from music_analyst_tpu.models.layers import causal_mask
    from music_analyst_tpu.models.llama import LlamaConfig, LlamaModel

    cfg = LlamaConfig(
        vocab_size=64, dim=32, n_layers=2, n_heads=4, n_kv_heads=2,
        hidden_dim=64, rope_theta=1e4, max_seq_len=32, n_experts=4,
        dtype="float32",
    )
    qcfg = dataclasses.replace(cfg, quant="int8")
    rng = np.random.default_rng(7)
    ids = jnp.asarray(rng.integers(0, cfg.vocab_size, (2, 16)), jnp.int32)
    pos = jnp.broadcast_to(jnp.arange(16), (2, 16))
    mask = causal_mask(16, 16, 0)
    model, qmodel = LlamaModel(cfg), LlamaModel(qcfg)
    params = model.init(jax.random.key(0), ids, pos, mask)["params"]
    dense_logits, _ = model.apply({"params": params}, ids, pos, mask)
    quant_logits, _ = qmodel.apply({"params": params}, ids, pos, mask)
    corr = np.corrcoef(
        np.asarray(dense_logits).ravel(), np.asarray(quant_logits).ravel()
    )[0, 1]
    assert corr > 0.99, corr


def test_int8_composes_with_flash_attention():
    """quant touches only the projections; the flash kernel must slot in
    unchanged between them."""
    from music_analyst_tpu.models.distilbert import (
        DistilBertConfig,
        DistilBertForSentiment,
    )

    base = dataclasses.replace(
        DistilBertConfig.tiny(), dtype="float32", quant="int8"
    )
    flash_cfg = dataclasses.replace(base, attn_impl="flash")
    dense_model = DistilBertForSentiment(base)
    flash_model = DistilBertForSentiment(flash_cfg)
    rng = np.random.default_rng(7)
    ids = jnp.asarray(rng.integers(0, base.vocab_size, (2, 64)), jnp.int32)
    lengths = jnp.asarray([64, 40], jnp.int32)  # padded row: mask via lengths
    params = dense_model.init(jax.random.key(0), ids, lengths)["params"]
    dense_logits = np.asarray(
        dense_model.apply({"params": params}, ids, lengths)
    )
    flash_logits = np.asarray(
        flash_model.apply({"params": params}, ids, lengths)
    )
    # Same params, same quant math — only the attention formulation
    # differs, so the two int8 forwards must agree tightly (incl. the
    # padding-masked row).
    np.testing.assert_allclose(flash_logits, dense_logits, rtol=2e-2,
                               atol=2e-2)


def test_int8_composes_with_kv_cache_decode():
    from music_analyst_tpu.models.llama import LlamaConfig, LlamaZeroShotClassifier

    cfg = dataclasses.replace(LlamaConfig.tiny(), quant="int8")
    clf = LlamaZeroShotClassifier(config=cfg, max_prompt_len=32, seed=1)
    outs = clf.generate_batch(["la la love", "rain"], max_new_tokens=4)
    assert len(outs) == 2 and all(isinstance(o, str) for o in outs)


def test_int8_classifier_end_to_end():
    from music_analyst_tpu.models.distilbert import DistilBertClassifier

    clf = DistilBertClassifier.from_pretrained_or_random(
        "distilbert-tiny-int8", max_len=64
    )
    assert clf.config.quant == "int8"
    labels = clf.classify_batch(["love and rain", "", "tears " * 30])
    assert labels[1] == "Neutral"
    assert all(l in ("Positive", "Neutral", "Negative") for l in labels)


def test_outlier_token_does_not_poison_batch():
    """Per-token activation scaling: one spiked row costs only its own
    resolution.  (The former per-tensor scale lost ~all precision on every
    other row once one activation spiked — VERDICT r3 weak #4.)"""
    from music_analyst_tpu.ops.quant import quant_matmul

    rng = np.random.default_rng(7)
    x = rng.normal(size=(32, 64)).astype(np.float32)
    x[5] *= 1000.0  # one outlier token
    w = rng.normal(size=(64, 16)).astype(np.float32)
    exact = x @ w
    got = np.asarray(quant_matmul(jnp.asarray(x), jnp.asarray(w)))
    normal_rows = np.r_[0:5, 6:32]
    rel = (
        np.abs(got[normal_rows] - exact[normal_rows]).max()
        / np.abs(exact[normal_rows]).max()
    )
    # Per-tensor scaling puts every normal row's max |qx| at ~0.127 -> rel
    # error ~100%; per-token keeps the usual int8 bound.
    assert rel < 0.03, rel
    # The outlier row itself is also fine (it owns its scale).
    rel_out = np.abs(got[5] - exact[5]).max() / np.abs(exact[5]).max()
    assert rel_out < 0.03, rel_out
