"""Classifier backends on a mesh ≡ unsharded (the 8-device CPU emulation
of the reference's 'mpirun -np 8 on one box', SURVEY.md §4)."""

import jax
import numpy as np
import pytest

from music_analyst_tpu.parallel.mesh import MeshSpec, build_mesh

TEXTS = [
    "love and sunshine all day",
    "tears and pain in the lonely night",
    "",
    "la la la " * 40,
    "cry me a river of joy",
]


@pytest.fixture(scope="module")
def dp_mesh():
    return build_mesh(MeshSpec((("dp", 8),)))


@pytest.fixture(scope="module")
def dp_tp_mesh():
    return build_mesh(MeshSpec((("dp", 2), ("tp", 4))))


def test_distilbert_dp_sharded_matches_unsharded(dp_mesh):
    from music_analyst_tpu.models.distilbert import (
        DistilBertClassifier,
        DistilBertConfig,
    )

    cfg = DistilBertConfig.tiny()
    plain = DistilBertClassifier(config=cfg, max_len=64, seed=5)
    sharded = DistilBertClassifier(config=cfg, max_len=64, seed=5,
                                   mesh=dp_mesh)
    assert plain.classify_batch(TEXTS) == sharded.classify_batch(TEXTS)


def test_distilbert_dp_tp_sharded_matches_unsharded(dp_tp_mesh):
    from music_analyst_tpu.models.distilbert import (
        DistilBertClassifier,
        DistilBertConfig,
    )

    cfg = DistilBertConfig.tiny()
    plain = DistilBertClassifier(config=cfg, max_len=64, seed=6)
    sharded = DistilBertClassifier(config=cfg, max_len=64, seed=6,
                                   mesh=dp_tp_mesh)
    assert plain.classify_batch(TEXTS) == sharded.classify_batch(TEXTS)


def test_llama_tp_sharded_matches_unsharded(dp_tp_mesh):
    from music_analyst_tpu.models.llama import (
        LlamaConfig,
        LlamaZeroShotClassifier,
    )

    cfg = LlamaConfig(
        vocab_size=300, dim=32, n_layers=2, n_heads=4, n_kv_heads=4,
        hidden_dim=64, rope_theta=1e4, max_seq_len=128, dtype="float32",
    )
    plain = LlamaZeroShotClassifier(config=cfg, max_prompt_len=64, seed=7)
    sharded = LlamaZeroShotClassifier(config=cfg, max_prompt_len=64, seed=7,
                                      mesh=dp_tp_mesh)
    assert plain.classify_batch(TEXTS) == sharded.classify_batch(TEXTS)


# ---------------------------------------------- tensor-parallel decode
#
# float32 on purpose: the tp all-reduce changes float summation order,
# and in bf16 that flips greedy argmax on near-ties (PERFORMANCE.md
# "Scale-out serving").  In float32 at these widths the reduction is
# exact, so tp=N must be BYTE-identical to the single-chip runtimes.

GEN_PROMPTS = [
    "golden sunshine on the river",
    "rain",
    "shadows fall across the empty street tonight",
    "la la la la",
    "winter wind and summer fire",
    "the long road home winds past the silver lake",
]


def _gen_clf(mesh=None):
    from music_analyst_tpu.models.llama import (
        LlamaConfig,
        LlamaZeroShotClassifier,
    )

    cfg = LlamaConfig(
        vocab_size=512, dim=128, n_layers=2, n_heads=8, n_kv_heads=4,
        hidden_dim=256, rope_theta=1e4, max_seq_len=128, dtype="float32",
    )
    return LlamaZeroShotClassifier(config=cfg, max_prompt_len=64, seed=11,
                                   mesh=mesh)


@pytest.fixture(scope="module")
def plain_gen_clf():
    return _gen_clf()


@pytest.fixture(scope="module")
def tp2_gen_clf():
    mesh = build_mesh(MeshSpec((("tp", 2),)), devices=jax.devices()[:2])
    return _gen_clf(mesh=mesh)


def test_slot_decode_tp_byte_identical(plain_gen_clf, tp2_gen_clf):
    """tp=2 slot runtime emits byte-identical greedy text to tp=1
    (``page_size=0`` pins the monolithic slot cache)."""
    kwargs = dict(max_new_tokens=8, n_slots=4, prefill_chunk=16,
                  page_size=0)
    plain = plain_gen_clf.generate_batch_continuous(GEN_PROMPTS, **kwargs)
    tp = tp2_gen_clf.generate_batch_continuous(GEN_PROMPTS, **kwargs)
    assert tp == plain


def test_paged_decode_tp_byte_identical(plain_gen_clf, tp2_gen_clf):
    """tp=2 paged runtime (prefix sharing on, the serving default) is
    byte-identical to tp=1 paged and to the tp=1 slot route."""
    kwargs = dict(max_new_tokens=8, n_slots=4, prefill_chunk=16)
    plain = plain_gen_clf.generate_batch_continuous(GEN_PROMPTS, **kwargs)
    tp = tp2_gen_clf.generate_batch_continuous(GEN_PROMPTS, **kwargs)
    assert tp == plain


def test_tp4_decode_byte_identical(plain_gen_clf):
    """tp=4 shards one KV head per chip — the extreme split still
    matches single-chip exactly."""
    mesh = build_mesh(MeshSpec((("tp", 4),)), devices=jax.devices()[:4])
    tp4 = _gen_clf(mesh=mesh)
    kwargs = dict(max_new_tokens=6, n_slots=2, prefill_chunk=16)
    plain = plain_gen_clf.generate_batch_continuous(GEN_PROMPTS, **kwargs)
    assert tp4.generate_batch_continuous(GEN_PROMPTS, **kwargs) == plain


@pytest.mark.parametrize("page_size", [0, None])
def test_tp_decode_zero_retraces(tp2_gen_clf, page_size):
    """The fixed-program discipline survives the mesh: after warmup a
    mixed-length tp workload compiles nothing new (slot and paged)."""
    from music_analyst_tpu.serving.decode_loop import ContinuousScheduler

    sched = ContinuousScheduler(
        tp2_gen_clf, n_slots=4, prefill_chunk=16, prompt_region=64,
        max_new_tokens=8, max_queue=64, page_size=page_size,
    )
    sched.warmup()
    before = sched.runtime.compiled_variants()
    prompts = [GEN_PROMPTS[i % len(GEN_PROMPTS)] for i in range(10)]
    reqs = [
        sched.submit(i, p, max_new_tokens=1 + i % 7)
        for i, p in enumerate(prompts)
    ]
    sched.run_until_idle()
    assert all(r.response and r.response.get("ok") for r in reqs)
    assert sched.runtime.compiled_variants() == before


def test_tp_runtime_kv_cache_is_head_sharded(tp2_gen_clf):
    """The slot cache's head axis actually lands on the tp axis (not
    silently replicated): 4 kv heads over tp=2."""
    from jax.sharding import PartitionSpec as P

    rt = tp2_gen_clf.slot_runtime(n_slots=2, prefill_chunk=16,
                                  max_new_tokens=4, prompt_region=32)
    caches = rt.init_caches()
    spec = caches[0].keys.sharding.spec
    assert tuple(spec) == (None, None, "tp", None)
    assert caches[0].length.sharding.is_fully_replicated


def test_kv_cache_spec_degrades_to_replicated(dp_mesh, dp_tp_mesh):
    """tp absent, or a tp width the head count can't split, falls back
    to the replicated single-chip layout instead of failing placement."""
    from jax.sharding import PartitionSpec as P

    from music_analyst_tpu.parallel.sharding import kv_cache_spec

    kv, lens = kv_cache_spec(dp_tp_mesh, n_kv_heads=4)  # tp=4 | 4 heads
    assert kv == P(None, None, "tp", None) and lens == P()
    kv, _ = kv_cache_spec(dp_tp_mesh, n_kv_heads=3)  # 4 ∤ 3 → replicate
    assert kv == P()
    kv, _ = kv_cache_spec(dp_mesh, n_kv_heads=4)  # no tp axis at all
    assert kv == P()


def test_serve_mesh_resolves_and_validates(monkeypatch):
    from music_analyst_tpu.serving.server import serve_mesh

    assert serve_mesh(None) is None
    assert serve_mesh(1) is None
    mesh = serve_mesh(2)
    assert mesh.axis_names == ("tp",) and mesh.devices.size == 2
    with pytest.raises(ValueError):
        serve_mesh(64)  # more chips than the host has
    monkeypatch.setenv("MUSICAAL_SERVE_TP", "4")
    assert serve_mesh(None).devices.size == 4


def test_sentiment_engine_with_mesh_backend(dp_mesh, tmp_path):
    """run_sentiment over a mesh-backed classifier produces the standard
    artifacts with all songs accounted for."""
    from music_analyst_tpu.engines.sentiment import run_sentiment
    from music_analyst_tpu.models.distilbert import (
        DistilBertClassifier,
        DistilBertConfig,
    )

    backend = DistilBertClassifier(
        config=DistilBertConfig.tiny(), max_len=64, mesh=dp_mesh
    )
    import os

    fixture = os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "fixtures",
        "mini_songs.csv",
    )
    result = run_sentiment(
        fixture, backend=backend, batch_size=3,
        output_dir=str(tmp_path), quiet=True,
    )
    assert sum(result.counts.values()) == len(result.rows) == 8
