"""Classifier backends on a mesh ≡ unsharded (the 8-device CPU emulation
of the reference's 'mpirun -np 8 on one box', SURVEY.md §4)."""

import jax
import numpy as np
import pytest

from music_analyst_tpu.parallel.mesh import MeshSpec, build_mesh

TEXTS = [
    "love and sunshine all day",
    "tears and pain in the lonely night",
    "",
    "la la la " * 40,
    "cry me a river of joy",
]


@pytest.fixture(scope="module")
def dp_mesh():
    return build_mesh(MeshSpec((("dp", 8),)))


@pytest.fixture(scope="module")
def dp_tp_mesh():
    return build_mesh(MeshSpec((("dp", 2), ("tp", 4))))


def test_distilbert_dp_sharded_matches_unsharded(dp_mesh):
    from music_analyst_tpu.models.distilbert import (
        DistilBertClassifier,
        DistilBertConfig,
    )

    cfg = DistilBertConfig.tiny()
    plain = DistilBertClassifier(config=cfg, max_len=64, seed=5)
    sharded = DistilBertClassifier(config=cfg, max_len=64, seed=5,
                                   mesh=dp_mesh)
    assert plain.classify_batch(TEXTS) == sharded.classify_batch(TEXTS)


def test_distilbert_dp_tp_sharded_matches_unsharded(dp_tp_mesh):
    from music_analyst_tpu.models.distilbert import (
        DistilBertClassifier,
        DistilBertConfig,
    )

    cfg = DistilBertConfig.tiny()
    plain = DistilBertClassifier(config=cfg, max_len=64, seed=6)
    sharded = DistilBertClassifier(config=cfg, max_len=64, seed=6,
                                   mesh=dp_tp_mesh)
    assert plain.classify_batch(TEXTS) == sharded.classify_batch(TEXTS)


def test_llama_tp_sharded_matches_unsharded(dp_tp_mesh):
    from music_analyst_tpu.models.llama import (
        LlamaConfig,
        LlamaZeroShotClassifier,
    )

    cfg = LlamaConfig(
        vocab_size=300, dim=32, n_layers=2, n_heads=4, n_kv_heads=4,
        hidden_dim=64, rope_theta=1e4, max_seq_len=128, dtype="float32",
    )
    plain = LlamaZeroShotClassifier(config=cfg, max_prompt_len=64, seed=7)
    sharded = LlamaZeroShotClassifier(config=cfg, max_prompt_len=64, seed=7,
                                      mesh=dp_tp_mesh)
    assert plain.classify_batch(TEXTS) == sharded.classify_batch(TEXTS)


def test_sentiment_engine_with_mesh_backend(dp_mesh, tmp_path):
    """run_sentiment over a mesh-backed classifier produces the standard
    artifacts with all songs accounted for."""
    from music_analyst_tpu.engines.sentiment import run_sentiment
    from music_analyst_tpu.models.distilbert import (
        DistilBertClassifier,
        DistilBertConfig,
    )

    backend = DistilBertClassifier(
        config=DistilBertConfig.tiny(), max_len=64, mesh=dp_mesh
    )
    import os

    fixture = os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "fixtures",
        "mini_songs.csv",
    )
    result = run_sentiment(
        fixture, backend=backend, batch_size=3,
        output_dir=str(tmp_path), quiet=True,
    )
    assert sum(result.counts.values()) == len(result.rows) == 8
