"""Differential tests against the ACTUAL reference binary.

The north-star correctness gate is "reproduce the CPU ``word_counts.csv``
ranking exactly" (BASELINE.md).  These tests compile the unmodified
reference source (``/root/reference/src/parallel_spotify.c``) against a
single-rank MPI stub (``tests/oracle/mpi.h``), run it, and diff this
framework's artifacts against the reference's **byte-for-byte**.
"""

import shutil
import subprocess

import pytest

REFERENCE_SRC = "/root/reference/src/parallel_spotify.c"


@pytest.fixture(scope="module")
def reference_binary(tmp_path_factory):
    import os
    import pathlib

    if not os.path.exists(REFERENCE_SRC):
        pytest.skip("reference source not available")
    cc = shutil.which("gcc") or shutil.which("cc")
    if cc is None:
        pytest.skip("no C compiler")
    out_dir = tmp_path_factory.mktemp("refbin")
    binary = out_dir / "parallel_spotify"
    stub_dir = pathlib.Path(__file__).parent / "oracle"
    proc = subprocess.run(
        [
            cc, "-O2", "-std=gnu11", f"-I{stub_dir}", "-o", str(binary),
            REFERENCE_SRC,
        ],
        capture_output=True,
        text=True,
    )
    if proc.returncode != 0:
        pytest.skip(f"reference compile failed: {proc.stderr[:400]}")
    return binary


def run_reference(binary, dataset, out_dir):
    proc = subprocess.run(
        [str(binary), str(dataset), "--output-dir", str(out_dir)],
        capture_output=True,
        text=True,
        timeout=300,
    )
    assert proc.returncode == 0, proc.stderr[:500]
    return proc.stdout


def run_ours(dataset, out_dir):
    from music_analyst_tpu.engines.wordcount import run_analysis

    return run_analysis(str(dataset), output_dir=str(out_dir), quiet=True)


@pytest.mark.parametrize("ingest_backend", ["python", "native"])
def test_fixture_byte_parity(
    reference_binary, fixture_csv, tmp_path, ingest_backend
):
    if ingest_backend == "native":
        from music_analyst_tpu.data import native

        if not native.available():
            pytest.skip("native lib unavailable")
    ref_out = tmp_path / "ref"
    our_out = tmp_path / "ours"
    stdout = run_reference(reference_binary, fixture_csv, ref_out)
    from music_analyst_tpu.engines.wordcount import run_analysis

    result = run_analysis(
        str(fixture_csv),
        output_dir=str(our_out),
        quiet=True,
        ingest_backend=ingest_backend,
    )
    assert (
        (ref_out / "word_counts.csv").read_bytes()
        == (our_out / "word_counts.csv").read_bytes()
    )
    assert (
        (ref_out / "top_artists.csv").read_bytes()
        == (our_out / "top_artists.csv").read_bytes()
    )
    # console totals agree with the engine's totals
    assert f"Total songs processed: {result.total_songs}" in stdout
    assert f"Total words counted: {result.total_words}" in stdout
    # the split_columns preprocessing artifacts are byte-identical too
    for name in ("artist.csv", "text.csv"):
        assert (
            (ref_out / "split_columns" / name).read_bytes()
            == (our_out / "split_columns" / name).read_bytes()
        ), f"split artifact {name} differs"


def test_synthetic_corpus_byte_parity(reference_binary, tmp_path):
    from music_analyst_tpu.data.synthetic import generate_dataset

    dataset = tmp_path / "synthetic.csv"
    generate_dataset(str(dataset), num_songs=3000, seed=5)
    ref_out = tmp_path / "ref"
    our_out = tmp_path / "ours"
    run_reference(reference_binary, dataset, ref_out)
    run_ours(dataset, our_out)
    assert (
        (ref_out / "word_counts.csv").read_bytes()
        == (our_out / "word_counts.csv").read_bytes()
    )
    assert (
        (ref_out / "top_artists.csv").read_bytes()
        == (our_out / "top_artists.csv").read_bytes()
    )


def test_word_limit_parity(reference_binary, fixture_csv, tmp_path):
    ref_out = tmp_path / "ref"
    our_out = tmp_path / "ours"
    proc = subprocess.run(
        [
            str(reference_binary), str(fixture_csv),
            "--word-limit", "5", "--artist-limit", "3",
            "--output-dir", str(ref_out),
        ],
        capture_output=True,
        timeout=300,
    )
    assert proc.returncode == 0
    from music_analyst_tpu.engines.wordcount import run_analysis

    run_analysis(
        str(fixture_csv), output_dir=str(our_out), word_limit=5,
        artist_limit=3, quiet=True,
    )
    assert (
        (ref_out / "word_counts.csv").read_bytes()
        == (our_out / "word_counts.csv").read_bytes()
    )
    assert (
        (ref_out / "top_artists.csv").read_bytes()
        == (our_out / "top_artists.csv").read_bytes()
    )
