"""CLI surface: flag compatibility and artifact wiring."""

import json

from music_analyst_tpu.cli.main import main


def test_analyze_command(fixture_csv, tmp_path, capsys):
    rc = main(
        [
            "analyze",
            str(fixture_csv),
            "--output-dir",
            str(tmp_path),
            "--word-limit",
            "5",
            "--ingest",
            "python",
        ]
    )
    assert rc == 0
    out = capsys.readouterr().out
    assert "=== Parallel Spotify Analysis ===" in out
    assert "Total songs processed:" in out
    assert (tmp_path / "word_counts.csv").exists()
    assert (tmp_path / "top_artists.csv").exists()
    assert (tmp_path / "performance_metrics.json").exists()


def test_analyze_count_modes_agree(fixture_csv, tmp_path, capsys):
    for mode in ("host-shard", "device-ids"):
        rc = main(
            [
                "analyze",
                str(fixture_csv),
                "--output-dir",
                str(tmp_path / mode),
                "--ingest",
                "python",
                "--count-mode",
                mode,
            ]
        )
        assert rc == 0
    capsys.readouterr()
    a = (tmp_path / "host-shard" / "word_counts.csv").read_bytes()
    b = (tmp_path / "device-ids" / "word_counts.csv").read_bytes()
    assert a == b


def test_sentiment_command_mock(fixture_csv, tmp_path, capsys):
    rc = main(
        [
            "sentiment",
            str(fixture_csv),
            "--mock",
            "--output-dir",
            str(tmp_path),
            "--limit",
            "3",
        ]
    )
    assert rc == 0
    out = capsys.readouterr().out
    assert "Sentiment summary:" in out
    totals = json.loads((tmp_path / "sentiment_totals.json").read_text())
    assert sum(totals.values()) == 3


def test_split_command(fixture_csv, tmp_path, capsys):
    rc = main(
        ["split", str(fixture_csv), "--output-dir", str(tmp_path / "cols")]
    )
    assert rc == 0
    assert (tmp_path / "cols" / "artist.csv").exists()


def test_wordcount_per_song_command(fixture_csv, tmp_path):
    rc = main(
        [
            "wordcount-per-song",
            str(fixture_csv),
            "--output-dir",
            str(tmp_path),
            "--workers",
            "2",
        ]
    )
    assert rc == 0
    assert (tmp_path / "word_counts_global.csv").exists()
    assert (tmp_path / "word_counts_by_song.csv").exists()


def test_sweep_command(fixture_csv, tmp_path, capsys):
    rc = main([
        "sweep", str(fixture_csv), "--devices", "1,2",
        "--output-dir", str(tmp_path),
    ])
    assert rc == 0
    out = capsys.readouterr().out
    assert "np=1" in out and "np=2" in out
    assert (tmp_path / "sweep_summary.json").exists()
    assert (tmp_path / "performance_metrics_np1.json").exists()
    assert (tmp_path / "performance_metrics_np2.json").exists()


def test_analyze_trace_dir_writes_profile(fixture_csv, tmp_path, capsys):
    rc = main([
        "analyze", str(fixture_csv), "--output-dir", str(tmp_path / "out"),
        "--no-split", "--trace-dir", str(tmp_path / "trace"),
    ])
    assert rc == 0
    capsys.readouterr()
    trace_files = list((tmp_path / "trace").rglob("*"))
    assert any(f.is_file() for f in trace_files), trace_files


def test_sentiment_devices_flag_builds_mesh_backend(fixture_csv, tmp_path):
    rc = main([
        "sentiment", str(fixture_csv), "--model", "distilbert-tiny",
        "--devices", "4", "--output-dir", str(tmp_path),
    ])
    assert rc == 0
    assert (tmp_path / "sentiment_totals.json").exists()
    details = (tmp_path / "sentiment_details.csv").read_text()
    assert details.count("\n") == 9  # header + 8 DictReader rows


def test_sentiment_length_buckets_auto(fixture_csv, tmp_path):
    rc = main([
        "sentiment", str(fixture_csv), "--model", "distilbert-tiny",
        "--length-buckets", "auto", "--output-dir", str(tmp_path),
    ])
    assert rc == 0
    assert (tmp_path / "sentiment_totals.json").exists()


def test_sentiment_length_buckets_usage_errors(fixture_csv, tmp_path, capsys):
    import pytest

    # Buckets with a non-encoder family fail at parse time, not mid-run.
    for argv in (
        ["sentiment", str(fixture_csv), "--mock", "--length-buckets", "32"],
        ["sentiment", str(fixture_csv), "--model", "llama3",
         "--length-buckets", "auto"],
        ["sentiment", str(fixture_csv), "--model", "distilbert-tiny",
         "--length-buckets", "0,32"],
        ["sweep", str(fixture_csv), "--devices", "-2"],
    ):
        with pytest.raises(SystemExit) as exc:
            main(argv)
        assert exc.value.code == 2
        assert "error" in capsys.readouterr().err
