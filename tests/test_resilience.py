"""Resilience layer: fault injection, retry policy, failover, atomicity.

The chaos contracts (ISSUE 9): every injected transient fault either
recovers with byte-identical artifacts (retries visible in telemetry)
or — for non-transient injection — fails with a structured taxonomy
error and no torn files.  Degrade paths honor the golden contracts
(``word_counts.csv`` byte-stable) too.
"""

import json
import os

import pytest

from music_analyst_tpu.resilience import (
    InjectedFatal,
    InjectedFault,
    RetryPolicy,
    arm_retry_deadline,
    classify_retryable,
    configure_faults,
    fault_point,
    fault_stats,
    parse_fault_spec,
    reset_retry_stats,
    resolve_fault_spec,
    resolve_http_retries,
    retry_stats,
    run_with_failover,
    should_failover,
)
from music_analyst_tpu.resilience.faults import FaultRule

FIXTURE = os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "fixtures", "mini_songs.csv"
)


@pytest.fixture(autouse=True)
def _pristine_resilience():
    """Every test starts and ends with no injector, stats, or deadline."""
    configure_faults(None)
    reset_retry_stats()
    arm_retry_deadline(None)
    yield
    configure_faults(None)
    reset_retry_stats()
    arm_retry_deadline(None)


def _zero_sleep_policy(**kwargs):
    return RetryPolicy(sleep=lambda s: None, **kwargs)


# ------------------------------------------------------------ spec parsing


def test_parse_full_grammar():
    rules = parse_fault_spec(
        "ollama.request:error@2;h2d.transfer:delay=5s@0.1%seed=7;"
        "ingest.read:fatal;prefetch.stage:error@3+"
    )
    by_site = {r.site: r for r in rules}
    assert by_site["ollama.request"].mode == "error"
    assert by_site["ollama.request"].nth == 2
    assert not by_site["ollama.request"].from_nth
    assert by_site["h2d.transfer"].mode == "delay"
    assert by_site["h2d.transfer"].delay_s == 5.0
    assert by_site["h2d.transfer"].probability == pytest.approx(0.001)
    assert by_site["h2d.transfer"].seed == 7
    assert by_site["ingest.read"].mode == "fatal"
    assert by_site["ingest.read"].nth is None
    assert by_site["prefetch.stage"].nth == 3
    assert by_site["prefetch.stage"].from_nth


@pytest.mark.parametrize("bad", [
    "nonsense.site:error",          # unknown site
    "ingest.read",                  # no mode
    "ingest.read:explode",          # unknown mode
    "ingest.read:error@zero",       # non-numeric trigger
    "ingest.read:error@0",          # calls are 1-based
    "ingest.read:delay=oops",       # bad delay
    "ingest.read:delay=9999s",      # above the sleep cap
    "ingest.read:error@150%",       # probability out of range
    "ingest.read:error@1seed=x",    # bad seed
    "; ;",                          # no rules at all
])
def test_parse_rejects_garbage(bad):
    with pytest.raises(ValueError):
        parse_fault_spec(bad)


def test_resolve_spec_explicit_beats_env(monkeypatch):
    monkeypatch.setenv("MUSICAAL_FAULTS", "ingest.read:error")
    assert resolve_fault_spec("ollama.request:error") == "ollama.request:error"
    assert resolve_fault_spec(None) == "ingest.read:error"
    monkeypatch.delenv("MUSICAAL_FAULTS")
    assert resolve_fault_spec(None) is None


def test_bad_env_spec_raises_loudly(monkeypatch):
    """Unlike the watchdog env knob, a garbage MUSICAAL_FAULTS raises —
    a chaos run silently testing nothing would be worse than crashing."""
    monkeypatch.setenv("MUSICAAL_FAULTS", "not-a-site:error")
    with pytest.raises(ValueError, match="unknown site"):
        configure_faults(resolve_fault_spec(None))


# ------------------------------------------------------- seeded determinism


def test_probabilistic_schedule_is_seed_deterministic():
    def schedule(seed):
        rule = FaultRule(site="ingest.read", mode="error",
                        probability=0.3, seed=seed)
        return [rule.should_trip(i) for i in range(1, 201)]

    assert schedule(7) == schedule(7)
    assert any(schedule(7))  # 0.3 over 200 draws trips w.p. ~1
    assert schedule(7) != schedule(8)


def test_injected_run_schedule_replays():
    """Same spec, fresh injector → identical trip schedule at the seam."""
    def trips(spec):
        configure_faults(spec)
        out = []
        for _ in range(50):
            try:
                fault_point("ingest.read")
                out.append(False)
            except InjectedFault:
                out.append(True)
        return out

    spec = "ingest.read:error@25%seed=3"
    first = trips(spec)
    assert first == trips(spec)
    assert any(first) and not all(first)


def test_nth_and_from_nth_triggers():
    configure_faults("ingest.read:error@2")
    fault_point("ingest.read")  # call 1: clean
    with pytest.raises(InjectedFault, match=r"call 2"):
        fault_point("ingest.read")
    fault_point("ingest.read")  # call 3: clean again
    assert fault_stats()["ingest.read"] == {
        "rules": [{"site": "ingest.read", "mode": "error", "nth": 2}],
        "calls": 3,
        "trips": 1,
    }

    configure_faults("ingest.read:error@2+")
    fault_point("ingest.read")
    for _ in range(3):  # every call from the 2nd on
        with pytest.raises(InjectedFault):
            fault_point("ingest.read")


def test_fatal_is_not_retryable():
    configure_faults("ingest.read:fatal")
    with pytest.raises(InjectedFatal) as exc_info:
        fault_point("ingest.read")
    retryable, kind = classify_retryable(exc_info.value)
    assert (retryable, kind) == (False, "fault_injected")
    # ...while a plain error is.
    assert classify_retryable(InjectedFault("ingest.read", 1)) == (
        True, "fault_injected"
    )


def test_fault_kind_matches_report_taxonomy():
    from music_analyst_tpu.observability.report import classify_error

    assert classify_error(str(InjectedFault("h2d.transfer", 3))) == (
        "fault_injected"
    )


# ------------------------------------------------------------- retry policy


def test_retry_recovers_and_counts():
    calls = {"n": 0}

    def flaky():
        calls["n"] += 1
        if calls["n"] < 3:
            raise InjectedFault("ingest.read", calls["n"])
        return "ok"

    policy = _zero_sleep_policy(retries=2)
    assert policy.call(flaky, site="unit.flaky") == "ok"
    stats = retry_stats()["unit.flaky"]
    assert stats == {"attempts": 3, "retries": 2,
                     "recoveries": 1, "gave_up": 0}


def test_non_retryable_raises_on_first_attempt():
    calls = {"n": 0}

    def broken():
        calls["n"] += 1
        raise ValueError("logic error")

    with pytest.raises(ValueError):
        _zero_sleep_policy(retries=5).call(broken, site="unit.broken")
    assert calls["n"] == 1
    assert "gave_up" not in {
        k: v for k, v in retry_stats()["unit.broken"].items() if v
    }


def test_exhausted_retries_reraise_last_error():
    def always_down():
        raise ConnectionError("refused")

    with pytest.raises(ConnectionError):
        _zero_sleep_policy(retries=2).call(always_down, site="unit.down")
    stats = retry_stats()["unit.down"]
    assert stats["attempts"] == 3 and stats["gave_up"] == 1


def test_deadline_forbids_sleeping_past_budget():
    """With no budget left the policy re-raises NOW instead of sleeping —
    the structured error line must beat the bench deadline."""
    arm_retry_deadline(0.0)
    calls = {"n": 0}

    def flaky():
        calls["n"] += 1
        raise InjectedFault("ingest.read", calls["n"])

    policy = RetryPolicy(retries=5, base_s=0.5, cap_s=2.0)
    with pytest.raises(InjectedFault):
        policy.call(flaky, site="unit.deadline")
    assert calls["n"] == 1  # never slept, never re-attempted
    assert retry_stats()["unit.deadline"]["gave_up"] == 1


def test_backoff_respects_cap():
    policy = RetryPolicy(base_s=10.0, cap_s=0.5)
    assert all(policy.backoff_s(attempt) <= 0.5 for attempt in range(1, 8))


def test_resolve_http_retries_validation(monkeypatch):
    assert resolve_http_retries(None, default=2) == 2
    assert resolve_http_retries("5") == 5
    monkeypatch.setenv("MUSICAAL_HTTP_RETRIES", "3")
    assert resolve_http_retries(None) == 3
    monkeypatch.setenv("MUSICAAL_HTTP_RETRIES", "lots")
    with pytest.raises(ValueError, match="MUSICAAL_HTTP_RETRIES"):
        resolve_http_retries(None)
    with pytest.raises(ValueError, match="-1"):
        resolve_http_retries(-1)


# ----------------------------------------------------------------- failover


def test_failover_reinit_then_recover():
    state = {"healthy": False, "reinits": 0}

    def compute():
        if not state["healthy"]:
            raise InjectedFault("collective.psum", 1)
        return 42

    def reinit():
        state["reinits"] += 1
        state["healthy"] = True

    result, degraded = run_with_failover(
        compute, site="unit.failover", reinit=reinit
    )
    assert (result, degraded) == (42, False)
    assert state["reinits"] == 1


def test_failover_degrades_after_second_failure():
    def compute():
        raise RuntimeError("tunnel dead: lease lost")

    result, degraded = run_with_failover(
        compute, site="unit.degrade", degrade=lambda: "host-path"
    )
    assert (result, degraded) == ("host-path", True)


def test_failover_ignores_logic_errors():
    def compute():
        raise KeyError("missing column")

    with pytest.raises(KeyError):
        run_with_failover(compute, site="unit.logic",
                          degrade=lambda: "never")
    assert not should_failover(KeyError("x"))
    assert should_failover(InjectedFault("collective.psum", 1))


# -------------------------------------------------------- prefetch seam


def test_prefetch_stage_retry_then_succeed():
    from music_analyst_tpu.runtime.prefetch import PrefetchPipeline, Stage

    configure_faults("prefetch.stage:error@2")
    pipe = PrefetchPipeline(
        [Stage("double", lambda x: x * 2)], depth=2, name="unit_pipe"
    )
    assert list(pipe.run(range(5))) == [0, 2, 4, 6, 8]
    assert fault_stats()["prefetch.stage"]["trips"] == 1
    assert retry_stats()["prefetch.stage"]["recoveries"] == 1


# ------------------------------------------------- engine-level chaos runs


def _word_counts_bytes(out_dir):
    with open(os.path.join(out_dir, "word_counts.csv"), "rb") as fh:
        return fh.read()


def test_wordcount_transient_ingest_fault_byte_identical(tmp_path):
    from music_analyst_tpu.engines.wordcount import run_analysis

    clean = tmp_path / "clean"
    faulted = tmp_path / "faulted"
    run_analysis(FIXTURE, output_dir=str(clean), write_split=False,
                 quiet=True, use_corpus_cache=False)
    configure_faults("ingest.read:error@1")
    run_analysis(FIXTURE, output_dir=str(faulted), write_split=False,
                 quiet=True, use_corpus_cache=False)
    assert _word_counts_bytes(clean) == _word_counts_bytes(faulted)
    assert retry_stats()["ingest.read"]["recoveries"] == 1
    manifest = json.loads((faulted / "run_manifest.json").read_text())
    assert manifest["resilience"]["faults"]["ingest.read"]["trips"] == 1
    assert manifest["counters"]["retry.ingest.read.recovered"] == 1


def test_wordcount_persistent_fault_degrades_byte_identical(tmp_path):
    """Persistent device-path failure → one failover retry, then the CPU
    degrade path — stamped in the manifest, bytes unchanged."""
    from music_analyst_tpu.engines.wordcount import run_analysis

    clean = tmp_path / "clean"
    degraded = tmp_path / "degraded"
    run_analysis(FIXTURE, output_dir=str(clean), write_split=False,
                 quiet=True, use_corpus_cache=False)
    configure_faults("collective.psum:error")
    run_analysis(FIXTURE, output_dir=str(degraded), write_split=False,
                 quiet=True, use_corpus_cache=False)
    assert _word_counts_bytes(clean) == _word_counts_bytes(degraded)
    manifest = json.loads((degraded / "run_manifest.json").read_text())
    assert manifest["degraded"] is True
    assert manifest["degraded_site"] == "wordcount.device_compute"
    assert manifest["degraded_reason"] == "fault_injected"
    counters = manifest["counters"]
    assert counters["failover.wordcount.device_compute.retries"] == 1
    assert counters["failover.wordcount.device_compute.degraded"] == 1


def test_fatal_injection_dies_structurally_no_torn_files(tmp_path):
    from music_analyst_tpu.engines.wordcount import run_analysis
    from music_analyst_tpu.observability.report import classify_error

    out = tmp_path / "fatal"
    configure_faults("ingest.read:fatal")
    with pytest.raises(InjectedFatal) as exc_info:
        run_analysis(FIXTURE, output_dir=str(out), write_split=False,
                     quiet=True, use_corpus_cache=False)
    assert classify_error(str(exc_info.value)) == "fault_injected"
    # No torn artifacts: the atomic writers never leave partial CSVs or
    # stray tmp files behind a failed run.
    leftovers = [
        name for name in os.listdir(out)
        if name.endswith(".csv") or ".tmp-" in name
    ] if out.exists() else []
    assert leftovers == []


def test_sentiment_mock_h2d_fault_byte_identical(tmp_path):
    from music_analyst_tpu.engines.sentiment import run_sentiment

    clean = tmp_path / "clean"
    faulted = tmp_path / "faulted"
    run_sentiment(FIXTURE, mock=True, output_dir=str(clean), quiet=True)
    configure_faults("h2d.transfer:error@1")
    run_sentiment(FIXTURE, mock=True, output_dir=str(faulted), quiet=True)
    for name in ("sentiment_details.csv", "sentiment_totals.json"):
        assert (clean / name).read_bytes() == (faulted / name).read_bytes()
    assert retry_stats()["prefetch.stage"]["recoveries"] >= 1


# ----------------------------------------------------------- serving seam


def test_serving_dispatch_retry_answers_everyone():
    from music_analyst_tpu.serving.batcher import DynamicBatcher

    configure_faults("serving.dispatch:error@1")
    ops = {"echo": lambda texts: [{"label": t} for t in texts]}
    batcher = DynamicBatcher(ops, max_batch=4, max_wait_ms=1.0,
                             max_queue=64).start()
    reqs = [batcher.submit(i, "echo", f"row {i}") for i in range(16)]
    for req in reqs:
        assert req.wait(timeout=30.0)
        assert req.response["ok"], req.response
    batcher.drain()
    assert retry_stats()["serving.dispatch"]["recoveries"] == 1


def test_residency_reload_swaps_poisoned_backend_mid_session():
    """Reload-on-poisoned-device: a backend that dies with a classified
    tunnel error is replaced under the live batcher; the request that hit
    it still gets an answer from the fresh backend."""
    from music_analyst_tpu.serving.batcher import DynamicBatcher
    from music_analyst_tpu.serving.residency import ModelResidency
    from music_analyst_tpu.serving.server import build_resident_ops

    class PoisonedBackend:
        name = "poisoned"

        def classify_batch(self, texts):
            raise ConnectionError("tunnel dead: device lease lost")

    residency = ModelResidency(model="mock", mock=True,
                               backend=PoisonedBackend())
    batcher = DynamicBatcher(
        build_resident_ops(residency),
        max_batch=4, max_wait_ms=1.0, max_queue=16,
        failover=lambda exc: residency.reload() is not None,
    ).start()
    req = batcher.submit("r1", "sentiment", "I love this happy day")
    assert req.wait(timeout=30.0)
    batcher.drain()
    assert req.response["ok"], req.response
    assert residency.snapshot()["reloads"] == 1
    assert batcher.stats()["failover_reloads"] == 1


# -------------------------------------------------------- flight recording


def test_flight_record_contains_injected_fault_events(tmp_path):
    from music_analyst_tpu.observability.flight import FlightRecorder

    rec = FlightRecorder()
    rec.install(signals=False, excepthook=False)
    try:
        configure_faults("ingest.read:error@1")
        with pytest.raises(InjectedFault):
            fault_point("ingest.read", path="unit.csv")
        path = rec.dump("unit-test", taxonomy="fault_injected",
                        directory=str(tmp_path))
    finally:
        rec.uninstall()
    record = json.loads(open(path, encoding="utf-8").read())
    faults = [e for e in record["events"]
              if e.get("name") == "fault_injected"]
    assert faults, "flight record lost the injected-fault event"
    assert faults[0]["attrs"]["site"] == "ingest.read"
    assert faults[0]["attrs"]["path"] == "unit.csv"


# -------------------------------------------------------- atomic artifacts


def test_atomic_write_replaces_only_on_success(tmp_path):
    from music_analyst_tpu.utils.atomic import atomic_write

    target = tmp_path / "out.csv"
    target.write_text("original")
    with pytest.raises(RuntimeError):
        with atomic_write(str(target)) as fh:
            fh.write("half a row")
            raise RuntimeError("crash mid-write")
    assert target.read_text() == "original"  # untouched
    assert [n for n in os.listdir(tmp_path) if ".tmp-" in n] == []
    with atomic_write(str(target)) as fh:
        fh.write("replaced")
    assert target.read_text() == "replaced"


def test_wq_cache_publish_retries_transient_rename(tmp_path):
    from music_analyst_tpu.engines.wq_cache import WqCacheWriter
    import numpy as np

    configure_faults("corpus_cache.publish:error@1")
    writer = WqCacheWriter(str(tmp_path), "entry")
    writer.add("layer/kernel", np.ones((2, 2), dtype=np.float32))
    assert writer.publish() is True  # retry absorbed the injected rename
    assert (tmp_path / "entry").is_dir()
    assert retry_stats()["corpus_cache.publish"]["recoveries"] == 1
