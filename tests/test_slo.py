"""Overload-robust serving: SLO-aware preemption, tenant isolation.

Contract families (ISSUE 13):

* **primitives** — per-tenant token buckets meter sustained admission
  with bounded burst; the fair queue serves strict priority classes
  with per-tenant WFQ inside each class (a flooding tenant cannot
  starve a light one); priority-aware eviction picks victims from
  lower classes / over-represented tenants, never equals.
* **shed taxonomy** — deadline-doomed admits shed ``slo_unattainable``
  (with the estimate that doomed them), capacity sheds are
  ``queue_full``; both carry ``retry_after_ms``; an over-budget tenant
  sheds at its OWN bucket while other tenants keep admitting.
* **preemption** — a waiting high-priority admit that would miss its
  TTFT target slot-steals from the longest-running low-priority
  decode; the preempted-then-resumed request's greedy tokens are
  byte-identical to an undisturbed run, with zero retraces, on BOTH
  the paged and the monolithic slot backends, under shuffled arrival.
* **supervision** — the router respawns a dead worker process (capped
  backoff, ``respawned`` health transition) and the telemetry report
  surfaces the respawn counts.

The wire-level parse contract (``tenant``/``priority``/``deadline_ms``
on the ndjson request) is pinned here too; trace-driven overload runs
live in the ``slo`` bench suite (benchmarks/slo.py).
"""

import json
import os
import random
import signal
import time

import pytest

from music_analyst_tpu.serving.batcher import (
    DynamicBatcher,
    ServeRequest,
    resolve_priority,
    resolve_tenant_budget,
    resolve_tpot_slo_ms,
    resolve_ttft_slo_ms,
)
from music_analyst_tpu.serving.slo import FairQueue, TokenBucket


# ------------------------------------------------------------ resolvers


def test_resolve_slo_knobs(monkeypatch):
    assert resolve_ttft_slo_ms(None) == 0.0  # disabled by default
    assert resolve_tpot_slo_ms(None) == 0.0
    assert resolve_tenant_budget(None) == 0.0
    assert resolve_priority(None) == 1
    monkeypatch.setenv("MUSICAAL_SERVE_SLO_TTFT_MS", "250")
    monkeypatch.setenv("MUSICAAL_SERVE_SLO_TPOT_MS", "40.5")
    monkeypatch.setenv("MUSICAAL_SERVE_TENANT_BUDGET", "2.5")
    monkeypatch.setenv("MUSICAAL_SERVE_PRIORITY", "3")
    assert resolve_ttft_slo_ms(None) == 250.0
    assert resolve_tpot_slo_ms(None) == 40.5
    assert resolve_tenant_budget(None) == 2.5
    assert resolve_priority(None) == 3
    monkeypatch.setenv("MUSICAAL_SERVE_SLO_TTFT_MS", "junk")
    assert resolve_ttft_slo_ms(None) == 0.0  # malformed env falls back
    with pytest.raises(ValueError):
        resolve_ttft_slo_ms("junk")  # explicit value is a usage error


# ------------------------------------------------------------ primitives


def test_token_bucket_burst_then_meter():
    bucket = TokenBucket(1.0)  # burst = max(2*rate, 1) = 2
    assert bucket.take() and bucket.take()
    assert not bucket.take()  # burst spent; refill is 1 token/s
    assert bucket.retry_after_ms() >= 1.0
    # rate <= 0 disables metering entirely.
    free = TokenBucket(0.0)
    assert all(free.take() for _ in range(100))
    assert free.retry_after_ms() == 0.0


def _req(rid, tenant="default", priority=1):
    return ServeRequest(rid, "sentiment", f"text {rid}",
                        tenant=tenant, priority=priority)


def test_fair_queue_strict_priority_then_wfq():
    q = FairQueue()
    for i in range(6):
        q.append(_req(f"bulk-{i}", tenant="bulk"))
    for i in range(2):
        q.append(_req(f"light-{i}", tenant="light"))
    q.append(_req("gold", tenant="gold", priority=5))
    assert len(q) == 9
    # Strict classes: the lone priority-5 request dispatches first even
    # though eight priority-1 requests queued ahead of it.
    assert q.popleft().id == "gold"
    # WFQ within the class: the light tenant's two requests interleave
    # with the flood instead of waiting behind all six bulk requests.
    order = [q.popleft().tenant for _ in range(8)]
    assert order.index("light") <= 1
    assert [t for t in order if t == "light"] == ["light", "light"]
    assert order[:4].count("light") == 2  # both served in the first half
    assert q.popleft() is None


def test_fair_queue_requeue_goes_to_head():
    q = FairQueue()
    first, second = _req("a", tenant="t"), _req("b", tenant="t")
    q.append(first)
    q.append(second)
    popped = q.popleft()
    assert popped is first
    q.requeue(popped)  # preempted: already paid its wait
    assert q.peek() is first
    assert len(q) == 2


def test_fair_queue_shed_candidate_rules():
    q = FairQueue()
    for i in range(3):
        q.append(_req(f"bulk-{i}", tenant="bulk", priority=1))
    # A higher-priority newcomer evicts from the class strictly below.
    victim = q.shed_candidate("gold", 5)
    assert victim is not None and victim.priority == 1
    # Same class: only a tenant holding strictly more than newcomer+1
    # queued requests is over-represented enough to evict from.
    victim = q.shed_candidate("gold", 1)
    assert victim is not None and victim.tenant == "bulk"
    # Equal standing sheds the newcomer (None): no eviction loops.
    q2 = FairQueue()
    q2.append(_req("a", tenant="t1"))
    q2.append(_req("b", tenant="t2"))
    assert q2.shed_candidate("t1", 1) is None


# ------------------------------------------------- batcher admission ladder


def _ops():
    return {"sentiment": lambda texts: [{"label": "Positive"}
                                        for _ in texts]}


def test_batcher_tenant_budget_isolates_tenants():
    """Starvation freedom: a tenant bursting past its budget sheds at
    its OWN bucket while another tenant's requests all admit and settle."""
    batcher = DynamicBatcher(
        _ops(), max_batch=4, max_wait_ms=1.0, max_queue=64,
        tenant_budget=1.0,  # burst 2
    ).start()
    try:
        bulk = [batcher.submit(f"b{i}", "sentiment", "x", tenant="bulk")
                for i in range(10)]
        gold = [batcher.submit(f"g{i}", "sentiment", "y", tenant="gold")
                for i in range(2)]
        sheds = [r for r in bulk if r.done and not r.response["ok"]]
        assert len(sheds) == 8, "burst of 2 admits, the rest shed"
        for shed in sheds:
            error = shed.response["error"]
            assert error["kind"] == "queue_full"
            assert error["retry_after_ms"] >= 1.0
            assert "budget" in error["detail"]
        for req in gold:
            assert req.wait(30.0) and req.response["ok"]
    finally:
        batcher.drain()
    snapshot = batcher.slo_snapshot()
    assert snapshot["sheds"]["shed_tenant_budget"] == 8
    assert snapshot["tenants"]["bulk"]["shed"] == 8
    assert snapshot["tenants"]["gold"]["shed"] == 0


def test_batcher_slo_unattainable_vs_queue_full_boundary():
    """The shed ladder's selection rules: a deadline the drain estimate
    already blows sheds ``slo_unattainable`` (with the estimate) while
    the queue still has room; pure capacity sheds are ``queue_full``;
    a higher-priority newcomer evicts queued low-priority work instead
    of shedding itself."""
    batcher = DynamicBatcher(
        _ops(), max_batch=4, max_wait_ms=1.0, max_queue=4
    )  # NOT started: the queue holds still so the boundary is exact
    # Pin the flush-rate EWMA (normally learned from completed batches)
    # so the drain estimate is deterministic: 100 rows/s.
    batcher._flush_rate = 100.0
    for i in range(3):
        assert not batcher.submit(f"fill-{i}", "sentiment", "x").done
    # 3 ahead / 100 rows/s + 1 ms flush deadline = 31 ms > 5 ms.
    doomed = batcher.submit("doomed", "sentiment", "x", deadline_ms=5.0)
    assert doomed.done
    error = doomed.response["error"]
    assert error["kind"] == "slo_unattainable"
    assert error["retry_after_ms"] >= 1.0
    assert error["estimate_ms"] > 5.0
    # The same estimate under a loose deadline admits fine.
    assert not batcher.submit(
        "fits", "sentiment", "x", deadline_ms=10_000.0
    ).done
    # Queue now full (4/4): an equal-standing newcomer sheds queue_full.
    bounced = batcher.submit("bounced", "sentiment", "x")
    assert bounced.response["error"]["kind"] == "queue_full"
    assert bounced.response["error"]["retry_after_ms"] >= 1.0
    # A priority-5 newcomer evicts a queued priority-1 request instead.
    vip = batcher.submit("vip", "sentiment", "x", priority=5,
                         deadline_ms=10_000.0)
    assert not vip.done
    evicted = [r for r in batcher.stats().items()
               if r[0] == "shed_evicted"]
    assert evicted == [("shed_evicted", 1)]
    stats = batcher.stats()
    assert stats["shed_slo_unattainable"] == 1
    assert stats["shed_queue_full"] == 1


def test_batcher_ttft_slo_is_default_deadline():
    batcher = DynamicBatcher(
        _ops(), max_batch=4, max_wait_ms=1.0, max_queue=64,
        ttft_slo_ms=5.0,
    )  # NOT started
    batcher._flush_rate = 100.0
    for i in range(3):
        batcher.submit(f"fill-{i}", "sentiment", "x",
                       deadline_ms=10_000.0)
    # No explicit deadline: the configured TTFT SLO arms the check.
    shed = batcher.submit("implicit", "sentiment", "x")
    assert shed.done
    assert shed.response["error"]["kind"] == "slo_unattainable"


# ------------------------------------------------------ decode scheduler


@pytest.fixture(scope="module")
def clf():
    from music_analyst_tpu.models.llama import (
        LlamaConfig,
        LlamaZeroShotClassifier,
    )

    return LlamaZeroShotClassifier(
        config=LlamaConfig.tiny(), max_prompt_len=64
    )


LOW_PROMPTS = [
    "slow burning ballad of the low priority tenant",
    "rain on the window all night long",
    "la la la the radio plays",
    "golden sunshine on the river",
]
HIGH_PROMPT = "gold tenant chorus arriving mid decode"


def _scheduler(clf, **kwargs):
    from music_analyst_tpu.serving.decode_loop import ContinuousScheduler

    kwargs.setdefault("prefill_chunk", 16)
    kwargs.setdefault("prompt_region", 64)
    kwargs.setdefault("max_new_tokens", 8)
    kwargs.setdefault("max_queue", 16)
    return ContinuousScheduler(clf, **kwargs)


@pytest.mark.parametrize("page_size", [None, 0], ids=["paged", "slots"])
def test_preempt_resume_byte_identity(clf, page_size):
    """A gold admit missing its TTFT target steals a slot mid-decode;
    the victim resumes and every answer — victim included — is
    byte-identical to the undisturbed static scan, with zero retraces.
    Runs on both KV backends (prefix-hit resume vs full re-prefill)."""
    static = clf.generate_batch(LOW_PROMPTS + [HIGH_PROMPT],
                                max_new_tokens=8)
    kwargs = dict(n_slots=2, ttft_slo_ms=1.0)
    if page_size is not None:
        kwargs["page_size"] = page_size
    sched = _scheduler(clf, **kwargs)
    sched.warmup()
    variants_before = sched.runtime.compiled_variants()
    order = list(range(len(LOW_PROMPTS)))
    random.Random(page_size or 7).shuffle(order)
    # Generous explicit deadlines: the 1 ms TTFT target exists to arm
    # preemption, not to shed this test's own requests.
    low = {
        i: sched.submit(i, LOW_PROMPTS[i], priority=1,
                        deadline_ms=60_000.0)
        for i in order
    }
    # Let a low request reach mid-decode (preemption only considers
    # actively decoding victims) before the gold arrival shows up.
    for _ in range(64):
        sched._tick()
        if any(s is not None and s.active and s.steps > 0
               for s in sched._slots):
            break
    high = sched.submit("gold", HIGH_PROMPT, priority=5,
                        deadline_ms=60_000.0)
    sched.run_until_idle()
    for i, want in enumerate(static[:-1]):
        resp = low[i].response
        assert resp["ok"], resp
        assert resp["text"] == want, f"prompt {i} diverged after preempt"
    assert high.response["ok"] and high.response["text"] == static[-1]
    stats = sched.stats()
    assert stats["preemptions"] >= 1
    assert stats["resumed"] >= 1
    assert sum(r.meta.get("preempted", 0) for r in low.values()) >= 1
    assert sched.runtime.compiled_variants() == variants_before
    snapshot = sched.slo_snapshot()
    assert snapshot["preemptions"] == stats["preemptions"]


def test_preempt_fault_degrades_to_no_steal(clf):
    """An injected ``scheduler.preempt`` fault means no steal this tick
    — never a half-released slot; the workload still settles and the
    output stays byte-identical."""
    from music_analyst_tpu.resilience import configure_faults

    static = clf.generate_batch([LOW_PROMPTS[0], HIGH_PROMPT],
                                max_new_tokens=8)
    sched = _scheduler(clf, n_slots=1, ttft_slo_ms=1.0)
    configure_faults("scheduler.preempt:error@1+")
    try:
        low = sched.submit("low", LOW_PROMPTS[0], priority=1,
                           deadline_ms=60_000.0)
        for _ in range(32):
            sched._tick()
            slot = sched._slots[0]
            if slot is not None and slot.active and slot.steps > 0:
                break
        high = sched.submit("gold", HIGH_PROMPT, priority=5,
                            deadline_ms=60_000.0)
        sched.run_until_idle()
    finally:
        configure_faults(None)
    assert low.response["ok"] and low.response["text"] == static[0]
    assert high.response["ok"] and high.response["text"] == static[1]
    stats = sched.stats()
    assert stats["preemptions"] == 0
    assert stats["preempt_faults"] >= 1


def test_decode_tenant_budget_and_deadline_sheds(clf):
    sched = _scheduler(clf, n_slots=2, tenant_budget=1.0)
    bulk = [sched.submit(f"b{i}", "x", max_new_tokens=1, tenant="bulk")
            for i in range(3)]
    shed = bulk[2]
    assert shed.done
    assert shed.response["error"]["kind"] == "queue_full"
    assert shed.response["error"]["retry_after_ms"] >= 1.0
    gold = sched.submit("g0", "y", max_new_tokens=1, tenant="gold")
    assert not gold.done  # its own bucket, untouched by bulk's burst
    sched.run_until_idle()
    assert all(r.response["ok"] for r in bulk[:2] + [gold])
    # With a settle rate and TTFT EWMA observed, a microscopic deadline
    # sheds slo_unattainable instead of queueing to miss.
    doomed = sched.submit("late", "z", max_new_tokens=1,
                          tenant="gold", deadline_ms=0.001)
    assert doomed.done
    error = doomed.response["error"]
    assert error["kind"] == "slo_unattainable"
    assert error["retry_after_ms"] >= 1.0
    snapshot = sched.slo_snapshot()
    assert snapshot["sheds"]["shed_tenant_budget"] == 1
    assert snapshot["sheds"]["shed_slo_unattainable"] == 1
    assert snapshot["tenants"]["bulk"]["shed"] == 1
    assert snapshot["tenants"]["gold"]["shed"] == 1


# -------------------------------------------------------- wire protocol


def test_wire_slo_fields_validated_and_forwarded():
    import io

    from music_analyst_tpu.serving.server import SentimentServer

    batcher = DynamicBatcher(
        _ops(), max_batch=2, max_wait_ms=2.0, max_queue=16
    ).start()
    server = SentimentServer(batcher, None, mode="stdio", decode=None)
    lines = [
        {"id": "ok", "op": "sentiment", "text": "hi",
         "tenant": "gold", "priority": 5, "deadline_ms": 60000},
        {"id": "t", "op": "sentiment", "text": "hi", "tenant": 5},
        {"id": "p", "op": "sentiment", "text": "hi", "priority": "high"},
        {"id": "d", "op": "sentiment", "text": "hi", "deadline_ms": "soon"},
        {"id": "pb", "op": "sentiment", "text": "hi", "priority": True},
        {"id": "end", "op": "stats"},
    ]
    wfile = io.StringIO()
    rfile = io.StringIO("".join(json.dumps(l) + "\n" for l in lines))
    server.handle_stream(rfile, wfile, drain_on_eof=True)
    replies = {r["id"]: r for r in
               (json.loads(l) for l in wfile.getvalue().splitlines())}
    assert replies["ok"]["ok"]
    for rid in ("t", "p", "d", "pb"):
        assert replies[rid]["error"]["kind"] == "bad_request", rid
    # The gold tenant's admission shows in the stats slo section.
    slo = replies["end"]["stats"]["slo"]
    assert slo["tenants"]["gold"]["admitted"] == 1


# ------------------------------------------------------------ supervision


def test_router_respawn_heals_a_killed_worker(tmp_path):
    """SIGKILL the only worker: the poll loop respawns it (capped
    backoff, ``respawned`` transition) and the fleet serves again."""
    from music_analyst_tpu.serving.router import (
        ReplicaRouter,
        spawn_replicas,
    )

    handles = spawn_replicas(
        1, str(tmp_path), model="mock", mock=True, warmup=False
    )
    router = ReplicaRouter(
        handles, poll_interval_s=0.05, respawn_backoff_s=0.1
    ).start()
    try:
        first = router.submit("r1", "sentiment", "happy day")
        assert first.wait(30.0) and first.response["ok"]
        os.kill(handles[0].proc.pid, signal.SIGKILL)
        deadline = time.monotonic() + 60.0
        while (router.stats()["respawns"] < 1
               and time.monotonic() < deadline):
            time.sleep(0.05)
        stats = router.stats()
        assert stats["respawns"] >= 1, stats["health_transitions"]
        assert any(t["kind"] == "respawned" and t["to"] == "healthy"
                   for t in stats["health_transitions"])
        assert stats["replicas"]["replica-0"]["respawns"] >= 1
        second = router.submit("r2", "sentiment", "happy again")
        assert second.wait(30.0) and second.response["ok"], second.response
    finally:
        router.drain()


def test_router_shed_ladder_boundaries(tmp_path):
    """The router's admission mirrors the batcher ladder: per-tenant
    budget, deadline-aware ``slo_unattainable``, priority eviction."""
    from music_analyst_tpu.serving.router import (
        ReplicaHandle,
        ReplicaRouter,
    )

    handle = ReplicaHandle("replica-0", str(tmp_path / "never.sock"))
    router = ReplicaRouter(
        [handle], max_queue=4, tenant_budget=1.0
    )  # dispatch NOT started: the queue holds still
    bulk = [router.submit(f"b{i}", "sentiment", "x", tenant="bulk")
            for i in range(3)]
    assert bulk[2].done
    assert bulk[2].response["error"]["kind"] == "queue_full"
    assert bulk[2].response["error"]["retry_after_ms"] >= 1.0
    assert not router.submit("g0", "sentiment", "y", tenant="gold").done
    # Pin a tiny observed settle rate (1 settle, 100 s of history) so
    # the drain estimate is huge and deterministic.
    router._stats["completed"] = 1
    router._started_mono -= 100.0
    # Fresh tenants below: each earlier tenant's burst-2 bucket is
    # already part spent, and this test pins exactly ONE budget shed.
    doomed = router.submit("late", "sentiment", "z", tenant="late",
                           deadline_ms=50.0)
    assert doomed.done
    error = doomed.response["error"]
    assert error["kind"] == "slo_unattainable"
    assert error["retry_after_ms"] >= 1.0 and error["estimate_ms"] > 50.0
    # Fill to capacity, then a priority-5 admit evicts queued
    # priority-1 work instead of shedding itself.
    router.submit("g1", "sentiment", "y", tenant="fill")
    vip = router.submit("vip", "sentiment", "v", tenant="vip",
                        priority=5, deadline_ms=1e9)
    assert not vip.done
    stats = router.stats()
    assert stats["shed_tenant_budget"] == 1
    assert stats["shed_slo_unattainable"] == 1
    assert stats["shed_evicted"] == 1
    # bulk's ledger charges both its budget shed and the evicted victim.
    assert router.slo_snapshot()["tenants"]["bulk"]["shed"] == 2


def test_report_surfaces_respawn_counts(tmp_path):
    from music_analyst_tpu.observability.report import (
        build_report,
        load_run,
        render_report,
    )

    manifest = {
        "run": "serve", "ok": True, "wall_seconds": 1.0,
        "serving": {
            "router": {
                "replica_count": 1, "healthy_count": 1,
                "dispatched": 5, "requeued": 1, "shed": 0,
                "respawns": 2,
                "health_transitions": [
                    {"replica": "replica-0", "from": "dead",
                     "to": "healthy", "kind": "respawned",
                     "reason": "supervised restart", "t_s": 1.0},
                ],
                "replicas": {
                    "replica-0": {"dispatched": 5, "requeues": 1,
                                  "respawns": 2, "health": "healthy"},
                },
            },
        },
    }
    run_dir = tmp_path / "run"
    run_dir.mkdir()
    (run_dir / "run_manifest.json").write_text(json.dumps(manifest))
    report = build_report([load_run(str(run_dir))])
    (entry,) = report["router_fleet"]
    assert entry["respawned"] == 2
    assert entry["replicas"]["replica-0"]["respawns"] == 2
    text = "\n".join(render_report(report))
    assert "2 respawned" in text
