"""Label contract shared across classifier backends."""

from music_analyst_tpu.utils.labels import normalise_label, score_to_label


def test_first_token_title_cased():
    assert normalise_label("positive") == "Positive"
    assert normalise_label("NEGATIVE obviously") == "Negative"
    assert normalise_label("neutral.") == "Neutral"  # 'Neutral.' not in set


def test_unknown_maps_to_neutral():
    assert normalise_label("happy") == "Neutral"


def test_empty_output_fixed_to_neutral():
    # The reference crashes here (scripts/sentiment_classifier.py:105,
    # ''.split()[0] -> IndexError); we normalize to Neutral instead.
    assert normalise_label("") == "Neutral"
    assert normalise_label("   ") == "Neutral"


def test_score_sign():
    assert score_to_label(2) == "Positive"
    assert score_to_label(-1) == "Negative"
    assert score_to_label(0) == "Neutral"
