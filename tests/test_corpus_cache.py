"""Persistent corpus cache + chunked streaming histogram contracts.

Three golden properties, tested differentially:

1. A warm-cache ``run_analysis`` writes byte-identical ``word_counts.csv``
   / ``top_artists.csv`` to a cold run AND matches the serial oracle —
   the cache may accelerate ingest, never change output bytes.
2. A corrupt entry (truncated ``.npy``, stale schema) is detected,
   counted, deleted, and falls back to a fresh ingest — the cache can
   never fail a run.
3. The chunked streaming device path produces bit-identical histograms
   to the whole-corpus put at EVERY chunk size (including sizes that
   don't divide the song count).

Plus the two satellite fixes: the XLA-cache enable failure staying
retryable, and bench child timeouts clamping to the parent budget.
"""

import json
import os
from collections import Counter

import numpy as np
import pytest

from music_analyst_tpu.data import corpus_cache
from music_analyst_tpu.data.csv_io import iter_dataset_exact, sort_count_entries
from music_analyst_tpu.data.ingest import ingest_dataset
from music_analyst_tpu.data.tokenizer import tokenize_ascii


def _stats_delta(before, after):
    return {k: after[k] - before.get(k, 0) for k in after}


# ------------------------------------------------------------ cache core


def test_cold_store_then_warm_hit_roundtrip(fixture_csv, tmp_path):
    cache_dir = str(tmp_path / "cache")
    before = corpus_cache.cache_stats()
    cold = ingest_dataset(str(fixture_csv), backend="python",
                          cache_dir=cache_dir)
    warm = ingest_dataset(str(fixture_csv), backend="python",
                          cache_dir=cache_dir)
    delta = _stats_delta(before, corpus_cache.cache_stats())
    assert delta["stores"] == 1
    assert delta["hits"] == 1
    assert delta["corrupt"] == 0
    assert delta["bytes_saved"] == os.path.getsize(fixture_csv)

    assert warm.song_count == cold.song_count
    assert warm.token_count == cold.token_count
    np.testing.assert_array_equal(np.asarray(warm.word_ids),
                                  np.asarray(cold.word_ids))
    np.testing.assert_array_equal(np.asarray(warm.word_offsets),
                                  np.asarray(cold.word_offsets))
    np.testing.assert_array_equal(np.asarray(warm.artist_ids),
                                  np.asarray(cold.artist_ids))
    assert warm.word_vocab.tokens == cold.word_vocab.tokens
    assert warm.artist_vocab.tokens == cold.artist_vocab.tokens
    # Zero-copy contract: the warm arrays are memory-mapped, not copies.
    assert isinstance(warm.word_ids, np.memmap)


def test_capture_records_round_trips_through_cache(fixture_csv, tmp_path):
    cache_dir = str(tmp_path / "cache")
    cold = ingest_dataset(str(fixture_csv), backend="python",
                          capture_records=True, cache_dir=cache_dir)
    warm = ingest_dataset(str(fixture_csv), backend="python",
                          capture_records=True, cache_dir=cache_dir)
    assert warm.has_records
    assert bytes(warm.records_blob) == bytes(cold.records_blob)
    np.testing.assert_array_equal(np.asarray(warm.record_offsets),
                                  np.asarray(cold.record_offsets))
    # And the plain entry is distinct: a records-less request must not be
    # served the record-bearing entry or vice versa.
    key_plain = corpus_cache.corpus_key(str(fixture_csv), None, False,
                                        "python")
    key_rec = corpus_cache.corpus_key(str(fixture_csv), None, True, "python")
    assert key_plain != key_rec


def test_key_separates_backend_limit_and_content(fixture_csv, tmp_path):
    path = str(fixture_csv)
    base = corpus_cache.corpus_key(path, None, False, "python")
    assert corpus_cache.corpus_key(path, None, False, "native") != base
    assert corpus_cache.corpus_key(path, 5, False, "python") != base
    # Any byte change re-keys; a pure rename does not.
    copy = tmp_path / "renamed.csv"
    copy.write_bytes(fixture_csv.read_bytes())
    assert corpus_cache.corpus_key(str(copy), None, False, "python") == base
    copy.write_bytes(fixture_csv.read_bytes() + b"x")
    assert corpus_cache.corpus_key(str(copy), None, False, "python") != base


def test_resolve_cache_dir_precedence(monkeypatch, tmp_path):
    monkeypatch.delenv("MUSICAAL_CORPUS_CACHE", raising=False)
    assert corpus_cache.resolve_cache_dir(None, False) is None
    assert corpus_cache.resolve_cache_dir("/x", None) == "/x"
    monkeypatch.setenv("MUSICAAL_CORPUS_CACHE", "off")
    assert corpus_cache.resolve_cache_dir(None, None) is None
    assert corpus_cache.resolve_cache_dir("/x", None) == "/x"  # arg wins
    monkeypatch.setenv("MUSICAAL_CORPUS_CACHE", str(tmp_path))
    assert corpus_cache.resolve_cache_dir(None, None) == str(tmp_path)
    monkeypatch.delenv("MUSICAAL_CORPUS_CACHE", raising=False)
    assert corpus_cache.resolve_cache_dir(None, None) == os.path.expanduser(
        "~/.cache/musicaal_corpus"
    )


# ----------------------------------------------------- corruption handling


def _entry_dir(cache_dir, path):
    key = corpus_cache.corpus_key(path, None, False, "python")
    return os.path.join(cache_dir, key)


def test_truncated_npy_falls_back_to_fresh_ingest(fixture_csv, tmp_path):
    cache_dir = str(tmp_path / "cache")
    path = str(fixture_csv)
    cold = ingest_dataset(path, backend="python", cache_dir=cache_dir)
    entry = _entry_dir(cache_dir, path)
    ids_path = os.path.join(entry, "word_ids.npy")
    with open(ids_path, "r+b") as fh:
        fh.truncate(os.path.getsize(ids_path) // 2)

    before = corpus_cache.cache_stats()
    assert corpus_cache.load(cache_dir, path, None, False, "python") is None
    delta = _stats_delta(before, corpus_cache.cache_stats())
    assert delta["corrupt"] == 1
    assert delta["hits"] == 0
    assert not os.path.isdir(entry)  # corrupt entry evicted

    # The engine-level path re-ingests and re-stores transparently.
    fresh = ingest_dataset(path, backend="python", cache_dir=cache_dir)
    assert fresh.token_count == cold.token_count
    assert os.path.isdir(entry)


def test_stale_schema_falls_back(fixture_csv, tmp_path):
    cache_dir = str(tmp_path / "cache")
    path = str(fixture_csv)
    ingest_dataset(path, backend="python", cache_dir=cache_dir)
    entry = _entry_dir(cache_dir, path)
    meta_path = os.path.join(entry, "meta.json")
    with open(meta_path, encoding="utf-8") as fh:
        meta = json.load(fh)
    meta["schema"] = corpus_cache.SCHEMA_VERSION + 999
    with open(meta_path, "w", encoding="utf-8") as fh:
        json.dump(meta, fh)

    before = corpus_cache.cache_stats()
    assert corpus_cache.load(cache_dir, path, None, False, "python") is None
    delta = _stats_delta(before, corpus_cache.cache_stats())
    assert delta["corrupt"] == 1
    assert not os.path.isdir(entry)


def test_store_never_raises_on_unwritable_dir(fixture_csv, tmp_path):
    corpus = ingest_dataset(str(fixture_csv), backend="python")
    missing = str(tmp_path / "no" / "such" / "file.csv")
    # Bad source path (corpus_key can't stat it): returns False, no raise.
    assert corpus_cache.store(str(tmp_path), missing, None, False,
                              "python", corpus) is False


# ---------------------------------------------- differential: run_analysis


def _oracle_entries(data: bytes):
    words = Counter()
    artists = Counter()
    for artist_raw, text_raw in iter_dataset_exact(data):
        words.update(tokenize_ascii(text_raw))
        if artist_raw:
            artists[artist_raw.decode("utf-8", errors="replace")] += 1
    return sort_count_entries(words.items()), sort_count_entries(
        artists.items()
    )


def test_warm_run_analysis_byte_identical_to_cold_and_oracle(
    fixture_csv, tmp_path
):
    from music_analyst_tpu.engines.wordcount import run_analysis

    cache_dir = str(tmp_path / "cache")
    before = corpus_cache.cache_stats()
    cold_out = tmp_path / "cold"
    warm_out = tmp_path / "warm"
    run_analysis(str(fixture_csv), output_dir=str(cold_out),
                 corpus_cache_dir=cache_dir, write_split=False, quiet=True)
    result = run_analysis(str(fixture_csv), output_dir=str(warm_out),
                          corpus_cache_dir=cache_dir, write_split=False,
                          quiet=True)
    delta = _stats_delta(before, corpus_cache.cache_stats())
    assert delta["hits"] >= 1

    for name in ("word_counts.csv", "top_artists.csv"):
        assert (cold_out / name).read_bytes() == (warm_out / name).read_bytes()

    word_entries, artist_entries = _oracle_entries(fixture_csv.read_bytes())
    assert result.word_entries == word_entries
    assert result.artist_entries == artist_entries

    # The run manifest carries the cache stats (telemetry/introspect.py).
    manifest = json.loads((warm_out / "run_manifest.json").read_text())
    assert manifest["corpus_cache"]["hits"] >= 1


def test_no_corpus_cache_opt_out_writes_nothing(fixture_csv, tmp_path):
    from music_analyst_tpu.engines.wordcount import run_analysis

    cache_dir = tmp_path / "cache"
    run_analysis(str(fixture_csv), output_dir=str(tmp_path / "out"),
                 corpus_cache_dir=str(cache_dir), use_corpus_cache=False,
                 write_split=False, quiet=True)
    assert not cache_dir.exists()


# --------------------------------------------------- streaming histogram


def test_resolve_chunk_songs():
    from music_analyst_tpu.ops.histogram import (
        _AUTO_STREAM_MIN_TOKENS,
        resolve_chunk_songs,
    )

    # Explicit: 0 = off, N = N (clamped to the corpus), negative rejected.
    assert resolve_chunk_songs(0, 100, 10_000) == 0
    assert resolve_chunk_songs(7, 100, 10_000) == 7
    assert resolve_chunk_songs(500, 100, 10_000) == 100
    with pytest.raises(ValueError):
        resolve_chunk_songs(-1, 100, 10_000)
    # Auto: off below the streaming floor, bounded chunks above it.
    assert resolve_chunk_songs(None, 100, 10_000) == 0
    assert resolve_chunk_songs("auto", 100, 10_000) == 0
    big = _AUTO_STREAM_MIN_TOKENS * 2
    chunk = resolve_chunk_songs(None, 1_000_000, big)
    assert 1 <= chunk <= 1_000_000


@pytest.mark.parametrize("chunk_songs", [1, 3, 7, 16, 1000])
@pytest.mark.parametrize("depth", [0, 2])
def test_streaming_histogram_bit_identical(fixture_csv, chunk_songs, depth):
    from music_analyst_tpu.ops.histogram import (
        sharded_histogram,
        sharded_histogram_streaming,
    )
    from music_analyst_tpu.parallel.mesh import data_parallel_mesh

    corpus = ingest_dataset(str(fixture_csv), backend="python")
    mesh = data_parallel_mesh()
    vocab = max(1, len(corpus.word_vocab))
    baseline = np.asarray(sharded_histogram(corpus.word_ids, vocab, mesh))
    streamed = sharded_histogram_streaming(
        corpus.word_ids, corpus.word_offsets, vocab, mesh,
        chunk_songs=chunk_songs, prefetch_depth=depth,
    )
    np.testing.assert_array_equal(streamed, baseline)


def test_streaming_run_analysis_byte_identical(fixture_csv, tmp_path):
    """word_counts.csv must not depend on the chunk size (golden
    contract: output bytes are invariant across device strategies)."""
    from music_analyst_tpu.engines.wordcount import run_analysis

    ref_out = tmp_path / "chunk0"
    run_analysis(str(fixture_csv), output_dir=str(ref_out), chunk_songs=0,
                 write_split=False, quiet=True)
    ref_words = (ref_out / "word_counts.csv").read_bytes()
    ref_artists = (ref_out / "top_artists.csv").read_bytes()
    for chunk in (1, 5, 64):
        out = tmp_path / f"chunk{chunk}"
        run_analysis(str(fixture_csv), output_dir=str(out),
                     chunk_songs=chunk, write_split=False, quiet=True)
        assert (out / "word_counts.csv").read_bytes() == ref_words
        assert (out / "top_artists.csv").read_bytes() == ref_artists


def test_streaming_empty_and_bad_args(fixture_csv):
    from music_analyst_tpu.ops.histogram import sharded_histogram_streaming
    from music_analyst_tpu.parallel.mesh import data_parallel_mesh

    mesh = data_parallel_mesh()
    with pytest.raises(ValueError):
        sharded_histogram_streaming(
            np.zeros(0, np.int32), np.zeros(1, np.int64), 4, mesh,
            chunk_songs=0,
        )
    empty = sharded_histogram_streaming(
        np.zeros(0, np.int32), np.zeros(1, np.int64), 4, mesh, chunk_songs=2,
    )
    np.testing.assert_array_equal(empty, np.zeros(4, np.int32))


# ------------------------------------------------------------- satellites


def test_xla_cache_enable_failure_stays_retryable(monkeypatch, tmp_path):
    """A transient enable failure must not permanently pin the process to
    cold compiles (the old bug set _enabled=True in the except path)."""
    import jax

    from music_analyst_tpu.telemetry import get_telemetry
    from music_analyst_tpu.utils import cache as xla_cache

    prev_enabled = xla_cache._enabled
    prev_dir = jax.config.jax_compilation_cache_dir
    try:
        xla_cache._enabled = False

        def boom(*args, **kwargs):
            raise OSError("disk full")

        monkeypatch.setattr(os, "makedirs", boom)
        before = get_telemetry().counters.get("xla_cache.enable_failed", 0)
        xla_cache.enable_persistent_compilation_cache(str(tmp_path / "x"))
        assert xla_cache._enabled is False  # retryable, not latched
        after = get_telemetry().counters.get("xla_cache.enable_failed", 0)
        assert after == before + 1

        monkeypatch.undo()
        xla_cache.enable_persistent_compilation_cache(str(tmp_path / "x"))
        assert xla_cache._enabled is True  # the retry succeeded
    finally:
        xla_cache._enabled = prev_enabled
        jax.config.update("jax_compilation_cache_dir", prev_dir)


def test_bench_child_timeout_clamps_to_parent_budget():
    from benchmarks import _util

    now = [1000.0]

    def clock():
        return now[0]

    try:
        # Unarmed: the caller's cap passes through untouched.
        _util.arm_deadline(None)
        assert _util.clamped_timeout(1200.0, clock=clock) == 1200.0
        # Armed with 480 s: a 1200 s cap clamps to budget minus safety.
        _util.arm_deadline(480.0, clock=clock)
        assert _util.clamped_timeout(1200.0, clock=clock) == pytest.approx(
            480.0 - _util._BUDGET_SAFETY_S
        )
        # Small caps under the budget are untouched.
        assert _util.clamped_timeout(30.0, clock=clock) == 30.0
        # Nearly-spent budget floors at 1 s (child launches and times out
        # rather than clamped_timeout raising on a non-positive value).
        now[0] = 1000.0 + 479.0
        assert _util.clamped_timeout(1200.0, clock=clock) == 1.0
    finally:
        _util.arm_deadline(None)
