"""Sharded psum histogram vs numpy oracle on the 8-device CPU mesh."""

import numpy as np
import pytest

from music_analyst_tpu.ops.histogram import (
    PAD_ID,
    shard_pad,
    sharded_histogram,
    sharded_total,
    token_histogram,
)
from music_analyst_tpu.parallel.mesh import (
    build_mesh,
    data_parallel_mesh,
    factor_devices,
)


def test_token_histogram_ignores_padding():
    ids = np.array([0, 2, 2, PAD_ID, 1, PAD_ID], dtype=np.int32)
    out = np.asarray(token_histogram(ids, 4))
    np.testing.assert_array_equal(out, [1, 1, 2, 0])


def test_shard_pad_even_split():
    out = shard_pad(np.arange(5, dtype=np.int32), 4, PAD_ID)
    assert out.shape == (8,)
    assert (out[5:] == PAD_ID).all()
    # already even: untouched
    same = shard_pad(np.arange(8, dtype=np.int32), 4, PAD_ID)
    assert same.shape == (8,)


def test_sharded_histogram_matches_bincount():
    rng = np.random.default_rng(0)
    vocab = 1000
    ids = rng.integers(0, vocab, size=100_003).astype(np.int32)
    mesh = data_parallel_mesh()
    assert mesh.shape["dp"] == 8
    got = np.asarray(sharded_histogram(ids, vocab, mesh))
    np.testing.assert_array_equal(got, np.bincount(ids, minlength=vocab))


def test_sharded_histogram_empty_corpus():
    mesh = data_parallel_mesh()
    got = np.asarray(sharded_histogram(np.array([], dtype=np.int32), 7, mesh))
    np.testing.assert_array_equal(got, np.zeros(7, np.int32))


def test_sharded_total():
    mesh = data_parallel_mesh()
    values = np.arange(17, dtype=np.int64)
    assert sharded_total(values, mesh) == int(values.sum())


def test_factor_devices_exact_product():
    for n in (1, 2, 4, 6, 8, 12):
        spec = factor_devices(n)
        assert spec.size() == n
    spec = factor_devices(8, fixed={"tp": 2})
    assert dict(spec.axes)["tp"] == 2
    assert spec.size() == 8


def test_multi_axis_mesh_histogram():
    # Histogram still correct when the mesh has extra (model) axes: ids are
    # sharded over dp and replicated over tp.
    import jax
    from jax.sharding import PartitionSpec as P

    mesh = build_mesh(factor_devices(8, ("dp", "tp"), fixed={"tp": 2}))
    assert mesh.shape == {"dp": 4, "tp": 2}
    ids = np.arange(64, dtype=np.int32) % 10

    import jax.numpy as jnp
    from music_analyst_tpu.ops.histogram import token_histogram
    from music_analyst_tpu.utils.jax_compat import shard_map

    fn = jax.jit(
        shard_map(
            lambda x: jax.lax.psum(token_histogram(x, 10), "dp"),
            mesh=mesh,
            in_specs=P("dp"),
            out_specs=P(),
        )
    )
    got = np.asarray(fn(ids))
    np.testing.assert_array_equal(got, np.bincount(ids, minlength=10))


def test_histogram_callables_cached_no_retrace():
    """Repeat calls reuse ONE compiled program per (mesh, axis, vocab) —
    the round-2 defect was a fresh jit(shard_map(lambda)) per call, which
    re-traced every invocation and made sweep timings compilation-bound."""
    from music_analyst_tpu.ops import histogram as H

    mesh = data_parallel_mesh()
    rng = np.random.default_rng(7)
    ids = rng.integers(0, 300, size=10_001).astype(np.int32)

    sharded_histogram(ids, 300, mesh)  # warm: builds + traces
    H.sharded_histogram_hostlocal(ids, 300, mesh)
    sharded_total(ids, mesh)
    keys = [
        (H._psum_ids_histogram, (mesh, "dp", 1 << 10)),
        (H._psum_rows, (mesh, "dp")),
        (H._psum_scalar, (mesh, "dp")),
    ]
    compiled = [factory(*key)._cache_size() for factory, key in keys]
    hits0 = [factory.cache_info().hits for factory, _ in keys]

    # Same shapes again — zero new traces, zero new jit cache entries.
    sharded_histogram(ids[:9_900], 300, mesh)  # same linear bucket
    H.sharded_histogram_hostlocal(ids, 300, mesh)
    sharded_total(ids, mesh)
    assert [factory(*key)._cache_size() for factory, key in keys] == compiled
    hits1 = [factory.cache_info().hits for factory, _ in keys]
    assert all(b > a for a, b in zip(hits0, hits1))


def test_hostlocal_timed_returns_per_shard_measurements():
    rng = np.random.default_rng(5)
    ids = rng.integers(0, 100, size=50_000).astype(np.int32)
    mesh = data_parallel_mesh()
    from music_analyst_tpu.ops.histogram import (
        sharded_histogram_hostlocal_timed,
    )

    counts, timings = sharded_histogram_hostlocal_timed(ids, 100, mesh)
    np.testing.assert_array_equal(counts, np.bincount(ids, minlength=100))
    assert len(timings.count_seconds) == 8
    assert all(s >= 0 for s in timings.count_seconds)
    assert timings.merge_seconds > 0
    per_chip = timings.per_chip_seconds()
    assert len(per_chip) == 8 and len(set(per_chip)) > 1


def test_hostlocal_matches_device_path():
    rng = np.random.default_rng(3)
    vocab = 5000
    ids = rng.integers(0, vocab, size=250_007).astype(np.int32)
    ids[::97] = -1  # padding ids ignored in both paths
    mesh = data_parallel_mesh()
    from music_analyst_tpu.ops.histogram import sharded_histogram_hostlocal

    a = np.asarray(sharded_histogram(ids, vocab, mesh))
    b = sharded_histogram_hostlocal(ids, vocab, mesh)
    np.testing.assert_array_equal(a, b)
    valid = ids[ids >= 0]
    np.testing.assert_array_equal(b, np.bincount(valid, minlength=vocab))
