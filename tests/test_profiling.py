"""Profiling layer: compile introspection, collective accounting, traces,
and the profile-diff regression gate.

The acceptance pins from the observability issue live here: a two-shape
workload must show exactly 2 compiles + 1 recompile (and the counter must
land in ``telemetry.jsonl``), collective byte counters must match the
analytic ring costs on the 8-device CPU mesh, ``profile-diff`` must catch
a synthetic 20% throughput regression with a nonzero exit, and golden
artifacts must stay byte-identical with profiling enabled.
"""

import json

import numpy as np
import pytest

from music_analyst_tpu.profiling.collectives import (
    all_gather_bytes,
    all_to_all_bytes,
    ppermute_bytes,
    psum_bytes,
    record_collective,
    stage_table,
)
from music_analyst_tpu.profiling.diff import run_profile_diff
from music_analyst_tpu.telemetry import configure, get_telemetry


@pytest.fixture(autouse=True)
def _fresh_telemetry():
    """Clean registry + empty stage-table accumulator per test."""
    from music_analyst_tpu.profiling.collectives import _STAGE_LOCK, _STAGE_TOTALS

    with _STAGE_LOCK:
        _STAGE_TOTALS.clear()
    yield configure(enabled=True, directory=None)
    configure(enabled=True, directory=None)
    with _STAGE_LOCK:
        _STAGE_TOTALS.clear()


def _jsonl_events(path, name=None):
    events = [
        json.loads(line) for line in path.read_text().splitlines() if line
    ]
    if name is not None:
        events = [e for e in events if e.get("name") == name]
    return events


# ------------------------------------------------------ analytic estimators


def test_ring_cost_estimators_hand_computed():
    # Ring all-reduce: reduce-scatter + all-gather halves.
    assert psum_bytes(1024, 8) == 2 * 7 * 1024 // 8
    assert all_gather_bytes(512, 8) == 7 * 512
    assert all_to_all_bytes(800, 8) == 7 * 800 // 8
    assert ppermute_bytes(64) == 64
    # Single participant moves nothing (ppermute still sends to itself's
    # neighbor — a ring of one is the identity, but the estimator reports
    # the payload; callers don't issue it on 1-device meshes).
    assert psum_bytes(1024, 1) == 0
    assert all_gather_bytes(512, 1) == 0
    assert all_to_all_bytes(800, 1) == 0


def test_record_collective_rejects_unknown_kind():
    with pytest.raises(ValueError, match="unknown collective kind"):
        record_collective("s", "broadcastify", payload_bytes=1, n_devices=2)


def test_record_collective_counters_events_and_stage_table(tmp_path):
    tel = get_telemetry()
    with tel.run_scope("x", str(tmp_path)):
        per_dev = record_collective(
            "stage_a", "psum", payload_bytes=4096, n_devices=8
        )
        record_collective(
            "stage_b", "ppermute", payload_bytes=64, n_devices=8, count=10
        )
        assert per_dev == psum_bytes(4096, 8)
        counters = dict(tel.counters)
        # run_scope exit emits + clears the table; snapshot it while open.
        rows = {r["stage"]: r for r in stage_table()}
    assert counters["collectives.psum_bytes"] == psum_bytes(4096, 8)
    assert counters["collectives.ppermute_bytes"] == 64 * 10
    assert (
        counters["collectives.total_bytes"]
        == psum_bytes(4096, 8) + 64 * 10
    )
    assert rows["stage_a"]["bytes"] == psum_bytes(4096, 8)
    assert rows["stage_b"]["calls"] == 10

    log = tmp_path / "telemetry.jsonl"
    events = _jsonl_events(log, "collective")
    assert {e["attrs"]["stage"] for e in events} == {"stage_a", "stage_b"}
    (table_event,) = _jsonl_events(log, "collective_stage_table")
    table = {r["stage"]: r for r in table_event["attrs"]["rows"]}
    assert table["stage_b"]["bytes"] == 64 * 10


# -------------------------------------------------- compile introspection


def test_recompile_detector_two_shapes(tmp_path):
    """Two distinct input shapes ⇒ exactly 2 compiles and 1 recompile,
    both visible in the JSONL stream (the issue's acceptance pin)."""
    import jax.numpy as jnp

    from music_analyst_tpu.profiling.compile import profiled_jit

    fn = profiled_jit(lambda x: x * 2 + 1, name="recompile_probe")
    tel = get_telemetry()
    with tel.run_scope("x", str(tmp_path)):
        a = np.arange(8, dtype=np.float32)
        b = np.arange(16, dtype=np.float32)
        np.testing.assert_allclose(np.asarray(fn(a)), a * 2 + 1)
        np.testing.assert_allclose(np.asarray(fn(a)), a * 2 + 1)  # cached
        np.testing.assert_allclose(np.asarray(fn(b)), b * 2 + 1)  # recompile
        counters = dict(tel.counters)
    assert counters["profiling.compiles"] == 2
    assert counters["profiling.recompiles"] == 1
    assert len(fn.records) == 2

    log = tmp_path / "telemetry.jsonl"
    compiles = [
        e for e in _jsonl_events(log, "compile")
        if e["attrs"]["fn"] == "recompile_probe"
    ]
    assert len(compiles) == 2
    (recompile,) = _jsonl_events(log, "recompile")
    assert recompile["attrs"]["fn"] == "recompile_probe"
    assert "float32[8]" in recompile["attrs"]["prev_aval"]
    assert "float32[16]" in recompile["attrs"]["new_aval"]
    # The recompile counter must land in the stream's run_end record too.
    (run_end,) = _jsonl_events(log, "run_end")
    assert run_end["attrs"]["counters"]["profiling.recompiles"] == 1


def test_compile_record_fields():
    from music_analyst_tpu.profiling.compile import profiled_jit

    fn = profiled_jit(lambda x: x @ x.T, name="record_fields_probe")
    x = np.ones((4, 4), dtype=np.float32)
    np.asarray(fn(x))
    (rec,) = fn.records.values()
    d = rec.as_dict()
    assert d["name"] == "record_fields_probe"
    assert "float32[4, 4]" in d["aval_key"]
    # The HLO fingerprint is the run-comparison anchor; cost/memory fields
    # are backend-dependent (CPU PJRT has no memory_analysis) and may be
    # null, but must be numeric when present.
    assert isinstance(d["hlo_fingerprint"], str) and d["hlo_fingerprint"]
    assert d["compile_seconds"] > 0
    for key in ("flops", "bytes_accessed", "temp_bytes"):
        assert d[key] is None or isinstance(d[key], (int, float))


def test_profiled_jit_under_outer_jit_defers_to_plain_jit():
    """jit-of-jit (the shard_map local fns): tracers must pass through."""
    import jax

    from music_analyst_tpu.profiling.compile import profiled_jit

    inner = profiled_jit(lambda x: x + 1, name="nested_probe")
    outer = jax.jit(lambda x: inner(x) * 3)
    np.testing.assert_allclose(
        np.asarray(outer(np.float32(2.0))), 9.0
    )
    # The traced call must NOT have minted an AOT record for the tracer.
    assert all("Traced" not in k for k in inner.records)


def test_manifest_profiling_section(tmp_path):
    from music_analyst_tpu.profiling.compile import profiled_jit

    fn = profiled_jit(lambda x: x - 5, name="manifest_probe")
    tel = get_telemetry()
    with tel.run_scope("x", str(tmp_path)):
        np.asarray(fn(np.arange(4, dtype=np.int32)))
    manifest = json.loads((tmp_path / "run_manifest.json").read_text())
    names = {rec["name"] for rec in manifest["profiling"]["compiles"]}
    assert "manifest_probe" in names


# ------------------------------------------- collective bytes vs analytic


def test_sharded_histogram_bytes_match_analytic(tmp_path):
    from music_analyst_tpu.ops.histogram import sharded_histogram
    from music_analyst_tpu.parallel.mesh import data_parallel_mesh
    from music_analyst_tpu.utils.shapes import round_pow2

    mesh = data_parallel_mesh(8)
    vocab = 100
    ids = np.arange(vocab, dtype=np.int32)
    tel = get_telemetry()
    with tel.run_scope("x", str(tmp_path)):
        counts = np.asarray(sharded_histogram(ids, vocab, mesh))
        counters = dict(tel.counters)
    np.testing.assert_array_equal(counts, np.ones(vocab, dtype=np.int32))
    padded_vocab = round_pow2(vocab, 1 << 10)
    expected = psum_bytes(padded_vocab * 4, 8)
    assert counters["collectives.psum_bytes"] == expected
    assert counters["collectives.total_bytes"] == expected


def test_pipeline_records_ppermute_and_broadcast(tmp_path):
    import jax.numpy as jnp

    from music_analyst_tpu.parallel.mesh import data_parallel_mesh
    from music_analyst_tpu.parallel.pipeline import pipeline_apply

    mesh = data_parallel_mesh(4, axis="pp")
    n_stages, n_micro, mb, dim = 4, 3, 2, 8
    params = {"w": jnp.ones((n_stages, 1, dim))}
    microbatches = jnp.ones((n_micro, mb, dim), jnp.float32)
    tel = get_telemetry()
    with tel.run_scope("x", str(tmp_path)):
        pipeline_apply(
            lambda p, x: x + p["w"][0], params, microbatches, mesh, axis="pp"
        )
        counters = dict(tel.counters)
    act = mb * dim * 4
    assert counters["collectives.ppermute_bytes"] == act * (
        n_micro + n_stages - 1
    )
    assert counters["collectives.psum_bytes"] == psum_bytes(
        n_micro * act, n_stages
    )


# --------------------------------------------------------- trace artifacts


def test_profile_run_writes_chrome_trace(tmp_path):
    from music_analyst_tpu.profiling.trace import profile_run

    tel = get_telemetry()
    with profile_run(str(tmp_path / "prof")):
        with tel.span("unit_test_stage", rows=7):
            pass
    trace = json.loads((tmp_path / "prof" / "trace_spans.json").read_text())
    events = trace["traceEvents"]
    (span_event,) = [e for e in events if e["name"] == "unit_test_stage"]
    assert span_event["ph"] == "X"
    assert span_event["dur"] >= 0
    assert span_event["args"]["rows"] == "7"
    assert any(e["ph"] == "M" and e["name"] == "thread_name" for e in events)


def test_cli_profile_dir_flag(fixture_csv, tmp_path, capsys):
    from music_analyst_tpu.cli.main import main

    prof = tmp_path / "prof"
    rc = main(
        [
            "analyze", str(fixture_csv),
            "--output-dir", str(tmp_path / "out"),
            "--ingest", "python",
            "--profile-dir", str(prof),
        ]
    )
    capsys.readouterr()
    assert rc == 0
    assert (prof / "trace_spans.json").exists()


# --------------------------------------------------- profile-diff gate


def _bench_line(value, metric="sentiment_songs_per_sec_distilbert"):
    return {"metric": metric, "value": value, "unit": "songs/sec"}


def test_profile_diff_detects_20pct_regression(tmp_path, capsys):
    a = tmp_path / "a.json"
    b = tmp_path / "b.json"
    a.write_text(json.dumps(_bench_line(1000.0)))
    b.write_text(json.dumps(_bench_line(800.0)))  # synthetic -20%
    assert run_profile_diff(str(a), str(b)) == 1
    out = capsys.readouterr().out
    assert "REGRESSION" in out


def test_profile_diff_passes_within_threshold(tmp_path, capsys):
    a = tmp_path / "a.json"
    b = tmp_path / "b.json"
    a.write_text(json.dumps(_bench_line(1000.0)))
    b.write_text(json.dumps(_bench_line(950.0)))  # -5% < 10% threshold
    assert run_profile_diff(str(a), str(b)) == 0
    assert "verdict: ok" in capsys.readouterr().out


def test_profile_diff_threshold_flag(tmp_path, capsys):
    a = tmp_path / "a.json"
    b = tmp_path / "b.json"
    a.write_text(json.dumps(_bench_line(1000.0)))
    b.write_text(json.dumps(_bench_line(950.0)))
    assert run_profile_diff(str(a), str(b), threshold=0.02) == 1
    capsys.readouterr()


def test_profile_diff_manifest_wall_regression(tmp_path, capsys):
    a = tmp_path / "a.json"
    b = tmp_path / "b.json"
    a.write_text(json.dumps({"schema": 1, "wall_seconds": 10.0}))
    b.write_text(json.dumps({"schema": 1, "wall_seconds": 14.0}))  # +40%
    assert run_profile_diff(str(a), str(b)) == 1
    capsys.readouterr()


def test_profile_diff_bad_input_exits_2(tmp_path, capsys):
    a = tmp_path / "a.json"
    a.write_text(json.dumps(_bench_line(1000.0)))
    assert run_profile_diff(str(a), "not json at all") == 2
    capsys.readouterr()


def test_profile_diff_cli_subcommand(tmp_path, capsys):
    from music_analyst_tpu.cli.main import main

    a = tmp_path / "a.json"
    b = tmp_path / "b.json"
    a.write_text(json.dumps(_bench_line(1000.0)))
    b.write_text(json.dumps(_bench_line(790.0)))
    assert main(["profile-diff", str(a), str(b)]) == 1
    assert main(["profile-diff", str(a), str(a)]) == 0
    capsys.readouterr()


# ------------------------------------------------- golden-artifact safety


def test_word_counts_byte_identical_with_profiling(fixture_csv, tmp_path,
                                                   capsys):
    """Profiling must ride alongside the golden contracts, never in them:
    the same analysis with telemetry off vs profiling fully on produces
    byte-identical word_counts.csv."""
    from music_analyst_tpu.cli.main import main

    rc = main(
        [
            "analyze", str(fixture_csv),
            "--output-dir", str(tmp_path / "plain"),
            "--ingest", "python",
            "--no-telemetry",
        ]
    )
    assert rc == 0
    configure(enabled=True, directory=None)
    rc = main(
        [
            "analyze", str(fixture_csv),
            "--output-dir", str(tmp_path / "profiled"),
            "--ingest", "python",
            "--profile-dir", str(tmp_path / "prof"),
        ]
    )
    assert rc == 0
    capsys.readouterr()
    assert (
        (tmp_path / "plain" / "word_counts.csv").read_bytes()
        == (tmp_path / "profiled" / "word_counts.csv").read_bytes()
    )
