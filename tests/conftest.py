"""Test harness: emulate an 8-device TPU mesh on CPU.

The JAX-native analogue of the reference's "mpirun -np N on one box"
verification strategy (SURVEY.md §4): force 8 virtual CPU devices so every
sharding/collective test exercises a real multi-device mesh without TPU
hardware.  Must run before the first ``import jax`` anywhere in the test
process.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
# Hermetic for subprocess-spawning tests too (benchmark suite children,
# multiprocess children): with the axon pool var cleared, the children's
# sitecustomize never registers the TPU plugin, so a wedged/dead tunnel
# cannot hang the CPU-only test suite.
os.environ["PALLAS_AXON_POOL_IPS"] = ""
# Hermetic corpus cache: engine runs cache ingests by default, and the
# default directory is under ~/.cache — point it at a per-session tmpdir
# so tests never read (or pollute) state from earlier runs.
import tempfile

os.environ["MUSICAAL_CORPUS_CACHE"] = tempfile.mkdtemp(
    prefix="musicaal-test-corpus-cache-"
)
# Same hermeticity for the quantized-checkpoint cache (engines/wq_cache.py
# defaults under ~/.cache): a per-session tmpdir keeps warm-hit assertions
# deterministic and host state untouched.
os.environ["MUSICAAL_WQ_CACHE"] = tempfile.mkdtemp(
    prefix="musicaal-test-wq-cache-"
)
# The response cache (serving/response_cache.py) is OFF under tests:
# unlike the artifact caches above, a hit changes serving *counters*
# (completed/batches/rows) that serving tests assert on, so even a
# per-session tmpdir would couple tests that reuse a lyric.  Tests that
# exercise the cache pass an explicit directory, which wins over this.
os.environ["MUSICAAL_RESPONSE_CACHE"] = "off"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

# A site hook may have imported jax and registered a hardware backend before
# this conftest runs; as long as no backend client is initialized yet, the
# platform can still be forced to CPU via the config API.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
assert jax.default_backend() == "cpu", "tests require the CPU-emulated mesh"
assert len(jax.devices()) == 8

import pathlib

import pytest

FIXTURES = pathlib.Path(__file__).parent / "fixtures"


@pytest.fixture(scope="session")
def fixture_csv() -> pathlib.Path:
    return FIXTURES / "mini_songs.csv"
