"""Model families: shapes, KV-cache consistency, TP-sharded equivalence."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from music_analyst_tpu.models.distilbert import (
    DistilBertClassifier,
    DistilBertConfig,
)
from music_analyst_tpu.models.llama import (
    LlamaConfig,
    LlamaModel,
    LlamaZeroShotClassifier,
    init_caches,
)
from music_analyst_tpu.models.layers import causal_mask, padding_mask
from music_analyst_tpu.parallel.mesh import build_mesh, factor_devices
from music_analyst_tpu.parallel.sharding import shard_params
from music_analyst_tpu.utils.labels import SUPPORTED_LABELS


class TestDistilBert:
    @pytest.fixture(scope="class")
    def clf(self):
        return DistilBertClassifier(
            config=DistilBertConfig.tiny(), max_len=32
        )

    def test_forward_shapes(self, clf):
        ids = jnp.zeros((3, 32), jnp.int32)
        lens = jnp.array([5, 1, 32], jnp.int32)
        logits = clf.model.apply({"params": clf.params}, ids, lens)
        assert logits.shape == (3, 2)
        assert logits.dtype == jnp.float32

    def test_padding_invariance(self, clf):
        """Garbage in padded positions must not change the prediction."""
        rng = np.random.default_rng(0)
        ids_a = np.zeros((1, 32), np.int32)
        ids_a[0, :6] = [101, 7, 8, 9, 10, 102]
        ids_b = ids_a.copy()
        ids_b[0, 6:] = rng.integers(1, 1000, 26)
        lens = jnp.array([6], jnp.int32)
        la = clf.model.apply({"params": clf.params}, jnp.asarray(ids_a), lens)
        lb = clf.model.apply({"params": clf.params}, jnp.asarray(ids_b), lens)
        np.testing.assert_allclose(np.asarray(la), np.asarray(lb), atol=2e-2)

    def test_classify_batch_contract(self, clf):
        labels = clf.classify_batch(["i love this", "", "terrible pain"])
        assert all(l in SUPPORTED_LABELS for l in labels)
        assert labels[1] == "Neutral"  # empty lyric rule

    def test_int16_wire_ids_lossless(self):
        """Token ids ship int16 (vocab fits) and widen on device; labels
        must match a forced-int32 wire exactly."""
        import numpy as np

        clf = DistilBertClassifier(config=DistilBertConfig.tiny(), max_len=32)
        assert clf._wire_dtype == np.int16
        texts = ["la la love", "pain and tears tonight", ""]
        got = clf.classify_batch(texts)
        clf._wire_dtype = np.int32
        assert clf.classify_batch(texts) == got

    def test_neutral_threshold_extremes(self):
        clf = DistilBertClassifier(
            config=DistilBertConfig.tiny(), max_len=16, neutral_threshold=1.1
        )
        # threshold > 1 -> everything Neutral
        assert clf.classify_batch(["anything at all"]) == ["Neutral"]


class TestDistilBertLengthBuckets:
    """Bucketed inference: same labels, shorter compiled sequences."""

    def _mixed_texts(self):
        return [
            "short",
            "",
            "a medium length lyric with a handful of words in it",
            "long " + "word " * 60,
            "tiny one",
            "another long lyric " + "la la love rain " * 20,
        ]

    def test_matches_unbucketed_float32(self):
        """In float32 the bucketed path is numerically the unbucketed path
        (padding invariance), so labels must agree exactly."""
        import dataclasses

        cfg = dataclasses.replace(DistilBertConfig.tiny(), dtype="float32")
        plain = DistilBertClassifier(config=cfg, max_len=64, seed=5)
        bucketed = DistilBertClassifier(
            config=cfg, max_len=64, seed=5, length_buckets=(16, 32)
        )
        bucketed.params = plain.params
        texts = self._mixed_texts() * 3
        assert bucketed.classify_batch(texts) == plain.classify_batch(texts)

    def test_routing_and_order_restoration(self):
        """Every row routes to the smallest sufficient bucket and comes
        back in input order (deterministic fake forward)."""
        clf = DistilBertClassifier(
            config=DistilBertConfig.tiny(), max_len=64,
            length_buckets=(16, 32), neutral_threshold=0.5,
        )
        seen_seqs = []

        def fake_forward(params, token_ids, lengths):
            seen_seqs.append(token_ids.shape[1])
            # class = row length parity; confidence = certain
            return np.asarray(lengths) % 2, np.ones(lengths.shape[0])

        clf._forward = fake_forward
        texts = self._mixed_texts()
        _, lengths = clf.tokenizer.encode_batch(texts, clf.max_len)
        labels = clf.classify_batch(texts)
        want = [
            "Neutral" if not t.strip()
            else clf._CLASS_LABELS[int(n) % 2]
            for t, n in zip(texts, lengths)
        ]
        assert labels == want
        assert set(seen_seqs) <= {16, 32, 64}
        assert len(seen_seqs) >= 2  # mixed lengths hit multiple buckets

    def test_single_bucket_when_all_short(self):
        clf = DistilBertClassifier(
            config=DistilBertConfig.tiny(), max_len=64, length_buckets=(16,)
        )
        seen = []
        real = clf._forward
        clf._forward = lambda p, i, l: (seen.append(i.shape), real(p, i, l))[1]
        clf.classify_batch(["hi there", "la la", "ok"])
        assert all(shape[1] == 16 for shape in seen)
        # rows round up to the power-of-two floor
        assert all(shape[0] == 16 for shape in seen)

    def test_bucketed_on_dp_mesh(self):
        mesh = build_mesh(factor_devices(8, ("dp",)))
        clf = DistilBertClassifier(
            config=DistilBertConfig.tiny(), max_len=64, mesh=mesh,
            length_buckets=(16, 32),
        )
        labels = clf.classify_batch(self._mixed_texts())
        assert len(labels) == 6
        assert all(l in SUPPORTED_LABELS for l in labels)
        assert labels[1] == "Neutral"

    def test_bucket_validation(self):
        with pytest.raises(ValueError, match="floor"):
            DistilBertClassifier(
                config=DistilBertConfig.tiny(), max_len=64, length_buckets=(4,)
            )
        with pytest.raises(ValueError, match="exceeds max_len"):
            DistilBertClassifier(
                config=DistilBertConfig.tiny(), max_len=64,
                length_buckets=(128,),
            )

    def test_derive_length_buckets(self):
        from music_analyst_tpu.models.distilbert import derive_length_buckets

        # Cap-dominated corpus (the headline shape): no bucket is worth a
        # compiled program, flat path stays.
        assert derive_length_buckets(np.full(100, 128), 128) == ()
        # Short-skewed corpus: real buckets come back, ascending.
        short = np.concatenate([np.full(40, 20), np.full(40, 50),
                                np.full(20, 128)])
        assert derive_length_buckets(short, 128) == (32, 64)
        # Rows of a dropped bucket roll upward into the next kept one.
        mixed = np.concatenate([np.full(3, 10), np.full(47, 30),
                                np.full(50, 128)])
        assert derive_length_buckets(mixed, 128) == (32,)
        # Degenerate inputs.
        assert derive_length_buckets(np.array([]), 128) == ()
        assert derive_length_buckets(np.full(10, 4), 16) == ()

    def test_auto_buckets_resolve_on_first_batch(self):
        clf = DistilBertClassifier(
            config=DistilBertConfig.tiny(), max_len=64, length_buckets="auto"
        )
        assert clf.length_buckets == "auto"
        labels = clf.classify_batch(["hi there you", "la la love"] * 20)
        assert len(labels) == 40
        # All-short corpus → a real short bucket was derived (plus the
        # implicit max_len bucket _check_buckets appends).
        assert isinstance(clf.length_buckets, tuple)
        assert clf.length_buckets[0] < 64
        # Second batch reuses the resolved buckets (no re-derivation).
        resolved = clf.length_buckets
        clf.classify_batch(["longer lyric " + "word " * 60])
        assert clf.length_buckets is resolved

    def test_auto_buckets_pend_through_empty_batches(self):
        """An empty first batch must not resolve auto to the flat path."""
        clf = DistilBertClassifier(
            config=DistilBertConfig.tiny(), max_len=64, length_buckets="auto"
        )
        assert clf.classify_batch([]) == []
        assert clf.length_buckets == "auto"  # still pending
        clf.classify_batch(["short words"] * 4)
        assert isinstance(clf.length_buckets, tuple)

    def test_auto_buckets_stay_flat_on_capped_corpus(self):
        clf = DistilBertClassifier(
            config=DistilBertConfig.tiny(), max_len=64, length_buckets="auto"
        )
        long_texts = ["word " * 100] * 8
        clf.classify_batch(long_texts)
        assert clf.length_buckets is None


class TestLlama:
    @pytest.fixture(scope="class")
    def clf(self):
        return LlamaZeroShotClassifier(
            config=LlamaConfig.tiny(), max_prompt_len=160
        )

    def test_prefill_matches_no_cache(self, clf):
        """Prefill-with-cache logits == plain forward logits."""
        cfg = clf.config
        B, S = 2, 12
        ids = jnp.asarray(
            np.random.default_rng(1).integers(0, 256, (B, S)), jnp.int32
        )
        pos = jnp.arange(S)[None, :].repeat(B, 0)
        mask = causal_mask(S, S, 0)
        plain, _ = clf.model.apply({"params": clf.params}, ids, pos, mask)
        caches = init_caches(cfg, B, S + 4)
        mask_c = causal_mask(S, S + 4, 0)
        cached, caches = clf.model.apply(
            {"params": clf.params}, ids, pos, mask_c, caches
        )
        np.testing.assert_allclose(
            np.asarray(plain), np.asarray(cached), rtol=2e-2, atol=2e-2
        )

    def test_incremental_decode_matches_full_forward(self, clf):
        """Token-by-token decode reproduces the full-sequence argmax path."""
        cfg = clf.config
        rng = np.random.default_rng(2)
        S = 10
        ids = jnp.asarray(rng.integers(0, 256, (1, S)), jnp.int32)
        pos = jnp.arange(S)[None, :]
        full_logits, _ = clf.model.apply(
            {"params": clf.params}, ids, pos, causal_mask(S, S, 0)
        )
        # incremental: prefill first 5, then decode 5 one at a time
        caches = init_caches(cfg, 1, S)
        pre = 5
        logits_p, caches = clf.model.apply(
            {"params": clf.params},
            ids[:, :pre],
            pos[:, :pre],
            causal_mask(pre, S, 0),
            caches,
        )
        step_logits = [logits_p[:, -1]]
        for t in range(pre, S):
            kv_pos = jnp.arange(S)[None, None, None, :]
            mask = kv_pos <= t
            logits_t, caches = clf.model.apply(
                {"params": clf.params},
                ids[:, t : t + 1],
                pos[:, t : t + 1],
                mask,
                caches,
            )
            step_logits.append(logits_t[:, -1])
        for t in range(pre, S):
            np.testing.assert_allclose(
                np.asarray(full_logits[:, t - 1]),
                np.asarray(step_logits[t - pre]),
                rtol=5e-2,
                atol=5e-2,
            )

    def test_classify_batch_contract(self, clf):
        labels = clf.classify_batch(["love and joy", "", "tears of pain"])
        assert all(l in SUPPORTED_LABELS for l in labels)
        assert labels[1] == "Neutral"

    def test_generation_path(self, clf):
        text = clf.generate("hello", max_new_tokens=4)
        assert isinstance(text, str)
        label = clf.classify_by_generation("some lyrics here")
        assert label in SUPPORTED_LABELS

    def test_preset_llama3_requires_checkpoint(self):
        with pytest.raises(RuntimeError, match="checkpoint"):
            LlamaZeroShotClassifier.from_pretrained_or_random("llama3")


class TestTensorParallel:
    def test_sharded_forward_matches_single_device(self):
        """dp×tp sharded forward == unsharded forward (same params)."""
        cfg = LlamaConfig.tiny()
        model = LlamaModel(cfg)
        rng = np.random.default_rng(3)
        ids = jnp.asarray(rng.integers(0, 256, (4, 16)), jnp.int32)
        pos = jnp.arange(16)[None, :].repeat(4, 0)
        mask = causal_mask(16, 16, 0)
        params = model.init(jax.random.key(0), ids, pos, mask)["params"]
        ref_logits, _ = model.apply({"params": params}, ids, pos, mask)

        mesh = build_mesh(factor_devices(8, ("dp", "tp"), fixed={"tp": 4}))
        sharded = shard_params(params, mesh)
        from jax.sharding import NamedSharding, PartitionSpec as P

        ids_s = jax.device_put(ids, NamedSharding(mesh, P("dp")))
        pos_s = jax.device_put(pos, NamedSharding(mesh, P("dp")))
        out, _ = jax.jit(
            lambda p, i, q: model.apply({"params": p}, i, q, mask)
        )(sharded, ids_s, pos_s)
        ref_np, out_np = np.asarray(ref_logits), np.asarray(out)
        # bf16 all-reduce ordering differs across shards; demand near-total
        # elementwise agreement plus identical argmax decisions wherever
        # the decision isn't a near-tie (a reduction-order flip can
        # legitimately swap a top-2 pair separated by less than bf16
        # noise — the typical margin on this corpus is ~0.24).
        close = np.isclose(ref_np, out_np, rtol=3e-2, atol=3e-2)
        assert close.mean() > 0.999
        agree = ref_np.argmax(-1) == out_np.argmax(-1)
        srt = np.sort(ref_np, axis=-1)
        margin = srt[..., -1] - srt[..., -2]
        assert agree[margin > 0.02].all(), margin[~agree]
        assert agree.mean() > 0.95

    def test_partition_specs_cover_attention_and_mlp(self):
        cfg = LlamaConfig.tiny()
        model = LlamaModel(cfg)
        ids = jnp.zeros((1, 8), jnp.int32)
        pos = jnp.zeros((1, 8), jnp.int32)
        params = model.init(jax.random.key(0), ids, pos, causal_mask(8, 8, 0))[
            "params"
        ]
        from music_analyst_tpu.parallel.sharding import partition_specs
        from jax.sharding import PartitionSpec as P

        specs = partition_specs(params)
        l0 = specs["layer_0"]
        assert l0["attention"]["q_proj"]["kernel"] == P(None, "tp", None)
        assert l0["attention"]["o_proj"]["kernel"] == P("tp", None, None)
        assert l0["feed_forward"]["gate_proj"]["kernel"] == P(None, "tp")
        assert l0["feed_forward"]["down_proj"]["kernel"] == P("tp", None)
        assert specs["tok_embeddings"]["embedding"] == P("tp", None)
        assert specs["lm_head"]["kernel"] == P(None, "tp")
        assert specs["norm"]["scale"] == P()


def test_generate_scan_matches_step_loop():
    """Single-jit scan generation ≡ the explicit per-token step loop."""
    from music_analyst_tpu.models.llama import (
        LlamaConfig,
        LlamaZeroShotClassifier,
    )

    cfg = LlamaConfig(
        vocab_size=300, dim=32, n_layers=2, n_heads=4, n_kv_heads=2,
        hidden_dim=64, rope_theta=1e4, max_seq_len=128, dtype="float32",
    )
    clf = LlamaZeroShotClassifier(config=cfg, max_prompt_len=32, seed=3)
    prompts = ["hello world", "la la la la la la", "x"]
    batched = clf.generate_batch(prompts, max_new_tokens=8)
    singles = [clf.generate(p, max_new_tokens=8) for p in prompts]
    assert batched == singles


def test_generation_decode_mode():
    """decode_mode='generate' classifies via batched free-text decode +
    the shared normalizer, honoring the empty-lyric rule."""
    from music_analyst_tpu.models.llama import (
        LlamaConfig,
        LlamaZeroShotClassifier,
    )

    cfg = LlamaConfig(
        vocab_size=300, dim=32, n_layers=1, n_heads=4, n_kv_heads=2,
        hidden_dim=64, rope_theta=1e4, max_seq_len=128, dtype="float32",
    )
    clf = LlamaZeroShotClassifier(
        config=cfg, max_prompt_len=32, decode_mode="generate"
    )
    labels = clf.classify_batch(["some lyrics", ""])
    assert labels[1] == "Neutral"
    assert all(l in ("Positive", "Neutral", "Negative") for l in labels)
    singles = [clf.classify_by_generation("some lyrics")]
    assert labels[0] == singles[0]  # batch ≡ single-song reference path


class TestLlamaPromptTrimming:
    """Prefill pads to a power-of-two over the batch's longest prompt,
    not to max_prompt_len (the decoder analogue of length buckets).

    The equality tests compare programs compiled at different widths.
    Masked padding contributes exact zeros, but XLA may reassociate the
    non-zero accumulations differently per shape, so equality is a
    last-ulp assumption: exact on the CI platform (CPU, fixed seed,
    float32 config), not a cross-platform guarantee.  A flake here on new
    hardware means a near-tied argmax, not a trimming bug.
    """

    def _clf(self, **kw):
        from music_analyst_tpu.models.llama import (
            LlamaConfig,
            LlamaZeroShotClassifier,
        )

        cfg = LlamaConfig(
            vocab_size=300, dim=32, n_layers=1, n_heads=4, n_kv_heads=2,
            hidden_dim=64, rope_theta=1e4, max_seq_len=1024, dtype="float32",
        )
        return LlamaZeroShotClassifier(
            config=cfg, max_prompt_len=512, **kw
        )

    def test_short_batch_scores_at_trimmed_width(self):
        clf = self._clf()
        seen = []
        real = clf._score_labels
        clf._score_labels = lambda p, ids, lens, li, ll: (
            seen.append(ids.shape), real(p, ids, lens, li, ll)
        )[1]
        clf.classify_batch(["la la", "short one"])
        assert seen and seen[0][1] < 512
        # width is a power of two >= the longest prompt
        assert seen[0][1] & (seen[0][1] - 1) == 0

    def test_trimming_preserves_labels(self):
        clf = self._clf()
        texts = ["short", "mid length lyric with several words " * 2,
                 "long " + "word " * 150, ""]
        trimmed = clf.classify_batch(texts)
        clf._trim_prompt_pad = lambda ids, lens: (ids, lens)  # disable
        flat = clf.classify_batch(texts)
        assert trimmed == flat

    def test_trimming_preserves_generations(self):
        clf = self._clf()
        prompts = ["say something nice", "la"]
        trimmed = clf.generate_batch(prompts, max_new_tokens=8)
        clf._trim_prompt_pad = lambda ids, lens: (ids, lens)
        flat = clf.generate_batch(prompts, max_new_tokens=8)
        assert trimmed == flat

    def test_long_prompt_not_cut(self):
        clf = self._clf()
        ids, lens = clf._encode_prompts(["word " * 600])  # > 512 tokens
        assert ids.shape[1] == 512
        assert int(lens[0]) == 512
