"""Continuous-batching decode runtime: equivalence, isolation, resilience.

Contract families (ISSUE 10):

* **equivalence** — the slot runtime's greedy tokens are byte-identical
  to the static ``generate_batch`` scan for the same prompts, at
  ``n_slots`` ∈ {2, 8}, under randomized arrival order, and with the
  early-exit static scan on or off; zero-shot labels agree between the
  static and continuous classify paths.
* **slots** — reuse across more requests than slots never leaks one
  sequence's KV into another; per-request budgets truncate exactly.
* **resilience** — a poison prompt fails alone while co-resident slots
  finish; a persistent decode fault fails the in-flight requests with
  structured errors and the scheduler keeps serving; a stalled decode
  dispatch trips the watchdog with taxonomy ``decode_stall``; zero
  retraces of the fixed compiled programs across a whole workload.

Paged-cache-specific contracts (page pool, radix tree, prefix sharing)
live in tests/test_kv_pages.py; this file exercises the default (paged)
backend through the same scheduler API it always had.
"""

import json
import random
import time

import pytest

from music_analyst_tpu.serving.batcher import (
    resolve_prefill_chunk,
    resolve_slots,
)


@pytest.fixture(scope="module")
def clf():
    from music_analyst_tpu.models.llama import (
        LlamaConfig,
        LlamaZeroShotClassifier,
    )

    return LlamaZeroShotClassifier(
        config=LlamaConfig.tiny(), max_prompt_len=64
    )


PROMPTS = [
    "golden sunshine on the river",
    "rain",
    "shadows fall across the empty street tonight",
    "my heart beats a broken drum",
    "la la la la",
    "winter wind and summer fire",
    "ok",
    "the long road home winds past the silver lake and over the hills",
]


def _scheduler(clf, **kwargs):
    from music_analyst_tpu.serving.decode_loop import ContinuousScheduler

    kwargs.setdefault("prefill_chunk", 16)
    kwargs.setdefault("prompt_region", 64)
    kwargs.setdefault("max_new_tokens", 8)
    kwargs.setdefault("max_queue", 64)
    return ContinuousScheduler(clf, **kwargs)


def _run(sched, prompts, budgets=None):
    budgets = budgets or [sched.plan.max_new] * len(prompts)
    reqs = [
        sched.submit(i, prompt, max_new_tokens=budget)
        for i, (prompt, budget) in enumerate(zip(prompts, budgets))
    ]
    sched.run_until_idle()
    out = []
    for req in reqs:
        resp = req.response or {}
        assert resp.get("ok"), resp
        out.append(resp)
    return out


# -------------------------------------------------------------- geometry


def test_resolve_slots_and_prefill_chunk(monkeypatch):
    assert resolve_slots(None) == 8
    assert resolve_slots(5) == 8  # rounded up to a power of two
    monkeypatch.setenv("MUSICAAL_SERVE_SLOTS", "4")
    assert resolve_slots(None) == 4
    monkeypatch.setenv("MUSICAAL_SERVE_SLOTS", "junk")
    assert resolve_slots(None) == 8  # malformed env falls back
    assert resolve_prefill_chunk(None) == 64
    monkeypatch.setenv("MUSICAAL_SERVE_PREFILL_CHUNK", "32")
    assert resolve_prefill_chunk(None) == 32
    with pytest.raises(ValueError):
        resolve_slots("junk")  # explicit value is a usage error


def test_slot_plan_validation():
    from music_analyst_tpu.ops.kv_slots import SlotPlan

    plan = SlotPlan(n_slots=4, prefill_chunk=16, prompt_region=64,
                    max_new=8, decode_span=4)
    assert plan.max_total == 72
    with pytest.raises(ValueError):
        SlotPlan(n_slots=3, prefill_chunk=16, prompt_region=64,
                 max_new=8, decode_span=4)
    with pytest.raises(ValueError):
        SlotPlan(n_slots=4, prefill_chunk=24, prompt_region=64,
                 max_new=8, decode_span=4)
    with pytest.raises(ValueError):
        SlotPlan(n_slots=4, prefill_chunk=16, prompt_region=64,
                 max_new=0, decode_span=4)


def test_runtime_rejects_geometry_beyond_max_seq_len(clf):
    # prompt_region clamps to max_prompt_len, so the overflow has to come
    # from the decode budget: 64 + 2048 > tiny's max_seq_len of 2048.
    with pytest.raises(ValueError):
        clf.slot_runtime(n_slots=2, prefill_chunk=64,
                         prompt_region=64, max_new_tokens=2048)


# ----------------------------------------------------------- equivalence


@pytest.mark.parametrize("n_slots", [2, 8])
def test_continuous_matches_static_greedy(clf, n_slots):
    """Byte-identical greedy text per prompt, randomized arrival order."""
    static = clf.generate_batch(PROMPTS, max_new_tokens=8)
    sched = _scheduler(clf, n_slots=n_slots)
    order = list(range(len(PROMPTS)))
    random.Random(n_slots).shuffle(order)
    reqs = {
        i: sched.submit(i, PROMPTS[i], max_new_tokens=8) for i in order
    }
    sched.run_until_idle()
    for i, want in enumerate(static):
        resp = reqs[i].response
        assert resp["ok"], resp
        assert resp["text"] == want, f"prompt {i} diverged"
    assert sched.stats()["completed"] == len(PROMPTS)


def test_generate_batch_continuous_wrapper_matches_static(clf):
    static = clf.generate_batch(PROMPTS, max_new_tokens=6)
    cont = clf.generate_batch_continuous(
        PROMPTS, max_new_tokens=6, n_slots=2, prefill_chunk=16
    )
    assert cont == static


def test_early_exit_scan_matches_full_scan(clf):
    full = clf.generate_batch(PROMPTS, max_new_tokens=8, early_exit=False)
    early = clf.generate_batch(PROMPTS, max_new_tokens=8, early_exit=True)
    assert early == full


def test_zero_shot_labels_agree_static_vs_continuous(clf, monkeypatch):
    texts = ["I love this sunny day", "so sad and lonely", "whatever"]
    static = clf.classify_batch_by_generation(texts)
    monkeypatch.setattr(clf, "continuous_slots", 2)
    continuous = clf.classify_batch_by_generation(texts)
    assert continuous == static


# ----------------------------------------------------------------- slots


def test_slot_reuse_is_isolated(clf):
    """3× more requests than slots, twice in different interleavings:
    outputs depend only on the prompt, never on which slot served it or
    what lived there before."""
    prompts = [PROMPTS[i % len(PROMPTS)] for i in range(12)]
    sched = _scheduler(clf, n_slots=4)
    first = [r["text"] for r in _run(sched, prompts)]
    second = [r["text"] for r in _run(sched, list(reversed(prompts)))]
    assert first == list(reversed(second))
    # Identical prompts through different slots give identical text.
    assert first[0] == first[8] and first[3] == first[11]


def test_budgets_truncate_per_request(clf):
    sched = _scheduler(clf, n_slots=2)
    full = _run(sched, PROMPTS[:4])
    short = _run(sched, PROMPTS[:4], budgets=[2, 8, 1, 3])
    for resp, budget in zip(short, [2, 8, 1, 3]):
        assert resp["tokens"] <= budget
    # The row whose budget equals the full budget is byte-identical.
    assert short[1]["text"] == full[1]["text"]


def test_zero_retraces_across_workload(clf):
    sched = _scheduler(clf, n_slots=4)
    sched.warmup()
    before = sched.runtime.compiled_variants()
    _run(sched, [PROMPTS[i % len(PROMPTS)] for i in range(10)],
         budgets=[1 + i % 7 for i in range(10)])
    assert sched.runtime.compiled_variants() == before
    assert sched.stats()["completed"] == 10


# ------------------------------------------------------------ resilience


def test_poison_prompt_fails_alone(clf, monkeypatch):
    from music_analyst_tpu.resilience.faults import InjectedFatal
    from music_analyst_tpu.serving import decode_loop

    sched = _scheduler(clf, n_slots=2)
    clean = [r["text"] for r in _run(sched, PROMPTS[:4])]

    real = decode_loop.ContinuousScheduler._device_prefill

    def poisoned(self, idx, slot):
        if "POISON" in slot.req.text:
            raise InjectedFatal("decode.step", 0)
        return real(self, idx, slot)

    monkeypatch.setattr(
        decode_loop.ContinuousScheduler, "_device_prefill", poisoned
    )
    prompts = PROMPTS[:2] + ["POISON pill"] + PROMPTS[2:4]
    reqs = [sched.submit(i, p) for i, p in enumerate(prompts)]
    sched.run_until_idle()
    responses = [r.response for r in reqs]
    assert not responses[2]["ok"]
    assert responses[2]["error"]["kind"] == "request_failed"
    survivors = [responses[i]["text"] for i in (0, 1, 3, 4)]
    assert survivors == clean  # co-resident slots finished, byte-equal


def test_persistent_decode_failure_is_structured_and_survivable(clf):
    from music_analyst_tpu.resilience import configure_faults

    sched = _scheduler(clf, n_slots=2)
    configure_faults("decode.step:fatal")
    try:
        reqs = [sched.submit(i, p) for i, p in enumerate(PROMPTS[:2])]
        sched.run_until_idle()
        for req in reqs:
            assert not req.response["ok"]
            assert req.response["error"]["kind"] == "request_failed"
    finally:
        configure_faults(None)
    # The scheduler survives: the very next workload succeeds.
    texts = [r["text"] for r in _run(sched, PROMPTS[:2])]
    assert texts == clf.generate_batch(PROMPTS[:2], max_new_tokens=8)
    assert sched.stats()["failed"] == 2


def test_transient_decode_fault_is_retried(clf):
    from music_analyst_tpu.resilience import (
        configure_faults,
        reset_retry_stats,
        retry_stats,
    )

    sched = _scheduler(clf, n_slots=2)
    reset_retry_stats()
    configure_faults("decode.step:error@1")
    try:
        out = _run(sched, PROMPTS[:2])
    finally:
        configure_faults(None)
    assert all(r["ok"] for r in out)
    assert retry_stats()["decode.step"]["retries"] >= 1


def test_decode_stall_trips_watchdog(clf):
    from music_analyst_tpu.observability.watchdog import (
        start_watchdog,
        stop_watchdog,
    )
    from music_analyst_tpu.resilience import configure_faults

    wd = start_watchdog(0.3)
    configure_faults("decode.step:delay=1s@1")
    try:
        out = _run(sched := _scheduler(clf, n_slots=2), PROMPTS[:1])
    finally:
        configure_faults(None)
        stop_watchdog()
    assert out[0]["ok"]
    assert any(t["taxonomy"] == "decode_stall" for t in wd.trips), wd.trips
    assert sched.stats()["completed"] == 1


def test_decode_stall_classifies_in_report():
    from music_analyst_tpu.observability.report import classify_error

    assert classify_error("watchdog: decode_stall in decode.dispatch") == \
        "decode_stall"


# ------------------------------------------------- admission + protocol


def test_admission_sheds_queue_full_and_draining(clf):
    # Distinct texts: identical in-flight generates would fold at the
    # dedup edge (tests/test_speculative.py) instead of ever queueing.
    sched = _scheduler(clf, n_slots=2, max_queue=2)
    blocked = [
        sched.submit(i, f"text {i}", max_new_tokens=1) for i in range(3)
    ]
    shed = blocked[2]
    assert shed.done and shed.response["error"]["kind"] == "queue_full"
    sched.run_until_idle()
    assert all(b.response["ok"] for b in blocked[:2])
    sched.drain()
    late = sched.submit("late", "text")
    assert late.response["error"]["kind"] == "draining"
    assert sched.stats()["shed"] == 2


def test_server_stats_and_generate_op(clf):
    """In-process stdio server: a generate request between two sentiment
    requests answers in order, and `stats` exposes the decode gauges."""
    import io

    from music_analyst_tpu.serving.batcher import DynamicBatcher
    from music_analyst_tpu.serving.server import SentimentServer, build_ops

    sched = _scheduler(clf, n_slots=2).start()
    batcher = DynamicBatcher(
        build_ops(clf), max_batch=2, max_wait_ms=2.0, max_queue=16
    ).start()
    server = SentimentServer(batcher, None, mode="stdio", decode=sched)
    lines = [
        {"id": "a", "op": "sentiment", "text": "happy joy"},
        {"id": "b", "op": "generate", "text": "sunny", "max_new_tokens": 3},
        {"id": "c", "op": "sentiment", "text": "sad rain"},
        {"id": "d", "op": "stats"},
        {"id": "e", "op": "generate", "text": "x", "max_new_tokens": "no"},
    ]
    wfile = io.StringIO()
    rfile = io.StringIO("".join(json.dumps(l) + "\n" for l in lines))
    server.handle_stream(rfile, wfile, drain_on_eof=True)
    replies = [json.loads(l) for l in wfile.getvalue().splitlines()]
    assert [r["id"] for r in replies] == ["a", "b", "c", "d", "e"]
    gen = replies[1]
    assert gen["ok"] and gen["op"] == "generate"
    assert "text" in gen and "label" in gen and gen["tokens"] <= 3
    stats = replies[3]["stats"]["decode"]
    for key in ("active_slots", "free_slots", "prefill_backlog",
                "tokens_generated", "ttft", "tpot", "slot_occupancy_hist"):
        assert key in stats, key
    assert replies[4]["error"]["kind"] == "bad_request"


def test_generate_without_slot_runtime_is_bad_request():
    import io

    from music_analyst_tpu.serving.batcher import DynamicBatcher
    from music_analyst_tpu.serving.server import SentimentServer

    batcher = DynamicBatcher(
        {"echo": lambda texts: [{"text": t} for t in texts]},
        max_batch=2, max_wait_ms=2.0, max_queue=4,
    ).start()
    server = SentimentServer(batcher, None, mode="stdio", decode=None)
    wfile = io.StringIO()
    rfile = io.StringIO(
        json.dumps({"id": 1, "op": "generate", "text": "hi"}) + "\n"
    )
    server.handle_stream(rfile, wfile, drain_on_eof=True)
    reply = json.loads(wfile.getvalue())
    assert not reply["ok"]
    assert reply["error"]["kind"] == "bad_request"


def test_threaded_scheduler_settles_and_drains(clf):
    sched = _scheduler(clf, n_slots=2).start()
    reqs = [sched.submit(i, p, max_new_tokens=4)
            for i, p in enumerate(PROMPTS[:4])]
    for req in reqs:
        assert req.wait(timeout=60.0), "request never settled"
        assert req.response["ok"]
    sched.drain()
    assert sched.stats()["completed"] == 4


def test_ttft_tpot_quantiles_populated(clf):
    sched = _scheduler(clf, n_slots=2)
    _run(sched, PROMPTS[:4])
    stats = sched.stats()
    assert stats["ttft"]["count"] == 4
    assert stats["ttft"]["p50_s"] > 0
    assert stats["tpot"]["count"] >= 1
    assert stats["tokens_per_s"] > 0


def test_decode_warmup_compiles_before_first_request(clf):
    # Default backend is the paged cache: four fixed programs (prefill,
    # decode, free, copy-on-write).  page_size=0 pins PR 10's monolithic
    # slot cache and its five (prefill, decode, free, plus the
    # checkpoint snapshot/restore pair).
    sched = _scheduler(clf, n_slots=2)
    record = sched.warmup()
    assert record["kv_backend"] == "paged"
    assert record["programs"] == 4 and record["seconds"] > 0
    variants = sched.runtime.compiled_variants()
    _run(sched, PROMPTS[:2])
    assert sched.runtime.compiled_variants() == variants

    mono = _scheduler(clf, n_slots=2, page_size=0)
    record = mono.warmup()
    assert record["kv_backend"] == "slots"
    assert record["programs"] == 5
