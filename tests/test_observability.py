"""Flight recorder + heartbeat watchdog + telemetry-report (PR 4).

The acceptance anchors (ISSUE 4):

* a deliberately hung prefetch stage trips the watchdog and dumps a
  parseable ``flight_record.json`` with thread stacks, the last ≥64
  telemetry events, and taxonomy ``stage_stall``;
* SIGTERM during a sentiment run leaves a record (and the process still
  dies by SIGTERM — the handler chains to the default disposition);
* ``telemetry-report`` over the committed ``BENCH_r01..r05.json``
  classifies r05 as ``tunnel_dead``;
* ``bench.py``'s terminal error line carries ``error_kind`` (and a
  ``flight_record`` path when a child left one) without disturbing the
  one-JSON-line / exact-salvage-passthrough contracts that
  ``tests/test_bench_budget.py`` pins.

Everything runs on the CPU-emulated mesh (conftest forces it).
"""

from __future__ import annotations

import json
import os
import pathlib
import subprocess
import sys
import textwrap
import time

import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import bench  # noqa: E402

from music_analyst_tpu.observability.flight import FlightRecorder  # noqa: E402
from music_analyst_tpu.observability.report import (  # noqa: E402
    build_report,
    classify_error,
    load_run,
    run_telemetry_report,
)
from music_analyst_tpu.observability.watchdog import (  # noqa: E402
    HeartbeatWatchdog,
    resolve_watchdog_timeout,
    start_watchdog,
    stop_watchdog,
)
from music_analyst_tpu.telemetry import configure, get_telemetry  # noqa: E402

REPO_ROOT = pathlib.Path(__file__).parent.parent


@pytest.fixture(autouse=True)
def _clean_observability_state():
    """Each test starts with no watchdog and a quiescent recorder."""
    stop_watchdog()
    yield
    stop_watchdog()
    from music_analyst_tpu.observability.flight import get_flight_recorder

    get_flight_recorder().uninstall()
    configure(enabled=True, directory=None)


# ------------------------------------------------------------------ flight


def test_flight_ring_is_bounded_and_taps_survive_reconfigure():
    tel = configure(enabled=True, directory=None)
    rec = FlightRecorder(capacity=16)
    tel.add_tap(rec.record)
    try:
        for i in range(40):
            tel.event("filler", i=i)
        events = rec.events()
        assert len(events) == 16
        assert events[-1]["attrs"]["i"] == 39  # newest kept, oldest dropped
        # configure() resets run state — the tap must keep recording.
        tel = configure(enabled=True, directory=None)
        tel.event("after_reset")
        assert rec.events()[-1]["name"] == "after_reset"
    finally:
        tel.remove_tap(rec.record)


def test_flight_dump_writes_parseable_record(tmp_path):
    tel = configure(enabled=True, directory=None)
    rec = FlightRecorder()
    tel.add_tap(rec.record)
    try:
        tel.count("songs", 7)
        for i in range(5):
            tel.event("warm", i=i)
        path = rec.dump(
            reason="unit_test", taxonomy="host_oom", detail="synthetic",
            directory=str(tmp_path),
        )
    finally:
        tel.remove_tap(rec.record)
    assert path == str(tmp_path / "flight_record.json")
    with open(path, encoding="utf-8") as fh:
        record = json.load(fh)
    assert record["schema"] == 1
    assert record["reason"] == "unit_test"
    assert record["taxonomy"] == "host_oom"
    assert record["counters"]["songs"] == 7
    assert [e["name"] for e in record["events"][-5:]] == ["warm"] * 5
    # faulthandler stacks: at least this very test frame is visible.
    assert "thread_stacks" in record and record["thread_stacks"]
    assert "test_observability" in record["thread_stacks"]
    assert record["vitals"]["pid"] == os.getpid()
    assert rec.dump_count == 1 and rec.last_dump_path == path


def test_flight_install_is_idempotent_and_uninstalls():
    rec = FlightRecorder()
    rec.install(signals=False, excepthook=False)
    rec.install(signals=False, excepthook=False)
    tel = get_telemetry()
    assert tel._taps.count(rec.record) == 1
    rec.uninstall()
    assert rec.record not in tel._taps


# ---------------------------------------------------------------- watchdog


def test_watchdog_stage_hang_trips_and_dumps(tmp_path, monkeypatch):
    """THE acceptance test: a hung prefetch stage ⇒ flight_record.json
    with thread stacks, ≥64 telemetry events, and taxonomy stage_stall."""
    from music_analyst_tpu.observability.flight import (
        install_flight_recorder,
    )
    from music_analyst_tpu.runtime import PrefetchPipeline, Stage

    monkeypatch.setenv("MUSICAAL_FLIGHT_RECORD_DIR", str(tmp_path))
    tel = configure(enabled=True, directory=None)
    install_flight_recorder(signals=False, excepthook=False)
    # Enough history that the dump proves the ring really holds the tail.
    for i in range(80):
        tel.event("preamble", i=i)
    wd = start_watchdog(0.3)
    assert wd is not None

    def hanging_stage(item):
        deadline = time.time() + 15.0
        while not wd.trips and time.time() < deadline:
            time.sleep(0.02)
        return item

    pipe = PrefetchPipeline(
        [Stage("tokenize", hanging_stage)], depth=1, name="bench"
    )
    results = list(pipe.run([1]))
    stop_watchdog()
    assert results == [1]
    assert wd.trips, "watchdog never tripped on the hung stage"
    trip = wd.trips[0]
    assert trip["taxonomy"] == "stage_stall"
    assert trip["task"] == "bench.tokenize"

    record_path = tmp_path / "flight_record.json"
    assert record_path.exists()
    with open(record_path, encoding="utf-8") as fh:
        record = json.load(fh)
    assert record["reason"] == "watchdog"
    assert record["taxonomy"] == "stage_stall"
    assert len(record["events"]) >= 64
    # The stacks must point at the actual hung frame.
    assert "hanging_stage" in record["thread_stacks"]
    assert record["watchdog"]["trips"][0]["task"] == "bench.tokenize"


def test_watchdog_beat_rearms_and_scope_exit_unregisters():
    wd = HeartbeatWatchdog(timeout_s=0.2, dump_flight_record=False).start()
    try:
        with wd.watch("steady", kind="host"):
            for _ in range(6):
                time.sleep(0.1)
                wd.beat("steady")
        assert wd.trips == []  # beats kept it alive past 3 timeouts
        with wd.watch("silent", kind="probe"):
            time.sleep(0.6)
        assert [t["taxonomy"] for t in wd.trips] == ["tunnel_dead"]
        time.sleep(0.4)  # scope exited: no further trips accumulate
        assert len(wd.trips) == 1
    finally:
        wd.stop()


def test_watchdog_noop_when_disabled():
    from music_analyst_tpu.observability.watchdog import beat, watch

    assert start_watchdog(0) is None  # 0 = disabled
    with watch("anything", kind="device") as task:
        assert task is None
        beat("anything")  # must not raise


def test_resolve_watchdog_timeout(monkeypatch):
    monkeypatch.delenv("MUSICAAL_WATCHDOG_S", raising=False)
    assert resolve_watchdog_timeout() == 0.0
    assert resolve_watchdog_timeout(default=120.0) == 120.0
    assert resolve_watchdog_timeout("2.5") == 2.5  # explicit flag wins
    with pytest.raises(ValueError):
        resolve_watchdog_timeout("2min")
    with pytest.raises(ValueError):
        resolve_watchdog_timeout(-1)
    monkeypatch.setenv("MUSICAAL_WATCHDOG_S", "45")
    assert resolve_watchdog_timeout() == 45.0
    assert resolve_watchdog_timeout(10) == 10.0  # flag beats env
    monkeypatch.setenv("MUSICAAL_WATCHDOG_S", "0")
    assert resolve_watchdog_timeout(default=120.0) == 0.0  # env 0 disables
    monkeypatch.setenv("MUSICAAL_WATCHDOG_S", "soon")
    assert resolve_watchdog_timeout(default=7.0) == 7.0  # malformed → default


def test_manifest_carries_observability_section(tmp_path):
    tel = configure(enabled=True, directory=str(tmp_path))
    start_watchdog(30.0)
    with tel.run_scope("persong", str(tmp_path)):
        pass
    stop_watchdog()
    with open(tmp_path / "run_manifest.json", encoding="utf-8") as fh:
        manifest = json.load(fh)
    assert manifest["observability"]["watchdog"]["timeout_s"] == 30.0


# ----------------------------------------------------------------- SIGTERM


def test_sigterm_during_sentiment_run_leaves_record(tmp_path):
    """SIGTERM mid-run: the handler dumps flight_record.json and then
    chains to the default disposition, so the process still dies BY
    SIGTERM (the parent's view of the exit status is unchanged)."""
    fixture = REPO_ROOT / "tests" / "fixtures" / "mini_songs.csv"
    script = textwrap.dedent(
        """
        import os, signal, threading, time
        from music_analyst_tpu.observability import install_flight_recorder
        from music_analyst_tpu.engines.sentiment import run_sentiment

        install_flight_recorder()

        class SlowBackend:
            name = "slow-mock"
            def classify_batch(self, texts):
                time.sleep(30)
                return ["Neutral"] * len(texts)

        threading.Timer(
            1.0, lambda: os.kill(os.getpid(), signal.SIGTERM)
        ).start()
        run_sentiment(
            %r, output_dir=%r, quiet=True, batch_size=2,
            backend=SlowBackend(), prefetch_depth=1,
        )
        """
        % (str(fixture), str(tmp_path / "out"))
    )
    env = dict(os.environ)
    env["MUSICAAL_FLIGHT_RECORD_DIR"] = str(tmp_path)
    proc = subprocess.run(
        [sys.executable, "-c", script],
        capture_output=True, text=True, timeout=60,
        cwd=str(REPO_ROOT), env=env,
    )
    assert proc.returncode == -15, (proc.returncode, proc.stderr[-500:])
    record_path = tmp_path / "flight_record.json"
    assert record_path.exists(), proc.stderr[-500:]
    with open(record_path, encoding="utf-8") as fh:
        record = json.load(fh)
    assert record["reason"] == "signal:SIGTERM"
    assert record["thread_stacks"]


# ---------------------------------------------------------- classification


def test_classify_error_patterns():
    assert classify_error(
        "device probe timed out after 40s (tunnel dead?)") == "tunnel_dead"
    assert classify_error(
        "attempt timed out after 155s (tunnel hang?)") == "tunnel_dead"
    assert classify_error(
        "RuntimeError: Unable to initialize backend 'axon': UNAVAILABLE"
    ) == "tunnel_dead"
    assert classify_error("MemoryError") == "host_oom"
    assert classify_error("compile timed out") == "compile_hang"
    assert classify_error("", rc=124) == "harness_killed"
    assert classify_error("deadline gone: no attempt fit inside the "
                          "deadline") == "deadline_expired"
    assert classify_error("step timed out") == "attempt_timeout"
    assert classify_error("weird explosion") == "unknown_error"
    assert classify_error("", rc=0) is None
    assert classify_error(None) is None


def test_report_classifies_committed_bench_captures():
    sources = [str(REPO_ROOT / f"BENCH_r0{i}.json") for i in range(1, 6)]
    records = [load_run(s) for s in sources]
    assert all(r is not None for r in records)
    by_label = {r["label"]: r for r in records}
    assert by_label["BENCH_r01"]["error_kind"] == "tunnel_dead"
    assert by_label["BENCH_r02"]["ok"] is True
    assert by_label["BENCH_r03"]["error_kind"] == "harness_killed"
    assert by_label["BENCH_r04"]["error_kind"] == "tunnel_dead"
    # THE acceptance anchor: r05's probe-timeout string ⇒ tunnel_dead.
    assert by_label["BENCH_r05"]["error_kind"] == "tunnel_dead"
    report = build_report(records)
    assert report["taxonomy_histogram"]["tunnel_dead"] == 3
    assert report["newest"] == {
        "label": "BENCH_r05", "ok": False, "error_kind": "tunnel_dead",
    }


def test_telemetry_report_over_synthetic_runs(tmp_path, capsys):
    """Two synthetic telemetry run dirs + a failed BENCH capture render
    the taxonomy histogram; exit 1 because the newest run failed."""
    # Run A: healthy manifest with a pipeline stall breakdown + recompiles.
    run_a = tmp_path / "run_a"
    run_a.mkdir()
    (run_a / "run_manifest.json").write_text(json.dumps({
        "schema": 1, "engine": "sentiment", "wall_seconds": 12.5,
        "compile": {"count": 3, "seconds": 4.2},
        "counters": {"profiling.recompiles": 2},
        "pipeline": {"pipeline": {"depth": 2, "stages": [
            {"stage": "tokenize", "items": 10, "work_s": 1.0,
             "stall_s": 0.4, "backpressure_s": 0.0, "queue_depth_max": 2},
        ], "max_queue_depth": 2}},
    }))
    (run_a / "telemetry.jsonl").write_text(
        "\n".join(json.dumps({"type": "event", "name": "x"})
                  for _ in range(5)) + "\n"
    )
    # Run B: a watchdog trip in the JSONL and a flight record on disk.
    run_b = tmp_path / "run_b"
    run_b.mkdir()
    (run_b / "telemetry.jsonl").write_text(json.dumps({
        "type": "event", "name": "watchdog_trip",
        "attrs": {"task": "bench.h2d", "taxonomy": "stage_stall"},
    }) + "\n")
    (run_b / "flight_record.json").write_text(json.dumps({
        "schema": 1, "reason": "watchdog", "taxonomy": "stage_stall",
        "detail": "bench.h2d silent for 2s", "events": [],
    }))
    rc = run_telemetry_report([
        str(run_a), str(run_b), str(REPO_ROOT / "BENCH_r05.json"),
    ])
    out = capsys.readouterr().out
    assert rc == 1  # newest (r05) failed
    assert "error taxonomy:" in out
    assert "stage_stall" in out and "tunnel_dead" in out
    assert "pipeline stalls" in out and "tokenize" in out
    assert "recompiles" in out and "run_a: 2" in out
    assert "FAILED (tunnel_dead)" in out


def test_telemetry_report_exit_codes(tmp_path, capsys):
    assert run_telemetry_report([str(tmp_path / "nope.json")]) == 2
    capsys.readouterr()
    ok_line = tmp_path / "ok.json"
    ok_line.write_text(json.dumps({
        "metric": bench.METRIC, "value": 100.0, "unit": "songs/sec",
    }))
    assert run_telemetry_report([str(ok_line)]) == 0
    capsys.readouterr()


def test_cli_telemetry_report_subcommand(capsys):
    from music_analyst_tpu.cli.main import main

    rc = main(["telemetry-report", "--json",
               str(REPO_ROOT / "BENCH_r01.json"),
               str(REPO_ROOT / "BENCH_r02.json")])
    out = capsys.readouterr().out.strip().splitlines()
    assert rc == 0  # newest (r02) is the healthy capture
    report = json.loads(out[-1])
    assert report["taxonomy_histogram"] == {"tunnel_dead": 1}


# ----------------------------------------------------------------- bench


def test_bench_error_line_carries_error_kind(capsys, monkeypatch):
    """Probe-timeout failure: the terminal line gains error_kind (and no
    flight_record key when no record dir is configured)."""
    monkeypatch.delenv("MUSICAAL_FLIGHT_RECORD_DIR", raising=False)
    clock_now = [0.0]

    def clock():
        return clock_now[0]

    def sleep(s):
        clock_now[0] += s

    def hang_run(cmd, capture_output, text, timeout):
        clock_now[0] += timeout
        raise subprocess.TimeoutExpired(cmd, timeout)

    rc = bench._run_parent(4, bench._DEFAULT_DEADLINE_S,
                           run=hang_run, sleep=sleep, clock=clock)
    assert rc == 0
    out = capsys.readouterr().out.strip().splitlines()
    assert len(out) == 1
    line = json.loads(out[0])
    assert line["error_kind"] == "tunnel_dead"
    assert "flight_record" not in line


def test_bench_prefers_child_flight_record_taxonomy(
    capsys, monkeypatch, tmp_path
):
    """A child that dumps a classified flight record before dying wins
    over string classification of its error tail."""
    monkeypatch.setenv("MUSICAAL_FLIGHT_RECORD_DIR", str(tmp_path))
    record_path = tmp_path / "flight_record.json"
    clock_now = [0.0]

    def clock():
        return clock_now[0]

    def sleep(s):
        clock_now[0] += s

    def run(cmd, capture_output, text, timeout):
        if "--probe" in cmd:
            clock_now[0] += 3.0
            return subprocess.CompletedProcess(
                cmd, returncode=0, stdout="1\n", stderr="")
        # The measurement child's watchdog classified a compile hang and
        # dumped the record just before the parent's timeout fired.
        record_path.write_text(json.dumps({
            "schema": 1, "reason": "watchdog", "taxonomy": "compile_hang",
        }))
        clock_now[0] += timeout
        raise subprocess.TimeoutExpired(cmd, timeout)

    rc = bench._run_parent(1, bench._DEFAULT_DEADLINE_S,
                           run=run, sleep=sleep, clock=clock)
    assert rc == 0
    line = json.loads(capsys.readouterr().out.strip())
    assert line["error_kind"] == "compile_hang"
    assert line["flight_record"] == str(record_path)


def test_bench_deadline_expiry_dumps_parent_record(
    capsys, monkeypatch, tmp_path
):
    """No attempt fits: the parent itself leaves a flight record stamped
    deadline_expired, so even 'nothing ran' is a diagnosable artifact."""
    monkeypatch.setenv("MUSICAAL_FLIGHT_RECORD_DIR", str(tmp_path))

    def never_run(cmd, capture_output, text, timeout):  # pragma: no cover
        raise AssertionError("no child should launch")

    rc = bench._run_parent(4, 5.0, run=never_run,
                           sleep=lambda s: None, clock=lambda: 0.0)
    assert rc == 0
    line = json.loads(capsys.readouterr().out.strip())
    assert line["error_kind"] == "deadline_expired"
    record = json.loads((tmp_path / "flight_record.json").read_text())
    assert record["reason"] == "bench_deadline"
    assert record["taxonomy"] == "deadline_expired"
    assert line["flight_record"] == str(tmp_path / "flight_record.json")


def test_nothing_in_package_imports_removed_shim():
    """Satellite: metrics/tracing.py is gone and nothing references it
    (the runtime-pipeline suite has the import-level twin of this)."""
    pkg_root = REPO_ROOT / "music_analyst_tpu"
    assert not (pkg_root / "metrics" / "tracing.py").exists()
    offenders = [
        str(p) for p in pkg_root.rglob("*.py")
        if "metrics.tracing" in p.read_text(encoding="utf-8")
    ]
    assert offenders == []
