"""Fused paged-attention decode kernel (ops/paged_attention.py).

Three layers of coverage for the ISSUE 18 tentpole:

* kernel vs. oracle — both Pallas bodies (exact batched and the
  page-streaming online-softmax TPU body, run under interpret) against
  the naive f32 reference, across page sizes {8, 16}, odd valid
  lengths, and table rows parked on the trash page;
* bitwise contract — the exact body must reproduce
  ``models/layers.dot_product_attention`` over the gathered view BIT FOR
  BIT (a 1-ulp logit difference flips greedy argmax near-ties, which is
  how the paged scheduler's byte-identity guarantee would silently rot);
* int8 KV — per-(page, row) symmetric quantization round-trips, bounds
  its error, survives the sharded end-to-end path with matching labels,
  reports its pool-byte savings, and degrades byte-identically when the
  ``kv_quant.dequant`` fault site fires.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from music_analyst_tpu.models.layers import dot_product_attention
from music_analyst_tpu.ops.paged_attention import (
    PagedAttnView,
    paged_attention,
    paged_attention_reference,
)
from music_analyst_tpu.ops.quant import dequantize_kv_page, quantize_kv_page
from music_analyst_tpu.serving.batcher import resolve_kv_quant
from music_analyst_tpu.utils.labels import normalise_label


# ---------------------------------------------------------------------------
# Random paged state
# ---------------------------------------------------------------------------


def _random_case(seed, page_size, *, n=3, H=4, n_kv=2, D=8, pps=4,
                 total=None, trash_garbage=0.0, quantized=False):
    """Random pool/table/mask with odd per-slot lengths and trash rows.

    Slot 0's final table entry points at the trash page (its valid
    length keeps it fully masked), mirroring a slot whose budget never
    reaches its last decode page.  ``trash_garbage`` fills the trash
    page with that constant so isolation is observable.
    """
    rng = np.random.RandomState(seed)
    P = page_size
    span = pps * P
    total = span if total is None else total
    n_pages = n * pps
    table = rng.permutation(n_pages).reshape(n, pps).astype(np.int32)
    table[0, -1] = n_pages  # trash page
    # Odd lengths, capped so slot 0 never reads its trash-backed page.
    lengths = np.array(
        [rng.randint(0, min(total, span - P) // 2) * 2 + 1
         for _ in range(n)],
        dtype=np.int32,
    )
    mask = np.arange(total)[None, :] < lengths[:, None]
    kv_shape = (n_pages + 1, P, n_kv, D)
    keys = rng.standard_normal(kv_shape).astype(np.float32)
    values = rng.standard_normal(kv_shape).astype(np.float32)
    keys[n_pages] = trash_garbage
    values[n_pages] = trash_garbage
    q = jnp.asarray(
        rng.standard_normal((n, 1, H, D)), dtype=jnp.bfloat16
    )
    if quantized:
        kq, ks = quantize_kv_page(jnp.asarray(keys))
        vq, vs = quantize_kv_page(jnp.asarray(values))
        pools = dict(key_scale=ks, value_scale=vs)
        kp, vp = kq, vq
    else:
        pools = {}
        kp = jnp.asarray(keys, dtype=jnp.bfloat16)
        vp = jnp.asarray(values, dtype=jnp.bfloat16)
    return dict(
        q=q, key_pages=kp, value_pages=vp,
        table=jnp.asarray(table), mask=jnp.asarray(mask),
        lengths=lengths, f32_keys=keys, f32_values=values, **pools,
    )


def _call(case, **kw):
    return paged_attention(
        case["q"], case["key_pages"], case["value_pages"],
        case["table"], case["mask"],
        key_scale=case.get("key_scale"),
        value_scale=case.get("value_scale"),
        interpret=True, **kw,
    )


def _oracle(case):
    return np.asarray(paged_attention_reference(
        case["q"], case["key_pages"], case["value_pages"],
        case["table"], case["mask"],
        key_scale=case.get("key_scale"),
        value_scale=case.get("value_scale"),
    ))


# ---------------------------------------------------------------------------
# Kernel vs. oracle (both bodies, both page sizes, odd lengths, trash rows)
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("page_size", [8, 16])
@pytest.mark.parametrize("stream", [False, True])
@pytest.mark.parametrize("seed", [0, 1, 2])
def test_kernel_matches_oracle(page_size, stream, seed):
    """Seeded property sweep: fused kernel ≈ naive f32 gather oracle.

    ``total`` deliberately lands off the page grid on odd seeds so the
    exact body's ``[:, :total]`` slice and the streaming body's padded
    mask tail both get exercised.
    """
    total = None if seed % 2 == 0 else page_size * 4 - 5
    case = _random_case(seed, page_size, total=total)
    out = np.asarray(_call(case, stream=stream), dtype=np.float32)
    ref = _oracle(case)
    assert out.shape == ref.shape
    np.testing.assert_allclose(out, ref, atol=0.06, rtol=0.06)


def test_kernel_matches_oracle_hypothesis():
    """Hypothesis variant of the sweep (skips when hypothesis is not
    installed — the seeded sweep above always runs)."""
    pytest.importorskip("hypothesis")
    from hypothesis import given, settings, strategies as st

    @settings(max_examples=20, deadline=None)
    @given(
        seed=st.integers(0, 2 ** 16),
        page_size=st.sampled_from([8, 16]),
        stream=st.booleans(),
    )
    def _property(seed, page_size, stream):
        case = _random_case(seed, page_size)
        out = np.asarray(_call(case, stream=stream), dtype=np.float32)
        np.testing.assert_allclose(out, _oracle(case), atol=0.06, rtol=0.06)

    _property()


@pytest.mark.parametrize("page_size", [8, 16])
def test_exact_body_bitwise_vs_dense(page_size):
    """The exact body IS dense attention over the gathered view, bitwise.

    The decode scan's byte-identity to the monolithic runtime rests on
    this: the kernel may not reassociate a single multiply-add relative
    to ``dot_product_attention`` (a 1-ulp logit drift flips greedy
    argmax near-ties — observed live during ISSUE 18 bring-up when a
    grouped no-repeat einsum replaced the repeat broadcast).
    """
    for seed in range(4):
        case = _random_case(seed, page_size)
        out = np.asarray(_call(case, stream=False))
        n, pps = case["table"].shape
        view = lambda pool: jnp.take(pool, case["table"], axis=0).reshape(
            n, pps * page_size, *pool.shape[2:]
        )
        dense = np.asarray(dot_product_attention(
            case["q"], view(case["key_pages"]), view(case["value_pages"]),
            case["mask"][:, None, None, :],
        ))
        assert out.tobytes() == dense.tobytes()


def test_trash_page_contents_never_leak():
    """Garbage in the trash page (dangling writes from freed slots) must
    not perturb any output lane, in either body."""
    for stream in (False, True):
        clean = _random_case(7, 8, trash_garbage=0.0)
        dirty = _random_case(7, 8, trash_garbage=7777.0)
        a = np.asarray(_call(clean, stream=stream))
        b = np.asarray(_call(dirty, stream=stream))
        assert a.tobytes() == b.tobytes()


def test_geometry_validation():
    case = _random_case(0, 8)
    with pytest.raises(ValueError, match="decode kernel"):
        paged_attention(
            jnp.zeros((3, 2, 4, 8), jnp.bfloat16), case["key_pages"],
            case["value_pages"], case["table"], case["mask"],
            interpret=True,
        )
    with pytest.raises(ValueError, match="passed together"):
        paged_attention(
            case["q"], case["key_pages"], case["value_pages"],
            case["table"], case["mask"],
            key_scale=jnp.ones((25, 8)), interpret=True,
        )


# ---------------------------------------------------------------------------
# PagedAttnView: the KVCache-shaped adapter the decode scan carries
# ---------------------------------------------------------------------------


def test_view_update_lands_in_physical_page():
    case = _random_case(3, 8)
    n = case["table"].shape[0]
    lengths = jnp.asarray(case["lengths"])
    view = PagedAttnView(
        keys=case["key_pages"], values=case["value_pages"],
        key_scale=None, value_scale=None,
        table=case["table"], length=lengths,
        page_size=8, total=case["mask"].shape[-1],
    )
    k_new = jnp.asarray(
        np.random.RandomState(9).standard_normal((n, 1, 2, 8)),
        dtype=jnp.bfloat16,
    )
    new = view.update(k_new, k_new * 2)
    assert np.array_equal(np.asarray(new.length), case["lengths"] + 1)
    table = np.asarray(case["table"])
    for s in range(n):
        off = int(case["lengths"][s])
        phys, r = table[s, off // 8], off % 8
        got = np.asarray(new.keys[phys, r])
        assert got.tobytes() == np.asarray(k_new[s, 0]).tobytes()
    # attend == the plain kernel call on the same state.
    mask = jnp.arange(view.total)[None, :] < (lengths + 1)[:, None]
    out = np.asarray(new.attend(case["q"], mask[:, None, None, :]))
    direct = np.asarray(paged_attention(
        case["q"], new.keys, new.values, new.table, mask, interpret=True,
    ))
    assert out.tobytes() == direct.tobytes()


def test_view_rejects_chunked_writes():
    case = _random_case(0, 8)
    view = PagedAttnView(
        keys=case["key_pages"], values=case["value_pages"],
        key_scale=None, value_scale=None,
        table=case["table"], length=jnp.zeros(3, jnp.int32),
        page_size=8, total=32,
    )
    with pytest.raises(ValueError, match="one decode token"):
        view.update(jnp.zeros((3, 4, 2, 8)), jnp.zeros((3, 4, 2, 8)))


# ---------------------------------------------------------------------------
# int8 KV pages
# ---------------------------------------------------------------------------


def test_quantize_roundtrip_contract():
    """The paged prefill re-scatters boundary pages, so round-trip drift
    must not compound: exact through f32, ≤ ±1 code through the bf16
    compute dtype, and the bf16 round-trip is a fixed point after one
    pass (rescattering the same page again changes nothing)."""
    for seed in (3, 11, 19):
        rng = np.random.RandomState(seed)
        x = jnp.asarray(
            rng.standard_normal((16, 2, 8)) * rng.uniform(0.1, 10),
            dtype=jnp.float32,
        )
        codes, scale = quantize_kv_page(x)
        exact, scale2 = quantize_kv_page(
            dequantize_kv_page(codes, scale, jnp.float32)
        )
        assert np.array_equal(np.asarray(codes), np.asarray(exact))
        np.testing.assert_allclose(
            np.asarray(scale2), np.asarray(scale), rtol=1e-2
        )
        once, s_once = quantize_kv_page(
            dequantize_kv_page(codes, scale, jnp.bfloat16)
        )
        drift = np.abs(
            np.asarray(once, np.int32) - np.asarray(codes, np.int32)
        )
        assert drift.max() <= 1
        twice, _ = quantize_kv_page(
            dequantize_kv_page(once, s_once, jnp.bfloat16)
        )
        assert np.array_equal(np.asarray(once), np.asarray(twice))


@pytest.mark.parametrize("stream", [False, True])
@pytest.mark.parametrize("page_size", [8, 16])
def test_int8_kernel_bounded_error(page_size, stream):
    """int8 path: tight against the int8 oracle (same codes, same
    dequant), bounded against the unquantized f32 truth."""
    for seed in range(3):
        case = _random_case(seed, page_size, quantized=True)
        out = np.asarray(_call(case, stream=stream), dtype=np.float32)
        np.testing.assert_allclose(out, _oracle(case), atol=0.06, rtol=0.06)
        exact = dict(case)
        exact.pop("key_scale"), exact.pop("value_scale")
        exact["key_pages"] = jnp.asarray(case["f32_keys"])
        exact["value_pages"] = jnp.asarray(case["f32_values"])
        err = np.abs(out - _oracle(exact))
        assert err.max() < 0.15
        assert err.mean() < 0.03


def test_int8_view_update_quantizes_row():
    case = _random_case(5, 8, quantized=True)
    n = case["table"].shape[0]
    lengths = jnp.asarray(case["lengths"])
    view = PagedAttnView(
        keys=case["key_pages"], values=case["value_pages"],
        key_scale=case["key_scale"], value_scale=case["value_scale"],
        table=case["table"], length=lengths,
        page_size=8, total=case["mask"].shape[-1],
    )
    k_new = jnp.asarray(
        np.random.RandomState(4).standard_normal((n, 1, 2, 8)),
        dtype=jnp.bfloat16,
    )
    new = view.update(k_new, k_new)
    table = np.asarray(case["table"])
    want_codes, want_scale = quantize_kv_page(k_new[:, 0])
    for s in range(n):
        off = int(case["lengths"][s])
        phys, r = table[s, off // 8], off % 8
        assert np.array_equal(
            np.asarray(new.keys[phys, r]), np.asarray(want_codes[s])
        )
        assert float(new.key_scale[phys, r]) == pytest.approx(
            float(want_scale[s])
        )


# ---------------------------------------------------------------------------
# Serving integration: knob, warmup, stats, sharded labels, chaos degrade
# ---------------------------------------------------------------------------

PROMPTS = [
    "golden sunshine over the river",
    "broken hearts mend slowly tonight",
    "dancing alone under silver skies",
    "thunder rolls across the mountain",
    "whisper my name in the morning",
    "yesterday is gone forever now",
]


@pytest.fixture(scope="module")
def clf():
    from music_analyst_tpu.models.llama import (
        LlamaConfig,
        LlamaZeroShotClassifier,
    )

    return LlamaZeroShotClassifier(
        config=LlamaConfig.tiny(), max_prompt_len=64
    )


def _scheduler(clf, **kwargs):
    from music_analyst_tpu.serving.decode_loop import ContinuousScheduler

    kwargs.setdefault("n_slots", 4)
    kwargs.setdefault("prefill_chunk", 16)
    kwargs.setdefault("prompt_region", 64)
    kwargs.setdefault("max_new_tokens", 8)
    return ContinuousScheduler(clf, **kwargs)


def _run(sched, prompts, budget=8):
    reqs = [
        sched.submit(i, p, max_new_tokens=budget)
        for i, p in enumerate(prompts)
    ]
    sched.run_until_idle()
    out = []
    for req in reqs:
        resp = req.response or {}
        assert resp.get("ok"), resp
        out.append(resp["text"])
    return out


def test_resolve_kv_quant_knob(monkeypatch):
    monkeypatch.delenv("MUSICAAL_SERVE_KV_QUANT", raising=False)
    assert resolve_kv_quant(None) == "none"
    assert resolve_kv_quant("int8") == "int8"
    monkeypatch.setenv("MUSICAAL_SERVE_KV_QUANT", "INT8")
    assert resolve_kv_quant(None) == "int8"
    assert resolve_kv_quant("none") == "none"  # explicit beats env
    monkeypatch.setenv("MUSICAAL_SERVE_KV_QUANT", "fp4")
    assert resolve_kv_quant(None) == "none"  # malformed env falls back
    with pytest.raises(ValueError, match="kv_quant"):
        resolve_kv_quant("fp4")  # explicit malformed raises


def test_kv_quant_requires_paged_backend(clf):
    with pytest.raises(ValueError, match="paged"):
        _scheduler(clf, page_size=0, kv_quant="int8")


def test_int8_scheduler_end_to_end(clf):
    """int8 pool: same labels as the unquantized scheduler, warmup stays
    at the pinned 4 programs, and the stats block reports the ≥1.8×
    pool-byte savings the manifest advertises."""
    plain = _run(_scheduler(clf, kv_quant="none"), PROMPTS)
    sched = _scheduler(clf, kv_quant="int8")
    record = sched.warmup()
    assert record["programs"] == 4
    assert record["kv_quant"] == "int8"
    texts = _run(sched, PROMPTS)
    labels = [normalise_label(t) for t in texts]
    want = [normalise_label(t) for t in plain]
    agreement = np.mean([a == b for a, b in zip(labels, want)])
    assert agreement >= 0.98
    kq = sched.stats()["kv_quant"]
    assert kq["scheme"] == "int8" and kq["degraded"] is False
    assert kq["compression"] >= 1.8
    assert kq["pool_bytes"] * 1.8 <= kq["pool_bytes_unquantized"]
    assert kq["bytes_saved"] == (
        kq["pool_bytes_unquantized"] - kq["pool_bytes"]
    )
    assert kq["hbm_bytes_per_seq"] * 1.8 <= (
        kq["hbm_bytes_per_seq_unquantized"]
    )


def test_int8_sharded_label_agreement():
    """End-to-end on the sharded mesh (dp×tp): int8 labels agree ≥ 0.98
    with the unquantized run, with speculation composed on top."""
    from music_analyst_tpu.models.llama import (
        LlamaConfig,
        LlamaZeroShotClassifier,
    )
    from music_analyst_tpu.parallel.mesh import build_mesh, factor_devices

    mesh = build_mesh(factor_devices(8, ("dp", "tp"), fixed={"tp": 2}))
    clf = LlamaZeroShotClassifier(
        config=LlamaConfig.tiny(), max_prompt_len=64, mesh=mesh
    )
    kw = dict(max_new_tokens=8, n_slots=4, prefill_chunk=16,
              speculate_k=2)
    plain = clf.generate_batch_continuous(PROMPTS, kv_quant="none", **kw)
    quant = clf.generate_batch_continuous(PROMPTS, kv_quant="int8", **kw)
    labels = [normalise_label(t) for t in quant]
    want = [normalise_label(t) for t in plain]
    agreement = np.mean([a == b for a, b in zip(labels, want)])
    assert agreement >= 0.98


def test_kv_quant_dequant_fault_degrades_byte_identical(clf):
    """Chaos drill for fault site ``kv_quant.dequant``: an int8
    scheduler degrades to the unquantized pool at construction — every
    reply byte-identical to a clean ``kv_quant="none"`` run, and the
    degrade visible in the stats block."""
    from music_analyst_tpu.resilience.faults import configure_faults

    clean = _run(_scheduler(clf, kv_quant="none"), PROMPTS)
    configure_faults("kv_quant.dequant:error@1+")
    try:
        sched = _scheduler(clf, kv_quant="int8")
    finally:
        configure_faults(None)
    assert _run(sched, PROMPTS) == clean
    kq = sched.stats()["kv_quant"]
    assert kq["degraded"] is True
    assert kq["scheme"] == "none"  # reads go through the unquantized pool
