"""Exact CSV record/field semantics and count-table export contract."""

import pytest

from music_analyst_tpu.data.csv_io import (
    clean_field,
    format_count_row,
    iter_csv_records_exact,
    iter_dataset_exact,
    iter_songs,
    parse_record_exact,
    sort_count_entries,
    write_count_csv,
)


class TestRecordReader:
    def test_simple_lines(self):
        recs = list(iter_csv_records_exact(b"a,b\nc,d\n"))
        assert recs == [b"a,b\n", b"c,d\n"]

    def test_quoted_newline_stays_in_record(self):
        data = b'x,"line1\nline2",y\nnext,row\n'
        recs = list(iter_csv_records_exact(data))
        assert recs == [b'x,"line1\nline2",y\n', b"next,row\n"]

    def test_escaped_quotes_do_not_close_field(self):
        data = b'a,"he said ""hi""\nmore",z\nb\n'
        recs = list(iter_csv_records_exact(data))
        assert len(recs) == 2
        assert recs[0].endswith(b",z\n")

    def test_crlf_and_bare_cr(self):
        recs = list(iter_csv_records_exact(b"a\r\nb\rc\n"))
        assert recs == [b"a\r\n", b"b\r", b"c\n"]

    def test_no_trailing_newline(self):
        assert list(iter_csv_records_exact(b"a,b")) == [b"a,b"]


class TestFieldCleaning:
    def test_unquote_and_unescape(self):
        assert clean_field(b'  "say ""hi"" now"  ') == b'say "hi" now'

    def test_preserve_outer_quotes(self):
        raw = b'"keep ""this"" quoted"'
        assert clean_field(raw, preserve_outer_quotes=True) == raw

    def test_unquoted_trimmed(self):
        assert clean_field(b"  plain \t") == b"plain"

    def test_lone_quote_not_treated_as_quoted(self):
        assert clean_field(b'"') == b'"'


class TestParseRecord:
    def test_text_is_everything_after_third_comma(self):
        rec = b"artist,song,link,one, two, three\n"
        artist, text = parse_record_exact(rec)
        assert artist == b"artist"
        assert text == b"one, two, three"

    def test_quoted_commas_do_not_split(self):
        rec = b'"Earth, Wind & Fire",September,/l,body text\n'
        artist, text = parse_record_exact(rec)
        assert artist == b"Earth, Wind & Fire"
        assert text == b"body text"

    def test_too_few_fields_rejected(self):
        assert parse_record_exact(b"only,two\n") is None

    def test_dataset_iteration_skips_header_and_bad_rows(self, fixture_csv):
        data = fixture_csv.read_bytes()
        rows = list(iter_dataset_exact(data))
        artists = [a.decode() for a, _ in rows]
        assert "BadRow" not in artists
        assert artists[0] == "ABBA"
        assert "Earth, Wind & Fire" in artists
        # Empty-artist row is still yielded (counts toward song total).
        assert "" in artists


class TestDictReaderPath:
    def test_iter_songs_limit_and_columns(self, fixture_csv):
        rows = list(iter_songs(str(fixture_csv), limit=2))
        assert len(rows) == 2
        artist, song, text = rows[0]
        assert artist == "ABBA"
        assert song == "Ahe's My Kind Of Girl"
        assert "wonderful face" in text


class TestCountExport:
    def test_sort_count_desc_tie_bytewise(self):
        entries = [("beta", 2), ("alpha", 2), ("zed", 5), ("Ab", 2)]
        # strcmp order: 'A' (0x41) < 'a' (0x61)
        assert sort_count_entries(entries) == [
            ("zed", 5),
            ("Ab", 2),
            ("alpha", 2),
            ("beta", 2),
        ]

    def test_quote_doubling(self):
        assert format_count_row('say "hi"', 3) == '"say ""hi""",3\n'

    def test_write_count_csv_limit_and_header(self, tmp_path):
        path = tmp_path / "word_counts.csv"
        write_count_csv(str(path), "word", [("b", 1), ("a", 3), ("c", 2)], limit=2)
        assert path.read_text() == 'word,count\n"a",3\n"c",2\n'

    def test_zero_limit_means_unlimited(self, tmp_path):
        path = tmp_path / "t.csv"
        write_count_csv(str(path), "artist", [("x", 1), ("y", 1)], limit=0)
        assert path.read_text().count("\n") == 3
