"""Sparse MoE dispatch vs the dense all-experts oracle.

The sparse path (token-choice top-k, capacity-bounded scatter/gather,
``models/moe.py``) must be the same *math* as the dense path — the only
sanctioned divergence is capacity drops.  With ``capacity_factor >=
n_experts`` no assignment can ever drop, so sparse must reproduce dense
(nearly) exactly; at production factors the divergence is bounded by the
dropped router mass.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from music_analyst_tpu.models.moe import MoESwiGLU

E, H, D, K = 4, 16, 8, 2


def _pair(dispatch_kwargs_a, dispatch_kwargs_b, x, seed=0):
    a = MoESwiGLU(E, H, top_k=K, dtype=jnp.float32, **dispatch_kwargs_a)
    b = MoESwiGLU(E, H, top_k=K, dtype=jnp.float32, **dispatch_kwargs_b)
    params = a.init(jax.random.key(seed), x)["params"]
    return a.apply({"params": params}, x), b.apply({"params": params}, x)


def test_sparse_lossless_capacity_matches_dense():
    x = jax.random.normal(jax.random.key(1), (2, 6, D), jnp.float32)
    dense, sparse = _pair(
        {"dispatch": "dense"},
        {"dispatch": "sparse", "capacity_factor": float(E)},
        x,
    )
    np.testing.assert_allclose(
        np.asarray(dense), np.asarray(sparse), rtol=1e-5, atol=1e-5
    )


def test_sparse_param_tree_identical_to_dense():
    """Dispatch is a compute strategy, not an architecture: checkpoints
    trained dense load into sparse and vice versa."""
    x = jnp.zeros((1, 4, D), jnp.float32)
    dense = MoESwiGLU(E, H, top_k=K, dispatch="dense")
    sparse = MoESwiGLU(E, H, top_k=K, dispatch="sparse")
    tree_a = jax.tree_util.tree_structure(
        dense.init(jax.random.key(0), x)["params"]
    )
    tree_b = jax.tree_util.tree_structure(
        sparse.init(jax.random.key(0), x)["params"]
    )
    assert tree_a == tree_b


def test_capped_capacity_divergence_bounded_by_dropped_mass():
    """At capacity_factor=1.0 drops can occur; the output still matches
    dense on every token whose assignments all fit."""
    x = jax.random.normal(jax.random.key(2), (2, 16, D), jnp.float32)
    dense, sparse = _pair(
        {"dispatch": "dense"},
        {"dispatch": "sparse", "capacity_factor": 1.0},
        x,
    )
    dense, sparse = np.asarray(dense), np.asarray(sparse)
    # Token-level: a token either matches dense (all assignments kept) or
    # lost some router mass (dropped expert) — never garbage.
    per_token = np.abs(dense - sparse).max(axis=-1).reshape(-1)
    matching = per_token < 1e-5
    assert matching.mean() >= 0.5  # most tokens fit at factor 1.0
    # Divergent tokens are bounded by the norm dense assigns (lost mass <=
    # full contribution), not unbounded garbage.
    assert np.abs(sparse).max() <= np.abs(dense).max() * 3 + 1.0


def test_sparse_is_differentiable():
    x = jax.random.normal(jax.random.key(3), (1, 8, D), jnp.float32)
    moe = MoESwiGLU(E, H, top_k=K, dtype=jnp.float32, dispatch="sparse")
    params = moe.init(jax.random.key(0), x)["params"]

    def loss(p):
        return jnp.sum(moe.apply({"params": p}, x) ** 2)

    grads = jax.grad(loss)(params)
    leaves = jax.tree_util.tree_leaves(grads)
    assert all(np.isfinite(np.asarray(g)).all() for g in leaves)
    # Expert weights receive gradient (dispatch routes real tokens).
    assert any(float(np.abs(np.asarray(g)).sum()) > 0 for g in leaves)


def test_sparse_flop_scaling():
    """The point of sparse dispatch: expert matmul work is k*cf per token,
    not E per token.  Count contraction sizes via the buffer shape."""
    T = 64
    x = jnp.zeros((1, T, D), jnp.float32)
    moe = MoESwiGLU(E, H, top_k=K, dispatch="sparse", capacity_factor=1.25)
    params = moe.init(jax.random.key(0), x)["params"]
    jaxpr = jax.make_jaxpr(
        lambda p: moe.apply({"params": p}, x)
    )(params)
    # Single source of truth for the slot count (no duplicated formula).
    from music_analyst_tpu.models.moe import moe_capacity

    capacity = moe_capacity(T, K, E, 1.25)
    buffer_rows = E * capacity
    dense_rows = E * T
    # Expert-matmul rows scale as k*cf per token instead of E: the ratio
    # is (k*cf)/E — an E/(k*cf)-fold FLOP drop (1.6x here; 3.2x at E=8).
    assert buffer_rows / dense_rows <= (K * 1.25) / E * 1.1
    # and the jaxpr indeed materializes the [E, capacity, H] intermediate
    assert f"{E},{capacity},{H}" in str(jaxpr).replace(" ", "").replace(
        "(", ""
    ).replace(")", "")


def test_int8_sparse_matches_int8_dense_at_lossless_capacity():
    """Both dispatches quantize per (expert, row), so with no capacity
    drops the quantized math is identical up to f32 reduction order —
    int8 must not widen the sparse/dense gap."""
    x = jax.random.normal(jax.random.key(4), (2, 6, D), jnp.float32)
    dense, sparse = _pair(
        {"dispatch": "dense", "quant": "int8"},
        {"dispatch": "sparse", "quant": "int8",
         "capacity_factor": float(E)},
        x,
    )
    np.testing.assert_allclose(
        np.asarray(dense), np.asarray(sparse), rtol=1e-4, atol=1e-4
    )


def test_int8_tracks_float_moe():
    """Per-expert int8 expert einsums stay inside the symmetric-int8
    error bound relative to the float module on the same params."""
    x = jax.random.normal(jax.random.key(5), (2, 8, D), jnp.float32)
    f32, q = _pair(
        {"dispatch": "sparse"},
        {"dispatch": "sparse", "quant": "int8"},
        x,
    )
    f32, q = np.asarray(f32), np.asarray(q)
    corr = np.corrcoef(f32.ravel(), q.ravel())[0, 1]
    assert corr > 0.99, corr
    # Not bit-identical (that would mean the int8 path never ran).
    assert np.abs(f32 - q).max() > 0


def test_int8_param_tree_identical_to_float():
    """quant is a compute strategy like dispatch: float checkpoints load
    into the int8 module unchanged."""
    x = jnp.zeros((1, 4, D), jnp.float32)
    tree_a = jax.tree_util.tree_structure(
        MoESwiGLU(E, H, top_k=K).init(jax.random.key(0), x)["params"]
    )
    tree_b = jax.tree_util.tree_structure(
        MoESwiGLU(E, H, top_k=K, quant="int8").init(
            jax.random.key(0), x
        )["params"]
    )
    assert tree_a == tree_b


def test_bad_dispatch_rejected():
    x = jnp.zeros((1, 4, D), jnp.float32)
    moe = MoESwiGLU(E, H, dispatch="typo")
    with pytest.raises(ValueError, match="dispatch"):
        moe.init(jax.random.key(0), x)


def test_capacity_ceils_not_truncates():
    """Decode-scale token counts keep their capacity headroom: the factor
    product ceils (2.5 -> 3 slots), never truncates back to fair share."""
    from music_analyst_tpu.models.moe import moe_capacity

    assert moe_capacity(4, 2, 4, 1.25) == 3
    assert moe_capacity(64, 2, 4, 1.0) == 32
    assert moe_capacity(64, 2, 4, 1.25) == 40
    assert moe_capacity(0, 2, 4, 1.25) == 1
    assert moe_capacity(16, 2, 4, 4.0) == 32  # lossless >= T*k/E*E


def test_sparse_moe_inside_classifier_forward():
    """Sparse dispatch composes with the real model: zero-shot scoring
    (vmapped label continuation) and scan generation both run with an
    MoE FFN, honoring the empty-lyric rule."""
    from music_analyst_tpu.models.llama import (
        LlamaConfig,
        LlamaZeroShotClassifier,
    )

    cfg = LlamaConfig(
        vocab_size=300, dim=32, n_layers=1, n_heads=4, n_kv_heads=2,
        hidden_dim=64, rope_theta=1e4, max_seq_len=256, dtype="float32",
        n_experts=4, moe_top_k=2,
    )
    clf = LlamaZeroShotClassifier(config=cfg, max_prompt_len=128)
    labels = clf.classify_batch(["love and rain", "", "pain " * 20])
    assert labels[1] == "Neutral"
    assert all(l in ("Positive", "Neutral", "Negative") for l in labels)
    outs = clf.generate_batch(["say hi", "la"], max_new_tokens=4)
    assert len(outs) == 2
