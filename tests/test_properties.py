"""Property-based tests (hypothesis) for the parity-critical contracts.

The golden/differential tests pin exact outputs on fixed corpora; these
push randomized inputs through the same contracts so edge cases the
fixtures missed (odd unicode, quote pileups, pathological whitespace)
still honor the reference semantics (SURVEY.md §5 contracts 1-2).
"""

import csv

import numpy as np
import pytest

hypothesis = pytest.importorskip("hypothesis")
from hypothesis import given, settings, strategies as st  # noqa: E402

from music_analyst_tpu.data.csv_io import sort_count_entries, write_count_csv
from music_analyst_tpu.data.tokenizer import tokenize_ascii
from music_analyst_tpu.models.tokenization import (
    HashWordTokenizer,
    NativeHashTokenizer,
)

text_strategy = st.text(
    alphabet=st.characters(codec="utf-8"), max_size=400
)


@given(text_strategy)
@settings(max_examples=200, deadline=None)
def test_ascii_tokenizer_contract(text):
    """Reference C tokenizer semantics (src/parallel_spotify.c:350-394):
    tokens are runs of lowercased ASCII alnum + apostrophe, length >= 3
    BYTES; everything else (incl. every non-ASCII byte) is a separator."""
    tokens = tokenize_ascii(text)
    for tok in tokens:
        assert len(tok.encode()) >= 3
        assert all(
            (c.isascii() and (c.isalnum() or c == "'")) for c in tok
        )
        assert tok == tok.lower()
    # Idempotence: tokens re-tokenize to themselves.
    for tok in tokens:
        assert tokenize_ascii(tok) == [tok]


@given(st.lists(st.text(alphabet=st.characters(codec="utf-8"), max_size=60),
                max_size=30))
@settings(max_examples=100, deadline=None)
def test_native_hash_tokenizer_matches_python(texts):
    """The C++ batch tokenizer is byte-equivalent to the Python spec."""
    from music_analyst_tpu.data import native

    if not native.available():
        return
    py = HashWordTokenizer(vocab_size=2048)
    cc = NativeHashTokenizer(vocab_size=2048)
    ids_py, len_py = py.encode_batch(texts, 64)
    # NativeHashTokenizer falls back to Python when the lib is missing;
    # native.available() above guarantees this exercises the C++ path.
    ids_cc, len_cc = cc.encode_batch(texts, 64)
    np.testing.assert_array_equal(ids_py, ids_cc)
    np.testing.assert_array_equal(len_py, len_cc)


count_entries = st.lists(
    st.tuples(
        st.text(alphabet=st.characters(codec="utf-8",
                                       exclude_characters="\x00"),
                min_size=1, max_size=20),
        st.integers(min_value=0, max_value=10**9),
    ),
    max_size=50,
    unique_by=lambda kv: kv[0],
)


@given(count_entries)
@settings(max_examples=150, deadline=None)
def test_sort_contract(entries):
    """Count desc, ties strcmp asc (src/parallel_spotify.c:178-188)."""
    ordered = sort_count_entries(entries)
    assert sorted(ordered, key=lambda kv: kv[0]) == sorted(
        entries, key=lambda kv: kv[0]
    )
    for (k1, v1), (k2, v2) in zip(ordered, ordered[1:]):
        assert v1 > v2 or (v1 == v2 and k1.encode() < k2.encode())


@given(count_entries, st.integers(min_value=0, max_value=10))
@settings(max_examples=100, deadline=None)
def test_count_csv_roundtrip(entries, limit):
    """The quoted CSV writer (src/parallel_spotify.c:307-344 semantics)
    always produces rows Python's csv module parses back verbatim."""
    import os
    import tempfile

    ordered = sort_count_entries(entries)
    fd, path = tempfile.mkstemp(suffix=".csv")
    os.close(fd)
    try:
        write_count_csv(path, "word", entries, limit=limit)
        with open(path, newline="", encoding="utf-8") as fh:
            rows = list(csv.reader(fh))
    finally:
        os.unlink(path)
    assert rows[0] == ["word", "count"]
    expect = ordered[:limit] if limit > 0 else ordered
    assert len(rows) - 1 == len(expect)
    for row, (key, value) in zip(rows[1:], expect):
        assert row == [key, str(value)]
