"""Weight-only quantized parameter store (ops/quant.py QuantizedParam +
engines/checkpoint.py streaming loader + engines/wq_cache.py).

Covers the tentpole contract end to end on the CPU-emulated mesh:
op-level error bounds for both schemes, streaming quantize-on-load with
the O(one layer) peak-host-staging bound (the float tree never exists),
cold→warm content-addressed cache round-trips with corruption injection,
quantized-tree sharding under the 8-device dp×tp mesh, and small-config
end-to-end label agreement vs the bf16 path.
"""

import dataclasses
import json
import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from music_analyst_tpu.ops.quant import (
    WQ_DEFAULT_GROUP,
    QuantizedParam,
    dequantize_param,
    param_tree_bytes,
    quantize_array,
    quantize_tree,
    wq_matmul,
    wq_rule_for_path,
)

torch = pytest.importorskip("torch")

from music_analyst_tpu.engines import wq_cache  # noqa: E402
from music_analyst_tpu.engines.checkpoint import (  # noqa: E402
    last_load_stats,
    load_quantized_params,
)
from music_analyst_tpu.models.distilbert import (  # noqa: E402
    DistilBertClassifier,
    DistilBertConfig,
    iter_hf_param_units,
)
from test_distilbert_checkpoint import _hf_state_dict  # noqa: E402


# --------------------------------------------------------------------- ops


class TestQuantOps:
    def test_int8_roundtrip_error_bound(self):
        w = np.random.RandomState(0).randn(64, 32).astype(np.float32)
        qp = quantize_array(w, "int8")
        assert isinstance(qp, QuantizedParam)
        assert qp.q.dtype == jnp.int8 and qp.scale.shape == (1, 32)
        back = np.asarray(dequantize_param(qp))
        # Symmetric per-channel int8: error ≤ scale/2 per element.
        bound = np.asarray(qp.scale)[0] / 2 + 1e-7
        assert (np.abs(back - w) <= bound).all()

    def test_int4_roundtrip_error_bound(self):
        w = np.random.RandomState(1).randn(256, 16).astype(np.float32)
        qp = quantize_array(w, "int4", group_size=128)
        assert qp.q.shape == (128, 16)  # packed pairs along axis 0
        back = np.asarray(dequantize_param(qp))
        assert back.shape == w.shape
        # Per-group scale = max|w|/7 → error ≤ scale/2.
        rel = np.abs(back - w).max() / np.abs(w).max()
        assert rel < 0.08, rel

    def test_int4_odd_leading_axis_raises(self):
        w = np.ones((7, 4), np.float32)
        with pytest.raises(ValueError, match="even"):
            quantize_array(w, "int4")

    def test_unknown_scheme_raises(self):
        with pytest.raises(ValueError, match="scheme"):
            quantize_array(np.ones((4, 4), np.float32), "int2")

    @pytest.mark.parametrize("scheme", ["int8", "int4"])
    def test_wq_matmul_tracks_float(self, scheme):
        rs = np.random.RandomState(2)
        w = rs.randn(128, 64).astype(np.float32)
        x = rs.randn(8, 128).astype(np.float32)
        qp = quantize_array(w, scheme)
        got = np.asarray(wq_matmul(jnp.asarray(x), qp))
        want = x @ w
        # Correlation, not mean-relative error: random-normal outputs
        # cancel toward zero and inflate ratio metrics meaninglessly.
        corr = np.corrcoef(got.ravel(), want.ravel())[0, 1]
        assert corr > (0.999 if scheme == "int8" else 0.99), corr

    def test_wq_dense_n_contract_2(self):
        from music_analyst_tpu.ops.quant import wq_dense_axis_last2

        rs = np.random.RandomState(3)
        w = rs.randn(4, 16, 32).astype(np.float32)  # [heads, hd, out]
        x = rs.randn(6, 4, 16).astype(np.float32)
        qp = quantize_array(w, "int8", n_contract=2)
        got = np.asarray(
            wq_dense_axis_last2(jnp.asarray(x), qp, out_dtype=jnp.float32)
        )
        want = np.einsum("bhk,hko->bo", x, w)
        corr = np.corrcoef(got.ravel(), want.ravel())[0, 1]
        assert corr > 0.999, corr

    def test_path_rules_select_projection_kernels_only(self):
        assert wq_rule_for_path("layer_0/attention/q_proj/kernel") == 1
        assert wq_rule_for_path("layer_3/attention/o_proj/kernel") == 2
        assert wq_rule_for_path("layer_1/feed_forward/gate_proj/kernel") == 1
        assert wq_rule_for_path("encoder/layer_0/ffn/lin1/kernel") == 1
        assert wq_rule_for_path("lm_head/kernel") == 1
        assert wq_rule_for_path("tok_embeddings/embedding") is None
        assert wq_rule_for_path("layer_0/attention/q_proj/bias") is None
        assert wq_rule_for_path("pre_classifier/kernel") is None

    def test_quantized_tree_flows_through_jit(self):
        w = np.random.RandomState(4).randn(32, 8).astype(np.float32)
        tree = {"layer_0": {"attention": {"q_proj": {"kernel": w}}}}
        qt = quantize_tree(tree, "int8")
        qp = qt["layer_0"]["attention"]["q_proj"]["kernel"]
        assert isinstance(qp, QuantizedParam)

        @jax.jit
        def f(t, x):
            return wq_matmul(x, t["layer_0"]["attention"]["q_proj"]["kernel"])

        out = f(qt, jnp.ones((2, 32)))
        assert out.shape == (2, 8)
        # Meta fields are static: a second call with the same structure
        # must not retrace.
        assert f._cache_size() == 1
        f(qt, jnp.ones((2, 32)))
        assert f._cache_size() == 1

    def test_param_tree_bytes_accounting(self):
        w = np.zeros((128, 64), np.float32)
        tree = {
            "layer_0": {"attention": {"q_proj": {"kernel": w}}},
            "norm": {"scale": np.zeros((64,), np.float32)},
        }
        acc = param_tree_bytes(quantize_tree(tree, "int8"))
        assert acc["n_quantized_leaves"] == 1
        assert acc["n_float_leaves"] == 1
        # codes (128·64·1) + scales (64·4) + float norm (64·4)
        assert acc["stored_bytes"] == 128 * 64 + 64 * 4 + 64 * 4
        assert acc["dequant_transient_bytes"] == 128 * 64 * 4


# --------------------------------------------------- streaming load + cache


@pytest.fixture()
def ckpt(tmp_path):
    cfg = DistilBertConfig.tiny()
    path = tmp_path / "pytorch_model.bin"
    torch.save(_hf_state_dict(cfg), path)
    return cfg, str(path)


def _params_shape(cfg, max_len=64):
    from music_analyst_tpu.models.distilbert import DistilBertForSentiment

    model = DistilBertForSentiment(cfg)
    return jax.eval_shape(
        model.init,
        jax.random.key(0),
        jnp.zeros((1, max_len), jnp.int32),
        jnp.ones((1,), jnp.int32),
    )["params"]


class TestStreamingLoad:
    def test_cold_then_warm_is_cache_hit(self, ckpt, tmp_path):
        cfg, path = ckpt
        cache_dir = str(tmp_path / "cache")
        os.makedirs(cache_dir)
        shape = _params_shape(cfg)
        key = wq_cache.wq_key(path, "distilbert", "int8", WQ_DEFAULT_GROUP)

        def load():
            return load_quantized_params(
                shape,
                lambda: iter_hf_param_units(shape, path, mmap=True),
                "int8",
                cache_dir=cache_dir,
                cache_key=key,
            )

        cold = load()
        st = last_load_stats()
        assert st["cache"] == "miss" and st["cache_stored"]
        warm = load()
        st = last_load_stats()
        assert st["cache"] == "hit"
        for a, b in zip(
            jax.tree_util.tree_leaves(cold), jax.tree_util.tree_leaves(warm)
        ):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_peak_staging_is_one_unit(self, ckpt, tmp_path):
        cfg, path = ckpt
        shape = _params_shape(cfg)
        load_quantized_params(
            shape, lambda: iter_hf_param_units(shape, path), "int8"
        )
        st = last_load_stats()
        # Units: embeddings, layer_0, layer_1, head.  The embeddings unit
        # (vocab × dim + positions × dim) is the largest; the bound is
        # peak ≤ (prefetch depth + 1) units, far below the full tree.
        total_float = sum(
            int(np.prod(l.shape)) * 4
            for l in jax.tree_util.tree_leaves(shape)
        )
        assert st["units"] == cfg.n_layers + 2
        assert 0 < st["peak_host_staging_bytes"] < total_float
        assert st["cache"] == "off"

    def test_loaded_tree_matches_eager_quantize(self, ckpt):
        cfg, path = ckpt
        from music_analyst_tpu.models.distilbert import (
            load_hf_torch_checkpoint,
        )

        shape = _params_shape(cfg)
        streamed = load_quantized_params(
            shape, lambda: iter_hf_param_units(shape, path), "int8"
        )
        float_params = jax.tree_util.tree_map(
            lambda l: np.zeros(l.shape, l.dtype), shape
        )
        float_params = load_hf_torch_checkpoint(float_params, path)
        eager = quantize_tree(float_params, "int8", WQ_DEFAULT_GROUP)
        sl = jax.tree_util.tree_leaves(streamed)
        el = jax.tree_util.tree_leaves(eager)
        assert len(sl) == len(el)
        for a, b in zip(sl, el):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_truncated_npy_entry_evicted_and_reloaded(self, ckpt, tmp_path):
        cfg, path = ckpt
        cache_dir = str(tmp_path / "cache")
        os.makedirs(cache_dir)
        shape = _params_shape(cfg)
        key = wq_cache.wq_key(path, "distilbert", "int8", WQ_DEFAULT_GROUP)

        def load():
            return load_quantized_params(
                shape,
                lambda: iter_hf_param_units(shape, path),
                "int8",
                cache_dir=cache_dir,
                cache_key=key,
            )

        load()
        entry = os.path.join(cache_dir, key)
        victim = next(
            os.path.join(entry, n)
            for n in sorted(os.listdir(entry)) if n.endswith(".q.npy")
        )
        with open(victim, "r+b") as fh:
            fh.truncate(16)  # torn mid-header
        before = wq_cache.cache_stats()["corrupt"]
        load()  # must not raise: corrupt → evict → miss → re-stream
        st = last_load_stats()
        assert st["cache"] == "miss"
        assert wq_cache.cache_stats()["corrupt"] == before + 1
        load()
        assert last_load_stats()["cache"] == "hit"  # re-published

    def test_stale_schema_key_misses(self, ckpt, tmp_path):
        cfg, path = ckpt
        cache_dir = str(tmp_path / "cache")
        os.makedirs(cache_dir)
        shape = _params_shape(cfg)
        key = wq_cache.wq_key(path, "distilbert", "int8", WQ_DEFAULT_GROUP)
        load_quantized_params(
            shape, lambda: iter_hf_param_units(shape, path), "int8",
            cache_dir=cache_dir, cache_key=key,
        )
        meta = os.path.join(cache_dir, key, "meta.json")
        with open(meta, encoding="utf-8") as fh:
            doc = json.load(fh)
        doc["schema"] = -1
        with open(meta, "w", encoding="utf-8") as fh:
            json.dump(doc, fh)
        load_quantized_params(
            shape, lambda: iter_hf_param_units(shape, path), "int8",
            cache_dir=cache_dir, cache_key=key,
        )
        assert last_load_stats()["cache"] == "miss"

    def test_different_scheme_is_different_key(self, ckpt):
        _, path = ckpt
        k8 = wq_cache.wq_key(path, "distilbert", "int8", WQ_DEFAULT_GROUP)
        k4 = wq_cache.wq_key(path, "distilbert", "int4", WQ_DEFAULT_GROUP)
        assert k8 != k4
        # Content-addressed: a byte flip changes the key.
        with open(path, "r+b") as fh:
            fh.seek(100)
            b = fh.read(1)
            fh.seek(100)
            fh.write(bytes([b[0] ^ 1]))
        assert wq_cache.wq_key(
            path, "distilbert", "int8", WQ_DEFAULT_GROUP
        ) != k8


# ----------------------------------------------------- mesh + end-to-end


def _mesh(dp, tp):
    devices = np.array(jax.devices()[: dp * tp]).reshape(dp, tp)
    return Mesh(devices, ("dp", "tp"))


class TestShardingAndEndToEnd:
    def test_quantized_tree_shards_under_dp_tp(self, ckpt):
        from music_analyst_tpu.parallel.sharding import (
            partition_specs,
            shard_params,
        )

        cfg, path = ckpt
        shape = _params_shape(cfg)
        tree = load_quantized_params(
            shape, lambda: iter_hf_param_units(shape, path), "int8"
        )
        mesh = _mesh(4, 2)
        specs = partition_specs(tree)
        qspec = specs["encoder"]["layer_0"]["attention"]["q_proj"]["kernel"]
        assert isinstance(qspec, QuantizedParam)
        assert "tp" in tuple(qspec.q)
        # Scales replicate over contraction axes only: feature axes keep
        # the kernel's placement so the epilogue multiply never gathers.
        assert tuple(qspec.scale)[0] is None
        sharded = shard_params(tree, mesh)
        qp = sharded["encoder"]["layer_0"]["attention"]["q_proj"]["kernel"]
        assert isinstance(qp, QuantizedParam)
        assert not qp.q.sharding.is_fully_replicated
        del sharded

    @pytest.mark.parametrize("scheme", ["int8", "int4"])
    def test_label_agreement_vs_bf16(self, ckpt, scheme):
        cfg, path = ckpt
        texts = [
            f"song {i}: love and rain over the lonely city " * (1 + i % 3)
            for i in range(32)
        ]
        bf16 = DistilBertClassifier(
            config=cfg, checkpoint_path=path, max_len=64, seed=0
        )
        want = bf16.classify_batch(texts)
        wq = DistilBertClassifier(
            config=dataclasses.replace(cfg, weight_quant=scheme),
            checkpoint_path=path, max_len=64, seed=0, mesh=_mesh(4, 2),
        )
        st = last_load_stats()
        assert st["scheme"] == scheme
        got = wq.classify_batch(texts)
        agree = sum(a == b for a, b in zip(want, got)) / len(texts)
        assert agree >= 0.98, (agree, scheme)

    def test_forward_donation_keeps_params_alive(self, ckpt):
        # The batch args are donated; a quantized param tree must survive
        # repeat classify calls (donating params would free the store).
        cfg, path = ckpt
        clf = DistilBertClassifier(
            config=dataclasses.replace(cfg, weight_quant="int8"),
            checkpoint_path=path, max_len=64, seed=0,
        )
        texts = ["love the rain", "hate the cold"] * 4
        first = clf.classify_batch(texts)
        second = clf.classify_batch(texts)
        assert first == second

    def test_weight_quant_excludes_dynamic_quant(self):
        with pytest.raises(ValueError, match="mutually exclusive"):
            DistilBertConfig(quant="int8", weight_quant="int8")
        with pytest.raises(ValueError, match="weight_quant"):
            DistilBertConfig(weight_quant="fp8")

    def test_get_backend_rejects_wq_for_mock(self):
        from music_analyst_tpu.engines.sentiment import get_backend

        with pytest.raises(ValueError, match="weight_quant"):
            get_backend("mock", weight_quant="int8")

    def test_manifest_records_wq_cache_section(self, ckpt, tmp_path):
        cfg, path = ckpt
        cache_dir = str(tmp_path / "cache")
        os.makedirs(cache_dir)
        shape = _params_shape(cfg)
        key = wq_cache.wq_key(path, "distilbert", "int8", WQ_DEFAULT_GROUP)
        for _ in range(2):  # miss then hit
            load_quantized_params(
                shape, lambda: iter_hf_param_units(shape, path), "int8",
                cache_dir=cache_dir, cache_key=key,
            )
        from music_analyst_tpu.telemetry import get_telemetry

        out = tmp_path / "run"
        tel = get_telemetry()
        with tel.run_scope("wq_manifest_test", str(out)):
            pass
        manifest_path = next(out.rglob("run_manifest.json"))
        doc = json.loads(manifest_path.read_text())
        assert doc["wq_cache"]["hits"] >= 1
        assert doc["wq_cache"]["last_load"]["cache"] == "hit"
