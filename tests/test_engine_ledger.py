"""Engine goodput ledger (ISSUE 19): exact attribution tiling.

Contract families:

* **tiling** — every accounted second lands in exactly one attribution
  class and the classes sum to the engine-wall span (coverage == 1.0 by
  construction); per-tenant chip-seconds tile the same span, with empty
  engine time on the reserved ``(idle)`` tenant.
* **knobs** — ``resolve_ledger_interval_ms`` / ``resolve_ledger_dir``
  follow the house resolve_* ladder: explicit flag raises on malformed,
  env falls back, the metrics-plane cadence is the default.
* **flushing** — cumulative O_APPEND JSONL records, never torn; the
  ``ledger.flush`` fault site degrades to counted ``ledger_drops`` and
  the file stays intact.
* **scheduler integration** — a real continuous-scheduler workload
  tiles ≥95%, chip-seconds within 2% of engine wall, self-measured
  overhead ≤1%, byte-identical replies and zero retraces with flushing
  on vs off.
* **fleet merge** — ledger counters flatten and sum across replicas
  exactly (mirroring tests/test_metrics_plane.py's merge oracle);
  fractions, ratios and config never sum; stale replicas are excluded.
* **surfaces** — monitor engine panel rows + the ``--idle-bubble-gate``
  exit code; telemetry-report's goodput trajectory and per-tenant
  chip-seconds table.
"""

import json
import os
import socket
import threading

import pytest

from music_analyst_tpu.observability.engine_ledger import (
    IDLE_TENANT,
    LEDGER_FILE,
    EngineLedger,
    resolve_ledger_dir,
    resolve_ledger_interval_ms,
)


class _Req:
    def __init__(self, tenant):
        self.tenant = tenant


class _Slot:
    def __init__(self, tenant):
        self.req = _Req(tenant)


# --------------------------------------------------------------- tiling


def test_tick_attribution_tiles_exactly():
    led = EngineLedger(4, interval_ms=0)
    t = 100.0
    led.record_tick(t, t + 1.0, prefill_s=0.25, chunks_cold=1,
                    decode_s=0.5, useful_frac=0.8, committed=4,
                    slots=[_Slot("gold"), _Slot("bulk"), None, None])
    led.idle_wait(t + 1.0, t + 1.5)
    led.record_tick(t + 2.0, t + 2.5, decode_s=0.4, committed=1,
                    slots=[_Slot("gold"), None, None, None])
    snap = led.snapshot()
    assert snap["engine_wall_s"] == pytest.approx(2.5)
    assert snap["coverage"] == pytest.approx(1.0)
    s = snap["seconds"]
    assert s["decode_useful"] == pytest.approx(0.5 * 0.8 + 0.4)
    assert s["spec_waste"] == pytest.approx(0.5 * 0.2)
    assert s["prefill"] == pytest.approx(0.25)
    # tick-1 residual 0.25 + inter-tick gap 0.5 + tick-2 residual 0.1
    assert s["host_gap"] == pytest.approx(0.85)
    assert s["idle_bubble"] == pytest.approx(0.5)  # the timed loop wait
    assert sum(s.values()) == pytest.approx(snap["engine_wall_s"])
    assert snap["goodput_fraction"] == pytest.approx(0.8 / 2.5)
    chip = snap["chip_seconds"]
    assert chip["gold"] == pytest.approx(0.5 + 1.0)  # half of t1 + all t2
    assert chip["bulk"] == pytest.approx(0.5)
    assert chip[IDLE_TENANT] == pytest.approx(0.5)
    assert sum(chip.values()) == pytest.approx(snap["engine_wall_s"])
    assert snap["tokens_committed"] == 5
    assert snap["prefill_chunks"] == {"cold": 1, "shared_hit": 0}


def test_empty_tick_is_idle_bubble():
    led = EngineLedger(2, interval_ms=0)
    led.record_tick(0.0, 0.5, slots=[None, None])
    snap = led.snapshot()
    assert snap["ticks"] == 1 and snap["idle_ticks"] == 1
    assert snap["seconds"]["idle_bubble"] == pytest.approx(0.5)
    assert snap["chip_seconds"] == {IDLE_TENANT: 0.5}
    assert snap["goodput_fraction"] == 0.0


def test_empty_ledger_snapshot_is_zeroed():
    snap = EngineLedger(2, interval_ms=0).snapshot()
    assert snap["ticks"] == 0
    assert snap["engine_wall_s"] == 0.0
    assert snap["coverage"] == 0.0
    assert snap["chip_seconds"] == {}


# ---------------------------------------------------------------- knobs


def test_resolve_interval_explicit_flag_raises_on_malformed():
    with pytest.raises(ValueError, match="ledger-interval-ms"):
        resolve_ledger_interval_ms("fast")
    with pytest.raises(ValueError, match="ledger-interval-ms"):
        resolve_ledger_interval_ms(-5)


def test_resolve_interval_env_ladder(monkeypatch):
    monkeypatch.delenv("MUSICAAL_LEDGER_INTERVAL_MS", raising=False)
    monkeypatch.delenv("MUSICAAL_METRICS_INTERVAL_MS", raising=False)
    assert resolve_ledger_interval_ms(None) == 0.0  # default: no flush
    monkeypatch.setenv("MUSICAAL_METRICS_INTERVAL_MS", "250")
    assert resolve_ledger_interval_ms(None) == 250.0  # metrics cadence
    monkeypatch.setenv("MUSICAAL_LEDGER_INTERVAL_MS", "125")
    assert resolve_ledger_interval_ms(None) == 125.0  # own env wins
    monkeypatch.setenv("MUSICAAL_LEDGER_INTERVAL_MS", "junk")
    assert resolve_ledger_interval_ms(None) == 250.0  # malformed env falls
    assert resolve_ledger_interval_ms(40) == 40.0  # explicit beats all


def test_resolve_dir_precedence(monkeypatch, tmp_path):
    monkeypatch.setenv("MUSICAAL_LEDGER_DIR", str(tmp_path / "env"))
    assert resolve_ledger_dir(str(tmp_path / "flag")) == str(
        tmp_path / "flag"
    )
    assert resolve_ledger_dir(None) == str(tmp_path / "env")


def test_file_disarmed_without_dir_or_interval(monkeypatch, tmp_path):
    for var in ("MUSICAAL_LEDGER_DIR", "MUSICAAL_METRICS_DIR",
                "MUSICAAL_LEDGER_INTERVAL_MS",
                "MUSICAAL_METRICS_INTERVAL_MS"):
        monkeypatch.delenv(var, raising=False)
    assert EngineLedger(1, interval_ms=0, directory=str(tmp_path)).path \
        is None
    assert EngineLedger(1, interval_ms=50, directory=None).path is None
    armed = EngineLedger(1, interval_ms=50, directory=str(tmp_path))
    assert armed.path == str(tmp_path / LEDGER_FILE)


# ------------------------------------------------------------- flushing


def test_flush_writes_cumulative_intact_jsonl(tmp_path):
    led = EngineLedger(2, interval_ms=10, directory=str(tmp_path))
    led.record_tick(0.0, 0.1, decode_s=0.05, committed=1,
                    slots=[_Slot("gold"), None])
    assert led.maybe_flush(force=True) is True
    led.record_tick(0.2, 0.3, decode_s=0.05, committed=1,
                    slots=[_Slot("gold"), None])
    led.close()  # drain: one final forced flush
    lines = (tmp_path / LEDGER_FILE).read_text().splitlines()
    assert len(lines) == led.flushes == 2
    recs = [json.loads(line) for line in lines]
    assert all(r["type"] == "ledger" for r in recs)
    assert recs[0]["ledger"]["ticks"] == 1
    assert recs[-1]["ledger"]["ticks"] == 2  # cumulative, last is final
    assert recs[-1]["pid"] == os.getpid()


def test_fault_site_ledger_flush_degrades_to_counted_drops(tmp_path):
    from music_analyst_tpu.resilience import configure_faults

    led = EngineLedger(2, interval_ms=10, directory=str(tmp_path))
    led.record_tick(0.0, 0.1, decode_s=0.05, slots=[_Slot("gold"), None])
    configure_faults("ledger.flush:error@1+")
    try:
        assert led.maybe_flush(force=True) is False
        assert led.maybe_flush(force=True) is False
    finally:
        configure_faults(None)
    assert led.ledger_drops == 2 and led.flushes == 0
    # a failed flush writes NOTHING — no torn line ever lands
    assert not (tmp_path / LEDGER_FILE).exists()
    # recovery: the next flush lands the full cumulative state,
    # drops included — nothing was lost, only the flush cadence
    assert led.maybe_flush(force=True) is True
    rec = json.loads((tmp_path / LEDGER_FILE).read_text())
    assert rec["ledger"]["ledger_drops"] == 2
    assert rec["ledger"]["ticks"] == 1


# ------------------------------------------------- scheduler integration


@pytest.fixture(scope="module")
def clf():
    from music_analyst_tpu.models.llama import (
        LlamaConfig,
        LlamaZeroShotClassifier,
    )

    return LlamaZeroShotClassifier(
        config=LlamaConfig.tiny(), max_prompt_len=64
    )


PROMPTS = [
    "golden sunshine on the river",
    "rain",
    "shadows fall across the empty street",
    "my heart beats a broken drum",
    "la la la la",
    "winter wind and summer fire",
]


def _texts(sched, tag):
    reqs = [
        sched.submit(f"{tag}-{i}", p, max_new_tokens=6,
                     tenant=("gold" if i % 2 == 0 else "bulk"))
        for i, p in enumerate(PROMPTS)
    ]
    sched.run_until_idle()
    out = []
    for req in reqs:
        resp = req.response or {}
        assert resp.get("ok"), resp
        out.append(resp["text"])
    return out


def test_scheduler_ledger_tiles_and_attributes(clf):
    from music_analyst_tpu.serving.decode_loop import ContinuousScheduler

    sched = ContinuousScheduler(
        clf, n_slots=2, prefill_chunk=16, prompt_region=64,
        max_new_tokens=8, max_queue=16, ledger_interval_ms=0,
    )
    sched.warmup()
    _texts(sched, "tile")
    snap = sched.stats()["ledger"]
    wall = snap["engine_wall_s"]
    assert snap["ticks"] > 0 and wall > 0
    # ISSUE bars: ≥95% coverage, chip-seconds within 2% of engine wall,
    # self-measured recording overhead ≤1%.
    assert snap["coverage"] >= 0.95
    assert sum(snap["seconds"].values()) == pytest.approx(wall, rel=0.05)
    chip = snap["chip_seconds"]
    assert sum(chip.values()) == pytest.approx(wall, rel=0.02)
    assert chip.get("gold", 0.0) > 0.0 and chip.get("bulk", 0.0) > 0.0
    assert snap["overhead_fraction"] <= 0.01
    assert snap["tokens_committed"] > 0
    assert snap["goodput_fraction"] > 0.0
    assert snap["prefill_chunks"]["cold"] >= 1
    occ = snap["occupancy"]
    assert occ["slots_total"] == 2
    assert "pages_free" in occ and "radix_nodes" in occ
    assert occ["kv_pool_bytes"] > 0
    # SLO surface: per-tenant TPOT EWMA + chip-second attribution
    tenants = sched.slo_snapshot()["tenants"]
    assert tenants["gold"]["tpot_ewma_ms"] > 0.0
    assert tenants["gold"]["chip_seconds"] == pytest.approx(
        chip["gold"], abs=1e-5
    )


def test_ledger_flush_keeps_bytes_identical_and_zero_retraces(
    clf, tmp_path
):
    from music_analyst_tpu.serving.decode_loop import ContinuousScheduler

    kw = dict(n_slots=2, prefill_chunk=16, prompt_region=64,
              max_new_tokens=8, max_queue=16)
    base = ContinuousScheduler(clf, ledger_interval_ms=0, **kw)
    base.warmup()
    want = _texts(base, "base")

    sched = ContinuousScheduler(
        clf, ledger_interval_ms=5, ledger_dir=str(tmp_path), **kw
    )
    sched.warmup()
    variants0 = sched.runtime.compiled_variants()
    assert _texts(sched, "flush") == want  # greedy bytes identical
    assert sched.runtime.compiled_variants() - variants0 == 0
    sched.drain()  # final forced flush
    lines = (tmp_path / LEDGER_FILE).read_text().splitlines()
    assert lines
    final = json.loads(lines[-1])["ledger"]
    assert final["coverage"] >= 0.95
    assert final["flushes"] >= 1 and final["ledger_drops"] == 0


# ---------------------------------------------------------- fleet merge


def _replica_ledger(scale_s: float) -> dict:
    led = EngineLedger(2, interval_ms=0)
    led.record_tick(0.0, scale_s, decode_s=scale_s * 0.5, committed=3,
                    slots=[_Slot("gold"), None])
    led.record_tick(scale_s, 2 * scale_s, slots=[None, None])
    return led.snapshot()


def test_fleet_merge_sums_ledger_counters_exactly():
    """Router-merged ledger == counter-wise sum of per-replica ledgers —
    the same exactness oracle test_metrics_plane.py holds merge_flat to."""
    from music_analyst_tpu.observability.metrics_plane import (
        flatten_stats,
        merge_flat,
    )

    flats = [
        flatten_stats({"decode": {"ledger": _replica_ledger(1.0)}})[0],
        flatten_stats({"decode": {"ledger": _replica_ledger(0.5)}})[0],
    ]
    fleet = merge_flat(flats)
    assert fleet["decode.ledger.seconds.decode_useful"] == pytest.approx(
        0.5 + 0.25
    )
    assert fleet["decode.ledger.seconds.idle_bubble"] == pytest.approx(1.5)
    assert fleet["decode.ledger.chip_seconds.gold"] == pytest.approx(1.5)
    assert fleet[f"decode.ledger.chip_seconds.{IDLE_TENANT}"] == (
        pytest.approx(1.5)
    )
    assert fleet["decode.ledger.engine_wall_s"] == pytest.approx(3.0)
    assert fleet["decode.ledger.ticks"] == 4.0
    assert fleet["decode.ledger.idle_ticks"] == 2.0
    assert fleet["decode.ledger.tokens_committed"] == 6.0
    # fleet fractions recompute from merged seconds / merged wall;
    # per-replica ratios and config must never sum
    for key in ("decode.ledger.goodput_fraction", "decode.ledger.coverage",
                "decode.ledger.fractions.prefill",
                "decode.ledger.fractions.idle_bubble",
                "decode.ledger.overhead_fraction",
                "decode.ledger.interval_ms"):
        assert key not in fleet, key


def test_stale_replica_excluded_from_ledger_merge():
    from music_analyst_tpu.observability.metrics_plane import MetricsPlane

    plane = MetricsPlane(50.0)
    plane.ingest_replica(
        "r0", {"decode": {"ledger": _replica_ledger(1.0)}}
    )
    plane.ingest_replica(
        "r1", {"decode": {"ledger": _replica_ledger(0.5)}}
    )
    plane.mark_replica_stale("r1")
    merged = plane.fleet_snapshot()["merged"]
    assert merged["decode.ledger.engine_wall_s"] == pytest.approx(2.0)
    assert merged["decode.ledger.seconds.decode_useful"] == (
        pytest.approx(0.5)
    )


# -------------------------------------------------------------- monitor


def _monitor_stats(idle_frac: float = 0.5) -> dict:
    ledger = _replica_ledger(1.0)
    ledger["occupancy"] = {
        "slots_total": 2, "slots_active": 1,
        "pages_free": 12, "pages_pinned": 3,
    }
    ledger["fractions"]["idle_bubble"] = idle_frac
    return {
        "mode": "server", "uptime_s": 1.0, "draining": False,
        "requests": {},
        "decode": {
            "ledger": ledger,
            "speculation": {"acceptance_rate": 0.75},
        },
    }


def test_monitor_engine_panel_rows_and_render():
    from music_analyst_tpu.observability.monitor import (
        build_view,
        extract_engine_row,
        render_view,
    )

    stats = _monitor_stats()
    row = extract_engine_row("local", stats)
    assert row["occupancy"] == 0.5
    assert row["pages_free"] == 12 and row["pages_pinned"] == 3
    assert row["spec_accept"] == 0.75
    assert row["goodput"] == stats["decode"]["ledger"]["goodput_fraction"]
    view = build_view({"stats": stats})
    assert view["engine"] and view["idle_bubble_max"] == 0.5
    text = "\n".join(render_view(view))
    assert "engine panel (goodput ledger):" in text
    assert "pool free=12 pinned=3" in text
    assert "spec=0.75" in text


def test_monitor_engine_row_absent_without_scheduler():
    from music_analyst_tpu.observability.monitor import (
        build_view,
        extract_engine_row,
    )

    assert extract_engine_row("local", {"requests": {}}) is None
    view = build_view({"stats": {"requests": {}}})
    assert view["engine"] == [] and view["idle_bubble_max"] is None


def _stub_stats_server(sock_path: str, stats: dict) -> threading.Thread:
    srv = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
    srv.bind(sock_path)
    srv.listen(1)

    def _serve():
        conn, _ = srv.accept()
        rfile = conn.makefile("r", encoding="utf-8")
        req = json.loads(rfile.readline())
        reply = {"id": req["id"], "ok": True, "stats": stats}
        conn.sendall((json.dumps(reply) + "\n").encode("utf-8"))
        conn.close()
        srv.close()

    thread = threading.Thread(target=_serve, daemon=True)
    thread.start()
    return thread


def test_monitor_once_idle_bubble_gate_exit_codes(tmp_path, capsys):
    from music_analyst_tpu.observability.monitor import run_monitor

    sock = str(tmp_path / "gate.sock")
    _stub_stats_server(sock, _monitor_stats(idle_frac=0.6))
    assert run_monitor(sock, once=True, idle_bubble_gate=0.5) == 1
    assert "exceeds gate" in capsys.readouterr().err

    sock2 = str(tmp_path / "ok.sock")
    _stub_stats_server(sock2, _monitor_stats(idle_frac=0.2))
    assert run_monitor(sock2, once=True, idle_bubble_gate=0.5) == 0


# --------------------------------------------------------------- report


def test_telemetry_report_ledger_trajectory_and_chip_table(tmp_path):
    from music_analyst_tpu.observability.report import (
        build_report,
        load_run,
        render_report,
    )

    d = tmp_path / "run"
    d.mkdir()
    early = _replica_ledger(0.5)
    final = _replica_ledger(1.0)
    with open(d / "engine_ledger.jsonl", "w", encoding="utf-8") as fh:
        fh.write(json.dumps({"type": "ledger", "t": 1.0,
                             "ledger": early}) + "\n")
        fh.write(json.dumps({"type": "ledger", "t": 2.0,
                             "ledger": final}) + "\n")
    rec = load_run(str(d))
    assert rec is not None
    summary = rec["engine_ledger"]
    assert summary["records"] == 2
    # records are cumulative — the LAST one is the run's final ledger
    assert summary["goodput_fraction"] == final["goodput_fraction"]
    assert summary["chip_seconds"] == final["chip_seconds"]
    report = build_report([rec])
    assert report["ledger_runs"][0]["goodput_fraction"] == (
        final["goodput_fraction"]
    )
    assert report["chip_seconds_by_tenant"]["gold"] == pytest.approx(
        final["chip_seconds"]["gold"]
    )
    text = "\n".join(render_report(report))
    assert "engine ledger (goodput trajectory):" in text
    assert "chip-seconds by tenant (all runs):" in text
    assert "gold" in text


def test_telemetry_report_ledger_manifest_fallback(tmp_path):
    from music_analyst_tpu.observability.report import load_run

    d = tmp_path / "run"
    d.mkdir()
    with open(d / "run_manifest.json", "w", encoding="utf-8") as fh:
        json.dump(
            {"serving": {"decode": {"ledger": _replica_ledger(1.0)}}}, fh
        )
    rec = load_run(str(d))
    assert rec is not None
    assert rec["engine_ledger"]["records"] == 0  # manifest, not jsonl
    assert rec["engine_ledger"]["goodput_fraction"] == pytest.approx(
        _replica_ledger(1.0)["goodput_fraction"]
    )
