"""Sentiment pipeline e2e with the mock backend: artifacts + counts."""

import csv
import json

from music_analyst_tpu.engines.sentiment import get_backend, run_sentiment
from tests.test_keyword_sentiment import reference_mock_classify


def test_end_to_end_mock(fixture_csv, tmp_path):
    result = run_sentiment(
        str(fixture_csv), mock=True, output_dir=str(tmp_path), quiet=True
    )
    # Oracle: run the reference heuristic over the same DictReader rows.
    import csv as _csv

    with open(fixture_csv, newline="", encoding="utf-8") as fh:
        rows = list(_csv.DictReader(fh))
    want = [reference_mock_classify(r.get("text") or "") for r in rows]
    got = [r.label for r in result.rows]
    assert got == want

    totals = json.loads((tmp_path / "sentiment_totals.json").read_text())
    assert list(totals.keys()) == ["Positive", "Neutral", "Negative"]
    assert sum(totals.values()) == len(rows)

    with open(tmp_path / "sentiment_details.csv", newline="") as fh:
        detail_rows = list(csv.DictReader(fh))
    assert [r["label"] for r in detail_rows] == want
    assert all(
        len(r["latency_seconds"].split(".")[1]) == 4 for r in detail_rows
    ), "latency must be 4-decimal formatted"


def test_limit_respected(fixture_csv, tmp_path):
    result = run_sentiment(
        str(fixture_csv), mock=True, limit=2, output_dir=str(tmp_path), quiet=True
    )
    assert len(result.rows) == 2


def test_backend_dispatch():
    assert get_backend("llama3", mock=True).name == "mock"
    assert get_backend("mock").name == "mock"


def test_length_buckets_end_to_end(fixture_csv, tmp_path):
    """Bucketed encoder run produces the full artifact set with one label
    per dataset row."""
    result = run_sentiment(
        str(fixture_csv),
        model="distilbert-tiny",
        output_dir=str(tmp_path),
        quiet=True,
        length_buckets=(16, 32),
        batch_size=4,
    )
    assert sum(result.counts.values()) == len(result.rows) > 0
    assert (tmp_path / "sentiment_totals.json").exists()


def test_length_buckets_rejected_for_non_encoder(fixture_csv, tmp_path):
    import pytest

    with pytest.raises(ValueError, match="encoder-classifier"):
        run_sentiment(
            str(fixture_csv), mock=True, output_dir=str(tmp_path),
            quiet=True, length_buckets=(16,),
        )


def test_injected_backend_guard_matches_get_backend_unset(fixture_csv,
                                                          tmp_path):
    """run_sentiment's injected-backend guard and get_backend must agree on
    what an "unset" length_buckets is: an empty sequence means no buckets
    in both entry points (r4 advisor finding), while a non-empty one still
    raises alongside an explicit backend."""
    import pytest

    from music_analyst_tpu.models.mock import MockKeywordClassifier

    result = run_sentiment(
        str(fixture_csv), backend=MockKeywordClassifier(),
        output_dir=str(tmp_path), quiet=True, length_buckets=(),
    )
    assert sum(result.counts.values()) == len(result.rows) > 0
    with pytest.raises(ValueError, match="cannot be combined"):
        run_sentiment(
            str(fixture_csv), backend=MockKeywordClassifier(),
            output_dir=str(tmp_path), quiet=True, length_buckets=(16,),
        )
    # A scalar slip gets a clear message at both entry points, not a bare
    # len(int) TypeError from deep inside.
    with pytest.raises(TypeError, match="sequence of ints"):
        run_sentiment(
            str(fixture_csv), backend=MockKeywordClassifier(),
            output_dir=str(tmp_path), quiet=True, length_buckets=32,
        )
    with pytest.raises(TypeError, match="sequence of ints"):
        get_backend("distilbert-tiny", length_buckets=32)


def test_mesh_capability_gate():
    """mesh= must reach only the on-device model families; the keyword
    kernel and the Ollama HTTP passthrough take no mesh kwarg."""
    from music_analyst_tpu.engines.sentiment import _mesh_capable

    assert _mesh_capable("distilbert", False)
    assert _mesh_capable("distilbert-tiny-int8", False)
    assert _mesh_capable("llama3-tiny", False)
    assert not _mesh_capable("mock", False)
    assert not _mesh_capable("distilbert", True)  # --mock wins
    assert not _mesh_capable("ollama:llama3", False)
