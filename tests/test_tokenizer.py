"""Tokenizer parity with both reference tokenizers (SURVEY.md §5 contract #1)."""

from music_analyst_tpu.data.tokenizer import tokenize_ascii, tokenize_latin1


class TestAsciiTokenizer:
    """C-binary semantics: src/parallel_spotify.c:350-394."""

    def test_basic_lowercase_and_min_length(self):
        assert tokenize_ascii("Look at her FACE it") == ["look", "her", "face"]

    def test_apostrophes_preserved_and_counted(self):
        assert tokenize_ascii("it's don't I'm") == ["it's", "don't", "i'm"]

    def test_all_apostrophe_token_is_counted(self):
        # The C tokenizer counts ''' (3 bytes of token chars) as a word.
        assert tokenize_ascii("x ''' y") == ["'''"]

    def test_short_tokens_dropped(self):
        assert tokenize_ascii("a an the it") == ["the"]

    def test_non_ascii_bytes_break_tokens(self):
        # 'café' = b'caf\xc3\xa9' — the UTF-8 bytes are separators, leaving
        # 'caf' (>=3); the fragments of 'naïve' ('na', 've') are < 3 bytes
        # and are dropped.
        assert tokenize_ascii("café naïve") == ["caf"]
        # 'naïveté' -> fragments 'na' (dropped), 'vet' (kept)
        assert tokenize_ascii("naïveté café") == ["vet", "caf"]

    def test_digits_are_token_chars(self):
        assert tokenize_ascii("route 66 abc123") == ["route", "abc123"]

    def test_punctuation_separates(self):
        assert tokenize_ascii("hi-de-hi! (ho)") == []
        assert tokenize_ascii("one,two;three") == ["one", "two", "three"]

    def test_bytes_input(self):
        assert tokenize_ascii(b"Hello WORLD") == ["hello", "world"]

    def test_trailing_token_flushed(self):
        assert tokenize_ascii("ends with word") == ["ends", "with", "word"]


class TestLatin1Tokenizer:
    """Serial-tool semantics: scripts/word_count_per_song.py:27-39."""

    def test_accented_chars_are_token_chars(self):
        assert list(tokenize_latin1("café naïve")) == ["café", "naïve"]

    def test_all_apostrophe_rejected(self):
        assert list(tokenize_latin1("x ''' y")) == []

    def test_min_length_in_characters(self):
        # 'été' is 3 characters (but 5 UTF-8 bytes) — counted here.
        assert list(tokenize_latin1("été ok")) == ["été"]

    def test_lowercasing_is_unicode(self):
        assert list(tokenize_latin1("CAFÉ")) == ["café"]

    def test_divergence_from_ascii_path(self):
        text = "café"
        assert list(tokenize_latin1(text)) == ["café"]
        assert tokenize_ascii(text) == ["caf"]
