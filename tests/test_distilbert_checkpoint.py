"""HF torch checkpoint loading for the DistilBERT classifier.

Mirrors ``tests/test_llama_checkpoint.py``: fabricate a tiny torch
``state_dict`` with the exact HF ``distilbert-base-uncased-finetuned-sst-2``
key schema (weights AND biases), load it through
``load_hf_torch_checkpoint``, and check the Flax forward against an
independent torch re-implementation computed straight from the state_dict —
so every transpose, head reshape, and bias in the mapping is verified
end-to-end, not just shape-checked.
"""

import numpy as np
import pytest

torch = pytest.importorskip("torch")

import jax
import jax.numpy as jnp

from music_analyst_tpu.models.distilbert import (
    DistilBertConfig,
    DistilBertForSentiment,
    load_hf_torch_checkpoint,
)

CFG = DistilBertConfig(
    vocab_size=128, dim=32, n_layers=2, n_heads=4, hidden_dim=64,
    max_positions=16, dtype="float32",
)
# The model pins HF DistilBERT's hardcoded epsilon; the oracle must use
# the same one to isolate mapping errors from eps noise.
from music_analyst_tpu.models.distilbert import LN_EPS  # noqa: E402


def _hf_state_dict(cfg: DistilBertConfig, seed: int = 0):
    g = torch.Generator().manual_seed(seed)

    def r(*shape):
        return torch.randn(*shape, generator=g) * 0.05

    sd = {
        "distilbert.embeddings.word_embeddings.weight": r(cfg.vocab_size, cfg.dim),
        "distilbert.embeddings.position_embeddings.weight": r(
            cfg.max_positions, cfg.dim
        ),
        "distilbert.embeddings.LayerNorm.weight": 1 + r(cfg.dim),
        "distilbert.embeddings.LayerNorm.bias": r(cfg.dim),
    }
    for i in range(cfg.n_layers):
        p = f"distilbert.transformer.layer.{i}."
        for lin in ("q_lin", "k_lin", "v_lin", "out_lin"):
            sd[p + f"attention.{lin}.weight"] = r(cfg.dim, cfg.dim)
            sd[p + f"attention.{lin}.bias"] = r(cfg.dim)
        sd[p + "sa_layer_norm.weight"] = 1 + r(cfg.dim)
        sd[p + "sa_layer_norm.bias"] = r(cfg.dim)
        sd[p + "ffn.lin1.weight"] = r(cfg.hidden_dim, cfg.dim)
        sd[p + "ffn.lin1.bias"] = r(cfg.hidden_dim)
        sd[p + "ffn.lin2.weight"] = r(cfg.dim, cfg.hidden_dim)
        sd[p + "ffn.lin2.bias"] = r(cfg.dim)
        sd[p + "output_layer_norm.weight"] = 1 + r(cfg.dim)
        sd[p + "output_layer_norm.bias"] = r(cfg.dim)
    sd["pre_classifier.weight"] = r(cfg.dim, cfg.dim)
    sd["pre_classifier.bias"] = r(cfg.dim)
    sd["classifier.weight"] = r(cfg.n_classes, cfg.dim)
    sd["classifier.bias"] = r(cfg.n_classes)
    return sd


def _oracle_forward(sd, cfg: DistilBertConfig, ids: torch.Tensor):
    """DistilBERT forward in plain torch ops, straight from the state_dict."""
    F = torch.nn.functional
    hd = cfg.dim // cfg.n_heads
    B, S = ids.shape

    def ln(x, prefix):
        w, b = sd[prefix + ".weight"], sd[prefix + ".bias"]
        mu = x.mean(-1, keepdim=True)
        var = x.var(-1, unbiased=False, keepdim=True)
        return (x - mu) / torch.sqrt(var + LN_EPS) * w + b

    def lin(x, prefix):
        return x @ sd[prefix + ".weight"].T + sd[prefix + ".bias"]

    x = (
        sd["distilbert.embeddings.word_embeddings.weight"][ids]
        + sd["distilbert.embeddings.position_embeddings.weight"][
            torch.arange(S)
        ]
    )
    x = ln(x, "distilbert.embeddings.LayerNorm")
    for i in range(cfg.n_layers):
        p = f"distilbert.transformer.layer.{i}"
        q = lin(x, p + ".attention.q_lin").view(B, S, cfg.n_heads, hd)
        k = lin(x, p + ".attention.k_lin").view(B, S, cfg.n_heads, hd)
        v = lin(x, p + ".attention.v_lin").view(B, S, cfg.n_heads, hd)
        scores = torch.einsum("bqhd,bkhd->bhqk", q, k) * hd**-0.5
        ctx = torch.einsum(
            "bhqk,bkhd->bqhd", F.softmax(scores, dim=-1), v
        ).reshape(B, S, cfg.dim)
        x = ln(x + lin(ctx, p + ".attention.out_lin"), p + ".sa_layer_norm")
        h = F.gelu(lin(x, p + ".ffn.lin1"))  # exact erf gelu, as the model
        x = ln(x + lin(h, p + ".ffn.lin2"), p + ".output_layer_norm")
    h = F.relu(lin(x[:, 0], "pre_classifier"))
    return lin(h, "classifier")


def _init_params(cfg: DistilBertConfig):
    model = DistilBertForSentiment(cfg)
    dummy = (jnp.zeros((1, 8), jnp.int32), jnp.ones((1,), jnp.int32))
    return model, model.init(jax.random.key(0), *dummy)["params"]


def test_loader_logits_match_torch_oracle(tmp_path):
    sd = _hf_state_dict(CFG)
    path = tmp_path / "pytorch_model.bin"
    torch.save(sd, path)
    model, params = _init_params(CFG)
    loaded = load_hf_torch_checkpoint(params, str(path))

    # Spot-check the head reshapes directly.
    hd = CFG.dim // CFG.n_heads
    q = sd["distilbert.transformer.layer.0.attention.q_lin.weight"].numpy()
    np.testing.assert_allclose(
        np.asarray(loaded["encoder"]["layer_0"]["attention"]["q_proj"]["kernel"]),
        q.T.reshape(CFG.dim, CFG.n_heads, hd),
    )
    qb = sd["distilbert.transformer.layer.0.attention.q_lin.bias"].numpy()
    np.testing.assert_allclose(
        np.asarray(loaded["encoder"]["layer_0"]["attention"]["q_proj"]["bias"]),
        qb.reshape(CFG.n_heads, hd),
    )

    S = 8
    ids = torch.tensor([[3, 17, 99, 4, 55, 2, 81, 6]], dtype=torch.long)
    want = _oracle_forward(sd, CFG, ids).numpy()
    got = np.asarray(
        model.apply(
            {"params": loaded},
            jnp.asarray(ids.numpy(), jnp.int32),
            jnp.full((1,), S, jnp.int32),  # full length: no padding mask
        )
    )
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)


def test_loader_rejects_unconsumed_keys(tmp_path):
    sd = _hf_state_dict(CFG)
    sd["distilbert.embeddings.position_ids"] = torch.arange(16)  # buffer: ok
    sd["vocab_transform.weight"] = torch.zeros(4, 4)  # MLM head: NOT ok
    path = tmp_path / "pytorch_model.bin"
    torch.save(sd, path)
    _, params = _init_params(CFG)
    with pytest.raises(ValueError, match="vocab_transform"):
        load_hf_torch_checkpoint(params, str(path))


def test_classifier_uses_loaded_checkpoint(tmp_path):
    from music_analyst_tpu.models.distilbert import DistilBertClassifier

    sd = _hf_state_dict(CFG, seed=1)
    path = tmp_path / "pytorch_model.bin"
    torch.save(sd, path)
    clf = DistilBertClassifier(
        config=CFG, checkpoint_path=str(path), max_len=16
    )
    assert clf.pretrained
    labels = clf.classify_batch(["la la love", ""])
    assert labels[1] == "Neutral"  # empty-lyric reference rule
    assert all(l in ("Positive", "Neutral", "Negative") for l in labels)
