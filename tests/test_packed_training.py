"""Packed-documents training loss ≡ per-document training loss.

``causal_lm_loss`` with ``segment_ids`` (engines/train.py) must charge
exactly the same per-token cross-entropies for documents sharing a row
as for documents in their own rows: same attention visibility (block-
diagonal causal), same position embeddings (restarted per document),
same valid-target set (no cross-document boundary prediction).
"""

import jax
import jax.numpy as jnp
import numpy as np

from music_analyst_tpu.engines.train import causal_lm_loss
from music_analyst_tpu.models.layers import causal_mask
from music_analyst_tpu.models.llama import LlamaConfig, LlamaModel

CFG = LlamaConfig(
    vocab_size=96, dim=32, n_layers=2, n_heads=4, n_kv_heads=2,
    hidden_dim=64, rope_theta=1e4, max_seq_len=64, dtype="float32",
)


def _model_and_params(ids):
    model = LlamaModel(CFG)
    pos = jnp.zeros_like(ids)
    params = model.init(
        jax.random.key(0), ids, pos,
        causal_mask(ids.shape[1], ids.shape[1], 0),
    )["params"]
    return model, params


def test_packed_loss_matches_separate_rows():
    rng = np.random.default_rng(0)
    l1, l2 = 20, 28
    doc1 = rng.integers(1, CFG.vocab_size, l1)
    doc2 = rng.integers(1, CFG.vocab_size, l2)

    # Packed: one row [doc1 doc2 pad...], segments 1/2/0.
    S = 56
    packed = np.zeros((1, S), np.int32)
    packed[0, :l1] = doc1
    packed[0, l1 : l1 + l2] = doc2
    seg = np.zeros((1, S), np.int32)
    seg[0, :l1] = 1
    seg[0, l1 : l1 + l2] = 2
    packed = jnp.asarray(packed)
    model, params = _model_and_params(packed)
    packed_loss = causal_lm_loss(
        model, params, packed, jnp.asarray([l1 + l2], jnp.int32),
        segment_ids=jnp.asarray(seg),
    )

    # Separate: each document in its own padded row (same S so the same
    # compiled shapes/params apply); combine as a token-weighted mean,
    # which is what one mean over the union of valid tokens is.
    def separate_loss(doc):
        row = np.zeros((1, S), np.int32)
        row[0, : len(doc)] = doc
        return float(
            causal_lm_loss(
                model, params, jnp.asarray(row),
                jnp.asarray([len(doc)], jnp.int32),
            )
        )

    n1, n2 = l1 - 1, l2 - 1  # valid next-token targets per document
    want = (separate_loss(doc1) * n1 + separate_loss(doc2) * n2) / (n1 + n2)
    np.testing.assert_allclose(float(packed_loss), want, rtol=2e-5,
                               atol=2e-5)


def test_packed_loss_differs_without_segments():
    """Sanity: dropping the segment ids (cross-document attention and the
    boundary target) must CHANGE the loss — the mask is load-bearing."""
    rng = np.random.default_rng(1)
    S = 48
    row = jnp.asarray(rng.integers(1, CFG.vocab_size, (1, S)), jnp.int32)
    seg = jnp.asarray([[1] * 24 + [2] * 24], jnp.int32)
    model, params = _model_and_params(row)
    lengths = jnp.asarray([S], jnp.int32)
    with_seg = float(causal_lm_loss(model, params, row, lengths,
                                    segment_ids=seg))
    without = float(causal_lm_loss(model, params, row, lengths))
    assert abs(with_seg - without) > 1e-6


def test_packed_loss_matches_separate_rows_flash():
    """Same contract on the flash impl: the loss routes segment ids to
    the kernel natively (mask arrays are discarded on that path)."""
    import dataclasses

    rng = np.random.default_rng(3)
    # The loss shifts inputs to S-1 tokens; pick S so the flash kernel's
    # block divisor search sees a clean 64-wide sequence.
    l1, l2 = 24, 40
    S = l1 + l2 + 1
    row = np.zeros((1, S), np.int32)
    row[0, :l1] = rng.integers(1, CFG.vocab_size, l1)
    row[0, l1 : l1 + l2] = rng.integers(1, CFG.vocab_size, l2)
    seg = np.zeros((1, S), np.int32)
    seg[0, :l1] = 1
    seg[0, l1 : l1 + l2] = 2
    fcfg = dataclasses.replace(CFG, attn_impl="flash")
    fmodel = LlamaModel(fcfg)
    ids = jnp.asarray(row)
    params = fmodel.init(
        jax.random.key(0), ids[:, :-1], jnp.zeros((1, S - 1), jnp.int32),
        None, lengths=jnp.asarray([S - 1], jnp.int32),
    )["params"]
    packed_loss = float(causal_lm_loss(
        fmodel, params, ids, jnp.asarray([l1 + l2], jnp.int32),
        segment_ids=jnp.asarray(seg),
    ))
    # Dense oracle on the same params (flash ≡ dense is its own tested
    # invariant; here it ties the packed-flash loss to the packed-dense
    # number this file already proved equals the per-document losses).
    dense_loss = float(causal_lm_loss(
        LlamaModel(CFG), params, ids, jnp.asarray([l1 + l2], jnp.int32),
        segment_ids=jnp.asarray(seg),
    ))
    np.testing.assert_allclose(packed_loss, dense_loss, rtol=2e-5,
                               atol=2e-5)


def test_train_step_accepts_segment_ids():
    """The jitted SPMD train step threads packed-document ids through to
    the loss (sharded like the tokens)."""
    from music_analyst_tpu.engines.train import (
        init_train_state,
        make_optimizer,
        make_train_step,
    )
    from music_analyst_tpu.parallel.mesh import build_mesh, MeshSpec

    mesh = build_mesh(MeshSpec((("dp", 4), ("sp", 2))))
    rng = np.random.default_rng(4)
    B, S = 4, 32
    ids = jnp.asarray(rng.integers(1, CFG.vocab_size, (B, S)), jnp.int32)
    seg = jnp.asarray(
        np.concatenate([np.full((B, 16), 1), np.full((B, 16), 2)], axis=1),
        jnp.int32,
    )
    lengths = jnp.full((B,), S, jnp.int32)
    model = LlamaModel(CFG)
    opt = make_optimizer()
    state = init_train_state(model, opt, (ids, lengths), mesh=mesh)
    step = make_train_step(model, opt, mesh=mesh)
    state, packed = step(state, ids, lengths, seg)
    _, unpacked = step(state, ids, lengths)
    assert np.isfinite(float(packed)) and np.isfinite(float(unpacked))
    assert abs(float(packed) - float(unpacked)) > 1e-7  # mask load-bearing


def test_packed_loss_is_differentiable():
    rng = np.random.default_rng(2)
    S = 32
    row = jnp.asarray(rng.integers(1, CFG.vocab_size, (1, S)), jnp.int32)
    seg = jnp.asarray([[1] * 10 + [2] * 15 + [0] * 7], jnp.int32)
    model, params = _model_and_params(row)
    grads = jax.grad(
        lambda p: causal_lm_loss(model, p, row,
                                 jnp.asarray([25], jnp.int32),
                                 segment_ids=seg)
    )(params)
    leaves = jax.tree_util.tree_leaves(grads)
    assert all(np.isfinite(np.asarray(g)).all() for g in leaves)
    assert any(float(np.abs(np.asarray(g)).sum()) > 0 for g in leaves)
