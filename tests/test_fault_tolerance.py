"""Resume and transient-failure recovery.

The reference is fail-fast with no recovery: any HTTP error crashes the
sentiment run (``scripts/sentiment_classifier.py:96,176-180``) and every run
recomputes from the CSV (SURVEY.md §5).  Here ``sentiment_details.csv``
streams as batches complete, interrupted runs resume from the on-disk
prefix, and the Ollama passthrough retries transient errors with backoff.
"""

import csv
import json

import pytest

from music_analyst_tpu.engines.sentiment import run_sentiment

import os

FIXTURE = os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "fixtures", "mini_songs.csv"
)


def _read_details(path):
    with open(path, newline="", encoding="utf-8") as fh:
        return list(csv.DictReader(fh))


def test_resume_completes_partial_run(tmp_path):
    full_dir = tmp_path / "full"
    part_dir = tmp_path / "partial"

    full = run_sentiment(FIXTURE, mock=True, output_dir=str(full_dir),
                         quiet=True)
    n_total = len(full.rows)
    assert n_total > 4

    # Simulate an interrupted run: classify only the first 3 songs.
    run_sentiment(FIXTURE, mock=True, limit=3, output_dir=str(part_dir),
                  quiet=True)
    assert len(_read_details(part_dir / "sentiment_details.csv")) == 3

    # Resume finishes the rest without reclassifying the prefix.
    resumed = run_sentiment(FIXTURE, mock=True, output_dir=str(part_dir),
                            quiet=True, resume=True)
    assert len(resumed.rows) == n_total - 3  # only the remainder ran

    assert _read_details(part_dir / "sentiment_details.csv") == _read_details(
        full_dir / "sentiment_details.csv"
    )
    with open(part_dir / "sentiment_totals.json") as fh:
        assert json.load(fh) == full.counts


def test_resume_truncates_torn_final_line(tmp_path):
    """A SIGKILL mid-write leaves a torn row; resume must re-classify it."""
    full_dir = tmp_path / "full"
    part_dir = tmp_path / "partial"
    full = run_sentiment(FIXTURE, mock=True, output_dir=str(full_dir),
                         quiet=True)

    run_sentiment(FIXTURE, mock=True, limit=3, output_dir=str(part_dir),
                  quiet=True)
    details = part_dir / "sentiment_details.csv"
    with open(details, "ab") as fh:  # torn write: row without newline
        fh.write(b"Torn Artist,Torn Song,Pos")

    run_sentiment(FIXTURE, mock=True, output_dir=str(part_dir), quiet=True,
                  resume=True)
    assert _read_details(details) == _read_details(
        full_dir / "sentiment_details.csv"
    )
    with open(part_dir / "sentiment_totals.json") as fh:
        assert json.load(fh) == full.counts


def test_resume_without_existing_details_is_full_run(tmp_path):
    result = run_sentiment(FIXTURE, mock=True, output_dir=str(tmp_path),
                           quiet=True, resume=True)
    assert len(result.rows) == sum(result.counts.values())


def test_details_stream_during_run(tmp_path):
    """A crash mid-run leaves the completed batches on disk."""

    class ExplodingBackend:
        name = "boom"
        reports_latency = False
        collects = 0

        def submit(self, texts):
            return list(texts)

        def collect(self, handle):
            self.collects += 1
            if self.collects > 1:
                raise RuntimeError("injected failure")
            return ["Neutral"] * len(handle)

    with pytest.raises(RuntimeError, match="injected"):
        run_sentiment(FIXTURE, backend=ExplodingBackend(), batch_size=2,
                      output_dir=str(tmp_path), quiet=True)
    rows = _read_details(tmp_path / "sentiment_details.csv")
    assert len(rows) == 2  # first batch persisted before the crash


class _FakeResponse:
    def __init__(self, status=200, body="Positive"):
        self.status_code = status
        self._body = body

    def raise_for_status(self):
        import requests

        if self.status_code >= 400:
            exc = requests.HTTPError(f"status {self.status_code}")
            exc.response = self
            raise exc

    def json(self):
        return {"response": self._body}


def test_ollama_retries_transient_then_succeeds(monkeypatch):
    import requests

    from music_analyst_tpu.models.ollama import OllamaClassifier

    calls = []

    def fake_post(url, json=None, timeout=None):
        calls.append(url)
        if len(calls) <= 2:
            raise requests.ConnectionError("transient")
        return _FakeResponse()

    monkeypatch.setattr(requests, "post", fake_post)
    clf = OllamaClassifier(retries=2, backoff_seconds=0.0)
    assert clf.classify_batch(["some lyrics"]) == ["Positive"]
    assert len(calls) == 3


def test_ollama_exhausted_retries_raise(monkeypatch):
    import requests

    from music_analyst_tpu.models.ollama import OllamaClassifier

    def fake_post(url, json=None, timeout=None):
        raise requests.ConnectionError("down")

    monkeypatch.setattr(requests, "post", fake_post)
    clf = OllamaClassifier(retries=1, backoff_seconds=0.0)
    with pytest.raises(requests.ConnectionError):
        clf.classify_batch(["some lyrics"])


def test_ollama_client_error_not_retried(monkeypatch):
    import requests

    from music_analyst_tpu.models.ollama import OllamaClassifier

    calls = []

    def fake_post(url, json=None, timeout=None):
        calls.append(url)
        return _FakeResponse(status=404)

    monkeypatch.setattr(requests, "post", fake_post)
    clf = OllamaClassifier(retries=3, backoff_seconds=0.0)
    with pytest.raises(requests.HTTPError):
        clf.classify_batch(["some lyrics"])
    assert len(calls) == 1


def test_resume_torn_inside_quoted_field(tmp_path):
    """A newline inside an open quoted field is row content, not a row end;
    truncation must cut back to the last real row boundary."""
    part_dir = tmp_path / "p"
    run_sentiment(FIXTURE, mock=True, limit=3, output_dir=str(part_dir),
                  quiet=True)
    details = part_dir / "sentiment_details.csv"
    before = _read_details(details)
    # torn write: quoted field opened, interior newline, then the kill
    with open(details, "ab") as fh:
        fh.write(b'"Torn\nArtist,Torn Song,Pos')

    resumed = run_sentiment(FIXTURE, mock=True, output_dir=str(part_dir),
                            quiet=True, resume=True)
    rows = _read_details(details)
    assert rows[:3] == before
    assert len(rows) == 3 + len(resumed.rows)
    assert all("\n" not in r["label"] for r in rows)


def test_sync_backend_latencies_not_shifted_across_batches(tmp_path):
    """Measured per-song latencies must stay with their own batch even
    though the engine submits batch i+1 before collecting batch i."""

    class MeasuringBackend:
        name = "meter"
        reports_latency = True

        def __init__(self):
            self.batch_no = 0
            self.last_latencies = []

        def classify_batch(self, texts):
            self.batch_no += 1
            # batch 1 -> 1.0s each, batch 2 -> 2.0s each, ...
            self.last_latencies = [float(self.batch_no)] * len(texts)
            return ["Neutral"] * len(texts)

        def submit(self, texts):
            return self.classify_batch(texts)

        def collect(self, handle):
            return handle

    run_sentiment(FIXTURE, backend=MeasuringBackend(), batch_size=2,
                  output_dir=str(tmp_path), quiet=True)
    rows = _read_details(tmp_path / "sentiment_details.csv")
    # rows 0-1 from batch 1, rows 2-3 from batch 2, ...
    for i, row in enumerate(rows):
        assert float(row["latency_seconds"]) == float(i // 2 + 1), (i, row)
