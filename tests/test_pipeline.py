"""Pipeline parallelism: stacked stages == sequential model, grads flow."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from music_analyst_tpu.models.layers import causal_mask
from music_analyst_tpu.models.llama import LlamaBlock, LlamaConfig
from music_analyst_tpu.parallel.mesh import MeshSpec, build_mesh
from music_analyst_tpu.parallel.pipeline import (
    pipeline_apply,
    stack_layer_params,
    unstack_layer_params,
)


@pytest.fixture(scope="module")
def pp_mesh():
    return build_mesh(MeshSpec((("pp", 4),)), devices=jax.devices()[:4])


def test_stack_unstack_roundtrip():
    params = {
        f"layer_{i}": {"w": jnp.full((2, 3), float(i))} for i in range(8)
    }
    stacked, n_layers = stack_layer_params(params, 4)
    assert n_layers == 8
    assert stacked["w"].shape == (4, 2, 2, 3)
    restored = unstack_layer_params(stacked)
    for i in range(8):
        np.testing.assert_array_equal(
            np.asarray(restored[f"layer_{i}"]["w"]),
            np.asarray(params[f"layer_{i}"]["w"]),
        )


def test_toy_linear_pipeline_matches_sequential(pp_mesh):
    rng = np.random.default_rng(0)
    n_stages, k, d = 4, 2, 16
    weights = rng.normal(size=(n_stages * k, d, d)).astype(np.float32) * 0.1
    params = {f"layer_{i}": {"w": jnp.asarray(weights[i])} for i in range(8)}
    stacked, _ = stack_layer_params(params, n_stages)

    def stage_fn(stage_params, x):
        def layer(x, w):
            return jnp.tanh(x @ w), None

        out, _ = jax.lax.scan(layer, x, stage_params["w"])
        return out

    n_micro, mb = 8, 4
    x = rng.normal(size=(n_micro, mb, d)).astype(np.float32)

    got = np.asarray(pipeline_apply(stage_fn, stacked, jnp.asarray(x), pp_mesh))

    want = x.copy()
    for i in range(8):
        want = np.tanh(want @ weights[i])
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


def test_llama_blocks_pipeline_matches_sequential(pp_mesh):
    cfg = LlamaConfig(
        vocab_size=64, dim=32, n_layers=4, n_heads=4, n_kv_heads=2,
        hidden_dim=64, rope_theta=1e4, max_seq_len=64,
    )
    block = LlamaBlock(cfg)
    rng = np.random.default_rng(1)
    S, mb, n_micro = 8, 2, 4
    x0 = jnp.asarray(rng.normal(size=(mb, S, cfg.dim)), jnp.float32)
    params = {}
    key = jax.random.key(0)
    for i in range(cfg.n_layers):
        key, sub = jax.random.split(key)
        params[f"layer_{i}"] = block.init(
            sub, x0, causal_mask(S, S, 0), jnp.zeros((mb, S), jnp.int32), None
        )["params"]

    def apply_block(p, x):
        positions = jnp.broadcast_to(jnp.arange(x.shape[1]), x.shape[:2])
        out, _ = block.apply(
            {"params": p}, x, causal_mask(x.shape[1], x.shape[1], 0),
            positions, None,
        )
        return out

    # sequential reference
    want = jnp.broadcast_to(x0, (n_micro,) + x0.shape)
    outs = []
    for m in range(n_micro):
        h = want[m]
        for i in range(cfg.n_layers):
            h = apply_block(params[f"layer_{i}"], h)
        outs.append(np.asarray(h))
    want_np = np.stack(outs)

    stacked, _ = stack_layer_params(params, 4)

    def stage_fn(stage_params, x):
        def one(x, p):
            return apply_block(p, x), None

        out, _ = jax.lax.scan(one, x, stage_params)
        return out

    mbs = jnp.broadcast_to(x0, (n_micro,) + x0.shape)
    got = np.asarray(pipeline_apply(stage_fn, stacked, mbs, pp_mesh))
    np.testing.assert_allclose(got, want_np, rtol=2e-3, atol=2e-3)


def test_gradients_flow_through_pipeline(pp_mesh):
    rng = np.random.default_rng(2)
    d = 8
    params = {f"layer_{i}": {"w": jnp.asarray(rng.normal(size=(d, d)) * 0.1,
                                              jnp.float32)} for i in range(4)}
    stacked, _ = stack_layer_params(params, 4)
    x = jnp.asarray(rng.normal(size=(4, 2, d)), jnp.float32)

    def stage_fn(sp, h):
        def layer(h, w):
            return jnp.tanh(h @ w), None

        out, _ = jax.lax.scan(layer, h, sp["w"])
        return out

    def loss(stacked_params):
        out = pipeline_apply(stage_fn, stacked_params, x, pp_mesh)
        return jnp.sum(out**2)

    grads = jax.grad(loss)(stacked)
    g = np.asarray(grads["w"])
    assert g.shape == stacked["w"].shape
    assert np.isfinite(g).all()
    assert np.abs(g).sum() > 0  # every stage got a gradient
    # each stage's grad is nonzero
    assert all(np.abs(g[s]).sum() > 0 for s in range(4))
