"""Aux subsystems: sweep archiving, checkpoint round-trip, tracing, ollama."""

import json

import numpy as np
import jax.numpy as jnp
import pytest

from music_analyst_tpu.engines.sweep import run_sweep


def test_sweep_archives_per_run_metrics(fixture_csv, tmp_path):
    summary = run_sweep(
        str(fixture_csv),
        device_counts=[1, 2, 4],
        output_dir=str(tmp_path),
    )
    assert [r["devices"] for r in summary["runs"]] == [1, 2, 4]
    for n in (1, 2, 4):
        metrics = json.loads(
            (tmp_path / f"performance_metrics_np{n}.json").read_text()
        )
        assert metrics["processes"] == n
    assert (tmp_path / "sweep_summary.json").exists()


def test_checkpoint_roundtrip(tmp_path):
    from music_analyst_tpu.engines.checkpoint import (
        restore_train_state,
        save_train_state,
    )
    from music_analyst_tpu.engines.train import (
        init_train_state,
        make_optimizer,
        make_train_step,
    )
    from music_analyst_tpu.models.llama import LlamaConfig, LlamaModel

    cfg = LlamaConfig.tiny()
    model = LlamaModel(cfg)
    opt = make_optimizer()
    rng = np.random.default_rng(0)
    ids = jnp.asarray(rng.integers(1, 256, (2, 9)), jnp.int32)
    lengths = jnp.full((2,), 9, jnp.int32)
    state = init_train_state(model, opt, (ids, lengths))
    step = make_train_step(model, opt)
    state, _ = step(state, ids, lengths)

    path = save_train_state(state, str(tmp_path / "ckpt"))
    restored = restore_train_state(path, like=state)
    assert int(restored.step) == int(state.step)
    leaf_a = state.params["layer_0"]["feed_forward"]["gate_proj"]["kernel"]
    leaf_b = restored.params["layer_0"]["feed_forward"]["gate_proj"]["kernel"]
    np.testing.assert_array_equal(np.asarray(leaf_a), np.asarray(leaf_b))

    # resume: one more step from the restored state runs fine
    restored, loss = step(restored, ids, lengths)
    assert np.isfinite(float(loss))


def test_tracing_context(tmp_path):
    import jax
    from music_analyst_tpu.profiling.trace import annotate, maybe_trace

    with maybe_trace(str(tmp_path / "trace")):
        with annotate("unit-test-region"):
            jax.block_until_ready(jnp.ones((8, 8)) @ jnp.ones((8, 8)))
    assert any((tmp_path / "trace").rglob("*")), "trace files written"
    # disabled path is a no-op
    with maybe_trace(None):
        pass


def test_ollama_backend_contract(monkeypatch):
    """Offline contract test: endpoint/prompt/normalization wiring."""
    from music_analyst_tpu.engines.sentiment import get_backend

    clf = get_backend("ollama:mymodel")
    assert clf.name == "ollama"
    assert clf.model == "mymodel"

    calls = {}

    class FakeResponse:
        def raise_for_status(self):
            pass

        def json(self):
            return {"response": "positive with enthusiasm"}

    def fake_post(url, json=None, timeout=None):
        calls["url"] = url
        calls["payload"] = json
        return FakeResponse()

    import requests

    monkeypatch.setattr(requests, "post", fake_post)
    labels = clf.classify_batch(["great lyrics", ""])
    assert labels == ["Positive", "Neutral"]  # empty short-circuits, no HTTP
    assert calls["url"].endswith("/api/generate")
    assert calls["payload"]["model"] == "mymodel"
    assert "Lyrics:" in calls["payload"]["prompt"]
    assert clf.last_latencies[1] == 0.0


def test_checkpoint_restores_across_mesh_layouts(tmp_path):
    """A state saved from a dp×tp mesh restores onto a differently-factored
    mesh (the elastic-resume contract the reference lacks entirely —
    SURVEY.md §5 'Checkpoint/resume: none')."""
    from music_analyst_tpu.engines.checkpoint import (
        restore_train_state,
        save_train_state,
    )
    from music_analyst_tpu.engines.train import (
        init_train_state,
        make_optimizer,
        make_train_step,
    )
    from music_analyst_tpu.models.llama import LlamaConfig, LlamaModel
    from music_analyst_tpu.parallel.mesh import MeshSpec, build_mesh

    cfg = LlamaConfig(
        vocab_size=256, dim=32, n_layers=2, n_heads=4, n_kv_heads=4,
        hidden_dim=64, rope_theta=1e4, max_seq_len=64, dtype="float32",
    )
    model = LlamaModel(cfg)
    opt = make_optimizer()
    rng = np.random.default_rng(1)
    ids = jnp.asarray(rng.integers(1, 256, (8, 9)), jnp.int32)
    lengths = jnp.full((8,), 9, jnp.int32)

    mesh_a = build_mesh(MeshSpec((("dp", 4), ("tp", 2))))
    state = init_train_state(model, opt, (ids, lengths), mesh=mesh_a,
                             zero1=True)
    step_a = make_train_step(model, opt, mesh=mesh_a)
    state, loss_a = step_a(state, ids, lengths)
    path = save_train_state(state, str(tmp_path / "ckpt"))

    # Restore onto a different factoring: dp=2 × tp=4.
    mesh_b = build_mesh(MeshSpec((("dp", 2), ("tp", 4))))
    template = init_train_state(model, opt, (ids, lengths), mesh=mesh_b)
    restored = restore_train_state(path, like=template)
    leaf_a = state.params["layer_0"]["feed_forward"]["gate_proj"]["kernel"]
    leaf_b = restored.params["layer_0"]["feed_forward"]["gate_proj"]["kernel"]
    np.testing.assert_array_equal(np.asarray(leaf_a), np.asarray(leaf_b))

    step_b = make_train_step(model, opt, mesh=mesh_b)
    restored, loss_b = step_b(restored, ids, lengths)
    assert np.isfinite(float(loss_b))
    # Same data, same restored weights -> same loss on the new mesh as one
    # more step on the old mesh.
    state, loss_a2 = step_a(state, ids, lengths)
    np.testing.assert_allclose(float(loss_b), float(loss_a2), rtol=1e-5)
