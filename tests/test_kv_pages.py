"""Prefix-shared paged KV cache: geometry, host structures, identity.

Contract families (ISSUE 11):

* **geometry** — ``PagePlan`` validation (pow2 pages/slots, region
  alignment, pool floor) and the ``--page-size`` / ``--kv-pages``
  resolver semantics (explicit raises, malformed env falls back).
* **host structures** — ``PagePool`` refcount/free-list invariants and
  ``RadixIndex`` match/insert/evict as pure data structures, including a
  hypothesis property sweep: random arrival orders never share pages
  past the common prefix and never evict a pinned page.
* **identity** — continuous greedy text over the paged cache is
  byte-identical to static ``generate_batch`` and the monolithic
  (``page_size=0``) slot runtime, at two page sizes, under shuffled
  arrival, under eviction pressure, with copy-on-write firing, and with
  the ``kv_pages.lookup`` fault forcing full-prefill fallback.
* **zero retraces** — ``compiled_variants()`` stays at the four fixed
  programs across sharing, CoW, eviction, and slot-reuse churn.
"""

import random

import numpy as np
import pytest

from music_analyst_tpu.ops.kv_pages import (
    PagePlan,
    PagePool,
    RadixIndex,
)
from music_analyst_tpu.serving.batcher import (
    resolve_kv_pages,
    resolve_page_size,
)


@pytest.fixture(scope="module")
def clf():
    from music_analyst_tpu.models.llama import (
        LlamaConfig,
        LlamaZeroShotClassifier,
    )

    return LlamaZeroShotClassifier(
        config=LlamaConfig.tiny(), max_prompt_len=64
    )


def _scheduler(clf, **kwargs):
    from music_analyst_tpu.serving.decode_loop import ContinuousScheduler

    kwargs.setdefault("prefill_chunk", 16)
    kwargs.setdefault("prompt_region", 64)
    kwargs.setdefault("max_new_tokens", 8)
    return ContinuousScheduler(clf, **kwargs)


def _run(sched, prompts, budget=8, order=None):
    order = order if order is not None else range(len(prompts))
    reqs = {
        i: sched.submit(i, prompts[i], max_new_tokens=budget) for i in order
    }
    sched.run_until_idle()
    out = []
    for i in range(len(prompts)):
        resp = reqs[i].response or {}
        assert resp.get("ok"), resp
        out.append(resp["text"])
    return out


SHARED = "the quick brown fox jumps over the lazy dog and then "
PROMPTS = [SHARED + tail for tail in
           ("runs away", "naps", "eats a pie", "digs", "sings", "hides")]


# -------------------------------------------------------------- geometry


def test_page_plan_validation():
    plan = PagePlan(n_slots=4, prefill_chunk=16, prompt_region=64,
                    max_new=8, decode_span=4, page_size=16, n_pages=20)
    assert plan.max_total == 72
    assert plan.prompt_pages == 4 and plan.decode_pages == 1
    assert plan.pages_per_slot == 5 and plan.slot_span == 80
    assert plan.trash_page == 20  # one past the allocatable pool
    with pytest.raises(ValueError):  # non-pow2 page size
        PagePlan(n_slots=4, prefill_chunk=16, prompt_region=64,
                 max_new=8, decode_span=4, page_size=12, n_pages=20)
    with pytest.raises(ValueError):  # region not page-aligned
        PagePlan(n_slots=4, prefill_chunk=16, prompt_region=48,
                 max_new=8, decode_span=4, page_size=32, n_pages=20)
    with pytest.raises(ValueError):  # pool below one page per slot
        PagePlan(n_slots=8, prefill_chunk=16, prompt_region=64,
                 max_new=8, decode_span=4, page_size=16, n_pages=6)
    with pytest.raises(ValueError):  # pool below one resident sequence
        PagePlan(n_slots=2, prefill_chunk=16, prompt_region=64,
                 max_new=8, decode_span=4, page_size=16, n_pages=4)


def test_resolve_page_size_and_kv_pages(monkeypatch):
    assert resolve_page_size(None) == 16
    assert resolve_page_size(8) == 8
    assert resolve_page_size(0) == 0  # monolithic escape
    with pytest.raises(ValueError):
        resolve_page_size(12)  # explicit non-pow2 is a usage error
    monkeypatch.setenv("MUSICAAL_SERVE_PAGE_SIZE", "32")
    assert resolve_page_size(None) == 32
    monkeypatch.setenv("MUSICAAL_SERVE_PAGE_SIZE", "12")
    assert resolve_page_size(None) == 16  # malformed env falls back
    monkeypatch.setenv("MUSICAAL_SERVE_PAGE_SIZE", "junk")
    assert resolve_page_size(None) == 16

    assert resolve_kv_pages(None) == 0  # auto-size
    assert resolve_kv_pages(64, n_slots=8) == 64
    with pytest.raises(ValueError):
        resolve_kv_pages(4, n_slots=8)  # pool must cover the slots
    monkeypatch.setenv("MUSICAAL_SERVE_KV_PAGES", "48")
    assert resolve_kv_pages(None, n_slots=8) == 48
    monkeypatch.setenv("MUSICAAL_SERVE_KV_PAGES", "4")
    assert resolve_kv_pages(None, n_slots=8) == 0  # too-small env → auto


def test_runtime_rejects_geometry_beyond_max_seq_len(clf):
    with pytest.raises(ValueError):
        clf.paged_runtime(n_slots=2, prefill_chunk=64,
                          prompt_region=64, max_new_tokens=2048)


# ------------------------------------------------------- host structures


def test_page_pool_refcounts():
    pool = PagePool(4)
    assert pool.free_count == 4
    row = pool.alloc(3)
    assert row == [0, 1, 2]  # ascending, deterministic
    assert pool.alloc(2) is None  # insufficient — caller defers
    for p in row:
        pool.pin(p)
    pool.tree_add(row[0])
    pool.unpin(row[0])
    assert pool.free_count == 1  # held by the tree, not free
    pool.tree_drop(row[0])
    assert pool.free_count == 2  # last reference gone → free
    with pytest.raises(ValueError):
        pool.unpin(row[0])  # double release
    with pytest.raises(ValueError):
        pool.tree_drop(row[0])
    for p in row[1:]:
        pool.unpin(p)
    assert pool.free_count == 4
    pool.check()


def _slot_insert(radix, pool, ids, n_pages):
    """Insert the way the scheduler does: the slot pins its row, offers
    it to the tree at prefill-complete, and unpins at completion — pages
    the tree didn't adopt (duplicates) return to the free list."""
    row = pool.alloc(n_pages)
    assert row is not None
    for p in row:
        pool.pin(p)
    adopted = radix.insert(ids, row, pool)
    for p in row:
        pool.unpin(p)
    return row, adopted


def test_radix_match_stops_at_common_prefix():
    pool = PagePool(16)
    radix = RadixIndex(page_size=4)
    a = [1, 2, 3, 4, 5, 6, 7, 8, 9, 10]  # 2 full pages + partial [9, 10]
    pages_a, adopted = _slot_insert(radix, pool, a, 3)
    assert adopted == 3

    # Identical prompt: both full pages + the full partial run — but
    # never more tokens than the query itself holds.
    m = radix.match(a)
    assert m.pages == pages_a[:2] and m.full_tokens == 8
    assert m.partial_phys == pages_a[2] and m.partial_tokens == 2
    assert m.tokens == len(a)

    # Diverges inside page 2: only page 1 shares; the second page is
    # offered as a partial (CoW) match up to the divergence point.
    m = radix.match([1, 2, 3, 4, 5, 6, 99, 99, 9])
    assert m.pages == pages_a[:1] and m.full_tokens == 4
    assert m.partial_phys == pages_a[1] and m.partial_tokens == 2

    # Diverges in the first token: nothing shared.
    m = radix.match([99, 2, 3, 4])
    assert m.pages == [] and m.tokens == 0 and m.partial_phys is None

    # Shorter query than one page: partial match only, capped at len(q).
    m = radix.match([1, 2, 3])
    assert m.pages == [] and m.partial_phys == pages_a[0]
    assert m.partial_tokens == 3

    # Re-inserting the same prompt adopts nothing (already cached); the
    # duplicate row frees when its slot completes.
    free_before = pool.free_count
    _, adopted = _slot_insert(radix, pool, a, 3)
    assert adopted == 0
    assert pool.free_count == free_before
    pool.check()


def test_radix_evict_lru_skips_pinned():
    pool = PagePool(8)
    radix = RadixIndex(page_size=2)
    seqs = {"a": [1, 2, 3, 4], "b": [1, 2, 9, 9], "c": [5, 6]}
    pages = {}
    for name, ids in seqs.items():
        pages[name], _ = _slot_insert(radix, pool, ids, len(ids) // 2)
    # b shares a's first page, so its own contribution is pages["b"][1];
    # c's leaf is the LRU candidate once b's page is pinned by a slot.
    radix.match(seqs["c"])
    radix.match(seqs["a"])
    pool.pin(pages["b"][1])  # b's page is mapped by a live slot
    assert radix.evict(pool, 1) == 1
    assert pool.in_tree[pages["b"][1]]  # pinned page survived
    assert not pool.in_tree[pages["c"][0]]  # coldest unpinned leaf went
    # Pin a's whole chain; unpin b.  Now only b's leaf is evictable:
    # a's leaf is pinned, and the shared [1, 2] page is both pinned and
    # an interior node until its children are gone.
    pool.pin(pages["a"][0])
    pool.pin(pages["a"][1])
    pool.unpin(pages["b"][1])
    assert radix.evict(pool, 10) == 1  # only b's leaf could go
    assert pool.in_tree[pages["a"][0]] and pool.in_tree[pages["a"][1]]
    pool.unpin(pages["a"][0])
    pool.unpin(pages["a"][1])
    pool.check()


def test_radix_property_random_arrivals():
    hypothesis = pytest.importorskip("hypothesis")
    from hypothesis import given, settings, strategies as st

    token_seq = st.lists(st.integers(0, 3), min_size=0, max_size=12)

    @given(
        seqs=st.lists(token_seq, min_size=1, max_size=6),
        query=token_seq,
        seed=st.integers(0, 2**16),
    )
    @settings(max_examples=150, deadline=None)
    def prop(seqs, query, seed):
        P = 4
        rng = random.Random(seed)
        rng.shuffle(seqs)
        pool = PagePool(64)
        radix = RadixIndex(page_size=P)
        tokens_of = {}  # phys -> the valid tokens stored on that page
        for ids in seqs:
            n_pages = max(1, -(-len(ids) // P))
            row, _ = _slot_insert(radix, pool, ids, n_pages)
            for pi in range(n_pages):
                seg = tuple(ids[pi * P:(pi + 1) * P])
                if seg and pool.in_tree[row[pi]]:
                    tokens_of.setdefault(row[pi], seg)
        m = radix.match(query)
        # Reconstruct what the match would map and require it to be a
        # prefix of the query — sharing never goes past the common prefix.
        got = []
        for pi, phys in enumerate(m.pages):
            seg = tokens_of[phys]
            assert len(seg) == P, "full-page walk crossed a partial page"
            got.extend(seg)
        assert m.full_tokens == len(got)
        if m.partial_tokens:
            seg = tokens_of[m.partial_phys]
            assert m.partial_tokens <= len(seg)
            got.extend(seg[: m.partial_tokens])
        assert m.tokens == len(got) <= len(query)
        assert list(query[: m.tokens]) == got
        # Pinned pages survive arbitrary eviction pressure.
        pinned = [p for p in tokens_of if rng.random() < 0.5]
        for p in pinned:
            pool.pin(p)
        radix.evict(pool, pool.n_pages)
        for p in pinned:
            assert pool.in_tree[p], "evicted a pinned page"
            pool.unpin(p)
        pool.check()

    prop()


# --------------------------------------------------------------- identity


def test_monolithic_escape_matches_static(clf):
    """``page_size=0`` pins PR 10's monolithic slot runtime — the A/B
    baseline — and its text matches the static scan too, so all three
    routes produce one byte sequence."""
    static = clf.generate_batch(PROMPTS, max_new_tokens=8)
    mono = _scheduler(clf, n_slots=2, page_size=0)
    assert mono.stats()["kv_backend"] == "slots"
    assert _run(mono, PROMPTS) == static


@pytest.mark.parametrize("page_size", [8, 16])
def test_paged_matches_static(clf, page_size):
    """Byte-identical greedy text at two page sizes under shuffled
    arrival (vs the monolithic runtime too, transitively through
    test_monolithic_escape_matches_static)."""
    static = clf.generate_batch(PROMPTS, max_new_tokens=8)
    paged = _scheduler(clf, n_slots=2, page_size=page_size)
    order = list(range(len(PROMPTS)))
    random.Random(page_size).shuffle(order)
    assert _run(paged, PROMPTS, order=order) == static
    stats = paged.stats()
    assert stats["kv_backend"] == "paged"
    assert stats["page_size"] == page_size
    assert stats["prefix_cache"]["hits"] >= 1  # shared template head
    assert stats["prefix_cache"]["cow_copies"] >= 1  # unaligned boundary
    paged._pool.check()


def test_prefix_hits_skip_chunks_and_share_pages(clf):
    """Sequential arrival through few slots: later requests must hit the
    tree, skip fully-shared chunks, and still match the static scan."""
    static = clf.generate_batch(PROMPTS, max_new_tokens=8)
    sched = _scheduler(clf, n_slots=2)
    sched.warmup()
    assert _run(sched, PROMPTS) == static
    pc = sched.stats()["prefix_cache"]
    # 2 slots admit the first two cold; the remaining four arrive after
    # at least one adoption and share the common head (3 × 16-token pages).
    assert pc["lookups"] == len(PROMPTS)
    assert pc["hits"] >= len(PROMPTS) - 2
    assert pc["chunks_skipped"] >= 4
    assert pc["tokens_shared"] > 0 and pc["pages_shared"] > 0
    assert pc["bytes_saved"] > 0
    assert 0.0 < pc["hit_rate"] <= 1.0
    assert pc["fallbacks"] == 0
    sched._pool.check()


def test_identity_under_eviction_pressure(clf):
    """A pool sized for exactly two resident disjoint sequences forces
    eviction and deferred admission; text stays byte-identical."""
    prompts = [f"song number {i} is about {'x' * 40}{i}" for i in range(8)]
    static = clf.generate_batch(prompts, max_new_tokens=4)
    sched = _scheduler(clf, n_slots=2, max_new_tokens=4, kv_pages=10)
    sched.warmup()
    before = sched.runtime.compiled_variants()
    assert _run(sched, prompts, budget=4) == static
    pc = sched.stats()["prefix_cache"]
    assert pc["evictions"] > 0
    assert sched.runtime.compiled_variants() == before  # churn ≠ retrace
    sched._pool.check()


def test_zero_retraces_across_paged_workload(clf):
    """The four fixed programs never retrace as the page table churns
    through sharing, CoW, eviction, and slot reuse."""
    sched = _scheduler(clf, n_slots=4)
    record = sched.warmup()
    assert record["kv_backend"] == "paged" and record["programs"] == 4
    before = sched.runtime.compiled_variants()
    prompts = [PROMPTS[i % len(PROMPTS)] for i in range(10)]
    _run(sched, prompts, budget=6)
    assert sched.runtime.compiled_variants() == before
    assert sched.stats()["completed"] == 10
    sched._pool.check()


def test_lookup_fault_falls_back_to_full_prefill(clf):
    """A corrupted/missed radix lookup (fault site ``kv_pages.lookup``)
    degrades to zero sharing — byte-identical text, never wrong tokens."""
    from music_analyst_tpu.resilience.faults import configure_faults

    static = clf.generate_batch(PROMPTS[:4], max_new_tokens=6)
    sched = _scheduler(clf, n_slots=2)
    configure_faults("kv_pages.lookup:error@1+")
    try:
        out = _run(sched, PROMPTS[:4], budget=6)
    finally:
        configure_faults(None)
    assert out == static
    pc = sched.stats()["prefix_cache"]
    assert pc["fallbacks"] == 4 and pc["hits"] == 0
    sched._pool.check()
    # With the fault gone the same scheduler shares again.
    assert _run(sched, PROMPTS[:4], budget=6) == static
    assert sched.stats()["prefix_cache"]["hits"] >= 1


def test_scheduler_env_selects_backend(clf, monkeypatch):
    monkeypatch.setenv("MUSICAAL_SERVE_PAGE_SIZE", "0")
    mono = _scheduler(clf, n_slots=2)
    assert mono.stats()["kv_backend"] == "slots"
    assert "prefix_cache" not in mono.stats()
    monkeypatch.setenv("MUSICAAL_SERVE_PAGE_SIZE", "8")
    paged = _scheduler(clf, n_slots=2)
    st = paged.stats()
    assert st["kv_backend"] == "paged" and st["page_size"] == 8
    assert st["prefix_cache"]["enabled"]
