"""REAL multi-process execution: two JAX processes, Gloo CPU collectives.

The reference verifies its parallelism by actually running N ranks
(``mpirun -np N``, src/parallel_spotify.c:725-730); the JAX-native
equivalent is two OS processes under ``jax.distributed.initialize`` with
4 virtual CPU devices each (8 global).  Each child ingests a disjoint
record range, merges vocabularies through the coordinator, psums dense
histograms across all 8 devices, and the coordinator's word_counts.csv
must be byte-identical to a single-process run of the same corpus.

These children must NOT inherit the conftest's in-process jax setup —
they configure their own platform via env before importing jax.
"""

import os
import socket
import subprocess
import sys

_CHILD = r"""
import os, sys
proc_id = int(sys.argv[1])
port = sys.argv[2]
dataset = sys.argv[3]
out_dir = sys.argv[4]
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
import jax
# jaxlib >= 0.4.36 dropped the implicit multiprocess CPU emulation: cross-
# process collectives on the CPU backend now need an explicit collectives
# implementation or psum fails with "Multiprocess computations aren't
# implemented on the CPU backend".  Gloo ships in-tree.
jax.config.update("jax_cpu_collectives_implementation", "gloo")
jax.distributed.initialize(
    coordinator_address=f"localhost:{port}", num_processes=2,
    process_id=proc_id,
)
assert jax.process_count() == 2, jax.process_count()
assert len(jax.devices()) == 8, len(jax.devices())
from music_analyst_tpu.parallel.distributed import distributed_wordcount
result = distributed_wordcount(dataset, output_dir=out_dir)
print(f"RESULT {result['total_songs']} {result['total_words']}")
"""


def test_distributed_wordcount_single_process_degenerates(tmp_path):
    """With one process the same code path must reduce to the plain
    engine result (every collective degrades per multihost.py)."""
    import numpy as np

    from music_analyst_tpu.data.csv_io import (
        sort_count_entries,
        write_count_csv,
    )
    from music_analyst_tpu.data.ingest import ingest_python
    from music_analyst_tpu.data.synthetic import generate_dataset
    from music_analyst_tpu.parallel.distributed import distributed_wordcount

    dataset = tmp_path / "songs.csv"
    generate_dataset(str(dataset), num_songs=60, seed=9)
    result = distributed_wordcount(str(dataset), output_dir=str(tmp_path / "o"))
    corpus = ingest_python(dataset.read_bytes())
    assert result["processes"] == 1
    assert result["total_songs"] == corpus.song_count
    assert result["total_words"] == corpus.token_count
    counts = np.bincount(
        corpus.word_ids[corpus.word_ids >= 0],
        minlength=len(corpus.word_vocab),
    )
    expect = tmp_path / "expect.csv"
    write_count_csv(
        str(expect), "word",
        sort_count_entries(corpus.word_vocab.counts_to_entries(counts)),
    )
    assert (tmp_path / "o" / "word_counts.csv").read_bytes() == expect.read_bytes()


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


def test_two_process_wordcount_matches_single_process(tmp_path):
    from music_analyst_tpu.data.csv_io import write_count_csv, sort_count_entries
    from music_analyst_tpu.data.ingest import ingest_python
    from music_analyst_tpu.data.synthetic import generate_dataset

    dataset = tmp_path / "songs.csv"
    generate_dataset(str(dataset), num_songs=300, seed=21)
    out_dir = tmp_path / "dist_out"

    env = dict(os.environ, PALLAS_AXON_POOL_IPS="")
    env.pop("JAX_PLATFORMS", None)
    env.pop("XLA_FLAGS", None)
    port = str(_free_port())
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    procs = [
        subprocess.Popen(
            [sys.executable, "-c", _CHILD, str(p), port, str(dataset),
             str(out_dir)],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
            env=env, cwd=repo,
        )
        for p in (0, 1)
    ]
    outs = []
    try:
        for proc in procs:
            out, err = proc.communicate(timeout=240)
            assert proc.returncode == 0, f"child failed:\n{err[-1500:]}"
            outs.append(out)
    finally:
        # A failed/timed-out child must not leave its peer blocked in
        # jax.distributed.initialize holding the coordinator port.
        for proc in procs:
            if proc.poll() is None:
                proc.kill()

    # Both processes report identical global totals.
    results = [
        line for out in outs for line in out.splitlines()
        if line.startswith("RESULT ")
    ]
    assert len(results) == 2 and results[0] == results[1], results

    # Coordinator's export is byte-identical to the single-process oracle.
    import numpy as np

    corpus = ingest_python(dataset.read_bytes())
    counts = np.bincount(
        corpus.word_ids[corpus.word_ids >= 0],
        minlength=len(corpus.word_vocab),
    )
    expect_path = tmp_path / "expect_word_counts.csv"
    write_count_csv(
        str(expect_path), "word",
        sort_count_entries(corpus.word_vocab.counts_to_entries(counts)),
    )
    got = (out_dir / "word_counts.csv").read_bytes()
    assert got == expect_path.read_bytes()
    total_songs = int(results[0].split()[1])
    assert total_songs == corpus.song_count

    artist_counts = np.bincount(
        corpus.artist_ids[corpus.artist_ids >= 0],
        minlength=len(corpus.artist_vocab),
    )
    expect_artists = tmp_path / "expect_top_artists.csv"
    write_count_csv(
        str(expect_artists), "artist",
        sort_count_entries(
            corpus.artist_vocab.counts_to_entries(artist_counts)
        ),
    )
    assert (out_dir / "top_artists.csv").read_bytes() == expect_artists.read_bytes()

    # The coordinator emits the multi-controller performance_metrics.json
    # (reference: per-rank MPI_Reduce timing stats) with one genuinely
    # measured sample per process.
    import json

    metrics = json.loads((out_dir / "performance_metrics.json").read_text())
    assert metrics["processes"] == 2
    assert metrics["total_songs"] == corpus.song_count
    per_proc = metrics["per_chip"]
    assert [entry["process"] for entry in per_proc] == [0, 1]
    samples = [entry["compute_seconds"] for entry in per_proc]
    assert all(s > 0 for s in samples)
    # Independent clocks: two processes never measure the same nanosecond.
    assert samples[0] != samples[1]
    # compute_time rounds to 6 decimals, samples keep 9.
    assert abs(metrics["compute_time"]["min_seconds"] - min(samples)) < 1e-5
    assert abs(metrics["compute_time"]["max_seconds"] - max(samples)) < 1e-5
    assert metrics["total_time"]["avg_seconds"] >= (
        metrics["compute_time"]["avg_seconds"]
    )
