"""HF torch checkpoint loading for the decoder LM family.

The reference has no weights at all (its LLM lives behind an Ollama HTTP
endpoint, ``scripts/sentiment_classifier.py:85-100``); here real HF Llama
state_dicts map onto the Flax params.  These tests fabricate tiny torch
state_dicts with the exact HF key schema and verify the mapping, the
sharded-directory path, and tied-embedding fallback.
"""

import numpy as np
import pytest

torch = pytest.importorskip("torch")

import jax
import jax.numpy as jnp

from music_analyst_tpu.models.layers import causal_mask
from music_analyst_tpu.models.llama import (
    LlamaConfig,
    LlamaModel,
    load_hf_torch_checkpoint,
)

CFG = LlamaConfig(
    vocab_size=64, dim=16, n_layers=2, n_heads=4, n_kv_heads=2,
    hidden_dim=32, rope_theta=1e4, max_seq_len=32,
)


def _hf_state_dict(cfg: LlamaConfig, seed: int = 0, tied: bool = False,
                   prefix: str = "model."):
    g = torch.Generator().manual_seed(seed)
    hd = cfg.dim // cfg.n_heads

    def r(*shape):
        return torch.randn(*shape, generator=g)

    sd = {f"{prefix}embed_tokens.weight": r(cfg.vocab_size, cfg.dim),
          f"{prefix}norm.weight": r(cfg.dim)}
    for i in range(cfg.n_layers):
        p = f"{prefix}layers.{i}."
        sd[p + "self_attn.q_proj.weight"] = r(cfg.n_heads * hd, cfg.dim)
        sd[p + "self_attn.k_proj.weight"] = r(cfg.n_kv_heads * hd, cfg.dim)
        sd[p + "self_attn.v_proj.weight"] = r(cfg.n_kv_heads * hd, cfg.dim)
        sd[p + "self_attn.o_proj.weight"] = r(cfg.dim, cfg.n_heads * hd)
        sd[p + "input_layernorm.weight"] = r(cfg.dim)
        sd[p + "post_attention_layernorm.weight"] = r(cfg.dim)
        sd[p + "mlp.gate_proj.weight"] = r(cfg.hidden_dim, cfg.dim)
        sd[p + "mlp.up_proj.weight"] = r(cfg.hidden_dim, cfg.dim)
        sd[p + "mlp.down_proj.weight"] = r(cfg.dim, cfg.hidden_dim)
    if not tied:
        sd["lm_head.weight"] = r(cfg.vocab_size, cfg.dim)
    return sd


def _init_params(cfg: LlamaConfig):
    model = LlamaModel(cfg)
    ids = jnp.zeros((1, 4), jnp.int32)
    pos = jnp.zeros((1, 4), jnp.int32)
    return model, model.init(
        jax.random.key(0), ids, pos, causal_mask(4, 4, 0)
    )["params"]


def test_loader_maps_every_tensor(tmp_path):
    sd = _hf_state_dict(CFG)
    path = tmp_path / "pytorch_model.bin"
    torch.save(sd, path)
    model, params = _init_params(CFG)
    loaded = load_hf_torch_checkpoint(params, str(path))

    hd = CFG.dim // CFG.n_heads
    np.testing.assert_allclose(
        np.asarray(loaded["tok_embeddings"]["embedding"]),
        sd["model.embed_tokens.weight"].numpy(),
    )
    q = sd["model.layers.0.self_attn.q_proj.weight"].numpy()
    np.testing.assert_allclose(
        np.asarray(loaded["layer_0"]["attention"]["q_proj"]["kernel"]),
        q.T.reshape(CFG.dim, CFG.n_heads, hd),
    )
    o = sd["model.layers.1.self_attn.o_proj.weight"].numpy()
    np.testing.assert_allclose(
        np.asarray(loaded["layer_1"]["attention"]["o_proj"]["kernel"]),
        o.T.reshape(CFG.n_heads, hd, CFG.dim),
    )
    np.testing.assert_allclose(
        np.asarray(loaded["layer_0"]["feed_forward"]["down_proj"]["kernel"]),
        sd["model.layers.0.mlp.down_proj.weight"].numpy().T,
    )
    np.testing.assert_allclose(
        np.asarray(loaded["lm_head"]["kernel"]),
        sd["lm_head.weight"].numpy().T,
    )

    # Loaded params run a forward pass with finite output.
    ids = jnp.asarray([[1, 2, 3, 4]], jnp.int32)
    pos = jnp.arange(4)[None, :]
    logits, _ = model.apply(
        {"params": loaded}, ids, pos, causal_mask(4, 4, 0)
    )
    assert np.isfinite(np.asarray(logits)).all()


def test_loader_sharded_dir_and_tied_embeddings(tmp_path):
    sd = _hf_state_dict(CFG, seed=1, tied=True)
    # split into two shard files, as HF multi-file checkpoints do
    keys = sorted(sd)
    torch.save({k: sd[k] for k in keys[: len(keys) // 2]},
               tmp_path / "pytorch_model-00001-of-00002.bin")
    torch.save({k: sd[k] for k in keys[len(keys) // 2:]},
               tmp_path / "pytorch_model-00002-of-00002.bin")
    _, params = _init_params(CFG)
    loaded = load_hf_torch_checkpoint(params, str(tmp_path))
    # tied: lm_head falls back to the (transposed) embedding matrix
    np.testing.assert_allclose(
        np.asarray(loaded["lm_head"]["kernel"]),
        sd["model.embed_tokens.weight"].numpy().T,
    )


def test_classifier_accepts_checkpoint_path(tmp_path):
    from music_analyst_tpu.models.llama import LlamaZeroShotClassifier

    cfg = LlamaConfig(
        vocab_size=300, dim=16, n_layers=1, n_heads=4, n_kv_heads=2,
        hidden_dim=32, rope_theta=1e4, max_seq_len=64,
    )
    sd = _hf_state_dict(cfg, seed=2)
    path = tmp_path / "pytorch_model.bin"
    torch.save(sd, path)
    clf = LlamaZeroShotClassifier(
        config=cfg, checkpoint_path=str(path), max_prompt_len=64
    )
    assert clf.pretrained
    labels = clf.classify_batch(["la la la", ""])
    assert labels[1] == "Neutral"  # empty-lyric reference rule
    assert all(l in ("Positive", "Neutral", "Negative") for l in labels)
