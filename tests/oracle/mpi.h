/*
 * Single-rank MPI stub — lets the *unmodified* reference binary
 * (/root/reference/src/parallel_spotify.c) compile and run without an MPI
 * installation, so the differential tests can diff this framework's output
 * against the reference's byte-for-byte.  Covers exactly the MPI surface
 * the reference uses (SURVEY.md §2.4): Init/Comm_rank/Comm_size/Bcast/
 * Barrier/Reduce/Send/Recv/Wtime/Abort/Finalize.  With world_size == 1 the
 * Send/Recv shuffle never executes and every Reduce is a copy.
 */
#ifndef MUSICAAL_TEST_MPI_STUB_H
#define MUSICAAL_TEST_MPI_STUB_H

#include <stdlib.h>
#include <string.h>
#include <time.h>

typedef int MPI_Comm;
typedef int MPI_Datatype;
typedef int MPI_Op;
typedef struct {
  int MPI_SOURCE;
  int MPI_TAG;
  int MPI_ERROR;
} MPI_Status;

#define MPI_COMM_WORLD 0
#define MPI_CHAR 1
#define MPI_INT 2
#define MPI_LONG_LONG 3
#define MPI_DOUBLE 4
#define MPI_SUM 10
#define MPI_MAX 11
#define MPI_MIN 12
#define MPI_SUCCESS 0

static size_t mpi_stub_sizeof(MPI_Datatype t) {
  switch (t) {
    case MPI_CHAR: return 1;
    case MPI_INT: return sizeof(int);
    case MPI_LONG_LONG: return sizeof(long long);
    case MPI_DOUBLE: return sizeof(double);
    default: return 1;
  }
}

static int MPI_Init(int *argc, char ***argv) {
  (void)argc; (void)argv;
  return MPI_SUCCESS;
}

static int MPI_Comm_rank(MPI_Comm comm, int *rank) {
  (void)comm;
  *rank = 0;
  return MPI_SUCCESS;
}

static int MPI_Comm_size(MPI_Comm comm, int *size) {
  (void)comm;
  *size = 1;
  return MPI_SUCCESS;
}

static int MPI_Bcast(void *buf, int count, MPI_Datatype t, int root,
                     MPI_Comm comm) {
  (void)buf; (void)count; (void)t; (void)root; (void)comm;
  return MPI_SUCCESS;
}

static int MPI_Barrier(MPI_Comm comm) {
  (void)comm;
  return MPI_SUCCESS;
}

static int MPI_Reduce(const void *in, void *out, int count, MPI_Datatype t,
                      MPI_Op op, int root, MPI_Comm comm) {
  (void)op; (void)root; (void)comm;
  memcpy(out, in, (size_t)count * mpi_stub_sizeof(t));
  return MPI_SUCCESS;
}

static int MPI_Send(const void *buf, int count, MPI_Datatype t, int dest,
                    int tag, MPI_Comm comm) {
  (void)buf; (void)count; (void)t; (void)dest; (void)tag; (void)comm;
  return MPI_SUCCESS; /* unreachable at world_size == 1 */
}

static int MPI_Recv(void *buf, int count, MPI_Datatype t, int source,
                    int tag, MPI_Comm comm, MPI_Status *status) {
  (void)buf; (void)count; (void)t; (void)source; (void)tag; (void)comm;
  (void)status;
  return MPI_SUCCESS; /* unreachable at world_size == 1 */
}

static double MPI_Wtime(void) {
  struct timespec ts;
  clock_gettime(CLOCK_MONOTONIC, &ts);
  return (double)ts.tv_sec + (double)ts.tv_nsec * 1e-9;
}

static int MPI_Abort(MPI_Comm comm, int code) {
  (void)comm;
  exit(code);
}

static int MPI_Finalize(void) { return MPI_SUCCESS; }

#endif /* MUSICAAL_TEST_MPI_STUB_H */
