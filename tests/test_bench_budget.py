"""Pin bench.py's wall-clock budget (VERDICT r3 weak #1).

Round 3's driver capture died at rc 124 because the retry loop's worst case
(~44 min) exceeded the driver's own timeout, so the "always one JSON line"
contract never executed.  These tests drive ``_run_parent`` with a fake
clock/sleep/run to prove the worst case — every attempt hanging until its
timeout — still emits the contractual error line BEFORE the overall
deadline, and a real-time smoke check proves the same end-to-end with a
deliberately broken child.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import bench  # noqa: E402


class FakeClock:
    def __init__(self):
        self.now = 0.0

    def __call__(self):
        return self.now

    def advance(self, s):
        self.now += s


def _probe_ok(cmd, clock, took=3.0):
    """Fake a healthy probe child (``--probe`` settles in seconds)."""
    clock.advance(took)
    return subprocess.CompletedProcess(cmd, returncode=0, stdout="1\n",
                                       stderr="")


def _parse_only_line(capsys) -> dict:
    out = capsys.readouterr().out.strip().splitlines()
    assert len(out) == 1, f"expected exactly one stdout line, got {out!r}"
    return json.loads(out[0])


def test_worst_case_all_attempts_hang_fits_deadline(capsys):
    """Everything times out; the error line lands before the deadline."""
    clock = FakeClock()
    timeouts = []
    cmds = []

    def hang_run(cmd, capture_output, text, timeout):
        timeouts.append(timeout)
        cmds.append(cmd)
        clock.advance(timeout)
        raise subprocess.TimeoutExpired(cmd, timeout)

    rc = bench._run_parent(
        4, bench._DEFAULT_DEADLINE_S,
        run=hang_run, sleep=clock.advance, clock=clock,
    )
    assert rc == 0
    line = _parse_only_line(capsys)
    assert line["metric"] == bench.METRIC
    assert line["value"] == 0.0
    assert "timed out" in line["error"]
    # The contract the driver relies on: line printed with SAFETY_S spare.
    assert clock.now <= bench._DEFAULT_DEADLINE_S - bench.SAFETY_S + 1e-6
    # At least two genuine tries happened before giving up.
    assert len(timeouts) >= 2
    # No single attempt may exceed its cap or the remaining budget.
    assert all(t <= bench.ATTEMPT_CAP_S for t in timeouts)
    # A dead tunnel is spent on cheap probes (VERDICT r4 #5); only the
    # tail window that can no longer fit a probe cycle goes to one
    # last-ditch blind attempt (which would ride out a late recovery).
    probe_t = [t for t, c in zip(timeouts, cmds) if "--probe" in c]
    child_t = [t for t, c in zip(timeouts, cmds) if "--child" in c]
    assert len(probe_t) >= 3
    assert len(child_t) <= 1
    assert all(t <= bench.PROBE_HUNG_TIMEOUT_S for t in probe_t)


def test_worst_case_slow_failures_fit_deadline(capsys):
    """Attempts that FAIL just under their timeout (rc != 0) also fit."""
    clock = FakeClock()

    def slow_fail_run(cmd, capture_output, text, timeout):
        # Probes and attempts alike fail just under their timeout.
        clock.advance(timeout - 1.0)
        return subprocess.CompletedProcess(
            cmd, returncode=1, stdout="", stderr="RuntimeError: UNAVAILABLE"
        )

    rc = bench._run_parent(
        4, bench._DEFAULT_DEADLINE_S,
        run=slow_fail_run, sleep=clock.advance, clock=clock,
    )
    assert rc == 0
    line = _parse_only_line(capsys)
    assert "UNAVAILABLE" in line["error"]
    assert clock.now <= bench._DEFAULT_DEADLINE_S


def test_constants_leave_room_for_an_attempt():
    """The default deadline admits at least one full-cap attempt plus the
    reserved tail — otherwise the headline could never be measured."""
    assert bench._DEFAULT_DEADLINE_S >= bench.ATTEMPT_CAP_S + bench.SAFETY_S
    # And the deadline sits well under the driver budget that killed r3
    # (>= 10 min): leave at least 2 minutes of margin.
    assert bench._DEFAULT_DEADLINE_S <= 480


def test_timed_out_child_stdout_is_salvaged(capsys):
    """A child that printed its result line and then hung at teardown
    (axon tunnel threads) must still count as a success."""
    clock = FakeClock()
    payload = {"metric": bench.METRIC, "value": 321.0,
               "unit": "songs/sec", "vs_baseline": 0.2}

    def hang_after_print(cmd, capture_output, text, timeout):
        if "--probe" in cmd:
            return _probe_ok(cmd, clock)
        clock.advance(timeout)
        raise subprocess.TimeoutExpired(
            cmd, timeout, output=json.dumps(payload) + "\n"
        )

    rc = bench._run_parent(
        4, bench._DEFAULT_DEADLINE_S,
        run=hang_after_print, sleep=clock.advance, clock=clock,
    )
    assert rc == 0
    assert _parse_only_line(capsys) == payload


def test_nonzero_exit_after_result_line_is_salvaged(capsys):
    """Same salvage rule when the child prints the line but exits rc!=0
    (teardown crash instead of hang)."""
    clock = FakeClock()
    payload = {"metric": bench.METRIC, "value": 77.0,
               "unit": "songs/sec", "vs_baseline": 0.05}

    def crash_after_print(cmd, capture_output, text, timeout):
        if "--probe" in cmd:
            return _probe_ok(cmd, clock)
        clock.advance(40.0)
        return subprocess.CompletedProcess(
            cmd, returncode=1,
            stdout=json.dumps(payload) + "\n",
            stderr="Fatal Python error during teardown",
        )

    rc = bench._run_parent(
        4, bench._DEFAULT_DEADLINE_S,
        run=crash_after_print, sleep=clock.advance, clock=clock,
    )
    assert rc == 0
    assert _parse_only_line(capsys) == payload


def test_probe_fail_then_recover_still_measures(capsys):
    """VERDICT r4 #5: a tunnel that is dead for most of the window must not
    exhaust the budget — cheap probes keep the attempts in reserve, so a
    recovery at t≈300 s still gets a full measurement in."""
    clock = FakeClock()
    recovery_at = 300.0
    launches = []
    payload = {"metric": bench.METRIC, "value": 2500.0,
               "unit": "songs/sec", "vs_baseline": 1.2}

    def run(cmd, capture_output, text, timeout):
        launches.append((clock.now, cmd))
        if "--probe" in cmd:
            if clock.now < recovery_at:
                # Dead tunnel: the probe child errors out in seconds.
                clock.advance(4.0)
                return subprocess.CompletedProcess(
                    cmd, returncode=1, stdout="",
                    stderr="RuntimeError: UNAVAILABLE: axon tunnel",
                )
            return _probe_ok(cmd, clock)
        clock.advance(90.0)  # healthy measurement: compile + sweep
        return subprocess.CompletedProcess(
            cmd, returncode=0, stdout=json.dumps(payload) + "\n", stderr=""
        )

    rc = bench._run_parent(
        4, bench._DEFAULT_DEADLINE_S,
        run=run, sleep=clock.advance, clock=clock,
    )
    assert rc == 0
    assert _parse_only_line(capsys) == payload
    assert clock.now <= bench._DEFAULT_DEADLINE_S - bench.SAFETY_S + 1e-6
    # No full measurement child before the tunnel recovered…
    measured_at = [t for t, cmd in launches if "--child" in cmd]
    assert measured_at and all(t >= recovery_at for t in measured_at)
    # …and the dead phase was spent on cheap probes only.
    dead_launches = [cmd for t, cmd in launches if t < recovery_at]
    assert dead_launches and all("--probe" in c for c in dead_launches)


def test_probe_timeout_budget_respects_min_attempt(capsys):
    """A probe is never given a budget that would eat into the minimum
    viable attempt window, and hung probes escalate the leash instead of
    re-SIGKILLing at 35 s (lease-wedge risk, CLAUDE.md)."""
    clock = FakeClock()
    probe_timeouts = []

    def run(cmd, capture_output, text, timeout):
        if "--probe" in cmd:
            probe_timeouts.append((clock.now, timeout))
        clock.advance(timeout)
        raise subprocess.TimeoutExpired(cmd, timeout)

    bench._run_parent(4, 250.0, run=run, sleep=clock.advance, clock=clock)
    capsys.readouterr()
    assert probe_timeouts
    for t, budget in probe_timeouts:
        assert budget <= bench.PROBE_HUNG_TIMEOUT_S
        assert budget <= 250.0 - t - bench.SAFETY_S - bench.MIN_ATTEMPT_S + 1e-6
    # The first probe uses the short leash; later ones (after a kill) may
    # use the long one.
    assert probe_timeouts[0][1] <= bench.PROBE_TIMEOUT_S


def test_tight_deadline_still_measures_without_probe(capsys):
    """The minimum deadline that admits a measurement must stay at
    MIN_ATTEMPT_S + SAFETY_S: a window too small to probe skips the probe
    rather than forfeiting the attempt."""
    clock = FakeClock()
    payload = {"metric": bench.METRIC, "value": 900.0,
               "unit": "songs/sec", "vs_baseline": 0.5}
    cmds = []

    def run(cmd, capture_output, text, timeout):
        cmds.append(cmd)
        clock.advance(100.0)
        return subprocess.CompletedProcess(
            cmd, returncode=0, stdout=json.dumps(payload) + "\n", stderr=""
        )

    deadline = bench.MIN_ATTEMPT_S + bench.SAFETY_S + 5.0  # < MIN_PROBE_S spare
    rc = bench._run_parent(4, deadline, run=run, sleep=clock.advance,
                           clock=clock)
    assert rc == 0
    assert _parse_only_line(capsys) == payload
    assert all("--child" in c for c in cmds)  # no probe fit, none launched


def test_malformed_deadline_env_falls_back(monkeypatch):
    for bad in ("8min", "inf", "nan", "-5", "0"):
        monkeypatch.setenv("MUSICAAL_BENCH_DEADLINE_S", bad)
        assert bench._env_deadline() == bench._DEFAULT_DEADLINE_S, bad
    monkeypatch.setenv("MUSICAAL_BENCH_DEADLINE_S", "240")
    assert bench._env_deadline() == 240.0


def test_success_passes_through(capsys):
    clock = FakeClock()
    payload = {"metric": bench.METRIC, "value": 123.4,
               "unit": "songs/sec", "vs_baseline": 0.1}

    def ok_run(cmd, capture_output, text, timeout):
        if "--probe" in cmd:
            return _probe_ok(cmd, clock)
        clock.advance(30.0)
        return subprocess.CompletedProcess(
            cmd, returncode=0, stdout=json.dumps(payload) + "\n", stderr=""
        )

    rc = bench._run_parent(
        4, bench._DEFAULT_DEADLINE_S,
        run=ok_run, sleep=clock.advance, clock=clock,
    )
    assert rc == 0
    assert _parse_only_line(capsys) == payload


def test_real_subprocess_tiny_deadline_emits_line():
    """End-to-end: a 3 s deadline cannot fit MIN_ATTEMPT_S, so the parent
    must emit the error line immediately, in real time."""
    proc = subprocess.run(
        [sys.executable, os.path.join(os.path.dirname(bench.__file__),
                                      "bench.py"),
         "--deadline", "3"],
        capture_output=True, text=True, timeout=30,
    )
    assert proc.returncode == 0
    lines = proc.stdout.strip().splitlines()
    assert len(lines) == 1
    parsed = json.loads(lines[0])
    assert parsed["metric"] == bench.METRIC
    assert parsed["value"] == 0.0
