"""Vocabulary + corpus encoding (host side of the dense-histogram design)."""

import numpy as np

from music_analyst_tpu.data.vocab import Vocab, encode_corpus


def test_insertion_order_ids():
    v = Vocab()
    assert v.add("love") == 0
    assert v.add("pain") == 1
    assert v.add("love") == 0
    assert len(v) == 2
    assert v.tokens == ["love", "pain"]
    assert v.get("missing") == -1


def test_encode_corpus_offsets():
    vocab, ids, offsets = encode_corpus([["a", "b", "a"], [], ["b", "c"]])
    assert ids.dtype == np.int32
    assert offsets.dtype == np.int64
    np.testing.assert_array_equal(ids, [0, 1, 0, 1, 2])
    np.testing.assert_array_equal(offsets, [0, 3, 3, 5])
    assert vocab.tokens == ["a", "b", "c"]


def test_counts_to_entries_drops_zeros():
    v = Vocab(["x", "y", "z"])
    entries = v.counts_to_entries(np.array([2, 0, 7]))
    assert entries == [("x", 2), ("z", 7)]
