"""Fleet metrics plane (ISSUE 17).

Contract families:

* **resolve** — flag > env > default (off); malformed explicit flag is
  a usage error, malformed env falls back, like every serving knob.
* **flatten** — stats snapshots become dotted scalar series; histogram
  dicts are captured whole AND summarized; junk never lands.
* **exact fleet merge** — merged histograms match a single-process
  oracle fed every value (bucket counts, count, sum, min/max exact);
  rates and counters sum, EWMAs/quantiles never do; stale replicas are
  listed but excluded.
* **ring bounds** — the series ring evicts oldest-first at its cap and
  counts every eviction.
* **burn-rate alerting** — fires only when BOTH windows burn >= 14x
  budget, resolves with hysteresis, and each record resolves to the
  kept trace exemplar nearest the breach.
* **degradation** — a failed scrape (including fault site
  ``metrics.scrape``) marks the series stale and counts
  ``scrape_errors``; nothing is written, nothing raises.
* **reports** — ``telemetry-report`` reads the trajectory + alert
  history; ``trace-report`` accepts an alert file and filters the
  waterfalls to the alert's trace ids.
"""

import json
import os

import pytest

from music_analyst_tpu.observability.metrics_plane import (
    BURN_FIRE,
    METRICS_FILE,
    MetricsPlane,
    configure_metrics,
    flatten_stats,
    get_metrics_plane,
    merge_flat,
    merge_histograms,
    resolve_metrics_interval_ms,
)
from music_analyst_tpu.telemetry.core import Histogram


# ---------------------------------------------------------------- resolve


def test_resolve_interval(monkeypatch):
    monkeypatch.delenv("MUSICAAL_METRICS_INTERVAL_MS", raising=False)
    assert resolve_metrics_interval_ms(None) == 0.0  # default: off
    assert resolve_metrics_interval_ms(250) == 250.0
    assert resolve_metrics_interval_ms("50.5") == 50.5
    monkeypatch.setenv("MUSICAAL_METRICS_INTERVAL_MS", "100")
    assert resolve_metrics_interval_ms(None) == 100.0
    monkeypatch.setenv("MUSICAAL_METRICS_INTERVAL_MS", "junk")
    assert resolve_metrics_interval_ms(None) == 0.0  # env falls back
    monkeypatch.setenv("MUSICAAL_METRICS_INTERVAL_MS", "-5")
    assert resolve_metrics_interval_ms(None) == 0.0
    with pytest.raises(ValueError):
        resolve_metrics_interval_ms("junk")  # explicit flag is usage error
    with pytest.raises(ValueError):
        resolve_metrics_interval_ms(-1.0)


def test_disabled_plane_is_inert(tmp_path):
    plane = MetricsPlane(0.0, directory=str(tmp_path))
    assert not plane.enabled
    plane.attach(lambda: {"requests": {"admitted": 1}})
    plane.start()  # no thread, no baseline
    plane.close()
    assert plane.series() == []
    assert not (tmp_path / METRICS_FILE).exists()
    assert get_metrics_plane().enabled is False  # module default: off


# ---------------------------------------------------------------- flatten


def test_flatten_stats_shapes():
    hist = Histogram()
    for v in (0.01, 0.2, 3.0):
        hist.observe(v)
    snap = {
        "requests": {
            "admitted": 7,
            "occupancy": 0.5,
            "draining": False,
            "latency": hist.as_dict(),
            "mode": "unix",          # string: dropped
            "ids": [1, 2, 3],        # list: dropped
            "missing": None,         # None: dropped
            "bad": float("nan"),     # non-finite: dropped
        },
    }
    flat, hists = flatten_stats(snap)
    assert flat["requests.admitted"] == 7.0
    assert flat["requests.draining"] == 0.0
    assert flat["requests.latency.count"] == 3.0  # summary fields flatten
    assert "requests.mode" not in flat
    assert "requests.ids" not in flat
    assert "requests.missing" not in flat
    assert "requests.bad" not in flat
    assert list(hists) == ["requests.latency"]  # captured whole too


# ------------------------------------------------------------ fleet merge


def test_histogram_merge_matches_single_process_oracle():
    import random

    rng = random.Random(7)
    values = [rng.expovariate(5.0) for _ in range(300)]
    oracle = Histogram()
    parts = [Histogram() for _ in range(3)]
    for i, v in enumerate(values):
        oracle.observe(v)
        parts[i % 3].observe(v)
    merged = merge_histograms([p.as_dict() for p in parts])
    want = oracle.as_dict()
    assert merged["buckets_le"] == want["buckets_le"]
    assert merged["counts"] == want["counts"]  # exact, bucket by bucket
    assert merged["count"] == want["count"]
    assert merged["sum_s"] == pytest.approx(want["sum_s"], abs=1e-6)
    assert merged["min_s"] == pytest.approx(want["min_s"], abs=1e-9)
    assert merged["max_s"] == pytest.approx(want["max_s"], abs=1e-9)
    # Quantiles are bucket-derived upper bounds: never below the exact
    # reservoir answer's bucket, always a real bucket bound (or the max).
    assert merged["p50_s"] is not None


def test_histogram_merge_refuses_mismatched_buckets():
    a = Histogram(buckets=(0.1, 1.0)).as_dict()
    b = Histogram(buckets=(0.2, 2.0)).as_dict()
    assert merge_histograms([a, b]) is None
    assert merge_histograms([]) is None


def test_merge_flat_sums_rates_and_counters_only():
    replicas = [
        {"requests.rates.req_s": 10.0, "requests.rates.window_s": 10.0,
         "requests.admitted": 5.0, "requests.latency.p50_s": 0.2,
         "requests.occupancy": 0.5},
        {"requests.rates.req_s": 4.0, "requests.rates.window_s": 10.0,
         "requests.admitted": 3.0, "requests.latency.p50_s": 0.9,
         "requests.occupancy": 0.7},
    ]
    fleet = merge_flat(replicas)
    assert fleet["requests.rates.req_s"] == 14.0  # rates sum
    assert fleet["requests.admitted"] == 8.0      # counters sum
    assert "requests.latency.p50_s" not in fleet  # quantiles never sum
    assert "requests.occupancy" not in fleet      # ratios never sum
    assert "requests.rates.window_s" not in fleet  # config never sums


def test_stale_replica_excluded_from_fleet_merge():
    plane = MetricsPlane(50.0)
    plane.ingest_replica("r0", {"requests": {"admitted": 10}})
    plane.ingest_replica("r1", {"requests": {"admitted": 4}})
    plane.mark_replica_stale("r1")
    fleet = plane.fleet_snapshot()
    assert fleet["replica_count"] == 2
    assert fleet["fresh_count"] == 1
    assert fleet["stale"] == ["r1"]
    assert fleet["merged"]["requests.admitted"] == 10.0  # r1 excluded
    assert fleet["replicas"]["r1"]["stale"] is True


def test_ingest_replica_junk_counts_scrape_error():
    plane = MetricsPlane(50.0)
    plane.ingest_replica("r0", "not a dict")
    snap = plane.snapshot()
    assert snap["scrape_errors"] == 1
    assert plane.fleet_snapshot()["stale"] == ["r0"]


# ------------------------------------------------------------ ring bounds


def test_ring_eviction_bounds():
    plane = MetricsPlane(50.0, max_samples=4)
    plane.attach(lambda: {"requests": {"admitted": 1}})
    for _ in range(7):
        assert plane.sample_now() is not None
    snap = plane.snapshot()
    assert snap["samples"] == 7
    assert snap["series_len"] == 4   # ring capped
    assert snap["evicted"] == 3      # every eviction counted
    assert len(plane.series()) == 4


# ------------------------------------------------------------ degradation


def test_failed_scrape_degrades_to_stale(tmp_path):
    calls = {"n": 0}

    def source():
        calls["n"] += 1
        if calls["n"] == 2:
            raise RuntimeError("scrape exploded")
        return {"requests": {"admitted": calls["n"]}}

    plane = MetricsPlane(50.0, directory=str(tmp_path))
    plane.attach(source)
    assert plane.sample_now() is not None
    assert plane.sample_now() is None       # the failure
    assert plane.stale is True
    assert plane.sample_now() is not None   # recovers
    assert plane.stale is False
    snap = plane.snapshot()
    assert snap["samples"] == 2
    assert snap["scrape_errors"] == 1
    # The failed scrape wrote nothing: every line intact, sample-typed.
    lines = (tmp_path / METRICS_FILE).read_text().splitlines()
    assert len(lines) == 2
    assert all(json.loads(l)["type"] == "sample" for l in lines)


def test_fault_site_metrics_scrape(tmp_path):
    from music_analyst_tpu.resilience import configure_faults, fault_stats

    plane = MetricsPlane(50.0, directory=str(tmp_path))
    plane.attach(lambda: {"requests": {"admitted": 1}})
    configure_faults("metrics.scrape:error@1+")
    try:
        assert plane.sample_now() is None
        assert plane.sample_now() is None
        trips = fault_stats()["metrics.scrape"]["trips"]
    finally:
        configure_faults(None)
    assert trips == 2
    assert plane.snapshot()["scrape_errors"] == trips
    assert not (tmp_path / METRICS_FILE).exists()  # nothing ever landed


def test_prom_exposition_written(tmp_path):
    hist = Histogram()
    hist.observe(0.05)
    plane = MetricsPlane(50.0, directory=str(tmp_path))
    plane.attach(lambda: {
        "requests": {"admitted": 3, "latency": hist.as_dict()},
    })
    plane.sample_now()
    text = (tmp_path / f"metrics.{os.getpid()}.prom").read_text()
    assert "musicaal_requests_admitted 3" in text
    assert 'musicaal_requests_latency_bucket{le="+Inf"} 1' in text
    assert "musicaal_requests_latency_count 1" in text


# -------------------------------------------------------------- burn rate


def _burn_sample(t, shed, admitted, ttft_misses=0, total=0):
    metrics = {
        "slo.tenants.bulk.shed": float(shed),
        "slo.tenants.bulk.admitted": float(admitted),
    }
    if total:
        metrics["requests.admitted"] = float(total)
        metrics["decode.ttft_slo_misses"] = float(ttft_misses)
    return {"type": "sample", "t": float(t), "pid": 0, "role": "test",
            "metrics": metrics}


def test_burn_alert_fires_and_resolves_with_hysteresis():
    plane = MetricsPlane(50.0)
    # Baseline, then a burst: 90 sheds of 100 offered = 90x budget on
    # both windows (fast and slow windows both reach back to baseline).
    plane._series.append(_burn_sample(1000.0, 0, 0))
    burst = _burn_sample(1030.0, 90, 10)
    plane._series.append(burst)
    records = plane._evaluate_alerts(burst)
    assert [r["state"] for r in records] == ["firing"]
    assert records[0]["alert"] == "shed_burn_rate"
    assert records[0]["tenant"] == "bulk"
    assert records[0]["burn_fast"] >= BURN_FIRE
    assert records[0]["burn_slow"] >= BURN_FIRE
    # Still burning a minute later: active alert does not re-fire.
    still = _burn_sample(1059.0, 95, 12)
    plane._series.append(still)
    assert plane._evaluate_alerts(still) == []
    assert len(plane.alerts(active_only=True)) == 1
    # Recovery: inside the fast window the shed counter goes flat while
    # admits keep flowing — fast burn drops under the resolve threshold.
    plane._series.append(_burn_sample(1150.0, 95, 200))
    calm = _burn_sample(1200.0, 95, 260)
    plane._series.append(calm)
    records = plane._evaluate_alerts(calm)
    assert [r["state"] for r in records] == ["resolved"]
    assert plane.alerts(active_only=True) == []
    snap = plane.snapshot()
    assert snap["alerts_fired"] == 1
    assert snap["alerts_resolved"] == 1


def test_burn_alert_needs_both_windows():
    plane = MetricsPlane(50.0)
    # Long healthy history, then a fast-window-only spike: the slow
    # window (10 min of near-zero burn) must hold the pager.
    plane._series.append(_burn_sample(1000.0, 0, 10_000))
    plane._series.append(_burn_sample(1550.0, 0, 20_000))
    spike = _burn_sample(1595.0, 30, 20_100)
    plane._series.append(spike)
    assert plane._evaluate_alerts(spike) == []


def test_steady_state_stays_silent():
    plane = MetricsPlane(50.0)
    for i in range(5):
        s = _burn_sample(1000.0 + i, 0, 100 * (i + 1))
        plane._series.append(s)
        assert plane._evaluate_alerts(s) == []
    assert plane.alerts() == []


def test_alert_record_carries_nearest_kept_trace(tmp_path):
    from music_analyst_tpu.telemetry.reqtrace import configure_reqtrace

    rt = configure_reqtrace(0.0, directory=str(tmp_path))
    try:
        class _Req:
            def __init__(self):
                self.id = "r1"
                self.op = "echo"
                self.tenant = "bulk"
                self.priority = 1
                self.meta = {}
                self.response = {"ok": False,
                                 "error": {"kind": "queue_full"}}

        req = _Req()
        rt.begin_request(req)
        rt.on_complete(req, req.response)  # shed settle: tail-keeps
        rt.finish_request(req)
        kept = rt.nearest_kept()
        assert kept is not None and kept["kept"] not in (None, "head")

        plane = MetricsPlane(50.0)
        plane._series.append(_burn_sample(1000.0, 0, 0))
        burst = _burn_sample(1030.0, 90, 10)
        plane._series.append(burst)
        records = plane._evaluate_alerts(burst)
        assert records and records[0]["trace_id"] == kept["trace_id"]
    finally:
        os.environ.pop("MUSICAAL_TRACE_DIR", None)
        os.environ.pop("MUSICAAL_TRACE_SAMPLE", None)
        configure_reqtrace(None, None)


def test_nearest_kept_picks_closest_in_time(tmp_path):
    from music_analyst_tpu.telemetry.reqtrace import configure_reqtrace

    rt = configure_reqtrace(0.0, directory=str(tmp_path))
    try:
        with rt._lock:
            rt._finished.extend([
                {"trace_id": "aaa", "kept": "shed", "t": 100.0},
                {"trace_id": "bbb", "kept": "slow", "t": 200.0},
                {"trace_id": "ccc", "kept": None, "t": 150.0},
            ])
        assert rt.nearest_kept(105.0)["trace_id"] == "aaa"
        assert rt.nearest_kept(190.0)["trace_id"] == "bbb"
        assert rt.nearest_kept()["trace_id"] == "bbb"  # newest kept
    finally:
        os.environ.pop("MUSICAAL_TRACE_DIR", None)
        os.environ.pop("MUSICAAL_TRACE_SAMPLE", None)
        configure_reqtrace(None, None)


# ----------------------------------------------------- sampling lifecycle


def test_start_close_bounds_series(tmp_path):
    plane = MetricsPlane(10_000.0, directory=str(tmp_path))
    plane.attach(lambda: {"requests": {"admitted": 1}})
    plane.start()   # baseline sample, interval far beyond the test
    plane.close()   # final sample
    assert plane.snapshot()["samples"] == 2  # baseline + final, always
    lines = (tmp_path / METRICS_FILE).read_text().splitlines()
    assert len(lines) == 2
    plane.close()  # idempotent
    assert plane.snapshot()["samples"] == 2


def test_configure_metrics_exports_env(tmp_path, monkeypatch):
    monkeypatch.delenv("MUSICAAL_METRICS_INTERVAL_MS", raising=False)
    monkeypatch.delenv("MUSICAAL_METRICS_DIR", raising=False)
    plane = configure_metrics(125.0, directory=str(tmp_path))
    try:
        assert plane.enabled and get_metrics_plane() is plane
        assert float(os.environ["MUSICAAL_METRICS_INTERVAL_MS"]) == 125.0
        assert os.environ["MUSICAAL_METRICS_DIR"] == str(tmp_path)
    finally:
        monkeypatch.delenv("MUSICAAL_METRICS_INTERVAL_MS", raising=False)
        monkeypatch.delenv("MUSICAAL_METRICS_DIR", raising=False)
        assert not configure_metrics(None, None).enabled


# ---------------------------------------------------------------- reports


def _write_metrics_jsonl(path, trace_id="t-123"):
    lines = [
        {"type": "sample", "t": 10.0, "pid": 1, "role": "server",
         "metrics": {"requests.rates.req_s": 5.0,
                     "requests.admitted": 10.0}},
        {"type": "sample", "t": 20.0, "pid": 1, "role": "server",
         "metrics": {"requests.rates.req_s": 9.0,
                     "requests.admitted": 80.0}},
        {"type": "alert", "schema": 1, "alert": "shed_burn_rate",
         "state": "firing", "severity": "page", "t": 20.0, "pid": 1,
         "role": "server", "tenant": "bulk", "burn_fast": 90.0,
         "burn_slow": 88.0, "threshold": 14.0, "budget": 0.01,
         "window_fast_s": 60.0, "window_slow_s": 600.0,
         "trace_id": trace_id, "trace_kept": "shed"},
    ]
    with open(path, "w", encoding="utf-8") as fh:
        for line in lines:
            fh.write(json.dumps(line) + "\n")


def test_telemetry_report_reads_metrics_trajectory(tmp_path, capsys):
    from music_analyst_tpu.observability.report import run_telemetry_report

    run_dir = tmp_path / "run"
    run_dir.mkdir()
    (run_dir / "run_manifest.json").write_text(json.dumps({
        "schema": 1, "engine": "serve", "wall_seconds": 1.0,
        "counters": {}, "histograms": {},
    }))
    _write_metrics_jsonl(run_dir / "metrics.jsonl")
    assert run_telemetry_report([str(run_dir)]) == 0
    out = capsys.readouterr().out
    assert "metrics plane" in out
    assert "requests.rates.req_s: 5.00 -> 9.00" in out
    assert "burn-rate alert history:" in out
    assert "shed_burn_rate tenant=bulk: firing" in out
    assert "trace=t-123" in out


def test_trace_report_accepts_alert_file(tmp_path, capsys):
    from music_analyst_tpu.observability.report import run_trace_report

    def _trace(trace_id):
        return {
            "schema": 1, "trace_id": trace_id, "span": "s-" + trace_id,
            "role": "server", "pid": 1, "op": "echo", "kept": "shed",
            "wire_s": 0.01,
            "spans": [
                {"name": "admit", "cat": "phase", "t": 0.0, "dur": 0.004},
                {"name": "reply", "cat": "phase", "t": 0.004, "dur": 0.006},
            ],
        }

    with open(tmp_path / "request_traces.jsonl", "w") as fh:
        fh.write(json.dumps(_trace("t-123")) + "\n")
        fh.write(json.dumps(_trace("t-999")) + "\n")
    _write_metrics_jsonl(tmp_path / "metrics.jsonl", trace_id="t-123")
    # The whole dir: both traces.
    assert run_trace_report([str(tmp_path)]) == 0
    assert "2 trace(s)" in capsys.readouterr().out
    # The alert file: filtered to the breaching trace only.
    assert run_trace_report([str(tmp_path / "metrics.jsonl")]) == 0
    out = capsys.readouterr().out
    assert "1 trace(s)" in out
    assert "alert filter: 1 alert record(s) -> 1 trace id(s)" in out
    assert "t-123" in out and "t-999" not in out


def test_offered_load_series():
    from benchmarks.loadgen import Arrival, offered_load_series

    arrivals = [
        Arrival(t_s=0.1, tenant="bulk", priority=1),
        Arrival(t_s=0.9, tenant="gold", priority=5),
        Arrival(t_s=1.2, tenant="bulk", priority=1),
    ]
    series = offered_load_series(arrivals)
    assert series == [
        {"t_s": 0, "req_s": 2,
         "classes": {"bulk/p1": 1, "gold/p5": 1}},
        {"t_s": 1, "req_s": 1, "classes": {"bulk/p1": 1}},
    ]
