"""Per-song tool parity: artifacts, tie-order, row skipping."""

import csv
from collections import Counter

from music_analyst_tpu.data.tokenizer import tokenize_latin1
from music_analyst_tpu.engines.persong import (
    detect_delimiter,
    process_row,
    resolve_workers,
    run_per_song_wordcount,
)


def test_detect_delimiter_fallback():
    assert detect_delimiter("a;b;c\n1;2;3\n") == ";"
    # empty sample raises csv.Error inside Sniffer -> fallback comma
    assert detect_delimiter("") == ","


def test_resolve_workers():
    assert resolve_workers(4) == 4
    assert resolve_workers(0) >= 1


def test_process_row_empty_tokens_none():
    assert process_row({"artist": "A", "song": "S", "text": "a b c"}) is None
    got = process_row({"artist": " A ", "song": "S", "text": "hello hello world"})
    assert got == ("A", "S", Counter({"hello": 2, "world": 1}))


def test_end_to_end(fixture_csv, tmp_path):
    global_path, per_song_path, rows = run_per_song_wordcount(
        str(fixture_csv), output_dir=str(tmp_path), quiet=True
    )
    # oracle over the same DictReader rows
    oracle = Counter()
    with open(fixture_csv, newline="", encoding="utf-8-sig") as fh:
        for row in csv.DictReader(fh):
            oracle.update(tokenize_latin1(row.get("text") or ""))

    with open(global_path, newline="") as fh:
        reader = csv.reader(fh)
        assert next(reader) == ["word", "count"]
        got = [(w, int(c)) for w, c in reader]
    # most_common() order: count desc, ties by first-seen insertion
    assert got == oracle.most_common()

    with open(per_song_path, newline="") as fh:
        reader = csv.reader(fh)
        assert next(reader) == ["artist", "song", "word", "count"]
        by_song = list(reader)
    total_from_rows = sum(int(c) for _, _, _, c in by_song)
    assert total_from_rows == sum(oracle.values())
