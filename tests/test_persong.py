"""Per-song tool parity: artifacts, tie-order, row skipping."""

import csv
from collections import Counter

from music_analyst_tpu.data.csv_io import sniff_delimiter
from music_analyst_tpu.data.tokenizer import tokenize_latin1
from music_analyst_tpu.engines.persong import (
    _DenseHistogram,
    _tokenize_chunk,
    run_per_song_wordcount,
)


def test_sniff_delimiter_fallback():
    assert sniff_delimiter("a;b;c\n1;2;3\n") == ";"
    # empty sample raises csv.Error inside Sniffer -> fallback comma
    assert sniff_delimiter("") == ","


def test_tokenize_chunk_empty_tokens_none():
    got = _tokenize_chunk(
        [("A", "S", "a b c"), ("A", "S2", "hello hello world")]
    )
    assert got[0] is None  # no token reaches the >=3-char threshold
    assert got[1] == ("A", "S2", (("hello", 2), ("world", 1)))


def test_dense_histogram_most_common_semantics():
    h = _DenseHistogram()
    for word, n in [("bb", 1), ("aa", 2), ("cc", 1), ("bb", 1)]:
        h.add(word, n)
    # count desc, ties in first-seen order — Counter.most_common() order
    assert list(h.ranked()) == [("bb", 2), ("aa", 2), ("cc", 1)]
    assert h.total == 5


def test_end_to_end(fixture_csv, tmp_path):
    global_path, per_song_path, rows = run_per_song_wordcount(
        str(fixture_csv), output_dir=str(tmp_path), quiet=True
    )
    # oracle over the same DictReader rows
    oracle = Counter()
    with open(fixture_csv, newline="", encoding="utf-8-sig") as fh:
        for row in csv.DictReader(fh):
            oracle.update(tokenize_latin1(row.get("text") or ""))

    with open(global_path, newline="") as fh:
        reader = csv.reader(fh)
        assert next(reader) == ["word", "count"]
        got = [(w, int(c)) for w, c in reader]
    # most_common() order: count desc, ties by first-seen insertion
    assert got == oracle.most_common()

    with open(per_song_path, newline="") as fh:
        reader = csv.reader(fh)
        assert next(reader) == ["artist", "song", "word", "count"]
        by_song = list(reader)
    total_from_rows = sum(int(c) for _, _, _, c in by_song)
    assert total_from_rows == sum(oracle.values())


def test_small_chunks_keep_order(fixture_csv, tmp_path, monkeypatch):
    """Chunked pipeline must fold in submission order regardless of chunk
    size or worker count."""
    import music_analyst_tpu.engines.persong as persong

    monkeypatch.setattr(persong, "_CHUNK_ROWS", 2)
    a = run_per_song_wordcount(
        str(fixture_csv), output_dir=str(tmp_path / "a"), workers=4,
        quiet=True,
    )
    monkeypatch.setattr(persong, "_CHUNK_ROWS", 512)
    b = run_per_song_wordcount(
        str(fixture_csv), output_dir=str(tmp_path / "b"), workers=1,
        quiet=True,
    )
    for pa, pb in zip(a[:2], b[:2]):
        assert open(pa, "rb").read() == open(pb, "rb").read()
