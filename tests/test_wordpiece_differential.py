"""WordPieceTokenizer ≡ transformers.BertTokenizer on the same vocab.

The real-weights path (``MUSICAAL_BERT_VOCAB`` + ``MUSICAAL_DISTILBERT_
CKPT``) is only as good as its tokenization: a single divergent id feeds
the checkpoint garbage.  This differential pins our offline WordPiece +
BasicTokenizer reimplementation against HF's own slow ``BertTokenizer``
(the checkpoint family's reference implementation) over adversarial and
randomized corpora.  Caught on introduction: missing accent stripping and
apostrophes not splitting as punctuation.
"""

import numpy as np
import pytest

transformers = pytest.importorskip("transformers")

from music_analyst_tpu.models.tokenization import (  # noqa: E402
    WordPieceTokenizer,
    bert_basic_tokenize,
)

VOCAB = [
    "[PAD]", "[UNK]", "[CLS]", "[SEP]", "[MASK]",
    "the", "love", "##ing", "##s", "rain", "un", "##known", "a", "b",
    "##c", ".", ",", "!", "'", "cafe", "don", "##t", "##'", "t", "$",
    "##ely", "lone", "night", "##time", "2", "##4", "7", "-",
]


@pytest.fixture(scope="module")
def pair(tmp_path_factory):
    path = tmp_path_factory.mktemp("vocab") / "vocab.txt"
    path.write_text("\n".join(VOCAB) + "\n", encoding="utf-8")
    return (
        WordPieceTokenizer(str(path)),
        transformers.BertTokenizer(vocab_file=str(path),
                                   do_lower_case=True),
    )


ADVERSARIAL = [
    "love loving rains",
    "UNKNOWNWORD love",
    "love, rain!  night-time 24/7",
    "café Café CAFÉ",                  # accent stripping
    "don't Don'T",                      # apostrophe is punctuation
    "a\tb\nc\r\x00d�",            # controls cleaned, whitespace kept
    "ab a￾b",               # Co private-use / Cn nonchar drop
    "the   the the",              # NBSP is Zs whitespace
    "$$$ lone.ly...",
    "",
    "   ",
    "love" * 50,                        # > max_word_chars -> UNK
    "爱 the 愛love",                    # CJK chars isolate
    "naïve résumé",                    # only accents differ from vocab
    "the [MASK] love",                  # literal specials never split
    "the[MASK]love [SEP] [mask] [UNK]x",
    "[CLS] [PAD][PAD]",
]


def _ours(tok, text, max_len=32):
    row, n = tok.encode(text, max_len)
    return [int(t) for t in row[:n]]


def test_adversarial_corpus_matches_hf(pair):
    ours, hf = pair
    for text in ADVERSARIAL:
        want = hf.encode(text, truncation=True, max_length=32)
        got = _ours(ours, text)
        assert got == want, (text, got, want)


def test_randomized_corpus_matches_hf(pair):
    """Seeded fuzz: random mixes of vocab pieces, unknowns, punctuation,
    unicode and whitespace."""
    ours, hf = pair
    rng = np.random.default_rng(0)
    pieces = ["love", "the", "rain", "unknown", "zzz", "don't", "café",
              ",", "!", ".", "$", "a", "b", "C", "愛", "naïve", "''",
              "  ", "\t", "x" * 120, "24", "7-7", "[MASK]", "[SEP]",
              "[mask]"]
    for _ in range(200):
        n = rng.integers(0, 12)
        text = "".join(
            rng.choice(pieces) + (" " if rng.random() < 0.7 else "")
            for _ in range(n)
        )
        want = hf.encode(text, truncation=True, max_length=24)
        got = _ours(ours, text, max_len=24)
        assert got == want, (text, got, want)


def test_basic_tokenize_matches_hf_basic(pair):
    _, hf = pair
    basic = hf.basic_tokenizer
    for text in ADVERSARIAL:
        assert bert_basic_tokenize(text) == basic.tokenize(text), text


def test_truncation_parity(pair):
    ours, hf = pair
    text = "love loving rains " * 20
    for max_len in (4, 8, 16):
        want = hf.encode(text, truncation=True, max_length=max_len)
        got = _ours(ours, text, max_len=max_len)
        assert got == want, (max_len, got, want)


def test_native_wordpiece_matches_python(pair, tmp_path_factory):
    """The C++ ASCII fast path is byte-exact with the Python tokenizer
    (itself pinned to HF above), and non-ASCII rows fall back."""
    from music_analyst_tpu.data import native
    from music_analyst_tpu.models.tokenization import (
        NativeWordPieceTokenizer,
    )

    if not native.available():
        pytest.skip(f"native lib unavailable: {native.unavailable_reason()}")
    path = tmp_path_factory.mktemp("nvocab") / "vocab.txt"
    path.write_text("\n".join(VOCAB) + "\n", encoding="utf-8")
    py = WordPieceTokenizer(str(path))
    nat = NativeWordPieceTokenizer(str(path))
    assert nat._handle is not None

    corpora = ADVERSARIAL + [
        "pure ascii love rain the don't $ 24/7 [MASK] x" * 3,
        "latin café naïve søster ßüber",      # table-handled, not fallback
        "the ελληνικά row",                   # Greek: per-row fallback
        "爱 love 愛",                          # CJK: per-row fallback
        "a\ud800b love",                      # lone surrogate: fallback,
    ]                                          # Python drops it (C* char)
    for max_len in (8, 32):
        want_ids, want_lens = py.encode_batch(corpora, max_len)
        got_ids, got_lens = nat.encode_batch(corpora, max_len)
        np.testing.assert_array_equal(got_ids, want_ids)
        np.testing.assert_array_equal(got_lens, want_lens)

    # The Latin table really gets exercised natively, not via fallback.
    _, _, handled = native.wp_encode_batch(
        nat._handle, ["café søster don't"], 16
    )
    assert handled[0] == 1

    rng = np.random.default_rng(1)
    pieces = ["love", "the", "rain", "zzz", "don't", ",", "!", "$", "a",
              "[MASK]", "[SEP]", "x" * 120, "24", "7-7", "\t", "  ",
              "café", "naïve", "«quoted»", "ßü"]
    fuzz = [
        "".join(rng.choice(pieces) + (" " if rng.random() < 0.7 else "")
                for _ in range(rng.integers(0, 14)))
        for _ in range(300)
    ]
    want_ids, want_lens = py.encode_batch(fuzz, 24)
    got_ids, got_lens = nat.encode_batch(fuzz, 24)
    np.testing.assert_array_equal(got_ids, want_ids)
    np.testing.assert_array_equal(got_lens, want_lens)


def test_native_wordpiece_universal_newline_vocab(pair, tmp_path_factory):
    """Bare-``\\r`` and ``\\r\\n`` vocab line terminators parse like the
    Python tokenizer's text-mode (universal-newline) read — a classic-Mac
    vocab used to fuse lines natively, shifting every later id by one."""
    from music_analyst_tpu.data import native
    from music_analyst_tpu.models.tokenization import (
        NativeWordPieceTokenizer,
    )

    if not native.available():
        pytest.skip(f"native lib unavailable: {native.unavailable_reason()}")
    path = tmp_path_factory.mktemp("crvocab") / "vocab.txt"
    terminators = ["\r", "\r\n", "\n"]
    blob = "".join(
        tok + terminators[i % len(terminators)]
        for i, tok in enumerate(VOCAB)
    )
    path.write_bytes(blob.encode("utf-8"))
    py = WordPieceTokenizer(str(path))
    nat = NativeWordPieceTokenizer(str(path))
    assert nat._handle is not None

    texts = ["love the rain", "don't stop loving", "rains rain rained"]
    want_ids, want_lens = py.encode_batch(texts, 16)
    got_ids, got_lens = nat.encode_batch(texts, 16)
    np.testing.assert_array_equal(got_ids, want_ids)
    np.testing.assert_array_equal(got_lens, want_lens)


def test_native_wordpiece_refuses_vocab_without_specials(tmp_path_factory):
    from music_analyst_tpu.data import native

    if not native.available():
        pytest.skip(f"native lib unavailable: {native.unavailable_reason()}")
    from music_analyst_tpu.models.tokenization import _wp_char_table

    path = tmp_path_factory.mktemp("badvocab") / "vocab.txt"
    path.write_text("just\nwords\n", encoding="utf-8")
    assert native.wp_create(str(path), _wp_char_table()) is None
