"""End-to-end analysis parity on the fixture corpus (golden oracle)."""

import json
from collections import Counter

import pytest

from music_analyst_tpu.data.csv_io import iter_dataset_exact, sort_count_entries
from music_analyst_tpu.data.tokenizer import tokenize_ascii
from music_analyst_tpu.engines.wordcount import run_analysis


def oracle_counts(data: bytes):
    """Serial restatement of the reference's counting semantics."""
    words = Counter()
    artists = Counter()
    songs = 0
    word_total = 0
    for artist_raw, text_raw in iter_dataset_exact(data):
        toks = tokenize_ascii(text_raw)
        words.update(toks)
        word_total += len(toks)
        songs += 1  # every record counts, even empty artist (contract #3)
        if artist_raw:
            artists[artist_raw.decode("utf-8", errors="replace")] += 1
    return words, artists, songs, word_total


@pytest.fixture(scope="module")
def analysis(fixture_csv_module, tmp_path_factory):
    out = tmp_path_factory.mktemp("analysis_out")
    return (
        run_analysis(str(fixture_csv_module), output_dir=str(out), quiet=True),
        out,
        fixture_csv_module.read_bytes(),
    )


@pytest.fixture(scope="module")
def fixture_csv_module():
    import pathlib

    return pathlib.Path(__file__).parent / "fixtures" / "mini_songs.csv"


def test_counts_match_oracle(analysis):
    result, _, data = analysis
    words, artists, songs, word_total = oracle_counts(data)
    assert result.total_songs == songs
    assert result.total_words == word_total
    assert result.word_entries == sort_count_entries(words.items())
    assert result.artist_entries == sort_count_entries(artists.items())


def test_output_files_exact_format(analysis):
    result, out, data = analysis
    words, artists, _, _ = oracle_counts(data)
    word_csv = (out / "word_counts.csv").read_text()
    lines = word_csv.splitlines()
    assert lines[0] == "word,count"
    top_word, top_count = sort_count_entries(words.items())[0]
    assert lines[1] == f'"{top_word}",{top_count}'
    artist_csv = (out / "top_artists.csv").read_text()
    assert artist_csv.splitlines()[0] == "artist,count"
    # Quoted-comma artist must round-trip with quote doubling rules
    assert '"Earth, Wind & Fire",1' in artist_csv


def test_metrics_schema(analysis):
    _, out, _ = analysis
    metrics = json.loads((out / "performance_metrics.json").read_text())
    assert metrics["processes"] == 8
    for key in ("total_songs", "total_words", "compute_time", "total_time"):
        assert key in metrics
    for sub in ("avg_seconds", "min_seconds", "max_seconds"):
        assert sub in metrics["compute_time"]
        assert sub in metrics["total_time"]
    assert len(metrics["per_chip"]) == 8
    assert metrics["device_platform"] == "cpu"


def test_per_chip_timings_are_measured_not_replicated(analysis):
    """Each shard's count phase is timed individually (the reference's
    per-rank MPI_Reduce stats, src/parallel_spotify.c:1077-1082) — the
    per_chip column must NOT be one number copied per device."""
    result, out, _ = analysis
    metrics = json.loads((out / "performance_metrics.json").read_text())
    per_chip = [entry["compute_seconds"] for entry in metrics["per_chip"]]
    assert len(per_chip) == 8
    assert all(s > 0 for s in per_chip)
    # Eight independent perf_counter measurements of different shard sizes;
    # identical-to-the-nanosecond values would mean replication, not
    # measurement.
    assert len(set(per_chip)) > 1
    assert len(set(result.per_chip_compute)) > 1
    # And the min/avg/max stats derive from that spread.
    assert metrics["compute_time"]["min_seconds"] <= metrics["compute_time"]["avg_seconds"]
    assert metrics["compute_time"]["max_seconds"] >= metrics["compute_time"]["avg_seconds"]


def test_split_artifacts_written(analysis):
    _, out, _ = analysis
    split = out / "split_columns"
    assert (split / "artist.csv").exists()
    assert (split / "text.csv").exists()


def test_per_chip_column_covers_multi_axis_mesh(fixture_csv_module, tmp_path):
    """On a dp×tp mesh the per_chip column still has one entry per DEVICE
    (devices in a dp row share their shard's measured time)."""
    from music_analyst_tpu.parallel.mesh import build_mesh, factor_devices

    mesh = build_mesh(factor_devices(8, ("dp", "tp"), fixed={"tp": 2}))
    result = run_analysis(
        str(fixture_csv_module), output_dir=str(tmp_path), mesh=mesh,
        quiet=True,
    )
    metrics = json.loads((tmp_path / "performance_metrics.json").read_text())
    assert len(metrics["per_chip"]) == 8
    assert len(result.per_chip_compute) == 8
    per_chip = [e["compute_seconds"] for e in metrics["per_chip"]]
    # 4 dp shards × 2 tp replicas: exactly 4 distinct shard timings, each
    # appearing twice.
    assert len(set(per_chip)) <= 4
    assert sorted(per_chip.count(v) for v in set(per_chip)) == [2] * len(set(per_chip))


def test_word_limit_truncates(fixture_csv_module, tmp_path):
    result = run_analysis(
        str(fixture_csv_module),
        output_dir=str(tmp_path),
        word_limit=3,
        artist_limit=2,
        quiet=True,
    )
    word_lines = (tmp_path / "word_counts.csv").read_text().splitlines()
    assert len(word_lines) == 4  # header + 3
    artist_lines = (tmp_path / "top_artists.csv").read_text().splitlines()
    assert len(artist_lines) == 3
