"""Every fault site is drilled somewhere — the coverage roster.

``resilience/faults.py`` SITES is the injection contract: a site nobody
trips is a seam whose failure story is untested, and it rots silently
when the call site moves.  Two scan tests hold the roster — every SITES
member must appear in at least one bench/chaos scenario (``benchmarks/``)
and at least one test (``tests/``) — and the micro-drills below close
the gaps the roster found when it landed: ``compile.first``,
``checkpoint.load``, ``loadgen.tick``, ``journal.append``,
``journal.compact``, and ``serve.reply`` each get a direct
inject → observe-degradation → recover exercise.
"""

import os
import time

import numpy as np
import pytest

from music_analyst_tpu.resilience import (
    configure_faults,
    fault_stats,
    reset_retry_stats,
    retry_stats,
)
from music_analyst_tpu.resilience.faults import SITES

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ------------------------------------------------------------- roster scans


def _corpus(directory, exclude_dirs=()):
    chunks = []
    for root, dirs, files in os.walk(directory):
        dirs[:] = [d for d in dirs if d not in exclude_dirs]
        for name in sorted(files):
            if name.endswith(".py"):
                path = os.path.join(root, name)
                with open(path, "r", encoding="utf-8",
                          errors="ignore") as fh:
                    chunks.append(fh.read())
    return "\n".join(chunks)


def test_every_fault_site_appears_in_a_bench_scenario():
    corpus = _corpus(os.path.join(_REPO, "benchmarks"),
                     exclude_dirs=("results",))
    missing = sorted(site for site in SITES if site not in corpus)
    assert not missing, (
        f"fault sites with no chaos/bench scenario: {missing} — add a "
        "scenario in benchmarks/ (chaos.py is the usual home)"
    )


def test_every_fault_site_appears_in_a_test():
    corpus = _corpus(os.path.join(_REPO, "tests"))
    missing = sorted(site for site in SITES if site not in corpus)
    assert not missing, (
        f"fault sites with no test drill: {missing} — add one here or in "
        "the subsystem's own test file"
    )


# ----------------------------------------------------------- compile.first


def test_drill_compile_first_transient_retries_to_identical_result():
    import jax.numpy as jnp

    from music_analyst_tpu.profiling.compile import profiled_jit

    x = jnp.arange(6, dtype=jnp.float32)
    clean = np.asarray(
        profiled_jit(lambda v: v * 2.0 - 1.0, name="fault_cov_clean")(x)
    )
    reset_retry_stats()
    configure_faults("compile.first:error@1")
    try:
        fn = profiled_jit(lambda v: v * 2.0 - 1.0, name="fault_cov_fault")
        faulted = np.asarray(fn(x))
        trips = fault_stats()["compile.first"]["trips"]
    finally:
        configure_faults(None)
    assert trips == 1
    assert retry_stats().get("compile.first", {}).get("recoveries", 0) >= 1
    assert np.array_equal(clean, faulted)


# ---------------------------------------------------------- checkpoint.load


def test_drill_checkpoint_load_transient_reruns_unit():
    import jax

    from music_analyst_tpu.engines.checkpoint import load_quantized_params

    rng = np.random.default_rng(11)
    weights = {
        f"layer{i}": {"kernel": rng.standard_normal((8, 8)).astype(
            np.float32)}
        for i in range(2)
    }

    def _unit_source():
        for unit, tree in weights.items():
            yield unit, [(f"{unit}/kernel", tree["kernel"])]

    def _leaves(tree):
        return [np.asarray(x) for x in jax.tree_util.tree_leaves(tree)]

    clean = _leaves(load_quantized_params(weights, _unit_source, "int8"))
    configure_faults("checkpoint.load:error@1")
    try:
        faulted = _leaves(
            load_quantized_params(weights, _unit_source, "int8")
        )
        trips = fault_stats()["checkpoint.load"]["trips"]
    finally:
        configure_faults(None)
    assert trips == 1
    assert len(clean) == len(faulted)
    assert all(np.array_equal(a, b) for a, b in zip(clean, faulted))


# -------------------------------------------------------------- loadgen.tick


def test_drill_loadgen_tick_fault_drops_offered_request():
    from benchmarks.loadgen import Arrival, LoadGen

    class _Settled:
        """Minimal ServeRequest stand-in: settles instantly, ok reply."""

        def __init__(self):
            self.done = True
            self.response = {"ok": True, "label": "Neutral"}
            self.t_enqueue = time.monotonic()
            self.t_settle = self.t_enqueue

        def wait(self, timeout=None):
            return True

    submitted = []

    def _submit(rid, arrival):
        submitted.append(rid)
        return _Settled()

    arrivals = [Arrival(t_s=0.0), Arrival(t_s=0.001), Arrival(t_s=0.002)]
    configure_faults("loadgen.tick:error@2")
    try:
        report = LoadGen(_submit).replay(arrivals, settle_timeout_s=5.0)
        trips = fault_stats()["loadgen.tick"]["trips"]
    finally:
        configure_faults(None)
    # The faulted tick drops the *offered* request before submit — the
    # target never sees a half-submitted request and the report says so.
    assert trips == 1
    assert report["ticks_faulted"] == 1
    assert report["offered"] == 3
    assert report["submitted"] == 2 and len(submitted) == 2
    assert report["ok"] == 2
    assert report["silent_drops"] == 0


# ------------------------------------------------------------ journal.append


def test_drill_journal_append_fault_counts_and_keeps_serving(tmp_path):
    from music_analyst_tpu.serving.journal import RequestJournal

    d = str(tmp_path / "wal")
    j = RequestJournal(d, sync_every=1)
    assert j.recover() == []
    configure_faults("journal.append:error@1")
    try:
        j.record_admitted("a", "sentiment", "first verse")  # faulted
        j.record_admitted("b", "sentiment", "second verse")  # lands
        trips = fault_stats()["journal.append"]["trips"]
    finally:
        configure_faults(None)
    stats = j.stats()
    assert trips == 1
    assert stats["append_errors"] == 1
    assert stats["admitted"] == 1  # only the landed admit counted
    # No torn state: the faulted admit never entered the replay index,
    # so a restart re-dispatches exactly what was durably admitted.
    j2 = RequestJournal(d, sync_every=1)
    replay = j2.recover()
    assert [r["id"] for r in replay] == ["b"]
    j2.close()


# ----------------------------------------------------------- journal.compact


def test_drill_journal_compact_fault_leaves_replayable_state(tmp_path):
    from music_analyst_tpu.serving.journal import RequestJournal

    d = str(tmp_path / "wal")
    j = RequestJournal(d, sync_every=1)
    assert j.recover() == []
    for rid in ("a", "b", "c"):
        j.record_admitted(rid, "sentiment", f"verse {rid}")
    j.record_replied("b", {"ok": True, "label": "Positive"})
    configure_faults("journal.compact:error@1")
    try:
        # The seam fires after the compacted segment is published and
        # before the sealed history is unlinked — both states replay
        # identically (records are idempotent upserts).
        j.compact()
        trips = fault_stats()["journal.compact"]["trips"]
    finally:
        configure_faults(None)
    assert trips == 1
    assert j.stats()["append_errors"] == 1
    # The journal keeps serving after the faulted compaction…
    j.record_admitted("d", "sentiment", "verse d")
    assert j.stats()["admitted"] == 4
    # …and a restart on the same directory replays the merged state:
    # old + compacted segments coexist, replay converges anyway.
    j2 = RequestJournal(d, sync_every=1)
    replay_ids = sorted(r["id"] for r in j2.recover())
    assert replay_ids == ["a", "c", "d"]
    assert j2.stats()["unclean_start"] is True  # never closed cleanly
    j2.close()


# --------------------------------------------------------------- serve.reply


def test_drill_serve_reply_crash_accounts_and_dedups(tmp_path):
    """Subprocess SIGKILL drill on the pre-reply seam: kill the journaled
    mock server as it is about to answer, restart on the same journal
    dir, re-send everything — 100% accounting, zero duplicate computes."""
    from benchmarks.crash import _MOCK_ARGS, _mock_trace, run_drill

    row = run_drill(
        "pre_reply", "serve.reply:crash@4", str(tmp_path),
        model_args=_MOCK_ARGS, trace=_mock_trace(8, seed=29),
    )
    assert row["killed_by_sigkill"] is True
    assert row["recovered_exit_ok"] is True
    assert row["all_accounted"] is True
    assert row["loadgen_silent_drops"] == 0
    assert row["duplicates_deduped"] is True
    assert row["unclean_stamped"] is True
    assert row["journal"]["unclean_start"] is True
