"""Sequence packing for the encoder path (SURVEY §7 "packed batching").

Packing puts several short lyrics into one max_len row behind a
block-diagonal attention mask with per-segment restarted positions
(``models/distilbert.py``).  The contract under test: packed
classification is the SAME function as flat classification — identical
labels and near-identical confidences on every input — while running
fewer device rows.  The reference has no analogue (it classifies one song
per blocking HTTP call, ``scripts/sentiment_classifier.py:144-154``).
"""

import dataclasses

import numpy as np
import pytest

from music_analyst_tpu.models.distilbert import (
    DistilBertClassifier,
    DistilBertConfig,
    pack_segments,
)

TEXTS = [
    "love and joy in the morning light",
    "",
    "rain rain rain sorrow endless grey rain",
    "la " * 60,                     # max_len-long row (truncates)
    "short one",
    "the memory of summer keeps me warm through winter",
    "word " * 200,                  # way past the cap
    "dance tonight",
]


def _f32_tiny():
    # float32 so flat and packed agree to tight tolerance: same math,
    # different batching, no bf16 rounding noise near the threshold.
    return dataclasses.replace(DistilBertConfig.tiny(), dtype="float32")


# ---------------------------------------------------------------- packer


def test_pack_segments_covers_every_input_once():
    rng = np.random.default_rng(0)
    lengths = rng.integers(2, 128, size=257)
    bin_of, slot_of, starts, row_len = pack_segments(lengths, 128)
    seen = set(zip(bin_of.tolist(), slot_of.tolist()))
    assert len(seen) == lengths.size  # one (row, slot) per input
    # Every (row, slot) points at a real start, and the segment spans
    # [start, start+len) within the occupied prefix.
    for i, (b, k) in enumerate(zip(bin_of, slot_of)):
        assert starts[b, k] < 128
        assert starts[b, k] + lengths[i] <= row_len[b]


def test_pack_segments_respects_capacity_and_is_contiguous():
    rng = np.random.default_rng(1)
    lengths = rng.integers(2, 64, size=300)
    bin_of, slot_of, starts, row_len = pack_segments(lengths, 128)
    assert row_len.max() <= 128
    #

    # Segments within a row are back to back: sorted starts + lengths
    # tile the prefix exactly.
    for b in range(starts.shape[0]):
        members = np.flatnonzero(bin_of == b)
        spans = sorted(
            (int(starts[b, slot_of[i]]), int(lengths[i])) for i in members
        )
        offset = 0
        for start, length in spans:
            assert start == offset
            offset += length
        assert offset == row_len[b]


def test_pack_segments_actually_packs():
    """Uniform short lyrics should land ~capacity/len per row, not 1."""
    lengths = np.full(64, 16)
    bin_of, _, starts, _ = pack_segments(lengths, 128)
    assert starts.shape[0] == 8          # 128//16 = 8 segments per row
    assert starts.shape[1] == 8


def test_pack_segments_rejects_bad_lengths():
    with pytest.raises(ValueError, match="length > 0"):
        pack_segments(np.array([4, 0, 8]), 128)
    with pytest.raises(ValueError, match="capacity"):
        pack_segments(np.array([4, 200]), 128)


def test_pack_segments_empty():
    bin_of, slot_of, starts, row_len = pack_segments(np.array([], int), 128)
    assert bin_of.size == slot_of.size == 0
    assert starts.shape == (0, 0) and row_len.size == 0


# ------------------------------------------------------------ classifier


def test_packed_labels_match_flat():
    cfg = _f32_tiny()
    flat = DistilBertClassifier(config=cfg, max_len=64, seed=3)
    packed = DistilBertClassifier(config=cfg, max_len=64, seed=3,
                                  packed=True)
    packed.params = flat.params
    assert packed.classify_batch(TEXTS) == flat.classify_batch(TEXTS)


def test_packed_confidences_match_flat():
    """Stronger than labels: the underlying per-lyric confidences agree,
    so parity isn't an artifact of coarse label binning."""
    cfg = _f32_tiny()
    flat = DistilBertClassifier(config=cfg, max_len=64, seed=4)
    packed = DistilBertClassifier(config=cfg, max_len=64, seed=4,
                                  packed=True)
    packed.params = flat.params

    def confidences(clf):
        texts, parts = clf.submit(TEXTS)
        conf = np.empty(len(texts))
        for rows, _, part_conf, n in parts:
            part_conf = np.asarray(part_conf)
            if isinstance(rows, tuple):
                conf[:n] = part_conf[rows[0], rows[1]]
            else:
                conf[:] = part_conf[:n]
        return conf

    np.testing.assert_allclose(
        confidences(packed), confidences(flat), rtol=1e-4, atol=1e-5
    )


def test_packed_index_wire_dtype():
    """Segment starts/row lengths ride the wire at int16 only when every
    position in [0, max_len] fits (max_len is the empty-slot sentinel);
    the wide dtype is behavior-identical, so the narrowing is pure wire
    format."""
    cfg = _f32_tiny()
    clf = DistilBertClassifier(config=cfg, max_len=64, seed=3, packed=True)
    assert clf._index_dtype is np.int16  # 64 < 2**15
    narrow = clf.classify_batch(TEXTS)
    clf._index_dtype = np.int32  # what a >= 2**15 max_len selects
    assert clf.classify_batch(TEXTS) == narrow


def test_packed_segment_isolation():
    """A lyric's result must not depend on its row-mates: classify it
    alone vs packed among neighbors and compare confidences."""
    cfg = _f32_tiny()
    clf = DistilBertClassifier(config=cfg, max_len=64, seed=5, packed=True)
    target = "love and rain and memory"
    alone = clf.submit([target])
    crowded = clf.submit([target] + TEXTS)

    def conf_of(handle, index):
        _, parts = handle
        (rows, _, part_conf, _) = parts[0]
        return float(np.asarray(part_conf)[rows[0][index], rows[1][index]])

    np.testing.assert_allclose(
        conf_of(alone, 0), conf_of(crowded, 0), rtol=1e-4, atol=1e-5
    )


def test_packed_uses_fewer_rows_than_flat():
    """The point of the lever: short lyrics share rows."""
    cfg = _f32_tiny()
    clf = DistilBertClassifier(config=cfg, max_len=64, seed=0, packed=True)
    texts = ["short lyric here"] * 64
    _, parts = clf.submit(texts)
    ((_, classes, _, _),) = parts
    assert np.asarray(classes).shape[0] < 64


def test_packed_suffix_and_guards():
    clf = DistilBertClassifier.from_pretrained_or_random(
        "distilbert-tiny-packed", max_len=64
    )
    assert clf.packed and clf.config.dim == 64
    # Every right-sizing/quant suffix composes, in any order.
    for name in ("distilbert-tiny-int8-packed",
                 "distilbert-tiny-packed-int8"):
        combo = DistilBertClassifier.from_pretrained_or_random(
            name, max_len=64
        )
        assert combo.packed and combo.config.quant == "int8"
        assert combo.config.dim == 64, name  # tiny config, any order
    assert combo.classify_batch(["love and joy", ""])[1] == "Neutral"
    with pytest.raises(ValueError, match="length_buckets"):
        DistilBertClassifier(
            config=DistilBertConfig.tiny(), max_len=64, packed=True,
            length_buckets=(16, 32),
        )


def test_packed_composes_with_flash_attention():
    """The Pallas flash kernel takes segment ids natively, so the packed
    classifier runs on the flash path with the same labels/confidences as
    the dense one (ops/flash_attention.py segment mode)."""
    dense_cfg = _f32_tiny()
    flash_cfg = dataclasses.replace(dense_cfg, attn_impl="flash")
    dense = DistilBertClassifier(config=dense_cfg, max_len=64, seed=8,
                                 packed=True)
    flash = DistilBertClassifier(config=flash_cfg, max_len=64, seed=8,
                                 packed=True)
    flash.params = dense.params
    assert flash.classify_batch(TEXTS) == dense.classify_batch(TEXTS)


def test_packed_on_dp_mesh():
    """Packed rows shard over dp like flat rows do."""
    import jax
    from jax.sharding import Mesh

    devices = np.array(jax.devices()[:4]).reshape(4)
    mesh = Mesh(devices, ("dp",))
    cfg = _f32_tiny()
    flat = DistilBertClassifier(config=cfg, max_len=64, seed=6)
    # Same seed → same params; the mesh only changes placement.
    packed = DistilBertClassifier(config=cfg, max_len=64, seed=6,
                                  packed=True, mesh=mesh)
    assert packed.classify_batch(TEXTS) == flat.classify_batch(TEXTS)


def test_packed_engine_end_to_end(tmp_path):
    """run_sentiment with --model distilbert-tiny-packed produces the
    full artifact set with one label per row."""
    from music_analyst_tpu.engines.sentiment import run_sentiment

    csv_path = tmp_path / "songs.csv"
    csv_path.write_text(
        "artist,text\n"
        + "\n".join(f"a{i},\"lyric {i} love\"" for i in range(9))
        + "\n",
        encoding="utf-8",
    )
    result = run_sentiment(
        str(csv_path), model="distilbert-tiny-packed",
        output_dir=str(tmp_path), quiet=True, batch_size=4,
    )
    assert sum(result.counts.values()) == len(result.rows) == 9
    assert (tmp_path / "sentiment_totals.json").exists()
