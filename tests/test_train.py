"""Training step: loss decreases; full dp/sp/tp (+ep MoE) sharded step runs."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from music_analyst_tpu.engines.train import (
    init_train_state,
    make_optimizer,
    make_train_step,
)
from music_analyst_tpu.models.llama import LlamaConfig, LlamaModel
from music_analyst_tpu.parallel.mesh import MeshSpec, build_mesh


def _batch(rng, B=8, S=33, vocab=256):
    ids = rng.integers(1, vocab, (B, S)).astype(np.int32)
    lengths = rng.integers(S // 2, S + 1, (B,)).astype(np.int32)
    return jnp.asarray(ids), jnp.asarray(lengths)


def test_loss_decreases_single_device():
    cfg = LlamaConfig.tiny()
    model = LlamaModel(cfg)
    opt = make_optimizer(1e-2)
    rng = np.random.default_rng(0)
    ids, lengths = _batch(rng)
    state = init_train_state(model, opt, (ids, lengths))
    step = make_train_step(model, opt)
    losses = []
    for _ in range(5):
        state, loss = step(state, ids, lengths)
        losses.append(float(loss))
    assert losses[-1] < losses[0]
    assert np.isfinite(losses).all()
    assert int(state.step) == 5


def test_sharded_step_dp_sp_tp():
    cfg = LlamaConfig.tiny()
    model = LlamaModel(cfg)
    opt = make_optimizer()
    mesh = build_mesh(MeshSpec((("dp", 2), ("sp", 2), ("tp", 2))))
    rng = np.random.default_rng(1)
    ids, lengths = _batch(rng, B=4, S=17)
    state = init_train_state(model, opt, (ids, lengths), mesh=mesh)
    step = make_train_step(model, opt, mesh=mesh)
    state, loss = step(state, ids, lengths)
    assert np.isfinite(float(loss))
    # params keep their tp sharding after the update
    spec = state.params["layer_0"]["feed_forward"]["gate_proj"][
        "kernel"
    ].sharding.spec
    assert "tp" in str(spec)


def test_sharded_matches_unsharded_loss():
    cfg = LlamaConfig.tiny()
    model = LlamaModel(cfg)
    opt = make_optimizer()
    rng = np.random.default_rng(2)
    ids, lengths = _batch(rng, B=4, S=17)
    state_a = init_train_state(model, opt, (ids, lengths), seed=7)
    step_a = make_train_step(model, opt)
    _, loss_a = step_a(state_a, ids, lengths)

    mesh = build_mesh(MeshSpec((("dp", 4), ("tp", 2))))
    state_b = init_train_state(model, opt, (ids, lengths), seed=7, mesh=mesh)
    step_b = make_train_step(model, opt, mesh=mesh)
    _, loss_b = step_b(state_b, ids, lengths)
    np.testing.assert_allclose(float(loss_a), float(loss_b), rtol=2e-2)


def test_moe_expert_parallel_step():
    cfg = LlamaConfig(
        vocab_size=256, dim=64, n_layers=2, n_heads=4, n_kv_heads=2,
        hidden_dim=128, rope_theta=1e4, max_seq_len=256,
        n_experts=4, moe_top_k=2,
    )
    model = LlamaModel(cfg)
    opt = make_optimizer()
    mesh = build_mesh(MeshSpec((("dp", 2), ("ep", 4))))
    rng = np.random.default_rng(3)
    ids, lengths = _batch(rng, B=4, S=17)
    state = init_train_state(model, opt, (ids, lengths), mesh=mesh)
    # expert stacks sharded over ep
    spec = state.params["layer_0"]["feed_forward_moe"][
        "gate_experts"
    ].sharding.spec
    assert "ep" in str(spec)
    step = make_train_step(model, opt, mesh=mesh)
    state, loss = step(state, ids, lengths)
    assert np.isfinite(float(loss))


def test_zero1_optimizer_state_sharding():
    """ZeRO-1: Adam moments shard over dp (arXiv:2004.13336), survive an
    update step, and change nothing numerically."""
    import numpy as np
    from jax.sharding import NamedSharding

    from music_analyst_tpu.engines.train import (
        init_train_state,
        make_optimizer,
        make_train_step,
    )
    from music_analyst_tpu.models.llama import LlamaConfig, LlamaModel
    from music_analyst_tpu.parallel.mesh import MeshSpec, build_mesh

    mesh = build_mesh(MeshSpec((("dp", 4), ("tp", 2))))
    cfg = LlamaConfig(
        vocab_size=256, dim=32, n_layers=2, n_heads=4, n_kv_heads=2,
        hidden_dim=64, rope_theta=1e4, max_seq_len=64, dtype="float32",
    )
    model = LlamaModel(cfg)
    opt = make_optimizer()
    rng = np.random.default_rng(0)
    ids = jnp.asarray(rng.integers(1, 256, (8, 17)), jnp.int32)
    lengths = jnp.full((8,), 17, jnp.int32)

    plain = init_train_state(model, opt, (ids, lengths), mesh=mesh)
    z1 = init_train_state(model, opt, (ids, lengths), mesh=mesh, zero1=True)

    # Moments must actually be dp-sharded: find at least one leaf whose
    # sharding spec names 'dp', and verify its addressable shard shrank.
    def dp_leaves(state):
        found = []
        for leaf in jax.tree_util.tree_leaves(state.opt_state):
            sh = getattr(leaf, "sharding", None)
            if isinstance(sh, NamedSharding) and "dp" in jax.tree_util.tree_leaves(
                tuple(sh.spec)
            ):
                found.append(leaf)
        return found

    assert not dp_leaves(plain)
    sharded_moments = dp_leaves(z1)
    assert sharded_moments
    leaf = sharded_moments[0]
    assert leaf.addressable_shards[0].data.size < leaf.size

    step_plain = make_train_step(model, opt, mesh=mesh)
    # No state_like: the step pins output shardings from its first input,
    # so zero1=True at init is the only knob needed.
    step_z1 = make_train_step(model, opt, mesh=mesh)
    plain, loss_a = step_plain(plain, ids, lengths)
    z1, loss_b = step_z1(z1, ids, lengths)
    np.testing.assert_allclose(float(loss_a), float(loss_b), rtol=1e-5)
    # dp-sharding survives the update (out_shardings pins it)
    assert dp_leaves(z1)
    # and a second step still agrees numerically
    plain, loss_a2 = step_plain(plain, ids, lengths)
    z1, loss_b2 = step_z1(z1, ids, lengths)
    np.testing.assert_allclose(float(loss_a2), float(loss_b2), rtol=1e-5)
