"""Training step: loss decreases; full dp/sp/tp (+ep MoE) sharded step runs."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from music_analyst_tpu.engines.train import (
    init_train_state,
    make_optimizer,
    make_train_step,
)
from music_analyst_tpu.models.llama import LlamaConfig, LlamaModel
from music_analyst_tpu.parallel.mesh import MeshSpec, build_mesh


def _batch(rng, B=8, S=33, vocab=256):
    ids = rng.integers(1, vocab, (B, S)).astype(np.int32)
    lengths = rng.integers(S // 2, S + 1, (B,)).astype(np.int32)
    return jnp.asarray(ids), jnp.asarray(lengths)


def test_loss_decreases_single_device():
    cfg = LlamaConfig.tiny()
    model = LlamaModel(cfg)
    opt = make_optimizer(1e-2)
    rng = np.random.default_rng(0)
    ids, lengths = _batch(rng)
    state = init_train_state(model, opt, (ids, lengths))
    step = make_train_step(model, opt)
    losses = []
    for _ in range(5):
        state, loss = step(state, ids, lengths)
        losses.append(float(loss))
    assert losses[-1] < losses[0]
    assert np.isfinite(losses).all()
    assert int(state.step) == 5


def test_sharded_step_dp_sp_tp():
    cfg = LlamaConfig.tiny()
    model = LlamaModel(cfg)
    opt = make_optimizer()
    mesh = build_mesh(MeshSpec((("dp", 2), ("sp", 2), ("tp", 2))))
    rng = np.random.default_rng(1)
    ids, lengths = _batch(rng, B=4, S=17)
    state = init_train_state(model, opt, (ids, lengths), mesh=mesh)
    step = make_train_step(model, opt, mesh=mesh)
    state, loss = step(state, ids, lengths)
    assert np.isfinite(float(loss))
    # params keep their tp sharding after the update
    spec = state.params["layer_0"]["feed_forward"]["gate_proj"][
        "kernel"
    ].sharding.spec
    assert "tp" in str(spec)


def test_sharded_matches_unsharded_loss():
    cfg = LlamaConfig.tiny()
    model = LlamaModel(cfg)
    opt = make_optimizer()
    rng = np.random.default_rng(2)
    ids, lengths = _batch(rng, B=4, S=17)
    state_a = init_train_state(model, opt, (ids, lengths), seed=7)
    step_a = make_train_step(model, opt)
    _, loss_a = step_a(state_a, ids, lengths)

    mesh = build_mesh(MeshSpec((("dp", 4), ("tp", 2))))
    state_b = init_train_state(model, opt, (ids, lengths), seed=7, mesh=mesh)
    step_b = make_train_step(model, opt, mesh=mesh)
    _, loss_b = step_b(state_b, ids, lengths)
    np.testing.assert_allclose(float(loss_a), float(loss_b), rtol=2e-2)


def test_moe_expert_parallel_step():
    cfg = LlamaConfig(
        vocab_size=256, dim=64, n_layers=2, n_heads=4, n_kv_heads=2,
        hidden_dim=128, rope_theta=1e4, max_seq_len=256,
        n_experts=4, moe_top_k=2,
    )
    model = LlamaModel(cfg)
    opt = make_optimizer()
    mesh = build_mesh(MeshSpec((("dp", 2), ("ep", 4))))
    rng = np.random.default_rng(3)
    ids, lengths = _batch(rng, B=4, S=17)
    state = init_train_state(model, opt, (ids, lengths), mesh=mesh)
    # expert stacks sharded over ep
    spec = state.params["layer_0"]["feed_forward_moe"][
        "gate_experts"
    ].sharding.spec
    assert "ep" in str(spec)
    step = make_train_step(model, opt, mesh=mesh)
    state, loss = step(state, ids, lengths)
    assert np.isfinite(float(loss))
