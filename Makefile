# Top-level convenience targets.  The native library has its own Makefile
# (make -C native); tests force the CPU platform via tests/conftest.py.

PY ?= python

.PHONY: smoke test native

# Fast observability gate: profiling + telemetry unit tests, then one
# smoke-shaped bench.py run through the full parent/child/--baseline
# machinery, asserting the ONE-JSON-line stdout contract the round driver
# depends on.  Runs in a couple of minutes on the sandboxed CPU.
smoke:
	env JAX_PLATFORMS=cpu PALLAS_AXON_POOL_IPS= \
		$(PY) -m pytest tests/test_profiling.py tests/test_telemetry.py \
		tests/test_telemetry_contract.py -q
	env JAX_PLATFORMS=cpu PALLAS_AXON_POOL_IPS= MUSICAAL_BENCH_SMOKE=1 \
		$(PY) bench.py --baseline --attempts 1 --deadline 240 \
		| $(PY) -c "import json,sys; \
lines=[l for l in sys.stdin.read().splitlines() if l.strip()]; \
assert len(lines)==1, f'expected ONE JSON line, got {len(lines)}'; \
payload=json.loads(lines[0]); \
assert 'vs_baseline_detail' in payload, 'missing --baseline detail'; \
print('smoke ok:', payload['metric'], payload['value'])"

test:
	$(PY) -m pytest tests/ -q

native:
	$(MAKE) -C native
