# Top-level convenience targets.  The native library has its own Makefile
# (make -C native); tests force the CPU platform via tests/conftest.py.

PY ?= python

.PHONY: smoke test native

# Router self-check body (exported below; the smoke recipe runs it with
# $(PY) -c "$$ROUTER_SELFCHECK" <telemetry-dir>): start 2 mock replica
# workers behind the ReplicaRouter, SIGKILL one mid-load, and assert
# every admitted request is answered or structurally shed AND the run
# manifest's serving.router section records the health transition.
define ROUTER_SELFCHECK
import json, os, signal, sys, tempfile
from music_analyst_tpu.telemetry import configure, get_telemetry
from music_analyst_tpu.serving.router import ReplicaRouter, spawn_replicas
from music_analyst_tpu.serving.server import SentimentServer

out = sys.argv[1]
configure(enabled=True, directory=out)
tel = get_telemetry()
with tel.run_scope("serve", None):
    with tempfile.TemporaryDirectory() as base:
        handles = spawn_replicas(2, base, model="mock", mock=True,
                                 warmup=False)
        router = ReplicaRouter(handles, poll_interval_s=0.1).start()
        server = SentimentServer(router, mode="unix", router=router)
        reqs = [router.submit(i, "sentiment", "happy %d" % i)
                for i in range(4)]
        os.kill(handles[0].proc.pid, signal.SIGKILL)
        reqs += [router.submit(4 + i, "sentiment", "gray %d" % i)
                 for i in range(4)]
        for r in reqs:
            assert r.wait(60), "request %s never settled" % r.id
        ok = sum(1 for r in reqs if r.response.get("ok"))
        shed = sum(1 for r in reqs if not r.response.get("ok")
                   and r.response["error"]["kind"] in
                   ("queue_full", "replica_lost", "draining"))
        assert ok + shed == len(reqs), [r.response for r in reqs]
        stats = router.stats()
        assert stats["health_transitions"], "no health transition"
        router.drain()
manifest = json.load(open(os.path.join(out, "run_manifest.json")))
rt = manifest["serving"]["router"]
assert rt["health_transitions"], rt
assert rt["requeued"] >= 0 and rt["replica_count"] == 2, rt
print("router self-check ok:", ok, "answered,", shed, "shed,",
      rt["requeued"], "requeued,",
      len(rt["health_transitions"]), "health transition(s)")
endef
export ROUTER_SELFCHECK

# Crash-recovery self-check body (exported below): the mid-decode drill
# from benchmarks/crash.py — SIGKILL a journaled generate server while a
# request is in flight on device, restart it on the SAME journal dir,
# re-send everything a reconnecting client would retry, and assert 100%
# accounting, zero duplicate computes (the journal's dedup index answers
# already-sent replies byte-identically), and the unclean_shutdown stamp
# in the restart's run manifest.
define CRASH_SELFCHECK
import sys
from benchmarks.crash import _GEN_ARGS, _gen_trace, run_drill
row = run_drill("mid_decode", "decode.step:crash@3", sys.argv[1],
                model_args=_GEN_ARGS, trace=_gen_trace(3, seed=17))
assert row["killed_by_sigkill"], row
assert row["recovered_exit_ok"], row
assert row["all_accounted"] and row["loadgen_silent_drops"] == 0, row
assert row["duplicates_deduped"], row
assert row["unclean_stamped"], row
print("crash-recovery self-check ok:",
      row["journal"]["replayed"], "replayed,",
      row["journal"]["deduped"], "deduped,",
      "%.1fs" % row["wall_s"])
endef
export CRASH_SELFCHECK

# Burn-rate self-check body (exported below; run with $(PY) -c
# "$$BURN_SELFCHECK" <burst-dir> <steady-dir>): the burst run's
# metrics.jsonl must hold EXACTLY ONE firing burn-rate alert — the bulk
# tenant's shed burn — whose trace_id resolves to a kept shed exemplar
# in the same run's request_traces.jsonl; the steady run must sample
# but stay silent.
define BURN_SELFCHECK
import json, sys
burst, steady = sys.argv[1], sys.argv[2]

def load(path):
    recs = [json.loads(l) for l in open(path) if l.strip()]
    return ([r for r in recs if r.get("type") == "sample"],
            [r for r in recs if r.get("type") == "alert"])

samples, alerts = load(burst + "/metrics.jsonl")
assert len(samples) >= 2, f"burst run took {len(samples)} sample(s)"
firing = [a for a in alerts if a["state"] == "firing"]
assert len(firing) == 1, [a.get("alert") for a in firing]
alert = firing[0]
assert alert["alert"] == "shed_burn_rate", alert
assert alert["tenant"] == "bulk", alert
assert alert["burn_fast"] >= alert["threshold"], alert
tid = alert.get("trace_id")
assert isinstance(tid, str) and tid, f"alert carries no trace_id: {alert}"
traces = [json.loads(l)
          for l in open(burst + "/request_traces.jsonl") if l.strip()]
assert any(t.get("trace_id") == tid for t in traces), \
    f"alert trace_id {tid} not kept in request_traces.jsonl"
s_samples, s_alerts = load(steady + "/metrics.jsonl")
assert len(s_samples) >= 2, f"steady run took {len(s_samples)} sample(s)"
s_firing = [a for a in s_alerts if a["state"] == "firing"]
assert not s_firing, s_firing
print("burn-rate self-check ok: shed_burn_rate tenant=bulk,",
      "burn %.0fx/%.0fx," % (alert["burn_fast"], alert["burn_slow"]),
      "trace", tid, "kept, steady run silent")
endef
export BURN_SELFCHECK

# Engine-ledger self-check body (exported below; run with $(PY) -c
# "$$LEDGER_SELFCHECK" <replies.ndjson> <profile-dir>): after a stdio
# generate burst over two tenants, every reply must be ok, the stats op
# must surface the live ledger, and the final cumulative record in
# engine_ledger.jsonl must tile — classified seconds covering >=95% of
# the engine wall and per-tenant chip-seconds summing to the wall within
# 2% — with zero flush drops and no torn line.
define LEDGER_SELFCHECK
import json, os, sys
replies_path, profile_dir = sys.argv[1], sys.argv[2]
replies = [json.loads(l) for l in open(replies_path) if l.strip()]
by_id = {r["id"]: r for r in replies}
gen = [r for r in replies if r["id"] != "end"]
assert gen and all(r.get("ok") for r in gen), \
    [r for r in gen if not r.get("ok")]
live = ((by_id["end"].get("stats") or {}).get("decode") or {}).get(
    "ledger") or {}
assert live.get("ticks", 0) > 0, f"stats op carries no live ledger: {live}"
path = os.path.join(profile_dir, "engine_ledger.jsonl")
raw = open(path, "rb").read()
assert raw.endswith(b"\n"), "torn final line in engine_ledger.jsonl"
recs = [json.loads(l) for l in raw.decode("utf-8").splitlines()
        if l.strip()]
assert recs and all(r.get("type") == "ledger" for r in recs), recs[:2]
final = recs[-1]["ledger"]
wall = final["engine_wall_s"]
assert wall > 0.0, final
covered = sum(final["seconds"].values())
assert covered >= 0.95 * wall, (covered, wall)
chip = sum(final["chip_seconds"].values())
assert abs(chip - wall) <= 0.02 * wall, (chip, wall)
assert final["ledger_drops"] == 0, final
print("engine-ledger self-check ok:",
      f"{len(recs)} flush(es), coverage {covered / wall:.3f},",
      "chip-seconds within",
      f"{abs(chip - wall) / max(wall, 1e-9) * 100.0:.2f}% of wall")
endef
export LEDGER_SELFCHECK

# Paged-attention kernel self-check body (exported below; run with
# $(PY) -c "$$KERNEL_SELFCHECK"): random pool/table/mask with odd valid
# lengths and a trash-page table row, both Pallas bodies (exact batched
# and the page-streaming TPU body) run in interpret mode against the
# naive f32 gather oracle, then the int8 path with dequant fused into
# the KV-load epilogue.
define KERNEL_SELFCHECK
import numpy as np
import jax.numpy as jnp
from music_analyst_tpu.ops.paged_attention import (
    paged_attention, paged_attention_reference)
from music_analyst_tpu.ops.quant import quantize_kv_page
rng = np.random.RandomState(0)
P, pps, n, n_kv, H, D = 8, 4, 3, 2, 4, 8
n_pages = n * pps
table = rng.permutation(n_pages).reshape(n, pps).astype(np.int32)
table[0, -1] = n_pages  # trash page
lengths = np.array([13, 7, 21], np.int32)  # odd, off the page grid
mask = jnp.asarray(np.arange(pps * P)[None, :] < lengths[:, None])
shape = (n_pages + 1, P, n_kv, D)
k = jnp.asarray(rng.standard_normal(shape), dtype=jnp.bfloat16)
v = jnp.asarray(rng.standard_normal(shape), dtype=jnp.bfloat16)
q = jnp.asarray(rng.standard_normal((n, 1, H, D)), dtype=jnp.bfloat16)
t = jnp.asarray(table)
ref = np.asarray(paged_attention_reference(q, k, v, t, mask))
for stream in (False, True):
    out = np.asarray(paged_attention(
        q, k, v, t, mask, interpret=True, stream=stream), np.float32)
    assert np.allclose(out, ref, atol=0.06, rtol=0.06), \
        f"stream={stream} body diverged from the f32 oracle"
kq, ks = quantize_kv_page(k.astype(jnp.float32))
vq, vs = quantize_kv_page(v.astype(jnp.float32))
out8 = np.asarray(paged_attention(
    q, kq, vq, t, mask, key_scale=ks, value_scale=vs,
    interpret=True), np.float32)
assert np.allclose(out8, ref, atol=0.15), "int8 path diverged"
print("paged-attention kernel self-check ok:",
      "exact+stream+int8 vs oracle at P=8, odd lengths, trash row")
endef
export KERNEL_SELFCHECK

# Fast observability gate: profiling + telemetry + pipeline +
# observability + corpus-cache/streaming unit tests, then one
# smoke-shaped bench.py run through the full parent/child/--baseline
# machinery, asserting the ONE-JSON-line stdout contract the round
# driver depends on, a two-invocation warm-corpus-cache self-check
# (second analyze of the same file must hit the cache AND write a
# byte-identical word_counts.csv), and finally profile-diff +
# telemetry-report self-checks over two smoke bench lines.  Runs in a
# few minutes on the sandboxed CPU.
smoke:
	env JAX_PLATFORMS=cpu PALLAS_AXON_POOL_IPS= \
		$(PY) -m pytest tests/test_profiling.py tests/test_telemetry.py \
		tests/test_telemetry_contract.py tests/test_runtime_pipeline.py \
		tests/test_observability.py tests/test_corpus_cache.py \
		tests/test_wq_store.py tests/test_serving.py \
		tests/test_resilience.py tests/test_continuous.py \
		tests/test_kv_pages.py tests/test_paged_attention.py \
		tests/test_router.py \
		tests/test_journal.py tests/test_speculative.py \
		tests/test_reqtrace.py tests/test_metrics_plane.py \
		tests/test_engine_ledger.py tests/test_fault_coverage.py \
		tests/test_response_cache.py -q
	# paged-attention kernel self-check (body in KERNEL_SELFCHECK above):
	# both interpret-mode kernel bodies + the int8 path vs the f32 oracle.
	env JAX_PLATFORMS=cpu PALLAS_AXON_POOL_IPS= \
		$(PY) -c "$$KERNEL_SELFCHECK" || \
		{ echo "paged-attention kernel self-check failed"; exit 1; }
	env JAX_PLATFORMS=cpu PALLAS_AXON_POOL_IPS= MUSICAAL_BENCH_SMOKE=1 \
		$(PY) bench.py --baseline --attempts 1 --deadline 240 \
		| $(PY) -c "import json,sys; \
lines=[l for l in sys.stdin.read().splitlines() if l.strip()]; \
assert len(lines)==1, f'expected ONE JSON line, got {len(lines)}'; \
payload=json.loads(lines[0]); \
assert 'vs_baseline_detail' in payload, 'missing --baseline detail'; \
print('smoke ok:', payload['metric'], payload['value'])"
	# corpus-cache warm self-check: analyze the same fixture twice with
	# the cache pointed at a fresh dir — the second run must record a
	# cache hit in its run manifest and write a byte-identical
	# word_counts.csv (golden contract: the cache may never change
	# output bytes).
	cachetmp=$$(mktemp -d) && trap 'rm -rf "$$cachetmp"' EXIT && \
	for run in cold warm; do \
		env JAX_PLATFORMS=cpu PALLAS_AXON_POOL_IPS= \
			MUSICAAL_CORPUS_CACHE="$$cachetmp/cache" \
			$(PY) -m music_analyst_tpu analyze tests/fixtures/mini_songs.csv \
			--output-dir "$$cachetmp/$$run" --no-split >/dev/null || \
			{ echo "corpus-cache $$run run failed"; exit 1; }; \
	done && \
	cmp "$$cachetmp/cold/word_counts.csv" "$$cachetmp/warm/word_counts.csv" || \
		{ echo "warm-cache word_counts.csv diverged from cold"; exit 1; }; \
	grep -q '"hits": [1-9]' "$$cachetmp/warm/run_manifest.json" || \
		{ echo "warm run did not hit the corpus cache"; exit 1; }; \
	echo "corpus-cache warm self-check ok"
	# profile-diff self-check: two smoke bench lines must both satisfy
	# the one-line contract and feed the regression gate without an
	# exit-2 (unusable input).  Exit 1 (regression verdict) is tolerated
	# — smoke shapes on a 1-core sandbox are too noisy to gate on.
	tmpdir=$$(mktemp -d) && trap 'rm -rf "$$tmpdir"' EXIT && \
	for side in a b; do \
		env JAX_PLATFORMS=cpu PALLAS_AXON_POOL_IPS= MUSICAAL_BENCH_SMOKE=1 \
			$(PY) bench.py --attempts 1 --deadline 240 \
			> "$$tmpdir/$$side.json" || exit 1; \
		test "$$(grep -c . "$$tmpdir/$$side.json")" = 1 || \
			{ echo "bench $$side: not ONE JSON line"; exit 1; }; \
	done && \
	env JAX_PLATFORMS=cpu PALLAS_AXON_POOL_IPS= \
		$(PY) -m music_analyst_tpu profile-diff \
		"$$tmpdir/a.json" "$$tmpdir/b.json" --threshold 0.5; rc=$$?; \
	if [ $$rc -eq 2 ]; then echo "profile-diff: unusable input"; exit 1; \
	else echo "profile-diff self-check ok (exit $$rc)"; fi; \
	env JAX_PLATFORMS=cpu PALLAS_AXON_POOL_IPS= \
		$(PY) -m music_analyst_tpu telemetry-report \
		"$$tmpdir/a.json" "$$tmpdir/b.json" || \
		{ echo "telemetry-report self-check failed"; exit 1; }; \
	echo "telemetry-report self-check ok"
	# serving self-check: start the stdio server, send 3 requests, and
	# assert the replies come back in order with the right ids AND that
	# the run manifest grew a `serving` section (warm residency + batcher
	# stats are a manifest contract, not just a wire one).
	servetmp=$$(mktemp -d) && trap 'rm -rf "$$servetmp"' EXIT && \
	printf '%s\n' \
		'{"id":"s1","op":"sentiment","text":"I love this happy day"}' \
		'{"id":"s2","op":"wordcount","text":"hello hello world"}' \
		'{"id":"s3","op":"ping"}' | \
	env JAX_PLATFORMS=cpu PALLAS_AXON_POOL_IPS= \
		$(PY) -m music_analyst_tpu serve --stdio --mock --quiet \
		--max-batch 2 --max-wait-ms 2 --telemetry-dir "$$servetmp" \
		> "$$servetmp/replies.ndjson" || { echo "serve run failed"; exit 1; }; \
	$(PY) -c "import json,sys; \
	lines=[json.loads(l) for l in open(sys.argv[1]) if l.strip()]; \
	assert [r['id'] for r in lines]==['s1','s2','s3'], [r['id'] for r in lines]; \
	assert all(r['ok'] for r in lines), lines; \
	manifest=json.load(open(sys.argv[2])); \
	serving=manifest['serving']; \
	assert serving['requests']['completed']==2, serving['requests']; \
	assert serving['residency']['warm'] is True, serving['residency']; \
	print('serving self-check ok:', serving['requests']['batches'], 'batch(es)')" \
		"$$servetmp/replies.ndjson" "$$servetmp/run_manifest.json" || \
		{ echo "serving self-check failed"; exit 1; }
	# response-cache self-check: the same sentiment request through two
	# serve processes sharing one cache dir — the warm process must
	# answer from the disk tier (stats: hits==1, ZERO batches dispatched,
	# the hit never reaches the device) with a reply byte-identical to
	# the cold one (the cache may never change output bytes; the `cached`
	# stamp lives in stats/trace, never the payload).
	rctmp=$$(mktemp -d) && trap 'rm -rf "$$rctmp"' EXIT && \
	for run in cold warm; do \
		printf '%s\n' \
			'{"id":"c1","op":"sentiment","text":"I love this happy day"}' \
			'{"id":"c2","op":"stats"}' | \
		env JAX_PLATFORMS=cpu PALLAS_AXON_POOL_IPS= \
			$(PY) -m music_analyst_tpu serve --stdio --mock --quiet \
			--max-batch 2 --max-wait-ms 2 \
			--response-cache-dir "$$rctmp/rcache" \
			> "$$rctmp/$$run.ndjson" || \
			{ echo "response-cache $$run run failed"; exit 1; }; \
	done && \
	$(PY) -c "import json,sys; \
	cold=[json.loads(l) for l in open(sys.argv[1]) if l.strip()]; \
	warm=[json.loads(l) for l in open(sys.argv[2]) if l.strip()]; \
	sans=lambda r: {k:v for k,v in r.items() if k!='id'}; \
	assert sans(warm[0])==sans(cold[0]), 'cached reply diverged from computed'; \
	assert 'cached' not in warm[0], warm[0]; \
	rc=warm[1]['stats']['response_cache']; \
	assert rc['hits']==1 and rc['disk_hits']==1, rc; \
	reqs=warm[1]['stats']['requests']; \
	assert reqs['batches']==0 and reqs['rows']==0, reqs; \
	print('response-cache self-check ok: 1 disk hit, 0 dispatches')" \
		"$$rctmp/cold.ndjson" "$$rctmp/warm.ndjson" || \
		{ echo "response-cache self-check failed"; exit 1; }
	# generate-interleave self-check: one continuous-decode generate
	# request sandwiched between two sentiment requests on the same
	# stdio stream — replies must come back in order, the generate reply
	# must carry text/label/tokens from the slot runtime, and the
	# manifest's serving section must grow a `decode` block.
	gentmp=$$(mktemp -d) && trap 'rm -rf "$$gentmp"' EXIT && \
	printf '%s\n' \
		'{"id":"g1","op":"sentiment","text":"I love this happy day"}' \
		'{"id":"g2","op":"generate","text":"sunny morning","max_new_tokens":4}' \
		'{"id":"g3","op":"sentiment","text":"sad and gray"}' | \
	env JAX_PLATFORMS=cpu PALLAS_AXON_POOL_IPS= \
		$(PY) -m music_analyst_tpu serve --stdio --model llama-tiny --quiet \
		--slots 2 --prefill-chunk 32 --max-new-tokens 4 \
		--max-batch 2 --max-wait-ms 2 --telemetry-dir "$$gentmp" \
		> "$$gentmp/replies.ndjson" || { echo "generate serve run failed"; exit 1; }; \
	$(PY) -c "import json,sys; \
	lines=[json.loads(l) for l in open(sys.argv[1]) if l.strip()]; \
	assert [r['id'] for r in lines]==['g1','g2','g3'], [r['id'] for r in lines]; \
	assert all(r['ok'] for r in lines), lines; \
	gen=lines[1]; \
	assert gen['op']=='generate' and 'text' in gen and 'label' in gen, gen; \
	manifest=json.load(open(sys.argv[2])); \
	decode=manifest['serving']['decode']; \
	assert decode['completed']==1, decode; \
	print('generate-interleave self-check ok:', decode['tokens_generated'], 'token(s)')" \
		"$$gentmp/replies.ndjson" "$$gentmp/run_manifest.json" || \
		{ echo "generate-interleave self-check failed"; exit 1; }
	# prefix-cache self-check: the same generate prompt three times on one
	# stdio stream — with 2 slots the third request must wait for a slot,
	# so it admits after a completed prefill seeded the radix tree: the
	# manifest's decode block must report prefix_cache hits >= 1 while the
	# replies stay identical (sharing may never change output bytes).
	pctmp=$$(mktemp -d) && trap 'rm -rf "$$pctmp"' EXIT && \
	printf '%s\n' \
		'{"id":"p1","op":"generate","text":"sunny morning","max_new_tokens":4}' \
		'{"id":"p2","op":"generate","text":"sunny morning","max_new_tokens":4}' \
		'{"id":"p3","op":"generate","text":"sunny morning","max_new_tokens":4}' | \
	env JAX_PLATFORMS=cpu PALLAS_AXON_POOL_IPS= \
		$(PY) -m music_analyst_tpu serve --stdio --model llama-tiny --quiet \
		--slots 2 --prefill-chunk 32 --max-new-tokens 4 --page-size 16 \
		--max-batch 2 --max-wait-ms 2 --telemetry-dir "$$pctmp" \
		> "$$pctmp/replies.ndjson" || { echo "prefix-cache serve run failed"; exit 1; }; \
	$(PY) -c "import json,sys; \
	lines=[json.loads(l) for l in open(sys.argv[1]) if l.strip()]; \
	assert [r['id'] for r in lines]==['p1','p2','p3'], [r['id'] for r in lines]; \
	assert all(r['ok'] for r in lines), lines; \
	texts={r['text'] for r in lines}; \
	assert len(texts)==1, f'identical prompts diverged: {texts}'; \
	decode=json.load(open(sys.argv[2]))['serving']['decode']; \
	assert decode['kv_backend']=='paged', decode['kv_backend']; \
	pc=decode['prefix_cache']; \
	assert pc['hits']>=1, pc; \
	print('prefix-cache self-check ok:', pc['hits'], 'hit(s),', \
	      pc['tokens_shared'], 'token(s) shared')" \
		"$$pctmp/replies.ndjson" "$$pctmp/run_manifest.json" || \
		{ echo "prefix-cache self-check failed"; exit 1; }
	# speculation self-check: one long repetitive generate prompt through
	# the stdio server with and without draft-and-verify (--speculate-k)
	# — the replies must be byte-identical (speculation may never change
	# output bytes), and the speculative run's manifest must show verify
	# dispatches that netted more than one committed token each once the
	# stream entered its cycle (the whole point of drafting).
	spectmp=$$(mktemp -d) && trap 'rm -rf "$$spectmp"' EXIT && \
	for arm in plain spec; do \
		if [ $$arm = spec ]; then sk=4; else sk=0; fi; \
		printf '%s\n' \
			'{"id":"k1","op":"generate","text":"la la la la la la","max_new_tokens":96}' | \
		env JAX_PLATFORMS=cpu PALLAS_AXON_POOL_IPS= \
			$(PY) -m music_analyst_tpu serve --stdio --model llama-tiny --quiet \
			--slots 2 --prefill-chunk 32 --max-new-tokens 96 --speculate-k $$sk \
			--max-batch 2 --max-wait-ms 2 --telemetry-dir "$$spectmp/$$arm" \
			> "$$spectmp/$$arm.ndjson" || \
			{ echo "speculation $$arm run failed"; exit 1; }; \
	done && \
	$(PY) -c "import json,sys; \
	plain=[json.loads(l) for l in open(sys.argv[1]) if l.strip()]; \
	spec=[json.loads(l) for l in open(sys.argv[2]) if l.strip()]; \
	assert [r['text'] for r in plain]==[r['text'] for r in spec], \
	    'speculation changed output bytes'; \
	sp=json.load(open(sys.argv[3]))['serving']['decode']['speculation']; \
	assert sp['enabled'] and sp['k']==4, sp; \
	assert sp['dispatches']>=1 and sp['fallbacks']==0, sp; \
	assert sp['accepted_tokens_per_dispatch']>1.0, sp; \
	print('speculation self-check ok:', sp['dispatches'], 'dispatch(es),', \
	      sp['accepted_tokens_per_dispatch'], 'tok/dispatch,', \
	      sp['acceptance_rate'], 'acceptance')" \
		"$$spectmp/plain.ndjson" "$$spectmp/spec.ndjson" \
		"$$spectmp/spec/run_manifest.json" || \
		{ echo "speculation self-check failed"; exit 1; }
	# router self-check (body in ROUTER_SELFCHECK above): 2 replicas,
	# 8 requests, SIGKILL one mid-load — zero admitted requests lost,
	# health transition in the manifest's serving.router section.
	routertmp=$$(mktemp -d) && trap 'rm -rf "$$routertmp"' EXIT && \
	env JAX_PLATFORMS=cpu PALLAS_AXON_POOL_IPS= \
		$(PY) -c "$$ROUTER_SELFCHECK" "$$routertmp" || \
		{ echo "router self-check failed"; exit 1; }
	# crash-recovery self-check (body in CRASH_SELFCHECK above): SIGKILL
	# the journaled generate server mid-decode, restart on the same
	# journal dir — every request answered, nothing computed twice,
	# unclean shutdown stamped.
	crashtmp=$$(mktemp -d) && trap 'rm -rf "$$crashtmp"' EXIT && \
	env JAX_PLATFORMS=cpu PALLAS_AXON_POOL_IPS= \
		$(PY) -c "$$CRASH_SELFCHECK" "$$crashtmp" || \
		{ echo "crash-recovery self-check failed"; exit 1; }
	# chaos self-check: analyze with a transient fault injected at the
	# ingest seam — the run must recover (retry counter in the manifest)
	# and write a word_counts.csv byte-identical to the clean run (the
	# golden contracts hold under injected failure).
	chaostmp=$$(mktemp -d) && trap 'rm -rf "$$chaostmp"' EXIT && \
	env JAX_PLATFORMS=cpu PALLAS_AXON_POOL_IPS= \
		$(PY) -m music_analyst_tpu analyze tests/fixtures/mini_songs.csv \
		--output-dir "$$chaostmp/clean" --no-split >/dev/null || \
		{ echo "chaos clean run failed"; exit 1; }; \
	env JAX_PLATFORMS=cpu PALLAS_AXON_POOL_IPS= \
		MUSICAAL_FAULTS="ingest.read:error@1" \
		$(PY) -m music_analyst_tpu analyze tests/fixtures/mini_songs.csv \
		--output-dir "$$chaostmp/faulted" --no-split >/dev/null || \
		{ echo "chaos injected run failed (retry did not recover)"; exit 1; }; \
	cmp "$$chaostmp/clean/word_counts.csv" "$$chaostmp/faulted/word_counts.csv" || \
		{ echo "injected-fault word_counts.csv diverged from clean"; exit 1; }; \
	grep -q '"retry.ingest.read"' "$$chaostmp/faulted/run_manifest.json" || \
		{ echo "injected run manifest lacks the retry counter"; exit 1; }; \
	echo "chaos injected-fault self-check ok"
	# trace self-check: one traced generate request under --trace-sample
	# 1.0 — request_traces.jsonl must hold its waterfall with >=6 phases
	# whose span sum covers >=95% of the request's measured wire latency,
	# and trace-report must reconstruct a complete waterfall (exit 0).
	tracetmp=$$(mktemp -d) && trap 'rm -rf "$$tracetmp"' EXIT && \
	printf '%s\n' \
		'{"id":"t1","op":"generate","text":"sunny morning","max_new_tokens":4}' | \
	env JAX_PLATFORMS=cpu PALLAS_AXON_POOL_IPS= \
		$(PY) -m music_analyst_tpu serve --stdio --model llama-tiny --quiet \
		--slots 2 --prefill-chunk 32 --max-new-tokens 4 \
		--max-batch 2 --max-wait-ms 2 --trace-sample 1.0 \
		--profile-dir "$$tracetmp" --telemetry-dir "$$tracetmp" \
		> "$$tracetmp/replies.ndjson" || { echo "traced serve run failed"; exit 1; }; \
	$(PY) -c "import json,sys; \
	lines=[json.loads(l) for l in open(sys.argv[1]) if l.strip()]; \
	assert lines and lines[0]['ok'] and 'trace_id' in lines[0], lines; \
	recs=[json.loads(l) for l in open(sys.argv[2]) if l.strip()]; \
	rec=[r for r in recs if r['trace_id']==lines[0]['trace_id']][0]; \
	phases=[s for s in rec['spans'] if s['cat']=='phase']; \
	assert len(phases)>=6, [s['name'] for s in phases]; \
	cover=sum(s['dur'] for s in phases); \
	assert cover >= 0.95*rec['wire_s'], (cover, rec['wire_s']); \
	print('trace self-check ok:', len(phases), 'phases,', \
	      round(100.0*cover/rec['wire_s'],1), 'pct coverage')" \
		"$$tracetmp/replies.ndjson" "$$tracetmp/request_traces.jsonl" || \
		{ echo "trace self-check failed"; exit 1; }; \
	env JAX_PLATFORMS=cpu PALLAS_AXON_POOL_IPS= \
		$(PY) -m music_analyst_tpu trace-report "$$tracetmp" >/dev/null || \
		{ echo "trace-report self-check failed"; exit 1; }; \
	echo "trace-report self-check ok"
	# overload self-check: burst one stdio stream past a 1 req/s bulk
	# tenant budget while a single high-priority gold request rides along
	# — gold must be answered ok inside its (generous) TTFT SLO, every
	# bulk shed must be structured (queue_full/slo_unattainable with a
	# numeric retry_after_ms), and the stats op's slo section must show
	# the sheds charged to the bulk tenant only (per-tenant isolation).
	overtmp=$$(mktemp -d) && trap 'rm -rf "$$overtmp"' EXIT && \
	{ for i in 0 1 2 3 4 5 6 7 8 9; do \
		printf '{"id":"b%s","op":"sentiment","text":"bulk row %s","tenant":"bulk","priority":1}\n' "$$i" "$$i"; \
	done; \
	printf '%s\n' \
		'{"id":"gold","op":"sentiment","text":"I love this happy day","tenant":"gold","priority":5}' \
		'{"id":"end","op":"stats"}'; } | \
	env JAX_PLATFORMS=cpu PALLAS_AXON_POOL_IPS= \
		$(PY) -m music_analyst_tpu serve --stdio --mock --quiet \
		--max-batch 4 --max-wait-ms 2 --max-queue 8 \
		--tenant-budget 1 --ttft-slo-ms 5000 \
		> "$$overtmp/replies.ndjson" || { echo "overload serve run failed"; exit 1; }; \
	$(PY) -c "import json,sys; \
	lines=[json.loads(l) for l in open(sys.argv[1]) if l.strip()]; \
	assert len(lines)==12, f'expected 12 replies, got {len(lines)}'; \
	by_id={r['id']: r for r in lines}; \
	assert by_id['gold']['ok'], by_id['gold']; \
	sheds=[r for r in lines if not r.get('ok') and r['id']!='end']; \
	assert sheds, 'burst past the tenant budget shed nothing'; \
	assert all(r['error']['kind'] in ('queue_full','slo_unattainable') \
	           and r['error'].get('retry_after_ms', 0) >= 1.0 \
	           for r in sheds), sheds; \
	slo=by_id['end']['stats']['slo']; \
	assert slo['tenants']['bulk']['shed'] >= 1, slo; \
	assert slo['tenants']['gold']['shed'] == 0, slo; \
	print('overload self-check ok:', by_id['gold']['label'], 'gold,', \
	      len(sheds), 'structured shed(s)')" \
		"$$overtmp/replies.ndjson" || \
		{ echo "overload self-check failed"; exit 1; }
	# burn-rate self-check (body in BURN_SELFCHECK above): the overload
	# burst replayed through a journaled, metered stdio server — the bulk
	# flood past its 1 req/s budget must fire exactly one burn-rate alert
	# whose trace_id resolves to a kept shed exemplar; a within-budget
	# steady run on the same flags must sample but fire zero.  The 200ms
	# interval makes the sample set deterministic (baseline + close-time
	# final, after every reply and kept trace has flushed).
	burntmp=$$(mktemp -d) && trap 'rm -rf "$$burntmp"' EXIT && \
	{ for i in 0 1 2 3 4 5 6 7 8 9; do \
		printf '{"id":"b%s","op":"sentiment","text":"bulk row %s","tenant":"bulk","priority":1}\n' "$$i" "$$i"; \
	done; \
	printf '%s\n' \
		'{"id":"gold","op":"sentiment","text":"I love this happy day","tenant":"gold","priority":5}'; } | \
	env JAX_PLATFORMS=cpu PALLAS_AXON_POOL_IPS= \
		$(PY) -m music_analyst_tpu serve --stdio --mock --quiet \
		--max-batch 4 --max-wait-ms 2 --max-queue 8 \
		--tenant-budget 1 --ttft-slo-ms 5000 \
		--journal-dir "$$burntmp/journal" --trace-sample 0 \
		--metrics-interval-ms 200 --profile-dir "$$burntmp/burst" \
		> "$$burntmp/burst.ndjson" || { echo "burn-rate burst run failed"; exit 1; }; \
	printf '%s\n' \
		'{"id":"c1","op":"sentiment","text":"calm seas","tenant":"bulk","priority":1}' \
		'{"id":"c2","op":"sentiment","text":"steady light","tenant":"gold","priority":5}' | \
	env JAX_PLATFORMS=cpu PALLAS_AXON_POOL_IPS= \
		$(PY) -m music_analyst_tpu serve --stdio --mock --quiet \
		--max-batch 4 --max-wait-ms 2 --max-queue 8 \
		--tenant-budget 1 --ttft-slo-ms 5000 \
		--journal-dir "$$burntmp/journal2" --trace-sample 0 \
		--metrics-interval-ms 200 --profile-dir "$$burntmp/steady" \
		> "$$burntmp/steady.ndjson" || { echo "burn-rate steady run failed"; exit 1; }; \
	$(PY) -c "$$BURN_SELFCHECK" "$$burntmp/burst" "$$burntmp/steady" || \
		{ echo "burn-rate self-check failed"; exit 1; }
	# live-monitor self-check: serve on a unix socket in the background,
	# wait for the socket to appear, and assert the jax-free
	# `monitor --once` renders a healthy snapshot (exit 0) against the
	# live front end.
	montmp=$$(mktemp -d) && trap 'rm -rf "$$montmp"' EXIT && \
	env JAX_PLATFORMS=cpu PALLAS_AXON_POOL_IPS= \
		$(PY) -m music_analyst_tpu serve --socket "$$montmp/sock" \
		--mock --quiet --max-batch 4 --max-wait-ms 2 \
		--metrics-interval-ms 200 --profile-dir "$$montmp" & \
	srvpid=$$!; \
	tries=0; \
	while [ ! -S "$$montmp/sock" ] && [ $$tries -lt 100 ]; do \
		sleep 0.1; tries=$$((tries + 1)); \
	done; \
	[ -S "$$montmp/sock" ] || { kill $$srvpid 2>/dev/null; \
		echo "monitor self-check: socket never appeared"; exit 1; }; \
	env JAX_PLATFORMS=cpu PALLAS_AXON_POOL_IPS= \
		$(PY) -m music_analyst_tpu monitor --once --socket "$$montmp/sock" || \
		{ kill $$srvpid 2>/dev/null; echo "monitor self-check failed"; exit 1; }; \
	kill $$srvpid 2>/dev/null; wait $$srvpid 2>/dev/null; \
	echo "monitor self-check ok"
	# engine-ledger self-check (body in LEDGER_SELFCHECK above): a stdio
	# generate burst over two tenants on the continuous scheduler, ledger
	# flushing on a 100ms cadence to the profile dir — the goodput
	# accounting must tile (coverage >= 0.95, chip-seconds within 2% of
	# the engine wall) and the JSONL must land intact.
	ledgertmp=$$(mktemp -d) && trap 'rm -rf "$$ledgertmp"' EXIT && \
	{ for i in 0 1 2 3 4 5; do \
		case $$(( i % 2 )) in 0) t=gold;; *) t=bulk;; esac; \
		printf '{"id":"g%s","op":"generate","text":"verse %s of the burst","tenant":"%s","max_new_tokens":4}\n' "$$i" "$$i" "$$t"; \
	done; \
	printf '%s\n' '{"id":"end","op":"stats"}'; } | \
	env JAX_PLATFORMS=cpu PALLAS_AXON_POOL_IPS= \
		MUSICAAL_LEDGER_INTERVAL_MS=100 \
		$(PY) -m music_analyst_tpu serve --stdio --model llama-tiny --quiet \
		--slots 2 --prefill-chunk 32 --max-new-tokens 4 \
		--max-batch 2 --max-wait-ms 2 --profile-dir "$$ledgertmp" \
		> "$$ledgertmp/replies.ndjson" || \
		{ echo "engine-ledger serve run failed"; exit 1; }; \
	$(PY) -c "$$LEDGER_SELFCHECK" "$$ledgertmp/replies.ndjson" "$$ledgertmp" || \
		{ echo "engine-ledger self-check failed"; exit 1; }

test:
	$(PY) -m pytest tests/ -q

native:
	$(MAKE) -C native
