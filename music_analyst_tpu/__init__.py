"""music_analyst_tpu — a TPU-native music-lyrics analytics framework.

A ground-up JAX/XLA/Pallas + C++ re-design of the capabilities of
``VictorGSchneider/Music-Analyst-AI`` (reference mounted read-only at
``/root/reference``):

* parallel word-count / artist-count over the Spotify Million Song dataset
  (reference: ``src/parallel_spotify.c``, MPI byte-range sharding + string
  hash-table Send/Recv shuffle) — here: a C++ multithreaded host ingest that
  produces a tokenized, HBM-resident id matrix, sharded over a
  ``jax.sharding.Mesh`` with a single ``psum`` dense-histogram reduction;
* LLM sentiment classification (reference:
  ``scripts/sentiment_classifier.py``, one Ollama HTTP round-trip per song)
  — here: batched on-device classifiers (vectorized ``--mock`` keyword
  kernel, DistilBERT-sst2-style encoder, Llama-3-style decoder with
  tensor-parallel sharded weights and KV cache);
* per-song word counts, CSV column splitting, and performance-metrics
  export with per-chip timings.

Layer map (SURVEY.md §7):

* ``data/``     — host ingest: CSV record reader, reference-exact tokenizers,
                  vocabulary, native C++ bindings.
* ``ops/``      — device compute: dense histogram, keyword-sentiment kernel,
                  attention (incl. ring attention).
* ``parallel/`` — mesh construction, sharding rules, collectives, multihost.
* ``models/``   — Flax model families (encoder classifier, decoder LM).
* ``engines/``  — end-to-end pipelines (wordcount, sentiment, per-song).
* ``metrics/``  — timers + performance_metrics.json writer.
* ``cli/``      — flag-compatible command-line surface.
"""

__version__ = "0.1.0"
