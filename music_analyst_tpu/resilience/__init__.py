"""Resilience layer: deterministic fault injection, retry policy, failover.

Three pieces, all jax-free at import so the CLI's host-side paths
(telemetry-report, profile-diff) and the jax-free packages that embed
fault points (data/, runtime/, observability/) stay importable on a dead
backend:

- :mod:`.faults` — a seeded fault-injection registry with named sites
  threaded through the real seams (``MUSICAAL_FAULTS`` /
  ``--inject-faults``).
- :mod:`.policy` — the one :class:`RetryPolicy` (exponential backoff,
  full jitter, cap, deadline-aware budget) shared by Ollama HTTP,
  prefetch stages, cache I/O, and serving dispatch.
- :mod:`.failover` — structured re-init-and-retry of a dead backend,
  then degrade-to-CPU with a ``degraded: true`` manifest stamp.
"""

from music_analyst_tpu.resilience.faults import (
    FaultRule,
    InjectedFault,
    InjectedFatal,
    configure_faults,
    fault_point,
    fault_stats,
    parse_fault_spec,
    resolve_fault_spec,
)
from music_analyst_tpu.resilience.policy import (
    RetryPolicy,
    arm_retry_deadline,
    classify_retryable,
    reset_retry_stats,
    resolve_http_retries,
    retry_deadline_remaining,
    retry_stats,
)
from music_analyst_tpu.resilience.failover import (
    run_with_failover,
    should_failover,
)

__all__ = [
    "FaultRule",
    "InjectedFault",
    "InjectedFatal",
    "configure_faults",
    "fault_point",
    "fault_stats",
    "parse_fault_spec",
    "resolve_fault_spec",
    "RetryPolicy",
    "arm_retry_deadline",
    "classify_retryable",
    "reset_retry_stats",
    "resolve_http_retries",
    "retry_deadline_remaining",
    "retry_stats",
    "run_with_failover",
    "should_failover",
]
