"""Deterministic, seeded fault injection at named seams.

Chaos testing only earns its keep if an injected run is REPRODUCIBLE: the
same spec (including seed) must trip the same calls in the same order, so
a failing chaos case replays exactly.  Every probabilistic decision
therefore draws from a per-rule ``random.Random(seed)`` — never the
global RNG — and every trigger counts calls per rule, not per process.

Spec grammar (``--inject-faults`` / ``MUSICAAL_FAULTS``)::

    spec    := rule (';' rule)*
    rule    := site ':' mode trigger? ('seed=' int)?
    mode    := 'error' | 'fatal' | 'crash' | 'delay=' seconds 's'?
    trigger := '@' N        -- trip exactly on the Nth call (1-based)
             | '@' N '+'    -- trip on every call from the Nth on
             | '@' P '%'    -- trip each call with probability P percent
             | (absent)     -- trip on every call

Examples::

    ollama.request:error@2                 # 2nd HTTP attempt fails once
    h2d.transfer:delay=5s@0.1%seed=7       # seeded 0.1% per-transfer stall
    ingest.read:fatal                      # non-retryable, every call
    serve.reply:crash@3                    # SIGKILL self before 3rd reply

``error`` raises :class:`InjectedFault` (classified retryable — the
retry/failover machinery must recover); ``fatal`` raises
:class:`InjectedFatal` (non-retryable — the run must die with a
structured taxonomy error and no torn artifacts); ``delay`` sleeps;
``crash`` SIGKILLs the process on the spot — no atexit, no flight
record, no flushed buffers — the process-crash chaos primitive the
``crash`` bench suite and the request journal's replay guarantees are
drilled against (``serving/journal.py``).

The module-level fast path matters: :func:`fault_point` sits on hot
seams (per prefetch item, per serving dispatch), so with no spec
configured it is one global load and a ``None`` check.
"""

from __future__ import annotations

import random
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from music_analyst_tpu.telemetry import get_telemetry

# The named seams.  Adding a site means adding a fault_point() call at the
# real code path — keep this list in sync with PERFORMANCE.md's table.
SITES = frozenset(
    {
        "ingest.read",
        "corpus_cache.publish",
        "prefetch.stage",
        "compile.first",
        "h2d.transfer",
        "collective.psum",
        "ollama.request",
        "serving.dispatch",
        "decode.step",
        "spec.draft",
        "checkpoint.load",
        "kv_pages.lookup",
        "router.dispatch",
        "scheduler.preempt",
        "loadgen.tick",
        # Crash-consistency seams (serving/journal.py, serving/server.py):
        # post-admit, pre-reply, and the journal's own append/compaction
        # paths — the four named SIGKILL points of the crash drill.
        "serve.admit",
        "serve.reply",
        "journal.append",
        "journal.compact",
        # Request-trace flush (telemetry/reqtrace.py): a failing flush must
        # degrade to dropped spans, never block the reply path.
        "reqtrace.flush",
        # Metrics scrape (observability/metrics_plane.py): a failing
        # scrape marks the series stale and counts scrape_errors —
        # serving bytes and replies are never affected.
        "metrics.scrape",
        # int8 KV-page dequantization (serving/decode_loop.py): a fault
        # here degrades the scheduler to the unquantized paged pool at
        # construction time — replies stay byte-identical, the stats
        # block flags ``kv_quant.degraded``.
        "kv_quant.dequant",
        # Engine-ledger flush (observability/engine_ledger.py): a failing
        # JSONL append degrades to a counted ``ledger_drops`` — replies
        # stay byte-identical and the file is never torn.
        "ledger.flush",
        # Response-cache tiers (serving/response_cache.py): a faulted
        # read counts a ``read_fallbacks`` and recomputes (byte-identical
        # reply); a faulted write counts ``write_errors`` and the settle
        # proceeds uncached.  Neither can fail or change a reply.
        "response_cache.read",
        "response_cache.write",
    }
)

_MAX_DELAY_S = 60.0  # cap injected sleeps: a typo must not outlive the bench


class InjectedFault(RuntimeError):
    """A transient injected failure; retry/failover must recover it."""

    def __init__(self, site: str, call: int, detail: str = "") -> None:
        self.site = site
        self.call = call
        extra = f" {detail}" if detail else ""
        super().__init__(
            f"fault injected at {site} (call {call}{extra})"
        )


class InjectedFatal(InjectedFault):
    """A non-transient injected failure; the run must die structurally."""

    def __init__(self, site: str, call: int) -> None:
        super().__init__(site, call, detail="fatal")


@dataclass
class FaultRule:
    """One parsed rule; owns its RNG so trip schedules are per-rule."""

    site: str
    mode: str  # error | fatal | delay
    delay_s: float = 0.0
    nth: Optional[int] = None  # @N / @N+
    from_nth: bool = False  # True for @N+
    probability: Optional[float] = None  # @P% as fraction in [0, 1]
    seed: int = 0
    rng: random.Random = field(init=False, repr=False)

    def __post_init__(self) -> None:
        self.rng = random.Random(self.seed)

    def should_trip(self, call: int) -> bool:
        """Decide for the ``call``-th (1-based) arrival at this site.

        Called for EVERY arrival, in order, so probabilistic draws stay
        aligned with the call counter regardless of earlier outcomes.
        """
        if self.probability is not None:
            return self.rng.random() < self.probability
        if self.nth is not None:
            return call >= self.nth if self.from_nth else call == self.nth
        return True

    def describe(self) -> Dict[str, object]:
        out: Dict[str, object] = {"site": self.site, "mode": self.mode}
        if self.mode == "delay":
            out["delay_s"] = self.delay_s
        if self.nth is not None:
            out["nth"] = self.nth
            if self.from_nth:
                out["from_nth"] = True
        if self.probability is not None:
            out["probability"] = self.probability
            out["seed"] = self.seed
        return out


def _parse_rule(text: str) -> FaultRule:
    head, sep, tail = text.partition(":")
    site = head.strip()
    if not sep or not tail.strip():
        raise ValueError(
            f"fault rule {text!r}: expected 'site:mode[@trigger][seed=K]'"
        )
    if site not in SITES:
        known = ", ".join(sorted(SITES))
        raise ValueError(f"fault rule {text!r}: unknown site {site!r} "
                         f"(known sites: {known})")

    body = tail.strip()
    seed = 0
    if "seed=" in body:
        body, _, seed_text = body.partition("seed=")
        try:
            seed = int(seed_text)
        except ValueError:
            raise ValueError(
                f"fault rule {text!r}: seed must be an integer, "
                f"got {seed_text!r}"
            ) from None

    mode_text, at, trigger = body.partition("@")
    mode_text = mode_text.strip()
    delay_s = 0.0
    if mode_text in ("error", "fatal", "crash"):
        mode = mode_text
    elif mode_text.startswith("delay="):
        mode = "delay"
        value = mode_text[len("delay="):].rstrip("s")
        try:
            delay_s = float(value)
        except ValueError:
            raise ValueError(
                f"fault rule {text!r}: delay must look like 'delay=5s', "
                f"got {mode_text!r}"
            ) from None
        if not 0.0 <= delay_s <= _MAX_DELAY_S:
            raise ValueError(
                f"fault rule {text!r}: delay must be in "
                f"[0, {_MAX_DELAY_S:g}] seconds, got {delay_s:g}"
            )
    else:
        raise ValueError(
            f"fault rule {text!r}: mode must be 'error', 'fatal', 'crash' "
            f"or 'delay=<seconds>s', got {mode_text!r}"
        )

    nth: Optional[int] = None
    from_nth = False
    probability: Optional[float] = None
    if at:
        trigger = trigger.strip()
        if trigger.endswith("%"):
            try:
                pct = float(trigger[:-1])
            except ValueError:
                raise ValueError(
                    f"fault rule {text!r}: bad probability {trigger!r}"
                ) from None
            if not 0.0 <= pct <= 100.0:
                raise ValueError(
                    f"fault rule {text!r}: probability must be in "
                    f"[0, 100]%, got {pct:g}%"
                )
            probability = pct / 100.0
        else:
            plus = trigger.endswith("+")
            if plus:
                trigger = trigger[:-1]
            try:
                nth = int(trigger)
            except ValueError:
                raise ValueError(
                    f"fault rule {text!r}: trigger must be '@N', '@N+' or "
                    f"'@P%', got '@{trigger}'"
                ) from None
            if nth < 1:
                raise ValueError(
                    f"fault rule {text!r}: call numbers are 1-based, "
                    f"got @{nth}"
                )
            from_nth = plus

    return FaultRule(
        site=site,
        mode=mode,
        delay_s=delay_s,
        nth=nth,
        from_nth=from_nth,
        probability=probability,
        seed=seed,
    )


def parse_fault_spec(spec: str) -> List[FaultRule]:
    """Parse a full ``MUSICAAL_FAULTS`` spec; raises ValueError loudly.

    Fault injection is an explicit testing tool: a malformed spec silently
    ignored would make a chaos run think it tested something it didn't,
    so — unlike the watchdog/prefetch env knobs — a bad ENV value raises
    too.
    """
    rules = []
    for part in spec.split(";"):
        part = part.strip()
        if part:
            rules.append(_parse_rule(part))
    if not rules:
        raise ValueError(f"fault spec {spec!r} contains no rules")
    return rules


def resolve_fault_spec(value: Optional[str] = None) -> Optional[str]:
    """Explicit flag value wins; otherwise ``MUSICAAL_FAULTS``; else None."""
    import os

    if value is not None and value.strip():
        return value
    env = os.environ.get("MUSICAAL_FAULTS", "").strip()
    return env or None


class FaultInjector:
    """Process-global registry: per-site rules, call and trip counters."""

    def __init__(self, rules: List[FaultRule]) -> None:
        self._rules: Dict[str, List[FaultRule]] = {}
        for rule in rules:
            self._rules.setdefault(rule.site, []).append(rule)
        self._lock = threading.Lock()
        self._calls: Dict[str, int] = {}
        self._trips: Dict[str, int] = {}

    def check(self, site: str, **attrs: object) -> None:
        rules = self._rules.get(site)
        if not rules:
            return
        with self._lock:
            call = self._calls.get(site, 0) + 1
            self._calls[site] = call
            tripped = [r for r in rules if r.should_trip(call)]
            if tripped:
                self._trips[site] = self._trips.get(site, 0) + 1
        if not tripped:
            return
        rule = tripped[0]
        tel = get_telemetry()
        tel.event(
            "fault_injected",
            site=site,
            mode=rule.mode,
            call=call,
            **attrs,
        )
        tel.count(f"faults.{site}.trips")
        if rule.mode == "delay":
            time.sleep(rule.delay_s)
            return
        if rule.mode == "crash":
            # The real thing, not an exception anyone can catch: SIGKILL
            # self, exactly as the OOM killer or a pulled cord would.  No
            # flight record, no drain, no journal compaction — whatever
            # recovery story the process claims must start from disk.
            import os
            import signal

            os.kill(os.getpid(), signal.SIGKILL)
            time.sleep(60.0)  # pragma: no cover — the signal lands first
            return
        if rule.mode == "fatal":
            raise InjectedFatal(site, call)
        raise InjectedFault(site, call)

    def stats(self) -> Dict[str, Dict[str, object]]:
        with self._lock:
            out: Dict[str, Dict[str, object]] = {}
            for site, rules in sorted(self._rules.items()):
                out[site] = {
                    "rules": [r.describe() for r in rules],
                    "calls": self._calls.get(site, 0),
                    "trips": self._trips.get(site, 0),
                }
            return out


_INJECTOR: Optional[FaultInjector] = None


def configure_faults(spec: Optional[str]) -> Optional[FaultInjector]:
    """Install (or, with None/empty, remove) the process fault injector."""
    global _INJECTOR
    if spec is None or not spec.strip():
        _INJECTOR = None
        return None
    _INJECTOR = FaultInjector(parse_fault_spec(spec))
    return _INJECTOR


def fault_point(site: str, **attrs: object) -> None:
    """Seam hook: no-op unless a configured rule targets ``site``."""
    injector = _INJECTOR
    if injector is not None:
        injector.check(site, **attrs)


def fault_stats() -> Dict[str, Dict[str, object]]:
    """Per-site calls/trips for the run manifest; {} when not configured."""
    injector = _INJECTOR
    return injector.stats() if injector is not None else {}
