"""The one retry policy: exponential backoff, full jitter, cap, budget.

Before this module, every recovery loop in the tree was ad-hoc — the
Ollama client slept ``0.5 * 2**attempt`` with no jitter, no cap, and no
awareness that the bench deadline could not fit another attempt.  All
retrying now goes through :class:`RetryPolicy`:

- **Full jitter** (AWS-style): sleep ``uniform(0, min(cap, base·2^k))``.
  Correlated retries are how transient congestion becomes persistent
  congestion; jitter decorrelates them.
- **Deadline-aware**: never sleeps past the armed process deadline
  (bench.py arms it at suite dispatch via ``benchmarks._util``), and
  gives up immediately when the remaining budget cannot fit the next
  sleep — sleeping into a deadline converts a retryable error into a
  SIGTERM with no structured line.
- **Watchdog-aware**: a retry sleep inside a watched scope counts as
  silence, so sleeps are clamped below the active watchdog timeout.
- **Classified**: retryability reuses ``observability/report.py``'s
  error taxonomy; only transiently-classified failures (tunnel drops,
  device loss, timeouts, injected transient faults, OS-level I/O
  hiccups) are retried.  Logic errors propagate on the first throw.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, Optional, Tuple

from music_analyst_tpu.observability.report import classify_error
from music_analyst_tpu.resilience.faults import InjectedFatal, InjectedFault
from music_analyst_tpu.telemetry import get_telemetry

# Taxonomy kinds worth another attempt: the failure is in the transport /
# device layer, not the program.
_TRANSIENT_KINDS = frozenset(
    {"tunnel_dead", "device_stall", "attempt_timeout", "fault_injected"}
)

# OSError subtypes that are verdicts about the input, not the transport.
_PERMANENT_OS_ERRORS = (
    FileNotFoundError,
    IsADirectoryError,
    NotADirectoryError,
    PermissionError,
)


def classify_retryable(exc: BaseException) -> Tuple[bool, Optional[str]]:
    """(retryable?, taxonomy kind) for an exception.

    Injected faults carry their verdict in their type; everything else is
    classified from its rendered message exactly the way telemetry-report
    would classify the run's death.
    """
    if isinstance(exc, InjectedFatal):
        return False, "fault_injected"
    if isinstance(exc, InjectedFault):
        return True, "fault_injected"
    kind = classify_error(f"{type(exc).__name__}: {exc}")
    if kind in _TRANSIENT_KINDS:
        return True, kind
    if isinstance(exc, (TimeoutError, ConnectionError)):
        return True, kind or "attempt_timeout"
    if isinstance(exc, OSError) and not isinstance(exc, _PERMANENT_OS_ERRORS):
        return True, kind
    return False, kind


# --- process retry deadline -------------------------------------------------
#
# Armed once per process (bench.py at suite dispatch, via
# benchmarks._util.arm_deadline).  Unarmed, retries only answer to the
# watchdog clamp.

_DEADLINE_AT: Optional[float] = None


def arm_retry_deadline(
    budget_s: Optional[float], *, clock: Callable[[], float] = time.monotonic
) -> None:
    """Arm (or, with None, disarm) the process-wide retry budget."""
    global _DEADLINE_AT
    _DEADLINE_AT = None if budget_s is None else clock() + float(budget_s)


def retry_deadline_remaining(
    *, clock: Callable[[], float] = time.monotonic
) -> Optional[float]:
    """Seconds left before the armed deadline; None when unarmed."""
    if _DEADLINE_AT is None:
        return None
    return _DEADLINE_AT - clock()


def _watchdog_cap() -> Optional[float]:
    """Longest sleep safe inside a watched scope (half the timeout)."""
    try:
        from music_analyst_tpu.observability.watchdog import get_watchdog

        wd = get_watchdog()
    except Exception:
        return None
    if wd is None:
        return None
    return wd.timeout_s / 2.0


# --- cross-run accounting ---------------------------------------------------

_STATS_LOCK = threading.Lock()
_STATS: Dict[str, Dict[str, int]] = {}


def _bump(site: str, key: str, n: int = 1) -> None:
    with _STATS_LOCK:
        entry = _STATS.setdefault(
            site, {"attempts": 0, "retries": 0, "recoveries": 0, "gave_up": 0}
        )
        entry[key] += n


def retry_stats() -> Dict[str, Dict[str, int]]:
    """Per-site attempt/retry/recovery counts for the run manifest."""
    with _STATS_LOCK:
        return {site: dict(counts) for site, counts in _STATS.items()}


def reset_retry_stats() -> None:
    with _STATS_LOCK:
        _STATS.clear()


class RetryPolicy:
    """Exponential backoff + full jitter + cap, budget- and fault-aware.

    ``retries`` is the number of RE-attempts after the first try.  The
    defaults (2 retries, 50 ms base, 2 s cap) suit host-side seams; the
    Ollama client overrides base/cap for network-scale latencies.
    """

    def __init__(
        self,
        retries: int = 2,
        base_s: float = 0.05,
        cap_s: float = 2.0,
        rng: Optional[Any] = None,
        sleep: Callable[[float], None] = time.sleep,
        deadline_fn: Callable[[], Optional[float]] = retry_deadline_remaining,
        classify: Callable[
            [BaseException], Tuple[bool, Optional[str]]
        ] = classify_retryable,
    ) -> None:
        if retries < 0:
            raise ValueError(f"retries must be >= 0, got {retries}")
        self.retries = int(retries)
        self.base_s = float(base_s)
        self.cap_s = float(cap_s)
        if rng is None:
            import random

            rng = random.Random()
        self._rng = rng
        self._sleep = sleep
        self._deadline_fn = deadline_fn
        self._classify = classify

    def backoff_s(self, attempt: int) -> float:
        """Full-jitter sleep before re-attempt ``attempt`` (1-based)."""
        ceiling = min(self.cap_s, self.base_s * (2 ** (attempt - 1)))
        cap = _watchdog_cap()
        if cap is not None:
            ceiling = min(ceiling, max(0.0, cap))
        return self._rng.uniform(0.0, ceiling)

    def call(
        self,
        fn: Callable[..., Any],
        *args: Any,
        site: str = "retry",
        **kwargs: Any,
    ) -> Any:
        """Run ``fn`` under the policy; raises the last error on give-up."""
        tel = get_telemetry()
        attempt = 0
        while True:
            attempt += 1
            _bump(site, "attempts")
            try:
                result = fn(*args, **kwargs)
            except Exception as exc:
                retryable, kind = self._classify(exc)
                if not retryable or attempt > self.retries:
                    if retryable:
                        _bump(site, "gave_up")
                        tel.count(f"retry.{site}.gave_up")
                    raise
                sleep_s = self.backoff_s(attempt)
                remaining = self._deadline_fn()
                if remaining is not None and sleep_s >= remaining:
                    # The budget cannot fit another attempt: re-raise NOW
                    # so the structured error line beats the deadline.
                    _bump(site, "gave_up")
                    tel.count(f"retry.{site}.gave_up")
                    raise
                _bump(site, "retries")
                tel.count(f"retry.{site}")
                tel.event(
                    "retry",
                    site=site,
                    attempt=attempt,
                    kind=kind,
                    sleep_s=round(sleep_s, 4),
                    error=str(exc)[:200],
                )
                if sleep_s > 0.0:
                    self._sleep(sleep_s)
                continue
            if attempt > 1:
                _bump(site, "recoveries")
                tel.count(f"retry.{site}.recovered")
                tel.event("retry_recovered", site=site, attempts=attempt)
            return result

    def wrap(
        self, fn: Callable[..., Any], site: str = "retry"
    ) -> Callable[..., Any]:
        def wrapped(*args: Any, **kwargs: Any) -> Any:
            return self.call(fn, *args, site=site, **kwargs)

        return wrapped


def resolve_http_retries(
    value: Optional[Any] = None, default: int = 2
) -> int:
    """Validated ``MUSICAAL_HTTP_RETRIES`` (the Ollama re-attempt count).

    Both an explicit value and the env var raise a clear ValueError on
    garbage — an HTTP retry knob silently falling back would hide the
    typo until the first outage needed it.
    """
    import os

    source = "http retries"
    if value is None:
        raw = os.environ.get("MUSICAAL_HTTP_RETRIES", "").strip()
        if not raw:
            return default
        source = "MUSICAAL_HTTP_RETRIES"
        value = raw
    try:
        retries = int(str(value).strip())
    except ValueError:
        raise ValueError(
            f"{source} must be an integer >= 0, got {value!r}"
        ) from None
    if retries < 0:
        raise ValueError(f"{source} must be >= 0, got {retries}")
    return retries
