"""Backend failover: one structured re-init-and-retry, then degrade.

The watchdog taxonomy (PR 4) can *name* a dead tunnel or a stalled
device; this module is what finally *acts* on the name.  An engine wraps
its device-dependent block in :func:`run_with_failover`:

1. the block runs; on success nothing else happens;
2. a failure classified as backend loss (``tunnel_dead`` /
   ``device_stall`` / a transient injected fault) triggers ONE re-init of
   the backend (caller-supplied ``reinit``) and one retry;
3. if that also fails and the caller supplied a ``degrade`` path (the
   CPU/numpy equivalent), the run finishes there — stamped
   ``degraded: true`` in the run manifest via ``tel.annotate`` — instead
   of dying minutes into a corpus pass.

Degrade paths must be bit-compatible: the golden contracts (byte-stable
``word_counts.csv``) hold on the degraded path too, which is what the
chaos suite asserts.
"""

from __future__ import annotations

from typing import Any, Callable, Optional, Tuple

from music_analyst_tpu.resilience.policy import classify_retryable
from music_analyst_tpu.telemetry import get_telemetry

# Kinds that mean "the backend, not the program": worth a re-init.
FAILOVER_KINDS = frozenset(
    {"tunnel_dead", "device_stall", "fault_injected"}
)


def should_failover(exc: BaseException) -> bool:
    """True when ``exc`` reads as recoverable backend loss."""
    retryable, kind = classify_retryable(exc)
    return retryable and kind in FAILOVER_KINDS


def run_with_failover(
    fn: Callable[[], Any],
    *,
    site: str,
    reinit: Optional[Callable[[], None]] = None,
    degrade: Optional[Callable[[], Any]] = None,
) -> Tuple[Any, bool]:
    """Run ``fn``; on classified backend loss re-init + retry, then degrade.

    Returns ``(result, degraded)``.  Anything not classified as backend
    loss — and any :class:`InjectedFatal` — propagates unchanged so
    logic errors keep failing fast.
    """
    tel = get_telemetry()
    try:
        return fn(), False
    except Exception as exc:
        if not should_failover(exc):
            raise
        _, kind = classify_retryable(exc)
        tel.count(f"failover.{site}.retries")
        tel.event(
            "failover_retry",
            site=site,
            kind=kind,
            error=str(exc)[:200],
        )
        if reinit is not None:
            try:
                reinit()
            except Exception as reinit_exc:
                tel.event(
                    "failover_reinit_failed",
                    site=site,
                    error=str(reinit_exc)[:200],
                )
        try:
            result = fn()
        except Exception as retry_exc:
            if degrade is None or not should_failover(retry_exc):
                raise
            _, retry_kind = classify_retryable(retry_exc)
            tel.count(f"failover.{site}.degraded")
            tel.event(
                "failover_degraded",
                site=site,
                kind=retry_kind,
                error=str(retry_exc)[:200],
            )
            tel.annotate(
                degraded=True,
                degraded_site=site,
                degraded_reason=retry_kind or "backend_loss",
            )
            return degrade(), True
        tel.count(f"failover.{site}.recoveries")
        tel.event("failover_recovered", site=site)
        return result, False
