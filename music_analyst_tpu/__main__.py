import sys

from music_analyst_tpu.cli.main import main

if __name__ == "__main__":
    try:
        raise SystemExit(main())
    except Exception as exc:  # top-level error reporting, like the reference
        print(f"Error: {exc}", file=sys.stderr)
        raise
