"""Warm model residency: load once, compile once, answer forever.

The batch engines pay model load + XLA compile on every invocation and
amortize it over a whole dataset; a server amortizes it over its
*lifetime* instead.  This manager owns that lifetime:

* **load once** — the backend resolves through the same
  ``engines/sentiment.get_backend`` dispatch the CLI uses, so
  ``--weight-quant`` streams the checkpoint through
  ``engines/checkpoint.load_quantized_params`` + the persistent
  ``wq_cache`` exactly like a batch run, and the persistent XLA
  compilation cache is enabled before the first compile;
* **pin for the server lifetime** — the classifier (and its on-device
  params) is held by this object until :meth:`release`; nothing about
  the request path can drop it;
* **warm explicitly** — :meth:`warmup` runs one dummy batch at every
  power-of-two bucket size the batcher can emit, so by the time the
  socket opens every steady-state shape is compiled and the first real
  request pays dispatch cost only (``--warmup``, default on).

Per-backend compile/warmup state is tracked in :meth:`snapshot` and
lands in the run manifest's ``serving.residency`` section.

This object is the single owner of a resident backend *everywhere*, not
just under the server: the batch sentiment engine and the weight
validator acquire through it too, so backend construction (persistent
compile cache, mesh placement, weight-quant streaming, length buckets)
is written once and reload-on-poisoned-device is one code path
(:meth:`reload`) whichever surface hit the failure.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional

from music_analyst_tpu.telemetry import get_telemetry


def warmup_sizes(max_batch: int) -> List[int]:
    """The power-of-two bucket ladder the batcher pads into: 1, 2, 4, …
    up to (and including) the bucket covering ``max_batch``."""
    sizes: List[int] = []
    size = 1
    while size < max_batch:
        sizes.append(size)
        size <<= 1
    sizes.append(size)
    return sizes


class ModelResidency:
    """Load-once, warm-once holder for a classifier backend."""

    def __init__(
        self,
        model: str = "mock",
        mock: bool = False,
        weight_quant: Optional[str] = None,
        mesh=None,
        backend=None,
        **backend_kwargs: Any,
    ) -> None:
        self.model = model
        self.mock = mock
        self.weight_quant = weight_quant
        self.mesh = mesh
        # Extra get_backend() options (length_buckets, checkpoint_path, …)
        # pinned at construction so a reload rebuilds the same backend.
        self.backend_kwargs = backend_kwargs
        self._backend = backend  # injected (tests) — skips loading
        self._lock = threading.Lock()
        self._state: Dict[str, Any] = {
            "model": model,
            "mock": bool(mock),
            "weight_quant": weight_quant or "none",
            "loaded": backend is not None,
            "load_seconds": 0.0,
            "warm": False,
            "warmup": None,
            "reloads": 0,
        }

    # ------------------------------------------------------------- loading

    def acquire(self):
        """The resident backend, loading it on first call (thread-safe)."""
        with self._lock:
            if self._backend is not None:
                return self._backend
            tel = get_telemetry()
            from music_analyst_tpu.engines.sentiment import get_backend
            from music_analyst_tpu.utils.cache import (
                enable_persistent_compilation_cache,
            )

            enable_persistent_compilation_cache()
            t0 = time.perf_counter()
            with tel.span("serve.load", model=self.model,
                          weight_quant=self.weight_quant or "none"):
                self._backend = get_backend(
                    self.model,
                    mock=self.mock,
                    mesh=self.mesh,
                    weight_quant=self.weight_quant,
                    **self.backend_kwargs,
                )
            load_s = time.perf_counter() - t0
            self._state.update(
                loaded=True,
                backend=getattr(self._backend, "name", "injected"),
                load_seconds=round(load_s, 6),
            )
            # Streaming weight-quant loads leave per-unit staging stats;
            # surface them next to the residency record when present.
            try:
                from music_analyst_tpu.engines.checkpoint import (
                    last_load_stats,
                )

                load_stats = last_load_stats()
                if load_stats:
                    self._state["wq_load"] = load_stats
            except Exception:
                pass
            return self._backend

    # ------------------------------------------------------------- warmup

    def warmup(self, max_batch: int) -> Dict[str, Any]:
        """Compile every batcher bucket shape before the first request.

        Dummy rows are empty strings (empty lyric → Neutral is a golden
        contract, so this is semantically inert for every backend).
        Returns and records {sizes, seconds, compiles} where ``compiles``
        is the XLA compile count the warmup itself triggered.
        """
        clf = self.acquire()
        tel = get_telemetry()
        sizes = warmup_sizes(max_batch)
        before = tel.compile_stats()
        t0 = time.perf_counter()
        with tel.span("serve.warmup", sizes=sizes):
            for size in sizes:
                clf.collect(clf.submit([""] * size))
        warm_s = time.perf_counter() - t0
        after = tel.compile_stats()
        record = {
            "sizes": sizes,
            "seconds": round(warm_s, 6),
            "compiles": after["count"] - before["count"],
            "compile_seconds": round(
                after["seconds"] - before["seconds"], 6
            ),
        }
        with self._lock:
            self._state["warm"] = True
            self._state["warmup"] = record
        tel.annotate(serve_warmup=record)
        return record

    def warmup_decode(self, scheduler) -> Dict[str, Any]:
        """Compile the continuous-decode programs before the first
        ``generate`` request lands (the decode analogue of :meth:`warmup`:
        dummy prefill + decode dispatch + free — after this the runtime's
        zero-retrace contract holds for the server lifetime).  The paged
        runtime walks a ladder of shifted page-table rows so page-gather
        indices are exercised as traced operands, not baked constants:
        the same four programs must serve every later table permutation."""
        tel = get_telemetry()
        with tel.span("serve.warmup_decode"):
            record = scheduler.warmup()
        with self._lock:
            self._state["decode_warmup"] = record
        return record

    def release(self) -> None:
        with self._lock:
            self._backend = None
            self._state["loaded"] = False

    def current(self):
        """The resident backend (loading lazily) — resolve PER CALL so a
        :meth:`reload` swaps the backend under live ops."""
        backend = self._backend
        return backend if backend is not None else self.acquire()

    def reload(self):
        """Drop the (poisoned) backend and load a fresh one.

        The recovery half of reload-on-poisoned-device: the batcher's
        failover hook calls this when a dispatch failure classifies as
        device loss, then retries the batch against the new backend —
        the server survives the device dying between batches.
        """
        tel = get_telemetry()
        with self._lock:
            self._backend = None
            self._state["loaded"] = False
            self._state["warm"] = False
            self._state["reloads"] += 1
        tel.count("serving.residency_reloads")
        tel.event("residency_reload", model=self.model)
        return self.acquire()

    # ------------------------------------------------------------ readouts

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            return dict(self._state)
