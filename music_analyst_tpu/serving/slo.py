"""SLO/overload primitives: per-tenant token buckets and weighted fair
queueing with strict priority classes.

Shared by the dynamic batcher (``serving/batcher.py``), the continuous
decode scheduler (``serving/decode_loop.py``), and the replica router
(``serving/router.py``) so all three admission points enforce ONE
overload contract:

* **priority classes** — an integer per request (higher serves first);
  classes are strict: queued high-priority work always dispatches before
  lower classes.  Within one class tenants share capacity fairly.
* **weighted fair queueing** — inside a priority class, each tenant owns
  a sub-queue and a virtual-time counter; the pop always takes the
  tenant with the smallest virtual time, so a tenant flooding the queue
  gets exactly its fair share of service while a light tenant's requests
  never wait behind the flood (the starvation-freedom contract the SLO
  tests pin).
* **token buckets** — ``TokenBucket`` meters per-tenant admission at a
  sustained requests/second budget with bounded burst; an over-budget
  tenant sheds at *its own* bucket while other tenants keep admitting
  (per-tenant shedding, not per-fleet).
* **priority-aware eviction** — when the bounded queue is full, the
  request shed is not blindly the newcomer: :meth:`FairQueue
  .shed_candidate` hands back a queued request from a lower priority
  class, or from the most over-represented tenant in the same class, so
  overload degrades the greedy/low-value traffic first.

Everything here is host-side bookkeeping with no device or jax imports —
it must stay importable before the test harness pins ``JAX_PLATFORMS``.
"""

from __future__ import annotations

import math
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional, Tuple


class RateMeter:
    """Rolling-window event rate: a time-decayed accumulator with a
    ~``tau_s`` horizon, so a live ``stats`` poller reads req/s, tokens/s
    and shed/s directly instead of differencing cumulative counters.

    ``mark(n)`` decays the accumulator by ``exp(-dt/tau)`` then adds
    ``n``; at a steady arrival rate ``r`` the accumulator converges to
    ``r * tau``, so ``rate() = acc / tau`` reads the sustained rate and
    forgets a burst within a few windows.  An idle meter costs nothing
    (decay happens lazily on access).
    """

    def __init__(self, tau_s: float = 10.0) -> None:
        self.tau_s = float(tau_s)
        self._acc = 0.0
        self._t_last = time.monotonic()
        self._lock = threading.Lock()

    def mark(self, n: float = 1.0) -> None:
        with self._lock:
            now = time.monotonic()
            self._acc *= math.exp(-(now - self._t_last) / self.tau_s)
            self._t_last = now
            self._acc += float(n)

    def rate(self) -> float:
        """Events per second over the rolling window."""
        with self._lock:
            now = time.monotonic()
            acc = self._acc * math.exp(-(now - self._t_last) / self.tau_s)
        return round(acc / self.tau_s, 6)


class TokenBucket:
    """Per-tenant admission meter: ``rate`` tokens/second, ``burst`` cap.

    ``rate <= 0`` disables metering (every ``take`` succeeds) — the
    resolve-from-env default.  Refill happens lazily on access, so an
    idle bucket costs nothing.
    """

    def __init__(self, rate: float, burst: Optional[float] = None) -> None:
        self.rate = float(rate)
        self.burst = (
            float(burst) if burst is not None
            else max(2.0 * self.rate, 1.0)
        )
        self._tokens = self.burst
        self._t_last = time.monotonic()
        self._lock = threading.Lock()

    def take(self, n: float = 1.0) -> bool:
        if self.rate <= 0.0:
            return True
        with self._lock:
            now = time.monotonic()
            self._tokens = min(
                self.burst, self._tokens + (now - self._t_last) * self.rate
            )
            self._t_last = now
            if self._tokens >= n:
                self._tokens -= n
                return True
            return False

    def retry_after_ms(self, n: float = 1.0) -> float:
        """Milliseconds until ``n`` tokens will have accrued — the
        backoff hint an over-budget shed carries."""
        if self.rate <= 0.0:
            return 0.0
        with self._lock:
            deficit = max(n - self._tokens, 0.0)
        return round(max(deficit / self.rate * 1000.0, 1.0), 3)


class FairQueue:
    """Strict priority classes; per-tenant WFQ within each class.

    Not thread-safe by itself — callers hold their own admission lock
    (the batcher/scheduler/router condition variable), exactly as they
    did around the plain ``deque`` this replaces.
    """

    def __init__(self) -> None:
        # priority -> tenant -> deque of requests
        self._classes: Dict[int, Dict[str, deque]] = {}
        # (priority, tenant) -> WFQ virtual finish time
        self._vtime: Dict[Tuple[int, str], float] = {}
        self._len = 0

    def __len__(self) -> int:
        return self._len

    def __bool__(self) -> bool:
        return self._len > 0

    def _tenant_queue(self, priority: int, tenant: str) -> deque:
        tenants = self._classes.setdefault(int(priority), {})
        q = tenants.get(tenant)
        if q is None:
            q = tenants[tenant] = deque()
        return q

    def append(self, req: Any) -> None:
        prio, tenant = int(req.priority), req.tenant
        q = self._tenant_queue(prio, tenant)
        if not q:
            # A tenant (re)joining the class starts at the current live
            # floor: an idle spell must not bank unbounded credit.
            live = [
                self._vtime.get((prio, t), 0.0)
                for t, tq in self._classes[prio].items() if tq
            ]
            floor = min(live) if live else 0.0
            key = (prio, tenant)
            self._vtime[key] = max(self._vtime.get(key, 0.0), floor)
        q.append(req)
        self._len += 1

    def requeue(self, req: Any) -> None:
        """Put a request back at the HEAD of its tenant sub-queue (a
        preempted or deferred request has already paid its wait) and
        refund the virtual-time charge its original pop cost."""
        prio, tenant = int(req.priority), req.tenant
        self._tenant_queue(prio, tenant).appendleft(req)
        key = (prio, tenant)
        self._vtime[key] = max(self._vtime.get(key, 0.0) - 1.0, 0.0)
        self._len += 1

    def peek(self) -> Optional[Any]:
        """The request the next :meth:`popleft` would return."""
        return self._select(pop=False)

    def popleft(self) -> Optional[Any]:
        return self._select(pop=True)

    def _select(self, pop: bool) -> Optional[Any]:
        for prio in sorted(self._classes, reverse=True):
            tenants = self._classes[prio]
            live = [(t, q) for t, q in tenants.items() if q]
            if not live:
                continue
            tenant, q = min(
                live,
                key=lambda kv: (self._vtime.get((prio, kv[0]), 0.0), kv[0]),
            )
            if not pop:
                return q[0]
            req = q.popleft()
            self._len -= 1
            self._vtime[(prio, tenant)] = (
                self._vtime.get((prio, tenant), 0.0) + 1.0
            )
            return req
        return None

    def head_wait_t(self) -> Optional[float]:
        """Earliest ``t_enqueue`` across every queued request (the flush
        deadline must honor the oldest request even if WFQ would serve a
        different one first)."""
        oldest: Optional[float] = None
        for tenants in self._classes.values():
            for q in tenants.values():
                if q and (oldest is None or q[0].t_enqueue < oldest):
                    oldest = q[0].t_enqueue
        return oldest

    def depth_ahead(self, priority: int) -> int:
        """How many queued requests would be served before a newcomer at
        ``priority`` (everything in higher classes, plus the newcomer's
        whole class — WFQ gives no head-of-class guarantee)."""
        ahead = 0
        for prio, tenants in self._classes.items():
            if prio >= int(priority):
                ahead += sum(len(q) for q in tenants.values())
        return ahead

    def tenant_depth(self, tenant: str) -> int:
        return sum(
            len(tenants.get(tenant) or ())
            for tenants in self._classes.values()
        )

    def shed_candidate(self, tenant: str, priority: int) -> Optional[Any]:
        """When the queue is full, pick a queued request to shed INSTEAD
        of the newcomer, or None to shed the newcomer itself.

        A victim is taken from the tail of the lowest priority class
        strictly below the newcomer's, or — within the newcomer's own
        class — from the tenant holding strictly more queued requests
        than the newcomer's tenant (the most over-represented one).
        Equal standing means no victim: the newcomer sheds, so two
        identical tenants cannot evict each other's work in a loop.
        """
        prio_in = int(priority)
        for prio in sorted(self._classes):
            if prio > prio_in:
                break
            tenants = self._classes[prio]
            if prio < prio_in:
                live = [(len(q), t) for t, q in tenants.items() if q]
                if not live:
                    continue
                _, victim_tenant = max(live)
                req = tenants[victim_tenant].pop()
                self._len -= 1
                return req
            mine = len(tenants.get(tenant) or ())
            live = [
                (len(q), t) for t, q in tenants.items()
                if q and t != tenant and len(q) > mine + 1
            ]
            if live:
                _, victim_tenant = max(live)
                req = tenants[victim_tenant].pop()
                self._len -= 1
                return req
        return None

    def drain_all(self) -> List[Any]:
        """Every queued request, in pop order (for fail-everything
        paths); leaves the queue empty."""
        out: List[Any] = []
        while True:
            req = self.popleft()
            if req is None:
                return out
            out.append(req)
