"""Resident inference server: newline-delimited JSON over a unix socket.

The reference's sentiment path is one process per invocation; this is
the shape of a production stack instead — a process that loads the model
once (``serving/residency.py``), keeps it warm, and answers requests as
they arrive through the dynamic batcher (``serving/batcher.py``).

**Protocol** (``ndjson/v1``, loopback-only by construction — a unix
socket or the process's own stdio; nothing here can reach a network):

* request: ``{"id": <any>, "op": "sentiment"|"wordcount"|"generate",
  "text": ...}`` (``op`` defaults to ``sentiment``; a missing ``id``
  gets an ``auto-<n>`` one).  Control ops: ``ping``, ``stats``,
  ``shutdown``.  ``generate`` (generative backends only) additionally
  accepts ``max_new_tokens`` and rides the continuous-batching decode
  runtime (``serving/decode_loop.py``) instead of the dynamic batcher:
  its reply is ``{"text":…, "label":…, "tokens":…}`` and it can
  overlap with sentiment/wordcount batches on the same connection.
  Every submit op also accepts the SLO/isolation fields
  (``serving/slo.py``): ``tenant`` (string fair-queue identity),
  ``priority`` (integer class, higher first), ``deadline_ms``
  (arrival-relative TTFT deadline; defaults to the configured
  ``--ttft-slo-ms`` when one is set).
* response: one JSON line per request, **in request arrival order per
  connection**: ``{"id":…, "ok": true, "op":…, …payload}`` or
  ``{"id":…, "ok": false, "error": {"kind":…, "detail":…}}``.
  Structured error kinds: ``queue_full`` (admission shed — retry with
  backoff), ``slo_unattainable`` (the drain estimate already blows the
  request's deadline; both sheds carry ``retry_after_ms``),
  ``bad_request``, ``request_failed`` (that request's model row raised;
  the server lives on), ``draining``.

**Graceful drain**: SIGTERM/SIGINT (or the ``shutdown`` op, or stdin
EOF in ``--stdio`` mode) stops admission, finishes every in-flight and
queued batch, writes the remaining replies, dumps a flight record
(``observability/flight.py``) so the drain is a diagnosable artifact,
and exits 0.  The heartbeat watchdog covers the dispatch edge with the
``serve`` kind (taxonomy ``serve_stall``), and per-request spans +
queue-depth/occupancy gauges flow through telemetry into the run
manifest's ``serving`` section.
"""

from __future__ import annotations

import collections
import json
import os
import queue
import sys
import threading
import time
from typing import Any, Dict, List, Optional

from music_analyst_tpu.resilience.faults import fault_point
from music_analyst_tpu.serving.batcher import (
    DynamicBatcher,
    ServeRequest,
    resolve_max_batch,
    resolve_max_queue,
    resolve_max_wait_ms,
    resolve_tp,
)
from music_analyst_tpu.serving.journal import (
    RequestJournal,
    resolve_journal_dir,
)
from music_analyst_tpu.serving.residency import ModelResidency
from music_analyst_tpu.serving.response_cache import (
    ResponseCache,
    backend_fingerprint,
    checkpoint_stamp,
    resolve_response_cache_dir,
)
from music_analyst_tpu.telemetry import get_telemetry
from music_analyst_tpu.observability.metrics_plane import (
    configure_metrics,
    get_metrics_plane,
)
from music_analyst_tpu.telemetry.reqtrace import (
    configure_reqtrace,
    get_reqtrace,
)

PROTOCOL = "ndjson/v1"

_EOF = object()  # reader→writer sentinel: the stream ended

# The live server (for the run manifest's ``serving`` section — the
# pattern corpus_cache/wq_cache established: stats only exist once the
# subsystem has been used, so serve-free runs keep their key set).
_LAST_SERVER: Optional["SentimentServer"] = None


def serving_stats() -> Dict[str, Any]:
    """Stats of the most recent server in this process ({} if none)."""
    server = _LAST_SERVER
    return server.stats_snapshot() if server is not None else {}


def _wordcount_batch(texts: List[str]) -> List[Dict[str, Any]]:
    """Per-request word counts with the serial per-song tool's tokenizer
    semantics (``data/tokenizer.tokenize_latin1``) and the golden ranking
    (count desc, then strcmp asc)."""
    from music_analyst_tpu.data.tokenizer import tokenize_latin1

    out: List[Dict[str, Any]] = []
    for text in texts:
        counts = collections.Counter(tokenize_latin1(text))
        ranked = dict(
            sorted(counts.items(), key=lambda kv: (-kv[1], kv[0]))
        )
        out.append({
            "counts": ranked,
            "total_words": int(sum(counts.values())),
        })
    return out


def build_ops(clf) -> Dict[str, Any]:
    """The batcher op table for a resident classifier backend."""
    def sentiment(texts: List[str]) -> List[Dict[str, Any]]:
        return [{"label": label} for label in clf.classify_batch(texts)]

    return {"sentiment": sentiment, "wordcount": _wordcount_batch}


def build_resident_ops(residency: ModelResidency) -> Dict[str, Any]:
    """Op table that resolves the backend through ``residency`` PER CALL,
    so a failover :meth:`ModelResidency.reload` swaps the model under the
    live batcher instead of pinning the poisoned instance."""
    def sentiment(texts: List[str]) -> List[Dict[str, Any]]:
        labels = residency.current().classify_batch(texts)
        return [{"label": label} for label in labels]

    return {"sentiment": sentiment, "wordcount": _wordcount_batch}


class SentimentServer:
    """Wire protocol + connection lifecycle around a DynamicBatcher."""

    def __init__(
        self,
        batcher: DynamicBatcher,
        residency: Optional[ModelResidency] = None,
        mode: str = "stdio",
        decode=None,
        router=None,
        journal: Optional[RequestJournal] = None,
    ) -> None:
        self.batcher = batcher
        self.residency = residency
        # Durable request journal (serving/journal.py): admitted records
        # write ahead of dispatch, replied records fsync ahead of the
        # wire, and re-dispatched ids settle from the dedup index instead
        # of recomputing.  None = the historical non-durable behavior.
        self.journal = journal
        # Optional ContinuousScheduler hosting the ``generate`` op; None
        # when the backend has no slot runtime (e.g. --mock) — generate
        # requests then settle as bad_request instead of crashing.
        self.decode = decode
        # Scale-out mode (serving/router.py): the ReplicaRouter sitting in
        # the batcher seat, kept separately so stats_snapshot can surface
        # the fleet view (per-replica dispatch counts, health transitions)
        # as the manifest's ``serving.router`` section.
        self.router = router
        self.mode = mode
        self.drain_event = threading.Event()
        self.drain_reason: Optional[str] = None
        self._drain_lock = threading.Lock()
        self._drained = False
        self._auto_ids = 0
        self._started_mono = time.monotonic()
        global _LAST_SERVER
        _LAST_SERVER = self

    # ------------------------------------------------------------- control

    def request_drain(self, reason: str, record: bool = True) -> None:
        """Begin a graceful drain (idempotent): stop admission, flush the
        queues, and (for signals/shutdown — not a routine stdio EOF) leave
        a flight record naming the reason."""
        if self.drain_event.is_set():
            return
        self.drain_reason = reason
        self.drain_event.set()
        tel = get_telemetry()
        tel.event("serve_drain", reason=reason)
        if not record:
            return
        try:
            from music_analyst_tpu.observability.flight import (
                get_flight_recorder,
            )

            get_flight_recorder().dump(
                reason=f"serve_drain:{reason}",
                detail=(
                    f"graceful drain ({reason}); queued requests flushed, "
                    "admission closed"
                ),
            )
        except Exception:
            pass

    def _drain_batcher(self) -> None:
        with self._drain_lock:
            if not self._drained:
                self.batcher.drain()
                if self.decode is not None:
                    self.decode.drain()
                self._drained = True

    # ------------------------------------------------------------ protocol

    def _control(self, rid: Any, op: str) -> Dict[str, Any]:
        if op == "ping":
            return {"id": rid, "ok": True, "op": "ping",
                    "protocol": PROTOCOL}
        if op == "stats":
            return {"id": rid, "ok": True, "op": "stats",
                    "stats": self.stats_snapshot()}
        # shutdown: the reply goes out first (in order), then the stream
        # loop sees drain_event and flushes the rest.
        self.request_drain("shutdown_op")
        return {"id": rid, "ok": True, "op": "shutdown", "draining": True}

    def _parse_submit(self, line: str) -> ServeRequest:
        """One wire line → an admitted/settled ServeRequest (parse errors
        settle immediately as ``bad_request`` so ordering still holds)."""
        t0_w = time.time()
        self._auto_ids += 1
        fallback_id = f"auto-{self._auto_ids}"
        try:
            payload = json.loads(line)
            if not isinstance(payload, dict):
                raise ValueError("request must be a JSON object")
        except ValueError as exc:
            req = ServeRequest(fallback_id, "invalid", "")
            req.fail("bad_request", f"unparseable request: {exc}"[:200])
            return req
        rid = payload.get("id", fallback_id)
        op = payload.get("op", "sentiment")
        if op in ("ping", "stats", "shutdown"):
            req = ServeRequest(rid, op, "")
            req.complete(self._control(rid, op))
            return req
        text = payload.get("text")
        if not isinstance(text, str):
            req = ServeRequest(rid, op, "")
            req.fail("bad_request", "missing/non-string 'text' field")
            return req
        tenant = payload.get("tenant")
        if tenant is not None and not isinstance(tenant, str):
            req = ServeRequest(rid, op, text)
            req.fail("bad_request", "'tenant' must be a string")
            return req
        priority = payload.get("priority")
        if priority is not None and (
            isinstance(priority, bool) or not isinstance(priority, int)
        ):
            req = ServeRequest(rid, op, text)
            req.fail("bad_request", "'priority' must be an integer")
            return req
        deadline_ms = payload.get("deadline_ms")
        if deadline_ms is not None and (
            isinstance(deadline_ms, bool)
            or not isinstance(deadline_ms, (int, float))
        ):
            req = ServeRequest(rid, op, text)
            req.fail("bad_request", "'deadline_ms' must be a number")
            return req
        slo = {"tenant": tenant, "priority": priority,
               "deadline_ms": deadline_ms}
        budget = None
        if op == "generate":
            if self.decode is None:
                req = ServeRequest(rid, op, text)
                req.fail(
                    "bad_request",
                    "generate requires a generative backend with a slot "
                    "runtime (not available on this server)",
                )
                return req
            budget = payload.get("max_new_tokens")
            if budget is not None and not isinstance(budget, int):
                req = ServeRequest(rid, op, text)
                req.fail("bad_request",
                         "'max_new_tokens' must be an integer")
                return req
        if self.journal is not None:
            # Exactly-once at the wire: a re-dispatched id whose reply is
            # journaled settles from the dedup index — nothing recomputes.
            deduped = self.journal.lookup_reply(rid)
            if deduped is not None:
                req = ServeRequest(rid, op, text)
                deduped["id"] = rid
                req.complete(deduped)
                return req
        rt = get_reqtrace()
        trace = None
        if rt.enabled:
            # Adopt the wire's optional "trace" field (absent ⇒ new
            # root: ndjson/v1 stays backward-compatible) and hand it to
            # the submit below on this same thread, clocked from the
            # moment the line arrived.
            trace = rt.mint(payload.get("trace"))
            rt.set_pending(trace, t0_w)
        if self.journal is not None:
            meta: Dict[str, Any] = {}
            if budget is not None:
                meta["max_new_tokens"] = budget
            if trace is not None:
                # Crash replay re-adopts the same trace id, so the
                # waterfall survives a restart (_replay_journal).
                meta["trace"] = trace
            self.journal.record_admitted(
                rid, op, text, tenant=tenant, priority=priority,
                deadline_ms=deadline_ms, meta=meta,
            )
        # Post-admit crash seam: admission journaled, no reply yet — a
        # SIGKILL here must replay the request on restart.
        fault_point("serve.admit", op=op)
        if op == "generate":
            return self.decode.submit(rid, text, max_new_tokens=budget,
                                      **slo)
        return self.batcher.submit(rid, op, text, **slo)

    # ---------------------------------------------------------- stream I/O

    def handle_stream(self, rfile, wfile, drain_on_eof: bool = False) -> int:
        """Serve one NDJSON stream: replies in request arrival order.

        A reader thread admits requests as fast as the peer sends them
        (so a whole burst coalesces); this thread writes each settled
        reply in order.  Returns the number of replies written.
        """
        tel = get_telemetry()
        rt = get_reqtrace()
        order: "queue.Queue" = queue.Queue()
        stop_reading = threading.Event()

        def read_loop() -> None:
            try:
                for line in rfile:
                    if stop_reading.is_set() or self.drain_event.is_set():
                        break
                    line = line.strip()
                    if not line:
                        continue
                    order.put(self._parse_submit(line))
            except (OSError, ValueError):
                pass  # peer vanished mid-line: the writer flushes and exits
            finally:
                order.put(_EOF)

        reader = threading.Thread(
            target=read_loop, name="serve-reader", daemon=True
        )
        reader.start()

        written = 0
        eof = False
        pending: "collections.deque[ServeRequest]" = collections.deque()

        def _pull(block: bool) -> None:
            """Drain the reader's queue into ``pending`` (arrival order
            preserved), folding the EOF sentinel into the flag."""
            nonlocal eof
            try:
                item = order.get(timeout=0.05) if block else \
                    order.get_nowait()
            except queue.Empty:
                return
            while True:
                if item is _EOF:
                    eof = True
                    if drain_on_eof:
                        self.request_drain("eof", record=False)
                        self._drain_batcher()
                else:
                    pending.append(item)
                try:
                    item = order.get_nowait()
                except queue.Empty:
                    return

        while True:
            if self.drain_event.is_set():
                # Admission is closed; everything already queued settles
                # once the batcher finishes its flush.
                self._drain_batcher()
            _pull(block=not pending)
            if not pending:
                if eof or (self.drain_event.is_set() and order.empty()):
                    break
                continue
            req: ServeRequest = pending.popleft()
            # Bounded waits so a drain can't strand the writer; the
            # batcher answers every admitted request on drain.
            while not req.wait(timeout=0.2):
                if self.drain_event.is_set():
                    self._drain_batcher()
            # Group commit: the settled head plus every already-settled
            # successor (one dynamic batch usually settles together)
            # journal their replies under ONE fsync, then the lines go
            # out in arrival order — the per-reply durability barrier
            # (record durable BEFORE its line hits the wire, so any
            # reply a client ever saw is deduplicable after a crash,
            # and one a crash ate is recomputed, never duplicated) at
            # amortized fsync cost.
            batch = [req]
            while pending and pending[0].done:
                batch.append(pending.popleft())
            journaled = False
            t_sync0 = time.time() if rt.enabled else None
            for settled in batch:
                # Pre-reply crash seam, then the durability barrier.
                fault_point("serve.reply", op=settled.op)
                if self.journal is not None and settled.op not in (
                    "ping", "stats", "shutdown", "invalid",
                ):
                    self.journal.record_replied(
                        settled.id, settled.response, sync=False
                    )
                    journaled = True
            if journaled:
                self.journal.sync()
            if rt.enabled:
                # The group-commit barrier is shared: every settled
                # request's ``commit`` phase runs settle → barrier end,
                # with the fsync itself an overlapping detail span.
                t_sync1 = time.time()
                for settled in batch:
                    tt = settled.meta.get("trace_t")
                    if tt is None:
                        continue
                    rt.phase(settled, "commit",
                             tt.get("cursor", t_sync0), t_sync1,
                             journaled=journaled, group=len(batch))
                    if journaled:
                        rt.detail(settled, "journal.sync",
                                  t_sync0, t_sync1)
                    tt["cursor"] = t_sync1
            for settled in batch:
                if rt.enabled:
                    rt.annotate_reply(settled)
                with tel.span("serve.reply", op=settled.op):
                    wfile.write(json.dumps(settled.response) + "\n")
                    wfile.flush()
                if rt.enabled:
                    rt.advance(settled, "reply", op=settled.op)
                    rt.finish_request(settled)
                written += 1
        stop_reading.set()
        return written

    # ------------------------------------------------------------- sockets

    def serve_unix(self, path: str) -> int:
        """Accept loop on a unix stream socket (thread per connection);
        returns the number of connections served after a drain."""
        import os
        import socket

        try:
            os.unlink(path)
        except OSError:
            pass
        sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        sock.bind(path)
        sock.listen(16)
        sock.settimeout(0.2)
        conns: List[threading.Thread] = []
        served = 0
        try:
            while not self.drain_event.is_set():
                try:
                    conn, _ = sock.accept()
                except socket.timeout:
                    continue
                except OSError:
                    break
                served += 1

                def _one(conn=conn) -> None:
                    with conn:
                        rfile = conn.makefile("r", encoding="utf-8")
                        wfile = conn.makefile("w", encoding="utf-8")
                        try:
                            self.handle_stream(rfile, wfile)
                        except (OSError, ValueError):
                            pass

                thread = threading.Thread(
                    target=_one, name=f"serve-conn-{served}", daemon=True
                )
                thread.start()
                conns.append(thread)
        finally:
            self._drain_batcher()
            for thread in conns:
                thread.join(timeout=5.0)
            sock.close()
            try:
                os.unlink(path)
            except OSError:
                pass
        return served

    # ------------------------------------------------------------ readouts

    def stats_snapshot(self, include_metrics: bool = True) -> Dict[str, Any]:
        out: Dict[str, Any] = {
            "protocol": PROTOCOL,
            "mode": self.mode,
            "uptime_s": round(time.monotonic() - self._started_mono, 3),
            "draining": self.drain_event.is_set(),
            "drain_reason": self.drain_reason,
            "requests": self.batcher.stats(),
        }
        if self.decode is not None:
            out["decode"] = self.decode.stats()
        if self.residency is not None:
            out["residency"] = self.residency.snapshot()
        if self.router is not None:
            out["router"] = self.router.stats()
        if self.journal is not None:
            out["journal"] = self.journal.stats()
        # Response cache (serving/response_cache.py) — one instance is
        # shared by whichever admission edges exist; only-when-used.
        for edge in (self.batcher, self.decode, self.router):
            cache = getattr(edge, "response_cache", None)
            if cache is not None:
                out["response_cache"] = cache.stats()
                break
        rt = get_reqtrace()
        if rt.enabled:
            out["reqtrace"] = rt.stats()
        # SLO layer (serving/slo.py) — only-when-used, like the
        # corpus-cache manifest section: empty snapshots stay out.
        slo: Dict[str, Any] = {}
        snap = getattr(self.batcher, "slo_snapshot", None)
        if callable(snap):
            slo.update(snap() or {})
        if self.decode is not None:
            snap = getattr(self.decode, "slo_snapshot", None)
            if callable(snap):
                decode_slo = snap() or {}
                if decode_slo:
                    slo["decode"] = decode_slo
        if slo:
            out["slo"] = slo
        # Metrics plane (observability/metrics_plane.py) — only when
        # sampling is on.  The plane's own sampler scrapes with
        # ``include_metrics=False`` so the series never nests itself.
        if include_metrics:
            plane = get_metrics_plane()
            if plane.enabled:
                out["metrics"] = plane.snapshot()
        return out


# ----------------------------------------------------------------- CLI glue


def _replay_journal(journal: RequestJournal, batcher, decode,
                    unanswered: List[Dict[str, Any]]) -> int:
    """Answer every admitted-but-unanswered journaled request before
    taking live traffic.  Ops are pure functions of their text, so the
    recompute is byte-identical to the reply the crash ate; journaling
    it makes a reconnecting client's re-submit settle from the dedup
    index."""
    if not unanswered:
        return 0
    rt = get_reqtrace()
    reqs: List[ServeRequest] = []
    for record in unanswered:
        rid = record.get("id")
        op = record.get("op")
        text = record.get("text") or ""
        meta = record.get("meta") or {}
        if rt.enabled and isinstance(meta.get("trace"), dict):
            # Continue the journaled trace (same id; the crashed
            # process's span becomes the parent) so the waterfall spans
            # the restart.
            rt.set_pending(rt.mint(meta["trace"]), time.time())
        slo = dict(
            tenant=record.get("tenant"),
            priority=record.get("priority"),
            deadline_ms=None,  # the journaled deadline already elapsed
        )
        if op == "generate":
            if decode is None:
                req = ServeRequest(rid, op, text)
                req.fail(
                    "request_failed",
                    "journaled generate request replayed on a server "
                    "without a decode runtime",
                )
            else:
                req = decode.submit(
                    rid, text,
                    max_new_tokens=meta.get("max_new_tokens"), **slo,
                )
        else:
            req = batcher.submit(rid, op or "invalid", text, **slo)
        reqs.append(req)
    for req in reqs:
        req.wait(timeout=60.0)
        if req.done:
            journal.record_replied(req.id, req.response)
    get_telemetry().count("journal.replayed", len(reqs))
    return len(reqs)


def _stale_flight_witness() -> bool:
    """The second unclean witness: a flight record already in the
    telemetry dir from a PREVIOUS process whose reason was not a
    graceful drain (SIGKILL writes none, but a fatal crash/watchdog dump
    survives the restart)."""
    directory = get_telemetry().directory
    if not directory:
        return False
    path = os.path.join(directory, "flight_record.json")
    try:
        with open(path, "r", encoding="utf-8") as fh:
            record = json.load(fh)
    except (OSError, ValueError):
        return False
    reason = str(record.get("reason") or "")
    return not reason.startswith("serve_drain")


def serve_mesh(tp: Optional[int]):
    """Mesh for ``--tp N``: a 1-D ``tp`` axis over the first N devices
    (attention heads + KV head axis shard over it, ``DECODE_KV_RULES``);
    None for the single-chip layout."""
    width = resolve_tp(tp)
    if width <= 1:
        return None
    import jax

    from music_analyst_tpu.parallel.mesh import MeshSpec, build_mesh

    devices = jax.devices()
    if len(devices) < width:
        raise ValueError(
            f"--tp {width} needs {width} device(s), have {len(devices)}"
        )
    return build_mesh(MeshSpec((("tp", width),)), devices=devices[:width])


def run_server(
    model: str = "mock",
    mock: bool = False,
    weight_quant: Optional[str] = None,
    stdio: bool = False,
    socket_path: Optional[str] = None,
    max_batch: Optional[int] = None,
    max_wait_ms: Optional[float] = None,
    max_queue: Optional[int] = None,
    warmup: bool = True,
    backend=None,
    quiet: bool = False,
    slots: Optional[int] = None,
    prefill_chunk: Optional[int] = None,
    max_new_tokens: int = 16,
    page_size: Optional[int] = None,
    kv_pages: Optional[int] = None,
    kv_quant: Optional[str] = None,
    speculate_k: Optional[int] = None,
    tp: Optional[int] = None,
    ttft_slo_ms: Optional[float] = None,
    tpot_slo_ms: Optional[float] = None,
    tenant_budget: Optional[float] = None,
    priority: Optional[int] = None,
    response_cache_dir: Optional[str] = None,
    use_response_cache: bool = True,
    journal_dir: Optional[str] = None,
    trace_sample: Optional[Any] = None,
    trace_dir: Optional[str] = None,
    metrics_interval_ms: Optional[Any] = None,
) -> int:
    """The ``serve`` subcommand: load, warm, then serve until drained.

    Startup chatter goes to stderr only — in ``--stdio`` mode stdout *is*
    the reply channel and must carry nothing but NDJSON responses.
    """
    tel = get_telemetry()
    # Request tracing (telemetry/reqtrace.py): enabled iff a directory
    # resolves (--profile-dir here, $MUSICAAL_TRACE_DIR in replica
    # workers the router spawned).  Disabled = inert.
    reqtrace = configure_reqtrace(
        trace_sample, directory=trace_dir, role="server"
    )
    # Metrics plane (observability/metrics_plane.py): enabled iff an
    # interval resolves (--metrics-interval-ms here,
    # $MUSICAAL_METRICS_INTERVAL_MS in spawned replicas).  Disabled =
    # zero wire effect.
    metrics = configure_metrics(
        metrics_interval_ms, directory=trace_dir, role="server"
    )
    resolved_batch = resolve_max_batch(max_batch)
    with tel.run_scope("serve", None):
        # Crash-consistency first: open the journal (replaying its state)
        # and check both unclean witnesses BEFORE any work this run could
        # overwrite them — the journal's missing clean marker (SIGKILL
        # writes no flight record, so the journal is the witness) and a
        # stale non-drain flight record from the previous process.
        journal: Optional[RequestJournal] = None
        unanswered: List[Dict[str, Any]] = []
        stale_flight = _stale_flight_witness()
        journal_path = resolve_journal_dir(journal_dir)
        if journal_path:
            journal = RequestJournal(journal_path)
            unanswered = journal.recover()
        unclean_journal = (
            journal is not None and journal.stats()["unclean_start"]
        )
        if unclean_journal or stale_flight:
            witness = "journal" if unclean_journal else "flight_record"
            tel.annotate(
                unclean_shutdown=True,
                unclean_witness=witness,
            )
            tel.event("unclean_shutdown_detected", witness=witness,
                      replayed=len(unanswered))
            if not quiet:
                print(
                    f"serve: unclean shutdown detected ({witness}); "
                    f"{len(unanswered)} journaled request(s) to replay",
                    file=sys.stderr,
                )
        residency = ModelResidency(
            model=model, mock=mock, weight_quant=weight_quant,
            backend=backend, mesh=serve_mesh(tp),
        )
        clf = residency.acquire()
        # Response cache (serving/response_cache.py): ONE instance shared
        # by every admission edge this server stands up.  The fingerprint
        # folds in everything that changes reply bytes — model identity,
        # checkpoint stamp, quant schemes, the decode budget clamp — so a
        # cache dir shared across configurations can never cross replies.
        rc_dir = resolve_response_cache_dir(
            response_cache_dir, use_response_cache
        )
        response_cache = None
        if rc_dir is not None:
            response_cache = ResponseCache(
                rc_dir,
                fingerprint=backend_fingerprint(
                    model=model,
                    backend=getattr(clf, "name", "injected"),
                    mock=bool(mock),
                    weight_quant=weight_quant or "none",
                    kv_quant=kv_quant or "none",
                    max_new_tokens=int(max_new_tokens),
                    tp=resolve_tp(tp),
                    checkpoint=checkpoint_stamp(),
                ),
            )
        if warmup:
            record = residency.warmup(resolved_batch)
            if not quiet:
                print(
                    f"serve: warmed {len(record['sizes'])} bucket shape(s) "
                    f"in {record['seconds']:.2f}s "
                    f"({record['compiles']} compile(s))",
                    file=sys.stderr,
                )
        batcher = DynamicBatcher(
            build_resident_ops(residency),
            max_batch=resolved_batch,
            max_wait_ms=max_wait_ms,
            max_queue=max_queue,
            failover=lambda exc: residency.reload() is not None,
            ttft_slo_ms=ttft_slo_ms,
            tenant_budget=tenant_budget,
            priority=priority,
            response_cache=response_cache,
        ).start()
        # Continuous decode runtime for the ``generate`` op — only when
        # the backend exposes a slot runtime (capability probe) and slots
        # weren't explicitly disabled with --slots=0.
        decode = None
        if hasattr(clf, "slot_runtime") and (slots is None or slots > 0):
            from music_analyst_tpu.serving.decode_loop import (
                ContinuousScheduler,
            )

            decode = ContinuousScheduler(
                clf,
                n_slots=slots,
                prefill_chunk=prefill_chunk,
                max_new_tokens=max_new_tokens,
                max_queue=max_queue,
                page_size=page_size,
                kv_pages=kv_pages,
                kv_quant=kv_quant,
                speculate_k=speculate_k,
                ttft_slo_ms=ttft_slo_ms,
                tpot_slo_ms=tpot_slo_ms,
                tenant_budget=tenant_budget,
                priority=priority,
                response_cache=response_cache,
                # Engine ledger: flushes to the same profile dir on the
                # metrics cadence ($MUSICAAL_LEDGER_* override either).
                ledger_dir=trace_dir,
            )
            if warmup:
                record = residency.warmup_decode(decode)
                if not quiet:
                    print(
                        f"serve: warmed decode runtime "
                        f"({record['n_slots']} slot(s)) in "
                        f"{record['seconds']:.2f}s "
                        f"({record['compiles']} compile(s))",
                        file=sys.stderr,
                    )
            decode.start()
        server = SentimentServer(
            batcher, residency, mode="stdio" if stdio else "unix",
            decode=decode, journal=journal,
        )
        if metrics.enabled:
            metrics.attach(
                lambda: server.stats_snapshot(include_metrics=False)
            )
            metrics.start()
        # Replay BEFORE live traffic: every journaled-but-unanswered
        # request settles (and its reply journals) so reconnecting
        # clients dedup instead of recomputing.
        if journal is not None and unanswered:
            replayed = _replay_journal(journal, batcher, decode, unanswered)
            if not quiet:
                print(
                    f"serve: replayed {replayed} journaled request(s)",
                    file=sys.stderr,
                )
        tel.annotate(
            backend=getattr(clf, "name", "injected"),
            serve_mode=server.mode,
            max_batch=batcher.max_batch,
            max_wait_ms=batcher.max_wait_ms,
            max_queue=batcher.max_queue,
            decode_slots=(decode.plan.n_slots if decode is not None else 0),
            serve_tp=resolve_tp(tp),
            journal_dir=journal_path,
            response_cache_dir=rc_dir,
        )

        # Graceful SIGTERM/SIGINT: drain instead of dying.  The flight
        # recorder's own handlers were installed by the CLI before this;
        # replacing them here means a signal drains the server (and the
        # drain itself dumps the flight record), rather than chaining to
        # the process-killing default.  Restored on exit.
        import signal

        previous: Dict[int, Any] = {}

        def _on_signal(signum, frame) -> None:
            try:
                name = signal.Signals(signum).name
            except ValueError:  # pragma: no cover
                name = str(signum)
            server.request_drain(f"signal:{name}")

        for signum in (signal.SIGTERM, signal.SIGINT):
            try:
                previous[signum] = signal.signal(signum, _on_signal)
            except (ValueError, OSError):  # non-main thread (tests)
                pass
        try:
            if stdio:
                if not quiet:
                    print(
                        f"serve: ready on stdio (max_batch="
                        f"{batcher.max_batch}, max_wait_ms="
                        f"{batcher.max_wait_ms}, max_queue="
                        f"{batcher.max_queue})",
                        file=sys.stderr,
                    )
                server.handle_stream(sys.stdin, sys.stdout,
                                     drain_on_eof=True)
            else:
                if not socket_path:
                    raise ValueError(
                        "serve: --socket PATH (or --stdio) is required"
                    )
                if not quiet:
                    print(
                        f"serve: listening on {socket_path}",
                        file=sys.stderr,
                    )
                server.serve_unix(socket_path)
        finally:
            server._drain_batcher()
            for signum, prev in previous.items():
                try:
                    signal.signal(signum, prev)
                except (ValueError, OSError):
                    pass
            # Graceful shutdown compacts the journal and writes the clean
            # marker — the exact step a SIGKILL cannot take, which is how
            # the next start detects it.
            if journal is not None:
                journal.close()
            # Final metrics sample (baseline + final bracket even the
            # shortest run), then the Chrome artifact, exactly once.
            metrics.close()
            reqtrace.close()
            stats = server.stats_snapshot()
            tel.gauge("serving.requests_total",
                      stats["requests"]["admitted"])
            tel.gauge("serving.shed_total", stats["requests"]["shed"])
            if not quiet:
                reqs = stats["requests"]
                print(
                    f"serve: drained ({server.drain_reason or 'eof'}): "
                    f"{reqs['completed']} completed, {reqs['shed']} shed, "
                    f"{reqs['batches']} batch(es), occupancy "
                    f"{reqs['occupancy']}",
                    file=sys.stderr,
                )
    return 0
