"""Online serving layer: dynamic batching, admission control, residency.

The batch engines answer "analyze this corpus"; this package answers
"keep the model warm and answer requests as they arrive" — the
production-inference shape the ROADMAP north star asks for:

* :mod:`music_analyst_tpu.serving.batcher` — deadline-aware dynamic
  batcher (flush on ``max_batch`` or ``max_wait_ms``) with bounded
  admission queues that shed via structured ``queue_full`` errors;
* :mod:`music_analyst_tpu.serving.residency` — load-once / warm-once
  backend holder (weight-quant + persistent caches included);
* :mod:`music_analyst_tpu.serving.server` — NDJSON protocol over a unix
  socket or stdio, graceful SIGTERM drain, watchdog + flight-recorder
  integration (the ``serve`` CLI subcommand);
* :mod:`music_analyst_tpu.serving.decode_loop` — continuous-batching
  decode scheduler (admit→prefill→decode over the slot-indexed KV cache
  in ``ops/kv_slots.py``) hosting the ``generate`` op;
* :mod:`music_analyst_tpu.serving.journal` — durable request journal
  (CRC-framed WAL): replay admitted-but-unanswered requests after a
  crash, dedup already-sent replies — exactly-once at the wire.
"""

from music_analyst_tpu.serving.batcher import (
    DEFAULT_MAX_BATCH,
    DEFAULT_MAX_QUEUE,
    DEFAULT_MAX_WAIT_MS,
    DEFAULT_PREFILL_CHUNK,
    DEFAULT_SLOTS,
    DynamicBatcher,
    ServeRequest,
    resolve_max_batch,
    resolve_max_queue,
    resolve_max_wait_ms,
    resolve_prefill_chunk,
    resolve_slots,
)
from music_analyst_tpu.serving.decode_loop import ContinuousScheduler
from music_analyst_tpu.serving.journal import (
    RequestJournal,
    resolve_journal_dir,
)
from music_analyst_tpu.serving.residency import ModelResidency, warmup_sizes
from music_analyst_tpu.serving.server import (
    PROTOCOL,
    SentimentServer,
    build_ops,
    run_server,
    serving_stats,
)

__all__ = [
    "ContinuousScheduler",
    "DEFAULT_MAX_BATCH",
    "DEFAULT_MAX_QUEUE",
    "DEFAULT_MAX_WAIT_MS",
    "DEFAULT_PREFILL_CHUNK",
    "DEFAULT_SLOTS",
    "DynamicBatcher",
    "ModelResidency",
    "PROTOCOL",
    "RequestJournal",
    "SentimentServer",
    "ServeRequest",
    "build_ops",
    "resolve_journal_dir",
    "resolve_max_batch",
    "resolve_max_queue",
    "resolve_max_wait_ms",
    "resolve_prefill_chunk",
    "resolve_slots",
    "run_server",
    "serving_stats",
    "warmup_sizes",
]
