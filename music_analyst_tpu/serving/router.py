"""Replica router: one dispatch point in front of N worker servers.

One resident server (``serving/server.py``) is one process on one
backend; the scale-out shape is N such workers — each a full
``SentimentServer`` listening on its own unix socket, typically spawned
by :func:`spawn_replicas` — behind this router:

* **join-shortest-queue dispatch** — each admitted request goes to the
  healthy replica with the fewest router-side in-flight requests, tie
  broken by the queue depth its last polled ``stats`` reply reported;
* **health** — a poll thread pings every replica's ``stats`` op; a
  transport failure, worker death, or dispatch failure classified by the
  watchdog taxonomy (``tunnel_dead`` / ``decode_stall``) marks the
  replica unhealthy, its undelivered in-flight requests are *requeued*
  and re-dispatched to the survivors (``resilience/failover.py``
  classification + the shared :class:`RetryPolicy` at the new
  ``router.dispatch`` fault site), and the transition is recorded for
  the run manifest's ``serving.router`` section.  A replica whose
  *process* died is respawned under supervision (capped exponential
  backoff, transition kind ``respawned``) — the fleet heals itself
  instead of shrinking monotonically;
* **per-tenant overload isolation** — the router's admission queue is
  the same :class:`~music_analyst_tpu.serving.slo.FairQueue` the batcher
  and decode scheduler use (strict priority classes, per-tenant WFQ),
  with per-tenant token buckets and deadline-aware ``slo_unattainable``
  sheds: one greedy tenant sheds at *its own* budget/queue share while
  the rest of the fleet's capacity keeps flowing;
* **zero loss** — every admitted request either settles with a replica's
  answer (possibly after re-dispatch) or fails with a structured error
  (``queue_full``/``slo_unattainable``, each with a ``retry_after_ms``
  hint; ``replica_lost`` when no healthy replica remains); nothing is
  dropped silently.  Sentiment and wordcount ops are pure functions of
  their text, so re-dispatching a request whose first answer died with
  its worker is idempotent;
* **graceful fleet drain** — SIGTERM (installed by :func:`run_router`)
  stops admission, settles everything in flight, then SIGTERMs each
  worker so *their* graceful-drain contract runs, escalating to SIGKILL
  only for stragglers.

The router speaks the same ``ndjson/v1`` wire protocol downstream that
it serves upstream; request ids are rewritten to router-scoped wire ids
on the way down and restored on the way up, so colliding client ids
across connections cannot cross-talk.  The router quacks like a
``DynamicBatcher`` (``submit``/``drain``/``stats``), so the front end is
a plain ``SentimentServer`` with this object in the batcher seat.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import threading
import time
from typing import Any, Dict, List, Optional

from music_analyst_tpu.observability import watchdog
from music_analyst_tpu.resilience.failover import should_failover
from music_analyst_tpu.resilience.faults import fault_point
from music_analyst_tpu.resilience.policy import RetryPolicy, classify_retryable
from music_analyst_tpu.serving.batcher import (
    _RETRY_AFTER_CAP_MS,
    DEFAULT_TENANT,
    ServeRequest,
    resolve_max_queue,
    resolve_priority,
    resolve_replicas,
    resolve_tenant_budget,
    resolve_tp,
    resolve_ttft_slo_ms,
)
from music_analyst_tpu.observability.metrics_plane import (
    configure_metrics,
    get_metrics_plane,
)
from music_analyst_tpu.serving.response_cache import (
    ResponseCache,
    backend_fingerprint,
    checkpoint_stamp,
    resolve_response_cache_dir,
    try_answer,
)
from music_analyst_tpu.serving.slo import FairQueue, RateMeter, TokenBucket
from music_analyst_tpu.telemetry import get_telemetry
from music_analyst_tpu.telemetry.reqtrace import (
    configure_reqtrace,
    get_reqtrace,
)

# Ops the router will forward; anything else is a bad_request at the edge
# (control ops never reach here — the front server answers them itself).
_FORWARD_OPS = ("sentiment", "wordcount", "generate")

# How long to wait for a spawned worker's socket + first ping.  Workers
# compile their warmup ladder before listening, so this is generous; a
# worker that cannot come up inside it is killed and reported.
_SPAWN_TIMEOUT_S = 120.0


_LAST_ROUTER: Optional["ReplicaRouter"] = None


def router_stats() -> Dict[str, Any]:
    """Stats of the most recent router in this process ({} if none)."""
    router = _LAST_ROUTER
    return router.stats() if router is not None else {}


def _is_transport(exc: BaseException) -> bool:
    """Failures that indict the replica's transport, not the request."""
    return isinstance(exc, (OSError, EOFError))


class ReplicaHandle:
    """One worker server: its process, socket, and in-flight table.

    ``proc`` is None for externally-managed workers (tests connect the
    router to servers they started themselves); health tracking and
    requeue work the same either way.
    """

    def __init__(self, name: str, socket_path: str,
                 proc: Optional[subprocess.Popen] = None,
                 cmd: Optional[List[str]] = None) -> None:
        self.name = name
        self.socket_path = socket_path
        self.proc = proc
        # The argv that started ``proc`` — what supervised respawn
        # relaunches.  None (externally-managed worker) disables respawn
        # for this handle.
        self.cmd = list(cmd) if cmd is not None else None
        self.health = "starting"
        self.dispatched = 0
        self.requeues = 0
        self.respawns = 0
        self.last_stats: Optional[Dict[str, Any]] = None
        self._sock = None
        self._wfile = None
        self._reader: Optional[threading.Thread] = None
        self._lock = threading.Lock()
        # wire id → (original id, ServeRequest); None req marks a poll.
        self._pending: Dict[int, Any] = {}
        self._on_lost = None     # set by the router at adoption
        self._on_reply = None    # ditto: per-settled-reply bookkeeping

    # ---------------------------------------------------------- lifecycle

    def connect(self, timeout_s: float = _SPAWN_TIMEOUT_S) -> None:
        """Wait for the worker's socket, connect, and start the reader."""
        import socket as socketlib

        deadline = time.monotonic() + timeout_s
        last_exc: Optional[BaseException] = None
        while time.monotonic() < deadline:
            if self.proc is not None and self.proc.poll() is not None:
                raise RuntimeError(
                    f"replica {self.name} exited rc={self.proc.returncode} "
                    "before its socket came up"
                )
            if os.path.exists(self.socket_path):
                sock = socketlib.socket(
                    socketlib.AF_UNIX, socketlib.SOCK_STREAM
                )
                try:
                    sock.connect(self.socket_path)
                except OSError as exc:
                    last_exc = exc
                    sock.close()
                else:
                    self._sock = sock
                    self._wfile = sock.makefile("w", encoding="utf-8")
                    self._reader = threading.Thread(
                        target=self._read_loop,
                        args=(sock.makefile("r", encoding="utf-8"),),
                        name=f"router-read-{self.name}",
                        daemon=True,
                    )
                    self._reader.start()
                    self.health = "healthy"
                    return
            time.sleep(0.05)
        raise RuntimeError(
            f"replica {self.name} not reachable at {self.socket_path} "
            f"after {timeout_s:.0f}s"
            + (f" ({last_exc})" if last_exc else "")
        )

    def alive(self) -> bool:
        return self.proc is None or self.proc.poll() is None

    def close(self) -> None:
        with self._lock:
            wfile, sock = self._wfile, self._sock
            self._wfile = self._sock = None
        for closable in (wfile, sock):
            try:
                if closable is not None:
                    closable.close()
            except OSError:
                pass

    # ------------------------------------------------------------- wire

    def send(self, wire_id: int, payload: Dict[str, Any],
             entry: Any) -> None:
        """Register ``entry`` under ``wire_id`` and write one request line.

        Registration happens first so a reply can never race its own
        pending record; on a write failure the record is withdrawn and the
        transport error propagates to the dispatcher."""
        with self._lock:
            wfile = self._wfile
            if wfile is None:
                raise ConnectionError(
                    f"replica {self.name} has no live connection"
                )
            self._pending[wire_id] = entry
            try:
                wfile.write(json.dumps(payload) + "\n")
                wfile.flush()
            except Exception:
                self._pending.pop(wire_id, None)
                raise

    def in_flight(self) -> int:
        with self._lock:
            return sum(
                1 for entry in self._pending.values() if entry[1] is not None
            )

    def take_pending(self) -> List[Any]:
        """Drain the in-flight table (replica lost): the unanswered
        requests, for the router to requeue."""
        with self._lock:
            entries = [
                entry for entry in self._pending.values()
                if entry[1] is not None
            ]
            self._pending.clear()
        return entries

    def _read_loop(self, rfile) -> None:
        try:
            for line in rfile:
                line = line.strip()
                if not line:
                    continue
                try:
                    payload = json.loads(line)
                except ValueError:
                    continue
                with self._lock:
                    entry = self._pending.pop(payload.get("id"), None)
                if entry is None:
                    continue
                original_id, req = entry
                if req is None:  # stats poll reply
                    self.last_stats = payload.get("stats")
                    # The poll doubles as the fleet metrics scrape: the
                    # plane keeps a per-replica series and merges the
                    # fresh ones (observability/metrics_plane.py).
                    plane = get_metrics_plane()
                    if plane.enabled:
                        plane.ingest_replica(self.name, self.last_stats)
                    continue
                payload["id"] = original_id
                rt = get_reqtrace()
                if rt.enabled:
                    # The worker answered: close the cross-process phase
                    # (its own record details what happened over there).
                    rt.advance(req, "downstream", replica=self.name)
                req.complete(payload)
                on_reply = self._on_reply
                if on_reply is not None:
                    on_reply(req, bool(payload.get("ok")))
        except (OSError, ValueError):
            pass
        finally:
            on_lost = self._on_lost
            if on_lost is not None:
                on_lost(self)

    # ----------------------------------------------------------- teardown

    def terminate(self, grace_s: float = 10.0) -> None:
        """SIGTERM the worker (its graceful drain), SIGKILL a straggler."""
        self.close()
        proc = self.proc
        if proc is None or proc.poll() is not None:
            return
        try:
            proc.terminate()
            proc.wait(timeout=grace_s)
        except subprocess.TimeoutExpired:
            proc.kill()
            try:
                proc.wait(timeout=5.0)
            except subprocess.TimeoutExpired:
                pass
        except OSError:
            pass

    def snapshot(self) -> Dict[str, Any]:
        return {
            "socket": self.socket_path,
            "health": self.health,
            "alive": self.alive(),
            "dispatched": self.dispatched,
            "requeues": self.requeues,
            "respawns": self.respawns,
            "in_flight": self.in_flight(),
            "last_stats": self.last_stats,
        }


class _RouterDecode:
    """Adapter putting the router in a ``SentimentServer``'s decode seat:
    ``generate`` requests forward to a replica (whose own scheduler hosts
    the decode runtime) instead of running in the router process."""

    def __init__(self, router: "ReplicaRouter") -> None:
        self._router = router

    def submit(self, rid: Any, text: str,
               max_new_tokens: Optional[int] = None,
               tenant: Optional[str] = None,
               priority: Optional[int] = None,
               deadline_ms: Optional[float] = None) -> ServeRequest:
        meta = (
            {"max_new_tokens": int(max_new_tokens)}
            if max_new_tokens is not None else {}
        )
        return self._router.submit(rid, "generate", text, meta=meta,
                                   tenant=tenant, priority=priority,
                                   deadline_ms=deadline_ms)

    def drain(self, timeout: Optional[float] = None) -> None:
        pass  # the router's own drain covers the fleet

    def stats(self) -> Dict[str, Any]:
        return {"forwarded": True}


class ReplicaRouter:
    """Join-shortest-queue dispatch with health-aware failover."""

    def __init__(
        self,
        replicas: List[ReplicaHandle],
        max_queue: Optional[int] = None,
        poll_interval_s: float = 0.25,
        redispatch_limit: int = 3,
        respawn: bool = True,
        respawn_backoff_s: float = 0.5,
        respawn_cap_s: float = 30.0,
        ttft_slo_ms: Optional[float] = None,
        tenant_budget: Optional[float] = None,
        priority: Optional[int] = None,
        response_cache=None,
    ) -> None:
        if not replicas:
            raise ValueError("router needs at least one replica")
        # Cross-request response cache (serving/response_cache.py),
        # consulted in submit() BEFORE the shed ladder and tenant
        # metering — a hit never reaches a replica; None leaves every
        # request on the forward path.
        self.response_cache = response_cache
        self.replicas = list(replicas)
        self.max_queue = resolve_max_queue(max_queue)
        self.poll_interval_s = float(poll_interval_s)
        self.redispatch_limit = int(redispatch_limit)
        self.respawn = bool(respawn)
        self.respawn_backoff_s = float(respawn_backoff_s)
        self.respawn_cap_s = float(respawn_cap_s)
        self.ttft_slo_ms = resolve_ttft_slo_ms(ttft_slo_ms)
        self.tenant_budget = resolve_tenant_budget(tenant_budget)
        self.default_priority = resolve_priority(priority)
        self._retry = RetryPolicy(base_s=0.05, cap_s=1.0)
        self._cond = threading.Condition()
        self._queue = FairQueue()
        self._buckets: Dict[str, TokenBucket] = {}
        self._draining = False
        self._threads: List[threading.Thread] = []
        self._wire_ids = 0
        self._stats_lock = threading.Lock()
        self._stats: Dict[str, Any] = {
            "admitted": 0, "shed": 0, "completed": 0, "failed": 0,
            "bad_request": 0, "dispatched": 0, "requeued": 0,
            "queue_depth_max": 0, "retry_after_ms_last": None,
            "respawns": 0, "respawn_failures": 0, "cache_hits": 0,
            "shed_queue_full": 0, "shed_slo_unattainable": 0,
            "shed_tenant_budget": 0, "shed_evicted": 0,
        }
        self._tenants: Dict[str, Dict[str, int]] = {}
        self._transitions: List[Dict[str, Any]] = []
        # Rolling-window rates (serving/slo.py RateMeter) for live
        # ``stats`` polls — fleet req/s and shed/s without client deltas.
        self._rates = {"req_s": RateMeter(), "shed_s": RateMeter()}
        self._started_mono = time.monotonic()
        # Per-replica respawn backoff: name -> [not_before_t, backoff_s].
        self._respawn_state: Dict[str, List[float]] = {}
        for handle in self.replicas:
            handle._on_lost = self._replica_lost
            handle._on_reply = self._reply_settled
        global _LAST_ROUTER
        _LAST_ROUTER = self

    # ----------------------------------------------------------- lifecycle

    def start(self) -> "ReplicaRouter":
        if not self._threads:
            for target, name in (
                (self._dispatch_loop, "router-dispatch"),
                (self._poll_loop, "router-poll"),
            ):
                thread = threading.Thread(target=target, name=name,
                                          daemon=True)
                thread.start()
                self._threads.append(thread)
        return self

    def drain(self, timeout: Optional[float] = 30.0) -> None:
        """Stop admission, settle every queued/in-flight request, then
        gracefully stop the fleet (each worker runs its own drain)."""
        with self._cond:
            self._draining = True
            self._cond.notify_all()
        deadline = time.monotonic() + (timeout or 30.0)
        while time.monotonic() < deadline:
            with self._cond:
                queued = len(self._queue)
            in_flight = sum(h.in_flight() for h in self.replicas)
            if queued == 0 and in_flight == 0:
                break
            time.sleep(0.02)
        for handle in self.replicas:
            for req_entry in handle.take_pending():
                _, req = req_entry
                if req is not None and not req.done:
                    req.fail("draining", "router drained before the "
                                         "replica answered")
        for handle in self.replicas:
            handle.terminate()
        for thread in self._threads:
            thread.join(timeout=2.0)
        self._threads = []

    @property
    def draining(self) -> bool:
        return self._draining

    # ----------------------------------------------------------- admission

    def submit(self, rid: Any, op: str, text: str,
               meta: Optional[Dict[str, Any]] = None,
               tenant: Optional[str] = None,
               priority: Optional[int] = None,
               deadline_ms: Optional[float] = None) -> ServeRequest:
        """Admit (or shed) one request; mirrors ``DynamicBatcher.submit``
        so a ``SentimentServer`` can sit directly in front — including
        the SLO shed ladder (per-tenant token bucket, deadline-aware
        ``slo_unattainable``, priority-aware eviction), so one greedy
        tenant sheds at its own budget instead of the whole fleet's."""
        tel = get_telemetry()
        if deadline_ms is None and self.ttft_slo_ms > 0.0:
            deadline_ms = self.ttft_slo_ms
        req = ServeRequest(
            rid, op, text, meta=meta,
            tenant=tenant or DEFAULT_TENANT,
            priority=(
                self.default_priority if priority is None else int(priority)
            ),
            deadline_ms=deadline_ms,
        )
        # Trace attach BEFORE the shed ladder: sheds carry trace ids too.
        get_reqtrace().begin_request(req)
        if op not in _FORWARD_OPS:
            req.fail("bad_request",
                     f"unknown op {op!r}; have: {sorted(_FORWARD_OPS)}")
            self._bump(bad_request=1)
            return req
        # Response cache BEFORE the shed ladder and the tenant meter: a
        # repeat of a settled request is answered at the router front —
        # no replica hop, no token-bucket charge — and a repeat that
        # would shed queue_full/slo_unattainable is answered instead.
        budget = req.meta.get("max_new_tokens")
        if try_answer(self.response_cache, req,
                      budget=None if budget is None else int(budget)):
            self._bump(cache_hits=1)
            self._rates["req_s"].mark()
            tel.count("router.cache_hits")
            return req
        with self._cond:
            if self._draining:
                req.fail("draining", "router is draining; not admitting")
                self._shed(req, None, None)
                return req
            if self.tenant_budget > 0.0:
                bucket = self._buckets.get(req.tenant)
                if bucket is None:
                    bucket = self._buckets[req.tenant] = TokenBucket(
                        self.tenant_budget
                    )
                if not bucket.take():
                    hint_ms = max(
                        bucket.retry_after_ms(), self.retry_after_ms(1)
                    )
                    req.fail(
                        "queue_full",
                        f"tenant {req.tenant!r} over its admission budget "
                        f"({self.tenant_budget:g} req/s); retry after "
                        f"{hint_ms:.0f} ms",
                        retry_after_ms=hint_ms,
                    )
                    self._shed(req, "shed_tenant_budget", hint_ms)
                    return req
            if req.deadline_ms is not None and req.deadline_ms > 0.0:
                est_ms = self._drain_estimate_ms(req.priority)
                if est_ms is not None and est_ms > req.deadline_ms:
                    hint_ms = self.retry_after_ms(len(self._queue))
                    req.fail(
                        "slo_unattainable",
                        f"drain estimate {est_ms:.0f} ms already exceeds "
                        f"the {req.deadline_ms:.0f} ms deadline; retry "
                        f"after {hint_ms:.0f} ms",
                        retry_after_ms=hint_ms,
                        estimate_ms=round(est_ms, 3),
                    )
                    self._shed(req, "shed_slo_unattainable", hint_ms)
                    return req
            depth = len(self._queue)
            if depth >= self.max_queue:
                victim = self._queue.shed_candidate(req.tenant, req.priority)
                hint_ms = self.retry_after_ms(depth)
                if victim is None:
                    req.fail(
                        "queue_full",
                        f"router queue full ({depth}/{self.max_queue}); "
                        f"retry after {hint_ms:.0f} ms",
                        retry_after_ms=hint_ms,
                    )
                    self._shed(req, "shed_queue_full", hint_ms)
                    return req
                victim.fail(
                    "queue_full",
                    f"evicted for a priority-{req.priority} admit with "
                    f"the router queue full ({depth}/{self.max_queue}); "
                    f"retry after {hint_ms:.0f} ms",
                    retry_after_ms=hint_ms,
                )
                self._shed(victim, "shed_evicted", hint_ms)
            self._queue.append(req)
            depth = len(self._queue)
            self._cond.notify_all()
        with self._stats_lock:
            self._stats["admitted"] += 1
            self._tenant_ledger(req.tenant)["admitted"] += 1
            if depth > self._stats["queue_depth_max"]:
                self._stats["queue_depth_max"] = depth
        self._rates["req_s"].mark()
        tel.count("router.admitted")
        tel.gauge("router.queue_depth", depth)
        return req

    def _tenant_ledger(self, tenant: str) -> Dict[str, int]:
        """Caller holds ``_stats_lock``."""
        ledger = self._tenants.get(tenant)
        if ledger is None:
            ledger = self._tenants[tenant] = {
                "admitted": 0, "completed": 0, "shed": 0,
            }
        return ledger

    def _shed(self, req: ServeRequest, kind_stat: Optional[str],
              hint_ms: Optional[float]) -> None:
        with self._stats_lock:
            self._stats["shed"] += 1
            if kind_stat in self._stats:
                self._stats[kind_stat] += 1
            if hint_ms is not None:
                self._stats["retry_after_ms_last"] = hint_ms
            self._tenant_ledger(req.tenant)["shed"] += 1
        self._rates["shed_s"].mark()
        get_telemetry().count("router.shed")

    def _settle_rate(self) -> float:
        """Fleet-wide settle throughput (requests/s since start)."""
        with self._stats_lock:
            settled = self._stats["completed"] + self._stats["failed"]
        elapsed = max(time.monotonic() - self._started_mono, 1e-6)
        return settled / elapsed if settled else 0.0

    def _drain_estimate_ms(self, priority: int) -> Optional[float]:
        """Time until a newcomer at ``priority`` would dispatch (caller
        holds cond); None before the first settle."""
        rate = self._settle_rate()
        if rate <= 0.0:
            return None
        return self._queue.depth_ahead(priority) / rate * 1000.0

    def retry_after_ms(self, depth: Optional[int] = None) -> float:
        """Backoff hint for a shed client (the batcher's formula over the
        fleet-wide settle rate)."""
        if depth is None:
            with self._cond:
                depth = len(self._queue)
        rate = self._settle_rate()
        hint = depth / rate * 1000.0 if rate > 0.0 else 50.0 * max(depth, 1)
        return round(min(max(hint, 1.0), _RETRY_AFTER_CAP_MS), 3)

    def _bump(self, **deltas: int) -> None:
        with self._stats_lock:
            for key, n in deltas.items():
                self._stats[key] += n

    # ------------------------------------------------------------ dispatch

    def _pick(self, excluded: set) -> Optional[ReplicaHandle]:
        """Healthy replica with the shortest queue: router-side in-flight
        first (exact), the replica's last-polled queue depth as the tie
        break (the ``stats()`` feed)."""
        best = None
        best_key = None
        for handle in self.replicas:
            if handle.health != "healthy" or handle.name in excluded:
                continue
            polled = 0
            stats = handle.last_stats
            if isinstance(stats, dict):
                requests = stats.get("requests", {})
                polled = int(requests.get("queue_depth_max", 0) or 0)
            key = (handle.in_flight(), polled)
            if best_key is None or key < best_key:
                best, best_key = handle, key
        return best

    def _wire_payload(self, wire_id: int, req: ServeRequest) -> Dict[str, Any]:
        payload = {"id": wire_id, "op": req.op, "text": req.text}
        budget = req.meta.get("max_new_tokens")
        if budget is not None:
            payload["max_new_tokens"] = budget
        # Forward the SLO identity so the worker's own scheduler sees the
        # same tenant/priority the router queued under.  The deadline is
        # NOT forwarded: the router already spent (and accounted for) the
        # queue wait; re-arming it downstream would double-count.
        if req.tenant != DEFAULT_TENANT:
            payload["tenant"] = req.tenant
        if req.priority != self.default_priority:
            payload["priority"] = req.priority
        # Trace continuation downstream: the worker adopts the trace id
        # and names the router's span as its parent (absent when tracing
        # is off — ndjson/v1 unchanged).
        trace = req.meta.get("trace")
        if trace is not None:
            payload["trace"] = {"id": trace["id"], "span": trace["span"]}
        return payload

    def _send_once(self, handle: ReplicaHandle, req: ServeRequest) -> None:
        fault_point("router.dispatch", replica=handle.name, op=req.op)
        with self._cond:
            self._wire_ids += 1
            wire_id = self._wire_ids
        handle.send(wire_id, self._wire_payload(wire_id, req), (req.id, req))

    def _dispatch_one(self, req: ServeRequest) -> None:
        tel = get_telemetry()
        excluded: set = set()
        while not req.done:
            handle = self._pick(excluded)
            if handle is None:
                req.fail(
                    "replica_lost",
                    "no healthy replica available (router_stall); "
                    "all workers are unhealthy or excluded",
                )
                self._bump(failed=1)
                tel.count("router.replica_lost")
                return
            try:
                # A wedged worker hangs the send/flush edge silently —
                # the watchdog names that router_stall; transient faults
                # (injected router.dispatch, a mid-write hiccup) retry in
                # place against the same replica first.
                with watchdog.watch("router.dispatch", kind="router"):
                    self._retry.call(
                        self._send_once, handle, req,
                        site="router.dispatch",
                    )
            except Exception as exc:  # noqa: BLE001 — failover boundary
                retryable, kind = classify_retryable(exc)
                if _is_transport(exc) or should_failover(exc):
                    # The replica, not the request: mark it, requeue its
                    # other in-flight work, and re-dispatch here to the
                    # next-shortest healthy queue.
                    self._mark_lost(
                        handle, kind or "tunnel_dead",
                        f"dispatch failed: {type(exc).__name__}: {exc}",
                    )
                    excluded.add(handle.name)
                    continue
                req.fail("request_failed",
                         f"{type(exc).__name__}: {exc}"[:300])
                self._bump(failed=1)
                return
            handle.dispatched += 1
            self._bump(dispatched=1)
            rt = get_reqtrace()
            if rt.enabled:
                # The router-side wait ends at the downstream write; the
                # worker's reply closes the ``downstream`` phase.
                rt.advance(req, "queue", replica=handle.name,
                           hops=req.meta.get("router_attempts", 0))
            tel.count("router.dispatched")
            return

    def _dispatch_loop(self) -> None:
        while True:
            with self._cond:
                while not self._queue:
                    if self._draining:
                        return
                    self._cond.wait(0.05)
                req = self._queue.popleft()
            if req is None or req.done:  # shed/settled while queued
                continue
            self._dispatch_one(req)
            watchdog.beat("router.dispatch")

    # -------------------------------------------------------------- health

    def _record_transition(self, handle: ReplicaHandle, new: str,
                           kind: str, reason: str) -> None:
        transition = {
            "replica": handle.name,
            "from": handle.health,
            "to": new,
            "kind": kind,
            "reason": reason[:200],
            "t_s": round(time.monotonic() - self._started_mono, 3),
        }
        handle.health = new
        with self._stats_lock:
            self._transitions.append(transition)
        tel = get_telemetry()
        tel.count("router.health_transitions")
        tel.event("router_health", **transition)

    def _replica_lost(self, handle: ReplicaHandle) -> None:
        """Reader-thread callback: the replica's connection died."""
        if self._draining or handle.health in ("unhealthy", "dead"):
            return
        self._mark_lost(handle, "tunnel_dead", "connection lost")

    def _mark_lost(self, handle: ReplicaHandle, kind: str,
                   reason: str) -> None:
        if handle.health in ("unhealthy", "dead"):
            return
        new = "unhealthy" if handle.alive() else "dead"
        self._record_transition(handle, new, kind, reason)
        # A lost replica cannot be scraped: freeze its series as stale
        # so the fleet merge stops counting its last numbers as live.
        plane = get_metrics_plane()
        if plane.enabled:
            plane.mark_replica_stale(handle.name)
        handle.close()
        pending = handle.take_pending()
        if not pending:
            return
        requeued = 0
        for original_id, req in pending:
            if req is None or req.done:
                continue
            attempts = req.meta.get("router_attempts", 0) + 1
            req.meta["router_attempts"] = attempts
            # Per-request hop trail: every replica that lost this request,
            # with the loss kind — the terminal error below replays the
            # request's whole journey instead of naming only the last hop.
            hops = req.meta.setdefault("router_hops", [])
            hops.append({"replica": handle.name, "kind": kind})
            rt = get_reqtrace()
            if rt.enabled:
                # The hop that died: requeued traces always flush.
                rt.advance(req, "hop.requeue", replica=handle.name,
                           kind=kind, hops=attempts)
                rt.keep(req, "requeued")
            if attempts > self.redispatch_limit:
                hint_ms = self.retry_after_ms()
                req.fail(
                    "replica_lost",
                    f"replica {handle.name} lost ({kind}) and the request "
                    f"exceeded {self.redispatch_limit} re-dispatches",
                    hops=attempts,
                    hop_trail=list(hops),
                    retry_after_ms=hint_ms,
                )
                self._bump(failed=1)
                continue
            with self._cond:
                # Head of its tenant queue: a re-dispatched request has
                # already waited one full replica lifetime.
                self._queue.requeue(req)
                self._cond.notify_all()
            requeued += 1
        handle.requeues += requeued
        self._bump(requeued=requeued)
        get_telemetry().count("router.requeued", requeued)

    def _poll_loop(self) -> None:
        """Per-replica ``stats`` polling: feeds the JSQ tie break, acts as
        a liveness probe, and notices worker death even when no request
        is in flight to trip on it."""
        while True:
            with self._cond:
                if self._draining:
                    return
            for handle in self.replicas:
                if handle.health == "healthy":
                    if not handle.alive():
                        self._mark_lost(handle, "tunnel_dead",
                                        "worker process exited")
                        continue
                    try:
                        with self._cond:
                            self._wire_ids += 1
                            wire_id = self._wire_ids
                        handle.send(
                            wire_id, {"id": wire_id, "op": "stats"},
                            (wire_id, None),
                        )
                    except Exception as exc:  # noqa: BLE001
                        _, kind = classify_retryable(exc)
                        self._mark_lost(handle, kind or "tunnel_dead",
                                        f"stats poll failed: {exc}")
                elif handle.health == "unhealthy" and handle.alive():
                    # The process survived a transport blip: one reconnect
                    # attempt per poll tick brings it back into rotation.
                    try:
                        handle.connect(timeout_s=0.5)
                    except Exception:
                        if not handle.alive():
                            self._record_transition(
                                handle, "dead", "tunnel_dead",
                                "worker process exited during reconnect",
                            )
                    else:
                        self._record_transition(
                            handle, "healthy", "recovered", "reconnected"
                        )
                elif handle.health == "unhealthy" and not handle.alive():
                    self._record_transition(
                        handle, "dead", "tunnel_dead",
                        "worker process exited",
                    )
                elif handle.health == "dead":
                    self._maybe_respawn(handle)
            time.sleep(self.poll_interval_s)

    def _maybe_respawn(self, handle: ReplicaHandle) -> None:
        """Supervised restart of a dead worker, gated by a capped
        exponential backoff so a crash-looping worker cannot monopolize
        the poll thread.  Success re-enters the handle into rotation with
        a ``respawned`` health transition; failure doubles the backoff
        and counts ``respawn_failures``.  Externally-managed workers
        (no spawn cmd) and a draining router never respawn."""
        if not self.respawn or handle.cmd is None or self._draining:
            return
        state = self._respawn_state.setdefault(
            handle.name, [0.0, self.respawn_backoff_s]
        )
        if time.monotonic() < state[0]:
            return
        handle.close()
        try:
            os.unlink(handle.socket_path)
        except OSError:
            pass
        try:
            handle.proc = subprocess.Popen(
                handle.cmd,
                stdin=subprocess.DEVNULL,
                stdout=subprocess.DEVNULL,
                stderr=subprocess.DEVNULL,
                start_new_session=True,
            )
            handle.connect()
        except Exception as exc:  # noqa: BLE001 — backoff and retry
            handle.terminate(grace_s=1.0)  # reap a half-started process
            state[0] = time.monotonic() + state[1]
            state[1] = min(state[1] * 2.0, self.respawn_cap_s)
            self._bump(respawn_failures=1)
            get_telemetry().count("router.respawn_failures")
            get_telemetry().event(
                "router_respawn_failed", replica=handle.name,
                error=str(exc)[:200],
                next_backoff_s=round(state[1], 3),
            )
            return
        state[0] = 0.0
        state[1] = self.respawn_backoff_s
        handle.respawns += 1
        self._bump(respawns=1)
        get_telemetry().count("router.respawns")
        self._record_transition(
            handle, "healthy", "respawned",
            f"respawned as pid {handle.proc.pid}",
        )

    # ------------------------------------------------------------ readouts

    def _reply_settled(self, req: ServeRequest, ok: bool) -> None:
        """Per-reply bookkeeping (called from each handle's reader
        thread); feeds the settle rate behind ``retry_after_ms`` and the
        per-tenant ledger."""
        with self._stats_lock:
            self._stats["completed" if ok else "failed"] += 1
            if ok:
                self._tenant_ledger(req.tenant)["completed"] += 1

    def stats(self) -> Dict[str, Any]:
        """JSON-able snapshot for the manifest's ``serving.router``
        section: per-replica dispatch counts, health transitions,
        requeues/respawns, and the admission counters."""
        with self._stats_lock:
            out: Dict[str, Any] = dict(self._stats)
            transitions = list(self._transitions)
        out.update(
            replica_count=len(self.replicas),
            healthy_count=sum(
                1 for h in self.replicas if h.health == "healthy"
            ),
            max_queue=self.max_queue,
            settle_rate_req_s=round(self._settle_rate(), 3),
            rates={
                "window_s": self._rates["req_s"].tau_s,
                "req_s": self._rates["req_s"].rate(),
                "shed_s": self._rates["shed_s"].rate(),
            },
            health_transitions=transitions,
            replicas={h.name: h.snapshot() for h in self.replicas},
        )
        if self.response_cache is not None:
            out["response_cache"] = self.response_cache.stats()
        return out

    def slo_snapshot(self) -> Dict[str, Any]:
        """The manifest's ``serving.slo`` contribution when the router is
        the admission edge; empty when neither configured nor
        exercised."""
        with self._stats_lock:
            tenants = {t: dict(v) for t, v in self._tenants.items()}
            sheds = {
                key: self._stats[key]
                for key in ("shed_queue_full", "shed_slo_unattainable",
                            "shed_tenant_budget", "shed_evicted")
            }
        configured = self.ttft_slo_ms > 0.0 or self.tenant_budget > 0.0
        exercised = (
            any(sheds.values())
            or any(t != DEFAULT_TENANT for t in tenants)
        )
        if not configured and not exercised:
            return {}
        return {
            "ttft_slo_ms": self.ttft_slo_ms,
            "tenant_budget_req_s": self.tenant_budget,
            "default_priority": self.default_priority,
            "sheds": sheds,
            "tenants": tenants,
        }


# ----------------------------------------------------------------- CLI glue


def _replica_cmd(
    socket_path: str,
    model: str,
    mock: bool,
    weight_quant: Optional[str],
    tp: int,
    max_batch: Optional[int],
    max_wait_ms: Optional[float],
    max_queue: Optional[int],
    slots: Optional[int],
    prefill_chunk: Optional[int],
    max_new_tokens: int,
    page_size: Optional[int],
    kv_pages: Optional[int],
    warmup: bool,
    kv_quant: Optional[str] = None,
    speculate_k: Optional[int] = None,
    ttft_slo_ms: Optional[float] = None,
    tpot_slo_ms: Optional[float] = None,
    tenant_budget: Optional[float] = None,
    priority: Optional[int] = None,
    journal_dir: Optional[str] = None,
    trace_sample: Optional[float] = None,
    metrics_interval_ms: Optional[float] = None,
    response_cache_dir: Optional[str] = None,
    use_response_cache: bool = True,
) -> List[str]:
    cmd = [
        sys.executable, "-m", "music_analyst_tpu", "serve",
        "--socket", socket_path, "--quiet", "--no-telemetry",
        "--model", model, "--max-new-tokens", str(int(max_new_tokens)),
    ]
    if mock:
        cmd.append("--mock")
    if weight_quant:
        cmd += ["--weight-quant", weight_quant]
    if tp > 1:
        cmd += ["--tp", str(int(tp))]
    for flag, value in (
        ("--max-batch", max_batch),
        ("--max-wait-ms", max_wait_ms),
        ("--max-queue", max_queue),
        ("--slots", slots),
        ("--prefill-chunk", prefill_chunk),
        ("--page-size", page_size),
        ("--kv-pages", kv_pages),
        ("--kv-quant", kv_quant),
        ("--speculate-k", speculate_k),
        ("--ttft-slo-ms", ttft_slo_ms),
        ("--tpot-slo-ms", tpot_slo_ms),
        ("--tenant-budget", tenant_budget),
        ("--priority", priority),
        ("--journal-dir", journal_dir),
        # Workers inherit $MUSICAAL_TRACE_DIR from the router's
        # configure_reqtrace; the explicit sample keeps the fleet's
        # head-sampling decision identical even if the env is scrubbed.
        ("--trace-sample", trace_sample),
        # Same belt-and-braces for the metrics plane: workers inherit
        # $MUSICAAL_METRICS_* from configure_metrics, the explicit flag
        # survives a scrubbed environment.
        ("--metrics-interval-ms", metrics_interval_ms),
        # Workers keep their own edge caches; an explicit dir flows
        # through so the fleet shares one on-disk tier across replicas
        # (content-addressed entries make concurrent publishers safe).
        ("--response-cache-dir", response_cache_dir),
    ):
        if value is not None:
            cmd += [flag, str(value)]
    if not warmup:
        cmd.append("--no-warmup")
    if not use_response_cache:
        cmd.append("--no-response-cache")
    return cmd


def spawn_replicas(
    n: int,
    base_dir: str,
    *,
    model: str = "mock",
    mock: bool = False,
    weight_quant: Optional[str] = None,
    tp: int = 1,
    max_batch: Optional[int] = None,
    max_wait_ms: Optional[float] = None,
    max_queue: Optional[int] = None,
    slots: Optional[int] = None,
    prefill_chunk: Optional[int] = None,
    max_new_tokens: int = 16,
    page_size: Optional[int] = None,
    kv_pages: Optional[int] = None,
    kv_quant: Optional[str] = None,
    speculate_k: Optional[int] = None,
    warmup: bool = True,
    connect: bool = True,
    ttft_slo_ms: Optional[float] = None,
    tpot_slo_ms: Optional[float] = None,
    tenant_budget: Optional[float] = None,
    priority: Optional[int] = None,
    journal_dir: Optional[str] = None,
    trace_sample: Optional[float] = None,
    metrics_interval_ms: Optional[float] = None,
    response_cache_dir: Optional[str] = None,
    use_response_cache: bool = True,
) -> List[ReplicaHandle]:
    """Start ``n`` worker server processes and (optionally) connect.

    Workers inherit the parent environment (so ``MUSICAAL_*`` and the
    CPU-emulation ``XLA_FLAGS`` flow through) and run with telemetry off
    — fleet-level stats live in the router's manifest section.  Each
    handle keeps its spawn cmd, so the router's supervised respawn can
    relaunch a dead worker in place.

    With ``journal_dir`` set, each worker gets its own subdirectory
    (``replica-<i>/``) passed explicitly on its command line — the
    explicit flag outranks any inherited ``MUSICAAL_SERVE_JOURNAL``, so
    replicas never share (and corrupt) one journal, and a supervised
    respawn relaunches the same cmd, pointing the new process at the
    dead one's journal to replay its unanswered requests.
    """
    handles: List[ReplicaHandle] = []
    try:
        for i in range(n):
            socket_path = os.path.join(base_dir, f"replica-{i}.sock")
            replica_journal = None
            if journal_dir:
                replica_journal = os.path.join(journal_dir, f"replica-{i}")
            cmd = _replica_cmd(
                socket_path, model, mock, weight_quant, tp, max_batch,
                max_wait_ms, max_queue, slots, prefill_chunk,
                max_new_tokens, page_size, kv_pages, warmup,
                kv_quant=kv_quant, speculate_k=speculate_k,
                ttft_slo_ms=ttft_slo_ms, tpot_slo_ms=tpot_slo_ms,
                tenant_budget=tenant_budget, priority=priority,
                journal_dir=replica_journal,
                trace_sample=trace_sample,
                metrics_interval_ms=metrics_interval_ms,
                response_cache_dir=response_cache_dir,
                use_response_cache=use_response_cache,
            )
            proc = subprocess.Popen(
                cmd,
                stdin=subprocess.DEVNULL,
                stdout=subprocess.DEVNULL,
                stderr=subprocess.DEVNULL,
                start_new_session=True,
            )
            handles.append(
                ReplicaHandle(f"replica-{i}", socket_path, proc=proc,
                              cmd=cmd)
            )
        if connect:
            for handle in handles:
                handle.connect()
    except Exception:
        for handle in handles:
            handle.terminate(grace_s=2.0)
        raise
    return handles


def run_router(
    model: str = "mock",
    mock: bool = False,
    weight_quant: Optional[str] = None,
    stdio: bool = False,
    socket_path: Optional[str] = None,
    replicas: Optional[int] = None,
    tp: Optional[int] = None,
    max_batch: Optional[int] = None,
    max_wait_ms: Optional[float] = None,
    max_queue: Optional[int] = None,
    warmup: bool = True,
    quiet: bool = False,
    slots: Optional[int] = None,
    prefill_chunk: Optional[int] = None,
    max_new_tokens: int = 16,
    page_size: Optional[int] = None,
    kv_pages: Optional[int] = None,
    kv_quant: Optional[str] = None,
    speculate_k: Optional[int] = None,
    ttft_slo_ms: Optional[float] = None,
    tpot_slo_ms: Optional[float] = None,
    tenant_budget: Optional[float] = None,
    priority: Optional[int] = None,
    journal_dir: Optional[str] = None,
    trace_sample: Optional[Any] = None,
    trace_dir: Optional[str] = None,
    metrics_interval_ms: Optional[Any] = None,
    response_cache_dir: Optional[str] = None,
    use_response_cache: bool = True,
) -> int:
    """``serve --replicas N`` (N > 1): spawn the fleet, route until
    drained.  The front end is a stock ``SentimentServer`` with the
    router in the batcher seat, so the wire protocol, reply ordering,
    and graceful-drain semantics are identical to a single server."""
    import signal
    import tempfile

    from music_analyst_tpu.serving.server import SentimentServer

    from music_analyst_tpu.serving.journal import resolve_journal_dir

    tel = get_telemetry()
    n = resolve_replicas(replicas)
    tp_width = resolve_tp(tp)
    # Resolve here (flag beats $MUSICAAL_SERVE_JOURNAL) so the fleet gets
    # per-replica subdirectories; workers inherit the env, and without an
    # explicit per-worker flag they would all journal into the same dir.
    journal_base = resolve_journal_dir(journal_dir)
    # Configure tracing BEFORE the fleet spawns: configure_reqtrace
    # exports the resolved dir/sample to the environment, which is how
    # workers (spawned without --profile-dir) join the same trace files.
    reqtrace = configure_reqtrace(
        trace_sample, directory=trace_dir, role="router"
    )
    # Same ordering for the metrics plane: configure_metrics exports the
    # resolved interval/dir, so every worker samples its own series into
    # the shared metrics.jsonl while the router merges their stats polls.
    metrics = configure_metrics(
        metrics_interval_ms, directory=trace_dir, role="router"
    )
    with tel.run_scope("serve", None):
        with tempfile.TemporaryDirectory(prefix="musicaal-fleet-") as base:
            handles = spawn_replicas(
                n, base, model=model, mock=mock, weight_quant=weight_quant,
                tp=tp_width, max_batch=max_batch, max_wait_ms=max_wait_ms,
                max_queue=max_queue, slots=slots,
                prefill_chunk=prefill_chunk,
                max_new_tokens=max_new_tokens, page_size=page_size,
                kv_pages=kv_pages, kv_quant=kv_quant,
                speculate_k=speculate_k, warmup=warmup,
                ttft_slo_ms=ttft_slo_ms, tpot_slo_ms=tpot_slo_ms,
                tenant_budget=tenant_budget, priority=priority,
                journal_dir=journal_base,
                response_cache_dir=response_cache_dir,
                use_response_cache=use_response_cache,
                trace_sample=(
                    reqtrace.sample if reqtrace.enabled else None
                ),
                metrics_interval_ms=(
                    metrics.interval_ms if metrics.enabled else None
                ),
            )
            # Response cache at the router front: a hit never reaches a
            # replica, so it costs the fleet nothing.  The fingerprint
            # covers everything the front knows that changes reply bytes;
            # keys are disjoint from the replicas' own edge caches (their
            # fingerprints add backend identity), which is harmless --
            # each tier answers from what it has seen settle.
            rc_dir = resolve_response_cache_dir(
                response_cache_dir, use_response_cache
            )
            response_cache = None
            if rc_dir is not None:
                response_cache = ResponseCache(
                    rc_dir,
                    fingerprint=backend_fingerprint(
                        model=model,
                        mock=bool(mock),
                        weight_quant=weight_quant or "none",
                        kv_quant=kv_quant or "none",
                        max_new_tokens=int(max_new_tokens),
                        tp=tp_width,
                        checkpoint=checkpoint_stamp(),
                    ),
                )
            router = ReplicaRouter(
                handles, max_queue=max_queue, ttft_slo_ms=ttft_slo_ms,
                tenant_budget=tenant_budget, priority=priority,
                response_cache=response_cache,
            ).start()
            server = SentimentServer(
                router, mode="stdio" if stdio else "unix",
                decode=_RouterDecode(router), router=router,
            )
            if metrics.enabled:
                metrics.attach(
                    lambda: server.stats_snapshot(include_metrics=False)
                )
                metrics.start()
            tel.annotate(
                serve_mode=server.mode, router_replicas=n, router_tp=tp_width,
            )
            if journal_base:
                tel.annotate(journal_dir=journal_base)
            if rc_dir:
                tel.annotate(response_cache_dir=rc_dir)
            if not quiet:
                print(
                    f"serve: routing over {n} replica(s) (tp={tp_width})",
                    file=sys.stderr,
                )

            previous: Dict[int, Any] = {}

            def _on_signal(signum, frame) -> None:
                try:
                    name = signal.Signals(signum).name
                except ValueError:  # pragma: no cover
                    name = str(signum)
                server.request_drain(f"signal:{name}")

            for signum in (signal.SIGTERM, signal.SIGINT):
                try:
                    previous[signum] = signal.signal(signum, _on_signal)
                except (ValueError, OSError):  # non-main thread (tests)
                    pass
            try:
                if stdio:
                    server.handle_stream(sys.stdin, sys.stdout,
                                         drain_on_eof=True)
                else:
                    if not socket_path:
                        raise ValueError(
                            "serve: --socket PATH (or --stdio) is required"
                        )
                    server.serve_unix(socket_path)
            finally:
                server._drain_batcher()
                for signum, prev in previous.items():
                    try:
                        signal.signal(signum, prev)
                    except (ValueError, OSError):
                        pass
                metrics.close()
                reqtrace.close()
                stats = router.stats()
                tel.gauge("router.requests_total", stats["admitted"])
                tel.gauge("router.requeued_total", stats["requeued"])
                if not quiet:
                    print(
                        f"serve: router drained "
                        f"({server.drain_reason or 'eof'}): "
                        f"{stats['dispatched']} dispatched, "
                        f"{stats['requeued']} requeued, "
                        f"{len(stats['health_transitions'])} health "
                        f"transition(s)",
                        file=sys.stderr,
                    )
    return 0
