"""Durable request journal: write-ahead log for crash-consistent serving.

Every robustness layer before this PR ends at the process boundary — a
SIGKILL of a server loses every admitted-but-unanswered request, and a
router requeue after worker death can re-execute a request whose reply
was already computed.  This module is the recovery primitive under both:
an append-only, CRC-framed, fsync-batched journal of ``admitted`` and
``replied`` records, consulted on server start to

* **replay** admitted-but-unanswered requests (the restart answers them
  instead of silently forgetting them), and
* serve a bounded **reply-dedup index** so a re-dispatched request id
  returns the journaled reply instead of recomputing — exactly-once at
  the wire, at-most-once on the device.

Format (one segment = ``journal-<seq>.log``)::

    record := u32 length | u32 crc32(payload) | payload  (big-endian)
    payload := JSON: {"kind": "admitted", "id", "op", "text", "tenant",
                       "priority", "deadline_ms", "meta"}
             | JSON: {"kind": "replied", "id", "response"}

A torn tail (crash mid-``write``) or bit-rot fails the length/CRC check;
replay counts it (``corrupt_truncated``), abandons that segment's tail,
and carries on — corruption degrades to recompute, never to a wrong or
duplicate answer (ops are pure functions of their text, so recompute is
byte-identical; the chaos suite drills this at the ``journal.append``
fault site).

Durability protocol: ``admitted`` records batch (one fsync per
``sync_every`` appends); a ``replied`` record is fsync'd *before* the
reply line reaches the wire — group-committed, so replies settled in the
same batch share one fsync.  A reply the client saw is therefore always
deduplicable after a crash; a reply the journal lost was never sent, and
recomputing it is invisible.  Rotation seals the active segment at
``rotate_bytes``; compaction collapses sealed history into one fresh
segment holding only the live state (unanswered admits + the dedup
window) via the repo's tmp+rename pattern with real fsyncs
(``utils/atomic.py`` ``durable=True``).

A ``clean`` marker (written by :meth:`close` after final compaction,
removed on open) is the dirty bit: segments on disk without the marker
mean the previous process never ran its shutdown path — SIGKILL can
never write a flight record, so the journal is the witness the
``unclean_shutdown`` manifest stamp rides on.
"""

from __future__ import annotations

import json
import os
import struct
import threading
import zlib
from collections import OrderedDict
from typing import Any, Dict, List, Optional, Tuple

from music_analyst_tpu.resilience.faults import fault_point
from music_analyst_tpu.telemetry import get_telemetry
from music_analyst_tpu.utils.atomic import atomic_write, fsync_dir

_HEADER = struct.Struct(">II")
_SEGMENT_PREFIX = "journal-"
_SEGMENT_SUFFIX = ".log"
_CLEAN_MARKER = "clean"

# Defaults: one fsync per 8 admits keeps journal overhead inside the
# ≤10% serving-throughput budget; 4096 remembered replies bound the
# dedup index (a re-dispatched id older than that recomputes — pure ops
# make that correct, just not free); 1 MiB segments keep compaction
# cheap and the unclean-shutdown scan fast.
DEFAULT_SYNC_EVERY = 8
DEFAULT_DEDUP_LIMIT = 4096
DEFAULT_ROTATE_BYTES = 1 << 20


def resolve_journal_dir(value: Any = None) -> Optional[str]:
    """``--journal-dir`` wins; else ``$MUSICAAL_SERVE_JOURNAL``; else None
    (journaling off — the historical, non-durable behavior)."""
    if value is not None and str(value).strip():
        return str(value)
    env = os.environ.get("MUSICAAL_SERVE_JOURNAL", "").strip()
    return env or None


def _key(rid: Any) -> str:
    """Canonical index key for a wire id (any JSON value, not always
    hashable as-is)."""
    try:
        return json.dumps(rid, sort_keys=True, separators=(",", ":"))
    except (TypeError, ValueError):
        return repr(rid)


class RequestJournal:
    """One serving process's write-ahead request journal."""

    def __init__(
        self,
        directory: str,
        *,
        sync_every: int = DEFAULT_SYNC_EVERY,
        dedup_limit: int = DEFAULT_DEDUP_LIMIT,
        rotate_bytes: int = DEFAULT_ROTATE_BYTES,
    ) -> None:
        self.directory = os.path.abspath(directory)
        self.sync_every = max(int(sync_every), 1)
        self.dedup_limit = max(int(dedup_limit), 1)
        self.rotate_bytes = max(int(rotate_bytes), 4096)
        os.makedirs(self.directory, exist_ok=True)
        self._lock = threading.RLock()
        self._fh = None
        self._seq = 0
        self._unsynced = 0
        self._closed = False
        # id-key → reply payload, LRU-bounded (the dedup index).
        self._replies: "OrderedDict[str, Dict[str, Any]]" = OrderedDict()
        # id-key → admitted record, for ids not yet replied.
        self._open_admits: "OrderedDict[str, Dict[str, Any]]" = OrderedDict()
        self._stats: Dict[str, Any] = {
            "admitted": 0, "replied": 0, "syncs": 0, "rotations": 0,
            "compactions": 0, "replayed": 0, "deduped": 0,
            "corrupt_truncated": 0, "append_errors": 0,
            "unclean_start": False,
        }

    # ------------------------------------------------------------- segments

    def _segments(self) -> List[Tuple[int, str]]:
        out = []
        for name in os.listdir(self.directory):
            if (name.startswith(_SEGMENT_PREFIX)
                    and name.endswith(_SEGMENT_SUFFIX)):
                seq_text = name[len(_SEGMENT_PREFIX):-len(_SEGMENT_SUFFIX)]
                try:
                    out.append((int(seq_text), name))
                except ValueError:
                    continue
        return sorted(out)

    def _segment_path(self, seq: int) -> str:
        return os.path.join(
            self.directory, f"{_SEGMENT_PREFIX}{seq:08d}{_SEGMENT_SUFFIX}"
        )

    def _open_active(self, seq: int) -> None:
        self._seq = seq
        self._fh = open(self._segment_path(seq), "ab")

    # -------------------------------------------------------------- recover

    def recover(self) -> List[Dict[str, Any]]:
        """Scan the journal, rebuild the dedup index, and return the
        admitted-but-unanswered records (oldest first) for re-dispatch.

        Must be called exactly once, before the first append.  Detects
        the unclean-shutdown dirty bit (segments without the ``clean``
        marker) and removes the marker so *this* process's crash is
        detectable by the next one.
        """
        with self._lock:
            segments = self._segments()
            marker = os.path.join(self.directory, _CLEAN_MARKER)
            had_marker = os.path.exists(marker)
            if segments and not had_marker:
                self._stats["unclean_start"] = True
            if had_marker:
                try:
                    os.unlink(marker)
                except OSError:
                    pass
            for _, name in segments:
                self._scan_segment(os.path.join(self.directory, name))
            unanswered = list(self._open_admits.values())
            self._stats["replayed"] = len(unanswered)
            next_seq = (segments[-1][0] + 1) if segments else 0
            self._open_active(next_seq)
            if unanswered or self._stats["unclean_start"]:
                get_telemetry().event(
                    "journal_recovered",
                    replayed=len(unanswered),
                    corrupt_truncated=self._stats["corrupt_truncated"],
                    unclean=self._stats["unclean_start"],
                )
            return unanswered

    def _scan_segment(self, path: str) -> None:
        """Apply one segment's records; a torn/corrupt frame abandons the
        segment's tail (everything before it already applied)."""
        try:
            with open(path, "rb") as fh:
                data = fh.read()
        except OSError:
            self._stats["corrupt_truncated"] += 1
            return
        offset = 0
        total = len(data)
        while offset < total:
            if offset + _HEADER.size > total:
                self._stats["corrupt_truncated"] += 1
                return
            length, crc = _HEADER.unpack_from(data, offset)
            start = offset + _HEADER.size
            end = start + length
            if length > total - start:
                self._stats["corrupt_truncated"] += 1
                return
            payload = data[start:end]
            if zlib.crc32(payload) & 0xFFFFFFFF != crc:
                self._stats["corrupt_truncated"] += 1
                return
            try:
                record = json.loads(payload.decode("utf-8"))
            except (ValueError, UnicodeDecodeError):
                self._stats["corrupt_truncated"] += 1
                return
            self._apply(record)
            offset = end

    def _apply(self, record: Dict[str, Any]) -> None:
        kind = record.get("kind")
        key = _key(record.get("id"))
        if kind == "admitted":
            if key not in self._replies:
                self._open_admits[key] = record
        elif kind == "replied":
            self._open_admits.pop(key, None)
            self._remember(key, record.get("response") or {})

    def _remember(self, key: str, response: Dict[str, Any]) -> None:
        self._replies[key] = response
        self._replies.move_to_end(key)
        while len(self._replies) > self.dedup_limit:
            self._replies.popitem(last=False)

    # --------------------------------------------------------------- append

    def _append(self, record: Dict[str, Any]) -> bool:
        """Frame + buffer one record (caller holds the lock); False when
        the write failed — the server keeps serving, just un-journaled."""
        fault_point("journal.append", kind=record.get("kind"))
        payload = json.dumps(
            record, separators=(",", ":"), sort_keys=True
        ).encode("utf-8")
        frame = _HEADER.pack(len(payload), zlib.crc32(payload) & 0xFFFFFFFF)
        self._fh.write(frame + payload)
        return True

    def _sync(self) -> None:
        self._fh.flush()
        os.fsync(self._fh.fileno())
        self._unsynced = 0
        self._stats["syncs"] += 1

    def _maybe_rotate(self) -> None:
        if self._fh.tell() < self.rotate_bytes:
            return
        self._sync()
        self._fh.close()
        self._stats["rotations"] += 1
        self._open_active(self._seq + 1)
        # Collapse the sealed history so the directory stays two files
        # and restart replay stays O(live state), not O(all traffic).
        self._compact_locked()

    def record_admitted(self, rid: Any, op: str, text: str, *,
                        tenant: Optional[str] = None,
                        priority: Optional[int] = None,
                        deadline_ms: Optional[float] = None,
                        meta: Optional[Dict[str, Any]] = None) -> None:
        """Write-ahead the admission (batched fsync: one per
        ``sync_every`` admits).  ``None`` SLO fields journal as null so a
        replay re-submits with the server's own defaults."""
        if self._closed:
            return
        record = {
            "kind": "admitted", "id": rid, "op": op, "text": text,
            "tenant": tenant, "priority": priority,
            "deadline_ms": deadline_ms, "meta": dict(meta or {}),
        }
        with self._lock:
            if self._fh is None:
                raise RuntimeError("journal used before recover()")
            try:
                self._append(record)
                self._open_admits[_key(rid)] = record
                self._stats["admitted"] += 1
                self._unsynced += 1
                if self._unsynced >= self.sync_every:
                    self._sync()
                self._maybe_rotate()
            except Exception:  # noqa: BLE001 — journal must not kill serve
                self._stats["append_errors"] += 1

    def record_replied(self, rid: Any, response: Dict[str, Any], *,
                       sync: bool = True) -> None:
        """Journal the reply and fsync — called BEFORE the reply line is
        written to the wire, so a reply the client saw always survives
        into the dedup index.

        ``sync=False`` is the group-commit half: the caller appends a
        whole batch of settled replies, then calls :meth:`sync` ONCE
        before any of their lines reach the wire — the same durability
        barrier at a fraction of the fsync count."""
        if self._closed:
            return
        record = {"kind": "replied", "id": rid, "response": response}
        with self._lock:
            if self._fh is None:
                raise RuntimeError("journal used before recover()")
            try:
                self._append(record)
                self._stats["replied"] += 1
                if sync:
                    self._sync()
                else:
                    self._unsynced += 1
                self._maybe_rotate()
            except Exception:  # noqa: BLE001
                self._stats["append_errors"] += 1
            key = _key(rid)
            self._open_admits.pop(key, None)
            self._remember(key, response)

    def sync(self) -> None:
        """The group-commit barrier: fsync every appended-but-unsynced
        record.  A failure counts (``append_errors``) instead of raising —
        the server keeps serving, just un-durably."""
        with self._lock:
            if self._fh is None or self._closed:
                return
            try:
                self._sync()
            except Exception:  # noqa: BLE001
                self._stats["append_errors"] += 1

    # ---------------------------------------------------------------- dedup

    def lookup_reply(self, rid: Any) -> Optional[Dict[str, Any]]:
        """The journaled reply for a re-dispatched id, or None.  A hit is
        the exactly-once path: the wire answer replays, nothing
        recomputes."""
        with self._lock:
            response = self._replies.get(_key(rid))
            if response is not None:
                self._stats["deduped"] += 1
                get_telemetry().count("journal.deduped")
                return dict(response)
        return None

    def open_requests(self) -> int:
        with self._lock:
            return len(self._open_admits)

    # ----------------------------------------------------------- compaction

    def _compact_locked(self) -> None:
        """Rewrite live state (open admits + dedup window) into one fresh
        segment and drop every older one.  tmp+rename with real fsyncs:
        a crash at ANY point leaves either the old segments or old+new —
        both replay to the same state (records are idempotent upserts)."""
        old = self._segments()
        if self._fh is not None:
            self._sync()
            self._fh.close()
            self._fh = None
        new_seq = (old[-1][0] + 1) if old else self._seq + 1
        path = self._segment_path(new_seq)
        with atomic_write(path, mode="wb", encoding=None,
                          durable=True) as fh:
            for record in self._open_admits.values():
                payload = json.dumps(
                    record, separators=(",", ":"), sort_keys=True
                ).encode("utf-8")
                fh.write(_HEADER.pack(
                    len(payload), zlib.crc32(payload) & 0xFFFFFFFF
                ) + payload)
            for key, response in self._replies.items():
                try:
                    rid = json.loads(key)
                except ValueError:  # non-JSON id (programmatic caller)
                    continue
                record = {
                    "kind": "replied", "id": rid,
                    "response": response,
                }
                payload = json.dumps(
                    record, separators=(",", ":"), sort_keys=True
                ).encode("utf-8")
                fh.write(_HEADER.pack(
                    len(payload), zlib.crc32(payload) & 0xFFFFFFFF
                ) + payload)
        # The mid-compaction crash seam: the compacted segment is
        # published, the sealed history not yet dropped.
        fault_point("journal.compact", segments=len(old))
        for _, name in old:
            try:
                os.unlink(os.path.join(self.directory, name))
            except OSError:
                pass
        fsync_dir(self.directory)
        self._stats["compactions"] += 1
        self._open_active(new_seq + 1)

    def compact(self) -> None:
        with self._lock:
            if self._fh is None or self._closed:
                return
            try:
                self._compact_locked()
            except Exception:  # noqa: BLE001
                self._stats["append_errors"] += 1
                if self._fh is None:
                    self._open_active(self._seq + 1)

    # ---------------------------------------------------------------- close

    def close(self) -> None:
        """Graceful shutdown: final compaction + the ``clean`` marker.
        A SIGKILL never gets here — which is exactly how the next start
        knows."""
        with self._lock:
            if self._closed or self._fh is None:
                return
            try:
                self._compact_locked()
                self._fh.close()
            except Exception:  # noqa: BLE001
                pass
            self._fh = None
            self._closed = True
            try:
                marker = os.path.join(self.directory, _CLEAN_MARKER)
                with atomic_write(marker, durable=True) as fh:
                    fh.write("clean\n")
            except OSError:
                pass

    # ---------------------------------------------------------------- stats

    def stats(self) -> Dict[str, Any]:
        """The run manifest's ``serving.journal`` section."""
        with self._lock:
            out = dict(self._stats)
            out.update(
                directory=self.directory,
                sync_every=self.sync_every,
                dedup_limit=self.dedup_limit,
                open_requests=len(self._open_admits),
                dedup_index=len(self._replies),
            )
        return out
