"""Request-level response cache: content-addressed, cross-restart.

Sentiment, wordcount and greedy generation are pure functions of
(request text, op, generation budget, backend) — yet before this module
every repeat of a popular song re-ran the chip.  The in-flight dedup
tiers (batcher row folding, decode slot folding) only collapse
*simultaneous* identical requests; at catalog scale most repeats arrive
seconds or days apart.  This cache memoizes settled replies across
requests AND across restarts, and is consulted at every admission edge
*before* tenant metering and the shed ladder, so a hit costs one hash +
one dict/file probe, is never charged to token buckets or WFQ, never
occupies a queue slot, and never bills engine-ledger chip-seconds.

Design (the proven ``data/corpus_cache.py`` / ``engines/wq_cache.py``
pattern, applied to replies):

* **Content-addressed key** — BLAKE2b over (schema version, normalized
  text, op, generation budget, backend fingerprint).  The fingerprint
  folds in model family, checkpoint identity, weight-quant and kv-quant
  scheme and any output-relevant config, so a cache shared between
  configurations can never serve a reply computed by a different
  backend.
* **Two tiers** — a bounded in-memory LRU front (``OrderedDict``) for
  the steady-state hit path, and an on-disk tier (one JSON file per
  entry, CRC32-guarded) that survives restarts: a rebooted server warms
  from the catalog its predecessor computed.
* **Atomic publish** — entries are staged as ``<key>.tmp-<pid>-<uuid>``
  and published with one ``os.rename``; concurrent writers race
  benignly (first rename wins, losers discard).
* **Corruption-tolerant, never-fail** — a truncated/CRC-flipped entry
  counts ``corrupt``, is evicted, and reads as a miss so the caller
  recomputes; injected ``response_cache.read``/``response_cache.write``
  faults degrade to recompute the same way.  The cache can never fail a
  request and can never serve a wrong answer — only a recomputed one.
* **Byte-identity** — the stored payload is the settled reply minus its
  ``id`` (insertion order preserved), so a hit rebuilt as
  ``{"id": ...} + payload`` is byte-for-byte what the compute path
  would have written.  The ``cached`` stamp rides in stats and the
  request trace, never in the reply payload.
* **LRU byte-bounded disk tier** — ``max_bytes`` caps the on-disk
  footprint; eviction drops oldest-access entries first (reads touch
  mtime, so a hot catalog survives).

Request identity is :func:`normalize_text` — whitespace runs collapsed,
ends stripped — shared with the in-flight dedup tiers so all
repeat-detection layers agree on what "identical request" means.  For
the whitespace-delimited ASCII tokenizers (sentiment/wordcount) the
collapse is provably output-invariant; for generate it is the serving
layer's declared identity contract: whitespace variants fold onto one
canonical compute, exactly as the decode slot-folding tier does.

Resolution: explicit ``cache_dir`` (``--response-cache-dir``) wins,
then ``$MUSICAAL_RESPONSE_CACHE`` (a directory, or ``0``/``off`` to
disable), then ``~/.cache/musicaal_responses``.  ``--no-response-cache``
/ ``use_cache=False`` opts out.  Stats land in the run manifest's
``serving.response_cache`` section and the metrics plane's series.

Host-side only: no jax imports (importable before the test harness pins
``JAX_PLATFORMS``), no device work on any path.
"""

from __future__ import annotations

import hashlib
import json
import os
import threading
import time
import uuid
import zlib
from collections import OrderedDict
from typing import Any, Dict, Optional

from music_analyst_tpu.resilience.faults import fault_point
from music_analyst_tpu.serving.slo import RateMeter
from music_analyst_tpu.telemetry import get_telemetry
from music_analyst_tpu.telemetry.reqtrace import get_reqtrace

SCHEMA_VERSION = 1

# Ops whose replies are pure functions of (text, budget, backend) and
# therefore safe to memoize.  Control/introspection ops (ping, stats,
# drain) never reach an admission edge; anything not listed here passes
# through uncached.
CACHEABLE_OPS = frozenset({"sentiment", "wordcount", "generate"})

# Process-lifetime aggregate (mirrored into telemetry counters as they
# happen) — the chaos/bench suites and tests read this without a server
# handle; per-instance counters live on ResponseCache.
_STATS_LOCK = threading.Lock()
_STATS: Dict[str, int] = {
    "lookups": 0,
    "hits": 0,
    "mem_hits": 0,
    "disk_hits": 0,
    "misses": 0,
    "stores": 0,
    "corrupt": 0,
    "evictions": 0,
    "read_fallbacks": 0,
    "write_errors": 0,
}


def _bump(name: str, n: int = 1) -> None:
    with _STATS_LOCK:
        _STATS[name] += n
    try:
        get_telemetry().count(f"response_cache.{name}", n)
    except Exception:
        pass


# Pre-rendered telemetry names for the hit path's counter burst — the
# hit path is the whole point of the cache, so its bookkeeping stays
# O(µs): one lock pass, no string formatting.
_MEM_HIT_NAMES = ("lookups", "hits", "mem_hits")
_MEM_HIT_TEL = tuple(f"response_cache.{n}" for n in _MEM_HIT_NAMES)


def _bump_mem_hit() -> None:
    with _STATS_LOCK:
        for name in _MEM_HIT_NAMES:
            _STATS[name] += 1
    try:
        tel = get_telemetry()
        for name in _MEM_HIT_TEL:
            tel.count(name)
    except Exception:
        pass


def cache_stats() -> Dict[str, int]:
    """Process-wide lookup/hit/store/corrupt/eviction aggregate."""
    with _STATS_LOCK:
        return dict(_STATS)


def reset_cache_stats() -> None:
    """Zero the process-wide aggregate (test/bench isolation)."""
    with _STATS_LOCK:
        for k in _STATS:
            _STATS[k] = 0


def resolve_response_cache_dir(
    cache_dir: Optional[str] = None, use_cache: Optional[bool] = None
) -> Optional[str]:
    """The directory to cache replies under, or ``None`` when off.

    ``use_cache=False`` (the ``--no-response-cache`` flag) always wins;
    then an explicit ``cache_dir`` (``--response-cache-dir``), then
    ``$MUSICAAL_RESPONSE_CACHE`` (``0``/``off``/``false`` disables),
    then the user-level default next to the corpus cache.
    """
    if use_cache is False:
        return None
    if cache_dir:
        return cache_dir
    env = os.environ.get("MUSICAAL_RESPONSE_CACHE", "").strip()
    if env.lower() in ("0", "off", "false", "no"):
        return None
    if env:
        return env
    return os.path.expanduser("~/.cache/musicaal_responses")


def normalize_text(text: str) -> str:
    """Canonical request-identity form shared by every repeat-detection
    tier (in-batch dedup, decode slot folding, this cache): whitespace
    runs collapse to one space, ends strip.  Provably token-invariant
    for the whitespace-delimited ASCII tokenizers; the declared identity
    contract for generate prompts (variants fold onto one compute)."""
    return " ".join(text.split())


def backend_fingerprint(**parts: Any) -> str:
    """Canonical fingerprint string from backend identity parts.

    ``None`` values drop out (absent ≠ empty), everything else is
    stringified and key-sorted, so two servers agree on the fingerprint
    iff they agree on every output-relevant knob they set.
    """
    kept = sorted(
        (k, str(v)) for k, v in parts.items() if v is not None
    )
    return ";".join(f"{k}={v}" for k, v in kept)


def checkpoint_stamp() -> Optional[str]:
    """Identity stamp for the real-weight checkpoints the ``MUSICAAL_*``
    env vars point at: path + size + mtime per configured artifact (a
    swapped checkpoint at the same path re-keys the cache without
    hashing gigabytes on startup).  ``None`` when no real weights are
    configured — the mock/synthetic backends are fully described by the
    model-name part of the fingerprint."""
    parts = []
    for var in (
        "MUSICAAL_LLAMA_CKPT",
        "MUSICAAL_LLAMA_TOKENIZER",
        "MUSICAAL_DISTILBERT_CKPT",
        "MUSICAAL_BERT_VOCAB",
    ):
        val = os.environ.get(var, "").strip()
        if not val:
            continue
        try:
            st = os.stat(val)
            parts.append(f"{var}:{val}:{st.st_size}:{int(st.st_mtime)}")
        except OSError:
            parts.append(f"{var}:{val}")
    return ";".join(parts) or None


def response_key(
    text: str, op: str, budget: Optional[int], fingerprint: str
) -> str:
    """Content-addressed entry name for one (request, backend) pair.

    The hash material is a flat ``\\x1f``-joined record with the
    normalized text LAST: every other field is fixed-format (version,
    op name, integer budget, server-controlled fingerprint), so with
    the prefix fixed the key is injective in the text — no framing
    needed, and no JSON encoder on the hot hit path."""
    material = (
        f"{SCHEMA_VERSION}\x1f{op}\x1f{budget}\x1f{fingerprint}\x1f"
        f"{normalize_text(text)}"
    )
    digest = hashlib.blake2b(
        material.encode("utf-8", errors="surrogatepass"), digest_size=16
    )
    return f"v{SCHEMA_VERSION}-{op}-{digest.hexdigest()}"


def _payload_crc(payload: Dict[str, Any]) -> int:
    blob = json.dumps(
        payload, separators=(",", ":"), sort_keys=True
    ).encode("utf-8", errors="surrogatepass")
    return zlib.crc32(blob) & 0xFFFFFFFF


class ResponseCache:
    """Two-tier (memory LRU + disk) content-addressed reply store.

    ``cache_dir=None`` disables the disk tier (memory-only: still folds
    repeats within one process, nothing survives a restart).  All
    methods are thread-safe and never raise — the cache is an
    optimization, not a dependency.
    """

    def __init__(
        self,
        cache_dir: Optional[str] = None,
        fingerprint: str = "",
        mem_entries: int = 4096,
        max_bytes: int = 64 << 20,
    ) -> None:
        self.cache_dir = cache_dir
        self.fingerprint = fingerprint
        self.mem_entries = max(1, int(mem_entries))
        self.max_bytes = int(max_bytes)
        self._mem: "OrderedDict[str, Dict[str, Any]]" = OrderedDict()
        self._lock = threading.Lock()
        self.hit_meter = RateMeter()
        self.lookup_meter = RateMeter()
        self._stats: Dict[str, int] = {
            k: 0 for k in (
                "lookups", "hits", "mem_hits", "disk_hits", "misses",
                "stores", "corrupt", "evictions", "read_fallbacks",
                "write_errors", "bytes", "bytes_saved",
            )
        }

    # ------------------------------------------------------------- keys

    def key_for(self, op: str, text: str, budget: Optional[int] = None) -> str:
        return response_key(text, op, budget, self.fingerprint)

    def _count(self, name: str, n: int = 1) -> None:
        with self._lock:
            self._stats[name] += n
        if name in _STATS:
            _bump(name, n)

    # ----------------------------------------------------------- lookup

    def lookup(self, key: str) -> Optional[Dict[str, Any]]:
        """The stored reply payload (id-less, insertion-ordered) or
        ``None``.  Memory tier first; a disk hit is promoted.  Any read
        failure — injected fault, unreadable file, CRC/schema mismatch —
        degrades to a miss (corrupt entries are evicted first)."""
        self.lookup_meter.mark()
        with self._lock:
            cached = self._mem.get(key)
            if cached is not None:
                self._mem.move_to_end(key)
                self._stats["lookups"] += 1
                self._stats["hits"] += 1
                self._stats["mem_hits"] += 1
        if cached is not None:
            _bump_mem_hit()
            self.hit_meter.mark()
            return dict(cached)
        self._count("lookups")
        payload = self._disk_lookup(key)
        if payload is None:
            self._count("misses")
            return None
        self._mem_put(key, payload)
        self._count("hits")
        self._count("disk_hits")
        self.hit_meter.mark()
        return dict(payload)

    def _disk_lookup(self, key: str) -> Optional[Dict[str, Any]]:
        if not self.cache_dir:
            return None
        path = os.path.join(self.cache_dir, f"{key}.json")
        try:
            fault_point("response_cache.read", key=key)
            with open(path, "r", encoding="utf-8") as fh:
                raw = fh.read()
        except FileNotFoundError:
            return None
        except Exception:
            # Fault-injected or an I/O error: fall back to compute.  The
            # entry stays — a transient read may succeed next time; only
            # *structurally corrupt* entries are evicted below.
            self._count("read_fallbacks")
            return None
        try:
            record = json.loads(raw)
            if record.get("schema") != SCHEMA_VERSION:
                raise ValueError("stale schema")
            payload = record["payload"]
            if not isinstance(payload, dict) or not payload.get("ok"):
                raise ValueError("payload is not an ok reply")
            if int(record["crc"]) != _payload_crc(payload):
                raise ValueError("crc mismatch")
        except Exception:
            # Corrupt entries are evicted, never served: recompute is
            # the only way a wrong answer stays impossible.
            self._count("corrupt")
            try:
                os.unlink(path)
            except OSError:
                pass
            return None
        try:
            os.utime(path, None)  # LRU touch for byte-bounded eviction
        except OSError:
            pass
        return payload

    # ------------------------------------------------------------ store

    def _mem_put(self, key: str, payload: Dict[str, Any]) -> None:
        with self._lock:
            self._mem[key] = dict(payload)
            self._mem.move_to_end(key)
            while len(self._mem) > self.mem_entries:
                self._mem.popitem(last=False)

    def put(self, key: str, payload: Dict[str, Any]) -> bool:
        """Persist one settled reply payload; never raises.

        Only ``ok`` replies are cacheable (errors are circumstance, not
        content).  The ``id`` field is stripped — identity belongs to
        the request, not the answer.  Returns True when the entry is
        available (stored now or already present).
        """
        try:
            if not isinstance(payload, dict) or not payload.get("ok"):
                return False
            stored = {k: v for k, v in payload.items() if k != "id"}
            with self._lock:
                already = key in self._mem
            self._mem_put(key, stored)
            if already or not self.cache_dir:
                return True
            return self._disk_put(key, stored)
        except Exception:
            # Cache is an optimization only; never fail a settle over it.
            return False

    def _disk_put(self, key: str, stored: Dict[str, Any]) -> bool:
        final = os.path.join(self.cache_dir, f"{key}.json")
        if os.path.exists(final):
            return True
        try:
            os.makedirs(self.cache_dir, exist_ok=True)
            tmp = os.path.join(
                self.cache_dir,
                f"{key}.tmp-{os.getpid()}-{uuid.uuid4().hex[:8]}",
            )
            record = {
                "schema": SCHEMA_VERSION,
                "key": key,
                "crc": _payload_crc(stored),
                "payload": stored,
            }
            blob = json.dumps(record, separators=(",", ":"))
            fault_point("response_cache.write", key=key)
            with open(tmp, "w", encoding="utf-8") as fh:
                fh.write(blob)
            try:
                os.rename(tmp, final)
            except OSError:
                # Lost the publish race — the winner's entry is
                # equivalent (content-addressed), drop ours.
                try:
                    os.unlink(tmp)
                except OSError:
                    pass
                return os.path.exists(final)
            self._count("stores")
            self._count("bytes", len(blob))
            self._evict_over_budget()
            return True
        except Exception:
            self._count("write_errors")
            return False

    def _evict_over_budget(self) -> None:
        """Drop oldest-access entries until the disk tier fits
        ``max_bytes``.  Best-effort: races with concurrent evictors and
        readers are benign (unlink of a missing file is ignored)."""
        try:
            entries = []
            total = 0
            with os.scandir(self.cache_dir) as it:
                for ent in it:
                    if not ent.name.endswith(".json"):
                        continue
                    try:
                        st = ent.stat()
                    except OSError:
                        continue
                    entries.append((st.st_mtime, st.st_size, ent.path))
                    total += st.st_size
            if total <= self.max_bytes:
                return
            for _, size, path in sorted(entries):
                if total <= self.max_bytes:
                    break
                try:
                    os.unlink(path)
                except OSError:
                    continue
                total -= size
                self._count("evictions")
        except Exception:
            pass

    # ------------------------------------------------------------ stats

    def stats(self) -> Dict[str, Any]:
        """Manifest/metrics snapshot for this instance."""
        with self._lock:
            out: Dict[str, Any] = dict(self._stats)
            out["mem_entries"] = len(self._mem)
        lookups = out["lookups"]
        out["hit_rate"] = round(
            out["hits"] / lookups, 6) if lookups else 0.0
        # Average answers served per unique compute: how much repeat
        # traffic the catalog actually carries.
        stores = max(out["stores"], out["mem_entries"], 1)
        out["dedup_factor"] = round(
            (out["hits"] + stores) / stores, 6)
        out["hits_per_s"] = self.hit_meter.rate()
        out["lookups_per_s"] = self.lookup_meter.rate()
        return out


def try_answer(
    cache: Optional[ResponseCache],
    req: Any,
    budget: Optional[int] = None,
) -> bool:
    """Consult ``cache`` for ``req`` at an admission edge; returns True
    when the request was settled from cache.

    Runs *before* the shed ladder and tenant metering by contract: a
    hit is rebuilt as ``{"id": req.id} + stored payload`` (byte-for-byte
    the compute path's reply), stamped ``cached`` in ``req.meta`` (and
    the request trace) but never in the payload, and completed on the
    spot — no queue slot, no token-bucket charge, no chip-seconds.  On
    a miss the key is parked in ``req.meta`` so the settle path can
    populate the entry, and the request proceeds unchanged.
    """
    if cache is None or req.op not in CACHEABLE_OPS:
        return False
    try:
        key = cache.key_for(req.op, req.text, budget)
    except Exception:
        return False
    t0 = time.monotonic()
    payload = cache.lookup(key)
    t1 = time.monotonic()
    try:
        get_reqtrace().detail(
            req, "cache.lookup", t0, t1, hit=payload is not None
        )
    except Exception:
        pass
    if payload is None:
        req.meta["rcache"] = cache
        req.meta["rcache_key"] = key
        return False
    req.meta["cached"] = True
    reply = {"id": req.id}
    reply.update(payload)
    req.complete(reply)
    return True


def populate_from_settle(req: Any) -> None:
    """Settle-path hook: store a freshly computed ok reply under the key
    parked by :func:`try_answer`'s miss.  Called from
    ``ServeRequest.complete`` so every settle route (batch dispatch,
    decode slot, dedup fan-out, router read-loop) populates through ONE
    seam.  Never raises."""
    try:
        meta = req.meta
        if meta.get("cached"):
            return
        cache = meta.get("rcache")
        key = meta.get("rcache_key")
        if cache is None or not key:
            return
        payload = req.response
        if isinstance(payload, dict) and payload.get("ok"):
            cache.put(key, payload)
    except Exception:
        pass
