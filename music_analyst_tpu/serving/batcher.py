"""Deadline-aware dynamic batcher with bounded admission control.

The serving analogue of the engines' batch loop: requests arrive one at
a time (one NDJSON line each, ``serving/server.py``), but the device
wants big, shape-stable batches.  This module coalesces queued requests
into padded power-of-two bucket batches (``utils/shapes.round_pow2`` —
the same rounding rule the engines compile under, so a warm server never
meets a new shape), flushing a batch when it reaches ``max_batch`` OR
when its oldest request has waited ``max_wait_ms`` — the classic
latency/throughput dial (cf. TensorFlow Serving's dynamic batcher).

Admission is *bounded*: a full queue sheds the request with a structured
``queue_full`` error instead of blocking the reader — under overload the
server stays responsive and the client learns to back off (the
reference's one-HTTP-call-per-song loop simply falls behind forever).

Fault isolation: a batch that raises is retried one request at a time,
so a poison request fails alone (structured ``request_failed`` carrying
its id) and its batchmates still get answers; the server never dies with
the batch.

Overload is a *scheduled* state, not an error path (``serving/slo.py``):
requests carry a tenant, a priority class, and an optional deadline; each
op queue is a :class:`~music_analyst_tpu.serving.slo.FairQueue` (strict
priority classes, per-tenant weighted fair queueing inside a class), a
per-tenant :class:`~music_analyst_tpu.serving.slo.TokenBucket` meters
admission when ``--tenant-budget`` is set, a full queue evicts
lower-priority / over-represented work before shedding a newcomer, and a
request whose deadline the EWMA drain estimate already blows sheds with
``slo_unattainable`` instead of joining a queue it cannot survive.  Every
shed carries the ``retry_after_ms`` hint.

Everything is mirrored into telemetry (``serving.*`` counters, queue
depth / occupancy gauges, latency histograms with p50/p95/p99) and into
a local stats dict the run manifest's ``serving`` section snapshots.
"""

from __future__ import annotations

import math
import os
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from music_analyst_tpu.observability import watchdog
from music_analyst_tpu.resilience.failover import should_failover
from music_analyst_tpu.resilience.faults import fault_point
from music_analyst_tpu.resilience.policy import RetryPolicy
from music_analyst_tpu.serving.response_cache import (
    normalize_text,
    populate_from_settle,
    try_answer,
)
from music_analyst_tpu.serving.slo import FairQueue, RateMeter, TokenBucket
from music_analyst_tpu.telemetry import get_telemetry
from music_analyst_tpu.telemetry.core import Histogram
from music_analyst_tpu.telemetry.reqtrace import get_reqtrace
from music_analyst_tpu.utils.shapes import round_pow2

# Flag defaults; $MUSICAAL_SERVE_* overrides, explicit flags win
# (the watchdog-timeout resolution pattern).
DEFAULT_MAX_BATCH = 32
DEFAULT_MAX_WAIT_MS = 5.0
DEFAULT_MAX_QUEUE = 1024
# Continuous decode runtime (serving/decode_loop.py): slot count is
# rounded up to a power of two (fixed compiled shapes, like max_batch's
# pow2 padding); prefill chunk is the fixed token width one prefill
# dispatch writes.
DEFAULT_SLOTS = 8
DEFAULT_PREFILL_CHUNK = 64
# Paged KV cache (ops/kv_pages.py): tokens per physical page (pow2; 0
# selects the monolithic per-slot cache) and pool size in pages (0 =
# auto: n_slots * pages_per_slot, i.e. no oversubscription).
DEFAULT_PAGE_SIZE = 16
DEFAULT_KV_PAGES = 0
# KV-page quantization (ops/kv_pages.py): "none" stores pages at the
# compute dtype; "int8" stores per-(page, row) symmetric int8 codes plus
# f32 scales, dequantized inside the paged-attention kernel's KV-load
# epilogue.  Requires the paged backend (page_size > 0).
DEFAULT_KV_QUANT = "none"
KV_QUANT_CHOICES = ("none", "int8")
# Speculative decoding (serving/decode_loop.py): max draft tokens the
# host self-drafter proposes per slot per verify dispatch (0 = off,
# plain one-token-per-step decode).
DEFAULT_SPECULATE_K = 0
# Scale-out serving (serving/router.py): replica worker count behind the
# router, and tensor-parallel width within each worker's decode runtime.
DEFAULT_REPLICAS = 1
DEFAULT_TP = 1
# SLO/overload layer (serving/slo.py): TTFT/TPOT targets the scheduler
# acts on (0 disables — no preemption, no deadline shedding), per-tenant
# sustained admission budget in requests/second (0 = unmetered), and the
# priority class assigned to wire requests that don't carry one.
DEFAULT_TTFT_SLO_MS = 0.0
DEFAULT_TPOT_SLO_MS = 0.0
DEFAULT_TENANT_BUDGET = 0.0
DEFAULT_PRIORITY = 1
DEFAULT_TENANT = "default"
# Bounds on the ``retry_after_ms`` hint a queue_full shed carries: never
# tell a client to come back sooner than one flush deadline, never park
# it for more than half a minute on a stale rate estimate.
_RETRY_AFTER_CAP_MS = 30_000.0

# Occupancy lives in (0, 1]; the latency-shaped default buckets would
# put every observation in one bin.
_OCCUPANCY_BUCKETS = (0.125, 0.25, 0.375, 0.5, 0.625, 0.75, 0.875, 1.0)

# Request-latency buckets: sub-ms host ops up to multi-second cold paths.
_LATENCY_BUCKETS = (
    0.0005, 0.001, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 5.0, 30.0,
)


def _resolve(value: Any, env: str, default: float, *, integer: bool,
             minimum: float) -> float:
    """Explicit value wins and raises on malformed input (usage error);
    a malformed env var falls back to the default — serving config must
    never crash the server before it can answer a request."""
    if value is None:
        raw = os.environ.get(env, "").strip()
        if not raw:
            return default
        try:
            parsed = float(raw)
        except ValueError:
            return default
        if not math.isfinite(parsed) or parsed < minimum:
            return default
        return int(parsed) if integer else parsed
    try:
        parsed = float(value)
    except (TypeError, ValueError):
        raise ValueError(
            f"expected a number >= {minimum}, got {value!r}"
        ) from None
    if not math.isfinite(parsed) or parsed < minimum:
        raise ValueError(f"expected a number >= {minimum}, got {value!r}")
    return int(parsed) if integer else parsed


def resolve_max_batch(value: Any = None) -> int:
    return int(_resolve(value, "MUSICAAL_SERVE_MAX_BATCH",
                        DEFAULT_MAX_BATCH, integer=True, minimum=1))


def resolve_max_wait_ms(value: Any = None) -> float:
    return _resolve(value, "MUSICAAL_SERVE_MAX_WAIT_MS",
                    DEFAULT_MAX_WAIT_MS, integer=False, minimum=0.0)


def resolve_max_queue(value: Any = None) -> int:
    return int(_resolve(value, "MUSICAAL_SERVE_MAX_QUEUE",
                        DEFAULT_MAX_QUEUE, integer=True, minimum=1))


def resolve_slots(value: Any = None) -> int:
    """Decode slot count (``--slots`` / ``$MUSICAAL_SERVE_SLOTS``),
    rounded up to a power of two — the slot cache is a compiled shape."""
    return round_pow2(
        int(_resolve(value, "MUSICAAL_SERVE_SLOTS",
                     DEFAULT_SLOTS, integer=True, minimum=1)),
        1,
    )


def resolve_prefill_chunk(value: Any = None) -> int:
    """Prefill chunk width (``--prefill-chunk`` /
    ``$MUSICAAL_SERVE_PREFILL_CHUNK``)."""
    return int(_resolve(value, "MUSICAAL_SERVE_PREFILL_CHUNK",
                        DEFAULT_PREFILL_CHUNK, integer=True, minimum=1))


def resolve_page_size(value: Any = None) -> int:
    """KV page size in tokens (``--page-size`` /
    ``$MUSICAAL_SERVE_PAGE_SIZE``).

    Must be a power of two (page-gather shapes are compiled); ``0``
    selects the monolithic per-slot cache of ``ops/kv_slots.py``.  An
    explicit non-pow2 value raises (usage error); a non-pow2 env value
    falls back to the default, like every other malformed serve env var.
    """
    page = int(_resolve(value, "MUSICAAL_SERVE_PAGE_SIZE",
                        DEFAULT_PAGE_SIZE, integer=True, minimum=0))
    if page and (page & (page - 1)):
        if value is not None:
            raise ValueError(
                f"page size must be a power of two (or 0 for the "
                f"monolithic cache), got {value!r}"
            )
        return DEFAULT_PAGE_SIZE
    return page


def resolve_kv_quant(value: Any = None) -> str:
    """KV-page quantization scheme (``--kv-quant`` /
    ``$MUSICAAL_SERVE_KV_QUANT``): ``none`` or ``int8``.

    An explicit unknown scheme raises (usage error); an unknown env
    value falls back to the default, like every other malformed serve
    env var.
    """
    if value is None:
        raw = os.environ.get("MUSICAAL_SERVE_KV_QUANT", "").strip().lower()
        return raw if raw in KV_QUANT_CHOICES else DEFAULT_KV_QUANT
    scheme = str(value).strip().lower()
    if scheme not in KV_QUANT_CHOICES:
        raise ValueError(
            f"kv_quant must be one of {'/'.join(KV_QUANT_CHOICES)}, "
            f"got {value!r}"
        )
    return scheme


def resolve_speculate_k(value: Any = None) -> int:
    """Max drafted tokens per slot per verify dispatch
    (``--speculate-k`` / ``$MUSICAAL_SERVE_SPECULATE_K``).  ``0``
    disables speculation (one greedy token per decode step).  An
    explicit negative/malformed value raises (usage error); a malformed
    env value falls back to the default."""
    return int(_resolve(value, "MUSICAAL_SERVE_SPECULATE_K",
                        DEFAULT_SPECULATE_K, integer=True, minimum=0))


def resolve_replicas(value: Any = None) -> int:
    """Replica worker count (``--replicas`` /
    ``$MUSICAAL_SERVE_REPLICAS``).  1 serves in-process; > 1 puts the
    replica router (``serving/router.py``) in front of that many worker
    processes."""
    return int(_resolve(value, "MUSICAAL_SERVE_REPLICAS",
                        DEFAULT_REPLICAS, integer=True, minimum=1))


def resolve_tp(value: Any = None) -> int:
    """Tensor-parallel width for the decode runtime (``--tp`` /
    ``$MUSICAAL_SERVE_TP``).  1 keeps the single-chip layout; > 1 shards
    attention heads and the KV cache over a ``tp`` mesh axis
    (``parallel/sharding.DECODE_KV_RULES``)."""
    return int(_resolve(value, "MUSICAAL_SERVE_TP",
                        DEFAULT_TP, integer=True, minimum=1))


def resolve_ttft_slo_ms(value: Any = None) -> float:
    """Time-to-first-token target (``--ttft-slo-ms`` /
    ``$MUSICAAL_SERVE_SLO_TTFT_MS``).  0 disables SLO enforcement: no
    preemption, no deadline-derived shedding."""
    return _resolve(value, "MUSICAAL_SERVE_SLO_TTFT_MS",
                    DEFAULT_TTFT_SLO_MS, integer=False, minimum=0.0)


def resolve_tpot_slo_ms(value: Any = None) -> float:
    """Per-output-token latency target (``--tpot-slo-ms`` /
    ``$MUSICAAL_SERVE_SLO_TPOT_MS``).  0 disables the decode scheduler's
    admission throttle."""
    return _resolve(value, "MUSICAAL_SERVE_SLO_TPOT_MS",
                    DEFAULT_TPOT_SLO_MS, integer=False, minimum=0.0)


def resolve_tenant_budget(value: Any = None) -> float:
    """Per-tenant sustained admission budget in requests/second
    (``--tenant-budget`` / ``$MUSICAAL_SERVE_TENANT_BUDGET``).  0 leaves
    tenants unmetered (fair queueing still applies)."""
    return _resolve(value, "MUSICAAL_SERVE_TENANT_BUDGET",
                    DEFAULT_TENANT_BUDGET, integer=False, minimum=0.0)


def resolve_priority(value: Any = None) -> int:
    """Default priority class for requests that don't carry one
    (``--priority`` / ``$MUSICAAL_SERVE_PRIORITY``; higher serves
    first)."""
    return int(_resolve(value, "MUSICAAL_SERVE_PRIORITY",
                        DEFAULT_PRIORITY, integer=True, minimum=0))


def resolve_kv_pages(value: Any = None, n_slots: Optional[int] = None) -> int:
    """KV pool size in pages (``--kv-pages`` /
    ``$MUSICAAL_SERVE_KV_PAGES``).

    ``0`` means auto-size (one full sequence per slot, no
    oversubscription).  The pool must hold at least one page per slot:
    an explicit smaller value raises, a too-small env value falls back
    to auto.
    """
    pages = int(_resolve(value, "MUSICAAL_SERVE_KV_PAGES",
                         DEFAULT_KV_PAGES, integer=True, minimum=0))
    if pages and n_slots and pages < n_slots:
        if value is not None:
            raise ValueError(
                f"kv pages ({pages}) must cover at least one page per "
                f"slot ({n_slots} slots); pass 0 to auto-size"
            )
        return DEFAULT_KV_PAGES
    return pages


class ServeRequest:
    """One admitted (or immediately shed) request and its settled reply.

    The reply dict is the wire payload minus nothing — the server writes
    ``response`` verbatim as one NDJSON line, so ordering/identity live
    entirely in the ``id`` the client supplied.
    """

    __slots__ = ("id", "op", "text", "t_enqueue", "t_settle", "_done",
                 "response", "meta", "tenant", "priority", "deadline_ms")

    def __init__(self, rid: Any, op: str, text: str,
                 meta: Optional[Dict[str, Any]] = None,
                 tenant: str = DEFAULT_TENANT,
                 priority: int = DEFAULT_PRIORITY,
                 deadline_ms: Optional[float] = None) -> None:
        self.id = rid
        self.op = op
        self.text = text
        self.t_enqueue = time.monotonic()
        self.t_settle: Optional[float] = None
        self._done = threading.Event()
        self.response: Optional[Dict[str, Any]] = None
        # Per-request knobs outside the batch contract (e.g. the decode
        # loop's max_new_tokens budget); the dynamic batcher ignores it.
        self.meta: Dict[str, Any] = meta or {}
        # SLO/isolation identity (serving/slo.py): fair-queue tenant,
        # strict priority class (higher first), optional arrival-relative
        # deadline the admission estimate is checked against.
        self.tenant = tenant
        self.priority = int(priority)
        self.deadline_ms = deadline_ms

    def complete(self, payload: Dict[str, Any]) -> None:
        # ONE settle choke point across every path (succeed, each shed
        # kind, failures, router-relayed replies): the trace recorder
        # stamps the reply with the request's trace id and tail-keeps
        # failures here, so no settle path can dodge tracing.
        rt = get_reqtrace()
        if rt.enabled:
            rt.on_complete(self, payload)
        self.t_settle = time.monotonic()
        self.response = payload
        # Response-cache populate rides the same choke point: every
        # settle route (batch dispatch, decode slot, dedup fan-out,
        # router read-loop) stores a fresh ok reply through ONE seam —
        # before the waiter wakes, so a hit is visible the moment the
        # reply is.  No-op unless an admission edge parked a miss key.
        populate_from_settle(self)
        self._done.set()

    def succeed(self, **fields: Any) -> None:
        out: Dict[str, Any] = {"id": self.id, "ok": True, "op": self.op}
        out.update(fields)
        self.complete(out)

    def fail(self, kind: str, detail: str = "", **extra: Any) -> None:
        error: Dict[str, Any] = {"kind": kind, "detail": detail}
        error.update(extra)
        self.complete({
            "id": self.id,
            "ok": False,
            "op": self.op,
            "error": error,
        })

    @property
    def done(self) -> bool:
        return self._done.is_set()

    def wait(self, timeout: Optional[float] = None) -> bool:
        return self._done.wait(timeout)


class DynamicBatcher:
    """Coalesce queued requests into padded power-of-two batches.

    ``ops`` maps an op name to a batch function: ``fn(texts) -> [payload
    dict per row]`` (e.g. ``{"label": "Positive"}``).  Padding rows are
    empty strings — safe for every backend (empty lyric → Neutral is a
    golden contract) — and their results are discarded.
    """

    def __init__(
        self,
        ops: Dict[str, Callable[[List[str]], List[Dict[str, Any]]]],
        max_batch: Optional[int] = None,
        max_wait_ms: Optional[float] = None,
        max_queue: Optional[int] = None,
        name: str = "serve",
        failover: Optional[Callable[[BaseException], bool]] = None,
        ttft_slo_ms: Optional[float] = None,
        tenant_budget: Optional[float] = None,
        priority: Optional[int] = None,
        response_cache=None,
    ) -> None:
        self._ops = dict(ops)
        # Cross-request response cache (serving/response_cache.py),
        # consulted in submit() BEFORE the shed ladder and tenant
        # metering; None leaves every request on the compute path.
        self.response_cache = response_cache
        # Classified device loss during dispatch tries this hook ONCE per
        # batch (e.g. ModelResidency.reload) before the one-by-one
        # isolation fallback — the server survives a device death between
        # batches instead of failing every queued request.
        self._failover = failover
        # Transiently-classified dispatch failures (and injected
        # serving.dispatch faults) re-attempt in place before any
        # failover/isolation machinery runs.
        self._retry = RetryPolicy(base_s=0.05, cap_s=1.0)
        self.max_batch = resolve_max_batch(max_batch)
        self.max_wait_ms = resolve_max_wait_ms(max_wait_ms)
        self.max_queue = resolve_max_queue(max_queue)
        self.name = name
        self.ttft_slo_ms = resolve_ttft_slo_ms(ttft_slo_ms)
        self.tenant_budget = resolve_tenant_budget(tenant_budget)
        self.default_priority = resolve_priority(priority)
        self._queues: Dict[str, FairQueue] = {
            op: FairQueue() for op in self._ops
        }
        self._buckets: Dict[str, TokenBucket] = {}
        self._cond = threading.Condition()
        self._draining = False
        self._thread: Optional[threading.Thread] = None
        self._latency = Histogram(_LATENCY_BUCKETS)
        self._occupancy = Histogram(_OCCUPANCY_BUCKETS)
        self._stats_lock = threading.Lock()
        self._stats: Dict[str, Any] = {
            "admitted": 0, "shed": 0, "completed": 0, "failed": 0,
            "bad_request": 0, "batches": 0, "rows": 0, "padded_rows": 0,
            "queue_depth_max": 0, "isolation_retries": 0,
            "failover_reloads": 0, "dedup_folded": 0, "cache_hits": 0,
            "retry_after_ms_last": None,
            "shed_queue_full": 0, "shed_slo_unattainable": 0,
            "shed_tenant_budget": 0, "shed_evicted": 0,
        }
        # Per-tenant admission ledger (manifest ``serving.slo`` section).
        self._tenants: Dict[str, Dict[str, int]] = {}
        # EWMA of observed flush throughput (rows/s) — feeds the
        # ``retry_after_ms`` hint a queue_full shed carries.
        self._flush_rate = 0.0
        # Rolling-window rates (serving/slo.py RateMeter): what a live
        # ``stats`` poller reads without differencing cumulative counters.
        self._rates = {"req_s": RateMeter(), "shed_s": RateMeter()}

    # ----------------------------------------------------------- lifecycle

    def start(self) -> "DynamicBatcher":
        if self._thread is None or not self._thread.is_alive():
            self._thread = threading.Thread(
                target=self._loop, name=f"{self.name}-batcher", daemon=True
            )
            self._thread.start()
        return self

    def drain(self, timeout: Optional[float] = 30.0) -> None:
        """Stop admitting, flush every queued request, stop the worker.

        Queued requests are *answered* (processed, or failed with a
        structured error if the backend breaks) — never dropped silently;
        the graceful-SIGTERM contract rides on this.
        """
        with self._cond:
            self._draining = True
            self._cond.notify_all()
        thread = self._thread
        if thread is not None and thread.is_alive():
            thread.join(timeout=timeout)
        self._thread = None

    @property
    def draining(self) -> bool:
        return self._draining

    # ----------------------------------------------------------- admission

    def submit(self, rid: Any, op: str, text: str,
               tenant: Optional[str] = None,
               priority: Optional[int] = None,
               deadline_ms: Optional[float] = None) -> ServeRequest:
        """Admit (or shed) one request; always returns a ServeRequest —
        a shed one is already completed with its structured error.

        ``tenant``/``priority`` place the request in its fair queue;
        ``deadline_ms`` (arrival-relative; defaults to the configured
        TTFT SLO when one is set) arms deadline-aware shedding: a
        request whose drain estimate already blows its deadline sheds
        ``slo_unattainable`` instead of queueing to miss.
        """
        tel = get_telemetry()
        if deadline_ms is None and self.ttft_slo_ms > 0.0:
            deadline_ms = self.ttft_slo_ms
        req = ServeRequest(
            rid, op, text,
            tenant=tenant or DEFAULT_TENANT,
            priority=self.default_priority if priority is None else priority,
            deadline_ms=deadline_ms,
        )
        # Trace context BEFORE the shed ladder: sheds carry trace ids too
        # (and tail sampling keeps every shed's trace).
        get_reqtrace().begin_request(req)
        if op not in self._ops:
            req.fail(
                "bad_request",
                f"unknown op {op!r}; have: {sorted(self._ops)}",
            )
            self._bump(bad_request=1)
            return req
        # Response cache BEFORE the shed ladder and the tenant meter: a
        # repeat of a settled request is answered for ~a hash + lookup —
        # never queued, never charged to its tenant's token bucket, and
        # a repeat that would shed queue_full/slo_unattainable is
        # answered instead (a free answer beats a structured rejection).
        if try_answer(self.response_cache, req):
            self._bump(cache_hits=1)
            self._rates["req_s"].mark()
            tel.count("serving.cache_hits")
            return req
        with self._cond:
            if self._draining:
                req.fail("draining", "server is draining; not admitting")
                self._shed(req, "draining", None)
                return req
            # Per-tenant token bucket: the saturating tenant sheds at its
            # OWN budget while everyone else keeps admitting.
            if self.tenant_budget > 0.0:
                bucket = self._buckets.get(req.tenant)
                if bucket is None:
                    bucket = self._buckets[req.tenant] = TokenBucket(
                        self.tenant_budget
                    )
                if not bucket.take():
                    hint_ms = max(
                        bucket.retry_after_ms(), self.retry_after_ms(1)
                    )
                    req.fail(
                        "queue_full",
                        f"tenant {req.tenant!r} over its admission budget "
                        f"({self.tenant_budget:g} req/s); retry after "
                        f"{hint_ms:.0f} ms",
                        retry_after_ms=hint_ms,
                    )
                    self._shed(req, "shed_tenant_budget", hint_ms)
                    return req
            queue = self._queues[op]
            # Deadline check BEFORE capacity: a request the drain
            # estimate already dooms must not evict anyone.
            if req.deadline_ms is not None and req.deadline_ms > 0.0:
                est_ms = self._drain_estimate_ms(queue, req.priority)
                if est_ms is not None and est_ms > req.deadline_ms:
                    hint_ms = self.retry_after_ms()
                    req.fail(
                        "slo_unattainable",
                        f"drain estimate {est_ms:.0f} ms already exceeds "
                        f"the {req.deadline_ms:.0f} ms deadline; retry "
                        f"after {hint_ms:.0f} ms",
                        retry_after_ms=hint_ms,
                        estimate_ms=round(est_ms, 3),
                    )
                    self._shed(req, "shed_slo_unattainable", hint_ms)
                    return req
            depth = sum(len(q) for q in self._queues.values())
            if depth >= self.max_queue:
                # Priority-aware eviction: shed queued lower-priority /
                # over-represented work before the newcomer.
                victim = queue.shed_candidate(req.tenant, req.priority)
                hint_ms = self.retry_after_ms(depth)
                if victim is None:
                    req.fail(
                        "queue_full",
                        f"admission queue full ({depth}/{self.max_queue}); "
                        f"retry after {hint_ms:.0f} ms",
                        retry_after_ms=hint_ms,
                    )
                    self._shed(req, "shed_queue_full", hint_ms)
                    return req
                victim.fail(
                    "queue_full",
                    f"evicted for a priority-{req.priority} admit with the "
                    f"queue full ({depth}/{self.max_queue}); retry after "
                    f"{hint_ms:.0f} ms",
                    retry_after_ms=hint_ms,
                )
                self._shed(victim, "shed_evicted", hint_ms)
            queue.append(req)
            depth = sum(len(q) for q in self._queues.values())
            self._cond.notify_all()
        with self._stats_lock:
            self._stats["admitted"] += 1
            self._tenant_ledger(req.tenant)["admitted"] += 1
            if depth > self._stats["queue_depth_max"]:
                self._stats["queue_depth_max"] = depth
        self._rates["req_s"].mark()
        tel.count("serving.admitted")
        tel.gauge("serving.queue_depth", depth)
        return req

    def _tenant_ledger(self, tenant: str) -> Dict[str, int]:
        """Caller holds ``_stats_lock``."""
        ledger = self._tenants.get(tenant)
        if ledger is None:
            ledger = self._tenants[tenant] = {
                "admitted": 0, "completed": 0, "shed": 0,
            }
        return ledger

    def _shed(self, req: ServeRequest, kind_stat: Optional[str],
              hint_ms: Optional[float]) -> None:
        with self._stats_lock:
            self._stats["shed"] += 1
            if kind_stat in self._stats:
                self._stats[kind_stat] += 1
            if hint_ms is not None:
                self._stats["retry_after_ms_last"] = hint_ms
            self._tenant_ledger(req.tenant)["shed"] += 1
        self._rates["shed_s"].mark()
        get_telemetry().count("serving.shed")

    def _drain_estimate_ms(self, queue: FairQueue,
                           priority: int) -> Optional[float]:
        """EWMA time estimate until a newcomer at ``priority`` would
        dispatch (caller holds cond).  None before the first flush — no
        rate observation means no grounds to shed on."""
        rate = self._flush_rate
        if rate <= 0.0:
            return None
        ahead = queue.depth_ahead(priority)
        return ahead / rate * 1000.0 + max(self.max_wait_ms, 1.0)

    def _bump(self, **deltas: int) -> None:
        with self._stats_lock:
            for key, n in deltas.items():
                self._stats[key] += n

    def retry_after_ms(self, depth: Optional[int] = None) -> float:
        """Backoff hint for a shed client: the estimated time to drain the
        current queue at the observed flush rate (EWMA of rows/s over
        completed batches), floored at one flush deadline and capped so a
        stale estimate can't park clients for minutes.  Before the first
        flush there is no rate yet — fall back to the number of full
        batches queued times the flush deadline."""
        if depth is None:
            with self._cond:
                depth = sum(len(q) for q in self._queues.values())
        floor_ms = max(self.max_wait_ms, 1.0)
        rate = self._flush_rate
        if rate > 0.0:
            hint = depth / rate * 1000.0
        else:
            hint = (depth / self.max_batch) * floor_ms
        return round(min(max(hint, floor_ms), _RETRY_AFTER_CAP_MS), 3)

    # -------------------------------------------------------------- worker

    def _oldest_op(self) -> Optional[str]:
        """Op whose oldest queued request has waited longest (caller
        holds cond).  The flush deadline honors the oldest request even
        when the fair queue would dispatch a different one first."""
        best: Optional[Tuple[float, str]] = None
        for op, q in self._queues.items():
            oldest = q.head_wait_t()
            if oldest is not None and (best is None or oldest < best[0]):
                best = (oldest, op)
        return best[1] if best else None

    def _next_batch(self) -> Tuple[Optional[str], List[ServeRequest]]:
        """Block until a batch is due (full, deadline hit, or draining);
        ``(None, [])`` means drained-and-empty: the worker exits."""
        with self._cond:
            while True:
                op = self._oldest_op()
                if op is None:
                    if self._draining:
                        return None, []
                    self._cond.wait(0.05)
                    continue
                q = self._queues[op]
                waited_ms = (
                    time.monotonic() - q.head_wait_t()
                ) * 1000.0
                if (len(q) >= self.max_batch or self._draining
                        or waited_ms >= self.max_wait_ms):
                    batch = []
                    for _ in range(min(len(q), self.max_batch)):
                        picked = q.popleft()
                        if picked is not None:
                            batch.append(picked)
                    return op, batch
                remaining_s = (self.max_wait_ms - waited_ms) / 1000.0
                self._cond.wait(min(max(remaining_s, 0.001), 0.05))

    def _loop(self) -> None:
        tel = get_telemetry()
        while True:
            op, batch = self._next_batch()
            if op is None:
                return
            self._dispatch(op, batch)
            tel.gauge(
                "serving.queue_depth",
                sum(len(q) for q in self._queues.values()),
            )
            watchdog.beat("serve.dispatch")

    def _run_op(self, op: str, texts: List[str]) -> List[Dict[str, Any]]:
        fault_point("serving.dispatch", op=op, rows=len(texts))
        return self._ops[op](texts)

    def _maybe_failover(self, exc: BaseException) -> bool:
        """Try the failover hook on classified device loss; True = retry."""
        if self._failover is None or not should_failover(exc):
            return False
        tel = get_telemetry()
        try:
            reloaded = bool(self._failover(exc))
        except Exception as reload_exc:  # noqa: BLE001 — must not kill loop
            tel.event(
                "serving_failover_failed", error=str(reload_exc)[:200]
            )
            return False
        if reloaded:
            self._bump(failover_reloads=1)
            tel.count("serving.failover_reloads")
            tel.event("serving_failover", error=str(exc)[:200])
        return reloaded

    def _dispatch(
        self, op: str, batch: List[ServeRequest], allow_failover: bool = True
    ) -> None:
        tel = get_telemetry()
        n = len(batch)
        # In-batch dedup: identical request texts occupy ONE device row;
        # the row's result fans out to every requester.  Ops are pure
        # batch functions over texts (same text → same payload), so this
        # is invisible on the wire and free occupancy when a burst repeats
        # itself (the same song submitted by many clients at once).
        # Identity is normalize_text (shared with the decode-loop fold
        # and the response-cache key) so every repeat-detection tier
        # agrees on what "identical request" means; the first arrival's
        # raw text is what actually dispatches.
        row_of: Dict[str, int] = {}
        rows: List[int] = []
        uniques: List[str] = []
        for req in batch:
            row_key = normalize_text(req.text)
            idx = row_of.get(row_key)
            if idx is None:
                idx = len(uniques)
                row_of[row_key] = idx
                uniques.append(req.text)
            rows.append(idx)
        n_unique = len(uniques)
        padded = round_pow2(n_unique, 1)
        texts = uniques + [""] * (padded - n_unique)
        rt = get_reqtrace()
        t0_w = time.time() if rt.enabled else None
        t0 = time.perf_counter()
        try:
            # The dispatch edge is where a wedged device/tunnel would hang
            # a resident server silently — the watchdog classifies that as
            # serve_stall instead of a mute socket.
            with watchdog.watch("serve.dispatch", kind="serve"):
                with tel.span("serve.batch", op=op, rows=n_unique,
                              padded=padded):
                    results = self._retry.call(
                        self._run_op, op, texts, site="serving.dispatch"
                    )[:n_unique]
            if len(results) != n_unique:
                raise RuntimeError(
                    f"op {op!r} returned {len(results)} results for "
                    f"{n_unique} rows"
                )
        except Exception as exc:  # noqa: BLE001 — isolation boundary
            # Classified backend loss: reload through the failover hook
            # and retry the whole batch once before isolating.
            if allow_failover and self._maybe_failover(exc):
                self._dispatch(op, batch, allow_failover=False)
                return
            if n == 1:
                batch[0].fail(
                    "request_failed",
                    f"{type(exc).__name__}: {exc}"[:300],
                )
                self._bump(failed=1)
                tel.count("serving.request_failed")
                return
            # Retry one-by-one: the poison request fails alone, its
            # batchmates still get answers.
            self._bump(isolation_retries=1)
            tel.count("serving.isolation_retries")
            for req in batch:
                self._dispatch(op, [req], allow_failover=False)
            return
        batch_s = time.perf_counter() - t0
        tel.observe("serving.batch_seconds", batch_s)
        occupancy = n_unique / padded
        now = time.monotonic()
        with self._stats_lock:
            self._stats["batches"] += 1
            self._stats["rows"] += n_unique
            self._stats["padded_rows"] += padded
            self._stats["completed"] += n
            self._stats["dedup_folded"] += n - n_unique
            self._occupancy.observe(occupancy)
            for req in batch:
                self._latency.observe(now - req.t_enqueue)
                self._tenant_ledger(req.tenant)["completed"] += 1
            # Flush-rate EWMA feeding retry_after_ms: requests retired per
            # wall second, smoothed so one anomalous batch can't swing the
            # backoff hint an order of magnitude.
            inst = n / max(batch_s, 1e-6)
            self._flush_rate = (
                inst if self._flush_rate == 0.0
                else 0.8 * self._flush_rate + 0.2 * inst
            )
        tel.observe(
            "serving.batch_occupancy", occupancy,
            buckets=_OCCUPANCY_BUCKETS,
        )
        if rt.enabled:
            # Cursor partition: WFQ wait ends when the device dispatch
            # starts; the batch phase covers dispatch → results.
            now_w = time.time()
            for req in batch:
                tt = req.meta.get("trace_t")
                if tt is None:
                    continue
                rt.phase(req, "queue", tt.get("cursor"), t0_w)
                rt.phase(req, "batch", t0_w, now_w, op=op,
                         rows=n_unique, padded=padded)
                tt["cursor"] = now_w
        for req, row in zip(batch, rows):
            tel.observe(
                "serving.request_seconds", now - req.t_enqueue,
                buckets=_LATENCY_BUCKETS,
            )
            req.succeed(**results[row])
        tel.count("serving.completed", n)

    # ------------------------------------------------------------ readouts

    def stats(self) -> Dict[str, Any]:
        """JSON-able snapshot: admission counters, batch shape economics,
        and request-latency quantiles (the manifest ``serving`` section
        and the serving bench suite both read this)."""
        with self._stats_lock:
            out: Dict[str, Any] = dict(self._stats)
            occupancy = (
                out["rows"] / out["padded_rows"] if out["padded_rows"] else None
            )
            latency = self._latency.as_dict()
            occ = self._occupancy.as_dict()
            flush_rate = self._flush_rate
        dedup_factor = (
            (out["rows"] + out["dedup_folded"]) / out["rows"]
            if out["rows"] else 1.0
        )
        out.update(
            max_batch=self.max_batch,
            max_wait_ms=self.max_wait_ms,
            max_queue=self.max_queue,
            occupancy=round(occupancy, 4) if occupancy is not None else None,
            dedup_factor=round(dedup_factor, 4),
            flush_rate_rows_s=round(flush_rate, 3),
            latency=latency,
            batch_occupancy_hist=occ,
            rates={
                "window_s": self._rates["req_s"].tau_s,
                "req_s": self._rates["req_s"].rate(),
                "shed_s": self._rates["shed_s"].rate(),
            },
        )
        if self.response_cache is not None:
            out["response_cache"] = self.response_cache.stats()
        return out

    def slo_snapshot(self) -> Dict[str, Any]:
        """The manifest's ``serving.slo`` contribution: targets, shed
        taxonomy, and the per-tenant ledger.  Empty when the SLO layer
        was neither configured nor exercised (only-when-used, like the
        corpus-cache section)."""
        with self._stats_lock:
            tenants = {t: dict(v) for t, v in self._tenants.items()}
            sheds = {
                key: self._stats[key]
                for key in ("shed_queue_full", "shed_slo_unattainable",
                            "shed_tenant_budget", "shed_evicted")
            }
        configured = self.ttft_slo_ms > 0.0 or self.tenant_budget > 0.0
        exercised = (
            any(sheds.values())
            or any(t != DEFAULT_TENANT for t in tenants)
        )
        if not configured and not exercised:
            return {}
        return {
            "ttft_slo_ms": self.ttft_slo_ms,
            "tenant_budget_req_s": self.tenant_budget,
            "default_priority": self.default_priority,
            "sheds": sheds,
            "tenants": tenants,
        }
